"""Availability-sampling scenarios: the DAS legs of the adversarial
sweep.

Scenario scripts are pure data built from a seed (all randomness drawn
at build time, exactly like ``sim/scenarios.py``), replayed against the
real eip7594 spec surface — so every leg (engine on, fault-injected,
engine off, silently corrupted) runs the identical event stream and
must produce the identical digest.

Step vocabulary (one block's worth of DAS traffic per scenario):

``publish``
    Compute the extended cells of seeded random blobs (recovery
    material) and register zero blobs (infinity commitment, all-zero
    cells, infinity proofs — the one blob family whose multiproofs are
    free to construct, so sampling verification exercises the real
    engine/spec pairing paths at sim scale).
``withhold``
    Mark a column set unavailable (the adversary).
``sample``
    Verify the listed columns of every zero blob through
    ``verify_cell_proof_batch`` (engine: ONE pairing; spec loop: one
    per cell) — a sampled column that is withheld marks the block
    unavailable, with the surviving columns still verified (the
    engine/pairing census sees every sample step that has at least one
    available column), and an optionally tampered cell must come back
    False on every leg.
``recover``
    Erasure-recover every random blob from its available columns
    through the engine's multi-blob path (``das.recover_many``:
    shared vanishing-polynomial work) — or assert the LOUD refusal
    when fewer than half the columns survive.
``custody``
    Deterministic custody assignment for a node set
    (``get_custody_columns``), recording assignments + coverage.

Scenario shapes: ``withheld_columns`` (adversarial withholding around
the sampling detector), ``recovery_boundary`` (exactly 50% present
succeeds, one fewer refuses loudly), ``custody_rotation`` (churning
node set re-assigns custody; coverage tracked), ``nonfinality_sampling``
(sampling retries across rounds while withheld data trickles in).

Legs + contract: see :func:`run_scenario_legs` — the PR-8 counted-
fallback contract and the PR-9 sentinel-audit quarantine applied to the
``das.verify`` / ``das.recover`` sites, with artifacts replayable by
``python -m consensus_specs_tpu.sim.repro``.
"""
import hashlib
from random import Random

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.sim.scenarios import Scenario
from consensus_specs_tpu.test_infra.metrics import counting

DAS_PREFIX = "das/"
DAS_SITES = ("das.verify", "das.recover")
N_COLUMNS = 128         # minimal preset: 2 * 4096 / 64


# ---------------------------------------------------------------------------
# Scenario builders (all randomness spent HERE, baked into the script)
# ---------------------------------------------------------------------------

def withheld_columns(rng: Random):
    """The adversary withholds a column set; sampling must flag the
    block unavailable whenever a sampled column is missing, recovery
    must succeed exactly when >= 50% of columns survive."""
    n_withheld = rng.choice([rng.randint(8, N_COLUMNS // 2),
                             rng.randint(N_COLUMNS // 2 + 1,
                                         N_COLUMNS - 8)])
    withheld = sorted(rng.sample(range(N_COLUMNS), n_withheld))
    script = [
        {"op": "publish", "blob_seeds": [rng.randrange(1 << 30)],
         "zero_blobs": 1},
        {"op": "withhold", "columns": withheld},
    ]
    for _ in range(rng.randint(2, 3)):
        script.append({"op": "sample",
                       "columns": sorted(rng.sample(range(N_COLUMNS),
                                                    rng.randint(4, 8)))})
    script.append({"op": "recover"})
    return script


def recovery_boundary(rng: Random):
    """Exactly CELLS_PER_BLOB/2 available -> recovery succeeds; one
    fewer -> the spec's loud refusal (never garbage)."""
    present = sorted(rng.sample(range(N_COLUMNS), N_COLUMNS // 2))
    withheld = sorted(set(range(N_COLUMNS)) - set(present))
    script = [
        {"op": "publish", "blob_seeds": [rng.randrange(1 << 30)],
         "zero_blobs": 1},
        {"op": "withhold", "columns": withheld},
        {"op": "sample",
         "columns": sorted(rng.sample(present, 4))},
        {"op": "recover"},                      # boundary: succeeds
        {"op": "withhold", "columns": [present[rng.randrange(
            len(present))]]},
        {"op": "recover"},                      # one short: loud refusal
    ]
    return script


def custody_rotation(rng: Random):
    """Exit churn over the custody table: nodes leave and join each
    epoch, assignments must stay deterministic, disjoint-per-node and
    fully covering in aggregate."""
    nodes = [rng.randrange(1 << 62) for _ in range(rng.randint(24, 40))]
    script = [{"op": "publish", "blob_seeds": [], "zero_blobs": 1}]
    for _ in range(rng.randint(3, 5)):
        exits = sorted(rng.sample(range(len(nodes)),
                                  rng.randint(1, max(1, len(nodes) // 6))),
                       reverse=True)
        for i in exits:
            nodes.pop(i)
        joins = [rng.randrange(1 << 62)
                 for _ in range(rng.randint(1, 6))]
        nodes.extend(joins)
        script.append({"op": "custody", "nodes": list(nodes),
                       "count": rng.choice([1, 2, 2, 4])})
    script.append({"op": "sample",
                   "columns": sorted(rng.sample(range(N_COLUMNS), 4))})
    return script


def nonfinality_sampling(rng: Random):
    """Sampling under non-finality: the same block re-sampled across
    rounds while the withheld set shrinks (late data trickles in) —
    the availability verdict must flip exactly when the samples clear,
    and recovery engages once >= 50% survive."""
    withheld = sorted(rng.sample(range(N_COLUMNS),
                                 rng.randint(N_COLUMNS // 2 + 8,
                                             N_COLUMNS - 16)))
    script = [
        {"op": "publish", "blob_seeds": [rng.randrange(1 << 30)],
         "zero_blobs": 1},
        {"op": "withhold", "columns": withheld},
    ]
    remaining = list(withheld)
    rounds = rng.randint(3, 4)
    for r in range(rounds):
        script.append({"op": "sample",
                       "columns": sorted(rng.sample(range(N_COLUMNS),
                                                    rng.randint(4, 6)))})
        if remaining:
            released = [remaining.pop(rng.randrange(len(remaining)))
                        for _ in range(min(len(remaining),
                                           rng.randint(20, 40)))]
            script.append({"op": "release", "columns": sorted(released)})
    script.append({"op": "recover"})
    # one adversarial round: a tampered sampled cell must fail closed
    script.append({"op": "sample", "columns": [0, 1], "tamper": True})
    return script


_CATALOG = (
    ("withheld_columns", withheld_columns),
    ("recovery_boundary", recovery_boundary),
    ("custody_rotation", custody_rotation),
    ("nonfinality_sampling", nonfinality_sampling),
)
NAMES = tuple(DAS_PREFIX + name for name, _ in _CATALOG)


def build(seed: int, name: str = None) -> Scenario:
    """Seed-indexed catalog entry (seed round-robins the shape unless
    ``name`` — with or without the ``das/`` prefix — forces one)."""
    rng = Random(seed ^ 0xDA5)
    if name is None:
        shape, builder = _CATALOG[seed % len(_CATALOG)]
    else:
        shape = name[len(DAS_PREFIX):] if name.startswith(DAS_PREFIX) \
            else name
        builder = dict(_CATALOG).get(shape)
        if builder is None:
            raise ValueError(f"unknown das scenario {name!r}")
    return Scenario(DAS_PREFIX + shape, seed, builder(rng), 0, None)


# ---------------------------------------------------------------------------
# Execution (no RNG in here — the script is the whole event stream)
# ---------------------------------------------------------------------------

class DasResult:
    """Event-sourced run record; the digest is the byte-identity
    contract every leg is held to."""

    def __init__(self):
        self.events = []
        self.rejected = 0       # loud refusals (expected adversarial)
        self.organic = {}
        self.finalized = (0, None)      # sweep-print compatibility

    def log(self, *parts):
        self.events.append("|".join(str(p) for p in parts))

    def digest(self) -> dict:
        h = hashlib.sha256()
        for e in self.events:
            h.update(e.encode())
            h.update(b"\x00")
        return {"events": h.hexdigest(), "count": len(self.events)}


def _zero_blob_batch(spec, columns, tamper=False):
    """A verify batch over the zero blob's columns: infinity commitment,
    all-zero cells, infinity proofs — a VALID multiproof family that is
    free to construct (p = 0), so the engine fold and the spec pairing
    loop both run for real."""
    cell = bytes(spec.BYTES_PER_CELL)
    cells = [cell] * len(columns)
    if tamper and cells:
        cells = list(cells)
        cells[0] = (1).to_bytes(32, "big") + cell[32:]
    inf = bytes(spec.G1_POINT_AT_INFINITY)
    return ([inf], [0] * len(columns), list(columns), cells,
            [inf] * len(columns))


def execute(spec, script, n_validators=0) -> DasResult:
    """Replay a das script against the spec surface.  ``n_validators``
    is accepted (and ignored) for harness-signature compatibility."""
    result = DasResult()
    blobs = []          # (seed, cells) random blobs (recovery material)
    zero_blobs = 0
    withheld = set()
    for step in script:
        op = step["op"]
        if op == "publish":
            for bseed in step["blob_seeds"]:
                rng = Random(bseed)
                width = int(spec.FIELD_ELEMENTS_PER_BLOB)
                blob = b"".join(
                    rng.randrange(int(spec.BLS_MODULUS)).to_bytes(32, "big")
                    for _ in range(width))
                cells = spec.compute_cells(blob)
                blobs.append((bseed, cells))
            zero_blobs += step.get("zero_blobs", 0)
            result.log("publish", len(blobs), zero_blobs)
        elif op == "withhold":
            withheld |= set(step["columns"])
            result.log("withhold", sorted(withheld))
        elif op == "release":
            withheld -= set(step["columns"])
            result.log("release", sorted(withheld))
        elif op == "sample":
            cols = [c for c in step["columns"] if c not in withheld]
            short = len(cols) < len(step["columns"])
            verdict = not short
            if cols and zero_blobs:
                ok = spec.verify_cell_proof_batch(
                    *_zero_blob_batch(spec, cols,
                                      tamper=step.get("tamper", False)))
                verdict = verdict and bool(ok)
            result.log("sample", step["columns"], "available" if verdict
                       else "unavailable")
        elif op == "recover":
            available = [c for c in range(int(spec.NUMBER_OF_COLUMNS))
                         if c not in withheld]
            if not blobs:
                result.log("recover", "no-blobs")
                continue
            requests = [
                (list(available),
                 [spec.cell_to_bytes(cells[c]) for c in available])
                for _, cells in blobs]
            try:
                from consensus_specs_tpu.das import recover_many
                outs = recover_many(spec, requests)
            except AssertionError:
                # the spec's loud refusal (insufficient columns) — an
                # expected adversarial outcome, recorded as data
                result.rejected += 1
                result.log("recover", "refused", len(available))
            else:
                h = hashlib.sha256()
                for out in outs:
                    for x in out:
                        h.update(int(x).to_bytes(32, "big"))
                result.log("recover", len(available), h.hexdigest())
        elif op == "custody":
            union = set()
            parts = []
            for node in step["nodes"]:
                cols = spec.get_custody_columns(node, step["count"])
                union |= set(map(int, cols))
                parts.append(f"{node}:{','.join(str(int(c)) for c in cols)}")
            result.log("custody", step["count"], len(union),
                       ";".join(parts))
        else:
            raise ValueError(f"unknown das op {op!r}")
    return result


# ---------------------------------------------------------------------------
# Legs (the PR-8/PR-9 contract at the das sites)
# ---------------------------------------------------------------------------

def run_leg(spec, scenario, schedule=None, env=None,
            reset_supervisor=True) -> DasResult:
    """One replay of the scenario: arm ``schedule`` (if any), apply
    ``env`` overrides for the duration, reset the supervisor cold."""
    import os
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        if reset_supervisor:
            supervisor.reset()
        if schedule is not None:
            with faults.injected(schedule):
                return execute(spec, scenario.script)
        return execute(spec, scenario.script)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_baseline(spec, scenario):
    """Engines-on reference leg under an observing schedule; returns
    (result, das-site census).  Organic fallback counts are recorded
    baseline-relative like the chain harness does."""
    from consensus_specs_tpu.sim import harness
    observer = faults.observing()
    with counting() as delta:
        result = run_leg(spec, scenario, schedule=observer,
                         env=harness.NEUTRAL_SUPERVISOR_ENV)
    result.organic = {
        "das.fallbacks{reason=guard}": delta["das.fallbacks{reason=guard}"]}
    return result, {site: n for site, n in observer.calls.items()
                    if site in DAS_SITES}


def run_injected(spec, scenario, baseline, site, ordinal):
    """Single-trigger injected leg at a das site: the schedule must
    discharge, the fallback must be counted (reason=injected, organic
    twin untouched), and the digest must match the baseline."""
    from consensus_specs_tpu.sim import harness
    schedule = faults.FaultSchedule({site: [ordinal]})
    kind = f"inject[{site}@{ordinal}]"
    with counting() as delta:
        result = run_leg(spec, scenario, schedule=schedule,
                         env=harness.NEUTRAL_SUPERVISOR_ENV)
    if not schedule.fully_fired():
        raise harness.LegFailure(
            kind, scenario, f"schedule did not discharge (site called "
            f"{schedule.calls.get(site, 0)}x)", schedule,
            category="no-discharge")
    counted = delta["das.fallbacks{reason=injected}"]
    if counted != len(schedule.fired):
        raise harness.LegFailure(
            kind, scenario, f"SILENT FALLBACK: {len(schedule.fired)} "
            f"fired but das.fallbacks{{reason=injected}} moved by "
            f"{counted}", schedule, category="silent-fallback")
    organic_base = baseline.organic.get("das.fallbacks{reason=guard}", 0)
    if delta["das.fallbacks{reason=guard}"] != organic_base:
        raise harness.LegFailure(
            kind, scenario, "injected fault leaked into the organic "
            "guard series", schedule, category="organic-leak")
    if result.digest() != baseline.digest():
        raise harness.LegFailure(
            kind, scenario, "fallback diverged from the uninjected "
            "replay", schedule, category="diverged")
    return result


def run_engine_off(spec, scenario, baseline):
    """CS_TPU_DAS=0 replay: the markdown spec loop must match the
    engine digest byte-for-byte."""
    from consensus_specs_tpu.sim import harness
    result = run_leg(spec, scenario,
                     env={"CS_TPU_DAS": "0",
                          **harness.NEUTRAL_SUPERVISOR_ENV})
    if result.digest() != baseline.digest():
        raise harness.LegFailure(
            "das-engine-off", scenario,
            "spec-loop replay diverged from engines-on", None)
    return result


def run_corrupt(spec, scenario, baseline, site, out_dir=None):
    """Persistent silent corruption at a das site under rate-1 audits:
    the sentinel must catch the first wrong answer, quarantine the
    site, dump a replayable artifact, and the digest must stay
    byte-identical (the spec answer is authoritative on every audited
    call).  Returns (result, artifact_path)."""
    from consensus_specs_tpu.sim import harness, repro
    schedule = faults.FaultSchedule(corrupt={site: [1]})
    kind = f"audit[{site}]"
    dumped = []

    def _dump(q_site, detail):
        path = repro.dump_artifact(
            scenario, kind,
            f"sentinel audit quarantined {q_site}: {detail}",
            schedule=schedule, out_dir=out_dir, fork="eip7594",
            preset="minimal")
        dumped.append(path)
        return path

    with supervisor.quarantine_hook(_dump):
        with counting() as delta:
            result = run_leg(spec, scenario, schedule=schedule,
                             env=harness.AUDIT_ENV)
    if not schedule.corrupted:
        raise harness.LegFailure(
            kind, scenario, "corruption never armed (site called "
            f"{schedule.calls.get(site, 0)}x)", schedule,
            category="no-discharge")
    if delta[f"supervisor.audits{{result=fail,site={site}}}"] < 1:
        raise harness.LegFailure(
            kind, scenario, "SILENT CORRUPTION: corrupted result(s) "
            "but no sentinel audit failed", schedule,
            category="silent-fallback")
    if delta[f"supervisor.quarantines{{site={site}}}"] != 1:
        raise harness.LegFailure(
            kind, scenario, "expected exactly one quarantine", schedule,
            category="silent-fallback")
    if not dumped:
        raise harness.LegFailure(
            kind, scenario, "quarantine fired but dumped no artifact",
            schedule, category="silent-fallback")
    if result.digest() != baseline.digest():
        raise harness.LegFailure(
            kind, scenario, "corrupted engine result reached the digest "
            "despite rate-1 audits", schedule, category="diverged")
    return result, dumped[0]


def replay_artifact(payload, out_dir=None) -> int:
    """Replay a das repro artifact (``sim/repro.py`` dispatches here on
    the ``das/`` scenario-name prefix).  Returns a process exit code:
    1 = the recorded failure reproduces (for a quarantine artifact:
    the sentinel audit catches and quarantines again, re-dumping its
    evidence into ``out_dir``), 0 = clean, 2 = a quarantine replay
    violated the leg contract itself (e.g. the corruption now slips
    past the audit — strictly worse than reproducing; the sweep's
    re-proof requires exactly 1)."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.sim import harness
    scenario = Scenario(payload["scenario"], payload["seed"],
                        payload["script"], 0, None)
    # defense in depth: das scenarios only ever run on a sampling-
    # capable fork — a stray chain fork in the payload (an artifact
    # dumped before the sweep recorded das forks correctly) must not
    # crash the replay with an AttributeError miles from the cause
    fork = payload.get("fork") or "eip7594"
    preset = payload.get("preset") or "minimal"
    if fork not in ("eip7594",):
        fork, preset = "eip7594", "minimal"
    spec = build_spec(fork, preset)
    baseline, census = run_baseline(spec, scenario)
    print(f"das baseline: {baseline.digest()['events'][:16]}... "
          f"({baseline.digest()['count']} events)")
    sched = payload.get("schedule") or {}
    corrupt = sched.get("corrupt") or None
    triggers = sched.get("triggers") or None
    try:
        if corrupt:
            # run_corrupt SUCCEEDING is the reproduction; a LegFailure
            # means the quarantine pipeline itself regressed (silent
            # corruption, missing artifact, digest divergence) — report
            # it as a distinct verdict instead of a hollow "reproduced"
            try:
                for site in corrupt:
                    _, path = run_corrupt(spec, scenario, baseline,
                                          site, out_dir=out_dir)
                    print(f"REPRODUCED: sentinel audit quarantined "
                          f"{site} again -> {path}")
            except harness.LegFailure as fail:
                print(f"QUARANTINE REPLAY VIOLATED ITS CONTRACT: {fail}")
                return 2
            return 1
        if triggers:
            for site, ns in triggers.items():
                for n in ns:
                    run_injected(spec, scenario, baseline, site, n)
        else:
            run_engine_off(spec, scenario, baseline)
    except harness.LegFailure as fail:
        print(f"REPRODUCED: {fail}")
        return 1
    print("das leg clean — failure did not reproduce")
    return 0
