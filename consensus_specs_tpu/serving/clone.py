"""Chunk-level state clones: whole-state snapshots at column-fork cost.

``Container.copy`` (utils/ssz/types.py) is structurally O(n) Python —
every element of every sequence gets a ``.copy()`` call and an owner
re-bind, so snapshotting a 1M-validator state costs millions of Python
method calls even though element copies of immutable leaves are no-ops
and the chunk trees copy as C-level bytearray memcpys.  The serving
pipeline snapshots a state per accepted block (and fork choice copies
those snapshots per child), so that loop is exactly the cost that caps
concurrent fork-choice heads.

:func:`clone_state` replaces the per-element walk with three per-field
policies:

* **fast** — sequences of immutable elements (``BasicValue`` ints,
  ``ByteVector``/``ByteList`` bytes).  Their base ``copy()`` is already
  ``[x.copy() for x in items]`` where every ``x.copy()`` returns ``x``;
  we produce the same result with one C-level ``list(items)`` plus the
  tree memcpy (``_copy_tree_into``) — byte-identical, none of the
  per-element interpreter work.
* **lazy** — large composite-element sequences (validators,
  historical summaries, ...).  The clone is an instance of a cached
  per-concrete-class subclass whose ``_items`` / ``_tree`` slots are
  shadowed by properties: element copies and the tree memcpy happen on
  first touch, against a strong reference to the frozen source.  A
  snapshot that is never mutated or re-merkleized (the common fate of
  ``store.block_states`` entries) never pays for either.
* **eager** — everything else (nested containers, bitfields, small
  sequences): the ordinary ``copy()``.

Laziness is only sound if the source cannot change under the clone, so
the lazy path carries a **frozen-source contract**: the source's
mutation generation (``_gen``) is recorded at clone time and re-checked
on every deferred touch; a mismatch raises ``RuntimeError`` instead of
silently materializing from a drifted source.  Computing a root on the
source does NOT trip the guard (root computation flushes chunk dirt
without bumping ``_gen``); any pending dirt is flushed into the
source's tree at clone time so a later lazy tree memcpy starts clean.
Post-state snapshots in the pipeline are frozen by construction —
fork choice only ever ``copy()``s them — which is why the contract
holds there.  Counters: ``serving.clones``, per-mode
``serving.clone_fields``, and ``serving.materializations`` (how much
of the deferred work was ever actually paid).
"""

from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.utils import env_flags
from consensus_specs_tpu.utils.ssz.types import (
    BasicValue,
    ByteListBase,
    ByteVectorBase,
    Container,
    _SequenceBase,
    _set_owner,
)

_C_CLONES = obs_registry.counter("serving.clones").labels()
_C_FIELD_FAST = obs_registry.counter("serving.clone_fields").labels(mode="fast")
_C_FIELD_LAZY = obs_registry.counter("serving.clone_fields").labels(mode="lazy")
_C_FIELD_EAGER = obs_registry.counter("serving.clone_fields").labels(mode="eager")
_C_MAT_ITEMS = obs_registry.counter("serving.materializations").labels(stage="items")
_C_MAT_TREE = obs_registry.counter("serving.materializations").labels(stage="tree")

# Element types whose ``copy()`` returns ``self`` and which never hold
# an owner backref — the precondition for sharing them across clones.
_IMMUTABLE_ELEMS = (BasicValue, ByteVectorBase, ByteListBase)

# Composite sequences shorter than this are cheaper to copy eagerly
# than to wrap (the lazy wrapper costs a class lookup + dict setup).
_DEFAULT_LAZY_MIN = 64

# Sentinel for "tree not copied from the source yet" — distinct from
# None, which is a legal tree value ("rebuild from leaves on demand").
_TREE_UNSET = object()

_lazy_cache = {}            # concrete sequence class -> lazy subclass
_fast_cache = {}            # concrete sequence class -> fast subclass


def _lazy_min() -> int:
    raw = env_flags.knob("CS_TPU_SERVING_LAZY_MIN")
    try:
        return int(raw)
    except (TypeError, ValueError):
        return _DEFAULT_LAZY_MIN


def _flush_source_dirt(src) -> None:
    """Flush pending chunk dirt into the source's tree so deferred tree
    memcpys (and the shared items list) start from a clean layer.  Root
    maintenance does not bump ``_gen``, so this never trips the
    frozen-source guard."""
    if getattr(src, "_tree", None) is not None and getattr(src, "_dirty", None):
        src._tree_root()


def _lazy_class(cls):
    lz = _lazy_cache.get(cls)
    if lz is not None:
        return lz

    def _check_src(self):
        d = self.__dict__
        src = d["_lz_src"]
        if src is None or getattr(src, "_gen", 0) != d["_lz_gen"]:
            raise RuntimeError(
                f"serving.clone: source {cls.__name__} mutated after a "
                "chunk-level clone; clone sources must stay frozen")
        return src

    def _maybe_release(self):
        # Once both halves are materialized the source is never touched
        # again — drop the strong ref so snapshots don't pin lineages.
        d = self.__dict__
        if d["_lz_items"] is not None and d["_lz_tree"] is not _TREE_UNSET:
            d["_lz_src"] = None

    def _materialize(self):
        src = _check_src(self)
        items = [x.copy() for x in src._items]
        for i, x in enumerate(items):
            _set_owner(x, self, i)
        self.__dict__["_lz_items"] = items
        _C_MAT_ITEMS.add()
        _maybe_release(self)
        return items

    def _get_items(self):
        items = self.__dict__["_lz_items"]
        return items if items is not None else _materialize(self)

    def _set_items(self, value):
        self.__dict__["_lz_items"] = value
        _maybe_release(self)

    def _get_tree(self):
        d = self.__dict__
        t = d["_lz_tree"]
        if t is _TREE_UNSET:
            src = _check_src(self)
            st = getattr(src, "_tree", None)
            t = st.copy() if st is not None else None
            d["_lz_tree"] = t
            _C_MAT_TREE.add()
            _maybe_release(self)
        return t

    def _set_tree(self, value):
        self.__dict__["_lz_tree"] = value
        _maybe_release(self)

    def _len(self):
        items = self.__dict__["_lz_items"]
        if items is not None:
            return len(items)
        return len(_check_src(self)._items)

    def _copy(self):
        d = self.__dict__
        if d["_lz_items"] is None:
            # Still virtual: another lazy clone off the same frozen
            # source — clone chains stay O(1) until someone writes.
            _C_FIELD_LAZY.add()
            return _lazy_sequence_clone(_check_src(self))
        # Materialized: behave exactly like the base-class copy, and
        # produce a PLAIN instance so laziness doesn't nest.
        new = object.__new__(cls)
        items = [x.copy() for x in d["_lz_items"]]
        object.__setattr__(new, "_items", items)
        for i, x in enumerate(items):
            _set_owner(x, new, i)
        _SequenceBase._copy_tree_into(self, new)
        return new

    lz = type(
        "_LazyClone_" + cls.__name__, (cls,),
        {
            "_serving_lazy": True,
            "_items": property(_get_items, _set_items),
            "_tree": property(_get_tree, _set_tree),
            "__len__": _len,
            "copy": _copy,
        },
    )
    _lazy_cache[cls] = lz
    return lz


def _lazy_sequence_clone(src):
    _flush_source_dirt(src)
    new = object.__new__(_lazy_class(type(src)))
    d = new.__dict__
    d["_lz_src"] = src                       # strong ref: frozen source
    d["_lz_gen"] = getattr(src, "_gen", 0)
    d["_lz_items"] = None
    d["_lz_tree"] = _TREE_UNSET
    object.__setattr__(new, "_dirty", set())
    object.__setattr__(new, "_root_memo", getattr(src, "_root_memo", None))
    return new


def _fast_class(cls):
    """Cached subclass whose ``copy()`` is the fast clone — so copies of
    fast clones (fork choice copying ``store.block_states`` entries)
    stay C-level through the whole lineage instead of reverting to the
    per-element base walk after the first generation."""
    fc = _fast_cache.get(cls)
    if fc is not None:
        return fc

    def _copy(self):
        _C_FIELD_FAST.add()
        return _fast_sequence_clone(self)

    fc = type(
        "_FastClone_" + cls.__name__, (cls,),
        {"_serving_fast": True, "_serving_base": cls, "copy": _copy},
    )
    _fast_cache[cls] = fc
    return fc


def _fast_sequence_clone(src):
    # Same result as the base copy() — whose element copies are all
    # identity for immutable elements — minus the per-element Python.
    # ``_serving_base`` keeps fast-of-fast from nesting subclasses.
    base = getattr(type(src), "_serving_base", type(src))
    new = object.__new__(_fast_class(base))
    object.__setattr__(new, "_items", list(src._items))
    src._copy_tree_into(new)
    return new


def _clone_value(v, lazy_min):
    if isinstance(v, _SequenceBase):
        cls = type(v)
        if getattr(cls, "_serving_lazy", False):
            # copy() on a lazy instance already does the right thing
            # (virtual -> sibling lazy clone, materialized -> plain);
            # it bumps the lazy counter itself when it stays virtual.
            if v.__dict__["_lz_items"] is None:
                return v.copy()
            _C_FIELD_EAGER.add()
            return v.copy()
        if issubclass(cls.elem_type, _IMMUTABLE_ELEMS):
            _C_FIELD_FAST.add()
            return _fast_sequence_clone(v)
        if len(v._items) >= lazy_min:
            _C_FIELD_LAZY.add()
            return _lazy_sequence_clone(v)
    _C_FIELD_EAGER.add()
    return v.copy()


def clone_state(state: Container) -> Container:
    """Chunk-level clone of an SSZ container (typically a BeaconState).

    Byte-identical to ``state.copy()`` — same serialization, same
    ``hash_tree_root`` — but large composite sequences are cloned
    lazily against the (frozen) source and immutable-element sequences
    share their item lists outright.  An attached ``StateArrays``
    column store is committed and forked exactly as in ``copy()``."""
    store = state.__dict__.get("_state_arrays")
    if store is not None:
        store.commit_for_copy()
    lazy_min = _lazy_min()
    cls = type(state)
    new = object.__new__(cls)
    for f in cls._fields:
        fv = _clone_value(getattr(state, f), lazy_min)
        object.__setattr__(new, f, fv)
        _set_owner(fv, new, f)
    # field clones have identical roots, so the memoized root carries over
    object.__setattr__(new, "_root_cache",
                       object.__getattribute__(state, "_root_cache"))
    if store is not None:
        store.fork(new)
    _C_CLONES.add()
    return new
