"""Block-serving pipeline (``CS_TPU_SERVING``): window-batched
optimistic block delivery with double-buffered flush overlap
(:mod:`.pipeline`) and chunk-level whole-state snapshots
(:mod:`.clone`).  See ``docs/serving.md``."""

from consensus_specs_tpu.serving.clone import clone_state
from consensus_specs_tpu.serving.pipeline import BlockServer

__all__ = ["BlockServer", "clone_state"]
