"""Block-serving pipeline: window-batched optimistic delivery with the
RLC/merkle flush overlapped into a worker lane.

The synchronous serving path (``sim/driver.py`` delivery semantics)
interleaves three very different cost classes per block: the Python
state transition, the deferred-batch RLC flush (Fiat-Shamir fold → MSM
→ one pairing), and the post-state merkleization that fork choice and
the sentinel audits read.  :class:`BlockServer` restructures that into
a two-deep pipeline over fixed-size event windows:

* **window batching** — ingested events (ticks, blocks, attestations,
  attester slashings) buffer until ``CS_TPU_SERVING_WINDOW`` blocks are
  in flight, then the whole window is processed optimistically: every
  signature verification lands in ONE :class:`_WindowBatch` (so sibling
  blocks carrying the same attestations — equivocation streams, reorg
  races — dedup into one RLC term), and every block body's attestation
  messages are prepared in one cross-block columnar pass
  (:func:`~consensus_specs_tpu.ops.att_prep.prepare_window_attestations`).
* **flush overlap** — the window's combined flush runs on a worker
  thread while the MAIN thread transitions the next window and
  merkleizes its post-states.  The crypto verdict is resolved one
  window late (a barrier join before the next submit), which is the
  double-buffering: device/crypto work for window N-1 overlaps host
  transition + tree maintenance for window N.  Spec code never runs off
  the main thread — the worker executes pure verification.
* **chunk-level snapshots** — each accepted post-state stored into
  ``store.block_states`` is swapped for a :func:`clone_state` snapshot,
  so the per-block whole-state copy (and every child's pre-state copy
  off it) costs what a column fork costs instead of an O(n) walk.

**Deferred-verdict semantics**: within a window, block acceptance is
optimistic — signature failures surface at the window barrier, not at
the ingest call.  On any barrier failure (flush verdict False, injected
fault, deadline, audit mismatch) the store is rolled back from a
journal snapshot (newest window first), the fork-choice engine is
rebuilt from the rolled-back store, and the SAME events are replayed
through the synchronous per-block path — so the post-drain store is
byte-identical to a synchronous run by construction, and per-block
errors land exactly where the spec path raises them.  The fallback is
counted per reason under ``serving.fallbacks`` and feeds the breaker
(:func:`supervisor.admit`) like every other engine site.

**Causal tracing**: each window captures a ``tracing.TraceContext``
(carrying a process-unique trace id) while its ``serving.window`` span
is open; the flush worker and the (next window's) barrier join adopt
it, so under ``CS_TPU_PROFILE``/``CS_TPU_TRACE`` the span tree shows
ONE tree per window — transition, worker-lane ``serving.flush``,
``serving.barrier``, and ``serving.replay`` when the unwind is taken —
instead of the flush rooting an orphan subtree on its own thread.
``BlockServer.window_log`` additionally keeps a per-window latency
breakdown (queued / optimistic / flush / barrier / replay seconds,
trace id, outcome) that ``obs_report --serving`` prints.
"""
import threading
import time

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.forkchoice import proto_array
from consensus_specs_tpu.obs import flight
from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.obs import tracing
from consensus_specs_tpu.ops import att_prep
from consensus_specs_tpu.serving.clone import clone_state
from consensus_specs_tpu.utils import bls, env_flags
from consensus_specs_tpu.utils.ssz import hash_tree_root

_C_WINDOWS = obs_registry.counter("serving.windows").labels()
_C_BLOCKS_PIPE = obs_registry.counter("serving.blocks").labels(path="pipelined")
_C_BLOCKS_SYNC = obs_registry.counter("serving.blocks").labels(path="sync")
_FALLBACKS = {
    "injected": obs_registry.counter("serving.fallbacks").labels(reason="injected"),
    "deadline": obs_registry.counter("serving.fallbacks").labels(reason="deadline"),
    "reverify": obs_registry.counter("serving.fallbacks").labels(reason="reverify"),
}
_H_LATENCY = obs_registry.histogram("serving.ingest_latency").labels()

# the sim driver's delivery contract: these reject a block/attestation
# without poisoning the store (sim/driver.py _REJECTED)
_REJECTED = (AssertionError, IndexError, KeyError, ValueError)

_DEFAULT_WINDOW = 4


def _window_depth() -> int:
    raw = env_flags.knob("CS_TPU_SERVING_WINDOW", str(_DEFAULT_WINDOW))
    try:
        return max(1, int(raw))
    except (TypeError, ValueError):
        return _DEFAULT_WINDOW


class _WindowBatch(bls.DeferredBatch):
    """A deferred batch that stays queued across the per-block
    ``assert_valid()`` calls inside ``on_block``: while ``_deferring``
    is set, ``flush()`` reports optimistic success without draining, so
    every block of the window folds into the ONE real flush issued by
    :meth:`resolve` at the window barrier (one pairing per window, and
    cross-block dedup of repeated (message, signature) terms)."""

    _deferring = True

    def flush(self):
        if self._deferring:
            return True
        return super().flush()

    def resolve(self):
        """The window's single real flush (worker lane)."""
        self._deferring = False
        return bls.DeferredBatch.flush(self)


class _Window:
    __slots__ = ("events", "journal", "batch", "accepted", "thread",
                 "outcome", "ctx", "stats")

    def __init__(self, events, journal):
        self.events = events
        self.journal = journal
        self.batch = _WindowBatch()
        self.accepted = []          # roots accepted by the optimistic pass
        self.thread = None
        self.outcome = None         # True | False | BaseException
        self.ctx = None             # tracing.TraceContext: the window's
        #                             trace id + span-tree handoff node
        self.stats = {}             # per-stage wall clock (window_log)

    @property
    def trace_id(self):
        return self.ctx.trace_id if self.ctx is not None else None


# -- store journal ----------------------------------------------------------

_CHECKPOINT_FIELDS = ("justified_checkpoint", "finalized_checkpoint",
                      "unrealized_justified_checkpoint",
                      "unrealized_finalized_checkpoint")
# add-only maps (or re-delivery overwrites with value-identical entries):
# rollback = delete the keys the window added
_GROW_ONLY_MAPS = ("blocks", "block_states", "checkpoint_states",
                   "unrealized_justifications")


def _snapshot(store) -> dict:
    """Rollback journal for one optimistic window.  ``latest_messages``
    and ``block_timeliness`` are journaled as full dict copies — their
    VALUES get overwritten in place (a newer vote replaces an index's
    LatestMessage; a re-delivered block can re-score timeliness) — while
    the grow-only maps only need their key sets."""
    j = {
        "time": store.time,
        "proposer_boost_root": store.proposer_boost_root,
        "equivocating_indices": set(store.equivocating_indices),
        "latest_messages": dict(store.latest_messages),
        "block_timeliness": dict(store.block_timeliness),
    }
    for name in _CHECKPOINT_FIELDS:
        j[name] = getattr(store, name).copy()
    for name in _GROW_ONLY_MAPS:
        j[name] = set(getattr(store, name))
    return j


def _rollback(store, j) -> None:
    store.time = j["time"]
    store.proposer_boost_root = j["proposer_boost_root"]
    store.equivocating_indices = set(j["equivocating_indices"])
    store.latest_messages = dict(j["latest_messages"])
    store.block_timeliness = dict(j["block_timeliness"])
    for name in _CHECKPOINT_FIELDS:
        setattr(store, name, j[name].copy())
    for name in _GROW_ONLY_MAPS:
        d = getattr(store, name)
        keep = j[name]
        for k in [k for k in d if k not in keep]:
            del d[k]


# -- delivery ---------------------------------------------------------------

def _deliver_block_ops(spec, store, signed) -> None:
    # accepting a block implies delivering its attestations and
    # attester slashings (the sim driver's contract — both lanes must
    # mirror it for byte-identical stores)
    for attestation in signed.message.body.attestations:
        try:
            spec.on_attestation(store, attestation, is_from_block=True)
        except _REJECTED:
            pass
    for slashing in signed.message.body.attester_slashings:
        try:
            spec.on_attester_slashing(store, slashing)
        except _REJECTED:
            pass


def _deliver_sync(spec, store, events, results) -> None:
    """The synchronous reference path: per-event delivery with the
    spec-default (per-block) signature verification."""
    for ev in events:
        kind = ev[0]
        if kind == "block":
            signed = ev[1]
            root = bytes(hash_tree_root(signed.message))
            try:
                spec.on_block(store, signed)
            except _REJECTED as exc:
                results[root] = (False, exc)
            else:
                results[root] = (True, None)
                _deliver_block_ops(spec, store, signed)
            if ev[2] is not None:
                _H_LATENCY.observe(time.perf_counter() - ev[2])
        elif kind == "tick":
            spec.on_tick(store, ev[1])
        elif kind == "attestation":
            try:
                spec.on_attestation(store, ev[1], is_from_block=False)
            except _REJECTED:
                pass
        else:
            try:
                spec.on_attester_slashing(store, ev[1])
            except _REJECTED:
                pass


def _tamper(state) -> None:
    # deterministic silent corruption for the harness corrupt leg: bump
    # one balance through the SSZ write path so every root memo above
    # it clears — the sentinel audit must catch a REAL divergence
    state.balances[0] = state.balances[0] + 1


class BlockServer:
    """Event-ordered block serving over a fork-choice ``store``.

    Feed it the same event stream the synchronous path would see —
    :meth:`on_tick`, :meth:`ingest` (blocks), :meth:`on_attestation`,
    :meth:`on_attester_slashing` — in delivery order, then
    :meth:`drain`.  With ``CS_TPU_SERVING`` on, delivery is pipelined
    (window batching + overlapped flush + chunk-level snapshots); off,
    or on breaker/fault/deadline/audit failure, every event goes
    through the synchronous path — the post-drain store is
    byte-identical either way, only the error-surfacing point moves
    (window barrier vs ingest call)."""

    def __init__(self, spec, store, window=None):
        self.spec = spec
        self.store = store
        self.window = int(window) if window else _window_depth()
        self.results = {}           # block root -> (accepted, error|None)
        self.window_log = []        # per-window latency breakdown dicts
        self._events = []
        self._pending_blocks = 0
        self._inflight = None

    # -- event intake ------------------------------------------------------

    def on_tick(self, t) -> None:
        self._events.append(("tick", int(t), None))

    def on_attestation(self, attestation) -> None:
        self._events.append(("attestation", attestation, None))

    def on_attester_slashing(self, attester_slashing) -> None:
        self._events.append(("attester_slashing", attester_slashing, None))

    def ingest(self, signed_block) -> None:
        """Queue a block (stamped for ingest-latency accounting); the
        window is processed once ``window`` blocks are buffered."""
        self._events.append(("block", signed_block, time.perf_counter()))
        self._pending_blocks += 1
        if self._pending_blocks >= self.window:
            self._flush_events()

    def drain(self) -> dict:
        """Process any partial window and resolve the in-flight flush;
        returns {block_root: (accepted, error|None)} for every block."""
        if self._events:
            self._flush_events()
        self._resolve_inflight()
        return dict(self.results)

    # -- window machinery --------------------------------------------------

    def _flush_events(self) -> None:
        events, self._events = self._events, []
        self._pending_blocks = 0
        self._process_window(events)

    def _process_window(self, events) -> None:
        spec, store = self.spec, self.store
        site = "serving.pipeline"
        nblocks = sum(1 for ev in events if ev[0] == "block")
        if not (env_flags.switch("CS_TPU_SERVING")
                and supervisor.admit(site)):
            self._resolve_inflight()
            _deliver_sync(spec, store, events, self.results)
            _C_BLOCKS_SYNC.add(nblocks)
            return
        journal = None
        try:
            faults.check(site)
            journal = _snapshot(store)
            with tracing.span("serving.window"), \
                    supervisor.deadline_scope(site):
                win = self._run_optimistic(events, journal)
        except (faults.InjectedFault, supervisor.DeadlineExceeded) as exc:
            if journal is not None:
                _rollback(store, journal)
                proto_array.attach_store_accel(spec, store)
            self._resolve_inflight()
            faults.count_fallback(_FALLBACKS, exc, organic="reverify",
                                  site=site)
            _deliver_sync(spec, store, events, self.results)
            _C_BLOCKS_SYNC.add(nblocks)
            return
        if win.accepted and faults.corrupt_armed(site):
            _tamper(store.block_states[win.accepted[-1]])
        if self._resolve_inflight(extra=win):
            self._submit(win)

    def _run_optimistic(self, events, journal) -> "_Window":
        spec, store = self.spec, self.store
        win = _Window(events, journal)
        # captured while the serving.window span is open (we are inside
        # _process_window's span), so the flush worker's and barrier's
        # spans parent under THIS window's node — one causal tree per
        # window, carrying one trace id end to end
        win.ctx = tracing.capture_context()
        t0 = time.perf_counter()
        stamps = [ev[2] for ev in events
                  if ev[0] == "block" and ev[2] is not None]
        win.stats["queued_s"] = t0 - min(stamps) if stamps else 0.0
        results = self.results
        # cross-block message prep: ONE columnar pass over every
        # in-flight block body plus the loose attestation stream,
        # keyed off a committed same-chain state (fork-boundary keys
        # miss into the spec body, never wrong-hit)
        groups = [ev[1].message.body.attestations
                  for ev in events if ev[0] == "block"]
        loose = [ev[1] for ev in events if ev[0] == "attestation"]
        if loose:
            groups.append(loose)
        anchor = store.block_states.get(
            bytes(store.justified_checkpoint.root))
        if anchor is not None and groups:
            att_prep.prepare_window_attestations(spec, anchor, groups)
        with bls.scoped_batch(win.batch):
            for ev in events:
                supervisor.deadline_check()
                kind = ev[0]
                if kind == "block":
                    signed = ev[1]
                    root = bytes(hash_tree_root(signed.message))
                    try:
                        spec.on_block(store, signed)
                    except _REJECTED as exc:
                        results[root] = (False, exc)
                    else:
                        # swap the stored post-state for a chunk-level
                        # snapshot: children's pre-state copies (and
                        # checkpoint-state copies) become column-fork
                        # cheap.  The swap touches a key this window
                        # added, so rollback stays delete-the-added-keys.
                        store.block_states[root] = clone_state(
                            store.block_states[root])
                        results[root] = (True, None)
                        win.accepted.append(root)
                        _deliver_block_ops(spec, store, signed)
                elif kind == "tick":
                    spec.on_tick(store, ev[1])
                elif kind == "attestation":
                    try:
                        spec.on_attestation(store, ev[1],
                                            is_from_block=False)
                    except _REJECTED:
                        pass
                else:
                    try:
                        spec.on_attester_slashing(store, ev[1])
                    except _REJECTED:
                        pass
        win.stats["optimistic_s"] = time.perf_counter() - t0
        return win

    def _submit(self, win) -> None:
        """Hand the window's single combined flush to the worker lane;
        it resolves at the NEXT window's barrier (or drain) while the
        main thread transitions ahead — the overlap.  The worker adopts
        the window's captured trace context, so its ``serving.flush``
        span lands INSIDE the window's tree instead of rooting an
        orphan subtree on its own thread."""
        def _run():
            t0 = time.perf_counter()
            try:
                with tracing.adopt_context(win.ctx), \
                        tracing.span("serving.flush"):
                    win.outcome = win.batch.resolve()
            except BaseException as exc:     # surfaces at the barrier
                win.outcome = exc
            win.stats["flush_s"] = time.perf_counter() - t0
        win.thread = threading.Thread(
            target=_run, name="serving-flush", daemon=True)
        win.thread.start()
        self._inflight = win
        flight.record("window", f"submit:{win.trace_id or 0}")
        _C_WINDOWS.add()

    def _resolve_inflight(self, extra=None) -> bool:
        """Barrier: join the in-flight window's flush and commit or
        unwind.  ``extra`` is the just-transitioned (not yet submitted)
        window — on failure BOTH are rolled back, newest journal first,
        and BOTH are replayed synchronously in order."""
        win, self._inflight = self._inflight, None
        if win is None:
            return True
        spec, store = self.spec, self.store
        site = "serving.pipeline"
        t_bar = time.perf_counter()
        # adopt the WINDOW's context (its worker may still hold it on
        # the other thread — cross-thread concurrent adoption is the
        # sanctioned overlap): the barrier span joins the same causal
        # tree as the transition and the flush it is waiting on
        with tracing.adopt_context(win.ctx), \
                tracing.span("serving.barrier"):
            win.thread.join()
        win.stats["barrier_s"] = time.perf_counter() - t_bar
        outcome = win.outcome
        ok = outcome is True
        if ok and supervisor.audit_due(site):
            # sentinel: every accepted post-state must merkleize to the
            # root its block committed to (catches the corrupt leg)
            audit_ok = all(
                bytes(hash_tree_root(store.block_states[r]))
                == bytes(store.blocks[r].state_root)
                for r in win.accepted)
            supervisor.audit_result(
                site, audit_ok,
                "pipelined post-state diverged from block state_root")
            ok = audit_ok
        if ok:
            supervisor.note_success(site)
            now = time.perf_counter()
            nblocks = 0
            for ev in win.events:
                if ev[0] == "block":
                    nblocks += 1
                    if ev[2] is not None:
                        _H_LATENCY.observe(now - ev[2])
            _C_BLOCKS_PIPE.add(nblocks)
            self._log_window(win, nblocks, "pipelined")
            return True
        # unwind: newest journal first, rebuild the fork-choice engine
        # from the rolled-back store, replay in original order
        if extra is not None:
            _rollback(store, extra.journal)
        _rollback(store, win.journal)
        proto_array.attach_store_accel(spec, store)
        exc = outcome if isinstance(outcome, BaseException) else None
        faults.count_fallback(_FALLBACKS, exc, organic="reverify",
                              site=site)
        replay = list(win.events)
        if extra is not None:
            replay += extra.events
        t_rep = time.perf_counter()
        # the rollback + synchronous replay is part of the failing
        # window's causal story — same tree, same trace id
        with tracing.adopt_context(win.ctx), \
                tracing.span("serving.replay"):
            _deliver_sync(spec, store, replay, self.results)
        win.stats["replay_s"] = time.perf_counter() - t_rep
        nblocks = sum(1 for ev in replay if ev[0] == "block")
        _C_BLOCKS_SYNC.add(nblocks)
        self._log_window(win, nblocks, "replayed")
        return False

    def _log_window(self, win, nblocks, outcome) -> None:
        entry = {"trace_id": win.trace_id, "blocks": nblocks,
                 "outcome": outcome}
        entry.update(win.stats)
        self.window_log.append(entry)
