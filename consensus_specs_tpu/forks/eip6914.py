"""EIP-6914 feature fork: reuse of fully-withdrawn validator indices.

Behavioral source: ``specs/_features/eip6914/beacon-chain.md``
(``SAFE_EPOCHS_TO_REUSE_INDEX`` :33, ``is_reusable_validator`` :43,
modified ``get_index_for_new_validator`` :60) and ``fork-choice.md``
(``on_reused_index`` :33). Fork DAG parent: capella. The reference
excludes this fork from its build and carries no tests for it; here it
is runnable (``tests/eip6914/``).

The registry is append-only in phase0..deneb, so it grows without bound
as validators exit and withdraw. After an index has been fully
withdrawn for ``SAFE_EPOCHS_TO_REUSE_INDEX`` epochs (~0.8 years — past
every slashing/attestation horizon), a new deposit may take over the
slot instead of appending.
"""
from . import register_fork
from .capella import CapellaSpec
from .base_types import Gwei, ValidatorIndex


@register_fork("eip6914")
class EIP6914Spec(CapellaSpec):
    fork = "eip6914"
    previous_fork = "capella"

    # preset (beacon-chain.md "Time parameters"); ~0.8 years of epochs
    SAFE_EPOCHS_TO_REUSE_INDEX = 2**16

    def is_reusable_validator(self, validator, balance, epoch) -> bool:
        """beacon-chain.md:43 — fully withdrawn and long past every
        slashing horizon."""
        return (
            int(epoch) > int(validator.withdrawable_epoch)
            + self.SAFE_EPOCHS_TO_REUSE_INDEX
            and int(balance) == 0
        )

    def get_index_for_new_validator(self, state) -> ValidatorIndex:
        """beacon-chain.md:60 — first reusable slot, else append."""
        for index, validator in enumerate(state.validators):
            if self.is_reusable_validator(validator, state.balances[index],
                                          self.get_current_epoch(state)):
                return ValidatorIndex(index)
        return ValidatorIndex(len(state.validators))

    def add_validator_to_registry(self, state, pubkey,
                                  withdrawal_credentials, amount) -> None:
        index = self.get_index_for_new_validator(state)
        if index == len(state.validators):
            # append path: the inherited chain appends EVERY per-validator
            # list (validators/balances + altair's participation flags and
            # inactivity scores)
            super().add_validator_to_registry(
                state, pubkey, withdrawal_credentials, amount)
            return
        # reuse path: overwrite the slot in every per-validator list — the
        # previous owner's participation/inactivity must not leak onto the
        # new validator
        state.validators[index] = self.get_validator_from_deposit(
            pubkey, withdrawal_credentials, amount)
        state.balances[index] = Gwei(amount)
        state.previous_epoch_participation[index] = 0
        state.current_epoch_participation[index] = 0
        state.inactivity_scores[index] = 0

    # -- fork choice (fork-choice.md) --------------------------------------
    def on_reused_index(self, store, index) -> None:
        """fork-choice.md:33 — a reused slot's equivocation record belongs
        to the previous owner; drop it."""
        store.equivocating_indices.discard(int(index))
