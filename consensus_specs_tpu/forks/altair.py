"""Altair fork: sync committees, participation-flag accounting, inactivity
scores.

Behavioral source: ``specs/altair/beacon-chain.md`` (constants ~:60, new
containers ~:120, helpers ``get_next_sync_committee_indices`` :275,
``process_sync_aggregate`` :535, flag-based epoch accounting
:300-530), ``specs/altair/bls.md`` (eth_aggregate_pubkeys :25,
eth_fast_aggregate_verify :61) and ``specs/altair/fork.md``
(``upgrade_to_altair`` :77, ``translate_participation`` :61).

Fork inheritance = class inheritance over :class:`Phase0Spec`; only the
altair deltas live here (the reference gets the same effect from markdown
dict-merge, ``pysetup/helpers.py:222-247``).
"""
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint8, uint64, Bytes32,
    Bitvector, Bitlist, Vector, List, Container,
)  # noqa: F401 (compiled-spec namespace)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.ops import epoch_kernels
from . import register_fork
from .phase0 import Phase0Spec
from .light_client import LightClientMixin
from .validator_guide import SyncDutiesMixin
from .base_types import (
    Slot, Epoch, ValidatorIndex, Gwei, Root, Version, BLSPubkey, BLSSignature,
    ParticipationFlags, GENESIS_EPOCH,
    DOMAIN_SYNC_COMMITTEE, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_CONTRIBUTION_AND_PROOF,
)  # noqa: F401 (compiled-spec namespace)

# incentivization weights (specs/altair/beacon-chain.md "Incentivization")
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = uint64(14)
TIMELY_TARGET_WEIGHT = uint64(26)
TIMELY_HEAD_WEIGHT = uint64(14)
SYNC_REWARD_WEIGHT = uint64(2)
PROPOSER_WEIGHT = uint64(8)
WEIGHT_DENOMINATOR = uint64(64)
PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT]

G2_POINT_AT_INFINITY = BLSSignature(b"\xc0" + b"\x00" * 95)


@register_fork("altair")
class AltairSpec(SyncDutiesMixin, LightClientMixin, Phase0Spec):
    fork = "altair"
    previous_fork = "phase0"

    TIMELY_SOURCE_FLAG_INDEX = TIMELY_SOURCE_FLAG_INDEX
    TIMELY_TARGET_FLAG_INDEX = TIMELY_TARGET_FLAG_INDEX
    TIMELY_HEAD_FLAG_INDEX = TIMELY_HEAD_FLAG_INDEX
    TIMELY_SOURCE_WEIGHT = TIMELY_SOURCE_WEIGHT
    TIMELY_TARGET_WEIGHT = TIMELY_TARGET_WEIGHT
    TIMELY_HEAD_WEIGHT = TIMELY_HEAD_WEIGHT
    SYNC_REWARD_WEIGHT = SYNC_REWARD_WEIGHT
    PROPOSER_WEIGHT = PROPOSER_WEIGHT
    WEIGHT_DENOMINATOR = WEIGHT_DENOMINATOR
    PARTICIPATION_FLAG_WEIGHTS = PARTICIPATION_FLAG_WEIGHTS
    G2_POINT_AT_INFINITY = G2_POINT_AT_INFINITY
    DOMAIN_SYNC_COMMITTEE = DOMAIN_SYNC_COMMITTEE
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF
    DOMAIN_CONTRIBUTION_AND_PROOF = DOMAIN_CONTRIBUTION_AND_PROOF
    ParticipationFlags = ParticipationFlags

    # -- type construction ---------------------------------------------------

    def _build_types(self):
        # sync-committee containers must exist before the base builder runs,
        # because it consults the overridden _block_body_fields/_state_fields
        S = self

        class SyncAggregate(Container):
            sync_committee_bits: Bitvector[S.SYNC_COMMITTEE_SIZE]
            sync_committee_signature: BLSSignature

        class SyncCommittee(Container):
            pubkeys: Vector[BLSPubkey, S.SYNC_COMMITTEE_SIZE]
            aggregate_pubkey: BLSPubkey

        self.SyncAggregate = SyncAggregate
        self.SyncCommittee = SyncCommittee
        super()._build_types()
        # light-client containers need BeaconState/BeaconBlockHeader built
        self._build_light_client_types()
        self._build_sync_duty_types()

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        fields["sync_aggregate"] = self.SyncAggregate
        return fields

    def _state_fields(self, t) -> dict:
        """Altair BeaconState layout: pending attestations are replaced by
        participation lists (same position), with inactivity scores and the
        two sync committees appended at the tail."""
        S = self
        fields = super()._state_fields(t)
        out = {}
        for k, v in fields.items():
            if k == "previous_epoch_attestations":
                out["previous_epoch_participation"] = \
                    List[ParticipationFlags, S.VALIDATOR_REGISTRY_LIMIT]
                out["current_epoch_participation"] = \
                    List[ParticipationFlags, S.VALIDATOR_REGISTRY_LIMIT]
            elif k == "current_epoch_attestations":
                continue
            else:
                out[k] = v
        out["inactivity_scores"] = List[uint64, S.VALIDATOR_REGISTRY_LIMIT]
        out["current_sync_committee"] = self.SyncCommittee
        out["next_sync_committee"] = self.SyncCommittee
        return out

    # -- crypto wrappers (specs/altair/bls.md) ------------------------------

    def eth_aggregate_pubkeys(self, pubkeys):
        """bls.md:25 - aggregate of 1+ pubkeys (asserts non-empty)."""
        assert len(pubkeys) > 0
        return bls.AggregatePKs(pubkeys)

    def eth_fast_aggregate_verify(self, pubkeys, message, signature) -> bool:
        """bls.md:61 - empty set + infinity signature verifies True."""
        if len(pubkeys) == 0 and signature == G2_POINT_AT_INFINITY:
            return True
        return bls.FastAggregateVerify(pubkeys, message, signature)

    # -- participation flags ------------------------------------------------

    def add_flag(self, flags, flag_index):
        return ParticipationFlags(flags | (2 ** flag_index))

    def has_flag(self, flags, flag_index) -> bool:
        flag = 2 ** flag_index
        return flags & flag == flag

    # -- sync committee selection (beacon-chain.md:275) ---------------------

    def get_next_sync_committee_indices(self, state):
        """Seeded effective-balance-weighted sampling via shuffled indices."""
        epoch = self.Epoch(self.get_current_epoch(state) + 1)
        MAX_RANDOM_BYTE = 2 ** 8 - 1
        active_validator_indices = self.get_active_validator_indices(state, epoch)
        active_validator_count = uint64(len(active_validator_indices))
        seed = self.get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
        i = 0
        sync_committee_indices = []
        while len(sync_committee_indices) < self.SYNC_COMMITTEE_SIZE:
            shuffled_index = self.compute_shuffled_index(
                uint64(i % active_validator_count), active_validator_count, seed)
            candidate_index = active_validator_indices[shuffled_index]
            random_byte = self.hash(
                seed + self.uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = state.validators[candidate_index].effective_balance
            if effective_balance * MAX_RANDOM_BYTE >= \
                    self.MAX_EFFECTIVE_BALANCE * random_byte:
                sync_committee_indices.append(candidate_index)
            i += 1
        return sync_committee_indices

    def get_next_sync_committee(self, state):
        indices = self.get_next_sync_committee_indices(state)
        pubkeys = [state.validators[index].pubkey for index in indices]
        aggregate_pubkey = self.eth_aggregate_pubkeys(pubkeys)
        return self.SyncCommittee(pubkeys=pubkeys,
                                  aggregate_pubkey=aggregate_pubkey)

    # -- participation / reward helpers -------------------------------------

    def get_base_reward_per_increment(self, state):
        return Gwei(self.EFFECTIVE_BALANCE_INCREMENT
                    * self.BASE_REWARD_FACTOR
                    // self.integer_squareroot(self.get_total_active_balance(state)))

    def get_base_reward(self, state, index):
        """Altair redefinition (beacon-chain.md Participation-flags rewards)."""
        increments = (state.validators[index].effective_balance
                      // self.EFFECTIVE_BALANCE_INCREMENT)
        return Gwei(increments * self.get_base_reward_per_increment(state))

    def get_unslashed_participating_indices(self, state, flag_index, epoch):
        assert epoch in (self.get_previous_epoch(state),
                         self.get_current_epoch(state))
        if epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation
        active_validator_indices = self.get_active_validator_indices(state, epoch)
        participating_indices = [
            i for i in active_validator_indices
            if self.has_flag(epoch_participation[i], flag_index)]
        return set(
            self.filter_out_slashed(state, participating_indices))

    def filter_out_slashed(self, state, indices):
        return [index for index in indices
                if not state.validators[index].slashed]

    def get_attestation_participation_flag_indices(self, state, data,
                                                   inclusion_delay):
        """Flags an attestation earns given its correctness + timeliness."""
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint
        is_matching_source = data.source == justified_checkpoint
        is_matching_target = is_matching_source and bytes(data.target.root) == \
            bytes(self.get_block_root(state, data.target.epoch))
        is_matching_head = is_matching_target and \
            bytes(data.beacon_block_root) == \
            bytes(self.get_block_root_at_slot(state, data.slot))
        assert is_matching_source

        participation_flag_indices = []
        if is_matching_source and inclusion_delay <= \
                self.integer_squareroot(self.SLOTS_PER_EPOCH):
            participation_flag_indices.append(TIMELY_SOURCE_FLAG_INDEX)
        if is_matching_target and inclusion_delay <= self.SLOTS_PER_EPOCH:
            participation_flag_indices.append(TIMELY_TARGET_FLAG_INDEX)
        if is_matching_head and inclusion_delay == \
                self.MIN_ATTESTATION_INCLUSION_DELAY:
            participation_flag_indices.append(TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def get_flag_index_deltas(self, state, flag_index):
        """Reward/penalty deltas for one participation flag."""
        rewards = [Gwei(0)] * len(state.validators)
        penalties = [Gwei(0)] * len(state.validators)
        previous_epoch = self.get_previous_epoch(state)
        unslashed_participating_indices = \
            self.get_unslashed_participating_indices(state, flag_index,
                                                     previous_epoch)
        weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]
        unslashed_participating_balance = self.get_total_balance(
            state, unslashed_participating_indices)
        unslashed_participating_increments = (
            unslashed_participating_balance // self.EFFECTIVE_BALANCE_INCREMENT)
        active_increments = (self.get_total_active_balance(state)
                             // self.EFFECTIVE_BALANCE_INCREMENT)
        for index in self.get_eligible_validator_indices(state):
            base_reward = self.get_base_reward(state, index)
            if index in unslashed_participating_indices:
                if not self.is_in_inactivity_leak(state):
                    reward_numerator = (base_reward * weight
                                        * unslashed_participating_increments)
                    rewards[index] += Gwei(reward_numerator
                                           // (active_increments
                                               * WEIGHT_DENOMINATOR))
            elif flag_index != TIMELY_HEAD_FLAG_INDEX:
                penalties[index] += Gwei(base_reward * weight
                                         // WEIGHT_DENOMINATOR)
        return rewards, penalties

    def get_inactivity_penalty_deltas(self, state):
        """Altair inactivity penalties via inactivity scores."""
        rewards = [Gwei(0)] * len(state.validators)
        penalties = [Gwei(0)] * len(state.validators)
        previous_epoch = self.get_previous_epoch(state)
        matching_target_indices = self.get_unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, previous_epoch)
        for index in self.get_eligible_validator_indices(state):
            if index not in matching_target_indices:
                penalty_numerator = (state.validators[index].effective_balance
                                     * state.inactivity_scores[index])
                penalty_denominator = (self.config.INACTIVITY_SCORE_BIAS
                                       * self.INACTIVITY_PENALTY_QUOTIENT_ALTAIR)
                penalties[index] += Gwei(penalty_numerator // penalty_denominator)
        return rewards, penalties

    # -- mutators ------------------------------------------------------------

    def slash_validator(self, state, slashed_index, whistleblower_index=None):
        """Altair variant: different slashing quotient + proposer reward
        weighting (beacon-chain.md Modified slash_validator)."""
        epoch = self.get_current_epoch(state)
        self.initiate_validator_exit(state, slashed_index)
        validator = state.validators[slashed_index]
        validator.slashed = True
        validator.withdrawable_epoch = max(
            validator.withdrawable_epoch,
            self.Epoch(epoch + self.EPOCHS_PER_SLASHINGS_VECTOR))
        state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] += \
            validator.effective_balance
        slashing_penalty = (validator.effective_balance
                            // self.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR)
        self.decrease_balance(state, slashed_index, slashing_penalty)

        proposer_index = self.get_beacon_proposer_index(state)
        if whistleblower_index is None:
            whistleblower_index = proposer_index
        whistleblower_reward = Gwei(validator.effective_balance
                                    // self.WHISTLEBLOWER_REWARD_QUOTIENT)
        proposer_reward = Gwei(whistleblower_reward * PROPOSER_WEIGHT
                               // WEIGHT_DENOMINATOR)
        self.increase_balance(state, proposer_index, proposer_reward)
        self.increase_balance(state, whistleblower_index,
                              Gwei(whistleblower_reward - proposer_reward))

    # -- block processing ----------------------------------------------------

    def process_block(self, state, block):
        # Same batched-signature discipline as phase0.process_block; the
        # sync-aggregate verify (<=512 pubkeys) joins the block batch too.
        with bls.batched_verification() as batch:
            self.process_block_header(state, block)
            self.process_randao(state, block.body)
            self.process_eth1_data(state, block.body)
            self.process_operations(state, block.body)
            self.process_sync_aggregate(state, block.body.sync_aggregate)
        batch.assert_valid()

    def process_attestation(self, state, attestation):
        """Altair rewrite: flags + immediate proposer reward."""
        data = attestation.data
        assert data.target.epoch in (self.get_previous_epoch(state),
                                     self.get_current_epoch(state))
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot)
        assert (data.slot + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
                <= data.slot + self.SLOTS_PER_EPOCH)
        assert data.index < self.get_committee_count_per_slot(state,
                                                              data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee)

        participation_flag_indices = \
            self.get_attestation_participation_flag_indices(
                state, data, state.slot - data.slot)

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(
                state, data, attestation.aggregation_bits):
            for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and \
                        not self.has_flag(epoch_participation[index], flag_index):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index)
                    proposer_reward_numerator += \
                        self.get_base_reward(state, index) * weight

        proposer_reward_denominator = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                                       * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
        proposer_reward = Gwei(proposer_reward_numerator
                               // proposer_reward_denominator)
        self.increase_balance(state, self.get_beacon_proposer_index(state),
                              proposer_reward)

    def add_validator_to_registry(self, state, pubkey,
                                  withdrawal_credentials, amount):
        super().add_validator_to_registry(state, pubkey,
                                          withdrawal_credentials, amount)
        state.previous_epoch_participation.append(ParticipationFlags(0))
        state.current_epoch_participation.append(ParticipationFlags(0))
        state.inactivity_scores.append(uint64(0))

    def process_sync_aggregate(self, state, sync_aggregate):
        """beacon-chain.md:535 - one aggregate verify over <=512 pubkeys,
        then the per-participant balance loop."""
        committee_pubkeys = state.current_sync_committee.pubkeys
        participant_pubkeys = [
            pubkey for pubkey, bit in
            zip(committee_pubkeys, sync_aggregate.sync_committee_bits) if bit]
        previous_slot = max(state.slot, Slot(1)) - Slot(1)
        domain = self.get_domain(state, DOMAIN_SYNC_COMMITTEE,
                                 self.compute_epoch_at_slot(previous_slot))
        signing_root = self.compute_signing_root(
            self.get_block_root_at_slot(state, previous_slot), domain)
        assert self.eth_fast_aggregate_verify(
            participant_pubkeys, signing_root,
            sync_aggregate.sync_committee_signature)

        total_active_increments = (self.get_total_active_balance(state)
                                   // self.EFFECTIVE_BALANCE_INCREMENT)
        total_base_rewards = Gwei(self.get_base_reward_per_increment(state)
                                  * total_active_increments)
        max_participant_rewards = Gwei(total_base_rewards * SYNC_REWARD_WEIGHT
                                       // WEIGHT_DENOMINATOR
                                       // self.SLOTS_PER_EPOCH)
        participant_reward = Gwei(max_participant_rewards
                                  // self.SYNC_COMMITTEE_SIZE)
        proposer_reward = Gwei(participant_reward * PROPOSER_WEIGHT
                               // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT))

        all_pubkeys = [v.pubkey for v in state.validators]
        committee_indices = [
            ValidatorIndex(all_pubkeys.index(pubkey))
            for pubkey in state.current_sync_committee.pubkeys]
        for participant_index, participation_bit in zip(
                committee_indices, sync_aggregate.sync_committee_bits):
            if participation_bit:
                self.increase_balance(state, participant_index,
                                      participant_reward)
                self.increase_balance(
                    state, self.get_beacon_proposer_index(state),
                    proposer_reward)
            else:
                self.decrease_balance(state, participant_index,
                                      participant_reward)

    # -- epoch processing ----------------------------------------------------

    def process_epoch(self, state):
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_flag_updates(state)
        self.process_sync_committee_updates(state)

    def process_justification_and_finalization(self, state):
        """Altair variant driven by target-flag participation."""
        if self.get_current_epoch(state) <= GENESIS_EPOCH + 1:
            return
        previous_indices = self.get_unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, self.get_previous_epoch(state))
        current_indices = self.get_unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, self.get_current_epoch(state))
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_total_balance(state, previous_indices)
        current_target_balance = self.get_total_balance(state, current_indices)
        self.weigh_justification_and_finalization(
            state, total_active_balance,
            previous_target_balance, current_target_balance)

    def process_inactivity_updates(self, state):
        if epoch_kernels.try_process_inactivity_updates(self, state):
            return
        if self.get_current_epoch(state) == GENESIS_EPOCH:
            return
        participating = self.get_unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, self.get_previous_epoch(state))
        for index in self.get_eligible_validator_indices(state):
            if index in participating:
                state.inactivity_scores[index] -= min(
                    uint64(1), state.inactivity_scores[index])
            else:
                state.inactivity_scores[index] += \
                    self.config.INACTIVITY_SCORE_BIAS
            if not self.is_in_inactivity_leak(state):
                state.inactivity_scores[index] -= min(
                    self.config.INACTIVITY_SCORE_RECOVERY_RATE,
                    state.inactivity_scores[index])

    def process_rewards_and_penalties(self, state):
        if epoch_kernels.try_process_rewards_and_penalties(self, state):
            return
        if self.get_current_epoch(state) == GENESIS_EPOCH:
            return
        flag_deltas = [self.get_flag_index_deltas(state, flag_index)
                       for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS))]
        deltas = flag_deltas + [self.get_inactivity_penalty_deltas(state)]
        for (rewards, penalties) in deltas:
            for index in range(len(state.validators)):
                self.increase_balance(state, ValidatorIndex(index),
                                      rewards[index])
                self.decrease_balance(state, ValidatorIndex(index),
                                      penalties[index])

    def process_slashings(self, state):
        if epoch_kernels.try_process_slashings(self, state):
            return
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(state.slashings) * self.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
            total_balance)
        for index, validator in enumerate(state.validators):
            if validator.slashed and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR \
                    // 2 == validator.withdrawable_epoch:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                penalty_numerator = (validator.effective_balance // increment
                                     * adjusted_total_slashing_balance)
                penalty = penalty_numerator // total_balance * increment
                self.decrease_balance(state, ValidatorIndex(index), penalty)

    def process_participation_flag_updates(self, state):
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = type(
            state.current_epoch_participation)(
                *[ParticipationFlags(0) for _ in range(len(state.validators))])

    def process_sync_committee_updates(self, state):
        next_epoch = self.get_current_epoch(state) + self.Epoch(1)
        if next_epoch % self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
            state.current_sync_committee = state.next_sync_committee
            state.next_sync_committee = self.get_next_sync_committee(state)

    def process_participation_record_updates(self, state):
        raise AttributeError("phase0-only (replaced by participation flags)")

    # -- fork upgrade (specs/altair/fork.md) ---------------------------------

    def translate_participation(self, post, pending_attestations):
        """fork.md:61 - re-grant flags for pending phase0 attestations."""
        for attestation in pending_attestations:
            data = attestation.data
            inclusion_delay = attestation.inclusion_delay
            participation_flag_indices = \
                self.get_attestation_participation_flag_indices(
                    post, data, inclusion_delay)
            epoch_participation = post.previous_epoch_participation
            # get_attesting_indices is inherited unchanged from phase0
            for index in self.get_attesting_indices(
                    post, data, attestation.aggregation_bits):
                for flag_index in participation_flag_indices:
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index)

    def upgrade_to_altair(self, pre):
        """fork.md:77 - phase0 BeaconState -> altair BeaconState."""
        epoch = self.get_current_epoch(pre)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.ALTAIR_FORK_VERSION,
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=pre.block_roots,
            state_roots=pre.state_roots,
            historical_roots=pre.historical_roots,
            eth1_data=pre.eth1_data,
            eth1_data_votes=pre.eth1_data_votes,
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=pre.validators,
            balances=pre.balances,
            randao_mixes=pre.randao_mixes,
            slashings=pre.slashings,
            previous_epoch_participation=[
                ParticipationFlags(0) for _ in range(len(pre.validators))],
            current_epoch_participation=[
                ParticipationFlags(0) for _ in range(len(pre.validators))],
            justification_bits=pre.justification_bits,
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=[uint64(0) for _ in range(len(pre.validators))],
        )
        self.translate_participation(post, pre.previous_epoch_attestations)
        sync_committee = self.get_next_sync_committee(post)
        post.current_sync_committee = sync_committee
        post.next_sync_committee = self.get_next_sync_committee(post)
        return post

    def initialize_beacon_state_from_eth1(self, eth1_block_hash,
                                          eth1_timestamp, deposits):
        """Altair testing variant (``specs/altair/beacon-chain.md``
        Testing section): genesis at the altair fork version, sync
        committees pre-filled (current == next at genesis)."""
        state = super().initialize_beacon_state_from_eth1(
            eth1_block_hash, eth1_timestamp, deposits)
        state.fork.previous_version = self.config.ALTAIR_FORK_VERSION
        state.fork.current_version = self.config.ALTAIR_FORK_VERSION
        state.current_sync_committee = self.get_next_sync_committee(state)
        state.next_sync_committee = self.get_next_sync_committee(state)
        return state

    # -- mock genesis hook ---------------------------------------------------

    def post_mock_genesis(self, state):
        """Fill altair-only genesis fields for harness-built states."""
        for _ in range(len(state.validators)):
            state.previous_epoch_participation.append(ParticipationFlags(0))
            state.current_epoch_participation.append(ParticipationFlags(0))
            state.inactivity_scores.append(uint64(0))
        sync_committee = self.get_next_sync_committee(state)
        state.current_sync_committee = sync_committee
        state.next_sync_committee = self.get_next_sync_committee(state)
