"""Sharding research fork: shard-data commitments over the beacon chain.

Behavioral source: the reference's sharding feature
(``specs/_features/sharding/``, preset ``presets/minimal/sharding.yaml``)
and the shard-transition data model its custody-game spec builds on
(``specs/_features/custody_game/beacon-chain.md:169-200`` references
``sharding.ShardTransition``; the surviving executable contract is the
sharding unittest suite ``test/sharding/unittests/test_get_start_shard.py``
— ``get_active_shard_count``, ``get_committee_count_delta``,
``get_start_shard``, ``state.current_epoch_start_shard``).

NOTE ON LINEAGE: the reference's sharding markdown at this version is a
work-in-progress rewrite (builder-block bids / sharded-commitment
containers) that is internally inconsistent — it references containers and
helpers that no longer exist, and its own test suite + preset files still
pin the EARLIER shard-header design (``ShardTransition``,
``current_epoch_start_shard``); the fork is excluded from the reference's
pyspec build entirely. This module implements the earlier design as the
EXECUTABLE surface (it is the one with a behavioral contract: the tests
and the custody game), parented on phase0 exactly as the original phase-1
lineage was. The rewrite's containers are documented in
``specs/_features/sharding/beacon-chain.md`` as prose.
"""
from consensus_specs_tpu.utils.ssz import (
    Container, List, uint64, Bytes32,
)
from . import register_fork
from .phase0 import Phase0Spec
from .base_types import (
    Slot, Gwei, Root, BLSSignature, DomainType,
)

Shard = uint64


@register_fork("sharding")
class ShardingSpec(Phase0Spec):
    fork = "sharding"
    previous_fork = "phase0"

    # Constants (non-configurable; sharding/beacon-chain.md "Misc")
    DOMAIN_SHARD_PROPOSER = DomainType("0x80000000")
    DOMAIN_SHARD_COMMITTEE = DomainType("0x81000000")
    # Shard-data geometry of the shard-header design: one attestation
    # crosslinks up to this many shard blocks, each at most
    # MAX_SHARD_BLOCK_SIZE bytes (the custody game's chunking base).
    MAX_SHARD_BLOCKS_PER_ATTESTATION = 12
    MAX_SHARD_BLOCK_SIZE = 2**20

    def get_active_shard_count(self, state, epoch=None) -> uint64:
        """Number of active shards (upper-bounds committees/slot).

        The epoch argument is accepted for forward compatibility with the
        epoch-dependent shard count of later designs; the count is a
        preset constant here (reference sharding preset
        ``INITIAL_ACTIVE_SHARDS``)."""
        return uint64(self.INITIAL_ACTIVE_SHARDS)

    def get_committee_count_delta(self, state, start_slot, stop_slot) -> uint64:
        """Sum of committee counts over ``[start_slot, stop_slot)``."""
        return uint64(sum(
            self.get_committee_count_per_slot(
                state, self.compute_epoch_at_slot(Slot(s)))
            for s in range(start_slot, stop_slot)
        ))

    def get_start_shard(self, state, slot) -> Shard:
        """Start shard of the committee rotation at ``slot``.

        Walks per-slot from the current epoch start, adding (future) or
        subtracting (past) that slot's committee count mod the active
        shard count; the subtraction is biased by a multiple of the shard
        count so it never goes negative."""
        current_epoch_start_slot = self.compute_start_slot_at_epoch(
            self.get_current_epoch(state))
        shard = int(state.current_epoch_start_shard)
        if slot > current_epoch_start_slot:
            for s in range(current_epoch_start_slot, slot):
                committee_count = self.get_committee_count_per_slot(
                    state, self.compute_epoch_at_slot(Slot(s)))
                active_shards = self.get_active_shard_count(
                    state, self.compute_epoch_at_slot(Slot(s)))
                shard = (shard + int(committee_count)) % int(active_shards)
        elif slot < current_epoch_start_slot:
            for s in reversed(range(slot, current_epoch_start_slot)):
                committee_count = self.get_committee_count_per_slot(
                    state, self.compute_epoch_at_slot(Slot(s)))
                active_shards = self.get_active_shard_count(
                    state, self.compute_epoch_at_slot(Slot(s)))
                shard = (shard
                         + int(active_shards) * int(self.MAX_COMMITTEES_PER_SLOT)
                         - int(committee_count)) % int(active_shards)
        return Shard(shard)

    # -- types ------------------------------------------------------------
    def _build_types(self):
        class ShardState(Container):
            slot: Slot
            gasprice: Gwei
            latest_block_root: Root

        S = self

        class ShardTransition(Container):
            start_slot: Slot
            shard_block_lengths: List[uint64, S.MAX_SHARD_BLOCKS_PER_ATTESTATION]
            shard_data_roots: List[Bytes32, S.MAX_SHARD_BLOCKS_PER_ATTESTATION]
            shard_states: List[ShardState, S.MAX_SHARD_BLOCKS_PER_ATTESTATION]
            proposer_signature_aggregate: BLSSignature

        self.ShardState = ShardState
        self.ShardTransition = ShardTransition
        super()._build_types()

    def _attestation_data_fields(self, t) -> dict:
        fields = super()._attestation_data_fields(t)
        # Crosslink commitment: the attested shard-transition root the
        # custody game challenges against (custody_game/beacon-chain.md
        # ``challenge.attestation.data.shard_transition_root``).
        fields["shard_transition_root"] = Root
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields["current_epoch_start_shard"] = Shard
        return fields

    # -- epoch processing -------------------------------------------------
    def process_shard_epoch_increment(self, state) -> None:
        """Rotate ``current_epoch_start_shard`` by the epoch's total
        committee count (what makes ``get_start_shard`` O(epoch-local))."""
        epoch_start = self.compute_start_slot_at_epoch(
            self.get_current_epoch(state))
        delta = self.get_committee_count_delta(
            state, epoch_start, epoch_start + self.SLOTS_PER_EPOCH)
        state.current_epoch_start_shard = Shard(
            (int(state.current_epoch_start_shard) + int(delta))
            % int(self.get_active_shard_count(state)))

    def process_epoch(self, state) -> None:
        super().process_epoch(state)
        self.process_shard_epoch_increment(state)
