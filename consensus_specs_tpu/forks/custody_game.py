"""Custody game research fork: proof-of-custody over shard data.

Behavioral source: ``specs/_features/custody_game/beacon-chain.md``
(constants :66-118, containers :120-240, helpers :245-345, block
processing :350-640, epoch processing :645-707) and the reference's
custody-game conformance suite
(``tests/core/pyspec/eth2spec/test/custody_game/``, 42 tests — the fork
is excluded from the reference's pyspec build, so those tests are the
only executable contract upstream).

Validators custody the shard data they attest to; a bit derived from the
data and a period secret (a BLS signature over the period's RANDAO epoch)
can be challenged (chunk challenges), must be revealed on schedule (key
reveals), and is slashable both for early reveals of derived secrets and
for incorrect custody claims (custody slashings).

Fork DAG parent: sharding (``custody_game/beacon-chain.md:63`` "building
upon the Sharding specification"); see ``sharding.py`` for the lineage
note.
"""
from consensus_specs_tpu.utils.ssz import (
    Container, List, Vector, ByteVector, ByteList, uint64, Bytes32,
    hash_tree_root, boolean,
)
from consensus_specs_tpu.utils import bls
from . import register_fork
from .sharding import ShardingSpec
from .base_types import (
    Epoch, Gwei, Root, ValidatorIndex, BLSSignature, DomainType,
    FAR_FUTURE_EPOCH,
)


def _ceillog2(n: int) -> int:
    assert n >= 1
    return (n - 1).bit_length()


@register_fork("custody_game")
class CustodyGameSpec(ShardingSpec):
    fork = "custody_game"
    previous_fork = "sharding"

    # custody's epoch ordering interleaves spec-loop balance writes
    # (reveal/challenge deadline slashings) between the engine
    # sub-transitions — deferred column commits would expose stale
    # balances to them, so this fork commits per sub-transition
    _defer_epoch_commits = False

    # Constants (beacon-chain.md "Misc")
    CUSTODY_PRIME = 2**256 - 189
    CUSTODY_SECRETS = 3
    BYTES_PER_CUSTODY_ATOM = 32
    CUSTODY_PROBABILITY_EXPONENT = 10
    BYTES_PER_CUSTODY_CHUNK = 2**12
    DOMAIN_CUSTODY_BIT_SLASHING = DomainType("0x83000000")
    # Preset value not customized by any preset file (beacon-chain.md
    # "Max operations per block"): 2**20 challenge-record slots.
    MAX_CUSTODY_CHUNK_CHALLENGE_RECORDS = 2**20

    @property
    def CUSTODY_RESPONSE_DEPTH(self) -> int:
        return _ceillog2(self.MAX_SHARD_BLOCK_SIZE
                         // self.BYTES_PER_CUSTODY_CHUNK)

    # -- types ------------------------------------------------------------
    def _validator_fields(self) -> dict:
        fields = super()._validator_fields()
        # Initialized to the validator's custody period at activation;
        # FAR_FUTURE_EPOCH until all secrets are revealed post-exit.
        fields["next_custody_secret_to_reveal"] = uint64
        fields["all_custody_secrets_revealed_epoch"] = Epoch
        return fields

    def finalize_mock_validator(self, validator, index: int) -> None:
        """Genesis hook: custody fields that are NOT zero-default."""
        validator.all_custody_secrets_revealed_epoch = FAR_FUTURE_EPOCH
        validator.next_custody_secret_to_reveal = \
            self.get_custody_period_for_validator(
                ValidatorIndex(index), self.GENESIS_EPOCH)

    def _build_custody_operation_types(self, Attestation):
        """Custody operation containers; called from the
        ``_block_body_fields`` hook once ``Attestation`` exists (the
        challenge/slashing ops embed whole attestations)."""
        S = self
        ShardTransition = self.ShardTransition

        class CustodyChunkChallenge(Container):
            responder_index: ValidatorIndex
            shard_transition: ShardTransition
            attestation: Attestation
            data_index: uint64
            chunk_index: uint64

        class CustodyChunkChallengeRecord(Container):
            challenge_index: uint64
            challenger_index: ValidatorIndex
            responder_index: ValidatorIndex
            inclusion_epoch: Epoch
            data_root: Root
            chunk_index: uint64

        class CustodyChunkResponse(Container):
            challenge_index: uint64
            chunk_index: uint64
            chunk: ByteVector[S.BYTES_PER_CUSTODY_CHUNK]
            branch: Vector[Root, S.CUSTODY_RESPONSE_DEPTH + 1]

        class CustodySlashing(Container):
            # shard_transition.shard_data_roots[data_index] commits to data
            data_index: uint64
            malefactor_index: ValidatorIndex
            malefactor_secret: BLSSignature
            whistleblower_index: ValidatorIndex
            shard_transition: ShardTransition
            attestation: Attestation
            data: ByteList[S.MAX_SHARD_BLOCK_SIZE]

        class SignedCustodySlashing(Container):
            message: CustodySlashing
            signature: BLSSignature

        class CustodyKeyReveal(Container):
            revealer_index: ValidatorIndex
            reveal: BLSSignature

        class EarlyDerivedSecretReveal(Container):
            revealed_index: ValidatorIndex
            epoch: Epoch
            reveal: BLSSignature
            masker_index: ValidatorIndex
            mask: Bytes32

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                setattr(self, name, typ)

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        self._build_custody_operation_types(t["Attestation"])
        fields.update(self._custody_body_fields())
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields.update(self._custody_state_fields())
        return fields

    def _custody_body_fields(self) -> dict:
        S = self
        return {
            "chunk_challenges": List[S.CustodyChunkChallenge,
                                     S.MAX_CUSTODY_CHUNK_CHALLENGES],
            "chunk_challenge_responses": List[
                S.CustodyChunkResponse, S.MAX_CUSTODY_CHUNK_CHALLENGE_RESP],
            "custody_key_reveals": List[S.CustodyKeyReveal,
                                        S.MAX_CUSTODY_KEY_REVEALS],
            "early_derived_secret_reveals": List[
                S.EarlyDerivedSecretReveal,
                S.MAX_EARLY_DERIVED_SECRET_REVEALS],
            "custody_slashings": List[S.SignedCustodySlashing,
                                      S.MAX_CUSTODY_SLASHINGS],
        }

    def _custody_state_fields(self) -> dict:
        S = self
        return {
            "exposed_derived_secrets": Vector[
                List[ValidatorIndex,
                     S.MAX_EARLY_DERIVED_SECRET_REVEALS * S.SLOTS_PER_EPOCH],
                S.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS],
            "custody_chunk_challenge_records": List[
                S.CustodyChunkChallengeRecord,
                S.MAX_CUSTODY_CHUNK_CHALLENGE_RECORDS],
            "custody_chunk_challenge_index": uint64,
        }

    # -- helpers (beacon-chain.md "Helpers") -------------------------------
    @staticmethod
    def replace_empty_or_append(lst, new_element) -> int:
        for i in range(len(lst)):
            if lst[i] == type(new_element)():
                lst[i] = new_element
                return i
        lst.append(new_element)
        return len(lst) - 1

    @staticmethod
    def legendre_bit(a: int, q: int) -> int:
        """((a/q) + 1) // 2 via the binary Jacobi algorithm."""
        if a >= q:
            return CustodyGameSpec.legendre_bit(a % q, q)
        if a == 0:
            return 0
        assert q > a > 0 and q % 2 == 1
        t, n = 1, q
        while a != 0:
            while a % 2 == 0:
                a //= 2
                r = n % 8
                if r == 3 or r == 5:
                    t = -t
            a, n = n, a
            if a % 4 == n % 4 == 3:
                t = -t
            a %= n
        return (t + 1) // 2 if n == 1 else 0

    def get_custody_atoms(self, bytez: bytes):
        """Right-pad to atom size and split into 32-byte atoms."""
        bytez = bytes(bytez)
        pad = (self.BYTES_PER_CUSTODY_ATOM
               - len(bytez) % self.BYTES_PER_CUSTODY_ATOM) \
            % self.BYTES_PER_CUSTODY_ATOM
        bytez += b"\x00" * pad
        return [bytez[i:i + self.BYTES_PER_CUSTODY_ATOM]
                for i in range(0, len(bytez), self.BYTES_PER_CUSTODY_ATOM)]

    def get_custody_secrets(self, key):
        """Secrets = 32-byte little-endian windows over the signature's
        G2 x-coordinate (c0 || c1, 48-byte little-endian each)."""
        from consensus_specs_tpu.ops.bls12_381.curve import g2_from_compressed
        pt = g2_from_compressed(bytes(key))
        signature_bytes = (int(pt.x.a.n).to_bytes(48, "little")
                           + int(pt.x.b.n).to_bytes(48, "little"))
        return [int.from_bytes(signature_bytes[i:i + self.BYTES_PER_CUSTODY_ATOM],
                               "little")
                for i in range(0, len(signature_bytes), 32)]

    def universal_hash_function(self, data_chunks, secrets) -> int:
        n = len(data_chunks)
        P = self.CUSTODY_PRIME
        return (
            sum(
                pow(secrets[i % self.CUSTODY_SECRETS], i, P)
                * int.from_bytes(atom, "little") % P
                for i, atom in enumerate(data_chunks)
            ) + pow(secrets[n % self.CUSTODY_SECRETS], n, P)
        ) % P

    def compute_custody_bit(self, key, data) -> int:
        custody_atoms = self.get_custody_atoms(data)
        secrets = self.get_custody_secrets(key)
        uhf = self.universal_hash_function(custody_atoms, secrets)
        legendre_bits = [
            self.legendre_bit(uhf + secrets[0] + i, self.CUSTODY_PRIME)
            for i in range(self.CUSTODY_PROBABILITY_EXPONENT)]
        return boolean(all(legendre_bits))

    def get_randao_epoch_for_custody_period(self, period, validator_index) -> Epoch:
        next_period_start = (int(period) + 1) * self.EPOCHS_PER_CUSTODY_PERIOD \
            - int(validator_index) % self.EPOCHS_PER_CUSTODY_PERIOD
        return Epoch(next_period_start + self.CUSTODY_PERIOD_TO_RANDAO_PADDING)

    def get_custody_period_for_validator(self, validator_index, epoch) -> uint64:
        """Reveal period of ``validator_index`` at ``epoch``."""
        return uint64((int(epoch)
                       + int(validator_index) % self.EPOCHS_PER_CUSTODY_PERIOD)
                      // self.EPOCHS_PER_CUSTODY_PERIOD)

    # -- block processing --------------------------------------------------
    def process_block(self, state, block) -> None:
        # The defunct phase-1 light-client aggregate stage is omitted
        # (sharding.py lineage note); everything else follows
        # custody_game/beacon-chain.md "Block processing".
        super().process_block(state, block)
        self.process_custody_game_operations(state, block.body)

    def process_custody_game_operations(self, state, body) -> None:
        def for_ops(operations, fn):
            for operation in operations:
                fn(state, operation)

        for_ops(body.chunk_challenges, self.process_chunk_challenge)
        for_ops(body.chunk_challenge_responses,
                self.process_chunk_challenge_response)
        for_ops(body.custody_key_reveals, self.process_custody_key_reveal)
        for_ops(body.early_derived_secret_reveals,
                self.process_early_derived_secret_reveal)
        for_ops(body.custody_slashings, self.process_custody_slashing)

    def process_chunk_challenge(self, state, challenge) -> None:
        # Attestation must be valid and still challengeable
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, challenge.attestation))
        max_challenge_epoch = Epoch(challenge.attestation.data.target.epoch
                                    + self.MAX_CHUNK_CHALLENGE_DELAY)
        assert self.get_current_epoch(state) <= max_challenge_epoch
        responder = state.validators[challenge.responder_index]
        if responder.exit_epoch < FAR_FUTURE_EPOCH:
            assert self.get_current_epoch(state) \
                <= responder.exit_epoch + self.MAX_CHUNK_CHALLENGE_DELAY
        assert self.is_slashable_validator(responder,
                                           self.get_current_epoch(state))
        # Responder must have participated
        attesters = self.get_attesting_indices(
            state, challenge.attestation.data,
            challenge.attestation.aggregation_bits)
        assert challenge.responder_index in attesters
        # The shard transition must be the attested one
        assert hash_tree_root(challenge.shard_transition) == \
            challenge.attestation.data.shard_transition_root
        data_root = \
            challenge.shard_transition.shard_data_roots[challenge.data_index]
        # No duplicate open challenge on (data, chunk)
        for record in state.custody_chunk_challenge_records:
            assert (record.data_root != data_root
                    or record.chunk_index != challenge.chunk_index)
        # Chunk index within the attested block length
        shard_block_length = int(
            challenge.shard_transition.shard_block_lengths[challenge.data_index])
        transition_chunks = (shard_block_length + self.BYTES_PER_CUSTODY_CHUNK
                             - 1) // self.BYTES_PER_CUSTODY_CHUNK
        assert challenge.chunk_index < transition_chunks
        new_record = self.CustodyChunkChallengeRecord(
            challenge_index=state.custody_chunk_challenge_index,
            challenger_index=self.get_beacon_proposer_index(state),
            responder_index=challenge.responder_index,
            inclusion_epoch=self.get_current_epoch(state),
            data_root=data_root,
            chunk_index=challenge.chunk_index,
        )
        self.replace_empty_or_append(
            state.custody_chunk_challenge_records, new_record)
        state.custody_chunk_challenge_index += 1
        # Freeze responder withdrawability until resolved
        responder.withdrawable_epoch = FAR_FUTURE_EPOCH

    def process_chunk_challenge_response(self, state, response) -> None:
        matching = [
            record for record in state.custody_chunk_challenge_records
            if record.challenge_index == response.challenge_index]
        assert len(matching) == 1
        challenge = matching[0]
        assert response.chunk_index == challenge.chunk_index
        # Chunk must sit in the attested data tree (depth +1 covers the
        # ByteList length mix-in)
        assert self.is_valid_merkle_branch(
            leaf=hash_tree_root(response.chunk),
            branch=response.branch,
            depth=self.CUSTODY_RESPONSE_DEPTH + 1,
            index=response.chunk_index,
            root=challenge.data_root,
        )
        index_in_records = list(
            state.custody_chunk_challenge_records).index(challenge)
        state.custody_chunk_challenge_records[index_in_records] = \
            self.CustodyChunkChallengeRecord()
        proposer_index = self.get_beacon_proposer_index(state)
        self.increase_balance(
            state, proposer_index,
            Gwei(self.get_base_reward(state, proposer_index)
                 // self.MINOR_REWARD_QUOTIENT))

    def process_custody_key_reveal(self, state, reveal) -> None:
        revealer = state.validators[reveal.revealer_index]
        epoch_to_sign = self.get_randao_epoch_for_custody_period(
            revealer.next_custody_secret_to_reveal, reveal.revealer_index)
        custody_reveal_period = self.get_custody_period_for_validator(
            reveal.revealer_index, self.get_current_epoch(state))
        # Past periods only — except the final period right after exit
        is_past_reveal = \
            revealer.next_custody_secret_to_reveal < custody_reveal_period
        is_exited = revealer.exit_epoch <= self.get_current_epoch(state)
        is_exit_period_reveal = (
            revealer.next_custody_secret_to_reveal
            == self.get_custody_period_for_validator(
                reveal.revealer_index, Epoch(revealer.exit_epoch - 1)))
        assert is_past_reveal or (is_exited and is_exit_period_reveal)
        assert self.is_slashable_validator(revealer,
                                           self.get_current_epoch(state))
        domain = self.get_domain(state, self.DOMAIN_RANDAO, epoch_to_sign)
        signing_root = self.compute_signing_root(Epoch(epoch_to_sign), domain)
        assert bls.Verify(revealer.pubkey, signing_root, reveal.reveal)
        if is_exited and is_exit_period_reveal:
            revealer.all_custody_secrets_revealed_epoch = \
                self.get_current_epoch(state)
        revealer.next_custody_secret_to_reveal += 1
        proposer_index = self.get_beacon_proposer_index(state)
        self.increase_balance(
            state, proposer_index,
            Gwei(self.get_base_reward(state, reveal.revealer_index)
                 // self.MINOR_REWARD_QUOTIENT))

    def process_early_derived_secret_reveal(self, state, reveal) -> None:
        revealed_validator = state.validators[reveal.revealed_index]
        derived_secret_location = uint64(
            reveal.epoch % self.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS)
        assert reveal.epoch >= \
            self.get_current_epoch(state) + self.RANDAO_PENALTY_EPOCHS
        assert reveal.epoch < self.get_current_epoch(state) \
            + self.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS
        assert not revealed_validator.slashed
        assert reveal.revealed_index not in \
            state.exposed_derived_secrets[derived_secret_location]
        # Masked reveal: aggregate of (secret over epoch, masker over mask)
        masker = state.validators[reveal.masker_index]
        pubkeys = [revealed_validator.pubkey, masker.pubkey]
        domain = self.get_domain(state, self.DOMAIN_RANDAO, reveal.epoch)
        signing_roots = [
            self.compute_signing_root(root, domain)
            for root in [hash_tree_root(Epoch(reveal.epoch)), reveal.mask]]
        assert bls.AggregateVerify(pubkeys, signing_roots, reveal.reveal)

        if reveal.epoch >= self.get_current_epoch(state) \
                + self.CUSTODY_PERIOD_TO_RANDAO_PADDING:
            # Early enough to be a custody-round key: full slashing
            self.slash_validator(state, reveal.revealed_index,
                                 reveal.masker_index)
        else:
            # Small penalty scaled by prior exposures this epoch window
            max_proposer_slot_reward = (
                int(self.get_base_reward(state, reveal.revealed_index))
                * self.SLOTS_PER_EPOCH
                // len(self.get_active_validator_indices(
                    state, self.get_current_epoch(state)))
                // self.PROPOSER_REWARD_QUOTIENT
            )
            penalty = Gwei(
                max_proposer_slot_reward
                * self.EARLY_DERIVED_SECRET_REVEAL_SLOT_REWARD_MULTIPLE
                * (len(state.exposed_derived_secrets[derived_secret_location])
                   + 1))
            proposer_index = self.get_beacon_proposer_index(state)
            whistleblower_index = reveal.masker_index
            whistleblowing_reward = Gwei(
                penalty // self.WHISTLEBLOWER_REWARD_QUOTIENT)
            proposer_reward = Gwei(
                whistleblowing_reward // self.PROPOSER_REWARD_QUOTIENT)
            self.increase_balance(state, proposer_index, proposer_reward)
            self.increase_balance(state, whistleblower_index,
                                  whistleblowing_reward - proposer_reward)
            self.decrease_balance(state, reveal.revealed_index, penalty)
            state.exposed_derived_secrets[derived_secret_location].append(
                reveal.revealed_index)

    def process_custody_slashing(self, state, signed_custody_slashing) -> None:
        custody_slashing = signed_custody_slashing.message
        attestation = custody_slashing.attestation
        # Whistleblower signs the claim; both parties must be slashable
        malefactor = state.validators[custody_slashing.malefactor_index]
        whistleblower = state.validators[custody_slashing.whistleblower_index]
        domain = self.get_domain(state, self.DOMAIN_CUSTODY_BIT_SLASHING,
                                 self.get_current_epoch(state))
        signing_root = self.compute_signing_root(custody_slashing, domain)
        assert bls.Verify(whistleblower.pubkey, signing_root,
                          signed_custody_slashing.signature)
        assert self.is_slashable_validator(whistleblower,
                                           self.get_current_epoch(state))
        assert self.is_slashable_validator(malefactor,
                                           self.get_current_epoch(state))
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))
        # Data must be the attested shard data
        shard_transition = custody_slashing.shard_transition
        assert hash_tree_root(shard_transition) == \
            attestation.data.shard_transition_root
        assert len(custody_slashing.data) == int(
            shard_transition.shard_block_lengths[custody_slashing.data_index])
        assert hash_tree_root(custody_slashing.data) == \
            shard_transition.shard_data_roots[custody_slashing.data_index]
        attesters = self.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)
        assert custody_slashing.malefactor_index in attesters
        # The malefactor's period secret must verify
        epoch_to_sign = self.get_randao_epoch_for_custody_period(
            self.get_custody_period_for_validator(
                custody_slashing.malefactor_index,
                attestation.data.target.epoch),
            custody_slashing.malefactor_index)
        domain = self.get_domain(state, self.DOMAIN_RANDAO, epoch_to_sign)
        signing_root = self.compute_signing_root(Epoch(epoch_to_sign), domain)
        assert bls.Verify(malefactor.pubkey, signing_root,
                          custody_slashing.malefactor_secret)

        computed_custody_bit = self.compute_custody_bit(
            custody_slashing.malefactor_secret, custody_slashing.data)
        if computed_custody_bit == 1:
            # Custody bit was indeed wrongly claimed: slash malefactor,
            # reward the rest of the committee
            self.slash_validator(state, custody_slashing.malefactor_index)
            committee = self.get_beacon_committee(
                state, attestation.data.slot, attestation.data.index)
            others_count = len(committee) - 1
            whistleblower_reward = Gwei(
                int(malefactor.effective_balance)
                // self.WHISTLEBLOWER_REWARD_QUOTIENT // others_count)
            for attester_index in attesters:
                if attester_index != custody_slashing.malefactor_index:
                    self.increase_balance(state, attester_index,
                                          whistleblower_reward)
        else:
            # False claim: the whistleblower induced the work, slash them
            self.slash_validator(state,
                                 custody_slashing.whistleblower_index)

    # -- epoch processing --------------------------------------------------
    def process_epoch(self, state) -> None:
        """custody_game/beacon-chain.md "Epoch transition" ordering; the
        defunct pending-shard-header stages are omitted (lineage note)."""
        self.process_justification_and_finalization(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        # Proof of custody
        self.process_reveal_deadlines(state)
        self.process_challenge_deadlines(state)
        self.process_slashings(state)
        # Final updates
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_record_updates(state)
        self.process_custody_final_updates(state)
        self.process_shard_epoch_increment(state)

    def process_reveal_deadlines(self, state) -> None:
        epoch = self.get_current_epoch(state)
        for index, validator in enumerate(state.validators):
            deadline = validator.next_custody_secret_to_reveal + 1
            if self.get_custody_period_for_validator(
                    ValidatorIndex(index), epoch) > deadline:
                self.slash_validator(state, ValidatorIndex(index))

    def process_challenge_deadlines(self, state) -> None:
        for challenge in state.custody_chunk_challenge_records:
            if self.get_current_epoch(state) > \
                    challenge.inclusion_epoch + self.EPOCHS_PER_CUSTODY_PERIOD:
                self.slash_validator(state, challenge.responder_index,
                                     challenge.challenger_index)
                index_in_records = list(
                    state.custody_chunk_challenge_records).index(challenge)
                state.custody_chunk_challenge_records[index_in_records] = \
                    self.CustodyChunkChallengeRecord()

    def process_custody_final_updates(self, state) -> None:
        # Re-arm the reveal slot for this epoch's window
        state.exposed_derived_secrets[
            self.get_current_epoch(state)
            % self.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS] = []
        # Withdrawability gating on open challenges / unrevealed secrets
        # NOTE: cleared (empty) records keep responder_index 0 in the set,
        # matching the reference exactly (custody_game/beacon-chain.md
        # "Final updates") — validator 0's withdrawability stays frozen
        # while any cleared record slot exists.
        records = state.custody_chunk_challenge_records
        validator_indices_in_records = set(
            int(record.responder_index) for record in records)
        for index, validator in enumerate(state.validators):
            if validator.exit_epoch != FAR_FUTURE_EPOCH:
                not_all_secrets_are_revealed = \
                    validator.all_custody_secrets_revealed_epoch \
                    == FAR_FUTURE_EPOCH
                if index in validator_indices_in_records \
                        or not_all_secrets_are_revealed:
                    validator.withdrawable_epoch = FAR_FUTURE_EPOCH
                elif validator.withdrawable_epoch == FAR_FUTURE_EPOCH:
                    validator.withdrawable_epoch = Epoch(
                        validator.all_custody_secrets_revealed_epoch
                        + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
