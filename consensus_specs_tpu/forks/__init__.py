"""Per-fork spec runtimes.

Each fork is a class extending the previous fork's class (fork inheritance =
class inheritance, replacing the reference's markdown dict-merge pipeline,
``pysetup/helpers.py:222-247``). ``build_spec(fork, preset)`` instantiates a
fork spec bound to a preset + config; instances are cached like the
reference's ``spec_targets`` (``test/helpers/specs.py:19-26``).
"""
from typing import Dict, Optional, Tuple

_REGISTRY = {}


def register_fork(name):
    def deco(cls):
        # span-instrument the transition surface from outside so the
        # method bodies stay spec-shaped (same pattern as the epoch /
        # fork-choice engine installs; zero-overhead unless
        # CS_TPU_PROFILE/CS_TPU_TRACE)
        from consensus_specs_tpu.obs import install_tracing
        from consensus_specs_tpu.ops.att_prep import install_att_prep
        from consensus_specs_tpu.das.engine import install_das_accel
        install_att_prep(cls)
        install_das_accel(cls)
        install_tracing(cls)
        _REGISTRY[name] = cls
        cls.fork = name
        return cls
    return deco


def fork_registry() -> Dict[str, type]:
    if not _REGISTRY:
        _import_all()
    return dict(_REGISTRY)


def _import_all():
    import importlib.util
    from . import phase0  # noqa: F401
    for mod in ("altair", "bellatrix", "capella", "deneb",
                "eip6110", "eip7002", "eip7594", "whisk",
                "sharding", "custody_game", "eip6914"):
        # Probe existence first so a real import error inside an existing
        # fork module propagates instead of silently dropping the fork
        # (and silently skipping its whole test suite).
        if importlib.util.find_spec(f"{__name__}.{mod}") is not None:
            __import__(f"{__name__}.{mod}")


_spec_cache: Dict[Tuple[str, str, Optional[frozenset]], object] = {}


def build_spec(fork: str, preset_name: str, config_overrides: Optional[dict] = None):
    """Build (or fetch cached) spec instance for fork × preset."""
    key = (fork, preset_name,
           frozenset(config_overrides.items()) if config_overrides else None)
    spec = _spec_cache.get(key)
    if spec is None:
        from consensus_specs_tpu.config import load_preset, load_config
        registry = fork_registry()
        if fork not in registry:
            raise ValueError(f"unknown fork {fork!r}; have {sorted(registry)}")
        preset = load_preset(preset_name)
        config = load_config(preset_name)
        if config_overrides:
            config = {**config, **config_overrides}
        spec = registry[fork](preset, config, preset_name=preset_name)
        _spec_cache[key] = spec
    return spec


def use_compiled_registry():
    """Swap the registry entries of all nine built forks for the
    markdown-COMPILED
    ladder (``make pyspec`` output, ``compiler/emit.py``), so the same
    conformance suite that exercises the hand-written classes runs
    against the classes built from ``specs/*/beacon-chain.md`` — pytest
    session flag ``--compiled`` (reference analog: the reference suite
    only ever runs the markdown-built pyspec).

    Always recompiles from the markdown first (a couple of seconds of
    pure python) so a green ``--compiled`` run certifies the CURRENT
    spec text, never a stale or half-written generated tree.  The swap
    covers the same 9-fork surface the reference builds
    (``pysetup/spec_builders/__init__.py:12-18``): phase0..deneb plus
    eip6110/eip7002/whisk/eip7594; the recompile also enforces the
    provenance guard (``compiler.emit.verify_provenance``), so a green
    run certifies every spec-logic method came from markdown.
    """
    import importlib
    fork_registry()  # populate before overriding (guard needs it too)
    from consensus_specs_tpu.compiler.emit import (
        main as _compile_all, _FORK_ORDER)
    _compile_all()
    importlib.invalidate_caches()  # compiled/ may have just been created
    from consensus_specs_tpu.obs import install_tracing
    from consensus_specs_tpu.ops.att_prep import install_att_prep
    from consensus_specs_tpu.ops.epoch_kernels import install_vectorized_epoch
    from consensus_specs_tpu.forkchoice.proto_array import (
        install_forkchoice_accel)
    from consensus_specs_tpu.das.engine import install_das_accel
    for fork in _FORK_ORDER:
        mod = importlib.import_module(f"{__name__}.compiled.{fork}")
        importlib.reload(mod)
        cls = getattr(mod, f"Compiled{fork.capitalize()}Spec")
        # compiled method bodies are emitted verbatim from the markdown,
        # so the vectorized-epoch, attestation message-prep, proto-array
        # fork-choice and DAS sampling dispatches (and the tracing
        # spans) wrap them from outside
        install_vectorized_epoch(cls)
        install_att_prep(cls)
        install_forkchoice_accel(cls)
        install_das_accel(cls)
        install_tracing(cls)
        _REGISTRY[fork] = cls
    _spec_cache.clear()
