"""Whisk feature fork: single secret leader election via shuffled trackers.

Behavioral source: ``specs/_features/whisk/beacon-chain.md``
(``WhiskTracker`` :134, tracker selections :196-230, modified
``process_block_header`` :247 (opening proof replaces the proposer-index
equality), ``process_shuffled_trackers`` :327,
``process_whisk_registration`` :352, whisk deposits :383, header-based
``get_beacon_proposer_index`` :429) and ``specs/_features/whisk/fork.md``
(``upgrade_to_whisk`` :55-125).  Fork DAG parent: capella
(``pysetup/md_doc_paths.py:23``).

Proof systems: :mod:`consensus_specs_tpu.ops.whisk_proofs` (DLEQ opening
proofs implemented for real; shuffle proofs via the documented
permutation-rerandomization stand-in — the reference defers both to the
external curdleproofs library).
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, Bytes32, Bytes48, ByteList, Vector, List,
    Container,
)
from consensus_specs_tpu.ops import whisk_proofs
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from . import register_fork
from .capella import CapellaSpec
from .base_types import (
    Epoch, ValidatorIndex, DomainType,
)

DOMAIN_WHISK_CANDIDATE_SELECTION = DomainType("0x07000000")
DOMAIN_WHISK_SHUFFLE = DomainType("0x07100000")
DOMAIN_WHISK_PROPOSER_SELECTION = DomainType("0x07200000")

BLSG1Point = Bytes48
BLS_G1_GENERATOR = whisk_proofs.BLS_G1_GENERATOR
WHISK_BLS_MODULUS = R_ORDER


def saturating_sub(a, b):
    return a - b if a > b else type(a)(0)


@register_fork("whisk")
class WhiskSpec(CapellaSpec):
    fork = "whisk"
    previous_fork = "capella"

    DOMAIN_WHISK_CANDIDATE_SELECTION = DOMAIN_WHISK_CANDIDATE_SELECTION
    DOMAIN_WHISK_SHUFFLE = DOMAIN_WHISK_SHUFFLE
    DOMAIN_WHISK_PROPOSER_SELECTION = DOMAIN_WHISK_PROPOSER_SELECTION
    BLSG1Point = BLSG1Point
    BLS_G1_GENERATOR = BLS_G1_GENERATOR
    BLS_MODULUS = WHISK_BLS_MODULUS
    saturating_sub = staticmethod(saturating_sub)

    # proof-system interface (beacon-chain.md:101-130)
    IsValidWhiskOpeningProof = staticmethod(
        whisk_proofs.IsValidWhiskOpeningProof)
    IsValidWhiskShuffleProof = staticmethod(
        whisk_proofs.IsValidWhiskShuffleProof)

    # -- type construction ---------------------------------------------------

    def _build_types(self):
        S = self

        class WhiskTracker(Container):
            r_G: BLSG1Point
            k_r_G: BLSG1Point

        self.WhiskTracker = WhiskTracker
        self.WhiskShuffleProof = ByteList[S.WHISK_MAX_SHUFFLE_PROOF_SIZE]
        self.WhiskTrackerProof = ByteList[S.WHISK_MAX_OPENING_PROOF_SIZE]
        super()._build_types()

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        fields["whisk_opening_proof"] = self.WhiskTrackerProof
        fields["whisk_post_shuffle_trackers"] = Vector[
            self.WhiskTracker, self.WHISK_VALIDATORS_PER_SHUFFLE]
        fields["whisk_shuffle_proof"] = self.WhiskShuffleProof
        fields["whisk_registration_proof"] = self.WhiskTrackerProof
        fields["whisk_tracker"] = self.WhiskTracker
        fields["whisk_k_commitment"] = BLSG1Point
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields["whisk_candidate_trackers"] = Vector[
            self.WhiskTracker, self.WHISK_CANDIDATE_TRACKERS_COUNT]
        fields["whisk_proposer_trackers"] = Vector[
            self.WhiskTracker, self.WHISK_PROPOSER_TRACKERS_COUNT]
        fields["whisk_trackers"] = List[
            self.WhiskTracker, self.VALIDATOR_REGISTRY_LIMIT]
        fields["whisk_k_commitments"] = List[
            BLSG1Point, self.VALIDATOR_REGISTRY_LIMIT]
        return fields

    # -- whisk crypto helpers (beacon-chain.md:69-100,383-428) ---------------

    def BLSG1ScalarMultiply(self, scalar, point) -> bytes:
        return whisk_proofs._to_point(point).mult(
            int(scalar) % R_ORDER).to_compressed()

    def whisk_bytes_to_bls_field(self, b: bytes) -> int:
        return int.from_bytes(bytes(b), "little") % R_ORDER

    def get_initial_whisk_k(self, validator_index, counter) -> int:
        return self.whisk_bytes_to_bls_field(hash(
            self.uint_to_bytes(uint64(validator_index))
            + self.uint_to_bytes(uint64(counter))))

    def is_k_commitment_unique(self, state, k_commitment) -> bool:
        return all(bytes(c) != bytes(k_commitment)
                   for c in state.whisk_k_commitments)

    def get_unique_whisk_k(self, state, validator_index) -> int:
        counter = 0
        while True:
            k = self.get_initial_whisk_k(validator_index, counter)
            if self.is_k_commitment_unique(
                    state, self.BLSG1ScalarMultiply(k, BLS_G1_GENERATOR)):
                return k
            counter += 1

    def get_k_commitment(self, k) -> bytes:
        return self.BLSG1ScalarMultiply(k, BLS_G1_GENERATOR)

    def get_initial_tracker(self, k):
        return self.WhiskTracker(
            r_G=BLS_G1_GENERATOR,
            k_r_G=self.BLSG1ScalarMultiply(k, BLS_G1_GENERATOR))

    # -- tracker selection (beacon-chain.md:196-230) -------------------------

    def select_whisk_proposer_trackers(self, state, epoch) -> None:
        proposer_seed = self.get_seed(
            state, saturating_sub(epoch, self.config.WHISK_PROPOSER_SELECTION_GAP),
            DOMAIN_WHISK_PROPOSER_SELECTION)
        for i in range(self.WHISK_PROPOSER_TRACKERS_COUNT):
            index = self.compute_shuffled_index(
                uint64(i), uint64(len(state.whisk_candidate_trackers)),
                proposer_seed)
            state.whisk_proposer_trackers[i] = \
                state.whisk_candidate_trackers[index]

    def select_whisk_candidate_trackers(self, state, epoch) -> None:
        active_validator_indices = self.get_active_validator_indices(
            state, epoch)
        for i in range(self.WHISK_CANDIDATE_TRACKERS_COUNT):
            seed = hash(self.get_seed(state, epoch,
                                      DOMAIN_WHISK_CANDIDATE_SELECTION)
                        + self.uint_to_bytes(uint64(i)))
            candidate_index = self.compute_proposer_index(
                state, active_validator_indices, seed)
            state.whisk_candidate_trackers[i] = \
                state.whisk_trackers[candidate_index]

    def process_whisk_updates(self, state) -> None:
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        if next_epoch % self.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE == 0:
            self.select_whisk_proposer_trackers(state, next_epoch)
            self.select_whisk_candidate_trackers(state, next_epoch)

    def process_epoch(self, state) -> None:
        super().process_epoch(state)
        self.process_whisk_updates(state)  # [New in Whisk]

    # -- block header (beacon-chain.md:247-280) ------------------------------

    def process_whisk_opening_proof(self, state, block) -> None:
        tracker = state.whisk_proposer_trackers[
            state.slot % self.WHISK_PROPOSER_TRACKERS_COUNT]
        k_commitment = state.whisk_k_commitments[block.proposer_index]
        assert self.IsValidWhiskOpeningProof(
            tracker, k_commitment, block.body.whisk_opening_proof)

    def process_block_header(self, state, block) -> None:
        # Verify slots and lineage; the proposer-index equality is
        # REPLACED by the whisk opening proof
        assert block.slot == state.slot
        assert block.slot > state.latest_block_header.slot
        assert block.parent_root == hash_tree_root(state.latest_block_header)
        state.latest_block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=Bytes32(),
            body_root=hash_tree_root(block.body),
        )
        proposer = state.validators[block.proposer_index]
        assert not proposer.slashed
        self.process_whisk_opening_proof(state, block)  # [New in Whisk]

    def get_beacon_proposer_index(self, state) -> ValidatorIndex:
        """beacon-chain.md:429 — the proposer is whoever opened the
        tracker; read it back from the processed header."""
        assert state.latest_block_header.slot == state.slot
        return state.latest_block_header.proposer_index

    # -- shuffling and registration (beacon-chain.md:311-381) ----------------

    def get_shuffle_indices(self, randao_reveal):
        indices = []
        for i in range(self.WHISK_VALIDATORS_PER_SHUFFLE):
            pre_image = bytes(randao_reveal) + self.uint_to_bytes(uint64(i))
            indices.append(self.bytes_to_uint64(hash(pre_image)[0:8])
                           % self.WHISK_CANDIDATE_TRACKERS_COUNT)
        return indices

    def process_shuffled_trackers(self, state, body) -> None:
        shuffle_epoch = self.get_current_epoch(state) \
            % self.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE
        if shuffle_epoch + self.config.WHISK_PROPOSER_SELECTION_GAP + 1 \
                >= self.config.WHISK_EPOCHS_PER_SHUFFLING_PHASE:
            # cooldown: trackers must be zeroed
            assert body.whisk_post_shuffle_trackers == Vector[
                self.WhiskTracker, self.WHISK_VALIDATORS_PER_SHUFFLE]()
            assert body.whisk_shuffle_proof == self.WhiskShuffleProof()
        else:
            shuffle_indices = self.get_shuffle_indices(body.randao_reveal)
            pre_shuffle_trackers = [state.whisk_candidate_trackers[i]
                                    for i in shuffle_indices]
            assert self.IsValidWhiskShuffleProof(
                pre_shuffle_trackers, body.whisk_post_shuffle_trackers,
                body.whisk_shuffle_proof)
            for i, shuffle_index in enumerate(shuffle_indices):
                state.whisk_candidate_trackers[shuffle_index] = \
                    body.whisk_post_shuffle_trackers[i]

    def process_whisk_registration(self, state, body) -> None:
        proposer_index = self.get_beacon_proposer_index(state)
        if bytes(state.whisk_trackers[proposer_index].r_G) == \
                BLS_G1_GENERATOR:  # first whisk proposal
            assert bytes(body.whisk_tracker.r_G) != BLS_G1_GENERATOR
            assert self.is_k_commitment_unique(state,
                                               body.whisk_k_commitment)
            assert self.IsValidWhiskOpeningProof(
                body.whisk_tracker, body.whisk_k_commitment,
                body.whisk_registration_proof)
            state.whisk_trackers[proposer_index] = body.whisk_tracker
            state.whisk_k_commitments[proposer_index] = \
                body.whisk_k_commitment
        else:  # subsequent proposals
            assert body.whisk_registration_proof == self.WhiskTrackerProof()
            assert body.whisk_tracker == self.WhiskTracker()
            assert bytes(body.whisk_k_commitment) == bytes(BLSG1Point())

    def process_block(self, state, block) -> None:
        from consensus_specs_tpu.utils import bls as _bls
        with _bls.batched_verification() as batch:
            self.process_block_header(state, block)
            self.process_withdrawals(state, block.body.execution_payload)
            self.process_execution_payload(state, block.body,
                                           self.EXECUTION_ENGINE)
            self.process_randao(state, block.body)
            self.process_eth1_data(state, block.body)
            self.process_operations(state, block.body)
            self.process_sync_aggregate(state, block.body.sync_aggregate)
            self.process_shuffled_trackers(state, block.body)
            self.process_whisk_registration(state, block.body)
        batch.assert_valid()

    # -- deposits (beacon-chain.md:383-428) ----------------------------------

    def add_validator_to_registry(self, state, pubkey,
                                  withdrawal_credentials, amount) -> None:
        super().add_validator_to_registry(state, pubkey,
                                          withdrawal_credentials, amount)
        k = self.get_unique_whisk_k(
            state, ValidatorIndex(len(state.validators) - 1))
        state.whisk_trackers.append(self.get_initial_tracker(k))
        state.whisk_k_commitments.append(self.get_k_commitment(k))

    # -- genesis / upgrade (fork.md:55-125) ----------------------------------

    def post_mock_genesis(self, state):
        super().post_mock_genesis(state)
        for index in range(len(state.validators)):
            k = self.get_initial_whisk_k(ValidatorIndex(index), 0)
            state.whisk_trackers.append(self.get_initial_tracker(k))
            state.whisk_k_commitments.append(self.get_k_commitment(k))
        epoch = self.get_current_epoch(state)
        self.select_whisk_candidate_trackers(state, Epoch(saturating_sub(
            epoch, self.config.WHISK_PROPOSER_SELECTION_GAP + 1)))
        self.select_whisk_proposer_trackers(state, epoch)
        self.select_whisk_candidate_trackers(state, epoch)

    def upgrade_to_whisk(self, pre):
        """fork.md:55 — capella state + whisk trackers for every
        validator, then the bootstrap selections."""
        epoch = self.get_current_epoch(pre)
        ks = [self.get_initial_whisk_k(ValidatorIndex(i), 0)
              for i in range(len(pre.validators))]
        post = self.BeaconState(
            **{f: getattr(pre, f) for f in type(pre).fields()
               if f != "fork"},
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.WHISK_FORK_VERSION,
                epoch=epoch,
            ),
            whisk_proposer_trackers=[
                self.WhiskTracker()
                for _ in range(self.WHISK_PROPOSER_TRACKERS_COUNT)],
            whisk_candidate_trackers=[
                self.WhiskTracker()
                for _ in range(self.WHISK_CANDIDATE_TRACKERS_COUNT)],
            whisk_trackers=[self.get_initial_tracker(k) for k in ks],
            whisk_k_commitments=[self.get_k_commitment(k) for k in ks],
        )
        self.select_whisk_candidate_trackers(post, Epoch(saturating_sub(
            epoch, self.config.WHISK_PROPOSER_SELECTION_GAP + 1)))
        self.select_whisk_proposer_trackers(post, epoch)
        self.select_whisk_candidate_trackers(post, epoch)
        return post
