"""Custom SSZ type aliases and fork-independent constants.

Reference: "Custom types" + "Constants" tables of
``specs/phase0/beacon-chain.md`` (lines ~290-350).
"""
from consensus_specs_tpu.utils.ssz import (
    uint8, uint64, Bytes4, Bytes20, Bytes32, Bytes48, Bytes96, ByteVector,
)  # noqa: F401 (compiled-spec namespace)

# custom types (aliases of basic/byte types)
Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
Hash32 = Bytes32
Version = Bytes4
DomainType = Bytes4
ForkDigest = Bytes4
Domain = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96
ExecutionAddress = Bytes20
ParticipationFlags = uint8
KZGCommitment = Bytes48
KZGProof = Bytes48

# constants (fork-independent, not preset/config)
GENESIS_SLOT = Slot(0)
GENESIS_EPOCH = Epoch(0)
FAR_FUTURE_EPOCH = Epoch(2**64 - 1)
BASE_REWARDS_PER_EPOCH = uint64(4)
DEPOSIT_CONTRACT_TREE_DEPTH = 2**5
JUSTIFICATION_BITS_LENGTH = 4
ENDIANNESS = "little"

BLS_WITHDRAWAL_PREFIX = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"

DOMAIN_BEACON_PROPOSER = DomainType("0x00000000")
DOMAIN_BEACON_ATTESTER = DomainType("0x01000000")
DOMAIN_RANDAO = DomainType("0x02000000")
DOMAIN_DEPOSIT = DomainType("0x03000000")
DOMAIN_VOLUNTARY_EXIT = DomainType("0x04000000")
DOMAIN_SELECTION_PROOF = DomainType("0x05000000")
DOMAIN_AGGREGATE_AND_PROOF = DomainType("0x06000000")
DOMAIN_SYNC_COMMITTEE = DomainType("0x07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = DomainType("0x08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = DomainType("0x09000000")
DOMAIN_BLS_TO_EXECUTION_CHANGE = DomainType("0x0A000000")
DOMAIN_APPLICATION_MASK = DomainType("0x00000001")
