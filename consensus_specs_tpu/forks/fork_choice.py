"""Phase0 LMD-GHOST fork choice.

Behavioral parity with ``specs/phase0/fork-choice.md`` (reference): the
``Store`` event machine (``:113``), ``get_forkchoice_store`` (``:157``),
weight accounting with proposer boost (``get_weight`` ``:249``,
``get_proposer_score`` ``:237``), viable-branch filtering with pulled-up
voting sources (``filter_block_tree`` ``:292``, ``get_voting_source``
``:273``), head selection (``get_head`` ``:361``), the proposer re-org
helpers (``get_proposer_head`` ``:474``), pull-up tips
(``compute_pulled_up_tip`` ``:523``) and the four handlers ``on_tick``
(``:636``), ``on_block`` (``:649``), ``on_attestation`` (``:699``),
``on_attester_slashing`` (``:724``).

Design differences from the reference (same observable behavior):
- ``get_ancestor`` is iterative (no recursion-limit hazard on long chains).
- ``filter_block_tree`` walks an explicit stack and memoizes children via a
  parent->children index built per call, instead of O(n^2) rescans.
- ``Store.checkpoint_states`` is keyed by ``(epoch, root)`` tuples because
  our SSZ containers are mutable (the reference relies on remerkleable
  view hashing).

The class bodies below stay spec-shaped; the performance layer is
installed from the outside (``install_forkchoice_accel`` at the bottom
of this module, mirroring ``ops/epoch_kernels.install_vectorized_epoch``
for the markdown-compiled ladder): ``get_head`` / ``get_weight`` /
``get_filtered_block_tree`` dispatch to the incremental proto-array
engine (``forkchoice/proto_array.py``, ``CS_TPU_PROTO_ARRAY=0`` to
disable), stores carry an incrementally-maintained parent->children
index (``_children_index`` rebuilds are O(1) instead of O(blocks) per
``filter_block_tree`` call), and ``get_ancestor`` memoizes its walks in
a per-store (root, slot)-keyed cache so the spec fallback stops paying
O(depth) per vote.
"""
from dataclasses import dataclass, field
from typing import Dict, Set, Tuple

from consensus_specs_tpu.forkchoice.proto_array import install_forkchoice_accel
from consensus_specs_tpu.obs import install_tracing
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import hash_tree_root

INTERVALS_PER_SLOT = 3


@dataclass(eq=True, frozen=True)
class LatestMessage:
    epoch: int
    root: bytes


@dataclass
class Store:
    time: int
    genesis_time: int
    justified_checkpoint: object
    finalized_checkpoint: object
    unrealized_justified_checkpoint: object
    unrealized_finalized_checkpoint: object
    proposer_boost_root: bytes
    equivocating_indices: Set[int]
    blocks: Dict[bytes, object] = field(default_factory=dict)
    block_states: Dict[bytes, object] = field(default_factory=dict)
    block_timeliness: Dict[bytes, bool] = field(default_factory=dict)
    checkpoint_states: Dict[Tuple[int, bytes], object] = field(default_factory=dict)
    latest_messages: Dict[int, LatestMessage] = field(default_factory=dict)
    unrealized_justifications: Dict[bytes, object] = field(default_factory=dict)


def _ckpt_key(checkpoint) -> Tuple[int, bytes]:
    return (int(checkpoint.epoch), bytes(checkpoint.root))


class ForkChoiceMixin:
    """Fork-choice methods mixed into the per-fork spec classes."""

    LatestMessage = LatestMessage
    Store = Store
    INTERVALS_PER_SLOT = INTERVALS_PER_SLOT

    # -- store construction -------------------------------------------------

    def get_forkchoice_store(self, anchor_state, anchor_block) -> Store:
        assert bytes(anchor_block.state_root) == hash_tree_root(anchor_state)
        anchor_root = hash_tree_root(anchor_block)
        anchor_epoch = self.get_current_epoch(anchor_state)
        justified = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        finalized = self.Checkpoint(epoch=anchor_epoch, root=anchor_root)
        return Store(
            time=int(anchor_state.genesis_time
                     + self.config.SECONDS_PER_SLOT * anchor_state.slot),
            genesis_time=int(anchor_state.genesis_time),
            justified_checkpoint=justified,
            finalized_checkpoint=finalized,
            unrealized_justified_checkpoint=justified.copy(),
            unrealized_finalized_checkpoint=finalized.copy(),
            proposer_boost_root=b"\x00" * 32,
            equivocating_indices=set(),
            blocks={anchor_root: anchor_block.copy()},
            block_states={anchor_root: anchor_state.copy()},
            checkpoint_states={_ckpt_key(justified): anchor_state.copy()},
            unrealized_justifications={anchor_root: justified.copy()},
        )

    # -- time helpers -------------------------------------------------------

    def get_slots_since_genesis(self, store) -> int:
        return (store.time - store.genesis_time) // int(self.config.SECONDS_PER_SLOT)

    def get_current_slot(self, store):
        return self.Slot(self.GENESIS_SLOT + self.get_slots_since_genesis(store))

    def get_current_store_epoch(self, store):
        return self.compute_epoch_at_slot(self.get_current_slot(store))

    def compute_slots_since_epoch_start(self, slot) -> int:
        return int(slot - self.compute_start_slot_at_epoch(
            self.compute_epoch_at_slot(slot)))

    def is_previous_epoch_justified(self, store) -> bool:
        return (store.justified_checkpoint.epoch + 1
                == self.get_current_store_epoch(store))

    # -- chain walking ------------------------------------------------------

    def get_ancestor(self, store, root, slot):
        root = bytes(root)
        block = store.blocks[root]
        while block.slot > slot:
            root = bytes(block.parent_root)
            block = store.blocks[root]
        return self.Root(root)

    def get_checkpoint_block(self, store, root, epoch):
        """Root of the checkpoint block at ``epoch`` on the chain of ``root``."""
        return self.get_ancestor(store, root,
                                 self.compute_start_slot_at_epoch(epoch))

    # -- weights ------------------------------------------------------------

    def calculate_committee_fraction(self, state, committee_percent):
        committee_weight = (self.get_total_active_balance(state)
                            // self.SLOTS_PER_EPOCH)
        return self.Gwei(committee_weight * committee_percent // 100)

    def get_proposer_score(self, store):
        justified_state = store.checkpoint_states[_ckpt_key(store.justified_checkpoint)]
        committee_weight = (self.get_total_active_balance(justified_state)
                            // self.SLOTS_PER_EPOCH)
        return self.Gwei(committee_weight * self.config.PROPOSER_SCORE_BOOST // 100)

    def get_weight(self, store, root):
        state = store.checkpoint_states[_ckpt_key(store.justified_checkpoint)]
        root = bytes(root)
        block_slot = store.blocks[root].slot
        score = 0
        for i in self.get_active_validator_indices(state, self.get_current_epoch(state)):
            if state.validators[i].slashed:
                continue
            msg = store.latest_messages.get(int(i))
            if msg is None or int(i) in store.equivocating_indices:
                continue
            if bytes(self.get_ancestor(store, msg.root, block_slot)) == root:
                score += int(state.validators[i].effective_balance)
        if bytes(store.proposer_boost_root) != b"\x00" * 32:
            if bytes(self.get_ancestor(
                    store, store.proposer_boost_root, block_slot)) == root:
                score += int(self.get_proposer_score(store))
        return self.Gwei(score)

    # -- viability filtering ------------------------------------------------

    def get_voting_source(self, store, block_root):
        """The justification a vote for ``block_root`` would carry
        (pulled up for blocks from prior epochs)."""
        block_root = bytes(block_root)
        block = store.blocks[block_root]
        if self.get_current_store_epoch(store) > self.compute_epoch_at_slot(block.slot):
            return store.unrealized_justifications[block_root]
        return store.block_states[block_root].current_justified_checkpoint

    def _children_index(self, store) -> Dict[bytes, list]:
        children: Dict[bytes, list] = {}
        for root, block in store.blocks.items():
            children.setdefault(bytes(block.parent_root), []).append(root)
        return children

    def _leaf_viable(self, store, block_root) -> bool:
        current_epoch = self.get_current_store_epoch(store)
        voting_source = self.get_voting_source(store, block_root)
        correct_justified = (
            store.justified_checkpoint.epoch == self.GENESIS_EPOCH
            or voting_source.epoch == store.justified_checkpoint.epoch
            or voting_source.epoch + 2 >= current_epoch)
        finalized_block = self.get_checkpoint_block(
            store, block_root, store.finalized_checkpoint.epoch)
        correct_finalized = (
            store.finalized_checkpoint.epoch == self.GENESIS_EPOCH
            or bytes(store.finalized_checkpoint.root) == bytes(finalized_block))
        return correct_justified and correct_finalized

    def filter_block_tree(self, store, block_root, blocks) -> bool:
        """Keep subtrees whose leaves carry the expected justification and
        finalization; explicit post-order walk instead of recursion."""
        children = self._children_index(store)
        viable: Dict[bytes, bool] = {}
        order = []
        stack = [bytes(block_root)]
        while stack:
            r = stack.pop()
            order.append(r)
            stack.extend(children.get(r, []))
        for r in reversed(order):
            kids = children.get(r, [])
            if kids:
                ok = any(viable[k] for k in kids)
            else:
                ok = self._leaf_viable(store, r)
            viable[r] = ok
            if ok:
                blocks[r] = store.blocks[r]
        return viable[bytes(block_root)]

    def get_filtered_block_tree(self, store):
        base = bytes(store.justified_checkpoint.root)
        blocks: Dict[bytes, object] = {}
        self.filter_block_tree(store, base, blocks)
        return blocks

    def get_head(self, store):
        blocks = self.get_filtered_block_tree(store)
        head = bytes(store.justified_checkpoint.root)
        children_of: Dict[bytes, list] = {}
        for root, block in blocks.items():
            children_of.setdefault(bytes(block.parent_root), []).append(root)
        while True:
            children = children_of.get(head, [])
            if not children:
                return self.Root(head)
            head = max(children,
                       key=lambda r: (int(self.get_weight(store, r)), r))

    def get_safe_beacon_block_root(self, store):
        """specs/fork_choice/safe-block.md — the engine-API ``safe`` tag:
        the most recent justified block (reorging it needs a slashable
        supermajority equivocation)."""
        return self.Root(store.justified_checkpoint.root)

    def get_safe_execution_payload_hash(self, store):
        """safe-block.md — the safe block's payload hash, or the zero
        hash for pre-merge blocks."""
        safe_block_root = self.get_safe_beacon_block_root(store)
        safe_block = store.blocks[safe_block_root]
        body = safe_block.body
        if hasattr(body, "execution_payload"):
            return self.Hash32(body.execution_payload.block_hash)
        return self.Hash32()

    # -- checkpoint bookkeeping --------------------------------------------

    def update_checkpoints(self, store, justified_checkpoint, finalized_checkpoint):
        if justified_checkpoint.epoch > store.justified_checkpoint.epoch:
            store.justified_checkpoint = justified_checkpoint.copy()
        if finalized_checkpoint.epoch > store.finalized_checkpoint.epoch:
            store.finalized_checkpoint = finalized_checkpoint.copy()

    def update_unrealized_checkpoints(self, store, unrealized_justified,
                                      unrealized_finalized):
        if unrealized_justified.epoch > store.unrealized_justified_checkpoint.epoch:
            store.unrealized_justified_checkpoint = unrealized_justified.copy()
        if unrealized_finalized.epoch > store.unrealized_finalized_checkpoint.epoch:
            store.unrealized_finalized_checkpoint = unrealized_finalized.copy()

    def compute_pulled_up_tip(self, store, block_root) -> None:
        """Eagerly run FFG processing on the block's post-state, recording
        the unrealized justification it would realize at the boundary."""
        state = store.block_states[bytes(block_root)].copy()
        self.process_justification_and_finalization(state)
        store.unrealized_justifications[bytes(block_root)] = \
            state.current_justified_checkpoint.copy()
        self.update_unrealized_checkpoints(
            store, state.current_justified_checkpoint, state.finalized_checkpoint)
        block_epoch = self.compute_epoch_at_slot(store.blocks[bytes(block_root)].slot)
        if block_epoch < self.get_current_store_epoch(store):
            self.update_checkpoints(
                store, state.current_justified_checkpoint, state.finalized_checkpoint)

    # -- proposer re-org helpers -------------------------------------------

    def is_head_late(self, store, head_root) -> bool:
        return not store.block_timeliness[bytes(head_root)]

    def is_shuffling_stable(self, slot) -> bool:
        return slot % self.SLOTS_PER_EPOCH != 0

    def is_ffg_competitive(self, store, head_root, parent_root) -> bool:
        return (store.unrealized_justifications[bytes(head_root)]
                == store.unrealized_justifications[bytes(parent_root)])

    def is_finalization_ok(self, store, slot) -> bool:
        epochs = (self.compute_epoch_at_slot(slot)
                  - store.finalized_checkpoint.epoch)
        return epochs <= self.config.REORG_MAX_EPOCHS_SINCE_FINALIZATION

    def is_proposing_on_time(self, store) -> bool:
        time_into_slot = ((store.time - store.genesis_time)
                          % int(self.config.SECONDS_PER_SLOT))
        cutoff = int(self.config.SECONDS_PER_SLOT) // INTERVALS_PER_SLOT // 2
        return time_into_slot <= cutoff

    def is_head_weak(self, store, head_root) -> bool:
        justified_state = store.checkpoint_states[_ckpt_key(store.justified_checkpoint)]
        threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_HEAD_WEIGHT_THRESHOLD)
        return self.get_weight(store, head_root) < threshold

    def is_parent_strong(self, store, parent_root) -> bool:
        justified_state = store.checkpoint_states[_ckpt_key(store.justified_checkpoint)]
        threshold = self.calculate_committee_fraction(
            justified_state, self.config.REORG_PARENT_WEIGHT_THRESHOLD)
        return self.get_weight(store, parent_root) > threshold

    def get_proposer_head(self, store, head_root, slot):
        """Single-slot re-org rule: build on the parent when the late, weak
        head can be safely orphaned by our boosted proposal."""
        head_root = bytes(head_root)
        head_block = store.blocks[head_root]
        parent_root = bytes(head_block.parent_root)
        parent_block = store.blocks[parent_root]
        assert bytes(store.proposer_boost_root) != head_root  # boost worn off
        conditions = (
            self.is_head_late(store, head_root),
            self.is_shuffling_stable(slot),
            self.is_ffg_competitive(store, head_root, parent_root),
            self.is_finalization_ok(store, slot),
            self.is_proposing_on_time(store),
            parent_block.slot + 1 == head_block.slot,
            head_block.slot + 1 == slot,
            self.is_head_weak(store, head_root),
            self.is_parent_strong(store, parent_root),
        )
        return self.Root(parent_root if all(conditions) else head_root)

    # -- handlers -----------------------------------------------------------

    def _on_block_merge_check(self, pre_state, block) -> None:
        """Pre-merge forks: nothing to validate (overridden in bellatrix)."""

    def _on_block_data_availability_check(self, block) -> None:
        """Pre-blob forks: nothing to check (overridden in deneb)."""

    def on_tick_per_slot(self, store, time) -> None:
        previous_slot = self.get_current_slot(store)
        store.time = int(time)
        current_slot = self.get_current_slot(store)
        if current_slot > previous_slot:
            store.proposer_boost_root = b"\x00" * 32
            if self.compute_slots_since_epoch_start(current_slot) == 0:
                self.update_checkpoints(store,
                                        store.unrealized_justified_checkpoint,
                                        store.unrealized_finalized_checkpoint)

    def on_tick(self, store, time) -> None:
        # catch up slot by slot so every boundary runs its per-slot logic
        tick_slot = (int(time) - store.genesis_time) // int(self.config.SECONDS_PER_SLOT)
        while self.get_current_slot(store) < tick_slot:
            previous_time = (store.genesis_time
                             + (int(self.get_current_slot(store)) + 1)
                             * int(self.config.SECONDS_PER_SLOT))
            self.on_tick_per_slot(store, previous_time)
        self.on_tick_per_slot(store, time)

    def on_block(self, store, signed_block) -> None:
        block = signed_block.message
        assert bytes(block.parent_root) in store.block_states
        pre_state = store.block_states[bytes(block.parent_root)].copy()
        assert self.get_current_slot(store) >= block.slot
        finalized_slot = self.compute_start_slot_at_epoch(
            store.finalized_checkpoint.epoch)
        assert block.slot > finalized_slot
        finalized_block = self.get_checkpoint_block(
            store, block.parent_root, store.finalized_checkpoint.epoch)
        assert bytes(store.finalized_checkpoint.root) == bytes(finalized_block)

        # One batched-verification scope spans the data-availability
        # check AND the state transition: the blob-KZG batch pairing
        # (deneb+) defers into the same flush as the block's signature
        # checks, so the whole on_block verifies with ONE pairing on the
        # RLC path (utils/bls.py; state_transition's nested scope joins
        # this batch and flushes it before any store mutation below).
        with bls.batched_verification() as batch:
            # deneb+: blob data-availability check
            # (deneb/fork-choice.md:70); no-op pre-deneb
            self._on_block_data_availability_check(block)

            state = pre_state
            block_root = hash_tree_root(block)
            self.state_transition(state, signed_block, True)
        batch.assert_valid()
        # bellatrix+: merge-transition validation hook
        # (specs/bellatrix/fork-choice.md:266); no-op pre-merge
        self._on_block_merge_check(store.block_states[bytes(block.parent_root)],
                                   block)
        store.blocks[block_root] = block.copy()
        store.block_states[block_root] = state

        time_into_slot = ((store.time - store.genesis_time)
                          % int(self.config.SECONDS_PER_SLOT))
        is_before_attesting_interval = (
            time_into_slot < int(self.config.SECONDS_PER_SLOT) // INTERVALS_PER_SLOT)
        is_timely = (self.get_current_slot(store) == block.slot
                     and is_before_attesting_interval)
        store.block_timeliness[block_root] = is_timely
        if is_timely and bytes(store.proposer_boost_root) == b"\x00" * 32:
            store.proposer_boost_root = block_root

        self.update_checkpoints(store, state.current_justified_checkpoint,
                                state.finalized_checkpoint)
        self.compute_pulled_up_tip(store, block_root)

    def validate_target_epoch_against_current_time(self, store, attestation) -> None:
        target = attestation.data.target
        current_epoch = self.get_current_store_epoch(store)
        previous_epoch = (current_epoch - 1 if current_epoch > self.GENESIS_EPOCH
                          else self.GENESIS_EPOCH)
        assert target.epoch in (current_epoch, previous_epoch)

    def validate_on_attestation(self, store, attestation, is_from_block) -> None:
        target = attestation.data.target
        if not is_from_block:
            self.validate_target_epoch_against_current_time(store, attestation)
        assert target.epoch == self.compute_epoch_at_slot(attestation.data.slot)
        assert bytes(target.root) in store.blocks
        assert bytes(attestation.data.beacon_block_root) in store.blocks
        # votes for future blocks or unreached slots are delayed, not applied
        assert (store.blocks[bytes(attestation.data.beacon_block_root)].slot
                <= attestation.data.slot)
        assert bytes(target.root) == bytes(self.get_checkpoint_block(
            store, attestation.data.beacon_block_root, target.epoch))
        assert self.get_current_slot(store) >= attestation.data.slot + 1

    def store_target_checkpoint_state(self, store, target) -> None:
        key = _ckpt_key(target)
        if key not in store.checkpoint_states:
            base_state = store.block_states[bytes(target.root)].copy()
            start = self.compute_start_slot_at_epoch(target.epoch)
            if base_state.slot < start:
                self.process_slots(base_state, start)
            store.checkpoint_states[key] = base_state

    def update_latest_messages(self, store, attesting_indices, attestation) -> None:
        target = attestation.data.target
        root = bytes(attestation.data.beacon_block_root)
        for i in attesting_indices:
            i = int(i)
            if i in store.equivocating_indices:
                continue
            prev = store.latest_messages.get(i)
            if prev is None or target.epoch > prev.epoch:
                store.latest_messages[i] = LatestMessage(
                    epoch=int(target.epoch), root=root)

    def on_attestation(self, store, attestation, is_from_block=False) -> None:
        self.validate_on_attestation(store, attestation, is_from_block)
        self.store_target_checkpoint_state(store, attestation.data.target)
        target_state = store.checkpoint_states[_ckpt_key(attestation.data.target)]
        indexed = self.get_indexed_attestation(target_state, attestation)
        assert self.is_valid_indexed_attestation(target_state, indexed)
        self.update_latest_messages(store, indexed.attesting_indices, attestation)

    def on_attester_slashing(self, store, attester_slashing) -> None:
        att1 = attester_slashing.attestation_1
        att2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(att1.data, att2.data)
        state = store.block_states[bytes(store.justified_checkpoint.root)]
        assert self.is_valid_indexed_attestation(state, att1)
        assert self.is_valid_indexed_attestation(state, att2)
        for index in (set(map(int, att1.attesting_indices))
                      & set(map(int, att2.attesting_indices))):
            store.equivocating_indices.add(index)


# proto-array dispatch + store bookkeeping, wrapped from the outside so
# the method bodies above stay spec-shaped (the compiled ladder gets the
# same treatment in ``forks.use_compiled_registry``)
install_forkchoice_accel(ForkChoiceMixin)
# span-instrument the handler surface on top of the accel dispatch
# (the fork classes only define the transition methods; on_block /
# on_attestation / on_tick live here on the mixin)
install_tracing(ForkChoiceMixin)
