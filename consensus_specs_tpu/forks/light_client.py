"""Altair light-client sync protocol.

Behavioral sources: ``specs/altair/light-client/sync-protocol.md``
(containers :85-170, ``is_better_update`` :196,
``initialize_light_client_store`` :287, ``validate_light_client_update``
:322, ``apply_light_client_update`` :406, force update :426,
``process_light_client_update`` :444, finality/optimistic wrappers
:495-535) and ``specs/altair/light-client/full-node.md`` (the
``create_light_client_*`` derivation helpers).  Mixed into
:class:`AltairSpec`; proofs come from the generic SSZ gindex machinery
(``utils/ssz/proofs.py``) instead of a hand-maintained backing tree.
"""
from dataclasses import dataclass
from typing import Optional

from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, Bytes32, Vector, Container,
    get_generalized_index, compute_merkle_proof,
)
from consensus_specs_tpu.utils import bls
from .base_types import Slot, Root, DOMAIN_SYNC_COMMITTEE  # noqa: F401 (compiled-spec namespace)


def floorlog2(x: int) -> int:
    return int(x).bit_length() - 1


class LightClientMixin:
    """Light-client protocol methods for altair+ spec classes."""

    MIN_SYNC_COMMITTEE_PARTICIPANTS = 1
    floorlog2 = staticmethod(floorlog2)

    # -- type construction (sync-protocol.md:60-170) -------------------------

    def _build_light_client_types(self):
        S = self
        self.FINALIZED_ROOT_GINDEX = get_generalized_index(
            self.BeaconState, "finalized_checkpoint", "root")
        self.CURRENT_SYNC_COMMITTEE_GINDEX = get_generalized_index(
            self.BeaconState, "current_sync_committee")
        self.NEXT_SYNC_COMMITTEE_GINDEX = get_generalized_index(
            self.BeaconState, "next_sync_committee")
        self.UPDATE_TIMEOUT = \
            self.SLOTS_PER_EPOCH * self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD

        FinalityBranch = Vector[Bytes32, floorlog2(self.FINALIZED_ROOT_GINDEX)]
        CurrentSyncCommitteeBranch = Vector[
            Bytes32, floorlog2(self.CURRENT_SYNC_COMMITTEE_GINDEX)]
        NextSyncCommitteeBranch = Vector[
            Bytes32, floorlog2(self.NEXT_SYNC_COMMITTEE_GINDEX)]
        self.FinalityBranch = FinalityBranch
        self.CurrentSyncCommitteeBranch = CurrentSyncCommitteeBranch
        self.NextSyncCommitteeBranch = NextSyncCommitteeBranch

        class LightClientHeader(Container):
            beacon: S.BeaconBlockHeader

        class LightClientBootstrap(Container):
            header: LightClientHeader
            current_sync_committee: S.SyncCommittee
            current_sync_committee_branch: CurrentSyncCommitteeBranch

        class LightClientUpdate(Container):
            attested_header: LightClientHeader
            next_sync_committee: S.SyncCommittee
            next_sync_committee_branch: NextSyncCommitteeBranch
            finalized_header: LightClientHeader
            finality_branch: FinalityBranch
            sync_aggregate: S.SyncAggregate
            signature_slot: Slot

        class LightClientFinalityUpdate(Container):
            attested_header: LightClientHeader
            finalized_header: LightClientHeader
            finality_branch: FinalityBranch
            sync_aggregate: S.SyncAggregate
            signature_slot: Slot

        class LightClientOptimisticUpdate(Container):
            attested_header: LightClientHeader
            sync_aggregate: S.SyncAggregate
            signature_slot: Slot

        @dataclass
        class LightClientStore:
            finalized_header: object
            current_sync_committee: object
            next_sync_committee: object
            best_valid_update: Optional[object]
            optimistic_header: object
            previous_max_active_participants: int
            current_max_active_participants: int

        self.LightClientHeader = LightClientHeader
        self.LightClientBootstrap = LightClientBootstrap
        self.LightClientUpdate = LightClientUpdate
        self.LightClientFinalityUpdate = LightClientFinalityUpdate
        self.LightClientOptimisticUpdate = LightClientOptimisticUpdate
        self.LightClientStore = LightClientStore

    # -- helpers (sync-protocol.md:172-281) ----------------------------------

    def is_valid_light_client_header(self, header) -> bool:
        return True  # altair; capella+ add execution-payload validation

    def is_sync_committee_update(self, update) -> bool:
        return update.next_sync_committee_branch != \
            self.NextSyncCommitteeBranch()

    def is_finality_update(self, update) -> bool:
        return update.finality_branch != self.FinalityBranch()

    def is_better_update(self, new_update, old_update) -> bool:
        """Update-ranking rules (sync-protocol.md:196)."""
        max_active_participants = len(
            new_update.sync_aggregate.sync_committee_bits)
        new_num = sum(new_update.sync_aggregate.sync_committee_bits)
        old_num = sum(old_update.sync_aggregate.sync_committee_bits)
        new_super = new_num * 3 >= max_active_participants * 2
        old_super = old_num * 3 >= max_active_participants * 2
        if new_super != old_super:
            return new_super > old_super
        if not new_super and new_num != old_num:
            return new_num > old_num

        new_relevant = self.is_sync_committee_update(new_update) and (
            self.compute_sync_committee_period_at_slot(
                new_update.attested_header.beacon.slot)
            == self.compute_sync_committee_period_at_slot(
                new_update.signature_slot))
        old_relevant = self.is_sync_committee_update(old_update) and (
            self.compute_sync_committee_period_at_slot(
                old_update.attested_header.beacon.slot)
            == self.compute_sync_committee_period_at_slot(
                old_update.signature_slot))
        if new_relevant != old_relevant:
            return new_relevant

        new_final = self.is_finality_update(new_update)
        old_final = self.is_finality_update(old_update)
        if new_final != old_final:
            return new_final

        if new_final:
            new_cf = (self.compute_sync_committee_period_at_slot(
                new_update.finalized_header.beacon.slot)
                == self.compute_sync_committee_period_at_slot(
                    new_update.attested_header.beacon.slot))
            old_cf = (self.compute_sync_committee_period_at_slot(
                old_update.finalized_header.beacon.slot)
                == self.compute_sync_committee_period_at_slot(
                    old_update.attested_header.beacon.slot))
            if new_cf != old_cf:
                return new_cf

        if new_num != old_num:
            return new_num > old_num
        if new_update.attested_header.beacon.slot != \
                old_update.attested_header.beacon.slot:
            return new_update.attested_header.beacon.slot < \
                old_update.attested_header.beacon.slot
        return new_update.signature_slot < old_update.signature_slot

    def is_next_sync_committee_known(self, store) -> bool:
        return store.next_sync_committee != self.SyncCommittee()

    def get_safety_threshold(self, store) -> int:
        return max(store.previous_max_active_participants,
                   store.current_max_active_participants) // 2

    def get_subtree_index(self, generalized_index: int) -> uint64:
        return uint64(generalized_index % 2**(floorlog2(generalized_index)))

    def compute_sync_committee_period(self, epoch):
        return uint64(epoch // self.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)

    def compute_sync_committee_period_at_slot(self, slot):
        return self.compute_sync_committee_period(
            self.compute_epoch_at_slot(slot))

    def compute_fork_version(self, epoch):
        """Fork version schedule (``specs/altair/fork.md`` pattern),
        walking the configured fork ladder newest-first."""
        ladder = (("DENEB_FORK_EPOCH", "DENEB_FORK_VERSION"),
                  ("CAPELLA_FORK_EPOCH", "CAPELLA_FORK_VERSION"),
                  ("BELLATRIX_FORK_EPOCH", "BELLATRIX_FORK_VERSION"),
                  ("ALTAIR_FORK_EPOCH", "ALTAIR_FORK_VERSION"))
        for epoch_name, version_name in ladder:
            fork_epoch = getattr(self.config, epoch_name, None)
            if fork_epoch is not None and epoch >= fork_epoch:
                return getattr(self.config, version_name)
        return self.config.GENESIS_FORK_VERSION

    # -- initialization (sync-protocol.md:287) -------------------------------

    def initialize_light_client_store(self, trusted_block_root, bootstrap):
        assert self.is_valid_light_client_header(bootstrap.header)
        assert hash_tree_root(bootstrap.header.beacon) == trusted_block_root

        assert self.is_valid_merkle_branch(
            leaf=hash_tree_root(bootstrap.current_sync_committee),
            branch=bootstrap.current_sync_committee_branch,
            depth=floorlog2(self.CURRENT_SYNC_COMMITTEE_GINDEX),
            index=self.get_subtree_index(self.CURRENT_SYNC_COMMITTEE_GINDEX),
            root=bootstrap.header.beacon.state_root,
        )
        return self.LightClientStore(
            finalized_header=bootstrap.header,
            current_sync_committee=bootstrap.current_sync_committee,
            next_sync_committee=self.SyncCommittee(),
            best_valid_update=None,
            optimistic_header=bootstrap.header,
            previous_max_active_participants=0,
            current_max_active_participants=0,
        )

    # -- update validation (sync-protocol.md:322) ----------------------------

    def validate_light_client_update(self, store, update, current_slot,
                                     genesis_validators_root) -> None:
        sync_aggregate = update.sync_aggregate
        assert sum(sync_aggregate.sync_committee_bits) >= \
            self.MIN_SYNC_COMMITTEE_PARTICIPANTS

        assert self.is_valid_light_client_header(update.attested_header)
        update_attested_slot = update.attested_header.beacon.slot
        update_finalized_slot = update.finalized_header.beacon.slot
        assert current_slot >= update.signature_slot > update_attested_slot \
            >= update_finalized_slot
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot)
        update_signature_period = self.compute_sync_committee_period_at_slot(
            update.signature_slot)
        if self.is_next_sync_committee_known(store):
            assert update_signature_period in (store_period, store_period + 1)
        else:
            assert update_signature_period == store_period

        update_attested_period = self.compute_sync_committee_period_at_slot(
            update_attested_slot)
        update_has_next_sync_committee = \
            not self.is_next_sync_committee_known(store) and (
                self.is_sync_committee_update(update)
                and update_attested_period == store_period)
        assert (update_attested_slot > store.finalized_header.beacon.slot
                or update_has_next_sync_committee)

        # finality branch confirms finalized_header against attested state
        if not self.is_finality_update(update):
            assert update.finalized_header == self.LightClientHeader()
        else:
            if update_finalized_slot == self.GENESIS_SLOT:
                assert update.finalized_header == self.LightClientHeader()
                finalized_root = Bytes32()
            else:
                assert self.is_valid_light_client_header(
                    update.finalized_header)
                finalized_root = hash_tree_root(update.finalized_header.beacon)
            assert self.is_valid_merkle_branch(
                leaf=finalized_root,
                branch=update.finality_branch,
                depth=floorlog2(self.FINALIZED_ROOT_GINDEX),
                index=self.get_subtree_index(self.FINALIZED_ROOT_GINDEX),
                root=update.attested_header.beacon.state_root,
            )

        # next sync committee branch
        if not self.is_sync_committee_update(update):
            assert update.next_sync_committee == self.SyncCommittee()
        else:
            if update_attested_period == store_period and \
                    self.is_next_sync_committee_known(store):
                assert update.next_sync_committee == store.next_sync_committee
            assert self.is_valid_merkle_branch(
                leaf=hash_tree_root(update.next_sync_committee),
                branch=update.next_sync_committee_branch,
                depth=floorlog2(self.NEXT_SYNC_COMMITTEE_GINDEX),
                index=self.get_subtree_index(self.NEXT_SYNC_COMMITTEE_GINDEX),
                root=update.attested_header.beacon.state_root,
            )

        # aggregate signature
        if update_signature_period == store_period:
            sync_committee = store.current_sync_committee
        else:
            sync_committee = store.next_sync_committee
        participant_pubkeys = [
            pubkey for (bit, pubkey) in zip(
                sync_aggregate.sync_committee_bits, sync_committee.pubkeys)
            if bit]
        fork_version_slot = max(update.signature_slot, Slot(1)) - Slot(1)
        fork_version = self.compute_fork_version(
            self.compute_epoch_at_slot(fork_version_slot))
        domain = self.compute_domain(DOMAIN_SYNC_COMMITTEE, fork_version,
                                     genesis_validators_root)
        signing_root = self.compute_signing_root(
            update.attested_header.beacon, domain)
        assert bls.FastAggregateVerify(
            participant_pubkeys, signing_root,
            sync_aggregate.sync_committee_signature)

    # -- apply / force / process (sync-protocol.md:406-535) ------------------

    def apply_light_client_update(self, store, update) -> None:
        store_period = self.compute_sync_committee_period_at_slot(
            store.finalized_header.beacon.slot)
        update_finalized_period = self.compute_sync_committee_period_at_slot(
            update.finalized_header.beacon.slot)
        if not self.is_next_sync_committee_known(store):
            assert update_finalized_period == store_period
            store.next_sync_committee = update.next_sync_committee
        elif update_finalized_period == store_period + 1:
            store.current_sync_committee = store.next_sync_committee
            store.next_sync_committee = update.next_sync_committee
            store.previous_max_active_participants = \
                store.current_max_active_participants
            store.current_max_active_participants = 0
        if update.finalized_header.beacon.slot > \
                store.finalized_header.beacon.slot:
            store.finalized_header = update.finalized_header
            if store.finalized_header.beacon.slot > \
                    store.optimistic_header.beacon.slot:
                store.optimistic_header = store.finalized_header

    def process_light_client_store_force_update(self, store,
                                                current_slot) -> None:
        if (current_slot > store.finalized_header.beacon.slot
                + self.UPDATE_TIMEOUT
                and store.best_valid_update is not None):
            if store.best_valid_update.finalized_header.beacon.slot <= \
                    store.finalized_header.beacon.slot:
                store.best_valid_update.finalized_header = \
                    store.best_valid_update.attested_header
            self.apply_light_client_update(store, store.best_valid_update)
            store.best_valid_update = None

    def process_light_client_update(self, store, update, current_slot,
                                    genesis_validators_root) -> None:
        self.validate_light_client_update(store, update, current_slot,
                                          genesis_validators_root)
        sync_committee_bits = update.sync_aggregate.sync_committee_bits

        if (store.best_valid_update is None
                or self.is_better_update(update, store.best_valid_update)):
            store.best_valid_update = update

        store.current_max_active_participants = max(
            store.current_max_active_participants, sum(sync_committee_bits))

        if (sum(sync_committee_bits) > self.get_safety_threshold(store)
                and update.attested_header.beacon.slot
                > store.optimistic_header.beacon.slot):
            store.optimistic_header = update.attested_header

        update_has_finalized_next_sync_committee = (
            not self.is_next_sync_committee_known(store)
            and self.is_sync_committee_update(update)
            and self.is_finality_update(update)
            and (self.compute_sync_committee_period_at_slot(
                update.finalized_header.beacon.slot)
                == self.compute_sync_committee_period_at_slot(
                    update.attested_header.beacon.slot)))
        if (sum(sync_committee_bits) * 3 >= len(sync_committee_bits) * 2
                and (update.finalized_header.beacon.slot
                     > store.finalized_header.beacon.slot
                     or update_has_finalized_next_sync_committee)):
            self.apply_light_client_update(store, update)
            store.best_valid_update = None

    def process_light_client_finality_update(self, store, finality_update,
                                             current_slot,
                                             genesis_validators_root) -> None:
        update = self.LightClientUpdate(
            attested_header=finality_update.attested_header,
            next_sync_committee=self.SyncCommittee(),
            next_sync_committee_branch=self.NextSyncCommitteeBranch(),
            finalized_header=finality_update.finalized_header,
            finality_branch=finality_update.finality_branch,
            sync_aggregate=finality_update.sync_aggregate,
            signature_slot=finality_update.signature_slot,
        )
        self.process_light_client_update(store, update, current_slot,
                                         genesis_validators_root)

    def process_light_client_optimistic_update(self, store, optimistic_update,
                                               current_slot,
                                               genesis_validators_root) -> None:
        update = self.LightClientUpdate(
            attested_header=optimistic_update.attested_header,
            next_sync_committee=self.SyncCommittee(),
            next_sync_committee_branch=self.NextSyncCommitteeBranch(),
            finalized_header=self.LightClientHeader(),
            finality_branch=self.FinalityBranch(),
            sync_aggregate=optimistic_update.sync_aggregate,
            signature_slot=optimistic_update.signature_slot,
        )
        self.process_light_client_update(store, update, current_slot,
                                         genesis_validators_root)

    # -- full-node derivation (full-node.md) ---------------------------------

    def block_to_light_client_header(self, block):
        return self.LightClientHeader(
            beacon=self.BeaconBlockHeader(
                slot=block.message.slot,
                proposer_index=block.message.proposer_index,
                parent_root=block.message.parent_root,
                state_root=block.message.state_root,
                body_root=hash_tree_root(block.message.body),
            ))

    def create_light_client_bootstrap(self, state, block):
        """full-node.md create_light_client_bootstrap."""
        assert self.compute_epoch_at_slot(state.slot) >= \
            self.config.ALTAIR_FORK_EPOCH
        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)
        return self.LightClientBootstrap(
            header=self.block_to_light_client_header(block),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=compute_merkle_proof(
                state, self.CURRENT_SYNC_COMMITTEE_GINDEX),
        )

    def create_light_client_update(self, state, block, attested_state,
                                   attested_block, finalized_block):
        """full-node.md create_light_client_update."""
        assert self.compute_epoch_at_slot(attested_state.slot) >= \
            self.config.ALTAIR_FORK_EPOCH
        assert sum(block.message.body.sync_aggregate.sync_committee_bits) >= \
            self.MIN_SYNC_COMMITTEE_PARTICIPANTS

        # signature block must correspond to the given state
        assert state.slot == state.latest_block_header.slot
        header = state.latest_block_header.copy()
        header.state_root = hash_tree_root(state)
        assert hash_tree_root(header) == hash_tree_root(block.message)
        assert attested_state.slot == attested_state.latest_block_header.slot

        attested_header = attested_state.latest_block_header.copy()
        attested_header.state_root = hash_tree_root(attested_state)
        assert hash_tree_root(attested_header) == \
            hash_tree_root(attested_block.message) == \
            block.message.parent_root

        update = self.LightClientUpdate()
        update.attested_header = \
            self.block_to_light_client_header(attested_block)
        update_attested_period = self.compute_sync_committee_period_at_slot(
            attested_block.message.slot)
        update_signature_period = self.compute_sync_committee_period_at_slot(
            block.message.slot)
        if update_attested_period == update_signature_period:
            update.next_sync_committee = attested_state.next_sync_committee
            update.next_sync_committee_branch = compute_merkle_proof(
                attested_state, self.NEXT_SYNC_COMMITTEE_GINDEX)
        if finalized_block is not None:
            if finalized_block.message.slot != self.GENESIS_SLOT:
                update.finalized_header = \
                    self.block_to_light_client_header(finalized_block)
                assert hash_tree_root(update.finalized_header.beacon) == \
                    attested_state.finalized_checkpoint.root
            else:
                assert attested_state.finalized_checkpoint.root == Bytes32()
            update.finality_branch = compute_merkle_proof(
                attested_state, self.FINALIZED_ROOT_GINDEX)
        update.sync_aggregate = block.message.body.sync_aggregate
        update.signature_slot = block.message.slot
        return update

    def create_light_client_finality_update(self, update):
        return self.LightClientFinalityUpdate(
            attested_header=update.attested_header,
            finalized_header=update.finalized_header,
            finality_branch=update.finality_branch,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot,
        )

    def create_light_client_optimistic_update(self, update):
        return self.LightClientOptimisticUpdate(
            attested_header=update.attested_header,
            sync_aggregate=update.sync_aggregate,
            signature_slot=update.signature_slot,
        )
