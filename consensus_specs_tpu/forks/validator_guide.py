"""Honest-validator duties, p2p subnet computation, weak subjectivity.

Behavioral sources:
- ``specs/phase0/validator.md`` (``get_committee_assignment`` :211,
  ``is_proposer`` :239, randao/eth1-vote/signing helpers :325-448,
  ``compute_subnet_for_attestation`` :519, selection proofs +
  ``is_aggregator`` :541-552, aggregate-and-proof :589-610)
- ``specs/phase0/p2p-interface.md`` (constants :184-206,
  ``compute_subscribed_subnet(s)`` :1021-1037)
- ``specs/phase0/weak-subjectivity.md``
  (``compute_weak_subjectivity_period`` :87,
  ``is_within_weak_subjectivity_period`` :171)
- ``specs/altair/validator.md`` (sync-committee duty containers :79-130,
  message/selection/aggregation helpers :271-400,
  ``process_sync_committee_contributions`` :222)
"""
from typing import Optional, Sequence, Set, Tuple

from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import uint64, Container, Bitvector
from consensus_specs_tpu.utils import bls
from .base_types import (
    Slot, Epoch, CommitteeIndex, ValidatorIndex, Root, BLSSignature,
    DOMAIN_RANDAO, DOMAIN_BEACON_PROPOSER, DOMAIN_BEACON_ATTESTER,
    DOMAIN_SELECTION_PROOF, DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_SYNC_COMMITTEE, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_CONTRIBUTION_AND_PROOF,
)

SubnetID = uint64
NodeID = int

# p2p-interface.md:184-206
TARGET_AGGREGATORS_PER_COMMITTEE = 2**4
NODE_ID_BITS = 256
EPOCHS_PER_SUBNET_SUBSCRIPTION = 2**8
SUBNETS_PER_NODE = 2
ATTESTATION_SUBNET_COUNT = 2**6
ATTESTATION_SUBNET_EXTRA_BITS = 0
ATTESTATION_SUBNET_PREFIX_BITS = (
    (ATTESTATION_SUBNET_COUNT - 1).bit_length() + ATTESTATION_SUBNET_EXTRA_BITS)

# weak-subjectivity.md:60-80
ETH_TO_GWEI = uint64(10**9)
SAFETY_DECAY = uint64(10)


class ValidatorGuideMixin:
    """phase0 honest-validator duties, mixed into the spec classes."""

    TARGET_AGGREGATORS_PER_COMMITTEE = TARGET_AGGREGATORS_PER_COMMITTEE
    NODE_ID_BITS = NODE_ID_BITS
    EPOCHS_PER_SUBNET_SUBSCRIPTION = EPOCHS_PER_SUBNET_SUBSCRIPTION
    SUBNETS_PER_NODE = SUBNETS_PER_NODE
    ATTESTATION_SUBNET_COUNT = ATTESTATION_SUBNET_COUNT
    ATTESTATION_SUBNET_PREFIX_BITS = ATTESTATION_SUBNET_PREFIX_BITS
    ETH_TO_GWEI = ETH_TO_GWEI
    SAFETY_DECAY = SAFETY_DECAY
    SubnetID = SubnetID

    # -- assignments (validator.md:211-241) ----------------------------------

    def get_committee_assignment(
            self, state, epoch, validator_index
    ) -> Optional[Tuple[Sequence[int], int, int]]:
        """(committee, committee index, slot) or None (validator.md:211)."""
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        assert epoch <= next_epoch

        start_slot = self.compute_start_slot_at_epoch(epoch)
        committee_count_per_slot = self.get_committee_count_per_slot(
            state, epoch)
        for slot in range(start_slot, start_slot + self.SLOTS_PER_EPOCH):
            for index in range(committee_count_per_slot):
                committee = self.get_beacon_committee(
                    state, Slot(slot), CommitteeIndex(index))
                if validator_index in committee:
                    return committee, CommitteeIndex(index), Slot(slot)
        return None

    def is_proposer(self, state, validator_index) -> bool:
        return self.get_beacon_proposer_index(state) == validator_index

    # -- signing helpers (validator.md:325-448,504) --------------------------

    def get_epoch_signature(self, state, block, privkey) -> bytes:
        domain = self.get_domain(state, DOMAIN_RANDAO,
                                 self.compute_epoch_at_slot(block.slot))
        signing_root = self.compute_signing_root(
            uint64(self.compute_epoch_at_slot(block.slot)), domain)
        return bls.Sign(privkey, signing_root)

    def get_block_signature(self, state, block, privkey) -> bytes:
        domain = self.get_domain(state, DOMAIN_BEACON_PROPOSER,
                                 self.compute_epoch_at_slot(block.slot))
        signing_root = self.compute_signing_root(block, domain)
        return bls.Sign(privkey, signing_root)

    def get_attestation_signature(self, state, attestation_data,
                                  privkey) -> bytes:
        domain = self.get_domain(state, DOMAIN_BEACON_ATTESTER,
                                 attestation_data.target.epoch)
        signing_root = self.compute_signing_root(attestation_data, domain)
        return bls.Sign(privkey, signing_root)

    # -- eth1 voting (validator.md:350-393) ----------------------------------

    def compute_time_at_slot(self, state, slot) -> uint64:
        return uint64(state.genesis_time
                      + slot * self.config.SECONDS_PER_SLOT)

    def voting_period_start_time(self, state) -> uint64:
        eth1_voting_period_start_slot = Slot(
            state.slot - state.slot % (self.EPOCHS_PER_ETH1_VOTING_PERIOD
                                       * self.SLOTS_PER_EPOCH))
        return self.compute_time_at_slot(state, eth1_voting_period_start_slot)

    def is_candidate_block(self, block, period_start) -> bool:
        follow = (self.config.SECONDS_PER_ETH1_BLOCK
                  * self.config.ETH1_FOLLOW_DISTANCE)
        return (block.timestamp + follow <= period_start
                and block.timestamp + follow * 2 >= period_start)

    def get_eth1_data(self, block):
        """Test stub mapping an Eth1Block to its vote data (the reference
        injects an equivalent stub, ``pysetup/spec_builders/phase0.py:37``)."""
        return self.Eth1Data(
            deposit_root=block.deposit_root,
            deposit_count=block.deposit_count,
            block_hash=self.hash_tree_root(block),
        )

    def get_eth1_vote(self, state, eth1_chain):
        """validator.md:369"""
        period_start = self.voting_period_start_time(state)
        votes_to_consider = [
            self.get_eth1_data(block) for block in eth1_chain
            if (self.is_candidate_block(block, period_start)
                and self.get_eth1_data(block).deposit_count
                >= state.eth1_data.deposit_count)
        ]
        valid_votes = [vote for vote in state.eth1_data_votes
                       if vote in votes_to_consider]
        default_vote = (votes_to_consider[len(votes_to_consider) - 1]
                        if any(votes_to_consider) else state.eth1_data)
        return max(
            valid_votes,
            key=lambda v: (valid_votes.count(v), -valid_votes.index(v)),
            default=default_vote,
        )

    # -- attestation aggregation (validator.md:519-610) ----------------------

    def compute_subnet_for_attestation(self, committees_per_slot, slot,
                                       committee_index) -> uint64:
        """validator.md:519"""
        slots_since_epoch_start = uint64(slot % self.SLOTS_PER_EPOCH)
        committees_since_epoch_start = (committees_per_slot
                                        * slots_since_epoch_start)
        return SubnetID((committees_since_epoch_start + committee_index)
                        % ATTESTATION_SUBNET_COUNT)

    def get_slot_signature(self, state, slot, privkey) -> bytes:
        domain = self.get_domain(state, DOMAIN_SELECTION_PROOF,
                                 self.compute_epoch_at_slot(slot))
        signing_root = self.compute_signing_root(uint64(slot), domain)
        return bls.Sign(privkey, signing_root)

    def is_aggregator(self, state, slot, index, slot_signature) -> bool:
        """validator.md:548"""
        committee = self.get_beacon_committee(state, slot, index)
        modulo = max(1, len(committee) // TARGET_AGGREGATORS_PER_COMMITTEE)
        return self.bytes_to_uint64(hash(slot_signature)[0:8]) % modulo == 0

    def get_aggregate_signature(self, attestations) -> bytes:
        return bls.Aggregate([a.signature for a in attestations])

    def get_aggregate_and_proof(self, state, aggregator_index, aggregate,
                                privkey):
        return self.AggregateAndProof(
            aggregator_index=aggregator_index,
            aggregate=aggregate,
            selection_proof=self.get_slot_signature(
                state, aggregate.data.slot, privkey),
        )

    def get_aggregate_and_proof_signature(self, state, aggregate_and_proof,
                                          privkey) -> bytes:
        aggregate = aggregate_and_proof.aggregate
        domain = self.get_domain(
            state, DOMAIN_AGGREGATE_AND_PROOF,
            self.compute_epoch_at_slot(aggregate.data.slot))
        signing_root = self.compute_signing_root(aggregate_and_proof, domain)
        return bls.Sign(privkey, signing_root)

    # -- p2p subnet backbone (p2p-interface.md:1021-1037) --------------------

    def compute_subscribed_subnet(self, node_id: int, epoch, index) -> uint64:
        node_id_prefix = node_id >> (NODE_ID_BITS
                                     - ATTESTATION_SUBNET_PREFIX_BITS)
        node_offset = node_id % EPOCHS_PER_SUBNET_SUBSCRIPTION
        permutation_seed = hash(self.uint_to_bytes(uint64(
            (epoch + node_offset) // EPOCHS_PER_SUBNET_SUBSCRIPTION)))
        permutated_prefix = self.compute_shuffled_index(
            node_id_prefix, 1 << ATTESTATION_SUBNET_PREFIX_BITS,
            permutation_seed)
        return SubnetID((permutated_prefix + index)
                        % ATTESTATION_SUBNET_COUNT)

    def compute_subscribed_subnets(self, node_id: int, epoch):
        return [self.compute_subscribed_subnet(node_id, epoch, index)
                for index in range(SUBNETS_PER_NODE)]

    # -- weak subjectivity (weak-subjectivity.md:87,171) ---------------------

    def compute_weak_subjectivity_period(self, state) -> uint64:
        ws_period = self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        N = len(self.get_active_validator_indices(
            state, self.get_current_epoch(state)))
        t = self.get_total_active_balance(state) // N // ETH_TO_GWEI
        T = self.MAX_EFFECTIVE_BALANCE // ETH_TO_GWEI
        delta = self.get_validator_churn_limit(state)
        Delta = self.MAX_DEPOSITS * self.SLOTS_PER_EPOCH
        D = SAFETY_DECAY

        if T * (200 + 3 * D) < t * (200 + 12 * D):
            epochs_for_validator_set_churn = (
                N * (t * (200 + 12 * D) - T * (200 + 3 * D))
                // (600 * delta * (2 * t + T)))
            epochs_for_balance_top_ups = N * (200 + 3 * D) // (600 * Delta)
            ws_period += max(epochs_for_validator_set_churn,
                             epochs_for_balance_top_ups)
        else:
            ws_period += 3 * N * D * t // (200 * Delta * (T - t))
        return uint64(ws_period)

    def is_within_weak_subjectivity_period(self, store, ws_state,
                                           ws_checkpoint) -> bool:
        assert ws_state.latest_block_header.state_root == ws_checkpoint.root
        assert self.compute_epoch_at_slot(ws_state.slot) == ws_checkpoint.epoch

        ws_period = self.compute_weak_subjectivity_period(ws_state)
        ws_state_epoch = self.compute_epoch_at_slot(ws_state.slot)
        current_epoch = self.compute_epoch_at_slot(
            self.get_current_slot(store))
        return current_epoch <= ws_state_epoch + ws_period


# altair/validator.md:71-72
TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 2**4
SYNC_COMMITTEE_SUBNET_COUNT = 4


class SyncDutiesMixin:
    """altair+ sync-committee duties (altair/validator.md)."""

    TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = \
        TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE
    SYNC_COMMITTEE_SUBNET_COUNT = SYNC_COMMITTEE_SUBNET_COUNT

    def _build_sync_duty_types(self):
        S = self

        class SyncCommitteeMessage(Container):
            slot: Slot
            beacon_block_root: Root
            validator_index: ValidatorIndex
            signature: BLSSignature

        class SyncCommitteeContribution(Container):
            slot: Slot
            beacon_block_root: Root
            subcommittee_index: uint64
            aggregation_bits: Bitvector[
                S.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT]
            signature: BLSSignature

        class ContributionAndProof(Container):
            aggregator_index: ValidatorIndex
            contribution: SyncCommitteeContribution
            selection_proof: BLSSignature

        class SignedContributionAndProof(Container):
            message: ContributionAndProof
            signature: BLSSignature

        class SyncAggregatorSelectionData(Container):
            slot: Slot
            subcommittee_index: uint64

        self.SyncCommitteeMessage = SyncCommitteeMessage
        self.SyncCommitteeContribution = SyncCommitteeContribution
        self.ContributionAndProof = ContributionAndProof
        self.SignedContributionAndProof = SignedContributionAndProof
        self.SyncAggregatorSelectionData = SyncAggregatorSelectionData

    def get_sync_committee_message(self, state, block_root, validator_index,
                                   privkey):
        """altair/validator.md:271"""
        epoch = self.get_current_epoch(state)
        domain = self.get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch)
        signing_root = self.compute_signing_root(block_root, domain)
        return self.SyncCommitteeMessage(
            slot=state.slot,
            beacon_block_root=block_root,
            validator_index=validator_index,
            signature=bls.Sign(privkey, signing_root),
        )

    def compute_subnets_for_sync_committee(self, state,
                                           validator_index) -> Set[int]:
        """altair/validator.md:292"""
        next_slot_epoch = self.compute_epoch_at_slot(Slot(state.slot + 1))
        if self.compute_sync_committee_period(
                self.get_current_epoch(state)) == \
                self.compute_sync_committee_period(next_slot_epoch):
            sync_committee = state.current_sync_committee
        else:
            sync_committee = state.next_sync_committee
        target_pubkey = state.validators[validator_index].pubkey
        sync_committee_indices = [
            index for index, pubkey in enumerate(sync_committee.pubkeys)
            if pubkey == target_pubkey]
        return set(
            uint64(index // (self.SYNC_COMMITTEE_SIZE
                             // SYNC_COMMITTEE_SUBNET_COUNT))
            for index in sync_committee_indices)

    def get_sync_committee_selection_proof(self, state, slot,
                                           subcommittee_index, privkey):
        domain = self.get_domain(state,
                                 DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
                                 self.compute_epoch_at_slot(slot))
        signing_data = self.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index)
        signing_root = self.compute_signing_root(signing_data, domain)
        return bls.Sign(privkey, signing_root)

    def is_sync_committee_aggregator(self, signature) -> bool:
        modulo = max(1, self.SYNC_COMMITTEE_SIZE
                     // SYNC_COMMITTEE_SUBNET_COUNT
                     // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)
        return self.bytes_to_uint64(hash(signature)[0:8]) % modulo == 0

    def get_contribution_and_proof(self, state, aggregator_index,
                                   contribution, privkey):
        selection_proof = self.get_sync_committee_selection_proof(
            state, contribution.slot, contribution.subcommittee_index,
            privkey)
        return self.ContributionAndProof(
            aggregator_index=aggregator_index,
            contribution=contribution,
            selection_proof=selection_proof,
        )

    def get_contribution_and_proof_signature(self, state,
                                             contribution_and_proof,
                                             privkey):
        contribution = contribution_and_proof.contribution
        domain = self.get_domain(state, DOMAIN_CONTRIBUTION_AND_PROOF,
                                 self.compute_epoch_at_slot(
                                     contribution.slot))
        signing_root = self.compute_signing_root(contribution_and_proof,
                                                 domain)
        return bls.Sign(privkey, signing_root)

    def process_sync_committee_contributions(self, block,
                                             contributions) -> None:
        """altair/validator.md:222"""
        sync_aggregate = self.SyncAggregate()
        signatures = []
        sync_subcommittee_size = (self.SYNC_COMMITTEE_SIZE
                                  // SYNC_COMMITTEE_SUBNET_COUNT)
        for contribution in contributions:
            subcommittee_index = contribution.subcommittee_index
            for index, participated in enumerate(
                    contribution.aggregation_bits):
                if participated:
                    participant_index = (sync_subcommittee_size
                                         * subcommittee_index + index)
                    sync_aggregate.sync_committee_bits[participant_index] = \
                        True
            signatures.append(contribution.signature)
        sync_aggregate.sync_committee_signature = bls.Aggregate(signatures)
        block.body.sync_aggregate = sync_aggregate
