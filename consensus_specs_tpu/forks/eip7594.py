"""EIP-7594 (PeerDAS) feature fork: data-availability sampling.

Behavioral sources: ``specs/_features/eip7594/fork.md`` (fork version
ladder, ``upgrade_to_eip7594``),
``specs/_features/eip7594/polynomial-commitments-sampling.md`` (cell
cosets, KZG multiproofs, vanishing-polynomial erasure recovery) and
``specs/_features/das/das-core.md`` (custody columns,
``DataColumnSidecar`` construction/verification, sampling-driven
``is_data_available``).  Fork DAG parent: deneb.

Unlike the pre-PR-11 delegate, the sampling methods below are the REAL
spec algorithms, mirrored line-for-line by the markdown documents the
compiler turns into ``forks/compiled/eip7594.py`` — this spec loop is
the authoritative fallback the accelerated DAS engine
(``consensus_specs_tpu/das``) degrades to.  Only the group-level
primitives (MSM, pairing check, point decompression) are module
bindings into :mod:`consensus_specs_tpu.ops`, exactly like the deneb
KZG library binds its curve backend.

The state layout is UNCHANGED from deneb (7594 is a data-availability
fork, not a state fork): the upgrade only rotates ``state.fork``.  What
changes is how availability is established — ``is_data_available``
samples extended-blob cells instead of downloading full blobs, so a
node custodies/examines only a fraction of each blob column.
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import (  # noqa: F401 (compiled-spec namespace)
    hash_tree_root, uint64, Bytes32, ByteVector, Vector, List, Container,
)
from . import register_fork
from .deneb import DenebSpec
from .base_types import KZGCommitment, KZGProof, Root  # noqa: F401
from consensus_specs_tpu.ops import kzg as _ops_kzg
from consensus_specs_tpu.ops import kzg_7594 as _ops_kzg7594
from consensus_specs_tpu.ops.bls12_381.curve import (  # noqa: F401
    G2_GENERATOR, g2_from_compressed,
)
from consensus_specs_tpu.obs import registry as _obs_registry

ColumnIndex = uint64
CellID = uint64
RowIndex = uint64


# -- ops bindings ----------------------------------------------------------
# Group-level primitives the spec bodies call by name (the markdown's
# import surface is owned by this module, emitter-scaffold contract).
# Everything ABOVE the group level — field math, FFTs, cosets, recovery —
# is spec logic and lives in the method bodies.

def bytes48_to_g1(b):
    """Compressed 48-byte G1 -> point (infinity encoding allowed)."""
    return _ops_kzg._g1_of(bytes(b))


def bytes96_to_g2(b):
    """Compressed 96-byte G2 -> point."""
    return g2_from_compressed(bytes(b))


_PAIRINGS = _obs_registry.counter("bls.pairings").labels()


def bls_pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 over point pairs (native C when built).
    Booked on the shared ``bls.pairings`` census so the spec loop's
    one-pairing-per-cell cost is counter-visible next to the engine's
    one-pairing-per-batch fold."""
    _PAIRINGS.add()
    return _ops_kzg._pairing_check(pairs)


def g1_lincomb(points, scalars) -> bytes:
    """G1 MSM over compressed points (Pippenger / native / device)."""
    return _ops_kzg.g1_lincomb(points, scalars)


def g2_lincomb(points, scalars) -> bytes:
    """G2 MSM over compressed points (group-generic Pippenger/native)."""
    return _ops_kzg7594.g2_lincomb(points, scalars)


def validate_kzg_g1(b) -> None:
    """KeyValidate semantics except infinity is allowed."""
    _ops_kzg.validate_kzg_g1(bytes(b))


@register_fork("eip7594")
class EIP7594Spec(DenebSpec):
    fork = "eip7594"
    previous_fork = "deneb"

    # polynomial-commitments-sampling.md constants
    FIELD_ELEMENTS_PER_CELL = uint64(64)
    RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"
    PRIMITIVE_ROOT_OF_UNITY = 7
    # das-core.md constants
    DATA_COLUMN_SIDECAR_SUBNET_COUNT = uint64(32)
    CUSTODY_REQUIREMENT = uint64(1)
    SAMPLES_PER_SLOT = uint64(8)

    # -- type construction (das-core.md) -----------------------------------

    def _build_types(self):
        super()._build_types()
        S = self
        self.NUMBER_OF_COLUMNS = uint64(self.cells_per_blob())
        self.BYTES_PER_CELL = 32 * int(self.FIELD_ELEMENTS_PER_CELL)
        self.Cell = ByteVector[self.BYTES_PER_CELL]
        self.ColumnIndex = uint64

        class DataColumnSidecar(Container):
            index: uint64
            column: List[S.Cell, S.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_commitments: List[KZGCommitment,
                                  S.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            kzg_proofs: List[KZGProof, S.MAX_BLOB_COMMITMENTS_PER_BLOCK]
            signed_block_header: S.SignedBeaconBlockHeader

        class DataColumnIdentifier(Container):
            block_root: Root
            index: uint64

        self.DataColumnSidecar = DataColumnSidecar
        self.DataColumnIdentifier = DataColumnIdentifier

    # -- field + domain helpers (polynomial-commitments-sampling.md) -------

    def cells_per_blob(self) -> int:
        """Cells in one 2x-extended blob."""
        return int(2 * self.FIELD_ELEMENTS_PER_BLOB
                   // self.FIELD_ELEMENTS_PER_CELL)

    def reverse_bits(self, n, order) -> int:
        """Reverse the log2(order)-bit representation of n."""
        order = int(order)
        assert order > 0 and order & (order - 1) == 0
        return int(format(int(n),
                          "0{}b".format(order.bit_length() - 1))[::-1], 2)

    def bit_reversal_permutation(self, sequence):
        return [sequence[self.reverse_bits(i, len(sequence))]
                for i in range(len(sequence))]

    def compute_roots_of_unity(self, order):
        """[w^0 .. w^(order-1)] for a primitive order-th root w."""
        modulus = int(self.BLS_MODULUS)
        assert (modulus - 1) % int(order) == 0
        root_of_unity = pow(int(self.PRIMITIVE_ROOT_OF_UNITY),
                            (modulus - 1) // int(order), modulus)
        powers = []
        current_power = 1
        for _ in range(int(order)):
            powers.append(current_power)
            current_power = current_power * root_of_unity % modulus
        return powers

    def bls_modular_inverse(self, x) -> int:
        modulus = int(self.BLS_MODULUS)
        assert int(x) % modulus != 0
        return pow(int(x), modulus - 2, modulus)

    def blob_to_polynomial(self, blob):
        """Blob bytes -> evaluation-form polynomial (validated)."""
        blob = bytes(blob)
        width = int(self.FIELD_ELEMENTS_PER_BLOB)
        modulus = int(self.BLS_MODULUS)
        assert len(blob) == 32 * width
        polynomial = []
        for i in range(width):
            element = int.from_bytes(blob[32 * i:32 * (i + 1)], "big")
            assert element < modulus
            polynomial.append(element)
        return polynomial

    def bytes_to_cell(self, cell_bytes):
        """FIELD_ELEMENTS_PER_CELL x Bytes32 -> field elements."""
        cell_bytes = bytes(cell_bytes)
        modulus = int(self.BLS_MODULUS)
        assert len(cell_bytes) == 32 * int(self.FIELD_ELEMENTS_PER_CELL)
        cell = []
        for i in range(int(self.FIELD_ELEMENTS_PER_CELL)):
            element = int.from_bytes(cell_bytes[32 * i:32 * (i + 1)], "big")
            assert element < modulus
            cell.append(element)
        return cell

    def cell_to_bytes(self, cell) -> bytes:
        return b"".join(int(x).to_bytes(32, "big") for x in cell)

    def bytes_to_kzg_commitment(self, b) -> bytes:
        validate_kzg_g1(bytes(b))
        return bytes(b)

    def bytes_to_kzg_proof(self, b) -> bytes:
        validate_kzg_g1(bytes(b))
        return bytes(b)

    # -- FFT over the scalar field ------------------------------------------

    def fft_field(self, vals, roots_of_unity, inv=False):
        """Radix-2 FFT / inverse FFT over the given root domain."""
        modulus = int(self.BLS_MODULUS)
        if inv:
            invlen = pow(len(vals), modulus - 2, modulus)
            inv_roots = list(roots_of_unity[0:1]) \
                + list(roots_of_unity[:0:-1])
            return [x * invlen % modulus
                    for x in self._fft_field(vals, inv_roots)]
        return self._fft_field(vals, roots_of_unity)

    def _fft_field(self, vals, roots_of_unity):
        """Iterative in-place butterfly schedule; output identical to
        the recursive formulation."""
        modulus = int(self.BLS_MODULUS)
        n = len(vals)
        if n == 1:
            return [int(vals[0])]
        out = [int(vals[self.reverse_bits(i, n)]) for i in range(n)]
        m = 2
        while m <= n:
            stride = n // m
            half = m // 2
            for start in range(0, n, m):
                for j in range(half):
                    w = roots_of_unity[j * stride]
                    a = out[start + j]
                    b = out[start + j + half] * w % modulus
                    out[start + j] = (a + b) % modulus
                    out[start + j + half] = (a - b) % modulus
            m *= 2
        return out

    # -- coefficient-form polynomial ring ------------------------------------

    def polynomial_eval_to_coeff(self, polynomial):
        """Evaluation form (brp domain) -> coefficient form."""
        roots_of_unity = self.compute_roots_of_unity(
            int(self.FIELD_ELEMENTS_PER_BLOB))
        return self.fft_field(
            self.bit_reversal_permutation(list(polynomial)),
            roots_of_unity, inv=True)

    def add_polynomialcoeff(self, a, b):
        a, b = (a, b) if len(a) >= len(b) else (b, a)
        modulus = int(self.BLS_MODULUS)
        return [(int(a[i]) + (int(b[i]) if i < len(b) else 0)) % modulus
                for i in range(len(a))]

    def neg_polynomialcoeff(self, a):
        modulus = int(self.BLS_MODULUS)
        return [(modulus - int(x)) % modulus for x in a]

    def multiply_polynomialcoeff(self, a, b):
        modulus = int(self.BLS_MODULUS)
        r = [0] * (len(a) + len(b) - 1)
        for power, coef in enumerate(a):
            c = int(coef)
            if c == 0:
                continue
            for j, x in enumerate(b):
                r[power + j] = (r[power + j] + c * int(x)) % modulus
        return r

    def divide_polynomialcoeff(self, a, b):
        """Long division (exact; remainder discarded)."""
        modulus = int(self.BLS_MODULUS)
        a = [int(x) for x in a]
        o = []
        apos = len(a) - 1
        bpos = len(b) - 1
        diff = apos - bpos
        while diff >= 0:
            quot = a[apos] * self.bls_modular_inverse(b[bpos]) % modulus
            o.insert(0, quot)
            for i in range(bpos, -1, -1):
                a[diff + i] = (a[diff + i] - int(b[i]) * quot) % modulus
            apos -= 1
            diff -= 1
        return [x % modulus for x in o]

    def shift_polynomialcoeff(self, polynomial_coeff, factor):
        """f(x) -> f(x / factor) via successive inverse powers."""
        modulus = int(self.BLS_MODULUS)
        inv_factor = self.bls_modular_inverse(factor)
        factor_power = 1
        o = []
        for p in polynomial_coeff:
            o.append(int(p) * factor_power % modulus)
            factor_power = factor_power * inv_factor % modulus
        return o

    def interpolate_polynomialcoeff(self, xs, ys):
        """Lagrange interpolation in coefficient form."""
        assert len(xs) == len(ys)
        modulus = int(self.BLS_MODULUS)
        r = [0]
        for i in range(len(xs)):
            summand = [int(ys[i])]
            for j in range(len(ys)):
                if j != i:
                    weight_adjustment = self.bls_modular_inverse(
                        (int(xs[i]) - int(xs[j])) % modulus)
                    summand = self.multiply_polynomialcoeff(
                        summand,
                        [(-weight_adjustment * int(xs[j])) % modulus,
                         weight_adjustment])
            r = self.add_polynomialcoeff(r, summand)
        return r

    def vanishing_polynomialcoeff(self, xs):
        modulus = int(self.BLS_MODULUS)
        p = [1]
        for x in xs:
            p = self.multiply_polynomialcoeff(p, [(-int(x)) % modulus, 1])
        return p

    def evaluate_polynomialcoeff(self, polynomial_coeff, z) -> int:
        modulus = int(self.BLS_MODULUS)
        y = 0
        for coef in reversed(polynomial_coeff):
            y = (y * int(z) + int(coef)) % modulus
        return y

    # -- cells (polynomial-commitments-sampling.md) --------------------------

    def coset_for_cell(self, cell_id):
        """The cell's reverse-bit-ordered coset of the 2N-th roots."""
        assert int(cell_id) < self.cells_per_blob()
        fe_per_cell = int(self.FIELD_ELEMENTS_PER_CELL)
        roots_of_unity_brp = self.bit_reversal_permutation(
            self.compute_roots_of_unity(
                2 * int(self.FIELD_ELEMENTS_PER_BLOB)))
        return roots_of_unity_brp[fe_per_cell * int(cell_id):
                                  fe_per_cell * (int(cell_id) + 1)]

    def compute_cells(self, blob):
        """Extended evaluations of the blob polynomial, cell-chunked."""
        width = int(self.FIELD_ELEMENTS_PER_BLOB)
        fe_per_cell = int(self.FIELD_ELEMENTS_PER_CELL)
        polynomial = self.blob_to_polynomial(blob)
        polynomial_coeff = self.polynomial_eval_to_coeff(polynomial)
        extended_data = self.fft_field(
            polynomial_coeff + [0] * width,
            self.compute_roots_of_unity(2 * width))
        extended_data_rbo = self.bit_reversal_permutation(extended_data)
        return [extended_data_rbo[i * fe_per_cell:(i + 1) * fe_per_cell]
                for i in range(self.cells_per_blob())]

    def compute_kzg_proof_multi_impl(self, polynomial_coeff, zs):
        """Multi-point proof [q(tau)]_1 with q = (p - I) / Z."""
        ys = [self.evaluate_polynomialcoeff(polynomial_coeff, z)
              for z in zs]
        interpolation_polynomial = self.interpolate_polynomialcoeff(zs, ys)
        polynomial_shifted = self.add_polynomialcoeff(
            polynomial_coeff,
            self.neg_polynomialcoeff(interpolation_polynomial))
        denominator_poly = self.vanishing_polynomialcoeff(zs)
        quotient_polynomial = self.divide_polynomialcoeff(
            polynomial_shifted, denominator_poly)
        setup = self.kzg_setup
        return g1_lincomb(
            setup.KZG_SETUP_G1_MONOMIAL[:len(quotient_polynomial)],
            quotient_polynomial), ys

    def compute_cells_and_proofs(self, blob):
        """All cells with one KZG multiproof per cell."""
        polynomial = self.blob_to_polynomial(blob)
        polynomial_coeff = self.polynomial_eval_to_coeff(polynomial)
        cells = []
        proofs = []
        for i in range(self.cells_per_blob()):
            coset = self.coset_for_cell(i)
            proof, ys = self.compute_kzg_proof_multi_impl(
                polynomial_coeff, coset)
            cells.append(ys)
            proofs.append(proof)
        return cells, proofs

    def verify_kzg_proof_multi_impl(self, commitment, zs, ys, proof):
        """e(proof, [Z(tau)]_2) == e(C - [I(tau)]_1, [1]_2): Z vanishes
        on zs, I interpolates ys over zs."""
        assert len(zs) == len(ys)
        setup = self.kzg_setup
        zero_poly = g2_lincomb(
            setup.KZG_SETUP_G2_MONOMIAL[:len(zs) + 1],
            self.vanishing_polynomialcoeff(zs))
        interpolated_poly = g1_lincomb(
            setup.KZG_SETUP_G1_MONOMIAL[:len(zs)],
            self.interpolate_polynomialcoeff(zs, ys))
        return bls_pairing_check([
            (bytes48_to_g1(proof), bytes96_to_g2(zero_poly)),
            (bytes48_to_g1(commitment)
             + (-bytes48_to_g1(interpolated_poly)), -G2_GENERATOR),
        ])

    def verify_cell_proof(self, commitment, cell_id, cell, proof):
        """One cell against its row commitment (one pairing check)."""
        coset = self.coset_for_cell(cell_id)
        return self.verify_kzg_proof_multi_impl(
            self.bytes_to_kzg_commitment(commitment), coset,
            self.bytes_to_cell(cell), self.bytes_to_kzg_proof(proof))

    def verify_cell_proof_batch(self, row_commitments, row_ids,
                                column_ids, cells, proofs):
        """One multiproof check per (row, column) cell.  This spec loop
        is the authoritative fallback; the DAS engine
        (consensus_specs_tpu/das) folds the whole batch into a single
        pairing check, byte-identical verdicts."""
        assert len(cells) == len(proofs) == len(row_ids) == len(column_ids)
        commitments = [
            self.bytes_to_kzg_commitment(row_commitments[int(r)])
            for r in row_ids]
        cosets = [self.coset_for_cell(c) for c in column_ids]
        cell_fields = [self.bytes_to_cell(cell) for cell in cells]
        kzg_proofs = [self.bytes_to_kzg_proof(proof) for proof in proofs]
        return all(
            self.verify_kzg_proof_multi_impl(commitment, coset, cell,
                                             proof)
            for commitment, coset, cell, proof
            in zip(commitments, cosets, cell_fields, kzg_proofs))

    # -- erasure recovery ----------------------------------------------------

    def construct_vanishing_polynomial(self, missing_cell_ids):
        """Coefficients + full-domain evaluations of the polynomial
        vanishing exactly on the missing cells' cosets."""
        num_cells = self.cells_per_blob()
        fe_per_cell = int(self.FIELD_ELEMENTS_PER_CELL)
        extended_width = 2 * int(self.FIELD_ELEMENTS_PER_BLOB)
        roots_of_unity_reduced = self.compute_roots_of_unity(num_cells)
        short_zero_poly = self.vanishing_polynomialcoeff([
            roots_of_unity_reduced[self.reverse_bits(int(mid), num_cells)]
            for mid in missing_cell_ids])
        zero_poly_coeff = [0] * extended_width
        for i, coeff in enumerate(short_zero_poly):
            zero_poly_coeff[i * fe_per_cell] = coeff
        zero_poly_eval = self.fft_field(
            zero_poly_coeff, self.compute_roots_of_unity(extended_width))
        zero_poly_eval_brp = self.bit_reversal_permutation(zero_poly_eval)
        for cell_id in range(num_cells):
            start = cell_id * fe_per_cell
            end = (cell_id + 1) * fe_per_cell
            if cell_id in missing_cell_ids:
                assert all(a == 0 for a in zero_poly_eval_brp[start:end])
            else:
                assert all(a != 0 for a in zero_poly_eval_brp[start:end])
        return zero_poly_coeff, zero_poly_eval

    def recover_polynomial(self, cell_ids, cells_bytes):
        """Recover the full extended evaluations from any >= 50% of the
        cells (vanishing-polynomial method over a shifted coset).
        Duplicate ids and an insufficient cell count fail loudly."""
        assert len(cell_ids) == len(cells_bytes)
        num_cells = self.cells_per_blob()
        assert len(set(int(c) for c in cell_ids)) == len(cell_ids)
        assert all(int(c) < num_cells for c in cell_ids)
        assert 2 * len(cell_ids) >= num_cells
        fe_per_cell = int(self.FIELD_ELEMENTS_PER_CELL)
        extended_width = 2 * int(self.FIELD_ELEMENTS_PER_BLOB)
        modulus = int(self.BLS_MODULUS)
        roots_of_unity_extended = self.compute_roots_of_unity(
            extended_width)
        cells = [self.bytes_to_cell(cb) for cb in cells_bytes]
        received = [int(c) for c in cell_ids]
        missing_cell_ids = [cid for cid in range(num_cells)
                            if cid not in received]
        zero_poly_coeff, zero_poly_eval = \
            self.construct_vanishing_polynomial(missing_cell_ids)
        extended_evaluation_rbo = [0] * extended_width
        for cell_id, cell in zip(received, cells):
            start = cell_id * fe_per_cell
            extended_evaluation_rbo[start:start + fe_per_cell] = cell
        extended_evaluation = self.bit_reversal_permutation(
            extended_evaluation_rbo)
        extended_evaluation_times_zero = [
            int(a) * int(b) % modulus
            for a, b in zip(zero_poly_eval, extended_evaluation)]
        extended_evaluations_fft = self.fft_field(
            extended_evaluation_times_zero, roots_of_unity_extended,
            inv=True)
        shift_factor = int(self.PRIMITIVE_ROOT_OF_UNITY)
        shift_inv = self.bls_modular_inverse(shift_factor)
        shifted_extended_evaluation = self.shift_polynomialcoeff(
            extended_evaluations_fft, shift_factor)
        shifted_zero_poly = self.shift_polynomialcoeff(
            zero_poly_coeff, shift_factor)
        eval_shifted_extended_evaluation = self.fft_field(
            shifted_extended_evaluation, roots_of_unity_extended)
        eval_shifted_zero_poly = self.fft_field(
            shifted_zero_poly, roots_of_unity_extended)
        eval_shifted_reconstructed_poly = [
            int(a) * self.bls_modular_inverse(b) % modulus
            for a, b in zip(eval_shifted_extended_evaluation,
                            eval_shifted_zero_poly)]
        shifted_reconstructed_poly = self.fft_field(
            eval_shifted_reconstructed_poly, roots_of_unity_extended,
            inv=True)
        reconstructed_poly = self.shift_polynomialcoeff(
            shifted_reconstructed_poly, shift_inv)
        reconstructed_data = self.bit_reversal_permutation(
            self.fft_field(reconstructed_poly, roots_of_unity_extended))
        for cell_id, cell in zip(received, cells):
            start = cell_id * fe_per_cell
            assert reconstructed_data[start:start + fe_per_cell] == cell
        return reconstructed_data

    def recover_cells_and_kzg_proofs(self, cell_ids, cells_bytes):
        """Recover every cell AND recompute every cell's multiproof."""
        reconstructed_data = self.recover_polynomial(cell_ids, cells_bytes)
        fe_per_cell = int(self.FIELD_ELEMENTS_PER_CELL)
        width = int(self.FIELD_ELEMENTS_PER_BLOB)
        recovered_cells = [
            reconstructed_data[i * fe_per_cell:(i + 1) * fe_per_cell]
            for i in range(self.cells_per_blob())]
        coeffs = self.fft_field(
            self.bit_reversal_permutation(reconstructed_data),
            self.compute_roots_of_unity(2 * width), inv=True)
        assert all(c == 0 for c in coeffs[width:])
        polynomial_coeff = coeffs[:width]
        recovered_proofs = []
        for i in range(self.cells_per_blob()):
            proof, ys = self.compute_kzg_proof_multi_impl(
                polynomial_coeff, self.coset_for_cell(i))
            assert ys == recovered_cells[i]
            recovered_proofs.append(proof)
        return recovered_cells, recovered_proofs

    # -- custody + sidecars (das-core.md) ------------------------------------

    def get_custody_columns(self, node_id, custody_subnet_count):
        """Deterministic custody assignment: hash-walk from node_id to
        custody_subnet_count distinct subnets, each subnet owning every
        DATA_COLUMN_SIDECAR_SUBNET_COUNT-th column."""
        assert int(custody_subnet_count) <= int(
            self.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
        subnet_count = int(self.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
        subnet_ids = []
        current_id = int(node_id)
        while len(subnet_ids) < int(custody_subnet_count):
            digest = hash(int(current_id).to_bytes(32, "little"))
            subnet_id = int.from_bytes(digest[0:8], "little") % subnet_count
            if subnet_id not in subnet_ids:
                subnet_ids.append(subnet_id)
            current_id = (current_id + 1) % 2**256
        columns_per_subnet = int(self.NUMBER_OF_COLUMNS) // subnet_count
        return sorted([
            ColumnIndex(subnet_count * i + subnet_id)
            for i in range(columns_per_subnet)
            for subnet_id in subnet_ids])

    def get_data_column_sidecars(self, signed_block, cells_and_proofs):
        """One DataColumnSidecar per column from a signed block's blob
        cells and proofs ([(cells, proofs)] in commitment order)."""
        block = signed_block.message
        blob_kzg_commitments = block.body.blob_kzg_commitments
        assert len(cells_and_proofs) == len(blob_kzg_commitments)
        signed_block_header = self.SignedBeaconBlockHeader(
            message=self.BeaconBlockHeader(
                slot=block.slot,
                proposer_index=block.proposer_index,
                parent_root=block.parent_root,
                state_root=block.state_root,
                body_root=hash_tree_root(block.body)),
            signature=signed_block.signature)
        sidecars = []
        for column_index in range(int(self.NUMBER_OF_COLUMNS)):
            column_cells = [cells[column_index]
                            for cells, _ in cells_and_proofs]
            column_proofs = [proofs[column_index]
                             for _, proofs in cells_and_proofs]
            sidecars.append(self.DataColumnSidecar(
                index=column_index,
                column=[self.Cell(self.cell_to_bytes(cell))
                        for cell in column_cells],
                kzg_commitments=[KZGCommitment(bytes(c))
                                 for c in blob_kzg_commitments],
                kzg_proofs=[KZGProof(bytes(proof))
                            for proof in column_proofs],
                signed_block_header=signed_block_header))
        return sidecars

    def verify_data_column_sidecar(self, sidecar) -> bool:
        """Structural validity: index in range, non-empty column,
        aligned cell/commitment/proof counts."""
        if int(sidecar.index) >= int(self.NUMBER_OF_COLUMNS):
            return False
        if len(sidecar.column) == 0:
            return False
        if not (len(sidecar.column) == len(sidecar.kzg_commitments)
                == len(sidecar.kzg_proofs)):
            return False
        return True

    def verify_data_column_sidecar_kzg_proofs(self, sidecar) -> bool:
        """Every cell of the column verifies against its row
        commitment (engine: the whole column is one pairing)."""
        assert self.verify_data_column_sidecar(sidecar)
        return self.verify_cell_proof_batch(
            [bytes(c) for c in sidecar.kzg_commitments],
            list(range(len(sidecar.column))),
            [int(sidecar.index)] * len(sidecar.column),
            [bytes(cell) for cell in sidecar.column],
            [bytes(proof) for proof in sidecar.kzg_proofs])

    # -- availability via sampling (replaces deneb full-blob checking) -----

    def is_data_available(self, beacon_block_root, blob_kzg_commitments):
        """Sampling-based availability: verify the retrieved cells of
        each committed blob against their multiproofs.

        ``retrieve_blobs_and_proofs`` remains the retrieval stub the
        harness monkeypatches (deneb fork-choice.md:70 pattern); a cell
        retrieval stub (``retrieve_cells_and_proofs``) takes precedence
        when the harness provides one.
        """
        retrieve = getattr(self, "retrieve_cells_and_proofs", None)
        if retrieve is None:
            # fall back to deneb full-blob verification
            return super().is_data_available(beacon_block_root,
                                             blob_kzg_commitments)
        sampled = retrieve(beacon_block_root)
        # every committed blob must have been sampled: a short return
        # means data was withheld, never availability
        if len(sampled) < len(blob_kzg_commitments):
            return False
        for commitment, (cell_ids, cells, proofs) in zip(
                blob_kzg_commitments, sampled):
            if not self.verify_cell_proof_batch(
                    [commitment], [0] * len(cell_ids), cell_ids,
                    cells, proofs):
                return False
        return True

    # -- fork ladder / upgrade (fork.md) -----------------------------------

    def compute_fork_version(self, epoch):
        cfg = self.config
        e7594 = getattr(cfg, "EIP7594_FORK_EPOCH", None)
        if e7594 is not None and epoch >= e7594:
            return cfg.EIP7594_FORK_VERSION
        return super().compute_fork_version(epoch)

    def upgrade_to_eip7594(self, pre):
        """State upgrade at EIP7594_FORK_EPOCH: identical layout, new
        fork version (fork.md:70 - 7594 'does not need a hard fork'
        beyond the version rotation)."""
        post = self.BeaconState.decode_bytes(pre.serialize())
        post.fork = self.Fork(
            previous_version=pre.fork.current_version,
            current_version=self.config.EIP7594_FORK_VERSION,
            epoch=self.get_current_epoch(pre),
        )
        return post
