"""EIP-7594 (PeerDAS) feature fork: data-availability sampling.

Behavioral source: ``specs/_features/eip7594/fork.md`` (fork version
ladder :40-56, ``upgrade_to_eip7594`` :70-125) and
``specs/_features/eip7594/polynomial-commitments-sampling.md`` — the
sampling math itself (cells, multiproofs, erasure recovery) lives in
``consensus_specs_tpu/ops/kzg_7594.py`` and is differential-tested by
``tests/test_kzg_7594*``.  Fork DAG parent: deneb.

The state layout is UNCHANGED from deneb (7594 is a data-availability
fork, not a state fork): the upgrade only rotates ``state.fork``.  What
changes is how availability is established — ``is_data_available``
samples extended-blob cells instead of downloading full blobs, so a
node custodies/examines only a fraction of each blob column.
"""
from consensus_specs_tpu.utils.ssz import hash_tree_root  # noqa: F401 (compiled-spec namespace)
from . import register_fork
from .deneb import DenebSpec
from consensus_specs_tpu.ops import kzg_7594 as K7


@register_fork("eip7594")
class EIP7594Spec(DenebSpec):
    fork = "eip7594"
    previous_fork = "deneb"

    # polynomial-commitments-sampling.md: cells per extended blob
    FIELD_ELEMENTS_PER_CELL = K7.FIELD_ELEMENTS_PER_CELL

    # -- sampling surface (polynomial-commitments-sampling.md) -------------

    def compute_cells(self, blob):
        return K7.compute_cells(bytes(blob), self.kzg_setup)

    def compute_cells_and_proofs(self, blob):
        return K7.compute_cells_and_proofs(bytes(blob), self.kzg_setup)

    def verify_cell_proof(self, commitment, cell_id, cell, proof):
        return K7.verify_cell_proof(bytes(commitment), int(cell_id),
                                    bytes(cell), bytes(proof),
                                    self.kzg_setup)

    def verify_cell_proof_batch(self, row_commitments, row_ids, column_ids,
                                cells, proofs):
        return K7.verify_cell_proof_batch(
            [bytes(c) for c in row_commitments],
            [int(r) for r in row_ids], [int(c) for c in column_ids],
            [bytes(c) for c in cells], [bytes(p) for p in proofs],
            self.kzg_setup)

    def recover_polynomial(self, cell_ids, cells_bytes):
        return K7.recover_polynomial([int(c) for c in cell_ids],
                                     [bytes(c) for c in cells_bytes],
                                     self.kzg_setup)

    # -- availability via sampling (replaces deneb full-blob checking) -----

    def is_data_available(self, beacon_block_root, blob_kzg_commitments):
        """Sampling-based availability: verify the retrieved cells of
        each committed blob against their multiproofs.

        ``retrieve_blobs_and_proofs`` remains the retrieval stub the
        harness monkeypatches (deneb fork-choice.md:70 pattern); a cell
        retrieval stub (``retrieve_cells_and_proofs``) takes precedence
        when the harness provides one.
        """
        retrieve = getattr(self, "retrieve_cells_and_proofs", None)
        if retrieve is None:
            # fall back to deneb full-blob verification
            return super().is_data_available(beacon_block_root,
                                             blob_kzg_commitments)
        sampled = retrieve(beacon_block_root)
        # every committed blob must have been sampled: a short return
        # means data was withheld, never availability
        if len(sampled) < len(blob_kzg_commitments):
            return False
        for commitment, (cell_ids, cells, proofs) in zip(
                blob_kzg_commitments, sampled):
            if not self.verify_cell_proof_batch(
                    [commitment], [0] * len(cell_ids), cell_ids,
                    cells, proofs):
                return False
        return True

    # -- fork ladder / upgrade (fork.md) -----------------------------------

    def compute_fork_version(self, epoch):
        cfg = self.config
        e7594 = getattr(cfg, "EIP7594_FORK_EPOCH", None)
        if e7594 is not None and epoch >= e7594:
            return cfg.EIP7594_FORK_VERSION
        return super().compute_fork_version(epoch)

    def upgrade_to_eip7594(self, pre):
        """State upgrade at EIP7594_FORK_EPOCH: identical layout, new
        fork version (fork.md:70 - 7594 'does not need a hard fork'
        beyond the version rotation)."""
        post = self.BeaconState.decode_bytes(pre.serialize())
        post.fork = self.Fork(
            previous_version=pre.fork.current_version,
            current_version=self.config.EIP7594_FORK_VERSION,
            epoch=self.get_current_epoch(pre),
        )
        return post
