"""EIP-6110 feature fork: in-protocol deposit receipts.

Behavioral source: ``specs/_features/eip6110/beacon-chain.md``
(``DepositReceipt`` :63, extended payload :76-118, modified
``process_operations`` :194, ``process_deposit_receipt`` :221) and
``specs/_features/eip6110/fork.md``.  Fork DAG parent: deneb
(``pysetup/md_doc_paths.py:22``).
"""
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, Bytes32, List, Container,
)
from . import register_fork
from .deneb import DenebSpec
from .base_types import Gwei, BLSPubkey, BLSSignature

UNSET_DEPOSIT_RECEIPTS_START_INDEX = uint64(2**64 - 1)


@register_fork("eip6110")
class EIP6110Spec(DenebSpec):
    fork = "eip6110"
    previous_fork = "deneb"

    UNSET_DEPOSIT_RECEIPTS_START_INDEX = UNSET_DEPOSIT_RECEIPTS_START_INDEX

    def _build_types(self):
        class DepositReceipt(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei
            signature: BLSSignature
            index: uint64

        self.DepositReceipt = DepositReceipt
        super()._build_types()

    def _execution_payload_fields(self) -> dict:
        fields = super()._execution_payload_fields()
        fields["deposit_receipts"] = List[
            self.DepositReceipt, self.MAX_DEPOSIT_RECEIPTS_PER_PAYLOAD]
        return fields

    def _execution_payload_header_fields(self) -> dict:
        fields = super()._execution_payload_header_fields()
        fields["deposit_receipts_root"] = Bytes32
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields["deposit_receipts_start_index"] = uint64
        return fields

    def _payload_to_header(self, payload):
        header = super()._payload_to_header(payload)
        header.deposit_receipts_root = hash_tree_root(
            payload.deposit_receipts)
        return header

    def process_operations(self, state, body):
        """beacon-chain.md:194 — former deposit channel winds down once
        the receipts flow starts; receipts processed from the payload."""
        eth1_deposit_index_limit = min(state.eth1_data.deposit_count,
                                       state.deposit_receipts_start_index)
        if state.eth1_deposit_index < eth1_deposit_index_limit:
            assert len(body.deposits) == min(
                self.MAX_DEPOSITS,
                eth1_deposit_index_limit - state.eth1_deposit_index)
        else:
            assert len(body.deposits) == 0

        for operation in body.proposer_slashings:
            self.process_proposer_slashing(state, operation)
        for operation in body.attester_slashings:
            self.process_attester_slashing(state, operation)
        for operation in body.attestations:
            self.process_attestation(state, operation)
        for operation in body.deposits:
            self.process_deposit(state, operation)
        for operation in body.voluntary_exits:
            self.process_voluntary_exit(state, operation)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)
        # [New in EIP6110]
        for operation in body.execution_payload.deposit_receipts:
            self.process_deposit_receipt(state, operation)

    def process_deposit_receipt(self, state, deposit_receipt):
        """beacon-chain.md:221"""
        if state.deposit_receipts_start_index == \
                UNSET_DEPOSIT_RECEIPTS_START_INDEX:
            state.deposit_receipts_start_index = deposit_receipt.index
        self.apply_deposit(
            state=state,
            pubkey=deposit_receipt.pubkey,
            withdrawal_credentials=deposit_receipt.withdrawal_credentials,
            amount=deposit_receipt.amount,
            signature=deposit_receipt.signature,
        )

    def post_mock_genesis(self, state):
        super().post_mock_genesis(state)
        state.deposit_receipts_start_index = \
            UNSET_DEPOSIT_RECEIPTS_START_INDEX

    def upgrade_to_eip6110(self, pre):
        """fork.md — deneb state + unset receipts start index."""
        post = self.BeaconState(
            **{f: getattr(pre, f) for f in type(pre).fields()
               if f not in ("fork", "latest_execution_payload_header")},
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.EIP6110_FORK_VERSION,
                epoch=self.get_current_epoch(pre),
            ),
            latest_execution_payload_header=self._translate_header(
                pre.latest_execution_payload_header),
            deposit_receipts_start_index=UNSET_DEPOSIT_RECEIPTS_START_INDEX,
        )
        return post

    def _translate_header(self, pre_header):
        fields = {f: getattr(pre_header, f)
                  for f in type(pre_header).fields()}
        return self.ExecutionPayloadHeader(**fields)
