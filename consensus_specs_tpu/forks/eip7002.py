"""EIP-7002 feature fork: execution-layer triggerable exits.

Behavioral source: ``specs/_features/eip7002/beacon-chain.md``
(``ExecutionLayerExit`` :54, extended payload :61-118, modified
``process_operations`` :200, ``process_execution_layer_exit`` :223).
Fork DAG parent: capella (``pysetup/md_doc_paths.py:24``).
"""
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, Bytes32, List, Container,
)
from . import register_fork
from .capella import CapellaSpec
from .base_types import (
    ValidatorIndex, ExecutionAddress, BLSPubkey,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
)


@register_fork("eip7002")
class EIP7002Spec(CapellaSpec):
    fork = "eip7002"
    previous_fork = "capella"

    # preset (beacon-chain.md:45)
    MAX_EXECUTION_LAYER_EXITS = 2**4

    def _build_types(self):
        class ExecutionLayerExit(Container):
            source_address: ExecutionAddress
            validator_pubkey: BLSPubkey

        self.ExecutionLayerExit = ExecutionLayerExit
        super()._build_types()

    def _execution_payload_fields(self) -> dict:
        fields = super()._execution_payload_fields()
        fields["exits"] = List[self.ExecutionLayerExit,
                               self.MAX_EXECUTION_LAYER_EXITS]
        return fields

    def _execution_payload_header_fields(self) -> dict:
        fields = super()._execution_payload_header_fields()
        fields["exits_root"] = Bytes32
        return fields

    def _payload_to_header(self, payload):
        header = super()._payload_to_header(payload)
        header.exits_root = hash_tree_root(payload.exits)
        return header

    def process_operations(self, state, body):
        """beacon-chain.md:200 — adds payload-carried exits."""
        super().process_operations(state, body)
        for operation in body.execution_payload.exits:
            self.process_execution_layer_exit(state, operation)

    def process_execution_layer_exit(self, state, execution_layer_exit):
        """beacon-chain.md:223 — credential/activation mismatches no-op;
        an unknown pubkey raises (ValueError = invalid block), exactly as
        the reference's list.index does."""
        validator_pubkeys = [v.pubkey for v in state.validators]
        validator_index = ValidatorIndex(validator_pubkeys.index(
            execution_layer_exit.validator_pubkey))
        validator = state.validators[validator_index]

        is_execution_address = bytes(
            validator.withdrawal_credentials[:1]) == \
            ETH1_ADDRESS_WITHDRAWAL_PREFIX
        is_correct_source_address = bytes(
            validator.withdrawal_credentials[12:]) == \
            bytes(execution_layer_exit.source_address)
        if not (is_execution_address and is_correct_source_address):
            return
        if not self.is_active_validator(validator,
                                        self.get_current_epoch(state)):
            return
        if validator.exit_epoch != self.FAR_FUTURE_EPOCH:
            return
        if self.get_current_epoch(state) < validator.activation_epoch \
                + self.config.SHARD_COMMITTEE_PERIOD:
            return
        self.initiate_validator_exit(state, validator_index)
