"""Optimistic sync (bellatrix+).

Behavioral source: ``sync/optimistic.md`` (compiled into bellatrix+ by the
reference, ``pysetup/md_doc_paths.py:34-36``): the OptimisticStore, the
optimistic/verified block distinction, and the candidate-import rule that
lets nodes import execution blocks before the execution engine has
validated them.
"""
from dataclasses import dataclass, field
from typing import Dict, Set

from consensus_specs_tpu.utils.ssz import hash_tree_root

SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = 128  # preset (optimistic.md:55)


@dataclass
class OptimisticStore:
    """optimistic.md:87"""
    optimistic_roots: Set[bytes]
    head_block_root: bytes
    blocks: Dict[bytes, object] = field(default_factory=dict)
    block_states: Dict[bytes, object] = field(default_factory=dict)


class OptimisticSyncMixin:
    """Mixed into bellatrix+ spec classes."""

    OptimisticStore = OptimisticStore
    SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY

    def get_optimistic_store(self, anchor_state, anchor_block):
        # anchor must be self-consistent (optimistic.md store init)
        assert bytes(anchor_block.state_root) == hash_tree_root(anchor_state)
        root = hash_tree_root(anchor_block)
        return OptimisticStore(
            optimistic_roots=set(),
            head_block_root=bytes(root),
            blocks={bytes(root): anchor_block.copy()},
            block_states={bytes(root): anchor_state.copy()},
        )

    def is_optimistic(self, opt_store, block) -> bool:
        """optimistic.md:96"""
        return bytes(hash_tree_root(block)) in opt_store.optimistic_roots

    def latest_verified_ancestor(self, opt_store, block):
        """optimistic.md:101 — ``block`` must not be INVALIDATED."""
        while True:
            if not self.is_optimistic(opt_store, block) \
                    or bytes(block.parent_root) == b"\x00" * 32:
                return block
            block = opt_store.blocks[bytes(block.parent_root)]

    def is_execution_block(self, block) -> bool:
        """optimistic.md:110"""
        return block.body.execution_payload != self.ExecutionPayload()

    def is_optimistic_candidate_block(self, opt_store, current_slot,
                                      block) -> bool:
        """optimistic.md:115 — import optimistically once the parent is an
        execution block or the block is old enough."""
        if self.is_execution_block(opt_store.blocks[bytes(block.parent_root)]):
            return True
        if block.slot + self.SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY \
                <= current_slot:
            return True
        return False

    def import_optimistic_block(self, opt_store, block) -> None:
        """Import a block whose execution payload has NOT been validated
        (optimistic.md "when importing an optimistic block").  The parent
        must already be in the store and not INVALIDATED."""
        root = bytes(hash_tree_root(block))
        assert bytes(block.parent_root) in opt_store.blocks
        opt_store.blocks[root] = block.copy()
        opt_store.optimistic_roots.add(root)

    def on_payload_status(self, opt_store, block_root: bytes,
                          valid: bool) -> None:
        """Execution-engine verdict for an optimistically-imported block
        (optimistic.md "how to apply" transitions):

        - VALID: the block and every optimistic ancestor become verified
          (a payload is only valid if its ancestors are).
        - INVALIDATED: the block and all its descendants are removed from
          the store entirely — they can never become canonical.
        """
        block_root = bytes(block_root)
        assert block_root in opt_store.blocks
        if valid:
            block = opt_store.blocks[block_root]
            while True:
                opt_store.optimistic_roots.discard(
                    bytes(hash_tree_root(block)))
                parent = bytes(block.parent_root)
                if parent not in opt_store.blocks:
                    break
                parent_block = opt_store.blocks[parent]
                if not self.is_optimistic(opt_store, parent_block):
                    break
                block = parent_block
            return
        # INVALIDATED: only not-yet-validated blocks can transition
        # (a verified block's payload verdict is final)
        assert block_root in opt_store.optimistic_roots
        # drop the subtree rooted at block_root
        doomed = {block_root}
        changed = True
        while changed:
            changed = False
            for root, blk in list(opt_store.blocks.items()):
                if root in doomed:
                    continue
                if bytes(blk.parent_root) in doomed:
                    doomed.add(root)
                    changed = True
        for root in doomed:
            opt_store.blocks.pop(root, None)
            opt_store.block_states.pop(root, None)
            opt_store.optimistic_roots.discard(root)
