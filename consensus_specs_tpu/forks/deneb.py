"""Deneb fork: blobs (EIP-4844), KZG commitments, EIP-7044/7045/7514.

Behavioral sources: ``specs/deneb/beacon-chain.md``
(``blob_kzg_commitments`` :118, ``kzg_commitment_to_versioned_hash`` :176,
modified ``get_attestation_participation_flag_indices`` :186,
``get_validator_activation_churn_limit`` :220, modified
``process_attestation`` :317, modified ``process_execution_payload`` :359,
modified ``process_voluntary_exit`` :411, modified
``process_registry_updates`` :438), ``specs/deneb/fork.md``
(``upgrade_to_deneb`` :77), ``specs/deneb/fork-choice.md``
(``is_data_available`` :53, modified ``on_block`` :70) and the KZG library
``specs/deneb/polynomial-commitments.md`` via :mod:`consensus_specs_tpu.ops.kzg`.
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, Bytes32, Bytes48, ByteVector, Vector, List,
    Container,
)  # noqa: F401 (compiled-spec namespace)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.ops import kzg as _kzg
from consensus_specs_tpu.ops import epoch_kernels
from . import register_fork
from .capella import CapellaSpec
from .base_types import (
    Epoch, Gwei, ValidatorIndex, Root, KZGCommitment, KZGProof,
    DOMAIN_VOLUNTARY_EXIT,
)

VERSIONED_HASH_VERSION_KZG = b"\x01"
MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT = uint64(2**3)
VersionedHash = Bytes32
BlobIndex = uint64


@register_fork("deneb")
class DenebSpec(CapellaSpec):
    fork = "deneb"
    previous_fork = "capella"

    VERSIONED_HASH_VERSION_KZG = VERSIONED_HASH_VERSION_KZG
    MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT = MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT
    VersionedHash = VersionedHash
    BlobIndex = BlobIndex
    KZGCommitment = KZGCommitment
    KZGProof = KZGProof
    BLS_MODULUS = _kzg.BLS_MODULUS
    BYTES_PER_FIELD_ELEMENT = _kzg.BYTES_PER_FIELD_ELEMENT
    G1_POINT_AT_INFINITY = _kzg.G1_POINT_AT_INFINITY

    # -- type construction ---------------------------------------------------

    def _build_types(self):
        S = self
        self.BYTES_PER_BLOB = _kzg.BYTES_PER_FIELD_ELEMENT \
            * S.FIELD_ELEMENTS_PER_BLOB
        self.Blob = ByteVector[self.BYTES_PER_BLOB]
        super()._build_types()

        class BlobSidecar(Container):
            index: BlobIndex
            blob: S.Blob
            kzg_commitment: KZGCommitment
            kzg_proof: KZGProof
            signed_block_header: S.SignedBeaconBlockHeader
            kzg_commitment_inclusion_proof: Vector[
                Bytes32, S.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH]

        class BlobIdentifier(Container):
            block_root: Root
            index: BlobIndex

        self.BlobSidecar = BlobSidecar
        self.BlobIdentifier = BlobIdentifier

    def _execution_payload_fields(self) -> dict:
        fields = super()._execution_payload_fields()
        fields["blob_gas_used"] = uint64
        fields["excess_blob_gas"] = uint64
        return fields

    def _execution_payload_header_fields(self) -> dict:
        fields = super()._execution_payload_header_fields()
        fields["blob_gas_used"] = uint64
        fields["excess_blob_gas"] = uint64
        return fields

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        fields["blob_kzg_commitments"] = List[
            KZGCommitment, self.MAX_BLOB_COMMITMENTS_PER_BLOCK]
        return fields

    def _new_payload_request_fields(self):
        return ("execution_payload", "versioned_hashes",
                "parent_beacon_block_root")

    def _build_engine(self):
        super()._build_engine()
        spec = self
        from dataclasses import dataclass

        @dataclass
        class NewPayloadRequest:
            """beacon-chain.md:236 (adds versioned hashes + parent root)."""
            execution_payload: object = None
            versioned_hashes: tuple = ()
            parent_beacon_block_root: bytes = b"\x00" * 32

        self.NewPayloadRequest = NewPayloadRequest

    # -- KZG library (polynomial-commitments.md), preset-bound ----------------

    @property
    def kzg_setup(self):
        return _kzg.trusted_setup(self.preset_name)

    def blob_to_kzg_commitment(self, blob) -> bytes:
        return KZGCommitment(_kzg.blob_to_kzg_commitment(
            bytes(blob), self.kzg_setup))

    def compute_kzg_proof(self, blob, z_bytes):
        proof, y = _kzg.compute_kzg_proof(bytes(blob), bytes(z_bytes),
                                          self.kzg_setup)
        return KZGProof(proof), Bytes32(y)

    def compute_blob_kzg_proof(self, blob, commitment_bytes) -> bytes:
        return KZGProof(_kzg.compute_blob_kzg_proof(
            bytes(blob), bytes(commitment_bytes), self.kzg_setup))

    def verify_kzg_proof(self, commitment_bytes, z_bytes, y_bytes,
                         proof_bytes) -> bool:
        return _kzg.verify_kzg_proof(bytes(commitment_bytes), bytes(z_bytes),
                                     bytes(y_bytes), bytes(proof_bytes),
                                     self.kzg_setup)

    def verify_blob_kzg_proof(self, blob, commitment_bytes,
                              proof_bytes) -> bool:
        return _kzg.verify_blob_kzg_proof(bytes(blob), bytes(commitment_bytes),
                                          bytes(proof_bytes), self.kzg_setup)

    def verify_blob_kzg_proof_batch(self, blobs, commitments, proofs) -> bool:
        return _kzg.verify_blob_kzg_proof_batch(
            [bytes(b) for b in blobs], [bytes(c) for c in commitments],
            [bytes(p) for p in proofs], self.kzg_setup)

    # -- misc (beacon-chain.md:176) -------------------------------------------

    def kzg_commitment_to_versioned_hash(self, kzg_commitment) -> bytes:
        return VersionedHash(
            VERSIONED_HASH_VERSION_KZG + hash(kzg_commitment)[1:])

    # -- modified accessors ---------------------------------------------------

    def get_attestation_participation_flag_indices(self, state, data,
                                                   inclusion_delay):
        """EIP-7045: target flag no longer bounded by inclusion delay
        (beacon-chain.md:186)."""
        from .altair import (TIMELY_SOURCE_FLAG_INDEX,
                             TIMELY_TARGET_FLAG_INDEX,
                             TIMELY_HEAD_FLAG_INDEX)
        if data.target.epoch == self.get_current_epoch(state):
            justified_checkpoint = state.current_justified_checkpoint
        else:
            justified_checkpoint = state.previous_justified_checkpoint
        is_matching_source = data.source == justified_checkpoint
        is_matching_target = is_matching_source and bytes(data.target.root) \
            == bytes(self.get_block_root(state, data.target.epoch))
        is_matching_head = is_matching_target and \
            bytes(data.beacon_block_root) == \
            bytes(self.get_block_root_at_slot(state, data.slot))
        assert is_matching_source

        participation_flag_indices = []
        if is_matching_source and inclusion_delay <= \
                self.integer_squareroot(self.SLOTS_PER_EPOCH):
            participation_flag_indices.append(TIMELY_SOURCE_FLAG_INDEX)
        if is_matching_target:  # [Modified in Deneb:EIP7045]
            participation_flag_indices.append(TIMELY_TARGET_FLAG_INDEX)
        if is_matching_head and inclusion_delay == \
                self.MIN_ATTESTATION_INCLUSION_DELAY:
            participation_flag_indices.append(TIMELY_HEAD_FLAG_INDEX)
        return participation_flag_indices

    def get_validator_activation_churn_limit(self, state) -> uint64:
        """EIP-7514 (beacon-chain.md:220)."""
        return min(MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT,
                   self.get_validator_churn_limit(state))

    # -- block processing -----------------------------------------------------

    def process_attestation(self, state, attestation):
        """EIP-7045: inclusion window extended to any later slot
        (beacon-chain.md:317)."""
        from .altair import PARTICIPATION_FLAG_WEIGHTS, PROPOSER_WEIGHT, \
            WEIGHT_DENOMINATOR
        data = attestation.data
        assert data.target.epoch in (self.get_previous_epoch(state),
                                     self.get_current_epoch(state))
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot)
        # [Modified in Deneb:EIP7045] no upper bound on inclusion delay
        assert data.slot + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
        assert data.index < self.get_committee_count_per_slot(
            state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee)

        participation_flag_indices = \
            self.get_attestation_participation_flag_indices(
                state, data, state.slot - data.slot)

        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))

        if data.target.epoch == self.get_current_epoch(state):
            epoch_participation = state.current_epoch_participation
        else:
            epoch_participation = state.previous_epoch_participation

        proposer_reward_numerator = 0
        for index in self.get_attesting_indices(
                state, data, attestation.aggregation_bits):
            for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
                if flag_index in participation_flag_indices and \
                        not self.has_flag(epoch_participation[index],
                                          flag_index):
                    epoch_participation[index] = self.add_flag(
                        epoch_participation[index], flag_index)
                    proposer_reward_numerator += \
                        self.get_base_reward(state, index) * weight

        proposer_reward_denominator = ((WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                                       * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT)
        proposer_reward = Gwei(proposer_reward_numerator
                               // proposer_reward_denominator)
        self.increase_balance(state, self.get_beacon_proposer_index(state),
                              proposer_reward)

    def process_execution_payload(self, state, body, execution_engine):
        """beacon-chain.md:359 — blob count cap + versioned hashes."""
        payload = body.execution_payload
        assert payload.parent_hash == \
            state.latest_execution_payload_header.block_hash
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        # [New in Deneb:EIP4844] Verify commitments are under limit
        assert len(body.blob_kzg_commitments) <= self.MAX_BLOBS_PER_BLOCK
        # [Modified in Deneb:EIP4844] pass versioned hashes + parent root
        versioned_hashes = [self.kzg_commitment_to_versioned_hash(c)
                            for c in body.blob_kzg_commitments]
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(
                execution_payload=payload,
                versioned_hashes=versioned_hashes,
                parent_beacon_block_root=state.latest_block_header.parent_root,
            ))
        state.latest_execution_payload_header = self._payload_to_header(payload)

    def _payload_to_header(self, payload):
        header = super()._payload_to_header(payload)
        header.blob_gas_used = payload.blob_gas_used
        header.excess_blob_gas = payload.excess_blob_gas
        return header

    def process_voluntary_exit(self, state, signed_voluntary_exit):
        """EIP-7044: pinned to the capella fork domain (beacon-chain.md:411)."""
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[voluntary_exit.validator_index]
        assert self.is_active_validator(validator,
                                        self.get_current_epoch(state))
        assert validator.exit_epoch == self.FAR_FUTURE_EPOCH
        assert self.get_current_epoch(state) >= voluntary_exit.epoch
        assert self.get_current_epoch(state) >= validator.activation_epoch \
            + self.config.SHARD_COMMITTEE_PERIOD
        # [Modified in Deneb:EIP7044]
        domain = self.compute_domain(DOMAIN_VOLUNTARY_EXIT,
                                     self.config.CAPELLA_FORK_VERSION,
                                     state.genesis_validators_root)
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert bls.Verify(validator.pubkey, signing_root,
                          signed_voluntary_exit.signature)
        self.initiate_validator_exit(state, voluntary_exit.validator_index)

    # -- epoch processing ------------------------------------------------------

    def process_registry_updates(self, state):
        """EIP-7514: activations capped by the activation churn limit
        (beacon-chain.md:438)."""
        if epoch_kernels.try_process_registry_updates(self, state):
            return
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = Epoch(
                    self.get_current_epoch(state) + 1)
            if (self.is_active_validator(validator,
                                         self.get_current_epoch(state))
                    and validator.effective_balance
                    <= self.config.EJECTION_BALANCE):
                self.initiate_validator_exit(state, ValidatorIndex(index))
        activation_queue = sorted([
            index for index, validator in enumerate(state.validators)
            if self.is_eligible_for_activation(state, validator)
        ], key=lambda index: (
            state.validators[index].activation_eligibility_epoch, index))
        # [Modified in Deneb:EIP7514]
        for index in activation_queue[
                :self.get_validator_activation_churn_limit(state)]:
            validator = state.validators[index]
            validator.activation_epoch = self.compute_activation_exit_epoch(
                self.get_current_epoch(state))

    # -- light client (specs/deneb/light-client/sync-protocol.md) -------------

    def is_valid_light_client_header(self, header) -> bool:
        """Deneb variant: blob-gas fields must be zero before the fork."""
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.DENEB_FORK_EPOCH:
            if header.execution.blob_gas_used != 0 \
                    or header.execution.excess_blob_gas != 0:
                return False
        return super().is_valid_light_client_header(header)

    # -- data availability (fork-choice.md:53) ---------------------------------

    def retrieve_blobs_and_proofs(self, beacon_block_root):
        """Test stub (``pysetup/spec_builders/deneb.py:24-28``); fork-choice
        blob tests swap this out."""
        return [], []

    def is_data_available(self, beacon_block_root, blob_kzg_commitments) -> bool:
        blobs, proofs = self.retrieve_blobs_and_proofs(beacon_block_root)
        return self.verify_blob_kzg_proof_batch(blobs, blob_kzg_commitments,
                                                proofs)

    def _on_block_data_availability_check(self, block) -> None:
        """Hook from ForkChoiceMixin.on_block (deneb fork-choice.md:70)."""
        assert self.is_data_available(hash_tree_root(block),
                                      block.body.blob_kzg_commitments)

    # -- fork upgrade (fork.md:77) ----------------------------------------------

    def upgrade_to_deneb(self, pre):
        epoch = self.get_current_epoch(pre)
        pre_header = pre.latest_execution_payload_header
        latest_execution_payload_header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            withdrawals_root=pre_header.withdrawals_root,
            blob_gas_used=uint64(0),   # [New in Deneb:EIP4844]
            excess_blob_gas=uint64(0),  # [New in Deneb:EIP4844]
        )
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.DENEB_FORK_VERSION,
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=pre.block_roots,
            state_roots=pre.state_roots,
            historical_roots=pre.historical_roots,
            eth1_data=pre.eth1_data,
            eth1_data_votes=pre.eth1_data_votes,
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=pre.validators,
            balances=pre.balances,
            randao_mixes=pre.randao_mixes,
            slashings=pre.slashings,
            previous_epoch_participation=pre.previous_epoch_participation,
            current_epoch_participation=pre.current_epoch_participation,
            justification_bits=pre.justification_bits,
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=pre.inactivity_scores,
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=latest_execution_payload_header,
            next_withdrawal_index=pre.next_withdrawal_index,
            next_withdrawal_validator_index=pre.next_withdrawal_validator_index,
            historical_summaries=pre.historical_summaries,
        )
        return post
