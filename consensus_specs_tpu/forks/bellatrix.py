"""Bellatrix (Merge) fork: execution payloads, engine protocol, merge
transition.

Behavioral sources: ``specs/bellatrix/beacon-chain.md`` (containers :100-200,
``is_merge_transition_complete`` :218, ``process_execution_payload`` :384,
modified ``slash_validator`` :279 / ``process_slashings`` :421 /
``get_inactivity_penalty_deltas`` :255), ``specs/bellatrix/fork.md``
(``upgrade_to_bellatrix`` :69) and ``specs/bellatrix/fork-choice.md``
(``PowBlock`` :180, ``is_valid_terminal_pow_block`` :195,
``validate_merge_block`` :204, modified ``on_block`` :235).  The Noop
execution engine mirrors ``pysetup/spec_builders/bellatrix.py:40-65``.
"""
from dataclasses import dataclass, field as _dc_field  # noqa: F401 (compiled-spec namespace)
from typing import Optional

from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, uint256, Bytes32,
    ByteList, ByteVector, Vector, List, Container,
)  # noqa: F401 (compiled-spec namespace)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.ops import epoch_kernels
from . import register_fork
from .altair import AltairSpec
from .optimistic_sync import OptimisticSyncMixin
from .base_types import (
    Epoch, Gwei, ValidatorIndex, Hash32, ExecutionAddress,
)


@register_fork("bellatrix")
class BellatrixSpec(OptimisticSyncMixin, AltairSpec):
    fork = "bellatrix"
    previous_fork = "altair"

    uint256 = uint256
    ExecutionAddress = ExecutionAddress

    # -- type construction ---------------------------------------------------

    def _build_types(self):
        S = self

        self.Transaction = ByteList[S.MAX_BYTES_PER_TRANSACTION]

        self.ExecutionPayload = type("ExecutionPayload", (Container,), {
            "__annotations__": self._execution_payload_fields()})
        self.ExecutionPayloadHeader = type(
            "ExecutionPayloadHeader", (Container,), {
                "__annotations__": self._execution_payload_header_fields()})

        class PowBlock(Container):
            block_hash: Hash32
            parent_hash: Hash32
            total_difficulty: uint256

        self.PowBlock = PowBlock
        super()._build_types()
        self._build_engine()

    def _execution_payload_common_fields(self) -> dict:
        """Execution block header fields shared by payload and header
        (beacon-chain.md:110-140)."""
        S = self
        return {
            "parent_hash": Hash32,
            "fee_recipient": ExecutionAddress,
            "state_root": Bytes32,
            "receipts_root": Bytes32,
            "logs_bloom": ByteVector[S.BYTES_PER_LOGS_BLOOM],
            "prev_randao": Bytes32,
            "block_number": uint64,
            "gas_limit": uint64,
            "gas_used": uint64,
            "timestamp": uint64,
            "extra_data": ByteList[S.MAX_EXTRA_DATA_BYTES],
            "base_fee_per_gas": uint256,
            "block_hash": Hash32,
        }

    def _execution_payload_fields(self) -> dict:
        fields = self._execution_payload_common_fields()
        fields["transactions"] = List[
            self.Transaction, self.MAX_TRANSACTIONS_PER_PAYLOAD]
        return fields

    def _execution_payload_header_fields(self) -> dict:
        fields = self._execution_payload_common_fields()
        fields["transactions_root"] = Bytes32
        return fields

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        fields["execution_payload"] = self.ExecutionPayload
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields["latest_execution_payload_header"] = self.ExecutionPayloadHeader
        return fields

    # -- execution engine (protocol + noop stub) -----------------------------

    def _new_payload_request_fields(self):
        return ("execution_payload",)

    def _build_engine(self):
        spec = self

        @dataclass
        class NewPayloadRequest:
            execution_payload: object = None

        class NoopExecutionEngine:
            """Reference stub behavior: every payload is valid
            (``pysetup/spec_builders/bellatrix.py:40-65``)."""

            def notify_new_payload(self, *args, **kwargs) -> bool:
                return True

            def notify_forkchoice_updated(self, head_block_hash,
                                          safe_block_hash,
                                          finalized_block_hash,
                                          payload_attributes):
                return None

            def get_payload(self, payload_id):
                raise NotImplementedError("no default block production")

            def is_valid_block_hash(self, *args, **kwargs) -> bool:
                return True

            def is_valid_versioned_hashes(self, new_payload_request) -> bool:
                return True

            def verify_and_notify_new_payload(self, new_payload_request) -> bool:
                return True

        self.NewPayloadRequest = NewPayloadRequest
        self.NoopExecutionEngine = NoopExecutionEngine
        self.EXECUTION_ENGINE = NoopExecutionEngine()

    # -- predicates (beacon-chain.md:218-234) --------------------------------

    def is_merge_transition_complete(self, state) -> bool:
        return state.latest_execution_payload_header != self.ExecutionPayloadHeader()

    def is_merge_transition_block(self, state, body) -> bool:
        return (not self.is_merge_transition_complete(state)
                and body.execution_payload != self.ExecutionPayload())

    def is_execution_enabled(self, state, body) -> bool:
        return (self.is_merge_transition_block(state, body)
                or self.is_merge_transition_complete(state))

    # -- misc ----------------------------------------------------------------

    def compute_timestamp_at_slot(self, state, slot) -> uint64:
        slots_since_genesis = slot - self.GENESIS_SLOT
        return uint64(state.genesis_time
                      + slots_since_genesis * self.config.SECONDS_PER_SLOT)

    # -- modified accessors / mutators ---------------------------------------

    def get_inactivity_penalty_deltas(self, state):
        """beacon-chain.md:255 — INACTIVITY_PENALTY_QUOTIENT_BELLATRIX."""
        from .altair import TIMELY_TARGET_FLAG_INDEX
        rewards = [Gwei(0)] * len(state.validators)
        penalties = [Gwei(0)] * len(state.validators)
        previous_epoch = self.get_previous_epoch(state)
        matching_target_indices = self.get_unslashed_participating_indices(
            state, TIMELY_TARGET_FLAG_INDEX, previous_epoch)
        for index in self.get_eligible_validator_indices(state):
            if index not in matching_target_indices:
                penalty_numerator = (state.validators[index].effective_balance
                                     * state.inactivity_scores[index])
                penalty_denominator = (self.config.INACTIVITY_SCORE_BIAS
                                       * self.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
                penalties[index] += Gwei(penalty_numerator // penalty_denominator)
        return rewards, penalties

    def slash_validator(self, state, slashed_index, whistleblower_index=None):
        """beacon-chain.md:279 — MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX."""
        from .altair import PROPOSER_WEIGHT, WEIGHT_DENOMINATOR
        epoch = self.get_current_epoch(state)
        self.initiate_validator_exit(state, slashed_index)
        validator = state.validators[slashed_index]
        validator.slashed = True
        validator.withdrawable_epoch = max(
            validator.withdrawable_epoch,
            Epoch(epoch + self.EPOCHS_PER_SLASHINGS_VECTOR))
        state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] += \
            validator.effective_balance
        slashing_penalty = (validator.effective_balance
                            // self.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX)
        self.decrease_balance(state, slashed_index, slashing_penalty)

        proposer_index = self.get_beacon_proposer_index(state)
        if whistleblower_index is None:
            whistleblower_index = proposer_index
        whistleblower_reward = Gwei(validator.effective_balance
                                    // self.WHISTLEBLOWER_REWARD_QUOTIENT)
        proposer_reward = Gwei(whistleblower_reward * PROPOSER_WEIGHT
                               // WEIGHT_DENOMINATOR)
        self.increase_balance(state, proposer_index, proposer_reward)
        self.increase_balance(state, whistleblower_index,
                              Gwei(whistleblower_reward - proposer_reward))

    def process_slashings(self, state):
        """beacon-chain.md:421 — PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX."""
        if epoch_kernels.try_process_slashings(self, state):
            return
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(state.slashings)
            * self.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
            total_balance)
        for index, validator in enumerate(state.validators):
            if validator.slashed and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR \
                    // 2 == validator.withdrawable_epoch:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                penalty_numerator = (validator.effective_balance // increment
                                     * adjusted_total_slashing_balance)
                penalty = penalty_numerator // total_balance * increment
                self.decrease_balance(state, ValidatorIndex(index), penalty)

    # -- block processing ----------------------------------------------------

    def process_block(self, state, block):
        """beacon-chain.md:370 — execution payload before randao."""
        with bls.batched_verification() as batch:
            self.process_block_header(state, block)
            if self.is_execution_enabled(state, block.body):
                self.process_execution_payload(
                    state, block.body, self.EXECUTION_ENGINE)
            self.process_randao(state, block.body)
            self.process_eth1_data(state, block.body)
            self.process_operations(state, block.body)
            self.process_sync_aggregate(state, block.body.sync_aggregate)
        batch.assert_valid()

    def process_execution_payload(self, state, body, execution_engine):
        """beacon-chain.md:384"""
        payload = body.execution_payload
        # Verify consistency of the parent hash with the previous header
        if self.is_merge_transition_complete(state):
            assert payload.parent_hash == \
                state.latest_execution_payload_header.block_hash
        # Verify prev_randao
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        # Verify timestamp
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        # Verify the execution payload is valid
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(execution_payload=payload))
        # Cache execution payload header
        state.latest_execution_payload_header = self._payload_to_header(payload)

    def _payload_to_header(self, payload):
        return self.ExecutionPayloadHeader(
            parent_hash=payload.parent_hash,
            fee_recipient=payload.fee_recipient,
            state_root=payload.state_root,
            receipts_root=payload.receipts_root,
            logs_bloom=payload.logs_bloom,
            prev_randao=payload.prev_randao,
            block_number=payload.block_number,
            gas_limit=payload.gas_limit,
            gas_used=payload.gas_used,
            timestamp=payload.timestamp,
            extra_data=payload.extra_data,
            base_fee_per_gas=payload.base_fee_per_gas,
            block_hash=payload.block_hash,
            transactions_root=hash_tree_root(payload.transactions),
        )

    # -- merge-transition fork choice (fork-choice.md) -----------------------

    def get_pow_block(self, block_hash) -> Optional[object]:
        """Test stub (``pysetup/spec_builders/bellatrix.py:21-23``);
        fork-choice tests swap this out per scenario."""
        return self.PowBlock(block_hash=block_hash, parent_hash=Bytes32(),
                             total_difficulty=uint256(0))

    def is_valid_terminal_pow_block(self, block, parent) -> bool:
        """fork-choice.md:195"""
        is_total_difficulty_reached = (
            block.total_difficulty >= self.config.TERMINAL_TOTAL_DIFFICULTY)
        is_parent_total_difficulty_valid = (
            parent.total_difficulty < self.config.TERMINAL_TOTAL_DIFFICULTY)
        return is_total_difficulty_reached and is_parent_total_difficulty_valid

    def validate_merge_block(self, block) -> None:
        """fork-choice.md:204"""
        if self.config.TERMINAL_BLOCK_HASH != Hash32():
            # Terminal-hash override: activation epoch must be reached
            assert self.compute_epoch_at_slot(block.slot) >= \
                self.config.TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH
            assert block.body.execution_payload.parent_hash == \
                self.config.TERMINAL_BLOCK_HASH
            return
        pow_block = self.get_pow_block(block.body.execution_payload.parent_hash)
        assert pow_block is not None
        pow_parent = self.get_pow_block(pow_block.parent_hash)
        assert pow_parent is not None
        assert self.is_valid_terminal_pow_block(pow_block, pow_parent)

    def _on_block_merge_check(self, pre_state, block) -> None:
        """Hook invoked by ForkChoiceMixin.on_block (fork-choice.md:266)."""
        if self.is_merge_transition_block(pre_state, block.body):
            self.validate_merge_block(block)

    # -- fork upgrade (fork.md:69) -------------------------------------------

    def upgrade_to_bellatrix(self, pre):
        epoch = self.get_current_epoch(pre)
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.BELLATRIX_FORK_VERSION,
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=pre.block_roots,
            state_roots=pre.state_roots,
            historical_roots=pre.historical_roots,
            eth1_data=pre.eth1_data,
            eth1_data_votes=pre.eth1_data_votes,
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=pre.validators,
            balances=pre.balances,
            randao_mixes=pre.randao_mixes,
            slashings=pre.slashings,
            previous_epoch_participation=pre.previous_epoch_participation,
            current_epoch_participation=pre.current_epoch_participation,
            justification_bits=pre.justification_bits,
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=pre.inactivity_scores,
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=self.ExecutionPayloadHeader(),
        )
        return post

    def initialize_beacon_state_from_eth1(self, eth1_block_hash,
                                          eth1_timestamp, deposits,
                                          execution_payload_header=None):
        """Bellatrix testing variant (``specs/bellatrix/beacon-chain.md``
        Testing section): genesis at the bellatrix fork version; an
        empty (default) payload header boots a pre-merge chain, a
        non-empty one starts post-transition."""
        state = super().initialize_beacon_state_from_eth1(
            eth1_block_hash, eth1_timestamp, deposits)
        version = getattr(self.config,
                          f"{self.fork.upper()}_FORK_VERSION")
        state.fork.previous_version = version
        state.fork.current_version = version
        if execution_payload_header is not None:
            state.latest_execution_payload_header = execution_payload_header
        return state

    # -- mock genesis hook ---------------------------------------------------

    def post_mock_genesis(self, state):
        super().post_mock_genesis(state)
        # Give harness states a post-merge header so execution is enabled
        # (reference helpers/genesis.py builds a default payload header).
        state.latest_execution_payload_header = self.default_payload_header()

    def default_payload_header(self):
        """A minimal non-empty header marking the merge as complete."""
        return self.ExecutionPayloadHeader(
            block_hash=Hash32(b"\x42" * 32),
            state_root=Bytes32(b"\x20" * 32),
            transactions_root=hash_tree_root(
                List[self.Transaction, self.MAX_TRANSACTIONS_PER_PAYLOAD]()),
        )
