"""Capella fork: withdrawals, BLS-to-execution changes, historical
summaries.

Behavioral sources: ``specs/capella/beacon-chain.md`` (``Withdrawal`` :102,
``BLSToExecutionChange`` :112, ``HistoricalSummary`` :129, withdrawal
predicates :260-291, ``get_expected_withdrawals`` :346,
``process_withdrawals`` :380, modified ``process_execution_payload`` :411,
``process_bls_to_execution_change`` :466,
``process_historical_summaries_update`` :318) and ``specs/capella/fork.md``
(``upgrade_to_capella`` :77).
"""
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint64, Bytes32, Vector, List, Container,
    get_generalized_index, compute_merkle_proof,
)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.hash_function import hash
from . import register_fork
from .bellatrix import BellatrixSpec
from .base_types import (
    Epoch, Gwei, ValidatorIndex, Root, ExecutionAddress, BLSPubkey,
    BLSSignature, BLS_WITHDRAWAL_PREFIX, ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
)

WithdrawalIndex = uint64


@register_fork("capella")
class CapellaSpec(BellatrixSpec):
    fork = "capella"
    previous_fork = "bellatrix"

    WithdrawalIndex = WithdrawalIndex
    DOMAIN_BLS_TO_EXECUTION_CHANGE = DOMAIN_BLS_TO_EXECUTION_CHANGE

    # -- type construction ---------------------------------------------------

    def _build_types(self):
        S = self

        class Withdrawal(Container):
            index: WithdrawalIndex
            validator_index: ValidatorIndex
            address: ExecutionAddress
            amount: Gwei

        class BLSToExecutionChange(Container):
            validator_index: ValidatorIndex
            from_bls_pubkey: BLSPubkey
            to_execution_address: ExecutionAddress

        class SignedBLSToExecutionChange(Container):
            message: BLSToExecutionChange
            signature: BLSSignature

        class HistoricalSummary(Container):
            # hash_tree_root-compatible with phase0 HistoricalBatch
            block_summary_root: Root
            state_summary_root: Root

        self.Withdrawal = Withdrawal
        self.BLSToExecutionChange = BLSToExecutionChange
        self.SignedBLSToExecutionChange = SignedBLSToExecutionChange
        self.HistoricalSummary = HistoricalSummary
        super()._build_types()

    def _execution_payload_fields(self) -> dict:
        """Adds the withdrawals list (beacon-chain.md:160)."""
        fields = super()._execution_payload_fields()
        fields["withdrawals"] = List[
            self.Withdrawal, self.MAX_WITHDRAWALS_PER_PAYLOAD]
        return fields

    def _execution_payload_header_fields(self) -> dict:
        fields = super()._execution_payload_header_fields()
        fields["withdrawals_root"] = Bytes32
        return fields

    def _block_body_fields(self, t) -> dict:
        fields = super()._block_body_fields(t)
        fields["bls_to_execution_changes"] = List[
            self.SignedBLSToExecutionChange, self.MAX_BLS_TO_EXECUTION_CHANGES]
        return fields

    def _state_fields(self, t) -> dict:
        fields = super()._state_fields(t)
        fields["next_withdrawal_index"] = WithdrawalIndex
        fields["next_withdrawal_validator_index"] = ValidatorIndex
        fields["historical_summaries"] = List[
            self.HistoricalSummary, self.HISTORICAL_ROOTS_LIMIT]
        return fields

    # -- withdrawal predicates (beacon-chain.md:260-291) ---------------------

    def has_eth1_withdrawal_credential(self, validator) -> bool:
        return bytes(validator.withdrawal_credentials[:1]) == \
            ETH1_ADDRESS_WITHDRAWAL_PREFIX

    def is_fully_withdrawable_validator(self, validator, balance, epoch) -> bool:
        return (self.has_eth1_withdrawal_credential(validator)
                and validator.withdrawable_epoch <= epoch
                and balance > 0)

    def is_partially_withdrawable_validator(self, validator, balance) -> bool:
        has_max_effective_balance = (
            validator.effective_balance == self.MAX_EFFECTIVE_BALANCE)
        has_excess_balance = balance > self.MAX_EFFECTIVE_BALANCE
        return (self.has_eth1_withdrawal_credential(validator)
                and has_max_effective_balance and has_excess_balance)

    # -- epoch processing ----------------------------------------------------

    def process_epoch(self, state):
        """beacon-chain.md:300 — historical summaries replace roots."""
        self.process_justification_and_finalization(state)
        self.process_inactivity_updates(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_summaries_update(state)
        self.process_participation_flag_updates(state)
        self.process_sync_committee_updates(state)

    def process_historical_summaries_update(self, state):
        """beacon-chain.md:318"""
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT
                         // self.SLOTS_PER_EPOCH) == 0:
            historical_summary = self.HistoricalSummary(
                block_summary_root=hash_tree_root(state.block_roots),
                state_summary_root=hash_tree_root(state.state_roots),
            )
            state.historical_summaries.append(historical_summary)

    def process_historical_roots_update(self, state):
        raise AttributeError("replaced by process_historical_summaries_update")

    # -- block processing ----------------------------------------------------

    def process_block(self, state, block):
        """beacon-chain.md:332 — withdrawals first, no execution-enabled
        gate (capella is unconditionally post-merge)."""
        with bls.batched_verification() as batch:
            self.process_block_header(state, block)
            self.process_withdrawals(state, block.body.execution_payload)
            self.process_execution_payload(state, block.body,
                                           self.EXECUTION_ENGINE)
            self.process_randao(state, block.body)
            self.process_eth1_data(state, block.body)
            self.process_operations(state, block.body)
            self.process_sync_aggregate(state, block.body.sync_aggregate)
        batch.assert_valid()

    def get_expected_withdrawals(self, state):
        """beacon-chain.md:346 — bounded sweep from the rotating cursor."""
        epoch = self.get_current_epoch(state)
        withdrawal_index = state.next_withdrawal_index
        validator_index = state.next_withdrawal_validator_index
        withdrawals = []
        bound = min(len(state.validators),
                    self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        for _ in range(bound):
            validator = state.validators[validator_index]
            balance = state.balances[validator_index]
            if self.is_fully_withdrawable_validator(validator, balance, epoch):
                withdrawals.append(self.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=ExecutionAddress(
                        bytes(validator.withdrawal_credentials[12:])),
                    amount=balance,
                ))
                withdrawal_index = WithdrawalIndex(withdrawal_index + 1)
            elif self.is_partially_withdrawable_validator(validator, balance):
                withdrawals.append(self.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=ExecutionAddress(
                        bytes(validator.withdrawal_credentials[12:])),
                    amount=balance - self.MAX_EFFECTIVE_BALANCE,
                ))
                withdrawal_index = WithdrawalIndex(withdrawal_index + 1)
            if len(withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
                break
            validator_index = ValidatorIndex(
                (validator_index + 1) % len(state.validators))
        return withdrawals

    def process_withdrawals(self, state, payload):
        """beacon-chain.md:380"""
        expected_withdrawals = self.get_expected_withdrawals(state)
        assert len(payload.withdrawals) == len(expected_withdrawals)

        for expected_withdrawal, withdrawal in zip(expected_withdrawals,
                                                   payload.withdrawals):
            assert withdrawal == expected_withdrawal
            self.decrease_balance(state, withdrawal.validator_index,
                                  withdrawal.amount)

        # Update the next withdrawal index if this block contained withdrawals
        if len(expected_withdrawals) != 0:
            latest_withdrawal = expected_withdrawals[-1]
            state.next_withdrawal_index = WithdrawalIndex(
                latest_withdrawal.index + 1)

        # Update the next validator index for the next sweep
        if len(expected_withdrawals) == self.MAX_WITHDRAWALS_PER_PAYLOAD:
            next_validator_index = ValidatorIndex(
                (expected_withdrawals[-1].validator_index + 1)
                % len(state.validators))
            state.next_withdrawal_validator_index = next_validator_index
        else:
            next_index = (state.next_withdrawal_validator_index
                          + self.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
            next_validator_index = ValidatorIndex(
                next_index % len(state.validators))
            state.next_withdrawal_validator_index = next_validator_index

    def process_execution_payload(self, state, body, execution_engine):
        """beacon-chain.md:411 — merge-transition check removed, capella
        header type (withdrawals_root)."""
        payload = body.execution_payload
        assert payload.parent_hash == \
            state.latest_execution_payload_header.block_hash
        assert payload.prev_randao == self.get_randao_mix(
            state, self.get_current_epoch(state))
        assert payload.timestamp == self.compute_timestamp_at_slot(
            state, state.slot)
        assert execution_engine.verify_and_notify_new_payload(
            self.NewPayloadRequest(execution_payload=payload))
        state.latest_execution_payload_header = self._payload_to_header(payload)

    def _payload_to_header(self, payload):
        header = super()._payload_to_header(payload)
        header.withdrawals_root = hash_tree_root(payload.withdrawals)
        return header

    def process_operations(self, state, body):
        """beacon-chain.md:447 — adds bls_to_execution_changes."""
        super().process_operations(state, body)
        for operation in body.bls_to_execution_changes:
            self.process_bls_to_execution_change(state, operation)

    def process_bls_to_execution_change(self, state, signed_address_change):
        """beacon-chain.md:466"""
        address_change = signed_address_change.message

        assert address_change.validator_index < len(state.validators)

        validator = state.validators[address_change.validator_index]

        assert bytes(validator.withdrawal_credentials[:1]) == \
            BLS_WITHDRAWAL_PREFIX
        assert bytes(validator.withdrawal_credentials[1:]) == \
            hash(address_change.from_bls_pubkey)[1:]

        # Fork-agnostic domain since address changes are valid across forks
        domain = self.compute_domain(
            DOMAIN_BLS_TO_EXECUTION_CHANGE,
            genesis_validators_root=state.genesis_validators_root)
        signing_root = self.compute_signing_root(address_change, domain)
        assert bls.Verify(address_change.from_bls_pubkey, signing_root,
                          signed_address_change.signature)

        validator.withdrawal_credentials = (
            ETH1_ADDRESS_WITHDRAWAL_PREFIX
            + b"\x00" * 11
            + bytes(address_change.to_execution_address)
        )

    # -- merge transition is over --------------------------------------------

    def _on_block_merge_check(self, pre_state, block) -> None:
        """capella: the merge is complete; nothing to validate."""

    # -- light client (specs/capella/light-client/sync-protocol.md) ----------

    def _build_light_client_types(self):
        """Capella LightClientHeader adds the execution payload header +
        its inclusion branch (sync-protocol.md:48)."""
        from .light_client import floorlog2
        S = self
        self.EXECUTION_PAYLOAD_GINDEX = get_generalized_index(
            self.BeaconBlockBody, "execution_payload")
        ExecutionBranch = Vector[
            Bytes32, floorlog2(self.EXECUTION_PAYLOAD_GINDEX)]
        self.ExecutionBranch = ExecutionBranch

        class LightClientHeader(Container):
            beacon: S.BeaconBlockHeader
            execution: S.ExecutionPayloadHeader
            execution_branch: ExecutionBranch

        super()._build_light_client_types()
        self.LightClientHeader = LightClientHeader
        # rebuild the dependent containers against the new header
        self._rebuild_light_client_containers(LightClientHeader)

    def _rebuild_light_client_containers(self, LightClientHeader):
        S = self

        class LightClientBootstrap(Container):
            header: LightClientHeader
            current_sync_committee: S.SyncCommittee
            current_sync_committee_branch: S.CurrentSyncCommitteeBranch

        class LightClientUpdate(Container):
            attested_header: LightClientHeader
            next_sync_committee: S.SyncCommittee
            next_sync_committee_branch: S.NextSyncCommitteeBranch
            finalized_header: LightClientHeader
            finality_branch: S.FinalityBranch
            sync_aggregate: S.SyncAggregate
            signature_slot: S.Slot

        class LightClientFinalityUpdate(Container):
            attested_header: LightClientHeader
            finalized_header: LightClientHeader
            finality_branch: S.FinalityBranch
            sync_aggregate: S.SyncAggregate
            signature_slot: S.Slot

        class LightClientOptimisticUpdate(Container):
            attested_header: LightClientHeader
            sync_aggregate: S.SyncAggregate
            signature_slot: S.Slot

        self.LightClientBootstrap = LightClientBootstrap
        self.LightClientUpdate = LightClientUpdate
        self.LightClientFinalityUpdate = LightClientFinalityUpdate
        self.LightClientOptimisticUpdate = LightClientOptimisticUpdate

    def get_lc_execution_root(self, header):
        """light-client/sync-protocol.md:61"""
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch >= self.config.CAPELLA_FORK_EPOCH:
            return hash_tree_root(header.execution)
        return Root()

    def is_valid_light_client_header(self, header) -> bool:
        """light-client/sync-protocol.md:73"""
        from .light_client import floorlog2
        epoch = self.compute_epoch_at_slot(header.beacon.slot)
        if epoch < self.config.CAPELLA_FORK_EPOCH:
            return (header.execution == self.ExecutionPayloadHeader()
                    and header.execution_branch == self.ExecutionBranch())
        return self.is_valid_merkle_branch(
            leaf=self.get_lc_execution_root(header),
            branch=header.execution_branch,
            depth=floorlog2(self.EXECUTION_PAYLOAD_GINDEX),
            index=self.get_subtree_index(self.EXECUTION_PAYLOAD_GINDEX),
            root=header.beacon.body_root,
        )

    def block_to_light_client_header(self, block):
        """light-client/full-node.md:27"""
        epoch = self.compute_epoch_at_slot(block.message.slot)
        beacon = self.BeaconBlockHeader(
            slot=block.message.slot,
            proposer_index=block.message.proposer_index,
            parent_root=block.message.parent_root,
            state_root=block.message.state_root,
            body_root=hash_tree_root(block.message.body),
        )
        if epoch >= self.config.CAPELLA_FORK_EPOCH:
            payload = block.message.body.execution_payload
            execution_header = self._payload_to_header(payload)
            execution_branch = compute_merkle_proof(
                block.message.body, self.EXECUTION_PAYLOAD_GINDEX)
            return self.LightClientHeader(
                beacon=beacon, execution=execution_header,
                execution_branch=execution_branch)
        return self.LightClientHeader(beacon=beacon)

    # -- fork upgrade (fork.md:77) -------------------------------------------

    def upgrade_to_capella(self, pre):
        epoch = self.get_current_epoch(pre)
        pre_header = pre.latest_execution_payload_header
        latest_execution_payload_header = self.ExecutionPayloadHeader(
            parent_hash=pre_header.parent_hash,
            fee_recipient=pre_header.fee_recipient,
            state_root=pre_header.state_root,
            receipts_root=pre_header.receipts_root,
            logs_bloom=pre_header.logs_bloom,
            prev_randao=pre_header.prev_randao,
            block_number=pre_header.block_number,
            gas_limit=pre_header.gas_limit,
            gas_used=pre_header.gas_used,
            timestamp=pre_header.timestamp,
            extra_data=pre_header.extra_data,
            base_fee_per_gas=pre_header.base_fee_per_gas,
            block_hash=pre_header.block_hash,
            transactions_root=pre_header.transactions_root,
            withdrawals_root=Root(),  # [New in Capella]
        )
        post = self.BeaconState(
            genesis_time=pre.genesis_time,
            genesis_validators_root=pre.genesis_validators_root,
            slot=pre.slot,
            fork=self.Fork(
                previous_version=pre.fork.current_version,
                current_version=self.config.CAPELLA_FORK_VERSION,
                epoch=epoch,
            ),
            latest_block_header=pre.latest_block_header,
            block_roots=pre.block_roots,
            state_roots=pre.state_roots,
            historical_roots=pre.historical_roots,
            eth1_data=pre.eth1_data,
            eth1_data_votes=pre.eth1_data_votes,
            eth1_deposit_index=pre.eth1_deposit_index,
            validators=pre.validators,
            balances=pre.balances,
            randao_mixes=pre.randao_mixes,
            slashings=pre.slashings,
            previous_epoch_participation=pre.previous_epoch_participation,
            current_epoch_participation=pre.current_epoch_participation,
            justification_bits=pre.justification_bits,
            previous_justified_checkpoint=pre.previous_justified_checkpoint,
            current_justified_checkpoint=pre.current_justified_checkpoint,
            finalized_checkpoint=pre.finalized_checkpoint,
            inactivity_scores=pre.inactivity_scores,
            current_sync_committee=pre.current_sync_committee,
            next_sync_committee=pre.next_sync_committee,
            latest_execution_payload_header=latest_execution_payload_header,
            next_withdrawal_index=WithdrawalIndex(0),
            next_withdrawal_validator_index=ValidatorIndex(0),
            historical_summaries=List[
                self.HistoricalSummary, self.HISTORICAL_ROOTS_LIMIT](),
        )
        return post
