"""phase0 beacon-chain spec runtime.

Behavioral port of ``specs/phase0/beacon-chain.md`` (reference, v1.4.0-beta.7)
re-architected as a preset-bound spec class: constants are instance
attributes, SSZ container types are built per preset at construction, and
fork inheritance is class inheritance. Function names, signatures and
semantics match the reference markdown (cited per method) so harness code
and vectors are interchangeable.

Exception-as-invalidity: processing functions raise AssertionError (or
IndexError/ValueError from SSZ bounds) on invalid input — the harness's
``expect_assertion_error`` and fork-choice invalid-block handling rely on it
(reference: ``test/context.py:299-310``).
"""
from types import SimpleNamespace
from typing import Dict, Sequence, Set

from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, uint_to_bytes, copy as ssz_copy,
    boolean, uint8, uint32, uint64, Bytes4, Bytes32, Bytes48, Bytes96,
    Bitlist, Bitvector, Vector, List, Container,
)  # noqa: F401 (compiled-spec namespace)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz.forest import hash_forest
from consensus_specs_tpu.ops import epoch_kernels
from consensus_specs_tpu.state import arrays as state_arrays
from . import register_fork
from .fork_choice import ForkChoiceMixin
from .validator_guide import ValidatorGuideMixin
from .base_types import (
    Slot, Epoch, CommitteeIndex, ValidatorIndex, Gwei, Root, Hash32, Version,
    DomainType, ForkDigest, Domain, BLSPubkey, BLSSignature,
    GENESIS_SLOT, GENESIS_EPOCH, FAR_FUTURE_EPOCH, BASE_REWARDS_PER_EPOCH,
    DEPOSIT_CONTRACT_TREE_DEPTH, JUSTIFICATION_BITS_LENGTH,
    BLS_WITHDRAWAL_PREFIX, ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    DOMAIN_BEACON_PROPOSER, DOMAIN_BEACON_ATTESTER, DOMAIN_RANDAO,
    DOMAIN_DEPOSIT, DOMAIN_VOLUNTARY_EXIT, DOMAIN_SELECTION_PROOF,
    DOMAIN_AGGREGATE_AND_PROOF,
)

_PRESET_VAR_TYPES = {}  # all plain ints


# Re-exported under the historical name: the compiled-spec scaffold and
# this module's caches both use it (shared impl: utils/lru.py).
from consensus_specs_tpu.utils.lru import LRUDict as _LRUDict  # noqa: E402


def _bytes_of(hexstr, width):
    if isinstance(hexstr, str) and hexstr.startswith("0x"):
        raw = bytes.fromhex(hexstr[2:])
    elif isinstance(hexstr, int):
        raw = hexstr.to_bytes(width, "big")
    else:
        raw = bytes(hexstr)
    if len(raw) != width:
        raise ValueError(f"expected {width} bytes, got {len(raw)}")
    return raw


@register_fork("phase0")
class Phase0Spec(ValidatorGuideMixin, ForkChoiceMixin):
    fork = "phase0"
    previous_fork = None

    # re-exported SSZ/crypto surface so harness code can do spec.hash_tree_root
    hash = staticmethod(hash)
    hash_tree_root = staticmethod(hash_tree_root)
    uint_to_bytes = staticmethod(uint_to_bytes)
    copy = staticmethod(ssz_copy)
    bls = bls

    # types
    Slot, Epoch, CommitteeIndex, ValidatorIndex = Slot, Epoch, CommitteeIndex, ValidatorIndex
    Gwei, Root, Hash32, Version, DomainType = Gwei, Root, Hash32, Version, DomainType
    ForkDigest, Domain, BLSPubkey, BLSSignature = ForkDigest, Domain, BLSPubkey, BLSSignature
    uint8, uint64 = uint8, uint64
    Bytes32 = Bytes32

    # constants
    GENESIS_SLOT, GENESIS_EPOCH, FAR_FUTURE_EPOCH = GENESIS_SLOT, GENESIS_EPOCH, FAR_FUTURE_EPOCH
    BASE_REWARDS_PER_EPOCH = BASE_REWARDS_PER_EPOCH
    DEPOSIT_CONTRACT_TREE_DEPTH = DEPOSIT_CONTRACT_TREE_DEPTH
    JUSTIFICATION_BITS_LENGTH = JUSTIFICATION_BITS_LENGTH
    BLS_WITHDRAWAL_PREFIX = BLS_WITHDRAWAL_PREFIX
    ETH1_ADDRESS_WITHDRAWAL_PREFIX = ETH1_ADDRESS_WITHDRAWAL_PREFIX
    DOMAIN_BEACON_PROPOSER = DOMAIN_BEACON_PROPOSER
    DOMAIN_BEACON_ATTESTER = DOMAIN_BEACON_ATTESTER
    DOMAIN_RANDAO = DOMAIN_RANDAO
    DOMAIN_DEPOSIT = DOMAIN_DEPOSIT
    DOMAIN_VOLUNTARY_EXIT = DOMAIN_VOLUNTARY_EXIT
    DOMAIN_SELECTION_PROOF = DOMAIN_SELECTION_PROOF
    DOMAIN_AGGREGATE_AND_PROOF = DOMAIN_AGGREGATE_AND_PROOF

    def __init__(self, preset: dict, config: dict, preset_name: str = "custom"):
        self.preset_name = preset_name
        self._preset = dict(preset)
        for k, v in preset.items():
            setattr(self, k, v)
        self.config = self._build_config(config)
        self._build_types()
        # Bounded like the reference's lru-dict caches
        # (pysetup/spec_builders/phase0.py:59-105); unbounded dicts would grow
        # without limit across a long generator run.
        self._caches: Dict[str, "_LRUDict"] = {
            "committee": _LRUDict(512, name="committee"),
            "proposer": _LRUDict(512, name="proposer"),
            "active_indices": _LRUDict(128, name="active_indices"),
            "total_balance": _LRUDict(128, name="total_balance"),
        }

    # -- config ------------------------------------------------------------
    def _build_config(self, config: dict) -> SimpleNamespace:
        c = SimpleNamespace()
        for k, v in config.items():
            if k.endswith("_FORK_VERSION") or k == "GENESIS_FORK_VERSION":
                v = Version(_bytes_of(v, 4))
            elif k in ("TERMINAL_BLOCK_HASH",):
                v = Hash32(_bytes_of(v, 32))
            elif k in ("DEPOSIT_CONTRACT_ADDRESS",):
                v = _bytes_of(v, 20)
            elif k.startswith("MESSAGE_DOMAIN_"):
                v = DomainType(_bytes_of(v, 4))
            setattr(c, k, v)
        return c

    # -- SSZ containers (preset-parameterized) ------------------------------
    def _build_types(self):
        """Containers from ``specs/phase0/beacon-chain.md`` ("Containers")."""
        S = self

        class Fork(Container):
            previous_version: Version
            current_version: Version
            epoch: Epoch

        class ForkData(Container):
            current_version: Version
            genesis_validators_root: Root

        class Checkpoint(Container):
            epoch: Epoch
            root: Root

        Validator = type("Validator", (Container,), {
            "__annotations__": self._validator_fields()})

        AttestationData = type("AttestationData", (Container,), {
            "__annotations__": self._attestation_data_fields(locals())})

        class IndexedAttestation(Container):
            attesting_indices: List[ValidatorIndex, S.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: BLSSignature

        class PendingAttestation(Container):
            aggregation_bits: Bitlist[S.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            inclusion_delay: Slot
            proposer_index: ValidatorIndex

        class Eth1Data(Container):
            deposit_root: Root
            deposit_count: uint64
            block_hash: Hash32

        class HistoricalBatch(Container):
            block_roots: Vector[Root, S.SLOTS_PER_HISTORICAL_ROOT]
            state_roots: Vector[Root, S.SLOTS_PER_HISTORICAL_ROOT]

        class DepositMessage(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei

        class DepositData(Container):
            pubkey: BLSPubkey
            withdrawal_credentials: Bytes32
            amount: Gwei
            signature: BLSSignature

        class BeaconBlockHeader(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body_root: Root

        class SigningData(Container):
            object_root: Root
            domain: Domain

        class SignedBeaconBlockHeader(Container):
            message: BeaconBlockHeader
            signature: BLSSignature

        class ProposerSlashing(Container):
            signed_header_1: SignedBeaconBlockHeader
            signed_header_2: SignedBeaconBlockHeader

        class AttesterSlashing(Container):
            attestation_1: IndexedAttestation
            attestation_2: IndexedAttestation

        class Attestation(Container):
            aggregation_bits: Bitlist[S.MAX_VALIDATORS_PER_COMMITTEE]
            data: AttestationData
            signature: BLSSignature

        class Deposit(Container):
            proof: Vector[Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1]
            data: DepositData

        class VoluntaryExit(Container):
            epoch: Epoch
            validator_index: ValidatorIndex

        class SignedVoluntaryExit(Container):
            message: VoluntaryExit
            signature: BLSSignature

        body_fields = self._block_body_fields(locals())
        BeaconBlockBody = type("BeaconBlockBody", (Container,), {
            "__annotations__": body_fields})

        class BeaconBlock(Container):
            slot: Slot
            proposer_index: ValidatorIndex
            parent_root: Root
            state_root: Root
            body: BeaconBlockBody

        class SignedBeaconBlock(Container):
            message: BeaconBlock
            signature: BLSSignature

        state_fields = self._state_fields(locals())
        BeaconState = type("BeaconState", (Container,), {
            "__annotations__": state_fields})

        class Eth1Block(Container):
            timestamp: uint64
            deposit_root: Root
            deposit_count: uint64

        class AggregateAndProof(Container):
            aggregator_index: ValidatorIndex
            aggregate: Attestation
            selection_proof: BLSSignature

        class SignedAggregateAndProof(Container):
            message: AggregateAndProof
            signature: BLSSignature

        for name, typ in list(locals().items()):
            if isinstance(typ, type) and issubclass(typ, Container):
                setattr(self, name, typ)

    def _validator_fields(self) -> dict:
        """``Validator`` fields (beacon-chain.md "Validator"); research
        forks (custody_game) append via override."""
        return {
            "pubkey": BLSPubkey,
            "withdrawal_credentials": Bytes32,
            "effective_balance": Gwei,
            "slashed": boolean,
            "activation_eligibility_epoch": Epoch,
            "activation_epoch": Epoch,
            "exit_epoch": Epoch,
            "withdrawable_epoch": Epoch,
        }

    def _attestation_data_fields(self, t) -> dict:
        """``AttestationData`` fields; the legacy sharding lineage appends
        ``shard_transition_root`` via override."""
        return {
            "slot": Slot,
            "index": CommitteeIndex,
            "beacon_block_root": Root,
            "source": t["Checkpoint"],
            "target": t["Checkpoint"],
        }

    def _block_body_fields(self, t) -> dict:
        S = self
        return {
            "randao_reveal": BLSSignature,
            "eth1_data": t["Eth1Data"],
            "graffiti": Bytes32,
            "proposer_slashings": List[t["ProposerSlashing"], S.MAX_PROPOSER_SLASHINGS],
            "attester_slashings": List[t["AttesterSlashing"], S.MAX_ATTESTER_SLASHINGS],
            "attestations": List[t["Attestation"], S.MAX_ATTESTATIONS],
            "deposits": List[t["Deposit"], S.MAX_DEPOSITS],
            "voluntary_exits": List[t["SignedVoluntaryExit"], S.MAX_VOLUNTARY_EXITS],
        }

    def _state_fields(self, t) -> dict:
        S = self
        return {
            "genesis_time": uint64,
            "genesis_validators_root": Root,
            "slot": Slot,
            "fork": t["Fork"],
            "latest_block_header": t["BeaconBlockHeader"],
            "block_roots": Vector[Root, S.SLOTS_PER_HISTORICAL_ROOT],
            "state_roots": Vector[Root, S.SLOTS_PER_HISTORICAL_ROOT],
            "historical_roots": List[Root, S.HISTORICAL_ROOTS_LIMIT],
            "eth1_data": t["Eth1Data"],
            "eth1_data_votes": List[t["Eth1Data"],
                                    S.EPOCHS_PER_ETH1_VOTING_PERIOD * S.SLOTS_PER_EPOCH],
            "eth1_deposit_index": uint64,
            "validators": List[t["Validator"], S.VALIDATOR_REGISTRY_LIMIT],
            "balances": List[Gwei, S.VALIDATOR_REGISTRY_LIMIT],
            "randao_mixes": Vector[Bytes32, S.EPOCHS_PER_HISTORICAL_VECTOR],
            "slashings": Vector[Gwei, S.EPOCHS_PER_SLASHINGS_VECTOR],
            "previous_epoch_attestations": List[t["PendingAttestation"],
                                                S.MAX_ATTESTATIONS * S.SLOTS_PER_EPOCH],
            "current_epoch_attestations": List[t["PendingAttestation"],
                                               S.MAX_ATTESTATIONS * S.SLOTS_PER_EPOCH],
            "justification_bits": Bitvector[JUSTIFICATION_BITS_LENGTH],
            "previous_justified_checkpoint": t["Checkpoint"],
            "current_justified_checkpoint": t["Checkpoint"],
            "finalized_checkpoint": t["Checkpoint"],
        }

    # ======================================================================
    # Math & crypto helpers (beacon-chain.md "Helper functions")
    # ======================================================================

    def integer_squareroot(self, n) -> uint64:
        """beacon-chain.md:597"""
        if n == 2**64 - 1:
            return uint64(4294967295)
        x, y = n, (n + 1) // 2
        while y < x:
            x, y = y, (y + n // y) // 2
        return uint64(x)

    def xor(self, bytes_1: bytes, bytes_2: bytes) -> Bytes32:
        return Bytes32(bytes(a ^ b for a, b in zip(bytes_1, bytes_2)))

    def bytes_to_uint64(self, data: bytes) -> uint64:
        return uint64(int.from_bytes(data, "little"))

    # -- predicates --------------------------------------------------------

    def is_active_validator(self, validator, epoch) -> bool:
        """beacon-chain.md:625 (is_active_validator)"""
        return validator.activation_epoch <= epoch < validator.exit_epoch

    def is_eligible_for_activation_queue(self, validator) -> bool:
        return (validator.activation_eligibility_epoch == FAR_FUTURE_EPOCH
                and validator.effective_balance == self.MAX_EFFECTIVE_BALANCE)

    def is_eligible_for_activation(self, state, validator) -> bool:
        return (validator.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
                and validator.activation_epoch == FAR_FUTURE_EPOCH)

    def is_slashable_validator(self, validator, epoch) -> bool:
        return (not validator.slashed) and (
            validator.activation_epoch <= epoch < validator.withdrawable_epoch)

    def is_slashable_attestation_data(self, data_1, data_2) -> bool:
        return (
            # double vote
            (data_1 != data_2 and data_1.target.epoch == data_2.target.epoch)
            # surround vote
            or (data_1.source.epoch < data_2.source.epoch
                and data_2.target.epoch < data_1.target.epoch)
        )

    def is_valid_indexed_attestation(self, state, indexed_attestation) -> bool:
        """beacon-chain.md:739"""
        indices = list(indexed_attestation.attesting_indices)
        if len(indices) == 0 or not indices == sorted(set(indices)):
            return False
        pubkeys = [state.validators[i].pubkey for i in indices]
        domain = self.get_domain(state, DOMAIN_BEACON_ATTESTER,
                                 indexed_attestation.data.target.epoch)
        signing_root = self.compute_signing_root(indexed_attestation.data, domain)
        return bls.FastAggregateVerify(pubkeys, signing_root, indexed_attestation.signature)

    def is_valid_merkle_branch(self, leaf, branch, depth, index, root) -> bool:
        """beacon-chain.md:757"""
        value = leaf
        for i in range(depth):
            if index // (2**i) % 2:
                value = hash(branch[i] + value)
            else:
                value = hash(value + branch[i])
        return value == root

    # -- misc --------------------------------------------------------------

    def compute_shuffled_index(self, index, index_count, seed) -> uint64:
        """Swap-or-not shuffle (beacon-chain.md:775)."""
        assert index < index_count
        for current_round in range(self.SHUFFLE_ROUND_COUNT):
            pivot = self.bytes_to_uint64(
                hash(seed + uint_to_bytes(uint8(current_round)))[0:8]) % index_count
            flip = (pivot + index_count - index) % index_count
            position = max(index, flip)
            source = hash(seed + uint_to_bytes(uint8(current_round))
                          + uint_to_bytes(uint32(position // 256)))
            byte_val = source[(position % 256) // 8]
            bit = (byte_val >> (position % 8)) % 2
            index = flip if bit else index
        return uint64(index)

    def compute_proposer_index(self, state, indices, seed) -> ValidatorIndex:
        """beacon-chain.md:799"""
        assert len(indices) > 0
        MAX_RANDOM_BYTE = 2**8 - 1
        i = uint64(0)
        total = uint64(len(indices))
        while True:
            candidate_index = indices[self.compute_shuffled_index(i % total, total, seed)]
            random_byte = hash(seed + uint_to_bytes(uint64(i // 32)))[i % 32]
            effective_balance = state.validators[candidate_index].effective_balance
            if effective_balance * MAX_RANDOM_BYTE >= self.MAX_EFFECTIVE_BALANCE * random_byte:
                return ValidatorIndex(candidate_index)
            i = uint64(i + 1)

    def compute_committee(self, indices, seed, index, count) -> Sequence[ValidatorIndex]:
        """beacon-chain.md:823"""
        start = (len(indices) * index) // count
        end = (len(indices) * (index + 1)) // count
        return [indices[self.compute_shuffled_index(uint64(i), uint64(len(indices)), seed)]
                for i in range(start, end)]

    def compute_epoch_at_slot(self, slot) -> Epoch:
        return Epoch(slot // self.SLOTS_PER_EPOCH)

    def compute_start_slot_at_epoch(self, epoch) -> Slot:
        return Slot(epoch * self.SLOTS_PER_EPOCH)

    def compute_activation_exit_epoch(self, epoch) -> Epoch:
        return Epoch(epoch + 1 + self.MAX_SEED_LOOKAHEAD)

    def compute_fork_data_root(self, current_version, genesis_validators_root) -> Root:
        return hash_tree_root(self.ForkData(
            current_version=current_version,
            genesis_validators_root=genesis_validators_root,
        ))

    def compute_fork_digest(self, current_version, genesis_validators_root) -> ForkDigest:
        return ForkDigest(
            self.compute_fork_data_root(current_version, genesis_validators_root)[:4])

    def compute_domain(self, domain_type, fork_version=None,
                       genesis_validators_root=None) -> Domain:
        """beacon-chain.md:890"""
        if fork_version is None:
            fork_version = self.config.GENESIS_FORK_VERSION
        if genesis_validators_root is None:
            genesis_validators_root = Root()
        fork_data_root = self.compute_fork_data_root(fork_version, genesis_validators_root)
        return Domain(bytes(domain_type) + fork_data_root[:28])

    def compute_signing_root(self, ssz_object, domain) -> Root:
        """beacon-chain.md:906"""
        return hash_tree_root(self.SigningData(
            object_root=hash_tree_root(ssz_object),
            domain=domain,
        ))

    # -- accessors ---------------------------------------------------------

    def get_current_epoch(self, state) -> Epoch:
        return self.compute_epoch_at_slot(state.slot)

    def get_previous_epoch(self, state) -> Epoch:
        current_epoch = self.get_current_epoch(state)
        return GENESIS_EPOCH if current_epoch == GENESIS_EPOCH else Epoch(current_epoch - 1)

    def get_block_root(self, state, epoch) -> Root:
        return self.get_block_root_at_slot(state, self.compute_start_slot_at_epoch(epoch))

    def get_block_root_at_slot(self, state, slot) -> Root:
        assert slot < state.slot <= slot + self.SLOTS_PER_HISTORICAL_ROOT
        return state.block_roots[slot % self.SLOTS_PER_HISTORICAL_ROOT]

    def get_randao_mix(self, state, epoch) -> Bytes32:
        return state.randao_mixes[epoch % self.EPOCHS_PER_HISTORICAL_VECTOR]

    def get_active_validator_indices(self, state, epoch) -> Sequence[ValidatorIndex]:
        key = (hash_tree_root(state.validators), epoch)
        cached = self._caches["active_indices"].get(key)
        if cached is None:
            cached = tuple(ValidatorIndex(i)
                           for i, v in enumerate(state.validators)
                           if self.is_active_validator(v, epoch))
            self._caches["active_indices"][key] = cached
        # an immutable tuple, returned without the old per-call O(n)
        # defensive list() copy: callers can index/iterate but cannot
        # poison the cache by mutating the returned sequence
        return cached

    def get_validator_churn_limit(self, state) -> uint64:
        active = self.get_active_validator_indices(state, self.get_current_epoch(state))
        return uint64(max(self.config.MIN_PER_EPOCH_CHURN_LIMIT,
                          len(active) // self.config.CHURN_LIMIT_QUOTIENT))

    def get_seed(self, state, epoch, domain_type) -> Bytes32:
        """beacon-chain.md (get_seed)"""
        mix = self.get_randao_mix(
            state, Epoch(epoch + self.EPOCHS_PER_HISTORICAL_VECTOR
                         - self.MIN_SEED_LOOKAHEAD - 1))
        return hash(bytes(domain_type) + uint_to_bytes(uint64(epoch)) + mix)

    def get_committee_count_per_slot(self, state, epoch) -> uint64:
        return uint64(max(1, min(
            self.MAX_COMMITTEES_PER_SLOT,
            len(self.get_active_validator_indices(state, epoch))
            // self.SLOTS_PER_EPOCH // self.TARGET_COMMITTEE_SIZE,
        )))

    def get_beacon_committee(self, state, slot, index) -> Sequence[ValidatorIndex]:
        """beacon-chain.md:1017; LRU-cached like pysetup/spec_builders/phase0.py:59-105"""
        key = (hash_tree_root(state.validators), hash_tree_root(state.randao_mixes),
               int(slot), int(index))
        cached = self._caches["committee"].get(key)
        if cached is None:
            epoch = self.compute_epoch_at_slot(slot)
            committees_per_slot = self.get_committee_count_per_slot(state, epoch)
            cached = tuple(self.compute_committee(
                indices=self.get_active_validator_indices(state, epoch),
                seed=self.get_seed(state, epoch, DOMAIN_BEACON_ATTESTER),
                index=(slot % self.SLOTS_PER_EPOCH) * committees_per_slot + index,
                count=committees_per_slot * self.SLOTS_PER_EPOCH,
            ))
            self._caches["committee"][key] = cached
        # immutable, uncopied: see get_active_validator_indices
        return cached

    def get_beacon_proposer_index(self, state) -> ValidatorIndex:
        key = (hash_tree_root(state.validators), hash_tree_root(state.randao_mixes),
               int(state.slot))
        cached = self._caches["proposer"].get(key)
        if cached is None:
            epoch = self.get_current_epoch(state)
            seed = hash(self.get_seed(state, epoch, DOMAIN_BEACON_PROPOSER)
                        + uint_to_bytes(uint64(state.slot)))
            indices = self.get_active_validator_indices(state, epoch)
            cached = self.compute_proposer_index(state, indices, seed)
            self._caches["proposer"][key] = cached
        return cached

    def get_total_balance(self, state, indices) -> Gwei:
        return Gwei(max(self.EFFECTIVE_BALANCE_INCREMENT,
                        sum(state.validators[index].effective_balance for index in indices)))

    def get_total_active_balance(self, state) -> Gwei:
        # root-keyed like the committee caches (reference analog:
        # pysetup's lru-cached get_total_active_balance): per-validator
        # reward loops call this once per index, and the O(validators)
        # sum would otherwise make every epoch function quadratic
        key = (hash_tree_root(state.validators), self.get_current_epoch(state))
        cached = self._caches["total_balance"].get(key)
        if cached is None:
            cached = self.get_total_balance(
                state,
                set(self.get_active_validator_indices(
                    state, self.get_current_epoch(state))))
            self._caches["total_balance"][key] = cached
        return cached

    def get_domain(self, state, domain_type, epoch=None) -> Domain:
        epoch = self.get_current_epoch(state) if epoch is None else epoch
        fork_version = (state.fork.previous_version if epoch < state.fork.epoch
                        else state.fork.current_version)
        return self.compute_domain(domain_type, fork_version, state.genesis_validators_root)

    def get_indexed_attestation(self, state, attestation):
        """beacon-chain.md:1085"""
        attesting_indices = self.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)
        return self.IndexedAttestation(
            attesting_indices=sorted(attesting_indices),
            data=attestation.data,
            signature=attestation.signature,
        )

    def get_attesting_indices(self, state, data, bits) -> Set[ValidatorIndex]:
        """beacon-chain.md:1101"""
        committee = self.get_beacon_committee(state, data.slot, data.index)
        return set(index for i, index in enumerate(committee) if bits[i])

    # -- mutators ----------------------------------------------------------

    def increase_balance(self, state, index, delta) -> None:
        state.balances[index] += delta

    def decrease_balance(self, state, index, delta) -> None:
        state.balances[index] = (
            0 if delta > state.balances[index] else state.balances[index] - delta)

    def initiate_validator_exit(self, state, index) -> None:
        """beacon-chain.md:1133"""
        validator = state.validators[index]
        if validator.exit_epoch != FAR_FUTURE_EPOCH:
            return
        exit_epochs = [v.exit_epoch for v in state.validators
                       if v.exit_epoch != FAR_FUTURE_EPOCH]
        exit_queue_epoch = max(
            exit_epochs + [self.compute_activation_exit_epoch(self.get_current_epoch(state))])
        exit_queue_churn = len(
            [v for v in state.validators if v.exit_epoch == exit_queue_epoch])
        if exit_queue_churn >= self.get_validator_churn_limit(state):
            exit_queue_epoch = Epoch(exit_queue_epoch + 1)
        validator.exit_epoch = exit_queue_epoch
        validator.withdrawable_epoch = Epoch(
            validator.exit_epoch + self.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)

    def slash_validator(self, state, slashed_index, whistleblower_index=None) -> None:
        """beacon-chain.md:1157"""
        epoch = self.get_current_epoch(state)
        self.initiate_validator_exit(state, slashed_index)
        validator = state.validators[slashed_index]
        validator.slashed = True
        validator.withdrawable_epoch = max(
            validator.withdrawable_epoch, Epoch(epoch + self.EPOCHS_PER_SLASHINGS_VECTOR))
        state.slashings[epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] += validator.effective_balance
        slashing_penalty = validator.effective_balance // self.MIN_SLASHING_PENALTY_QUOTIENT
        self.decrease_balance(state, slashed_index, slashing_penalty)

        proposer_index = self.get_beacon_proposer_index(state)
        if whistleblower_index is None:
            whistleblower_index = proposer_index
        whistleblower_reward = Gwei(
            validator.effective_balance // self.WHISTLEBLOWER_REWARD_QUOTIENT)
        proposer_reward = Gwei(whistleblower_reward // self.PROPOSER_REWARD_QUOTIENT)
        self.increase_balance(state, proposer_index, proposer_reward)
        self.increase_balance(
            state, whistleblower_index, Gwei(whistleblower_reward - proposer_reward))

    # ======================================================================
    # Genesis (beacon-chain.md:1195)
    # ======================================================================

    def initialize_beacon_state_from_eth1(self, eth1_block_hash, eth1_timestamp, deposits):
        fork = self.Fork(
            previous_version=self.config.GENESIS_FORK_VERSION,
            current_version=self.config.GENESIS_FORK_VERSION,
            epoch=GENESIS_EPOCH,
        )
        state = self.BeaconState(
            genesis_time=eth1_timestamp + self.config.GENESIS_DELAY,
            fork=fork,
            eth1_data=self.Eth1Data(
                block_hash=eth1_block_hash, deposit_count=uint64(len(deposits))),
            latest_block_header=self.BeaconBlockHeader(
                body_root=hash_tree_root(self.BeaconBlockBody())),
            randao_mixes=[eth1_block_hash] * self.EPOCHS_PER_HISTORICAL_VECTOR,
        )
        # Process genesis deposits
        leaves = [d.data for d in deposits]
        DepositDataList = List[self.DepositData, 2**(DEPOSIT_CONTRACT_TREE_DEPTH)]
        for index, deposit in enumerate(deposits):
            deposit_data_list = DepositDataList(leaves[:index + 1])
            state.eth1_data.deposit_root = hash_tree_root(deposit_data_list)
            self.process_deposit(state, deposit)
        # Process activations
        for index, validator in enumerate(state.validators):
            balance = state.balances[index]
            validator.effective_balance = min(
                balance - balance % self.EFFECTIVE_BALANCE_INCREMENT,
                self.MAX_EFFECTIVE_BALANCE)
            if validator.effective_balance == self.MAX_EFFECTIVE_BALANCE:
                validator.activation_eligibility_epoch = GENESIS_EPOCH
                validator.activation_epoch = GENESIS_EPOCH
        # Set genesis validators root for domain separation and chain versioning
        state.genesis_validators_root = hash_tree_root(state.validators)
        return state

    def is_valid_genesis_state(self, state) -> bool:
        if state.genesis_time < self.config.MIN_GENESIS_TIME:
            return False
        if len(self.get_active_validator_indices(state, GENESIS_EPOCH)) \
                < self.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT:
            return False
        return True

    # ======================================================================
    # State transition (beacon-chain.md:1256)
    # ======================================================================

    def state_transition(self, state, signed_block, validate_result=True) -> None:
        block = signed_block.message
        # Process slots (including those with no blocks) since block
        self.process_slots(state, block.slot)
        # One batched signature dispatch covers the proposer signature and
        # the whole block body (see utils/bls.py batched_verification).
        with bls.batched_verification() as batch:
            # Verify signature
            if validate_result:
                assert self.verify_block_signature(state, signed_block)
            # Process block
            self.process_block(state, block)
        batch.assert_valid()
        # Verify state root
        if validate_result:
            with hash_forest():
                assert block.state_root == hash_tree_root(state)

    def verify_block_signature(self, state, signed_block) -> bool:
        proposer = state.validators[signed_block.message.proposer_index]
        signing_root = self.compute_signing_root(
            signed_block.message, self.get_domain(state, DOMAIN_BEACON_PROPOSER))
        return bls.Verify(proposer.pubkey, signing_root, signed_block.signature)

    # Epoch transitions run inside a StateArrays commit scope: the
    # engine's balance-family column writes flush back to SSZ chunks
    # ONCE at scope exit instead of once per sub-transition.  Forks
    # whose epoch ordering interleaves non-engine balance writes between
    # the engine sub-transitions (custody_game's reveal/challenge
    # deadlines) opt out by overriding this to False.
    _defer_epoch_commits = True

    def process_slots(self, state, slot) -> None:
        assert state.slot < slot
        while state.slot < slot:
            self.process_slot(state)
            # Process epoch on the start slot of the next epoch
            if (state.slot + 1) % self.SLOTS_PER_EPOCH == 0:
                if self._defer_epoch_commits:
                    with state_arrays.commit_scope(state):
                        self.process_epoch(state)
                else:
                    self.process_epoch(state)
            state.slot = Slot(state.slot + 1)

    def process_slot(self, state) -> None:
        # Cache state root.  The forest scope batches the dirty re-hash
        # level-aligned across every mutated tree of the state (balances,
        # roots vectors, registry, ...) — see utils/ssz/forest.py.
        with hash_forest():
            previous_state_root = hash_tree_root(state)
        state.state_roots[state.slot % self.SLOTS_PER_HISTORICAL_ROOT] = previous_state_root
        # Cache latest block header state root
        if state.latest_block_header.state_root == Bytes32():
            state.latest_block_header.state_root = previous_state_root
        # Cache block root
        state.block_roots[state.slot % self.SLOTS_PER_HISTORICAL_ROOT] = \
            hash_tree_root(state.latest_block_header)

    # -- epoch processing --------------------------------------------------

    def process_epoch(self, state) -> None:
        """beacon-chain.md:1304"""
        self.process_justification_and_finalization(state)
        self.process_rewards_and_penalties(state)
        self.process_registry_updates(state)
        self.process_slashings(state)
        self.process_eth1_data_reset(state)
        self.process_effective_balance_updates(state)
        self.process_slashings_reset(state)
        self.process_randao_mixes_reset(state)
        self.process_historical_roots_update(state)
        self.process_participation_record_updates(state)

    def get_matching_source_attestations(self, state, epoch):
        assert epoch in (self.get_previous_epoch(state), self.get_current_epoch(state))
        return (state.current_epoch_attestations
                if epoch == self.get_current_epoch(state)
                else state.previous_epoch_attestations)

    def get_matching_target_attestations(self, state, epoch):
        return [a for a in self.get_matching_source_attestations(state, epoch)
                if a.data.target.root == self.get_block_root(state, epoch)]

    def get_matching_head_attestations(self, state, epoch):
        return [a for a in self.get_matching_target_attestations(state, epoch)
                if a.data.beacon_block_root == self.get_block_root_at_slot(state, a.data.slot)]

    def get_unslashed_attesting_indices(self, state, attestations) -> Set[ValidatorIndex]:
        output = set()
        for a in attestations:
            output = output.union(
                self.get_attesting_indices(state, a.data, a.aggregation_bits))
        return set(filter(lambda index: not state.validators[index].slashed, output))

    def get_attesting_balance(self, state, attestations) -> Gwei:
        return self.get_total_balance(
            state, self.get_unslashed_attesting_indices(state, attestations))

    def process_justification_and_finalization(self, state) -> None:
        """beacon-chain.md:1359"""
        # Initial FFG checkpoint values have a `0x00` stub for `root`.
        # Skip FFG updates in the first two epochs to avoid corner cases.
        if self.get_current_epoch(state) <= GENESIS_EPOCH + 1:
            return
        previous_attestations = self.get_matching_target_attestations(
            state, self.get_previous_epoch(state))
        current_attestations = self.get_matching_target_attestations(
            state, self.get_current_epoch(state))
        total_active_balance = self.get_total_active_balance(state)
        previous_target_balance = self.get_attesting_balance(state, previous_attestations)
        current_target_balance = self.get_attesting_balance(state, current_attestations)
        self.weigh_justification_and_finalization(
            state, total_active_balance, previous_target_balance, current_target_balance)

    def weigh_justification_and_finalization(self, state, total_active_balance,
                                             previous_epoch_target_balance,
                                             current_epoch_target_balance) -> None:
        previous_epoch = self.get_previous_epoch(state)
        current_epoch = self.get_current_epoch(state)
        old_previous_justified_checkpoint = state.previous_justified_checkpoint
        old_current_justified_checkpoint = state.current_justified_checkpoint

        # Process justifications
        state.previous_justified_checkpoint = state.current_justified_checkpoint
        bits = list(state.justification_bits)
        state.justification_bits = [False] + bits[:JUSTIFICATION_BITS_LENGTH - 1]
        if previous_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=previous_epoch, root=self.get_block_root(state, previous_epoch))
            state.justification_bits[1] = True
        if current_epoch_target_balance * 3 >= total_active_balance * 2:
            state.current_justified_checkpoint = self.Checkpoint(
                epoch=current_epoch, root=self.get_block_root(state, current_epoch))
            state.justification_bits[0] = True

        # Process finalizations
        bits = state.justification_bits
        # The 2nd/3rd/4th most recent epochs are justified, the 2nd using the 4th as source
        if all(bits[1:4]) and old_previous_justified_checkpoint.epoch + 3 == current_epoch:
            state.finalized_checkpoint = old_previous_justified_checkpoint
        if all(bits[1:3]) and old_previous_justified_checkpoint.epoch + 2 == current_epoch:
            state.finalized_checkpoint = old_previous_justified_checkpoint
        if all(bits[0:3]) and old_current_justified_checkpoint.epoch + 2 == current_epoch:
            state.finalized_checkpoint = old_current_justified_checkpoint
        if all(bits[0:2]) and old_current_justified_checkpoint.epoch + 1 == current_epoch:
            state.finalized_checkpoint = old_current_justified_checkpoint

    # -- rewards and penalties (beacon-chain.md:1414) ----------------------

    def get_base_reward(self, state, index) -> Gwei:
        total_balance = self.get_total_active_balance(state)
        effective_balance = state.validators[index].effective_balance
        return Gwei(effective_balance * self.BASE_REWARD_FACTOR
                    // self.integer_squareroot(total_balance) // BASE_REWARDS_PER_EPOCH)

    def get_proposer_reward(self, state, attesting_index) -> Gwei:
        return Gwei(self.get_base_reward(state, attesting_index)
                    // self.PROPOSER_REWARD_QUOTIENT)

    def get_finality_delay(self, state) -> uint64:
        return self.get_previous_epoch(state) - state.finalized_checkpoint.epoch

    def is_in_inactivity_leak(self, state) -> bool:
        return self.get_finality_delay(state) > self.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    def get_eligible_validator_indices(self, state) -> Sequence[ValidatorIndex]:
        previous_epoch = self.get_previous_epoch(state)
        return [ValidatorIndex(index) for index, v in enumerate(state.validators)
                if self.is_active_validator(v, previous_epoch)
                or (v.slashed and previous_epoch + 1 < v.withdrawable_epoch)]

    def get_attestation_component_deltas(self, state, attestations):
        """Helper with shared logic for use by get source/target/head deltas"""
        rewards = [Gwei(0)] * len(state.validators)
        penalties = [Gwei(0)] * len(state.validators)
        total_balance = self.get_total_active_balance(state)
        unslashed_attesting_indices = self.get_unslashed_attesting_indices(
            state, attestations)
        attesting_balance = self.get_total_balance(state, unslashed_attesting_indices)
        for index in self.get_eligible_validator_indices(state):
            if index in unslashed_attesting_indices:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                if self.is_in_inactivity_leak(state):
                    # Full base reward will be canceled out by inactivity penalty deltas
                    rewards[index] += self.get_base_reward(state, index)
                else:
                    reward_numerator = self.get_base_reward(state, index) \
                        * (attesting_balance // increment)
                    rewards[index] += reward_numerator // (total_balance // increment)
            else:
                penalties[index] += self.get_base_reward(state, index)
        return rewards, penalties

    def get_source_deltas(self, state):
        matching_source_attestations = self.get_matching_source_attestations(
            state, self.get_previous_epoch(state))
        return self.get_attestation_component_deltas(state, matching_source_attestations)

    def get_target_deltas(self, state):
        matching_target_attestations = self.get_matching_target_attestations(
            state, self.get_previous_epoch(state))
        return self.get_attestation_component_deltas(state, matching_target_attestations)

    def get_head_deltas(self, state):
        matching_head_attestations = self.get_matching_head_attestations(
            state, self.get_previous_epoch(state))
        return self.get_attestation_component_deltas(state, matching_head_attestations)

    def get_inclusion_delay_deltas(self, state):
        rewards = [Gwei(0)] * len(state.validators)
        matching_source_attestations = self.get_matching_source_attestations(
            state, self.get_previous_epoch(state))
        for index in self.get_unslashed_attesting_indices(state, matching_source_attestations):
            attestation = min([
                a for a in matching_source_attestations
                if index in self.get_attesting_indices(state, a.data, a.aggregation_bits)
            ], key=lambda a: a.inclusion_delay)
            rewards[attestation.proposer_index] += self.get_proposer_reward(state, index)
            max_attester_reward = Gwei(
                self.get_base_reward(state, index) - self.get_proposer_reward(state, index))
            rewards[index] += Gwei(max_attester_reward // attestation.inclusion_delay)
        # No penalties associated with inclusion delay
        penalties = [Gwei(0)] * len(state.validators)
        return rewards, penalties

    def get_inactivity_penalty_deltas(self, state):
        penalties = [Gwei(0)] * len(state.validators)
        if self.is_in_inactivity_leak(state):
            matching_target_attestations = self.get_matching_target_attestations(
                state, self.get_previous_epoch(state))
            matching_target_attesting_indices = self.get_unslashed_attesting_indices(
                state, matching_target_attestations)
            for index in self.get_eligible_validator_indices(state):
                # If validator is performing optimally this cancels all rewards for a neutral balance
                base_reward = self.get_base_reward(state, index)
                penalties[index] += Gwei(
                    BASE_REWARDS_PER_EPOCH * base_reward
                    - self.get_proposer_reward(state, index))
                if index not in matching_target_attesting_indices:
                    effective_balance = state.validators[index].effective_balance
                    penalties[index] += Gwei(
                        effective_balance * self.get_finality_delay(state)
                        // self.INACTIVITY_PENALTY_QUOTIENT)
        rewards = [Gwei(0)] * len(state.validators)
        return rewards, penalties

    def get_attestation_deltas(self, state):
        source_rewards, source_penalties = self.get_source_deltas(state)
        target_rewards, target_penalties = self.get_target_deltas(state)
        head_rewards, head_penalties = self.get_head_deltas(state)
        inclusion_delay_rewards, _ = self.get_inclusion_delay_deltas(state)
        _, inactivity_penalties = self.get_inactivity_penalty_deltas(state)
        rewards = [source_rewards[i] + target_rewards[i] + head_rewards[i]
                   + inclusion_delay_rewards[i] for i in range(len(state.validators))]
        penalties = [source_penalties[i] + target_penalties[i] + head_penalties[i]
                     + inactivity_penalties[i] for i in range(len(state.validators))]
        return rewards, penalties

    def process_rewards_and_penalties(self, state) -> None:
        if epoch_kernels.try_process_rewards_and_penalties(self, state):
            return
        # No rewards are applied at the end of `GENESIS_EPOCH` because rewards
        # are for work done in the previous epoch
        if self.get_current_epoch(state) == GENESIS_EPOCH:
            return
        rewards, penalties = self.get_attestation_deltas(state)
        for index in range(len(state.validators)):
            self.increase_balance(state, ValidatorIndex(index), rewards[index])
            self.decrease_balance(state, ValidatorIndex(index), penalties[index])

    # -- registry / slashings / resets -------------------------------------

    def process_registry_updates(self, state) -> None:
        """beacon-chain.md:1592"""
        if epoch_kernels.try_process_registry_updates(self, state):
            return
        # Process activation eligibility and ejections
        for index, validator in enumerate(state.validators):
            if self.is_eligible_for_activation_queue(validator):
                validator.activation_eligibility_epoch = Epoch(
                    self.get_current_epoch(state) + 1)
            if (self.is_active_validator(validator, self.get_current_epoch(state))
                    and validator.effective_balance <= self.config.EJECTION_BALANCE):
                self.initiate_validator_exit(state, ValidatorIndex(index))
        # Queue validators eligible for activation and not yet dequeued for activation
        activation_queue = sorted([
            index for index, validator in enumerate(state.validators)
            if self.is_eligible_for_activation(state, validator)
            # Order by the sequence of activation_eligibility_epoch setting and then index
        ], key=lambda index: (state.validators[index].activation_eligibility_epoch, index))
        # Dequeued validators for activation up to churn limit
        for index in activation_queue[:self.get_validator_churn_limit(state)]:
            validator = state.validators[index]
            validator.activation_epoch = self.compute_activation_exit_epoch(
                self.get_current_epoch(state))

    def process_slashings(self, state) -> None:
        """beacon-chain.md:1619"""
        if epoch_kernels.try_process_slashings(self, state):
            return
        epoch = self.get_current_epoch(state)
        total_balance = self.get_total_active_balance(state)
        adjusted_total_slashing_balance = min(
            sum(state.slashings) * self.PROPORTIONAL_SLASHING_MULTIPLIER, total_balance)
        for index, validator in enumerate(state.validators):
            if validator.slashed and epoch + self.EPOCHS_PER_SLASHINGS_VECTOR // 2 \
                    == validator.withdrawable_epoch:
                increment = self.EFFECTIVE_BALANCE_INCREMENT
                penalty_numerator = (validator.effective_balance // increment
                                     * adjusted_total_slashing_balance)
                penalty = penalty_numerator // total_balance * increment
                self.decrease_balance(state, ValidatorIndex(index), penalty)

    def process_eth1_data_reset(self, state) -> None:
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        if next_epoch % self.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
            state.eth1_data_votes = type(state.eth1_data_votes)()

    def process_effective_balance_updates(self, state) -> None:
        if epoch_kernels.try_process_effective_balance_updates(self, state):
            return
        for index, validator in enumerate(state.validators):
            balance = state.balances[index]
            HYSTERESIS_INCREMENT = uint64(
                self.EFFECTIVE_BALANCE_INCREMENT // self.HYSTERESIS_QUOTIENT)
            DOWNWARD_THRESHOLD = HYSTERESIS_INCREMENT * self.HYSTERESIS_DOWNWARD_MULTIPLIER
            UPWARD_THRESHOLD = HYSTERESIS_INCREMENT * self.HYSTERESIS_UPWARD_MULTIPLIER
            if (balance + DOWNWARD_THRESHOLD < validator.effective_balance
                    or validator.effective_balance + UPWARD_THRESHOLD < balance):
                validator.effective_balance = min(
                    balance - balance % self.EFFECTIVE_BALANCE_INCREMENT,
                    self.MAX_EFFECTIVE_BALANCE)

    def process_slashings_reset(self, state) -> None:
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        state.slashings[next_epoch % self.EPOCHS_PER_SLASHINGS_VECTOR] = Gwei(0)

    def process_randao_mixes_reset(self, state) -> None:
        current_epoch = self.get_current_epoch(state)
        next_epoch = Epoch(current_epoch + 1)
        state.randao_mixes[next_epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = \
            self.get_randao_mix(state, current_epoch)

    def process_historical_roots_update(self, state) -> None:
        next_epoch = Epoch(self.get_current_epoch(state) + 1)
        if next_epoch % (self.SLOTS_PER_HISTORICAL_ROOT // self.SLOTS_PER_EPOCH) == 0:
            historical_batch = self.HistoricalBatch(
                block_roots=state.block_roots, state_roots=state.state_roots)
            state.historical_roots.append(hash_tree_root(historical_batch))

    def process_participation_record_updates(self, state) -> None:
        state.previous_epoch_attestations = state.current_epoch_attestations
        state.current_epoch_attestations = type(state.current_epoch_attestations)()

    # ======================================================================
    # Block processing (beacon-chain.md:1701)
    # ======================================================================

    def process_block(self, state, block) -> None:
        # Batch the block's assert-style signature checks (randao +
        # slashings + up to MAX_ATTESTATIONS aggregates + exits) into one
        # device dispatch — the TPU-native replacement for the reference's
        # serial per-operation FFI loop (beacon-chain.md:1757-1774).
        with bls.batched_verification() as batch:
            self.process_block_header(state, block)
            self.process_randao(state, block.body)
            self.process_eth1_data(state, block.body)
            self.process_operations(state, block.body)
        batch.assert_valid()

    def process_block_header(self, state, block) -> None:
        # Verify that the slots match
        assert block.slot == state.slot
        # Verify that the block is newer than latest block header
        assert block.slot > state.latest_block_header.slot
        # Verify that proposer index is the correct index
        assert block.proposer_index == self.get_beacon_proposer_index(state)
        # Verify that the parent matches
        assert block.parent_root == hash_tree_root(state.latest_block_header)
        # Cache current block as the new latest block
        state.latest_block_header = self.BeaconBlockHeader(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=Bytes32(),  # Overwritten in the next process_slot call
            body_root=hash_tree_root(block.body),
        )
        # Verify proposer is not slashed
        proposer = state.validators[block.proposer_index]
        assert not proposer.slashed

    def process_randao(self, state, body) -> None:
        epoch = self.get_current_epoch(state)
        # Verify RANDAO reveal
        proposer = state.validators[self.get_beacon_proposer_index(state)]
        signing_root = self.compute_signing_root(
            uint64(epoch), self.get_domain(state, DOMAIN_RANDAO))
        assert bls.Verify(proposer.pubkey, signing_root, body.randao_reveal)
        # Mix in RANDAO reveal
        mix = self.xor(self.get_randao_mix(state, epoch), hash(body.randao_reveal))
        state.randao_mixes[epoch % self.EPOCHS_PER_HISTORICAL_VECTOR] = mix

    def process_eth1_data(self, state, body) -> None:
        state.eth1_data_votes.append(body.eth1_data)
        if list(state.eth1_data_votes).count(body.eth1_data) * 2 \
                > self.EPOCHS_PER_ETH1_VOTING_PERIOD * self.SLOTS_PER_EPOCH:
            state.eth1_data = body.eth1_data

    def process_operations(self, state, body) -> None:
        """beacon-chain.md:1757"""
        # Verify that outstanding deposits are processed up to the maximum
        assert len(body.deposits) == min(
            self.MAX_DEPOSITS,
            state.eth1_data.deposit_count - state.eth1_deposit_index)

        def for_ops(operations, fn):
            for operation in operations:
                fn(state, operation)

        for_ops(body.proposer_slashings, self.process_proposer_slashing)
        for_ops(body.attester_slashings, self.process_attester_slashing)
        for_ops(body.attestations, self.process_attestation)
        for_ops(body.deposits, self.process_deposit)
        for_ops(body.voluntary_exits, self.process_voluntary_exit)

    def process_proposer_slashing(self, state, proposer_slashing) -> None:
        header_1 = proposer_slashing.signed_header_1.message
        header_2 = proposer_slashing.signed_header_2.message
        # Verify header slots match
        assert header_1.slot == header_2.slot
        # Verify header proposer indices match
        assert header_1.proposer_index == header_2.proposer_index
        # Verify the headers are different
        assert header_1 != header_2
        # Verify the proposer is slashable
        proposer = state.validators[header_1.proposer_index]
        assert self.is_slashable_validator(proposer, self.get_current_epoch(state))
        # Verify signatures
        for signed_header in (proposer_slashing.signed_header_1,
                              proposer_slashing.signed_header_2):
            domain = self.get_domain(
                state, DOMAIN_BEACON_PROPOSER,
                self.compute_epoch_at_slot(signed_header.message.slot))
            signing_root = self.compute_signing_root(signed_header.message, domain)
            assert bls.Verify(proposer.pubkey, signing_root, signed_header.signature)
        self.slash_validator(state, header_1.proposer_index)

    def process_attester_slashing(self, state, attester_slashing) -> None:
        attestation_1 = attester_slashing.attestation_1
        attestation_2 = attester_slashing.attestation_2
        assert self.is_slashable_attestation_data(attestation_1.data, attestation_2.data)
        assert self.is_valid_indexed_attestation(state, attestation_1)
        assert self.is_valid_indexed_attestation(state, attestation_2)

        slashed_any = False
        indices = set(attestation_1.attesting_indices).intersection(
            attestation_2.attesting_indices)
        for index in sorted(indices):
            if self.is_slashable_validator(
                    state.validators[index], self.get_current_epoch(state)):
                self.slash_validator(state, index)
                slashed_any = True
        assert slashed_any

    def process_attestation(self, state, attestation) -> None:
        """beacon-chain.md:1822"""
        data = attestation.data
        assert data.target.epoch in (
            self.get_previous_epoch(state), self.get_current_epoch(state))
        assert data.target.epoch == self.compute_epoch_at_slot(data.slot)
        assert data.slot + self.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot \
            <= data.slot + self.SLOTS_PER_EPOCH
        assert data.index < self.get_committee_count_per_slot(state, data.target.epoch)

        committee = self.get_beacon_committee(state, data.slot, data.index)
        assert len(attestation.aggregation_bits) == len(committee)

        pending_attestation = self.PendingAttestation(
            data=data,
            aggregation_bits=attestation.aggregation_bits,
            inclusion_delay=state.slot - data.slot,
            proposer_index=self.get_beacon_proposer_index(state),
        )

        if data.target.epoch == self.get_current_epoch(state):
            assert data.source == state.current_justified_checkpoint
            state.current_epoch_attestations.append(pending_attestation)
        else:
            assert data.source == state.previous_justified_checkpoint
            state.previous_epoch_attestations.append(pending_attestation)

        # Verify signature
        assert self.is_valid_indexed_attestation(
            state, self.get_indexed_attestation(state, attestation))

    def get_validator_from_deposit(self, pubkey, withdrawal_credentials, amount):
        """beacon-chain.md:1853"""
        effective_balance = min(
            amount - amount % self.EFFECTIVE_BALANCE_INCREMENT, self.MAX_EFFECTIVE_BALANCE)
        return self.Validator(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            activation_eligibility_epoch=FAR_FUTURE_EPOCH,
            activation_epoch=FAR_FUTURE_EPOCH,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
            effective_balance=effective_balance,
        )

    def add_validator_to_registry(self, state, pubkey, withdrawal_credentials, amount) -> None:
        state.validators.append(
            self.get_validator_from_deposit(pubkey, withdrawal_credentials, amount))
        state.balances.append(amount)

    def apply_deposit(self, state, pubkey, withdrawal_credentials, amount, signature) -> None:
        """beacon-chain.md:1877"""
        validator_pubkeys = [v.pubkey for v in state.validators]
        if pubkey not in validator_pubkeys:
            # Verify the deposit signature (proof of possession) which is not
            # checked by the deposit contract
            deposit_message = self.DepositMessage(
                pubkey=pubkey,
                withdrawal_credentials=withdrawal_credentials,
                amount=amount,
            )
            # Fork-agnostic domain since deposits are valid across forks
            domain = self.compute_domain(DOMAIN_DEPOSIT)
            signing_root = self.compute_signing_root(deposit_message, domain)
            # Eager: this boolean steers state (invalid PoP skips the
            # validator, it does NOT invalidate the block) so it cannot
            # join the deferred block batch.
            if bls.VerifyEager(pubkey, signing_root, signature):
                self.add_validator_to_registry(
                    state, pubkey, withdrawal_credentials, amount)
        else:
            # Increase balance by deposit amount
            index = ValidatorIndex(validator_pubkeys.index(pubkey))
            self.increase_balance(state, index, amount)

    def process_deposit(self, state, deposit) -> None:
        """beacon-chain.md:1901"""
        # Verify the Merkle branch
        assert self.is_valid_merkle_branch(
            leaf=hash_tree_root(deposit.data),
            branch=deposit.proof,
            depth=DEPOSIT_CONTRACT_TREE_DEPTH + 1,  # add 1 for the List length mix-in
            index=state.eth1_deposit_index,
            root=state.eth1_data.deposit_root,
        )
        # Deposits must be processed in order
        state.eth1_deposit_index += 1
        self.apply_deposit(
            state=state,
            pubkey=deposit.data.pubkey,
            withdrawal_credentials=deposit.data.withdrawal_credentials,
            amount=deposit.data.amount,
            signature=deposit.data.signature,
        )

    def process_voluntary_exit(self, state, signed_voluntary_exit) -> None:
        voluntary_exit = signed_voluntary_exit.message
        validator = state.validators[voluntary_exit.validator_index]
        # Verify the validator is active
        assert self.is_active_validator(validator, self.get_current_epoch(state))
        # Verify exit has not been initiated
        assert validator.exit_epoch == FAR_FUTURE_EPOCH
        # Exits must specify an epoch when they become valid; they are not valid before then
        assert self.get_current_epoch(state) >= voluntary_exit.epoch
        # Verify the validator has been active long enough
        assert self.get_current_epoch(state) >= validator.activation_epoch \
            + self.config.SHARD_COMMITTEE_PERIOD
        # Verify signature
        domain = self.get_domain(state, DOMAIN_VOLUNTARY_EXIT, voluntary_exit.epoch)
        signing_root = self.compute_signing_root(voluntary_exit, domain)
        assert bls.Verify(validator.pubkey, signing_root, signed_voluntary_exit.signature)
        # Initiate exit
        self.initiate_validator_exit(state, voluntary_exit.validator_index)
