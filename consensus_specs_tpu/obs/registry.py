"""Metrics registry: typed, labeled counters / gauges / histograms.

The engine stack's dispatch accounting used to live in three private
module dicts (``utils/ssz/merkle._stats``, ``forkchoice/proto_array
._stats``, ``ops/epoch_kernels._stats``); this registry unifies them
into named, labeled series so exporters (``obs/export.py``), the span
tracer (``obs/tracing.py``) and the bench smokes read one surface::

    from consensus_specs_tpu.obs import registry

    _HEADS_ENGINE = registry.counter("forkchoice.head").labels(path="engine")
    ...
    _HEADS_ENGINE.add()          # hot path: a single int add

Hot-path contract (enforced by the speclint O5xx pass): series are
resolved ONCE at module import (``counter(name).labels(**kv)``) and the
per-event cost is one bound-attribute integer add.  ``counter()``
/ ``labels()`` involve dict lookups and a lock and must never sit on a
per-pair / per-validator path.

Thread model (the serving pipeline bumps handles from both the main
thread and the flush-worker lane concurrently):

* **Counter adds are lock-free and lose nothing under the GIL.**
  ``self.n += n`` compiles to a load/add/store run with no call and no
  backward jump between the load and the store — exactly the points
  where CPython's eval-breaker can hand the GIL to another thread — so
  the read-modify-write cannot be preempted mid-flight and two threads
  hammering one handle drop zero increments
  (``tests/test_observability.py::test_counter_hammer_two_threads``
  pins this empirically).  The value can never tear either way: ints
  are immutable objects, the slot store is atomic.
* **Histogram observations take a per-series lock.**  ``observe``
  mutates five fields and loops over the bucket bounds; the loop's
  backward jumps ARE preemption points, so without the lock a
  concurrent pair of observations could interleave (count drift,
  torn min/max).  Histogram sites are per-window / per-block — never
  per-pair — so the ~100ns lock is off the O5xx-guarded paths.
* **Snapshot readers copy before iterating.**  ``counter_values`` /
  ``snapshot`` / ``reset`` materialize the live dicts via C-level
  ``list()``/``sorted()`` (atomic under the GIL) before walking them,
  so a scrape racing a first-time ``labels()`` registration never sees
  "dictionary changed size during iteration".

Counters are always on: the differential suites assert on them to prove
which engine actually answered, so they cannot hide behind an env flag.
``CS_TPU_PROFILE`` / ``CS_TPU_TRACE`` gate the *span* machinery only
(``obs/tracing.py``).

Snapshots (:func:`snapshot`) are plain nested dicts, deep-copied —
mutating one never writes back into the registry.  :func:`reset` zeroes
series **in place** so module-held bound series keep working.
"""
import threading

_lock = threading.Lock()
# speclint: cost: bounded: one entry per metric NAME (static set)
_metrics = {}           # name -> Counter | Gauge | Histogram


class _CounterSeries:
    """One labeled counter time series.  ``add`` is the hot-path entry:
    a single GIL-relying int add, no locks, no lookups."""

    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def add(self, n=1):
        self.n += n

    def _reset(self):
        self.n = 0

    def _value(self):
        return self.n


class _GaugeSeries:
    """One labeled gauge series: last-set value plus a running-max
    helper (``set_max``) for high-watermark style gauges."""

    __slots__ = ("v",)

    def __init__(self):
        self.v = 0

    def set(self, v):
        self.v = v

    def set_max(self, v):
        if v > self.v:
            self.v = v

    def _reset(self):
        self.v = 0

    def _value(self):
        return self.v


# Default histogram buckets: sub-ms to minutes, a wall-clock-seconds
# shape (the main histogram customers are span-adjacent timings).
DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0)


class _HistogramSeries:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max",
                 "_hlock")

    def __init__(self, buckets):
        self.buckets = buckets
        self._hlock = threading.Lock()
        self._reset()

    def observe(self, v):
        # multi-field update with preemption points (the bucket loop's
        # backward jumps) — locked, unlike counter adds; see the thread
        # model in the module docstring
        with self._hlock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self.counts[i] += 1
                    break
            else:
                self.counts[-1] += 1     # +Inf overflow bucket

    def _reset(self):
        with self._hlock:
            self.counts = [0] * (len(self.buckets) + 1)
            self.count = 0
            self.sum = 0.0
            self.min = None
            self.max = None

    def quantile(self, q: float):
        """Bucket-interpolated quantile estimate (the
        ``histogram_quantile`` rule: linear within the landing bucket),
        sharpened by the tracked ``min``/``max`` — the first bucket
        interpolates up from the true minimum, the overflow bucket from
        its lower bound to the true maximum, and the result is clamped
        to the observed range.  None when nothing was observed."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            if cum + n >= rank:
                lo = self.min if i == 0 else self.buckets[i - 1]
                hi = self.max if i == len(self.buckets) else self.buckets[i]
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi <= lo:
                    return lo
                frac = (rank - cum) / n
                return min(max(lo + (hi - lo) * frac, self.min), self.max)
            cum += n
        return self.max

    def _value(self):
        # bucket keys as strings ("0.1" ... "+Inf"): keeps the snapshot
        # JSON-sortable and maps 1:1 onto Prometheus ``le`` label values
        keys = [str(b) for b in self.buckets] + ["+Inf"]
        with self._hlock:     # consistent multi-field view vs observe()
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    "p50": self.quantile(0.5), "p90": self.quantile(0.9),
                    "p99": self.quantile(0.99),
                    "buckets": dict(zip(keys, self.counts))}


def _label_key(kv: dict) -> tuple:
    """Canonical, hashable identity of a label set."""
    return tuple(sorted((str(k), str(v)) for k, v in kv.items()))


def render_labels(key: tuple) -> str:
    """``{k=v,...}`` suffix used in snapshots / test assertions; empty
    string for the unlabeled series."""
    if not key:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class _Metric:
    """Shared series-table plumbing; subclasses pick the series type."""

    kind = None
    _series_cls = None

    def __init__(self, name: str):
        self.name = name
        self._series = {}    # label key tuple -> series

    def _make_series(self):
        return self._series_cls()

    def labels(self, **kv):
        """The bound series for one label set — resolve at module scope,
        then bump the returned handle on the hot path."""
        key = _label_key(kv)
        s = self._series.get(key)
        if s is None:
            with _lock:
                s = self._series.setdefault(key, self._make_series())
        return s

    def value(self, **kv):
        key = _label_key(kv)
        s = self._series.get(key)
        return s._value() if s is not None else 0

    def reset(self):
        for s in list(self._series.values()):
            s._reset()

    def series_values(self) -> dict:
        """{rendered-label-suffix: value} snapshot of every series.
        ``sorted()`` materializes the dict C-atomically, so a scrape
        racing a first-time ``labels()`` registration stays safe."""
        return {render_labels(k): s._value()
                for k, s in sorted(self._series.items())}

    def series_items(self):
        return list(self._series.items())


class Counter(_Metric):
    kind = "counter"
    _series_cls = _CounterSeries

    def inc(self, n=1, **kv):
        """Convenience slow path (label resolution per call) — tests and
        cold paths only; hot paths pre-bind via :meth:`labels`."""
        self.labels(**kv).add(n)

    def total(self) -> int:
        return sum(s.n for s in list(self._series.values()))


class Gauge(_Metric):
    kind = "gauge"
    _series_cls = _GaugeSeries

    def set(self, v, **kv):
        self.labels(**kv).set(v)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, buckets=DEFAULT_BUCKETS):
        super().__init__(name)
        self.buckets = tuple(buckets)

    def _make_series(self):
        return _HistogramSeries(self.buckets)

    def observe(self, v, **kv):
        self.labels(**kv).observe(v)


def _get_or_create(name, cls, **kw):
    m = _metrics.get(name)
    if m is None:
        with _lock:
            m = _metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                _metrics[name] = m
    if not isinstance(m, cls):
        raise TypeError(f"metric {name!r} already registered as {m.kind}")
    return m


def counter(name: str) -> Counter:
    return _get_or_create(name, Counter)


def gauge(name: str) -> Gauge:
    return _get_or_create(name, Gauge)


def histogram(name: str, buckets=None) -> Histogram:
    if buckets is not None:
        return _get_or_create(name, Histogram, buckets=buckets)
    return _get_or_create(name, Histogram)


def metrics() -> dict:
    """Live name -> metric mapping (read-only by convention)."""
    return dict(_metrics)


def snapshot() -> dict:
    """Deep plain-data snapshot: {name: {"type": kind, "series":
    {label-suffix: value}}}.  Isolated — mutate freely."""
    return {name: {"type": m.kind, "series": m.series_values()}
            for name, m in sorted(_metrics.items())}


def counter_values() -> dict:
    """Flat {name + label-suffix: int} over counters only — the cheap
    view the span tracer diffs on span entry/exit."""
    out = {}
    for name, m in list(_metrics.items()):   # C-atomic copy: scrape-safe
        if m.kind != "counter":
            continue
        for key, s in m.series_items():
            out[name + render_labels(key)] = s.n
    return out


def parse_series_key(flat_key: str):
    """Inverse of ``name + render_labels(key)``: split one
    :func:`counter_values` key back into ``(name, labels_dict)``."""
    if flat_key.endswith("}") and "{" in flat_key:
        name, _, suffix = flat_key.partition("{")
        labels = {}
        for pair in suffix[:-1].split(","):
            k, _, v = pair.partition("=")
            labels[k] = v
        return name, labels
    return flat_key, {}


def book_flat_deltas(deltas: dict) -> None:
    """Re-book counter deltas exported from ANOTHER process's registry.

    A fork-pool worker's counters die with the child; the gen runner
    ships each case's nonzero deltas (flat :func:`counter_values` keys)
    back through the pool result and the parent adds them here, so
    ``obs_report`` sees one coherent ledger regardless of which process
    ran the case.  Negative deltas are dropped: a counter can only go
    backwards if the child reset it, which is a child-local act with no
    parent-side meaning."""
    for flat_key, n in deltas.items():
        if n <= 0:
            continue
        name, labels = parse_series_key(flat_key)
        counter(name).labels(**labels).add(n)


def reset(prefix: str = "") -> None:
    """Zero every series (in place — bound handles stay live) whose
    metric name starts with ``prefix``; everything when empty."""
    for name, m in list(_metrics.items()):
        if name.startswith(prefix):
            m.reset()
