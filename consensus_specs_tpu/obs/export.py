"""Telemetry exporters: JSON snapshot, Prometheus text format, and the
human ``report()`` table.

All three read the same two sources — the metrics registry
(``obs/registry.py``) and the span tree (``obs/tracing.py``) — and are
pure functions of a snapshot, so the bench smokes can embed
:func:`snapshot` output in their emitted measurement lines and CI can
:func:`schema_check` it without re-running anything.
"""
import json

from . import registry, tracing

# Prometheus metric name prefix (component namespace per the Prometheus
# naming conventions).
PROM_PREFIX = "cs_tpu_"


def snapshot() -> dict:
    """The full telemetry snapshot: metrics + span tree + gate states.
    Plain data, deep-copied, JSON-serializable."""
    return {
        "metrics": registry.snapshot(),
        "spans": tracing.span_tree(),
        "flags": {
            "profile": tracing.is_enabled(),
            "trace_counters": tracing.trace_counters_enabled(),
        },
    }


def to_json(indent=None) -> str:
    return json.dumps(snapshot(), indent=indent, sort_keys=True)


def _prom_name(name: str) -> str:
    return PROM_PREFIX + name.replace(".", "_").replace("-", "_")


def _prom_labels(suffix: str) -> str:
    """Registry label suffix ``{k=v,...}`` -> Prometheus ``{k="v",...}``."""
    if not suffix:
        return ""
    body = suffix[1:-1]
    parts = []
    for kv in body.split(","):
        k, _, v = kv.partition("=")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def to_prometheus() -> str:
    """Prometheus text exposition format (version 0.0.4) of the metrics
    registry.  Spans are exported as three synthetic per-name counters
    (``_span_count`` / ``_span_seconds`` / ``_span_self_seconds``)."""
    lines = []
    for name, m in sorted(registry.metrics().items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} {m.kind}")
        for suffix, value in m.series_values().items():
            labels = _prom_labels(suffix)
            if m.kind == "histogram":
                # snapshot buckets are per-interval counts; Prometheus
                # requires CUMULATIVE le buckets with +Inf == _count
                cum = 0
                for le, c in value["buckets"].items():
                    cum += c
                    lb = labels[1:-1] + "," if labels else ""
                    lines.append(
                        f'{pname}_bucket{{{lb}le="{le}"}} {cum}')
                lines.append(f"{pname}_sum{labels} {value['sum']}")
                lines.append(f"{pname}_count{labels} {value['count']}")
                # bucket-interpolated quantile summaries (computed at
                # export time, not stored): one gauge line per q so
                # dashboards get p50/p90/p99 without a PromQL
                # histogram_quantile over the raw buckets
                for q, pkey in (("0.5", "p50"), ("0.9", "p90"),
                                ("0.99", "p99")):
                    est = value.get(pkey)
                    if est is not None:
                        lb = labels[1:-1] + "," if labels else ""
                        lines.append(
                            f'{pname}_quantile{{{lb}q="{q}"}} {est}')
            else:
                lines.append(f"{pname}{labels} {value}")
    flat = tracing.stats()
    if flat:
        lines.append(f"# TYPE {PROM_PREFIX}span_seconds counter")
        for name, s in sorted(flat.items()):
            labels = f'{{span="{name}"}}'
            lines.append(f"{PROM_PREFIX}span_count{labels} {s['count']}")
            lines.append(f"{PROM_PREFIX}span_seconds{labels} {s['total_s']}")
            lines.append(
                f"{PROM_PREFIX}span_self_seconds{labels} {s['self_s']}")
    return "\n".join(lines) + "\n"


def _fmt_count(v) -> str:
    return f"{v:,}" if isinstance(v, int) else str(v)


def report() -> str:
    """Human-readable table: span tree (indented, cumulative + self
    time) followed by the non-zero metric series."""
    out = []
    tree = tracing.span_tree()
    if tree:
        out.append(f"{'span':<44}  {'count':>7}  {'total':>9}  "
                   f"{'self':>9}  {'max':>9}")

        def _walk(children, depth):
            rows = sorted(children.items(),
                          key=lambda kv: -kv[1]["total_s"])
            for name, node in rows:
                label = "  " * depth + name
                # a root subtree born on a worker thread that adopted no
                # trace context: its time is causally unattributed, so
                # say so instead of letting it read like a call site
                if node.get("orphan"):
                    label += "  [orphan thread]"
                out.append(f"{label:<44}  {node['count']:>7}  "
                           f"{node['total_s']:>8.3f}s  "
                           f"{node['self_s']:>8.3f}s  "
                           f"{node['max_s']:>8.4f}s")
                _walk(node["children"], depth + 1)

        _walk(tree, 0)
        out.append("")
    elif tracing.is_enabled():
        out.append("spans: none recorded")
        out.append("")
    else:
        out.append("spans: disabled (CS_TPU_PROFILE=1 to enable)")
        out.append("")
    rows = []
    for name, m in sorted(registry.snapshot().items()):
        for suffix, value in m["series"].items():
            if m["type"] == "histogram":
                if value["count"]:
                    qs = "".join(
                        f" {k}={value[k]:.4f}"
                        for k in ("p50", "p90", "p99")
                        if isinstance(value.get(k), (int, float)))
                    rows.append((name + suffix,
                                 f"count={value['count']} "
                                 f"sum={value['sum']:.4f}{qs} "
                                 f"max={value['max']:.4f}"))
            elif value:
                rows.append((name + suffix, _fmt_count(value)))
    if rows:
        width = max(len(n) for n, _ in rows)
        out.append(f"{'metric'.ljust(width)}  value")
        for name, value in rows:
            out.append(f"{name.ljust(width)}  {value}")
    else:
        out.append("metrics: all zero")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Snapshot schema validation (bench smokes / CI assert on this)
# ---------------------------------------------------------------------------

def schema_problems(snap) -> list:
    """Structural problems of a :func:`snapshot`-shaped dict, empty when
    valid.  Deliberately dependency-free (no jsonschema in the image)."""
    probs = []
    if not isinstance(snap, dict):
        return ["snapshot is not a dict"]
    metrics = snap.get("metrics")
    if not isinstance(metrics, dict):
        probs.append("missing/invalid 'metrics'")
        metrics = {}
    for name, m in metrics.items():
        if not isinstance(m, dict) or "type" not in m or "series" not in m:
            probs.append(f"metric {name!r}: missing type/series")
            continue
        if m["type"] not in ("counter", "gauge", "histogram"):
            probs.append(f"metric {name!r}: unknown type {m['type']!r}")
        if not isinstance(m["series"], dict):
            probs.append(f"metric {name!r}: series is not a dict")
            continue
        for suffix, value in m["series"].items():
            if suffix and not (suffix.startswith("{")
                               and suffix.endswith("}")):
                probs.append(f"metric {name!r}: bad label suffix "
                             f"{suffix!r}")
            if m["type"] == "histogram":
                if not isinstance(value, dict) or "count" not in value:
                    probs.append(f"metric {name!r}{suffix}: bad "
                                 "histogram value")
                elif value["count"]:
                    qs = [value.get(k) for k in ("p50", "p90", "p99")]
                    if any(not isinstance(q, (int, float)) for q in qs):
                        probs.append(f"metric {name!r}{suffix}: missing "
                                     "quantile summaries")
                    elif not (value["min"] <= qs[0] <= qs[1] <= qs[2]
                              <= value["max"]):
                        probs.append(f"metric {name!r}{suffix}: quantile "
                                     f"ordering violated ({qs})")
            elif not isinstance(value, (int, float)):
                probs.append(f"metric {name!r}{suffix}: non-numeric value")
    spans = snap.get("spans")
    if not isinstance(spans, dict):
        probs.append("missing/invalid 'spans'")
    else:
        def _walk(children, path):
            for name, node in children.items():
                for field in ("count", "total_s", "self_s", "children"):
                    if field not in node:
                        probs.append(f"span {path + name!r}: missing "
                                     f"{field!r}")
                        return
                _walk(node["children"], path + name + ">")

        _walk(spans, "")
    return probs


def assert_schema(snap, require_nonempty=()) -> None:
    """Raise AssertionError on schema problems; ``require_nonempty``
    lists metric-name prefixes that must have at least one non-zero
    counter series (the bench smokes' "the engine really ran" check)."""
    probs = schema_problems(snap)
    assert not probs, f"telemetry snapshot schema problems: {probs}"
    for prefix in require_nonempty:
        hit = False
        for name, m in snap["metrics"].items():
            if name.startswith(prefix) and m["type"] == "counter" \
                    and any(v for v in m["series"].values()):
                hit = True
                break
        assert hit, (f"no non-zero counter under prefix {prefix!r} "
                     f"in telemetry snapshot")
