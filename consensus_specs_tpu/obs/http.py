"""Live telemetry plane: a stdlib HTTP endpoint over the obs surface.

The first real transport in front of the serving event surface
(ROADMAP item 2): a background ``http.server`` thread exposing the
registry/span/supervisor state that the exporters already produce, so
a running replay can be scraped instead of post-processed::

    from consensus_specs_tpu import obs

    srv = obs.serve(port=0)          # 0 = ephemeral; srv.port tells
    ...                              # ... replay traffic ...
    srv.close()

Endpoints (all GET; anything else is a counted 404):

* ``/metrics``  — the Prometheus text exposition
  (``obs.export.to_prometheus``), content type ``text/plain``.
* ``/healthz``  — supervisor breaker/quarantine states as JSON;
  **503** while any site is quarantined (a scraper's liveness gate),
  200 otherwise.
* ``/snapshot`` — the full schema-checked JSON snapshot
  (``obs.export.snapshot``); the handler runs ``schema_problems``
  before answering and turns violations into a 500, so a scraped
  snapshot is *always* schema-valid.

Every request bumps ``obs.http.requests{endpoint=}``.  Handlers run on
daemon threads (``ThreadingHTTPServer``) and only *read* the registry
— the snapshot paths copy C-atomically (see ``obs/registry.py``'s
thread model), so scraping never perturbs or blocks the replay being
observed.  This module is imported lazily by :func:`obs.serve`; the
default path never pays for it.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import supervisor
from . import export
from . import registry

_C_REQ = registry.counter("obs.http.requests")
_ENDPOINTS = {
    "/metrics": _C_REQ.labels(endpoint="metrics"),
    "/healthz": _C_REQ.labels(endpoint="healthz"),
    "/snapshot": _C_REQ.labels(endpoint="snapshot"),
}
_REQ_OTHER = _C_REQ.labels(endpoint="other")


class _Handler(BaseHTTPRequestHandler):
    server_version = "cs-tpu-obs/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):     # no stderr chatter under pytest
        pass

    def _send(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):                 # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        _ENDPOINTS.get(path, _REQ_OTHER).add()
        try:
            if path == "/metrics":
                body = export.to_prometheus().encode()
                self._send(200, "text/plain; version=0.0.4", body)
            elif path == "/healthz":
                self._healthz()
            elif path == "/snapshot":
                self._snapshot()
            else:
                self._send(404, "text/plain", b"not found\n")
        except BrokenPipeError:       # scraper hung up mid-reply
            pass
        except Exception as exc:      # never kill the serving thread
            try:
                self._send(500, "text/plain",
                           f"telemetry error: {exc}\n".encode())
            except OSError:
                pass

    def _healthz(self) -> None:
        states = supervisor.states()
        quarantined = sorted(s for s, st in states.items()
                             if st == "quarantined")
        body = json.dumps({
            "ok": not quarantined,
            "supervisor_enabled": supervisor.enabled(),
            "quarantined": quarantined,
            "breakers": states,
        }, sort_keys=True).encode()
        self._send(503 if quarantined else 200, "application/json", body)

    def _snapshot(self) -> None:
        snap = export.snapshot()
        problems = export.schema_problems(snap)
        if problems:
            self._send(500, "application/json",
                       json.dumps({"schema_problems": problems}).encode())
            return
        self._send(200, "application/json",
                   json.dumps(snap, sort_keys=True).encode())


class TelemetryServer:
    """Handle on a running telemetry endpoint; context-manager aware."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


def serve(port: int = 0, host: str = "127.0.0.1") -> TelemetryServer:
    """Start the telemetry plane on a daemon thread and return its
    handle.  ``port=0`` binds an ephemeral port (read ``.port``)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, args=(0.05,),
                              name="obs-http", daemon=True)
    thread.start()
    return TelemetryServer(httpd, thread)
