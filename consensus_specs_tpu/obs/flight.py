"""Flight recorder: bounded per-thread ring buffers of engine events.

A serving process that dies mid-window leaves the span tree (aggregates,
no ordering) and the counter ledger (totals, no timeline) — neither says
*what happened last*.  The flight recorder keeps the last-N events per
thread in a fixed-slot ring so every evidence path (supervisor
quarantine artifacts, sim ``LegFailure`` dumps, recovery divergence
info, ``DurableReplay`` crash resume) can attach an ordered tail of
span enters/exits, fallback classifications, and breaker transitions::

    from consensus_specs_tpu.obs import flight

    flight.record("fallback", "bls.flush", 0.0)   # cold sites only
    payload["flight"] = flight.dump(trigger="quarantine")

Record vocabulary (one fixed-slot tuple per event —
``(seq, t_perf, code, detail, value)``):

* ``span>`` / ``span<`` — span enter / exit (``detail`` = span name,
  ``value`` = duration on exit).  Emitted by ``obs.tracing`` only when
  spans are on, so the default (profile-off) replay pays nothing.
* ``fallback`` — a :func:`faults.count_fallback` classification
  (``detail`` = ``site:reason``), hooked via ``faults._flight_hook``.
* ``breaker`` — a supervisor breaker transition (``detail`` =
  ``site:state``).
* ``quarantine`` / ``divergence`` / ``note`` — cold-path annotations.

Gating: ``CS_TPU_FLIGHT`` (default **on** — recording is cold-path
only, see above) arms the recorder; the disarmed cost of
:func:`record` is one module-global read, bench-gated <2% alongside
the span machinery in ``benchmarks/bench_obs_overhead.py``.
``CS_TPU_FLIGHT_SIZE`` bounds each ring (default 1024 slots).

Rings are thread-local for writes (no locks on the record path; slot
stores are single list-item assignments, atomic under the GIL) and
merged by thread name at :func:`dump` time.  A dump taken while other
threads are writing is a best-effort snapshot: records are tagged with
a global sequence number and sorted, so the merged view is totally
ordered even across a racing wrap.
"""
import itertools
import json
import threading
import time

from ..utils import env_flags
from . import registry

DEFAULT_SIZE = 1024

# hot-path handle: one bound int add per record (same contract as every
# engine counter — see obs/registry.py)
_C_RECORDS = registry.counter("obs.flight.records").labels()
_C_DUMPS = registry.counter("obs.flight.dumps")   # labeled per trigger

_armed = env_flags.switch("CS_TPU_FLIGHT")
_lock = threading.Lock()
_rings = []             # every live ring (any thread), for dump()
_tls = threading.local()
_gen = 0                # bumped by reset(): stale thread-local rings die
_seq = itertools.count()


def _ring_size() -> int:
    raw = env_flags.knob("CS_TPU_FLIGHT_SIZE")
    try:
        return max(8, int(raw)) if raw else DEFAULT_SIZE
    except ValueError:
        return DEFAULT_SIZE


_size = _ring_size()


class _Ring:
    """One thread's fixed-slot record ring."""

    __slots__ = ("thread", "gen", "size", "slots", "idx")

    def __init__(self, thread: str, gen: int, size: int):
        self.thread = thread
        self.gen = gen
        self.size = size
        self.slots = [None] * size
        self.idx = 0


def _ring() -> _Ring:
    r = getattr(_tls, "ring", None)
    if r is None or r.gen != _gen:
        r = _Ring(threading.current_thread().name, _gen, _size)
        _tls.ring = r
        with _lock:
            _rings.append(r)
    return r


def record(code: str, detail: str = "", value: float = 0.0) -> None:
    """Append one event to the calling thread's ring.  Disarmed cost:
    one module-global read.  Armed cost: a counter-next, two attribute
    reads and a list-slot store — still cold-path-only by convention
    (speclint O5xx keeps per-pair paths clean of *any* bookkeeping)."""
    if not _armed:
        return
    r = _ring()
    i = r.idx
    r.slots[i % r.size] = (next(_seq), time.perf_counter(), code,
                           detail, value)
    r.idx = i + 1
    _C_RECORDS.add()


def is_enabled() -> bool:
    return _armed


def enable(on: bool = True) -> None:
    """Arm/disarm at runtime (the env switch sets the default)."""
    global _armed
    _armed = bool(on)


def reset(refresh_env: bool = False) -> None:
    """Drop every recorded event (all threads).  ``refresh_env=True``
    additionally re-reads ``CS_TPU_FLIGHT`` / ``CS_TPU_FLIGHT_SIZE`` —
    the sim harness passes it so each leg's env applies cleanly."""
    global _gen, _armed, _size, _seq
    with _lock:
        _rings.clear()
    _gen += 1
    _seq = itertools.count()
    if refresh_env:
        _armed = env_flags.switch("CS_TPU_FLIGHT")
        _size = _ring_size()


def record_count() -> int:
    """Total records currently retained across all rings (bounded by
    threads x ring size; the cumulative count is ``obs.flight.records``)."""
    with _lock:
        rings = list(_rings)
    return sum(min(r.idx, r.size) for r in rings)


def dump(trigger: str = "manual") -> dict:
    """Plain-data snapshot of every ring, merged by thread name and
    ordered by the global sequence number.  Safe to call from any
    thread at any time (including inside crash/quarantine paths); the
    result is JSON-ready and attached verbatim to evidence artifacts."""
    with _lock:
        rings = list(_rings)
    threads = {}
    dropped = 0
    for r in rings:
        idx, size, slots = r.idx, r.size, r.slots
        dropped += max(0, idx - size)
        recs = threads.setdefault(r.thread, [])
        for j in range(max(0, idx - size), idx):
            rec = slots[j % size]
            if rec is not None:
                recs.append([rec[0], round(rec[1], 6), rec[2], rec[3],
                             round(rec[4], 6)])
    for recs in threads.values():
        recs.sort()
    _C_DUMPS.labels(trigger=trigger).add()
    return {"kind": "flight", "trigger": trigger, "enabled": _armed,
            "size": _size, "dropped": dropped, "threads": threads}


def format_dump(d: dict) -> str:
    """Human rendering of a :func:`dump` payload (used by
    ``sim.repro.replay`` when an artifact carries a flight tail)."""
    if not d or not d.get("threads"):
        return "flight recorder: no records"
    all_t = [rec[1] for recs in d["threads"].values() for rec in recs]
    t0 = min(all_t) if all_t else 0.0
    lines = [f"flight recorder (trigger={d.get('trigger', '?')}, "
             f"{sum(len(r) for r in d['threads'].values())} records, "
             f"{d.get('dropped', 0)} dropped):"]
    for thread in sorted(d["threads"]):
        lines.append(f"  [{thread}]")
        for seq, t, code, detail, value in d["threads"][thread]:
            suffix = f"  {value * 1e3:.3f}ms" if value else ""
            lines.append(f"    {seq:>6}  +{(t - t0) * 1e3:9.3f}ms  "
                         f"{code:<10} {detail}{suffix}")
    return "\n".join(lines)


def to_chrome_trace(d: dict = None) -> dict:
    """Chrome-trace / Perfetto JSON view of a dump: ``span<`` records
    become complete ("X") slices with real thread lanes, everything
    else an instant event — load the file in ``chrome://tracing`` or
    ``ui.perfetto.dev`` to see a serving window's double-buffered
    overlap (main-thread transition vs ``serving-flush`` lane) on a
    timeline."""
    if d is None:
        d = dump(trigger="export")
    events = []
    tids = {}
    for tname in sorted(d.get("threads", {})):
        tid = tids.setdefault(tname, len(tids) + 1)
        events.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": tname}})
        for seq, t, code, detail, value in d["threads"][tname]:
            ts = t * 1e6
            if code == "span<":
                events.append({"ph": "X", "name": detail, "cat": "span",
                               "pid": 1, "tid": tid,
                               "ts": round(ts - value * 1e6, 3),
                               "dur": round(value * 1e6, 3)})
            elif code != "span>":
                events.append({"ph": "i", "s": "t", "cat": code,
                               "name": f"{code} {detail}".strip(),
                               "pid": 1, "tid": tid, "ts": round(ts, 3)})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, d: dict = None) -> int:
    """Write :func:`to_chrome_trace` JSON to ``path``; returns the
    event count (``obs_report --trace-out``)."""
    trace = to_chrome_trace(d)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])


def _on_fallback(site: str, reason: str) -> None:
    record("fallback", f"{site}:{reason}")


# Register the faults hook at import (same pattern as the supervisor's
# _failure_hook: faults.py stays import-dependency-free).
from .. import faults as _faults                       # noqa: E402

_faults._flight_hook = _on_fallback
