"""Hierarchical tracing spans: a per-replay span tree with wall-clock,
call counts, self-vs-cumulative time, and attached counter deltas.

Replaces the flat span timer that lived in ``utils/profiling.py`` (that
module is now a thin alias layer over this one).  A span both feeds a
flat per-name aggregate (``stats()`` — the old surface, now with the
nesting double-count fixed via explicit self-time) and a position-aware
tree (``span_tree()``) keyed by call path, so a 32-slot replay reads
as::

    state_transition            32   1.84s (self 0.02s)
      process_slots             32   1.21s (self 0.11s)
        process_epoch            4   0.63s ...
        hash_forest.flush      288   0.41s ...
          sha256.dispatch     1152   0.38s ...

Gating (registered in ``utils/env_flags.py``):

* ``CS_TPU_PROFILE=1`` — spans record timing (flat stats + tree).
* ``CS_TPU_TRACE=1``   — additionally attaches per-span counter deltas
  (a registry-wide counter diff on entry/exit; implies PROFILE).

Disabled path (the default, speclint O5xx's sanctioned pattern): one
module-global read in ``__enter__`` and one attribute test in
``__exit__`` — branch-predictable, allocation-free, and measured at
<2% on the 32-slot replay by ``benchmarks/bench_obs_overhead.py``.

Span state is thread-local, so by default concurrent threads build
disjoint subtrees under the shared root.  Cross-thread causality is
explicit: the submitting thread calls :func:`capture_context` while
its span of interest is open, hands the returned :class:`TraceContext`
to the worker, and the worker wraps its work in
:func:`adopt_context` — its spans then parent under the captured node
(one causally-linked tree per request) and carry the context's
``trace_id``.  A root-level subtree opened on a non-main thread that
*didn't* adopt a context is flagged ``orphan`` in :func:`span_tree`
so reports can call out unattributed worker-lane time instead of
silently merging it (speclint O504 statically flags thread submits
that skip the handoff).
"""
import itertools
import threading
import time

from ..utils import env_flags
from . import flight
from . import registry

_enabled = env_flags.PROFILE or env_flags.TRACE
_trace_counters = env_flags.TRACE


class _Node:
    """One position in the span tree (aggregated across invocations of
    the same call path)."""

    __slots__ = ("name", "count", "total", "child_total", "max",
                 "children", "counters", "orphan")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0        # cumulative wall-clock
        self.child_total = 0.0  # time attributed to child spans
        self.max = 0.0
        self.children = {}      # name -> _Node
        self.counters = {}      # metric+labels -> cumulative delta
        self.orphan = False     # root created on a non-adopted thread


_root = _Node("<root>")
# flat per-name aggregate (the profiling.stats() surface):
# name -> [count, cum_total, max]; self-time is derived from the tree
# (per-position child_total) at stats() time, not stored here
_flat = {}
_tls = threading.local()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = [_root]
        _tls.stack = st
    return st


def enable(on: bool = True, counters=None) -> None:
    """Turn span recording on/off at runtime (the env flags set the
    default).  ``counters`` optionally overrides counter-delta
    attachment; default: leave the CS_TPU_TRACE-derived setting."""
    global _enabled, _trace_counters
    _enabled = on
    if counters is not None:
        _trace_counters = counters


def is_enabled() -> bool:
    return _enabled


def trace_counters_enabled() -> bool:
    return _trace_counters


def reset() -> None:
    """Drop all recorded spans (flat stats and the tree) and re-seed
    the trace-id counter: a fresh tree hands out ids from 1 again, so
    seeded replays leave byte-deterministic flight tails."""
    global _trace_ids
    _flat.clear()
    _root.children.clear()
    _root.count = 0
    _root.total = _root.child_total = _root.max = 0.0
    _root.counters.clear()
    _trace_ids = itertools.count(1)


_trace_ids = itertools.count(1)


class TraceContext:
    """An explicit cross-thread handoff of one tree position.

    Captured on the thread whose span should become the parent, adopted
    (usually once) on the thread doing work on its behalf.  ``trace_id``
    is a process-unique request identifier the pipeline threads through
    window ingest, the flush-worker submit and the barrier join.
    Concurrent adoption from *different* threads is allowed — the
    serving barrier joins a window whose flush worker is still inside
    its adoption — but a thread re-adopting a context it already holds
    is refused (it would double-push the same node on one stack)."""

    __slots__ = ("node", "trace_id", "_threads")

    def __init__(self, node, trace_id):
        self.node = node
        self.trace_id = trace_id
        self._threads = set()   # idents currently inside adopt_context

    def __repr__(self):
        where = self.node.name if self.node is not None else None
        return f"TraceContext(trace_id={self.trace_id}, node={where!r})"


def capture_context():
    """Capture the calling thread's current tree position (the
    innermost open span) for adoption on another thread.  Returns
    ``None`` when spans are disabled — :func:`adopt_context` treats
    ``None`` as a no-op, so call sites need no gating of their own."""
    if not _enabled:
        return None
    return TraceContext(_stack()[-1], next(_trace_ids))


class adopt_context:
    """Context manager parenting the calling thread's spans under a
    captured :class:`TraceContext` — the worker half of the handoff.

    Exception-safe: unwinding pops everything the adopted region
    pushed, even if a span inside leaked (the stack is restored to its
    pre-adoption shape).  ``None`` (or a context captured while
    disabled) adopts nothing and costs one attribute test."""

    __slots__ = ("ctx", "_pushed")

    def __init__(self, ctx):
        self.ctx = ctx
        self._pushed = False

    def __enter__(self):
        ctx = self.ctx
        if ctx is None or ctx.node is None or not _enabled:
            return self
        ident = threading.get_ident()
        if ident in ctx._threads:
            raise RuntimeError(
                f"trace context {ctx.trace_id} already adopted on this "
                f"thread (double-adopt)")
        ctx._threads.add(ident)
        _stack().append(ctx.node)
        self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._pushed:
            return False
        self._pushed = False
        self.ctx._threads.discard(threading.get_ident())
        stack = _stack()
        node = self.ctx.node
        # pop leaked spans (exception unwind) down to, and including,
        # the adopted node; never pop the thread's own root sentinel
        while len(stack) > 1 and stack[-1] is not node:
            stack.pop()
        if len(stack) > 1 and stack[-1] is node:
            stack.pop()
        return False


class span:
    """Context manager recording one span occurrence.

    Class-based (not a generator) so the disabled path is a plain
    attribute store + one global read, and instances are cheap enough
    to construct per call site.
    """

    __slots__ = ("name", "_node", "_t0", "_c0")

    def __init__(self, name: str):
        self.name = name
        self._node = None

    def __enter__(self):
        if not _enabled:
            return self
        stack = _stack()
        parent = stack[-1]
        node = parent.children.get(self.name)
        if node is None:
            node = parent.children[self.name] = _Node(self.name)
            if (parent is _root and threading.current_thread()
                    is not threading.main_thread()):
                # a worker thread rooting its own subtree: no context
                # was adopted, so this time is causally unattributed
                node.orphan = True
        stack.append(node)
        self._node = node
        if flight._armed:
            flight.record("span>", self.name)
        self._c0 = registry.counter_values() if _trace_counters else None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        node = self._node
        if node is None:
            return False
        dt = time.perf_counter() - self._t0
        self._node = None
        if flight._armed:
            flight.record("span<", node.name, dt)
        stack = _stack()
        stack.pop()
        stack[-1].child_total += dt
        node.count += 1
        node.total += dt
        if dt > node.max:
            node.max = dt
        if self._c0 is not None:
            c1 = registry.counter_values()
            c0 = self._c0
            self._c0 = None
            for k, v in c1.items():
                d = v - c0.get(k, 0)
                if d:
                    node.counters[k] = node.counters.get(k, 0) + d
        f = _flat.get(node.name)
        if f is None:
            f = _flat[node.name] = [0, 0.0, 0.0]
        f[0] += 1
        f[1] += dt
        if dt > f[2]:
            f[2] = dt
        return False


def stats() -> dict:
    """Flat per-name aggregate:
    {name: {count, total_s, self_s, mean_s, max_s}}.

    ``total_s`` is cumulative (a nested span's time also counts in its
    parent); ``self_s`` excludes time spent inside child spans, so
    column sums of ``self_s`` are double-count-free.
    """
    # self-time lives on the tree (per-position child_total); fold it
    # into the flat view by name
    self_by_name = {}

    def _walk(node):
        for child in node.children.values():
            self_by_name[child.name] = (
                self_by_name.get(child.name, 0.0)
                + child.total - child.child_total)
            _walk(child)

    _walk(_root)
    out = {}
    for name, (c, total, mx) in _flat.items():
        self_s = self_by_name.get(name, total)
        out[name] = {"count": c, "total_s": round(total, 6),
                     "self_s": round(self_s, 6),
                     "mean_s": round(total / c, 6) if c else 0.0,
                     "max_s": round(mx, 6)}
    return out


def span_tree() -> dict:
    """Nested plain-data snapshot of the span tree:
    {name: {count, total_s, self_s, max_s, counters, children}}."""

    def _dump(node):
        out = {
            "count": node.count,
            "total_s": round(node.total, 6),
            "self_s": round(node.total - node.child_total, 6),
            "max_s": round(node.max, 6),
            "counters": dict(node.counters),
            "children": {n: _dump(c) for n, c in
                         sorted(node.children.items())},
        }
        if node.orphan:
            out["orphan"] = True
        return out

    return {n: _dump(c) for n, c in sorted(_root.children.items())}
