"""Hierarchical tracing spans: a per-replay span tree with wall-clock,
call counts, self-vs-cumulative time, and attached counter deltas.

Replaces the flat span timer that lived in ``utils/profiling.py`` (that
module is now a thin alias layer over this one).  A span both feeds a
flat per-name aggregate (``stats()`` — the old surface, now with the
nesting double-count fixed via explicit self-time) and a position-aware
tree (``span_tree()``) keyed by call path, so a 32-slot replay reads
as::

    state_transition            32   1.84s (self 0.02s)
      process_slots             32   1.21s (self 0.11s)
        process_epoch            4   0.63s ...
        hash_forest.flush      288   0.41s ...
          sha256.dispatch     1152   0.38s ...

Gating (registered in ``utils/env_flags.py``):

* ``CS_TPU_PROFILE=1`` — spans record timing (flat stats + tree).
* ``CS_TPU_TRACE=1``   — additionally attaches per-span counter deltas
  (a registry-wide counter diff on entry/exit; implies PROFILE).

Disabled path (the default, speclint O5xx's sanctioned pattern): one
module-global read in ``__enter__`` and one attribute test in
``__exit__`` — branch-predictable, allocation-free, and measured at
<2% on the 32-slot replay by ``benchmarks/bench_obs_overhead.py``.
Span state is thread-local; concurrent threads build disjoint subtrees
under the shared root.
"""
import threading
import time

from ..utils import env_flags
from . import registry

_enabled = env_flags.PROFILE or env_flags.TRACE
_trace_counters = env_flags.TRACE


class _Node:
    """One position in the span tree (aggregated across invocations of
    the same call path)."""

    __slots__ = ("name", "count", "total", "child_total", "max",
                 "children", "counters")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0        # cumulative wall-clock
        self.child_total = 0.0  # time attributed to child spans
        self.max = 0.0
        self.children = {}      # name -> _Node
        self.counters = {}      # metric+labels -> cumulative delta


_root = _Node("<root>")
# flat per-name aggregate (the profiling.stats() surface):
# name -> [count, cum_total, max]; self-time is derived from the tree
# (per-position child_total) at stats() time, not stored here
_flat = {}
_tls = threading.local()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = [_root]
        _tls.stack = st
    return st


def enable(on: bool = True, counters=None) -> None:
    """Turn span recording on/off at runtime (the env flags set the
    default).  ``counters`` optionally overrides counter-delta
    attachment; default: leave the CS_TPU_TRACE-derived setting."""
    global _enabled, _trace_counters
    _enabled = on
    if counters is not None:
        _trace_counters = counters


def is_enabled() -> bool:
    return _enabled


def trace_counters_enabled() -> bool:
    return _trace_counters


def reset() -> None:
    """Drop all recorded spans (flat stats and the tree)."""
    _flat.clear()
    _root.children.clear()
    _root.count = 0
    _root.total = _root.child_total = _root.max = 0.0
    _root.counters.clear()


class span:
    """Context manager recording one span occurrence.

    Class-based (not a generator) so the disabled path is a plain
    attribute store + one global read, and instances are cheap enough
    to construct per call site.
    """

    __slots__ = ("name", "_node", "_t0", "_c0")

    def __init__(self, name: str):
        self.name = name
        self._node = None

    def __enter__(self):
        if not _enabled:
            return self
        stack = _stack()
        parent = stack[-1]
        node = parent.children.get(self.name)
        if node is None:
            node = parent.children[self.name] = _Node(self.name)
        stack.append(node)
        self._node = node
        self._c0 = registry.counter_values() if _trace_counters else None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        node = self._node
        if node is None:
            return False
        dt = time.perf_counter() - self._t0
        self._node = None
        stack = _stack()
        stack.pop()
        stack[-1].child_total += dt
        node.count += 1
        node.total += dt
        if dt > node.max:
            node.max = dt
        if self._c0 is not None:
            c1 = registry.counter_values()
            c0 = self._c0
            self._c0 = None
            for k, v in c1.items():
                d = v - c0.get(k, 0)
                if d:
                    node.counters[k] = node.counters.get(k, 0) + d
        f = _flat.get(node.name)
        if f is None:
            f = _flat[node.name] = [0, 0.0, 0.0]
        f[0] += 1
        f[1] += dt
        if dt > f[2]:
            f[2] = dt
        return False


def stats() -> dict:
    """Flat per-name aggregate:
    {name: {count, total_s, self_s, mean_s, max_s}}.

    ``total_s`` is cumulative (a nested span's time also counts in its
    parent); ``self_s`` excludes time spent inside child spans, so
    column sums of ``self_s`` are double-count-free.
    """
    # self-time lives on the tree (per-position child_total); fold it
    # into the flat view by name
    self_by_name = {}

    def _walk(node):
        for child in node.children.values():
            self_by_name[child.name] = (
                self_by_name.get(child.name, 0.0)
                + child.total - child.child_total)
            _walk(child)

    _walk(_root)
    out = {}
    for name, (c, total, mx) in _flat.items():
        self_s = self_by_name.get(name, total)
        out[name] = {"count": c, "total_s": round(total, 6),
                     "self_s": round(self_s, 6),
                     "mean_s": round(total / c, 6) if c else 0.0,
                     "max_s": round(mx, 6)}
    return out


def span_tree() -> dict:
    """Nested plain-data snapshot of the span tree:
    {name: {count, total_s, self_s, max_s, counters, children}}."""

    def _dump(node):
        return {
            "count": node.count,
            "total_s": round(node.total, 6),
            "self_s": round(node.total - node.child_total, 6),
            "max_s": round(node.max, 6),
            "counters": dict(node.counters),
            "children": {n: _dump(c) for n, c in
                         sorted(node.children.items())},
        }

    return {n: _dump(c) for n, c in sorted(_root.children.items())}
