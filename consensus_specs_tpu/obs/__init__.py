"""Unified telemetry subsystem: metrics registry, tracing spans, and
exporters for the whole engine stack.

Three layers, importable without jax or the fork registry:

* ``obs.registry`` — typed, labeled metrics (``counter`` / ``gauge`` /
  ``histogram``).  Always on; hot paths pre-bind series at module scope
  and pay one int add per event.
* ``obs.tracing``  — hierarchical wall-clock spans with self-vs-
  cumulative time and (under ``CS_TPU_TRACE=1``) attached counter
  deltas.  Zero-overhead when disabled.
* ``obs.export``   — JSON snapshot, Prometheus text format, human
  ``report()`` table, and the snapshot schema check the bench smokes
  assert on.

CLI: ``python -m consensus_specs_tpu.tools.obs_report`` replays a
configurable slot window with full telemetry and prints any exporter's
view.  Docs: ``docs/observability.md``.
"""
from .registry import (                              # noqa: F401
    counter, gauge, histogram, metrics)
from .tracing import span, span_tree, stats          # noqa: F401
from .export import (                                # noqa: F401
    snapshot, report, to_json, to_prometheus, assert_schema,
    schema_problems)
from .instrument import install_tracing              # noqa: F401
from . import registry, tracing, export              # noqa: F401


def enable(on: bool = True, counters=None) -> None:
    """Runtime gate for span recording (see ``tracing.enable``)."""
    tracing.enable(on, counters)


def reset_all() -> None:
    """Zero every metric series and drop all recorded spans."""
    registry.reset()
    tracing.reset()
