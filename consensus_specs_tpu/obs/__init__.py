"""Unified telemetry subsystem: metrics registry, tracing spans, flight
recorder, exporters, and a live HTTP plane for the whole engine stack.

Five layers, importable without jax or the fork registry:

* ``obs.registry`` — typed, labeled metrics (``counter`` / ``gauge`` /
  ``histogram``).  Always on; hot paths pre-bind series at module scope
  and pay one int add per event.
* ``obs.tracing``  — hierarchical wall-clock spans with self-vs-
  cumulative time and (under ``CS_TPU_TRACE=1``) attached counter
  deltas.  Zero-overhead when disabled.  Cross-thread causality via
  ``capture_context()`` / ``adopt_context()`` (the serving pipeline's
  flush-worker lane parents under its window's span).
* ``obs.flight``   — bounded per-thread ring buffers of span / fault /
  breaker events (``CS_TPU_FLIGHT``, default on); ``dump()`` is
  attached to every evidence artifact and exports to Chrome-trace
  JSON.
* ``obs.export``   — JSON snapshot, Prometheus text format, human
  ``report()`` table, and the snapshot schema check the bench smokes
  assert on.
* ``obs.http``     — ``obs.serve(port)``: ``/metrics`` + ``/healthz``
  + ``/snapshot`` scraped live during a replay (lazily imported).

CLI: ``python -m consensus_specs_tpu.tools.obs_report`` replays a
configurable slot window (or, with ``--serving``, a pipelined
``sim/load`` stream) with full telemetry and prints any exporter's
view.  Docs: ``docs/observability.md``.
"""
from .registry import (                              # noqa: F401
    counter, gauge, histogram, metrics)
from .tracing import (                               # noqa: F401
    span, span_tree, stats, capture_context, adopt_context)
from .export import (                                # noqa: F401
    snapshot, report, to_json, to_prometheus, assert_schema,
    schema_problems)
from .instrument import install_tracing              # noqa: F401
from . import registry, tracing, flight, export      # noqa: F401


def enable(on: bool = True, counters=None) -> None:
    """Runtime gate for span recording (see ``tracing.enable``)."""
    tracing.enable(on, counters)


def reset_all() -> None:
    """Zero every metric series, drop all recorded spans, and clear
    the flight-recorder rings."""
    registry.reset()
    tracing.reset()
    flight.reset()


def serve(port: int = 0, host: str = "127.0.0.1"):
    """Start the live telemetry HTTP plane (see ``obs.http.serve``);
    imported lazily so the default path never loads ``http.server``."""
    from . import http
    return http.serve(port, host)
