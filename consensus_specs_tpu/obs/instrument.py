"""From-outside span instrumentation of the spec classes.

The fork ladders keep their method bodies spec-shaped (hand-written
classes mirror the markdown; compiled classes ARE the markdown), so
tracing wraps them from outside — the same installation pattern as
``ops/epoch_kernels.install_vectorized_epoch`` and
``forkchoice/proto_array.install_forkchoice_accel``:
``forks.register_fork`` applies :func:`install_tracing` to every
hand-written fork class at definition time, and
``forks.use_compiled_registry`` applies it to each compiled class.

Only methods defined on the class itself are wrapped (an inherited
method is already wrapped on the base; a fork's override gets its own
wrapper), and wrapping is idempotent.  The wrapper's disabled path is
one module-global read on top of the original call — per-slot / per-
block granularity, so it never sits inside a per-validator loop.

These wrappers record on whichever thread calls them: spec code runs
on the main thread by contract (``serving/pipeline.py`` keeps the
worker lane to pure verification), so wrapped spans nest under the
caller's open span there — e.g. ``on_block`` under ``serving.window``.
Code that DOES move work to a thread must hand over a
``tracing.capture_context()`` / ``adopt_context()`` pair, or its spans
root an ``[orphan thread]`` subtree (speclint O504 flags the miss).
"""
import functools

from . import tracing

# The traced spec surface: block/epoch-granularity transition stages.
# Order is irrelevant; nesting comes from runtime call structure.
TRACED_METHODS = (
    "state_transition",
    "process_slots",
    "process_slot",
    "process_epoch",
    "process_block",
    "process_operations",
    "on_block",
    "on_attestation",
    "on_tick",
)


def install_tracing(cls) -> None:
    """Wrap ``cls``'s own transition-stage methods in tracing spans."""
    for name in TRACED_METHODS:
        fn = cls.__dict__.get(name)
        if fn is None or getattr(fn, "_obs_span_wrapper", False):
            continue
        setattr(cls, name, _make_wrapper(name, fn))


def _make_wrapper(name, fn):
    _span = tracing.span

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        if not tracing._enabled:
            return fn(self, *args, **kwargs)
        with _span(name):
            return fn(self, *args, **kwargs)

    wrapper._obs_span_wrapper = True
    return wrapper
