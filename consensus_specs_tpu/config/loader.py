"""Load preset and config YAML into typed python values.

Reference behavior: ``setup.py:306-331`` (load_preset/load_config) and
``config/config_util.py:5-63`` (parse_config_vars). Values that look like
integers become ``int``; ``0x…`` values stay as hex strings at this layer
(spec construction converts them to the right SSZ byte types).
"""
import os
from pathlib import Path
from typing import Any, Dict

PKG_ROOT = Path(__file__).resolve().parent.parent


def _read_flat_yaml(path) -> Dict[str, str]:
    """Parse a flat ``KEY: value`` yaml file preserving ``0x…`` tokens.

    PyYAML eagerly converts unquoted ``0x…`` scalars to int, destroying the
    byte width of Version/Hash constants — so preset/config files (which are
    strictly flat) are parsed directly.
    """
    out: Dict[str, str] = {}
    for raw in open(path):
        line = raw.split("#", 1)[0].strip()
        if not line or ":" not in line:
            continue
        k, v = line.split(":", 1)
        out[k.strip()] = v.strip().strip("'\"")
    return out


def preset_dir(preset_name: str) -> Path:
    return PKG_ROOT / "presets" / preset_name


def config_path(config_name: str) -> Path:
    return PKG_ROOT / "configs" / (config_name + ".yaml")


def _parse_value(v: Any) -> Any:
    if isinstance(v, int):
        return v
    if isinstance(v, str):
        s = v.strip()
        if s.startswith("0x"):
            return s  # hex constant; typed later
        if s.isdigit() or (s.startswith("-") and s[1:].isdigit()):
            return int(s)
    return v


def load_preset(preset_name: str, forks=None) -> Dict[str, Any]:
    """Merge all per-fork preset files for a preset base into one dict.

    ``forks`` restricts which fork preset files are merged (ordered); by
    default every stable fork file present on disk is merged in fork order.
    """
    order = forks or [
        "phase0", "altair", "bellatrix", "capella", "deneb",
        "eip6110", "eip7594", "whisk", "custody_game", "sharding",
    ]
    base = preset_dir(preset_name)
    if not base.is_dir():
        raise FileNotFoundError(f"unknown preset: {preset_name!r} ({base})")
    out: Dict[str, Any] = {}
    for fork in order:
        p = base / (fork + ".yaml")
        if not p.exists():
            continue
        for k, v in _read_flat_yaml(p).items():
            out[k] = _parse_value(v)
    return out


def parse_config_vars(conf: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _parse_value(v) for k, v in conf.items()}


def load_config_file(path: os.PathLike) -> Dict[str, Any]:
    return parse_config_vars(_read_flat_yaml(path))


def load_config(config_name: str) -> Dict[str, Any]:
    return load_config_file(config_path(config_name))
