"""Preset / config system.

Mirrors the reference's two-tier constant system (reference:
``setup.py:306-331``, ``tests/core/pyspec/eth2spec/config/config_util.py``):

* **presets** — compile-time constants (SSZ list lengths, committee sizes)
  loaded from ``consensus_specs_tpu/presets/<preset>/<fork>.yaml``.
* **configs** — runtime-swappable parameters (fork epochs, genesis params)
  loaded from ``consensus_specs_tpu/configs/<name>.yaml``.

Unlike the reference (which bakes presets into generated modules and rewrites
config references via regex), our spec classes bind both at instance-build
time, so a test can instantiate a spec with config overrides in one call.
"""
from .loader import (
    load_preset,
    load_config,
    load_config_file,
    parse_config_vars,
    preset_dir,
    config_path,
)

__all__ = [
    "load_preset",
    "load_config",
    "load_config_file",
    "parse_config_vars",
    "preset_dir",
    "config_path",
]
