"""Engine supervisor: circuit breakers, deadline guards, and online
sentinel audits over every accelerated dispatch path.

PR 8's fault harness proved the *stateless* half of the degradation
contract: any single fault at an engine entry point completes on the
spec loop and books a counted fallback.  This module adds the
*stateful* half a serving deployment needs — engines that demote
themselves when persistently broken, heal themselves when the fault
clears, and audit themselves online against the spec loop:

circuit breakers
    Every site in :data:`faults.SITES` carries a breaker.  After
    ``CS_TPU_BREAKER_THRESHOLD`` counted fallbacks within
    ``CS_TPU_BREAKER_WINDOW_MS`` the breaker *opens*: :func:`admit`
    answers False and the engine skips its fast-path attempt entirely
    (the spec-shaped path serves the call, byte-identical, without
    re-paying the failure cost).  After an exponential backoff with
    seeded jitter the next call is admitted as a *half-open* probe: a
    success re-closes the breaker, a failure re-opens it with doubled
    backoff.  Transitions (``closed -> open -> half_open -> closed``)
    are counters; per-site state is a gauge.

deadline guards
    :func:`deadline_scope` arms a wall-clock budget
    (``CS_TPU_DEADLINE_MS``) around a compiled/native dispatch;
    :func:`deadline_check` at cooperative dispatch boundaries raises
    :class:`DeadlineExceeded` — a fallback-class exception the engine
    handlers absorb through ``faults.count_fallback`` as a
    ``reason=deadline`` trip, so a pathologically slow engine degrades
    to the spec path instead of stalling the replay.  A dispatch that
    *completes* over budget books a deadline trip (and a breaker
    failure) post-hoc without discarding its correct result.

sentinel audits
    Every Kth call per site (``CS_TPU_AUDIT_RATE``, seeded sampling
    offset) the engine replays the call through the spec loop and
    compares byte-identical.  On a mismatch the spec answer is
    authoritative, the site is *quarantined* — its breaker opens with
    ``reason=audit`` and never re-probes (a silently-wrong engine must
    not heal itself back in) — and a replayable artifact is dumped
    (``sim/repro.py`` replays it; the default hook writes a minimal
    JSON with the site, detail, and env snapshot).

Everything is behind ``CS_TPU_SUPERVISOR`` (default on, live re-read
through ``utils/env_flags.switch``): with the switch off every function
here is a pass-through and behavior is exactly pre-PR-9.  Numeric knobs
are read once per :func:`reset` (the sim harness resets per leg after
applying env overrides); docs: ``docs/robustness.md``.

Thread model: like ``faults``, breaker state is process-global (its
mutations are GIL-atomic counter adds and dict stores); the deadline
stack is per-thread because the serving pipeline resolves supervised
``bls.flush`` dispatches on a worker lane while the main thread keeps
arming scopes of its own.  The disarmed/closed hot path is one env
read plus a dict lookup.
"""
import os
import random
import threading
import time
from contextlib import contextmanager

from consensus_specs_tpu import faults
from consensus_specs_tpu.obs import flight as _flight
from consensus_specs_tpu.obs import registry as _obs
from consensus_specs_tpu.utils import env_flags as _env_flags

# test seam: monkeypatch to drive breaker/deadline time deterministically
_clock = time.monotonic


class DeadlineExceeded(Exception):
    """Raised by :func:`deadline_check` when the armed dispatch budget
    is spent.  A fallback-class exception: engine handlers catch it
    alongside their ``_Fallback`` guards and ``InjectedFault`` and
    route it through ``faults.count_fallback`` (``reason=deadline``)."""

    def __init__(self, site: str, elapsed_s: float, budget_s: float):
        super().__init__(f"{site}: dispatch exceeded its deadline "
                         f"({elapsed_s * 1e3:.1f}ms > {budget_s * 1e3:.1f}ms)")
        self.site = site


def enabled() -> bool:
    """Supervisor master switch (live, ``utils/env_flags.switch``)."""
    return _env_flags.switch("CS_TPU_SUPERVISOR")


# ---------------------------------------------------------------------------
# Metrics (one series per site, pre-bound at import — speclint O5xx)
# ---------------------------------------------------------------------------

_C_TRANSITIONS = _obs.counter("supervisor.transitions")
_G_BREAKER = _obs.gauge("supervisor.breaker")
_GAUGE_STATE = {"closed": 0, "open": 1, "half_open": 2, "quarantined": 3}

_SKIPS = {site: _obs.counter("supervisor.breaker.skips").labels(site=site)
          for site in faults.SITES}
_AUDIT_PASS = {site: _obs.counter("supervisor.audits")
               .labels(site=site, result="pass") for site in faults.SITES}
_AUDIT_FAIL = {site: _obs.counter("supervisor.audits")
               .labels(site=site, result="fail") for site in faults.SITES}
_QUARANTINES = {site: _obs.counter("supervisor.quarantines")
                .labels(site=site) for site in faults.SITES}
_DEADLINE_TRIPS = {site: _obs.counter("supervisor.deadline.trips")
                   .labels(site=site) for site in faults.SITES}
_TRANSITIONS = {(site, to): _C_TRANSITIONS.labels(site=site, to=to)
                for site in faults.SITES
                for to in ("open", "half_open", "closed")}
_GAUGES = {site: _G_BREAKER.labels(site=site) for site in faults.SITES}


_TABLE_NAMES = {id(_SKIPS): "supervisor.breaker.skips",
                id(_AUDIT_PASS): "supervisor.audits",
                id(_AUDIT_FAIL): "supervisor.audits",
                id(_QUARANTINES): "supervisor.quarantines",
                id(_DEADLINE_TRIPS): "supervisor.deadline.trips"}


def _series(table, site, **kv):
    """Pre-bound series for a known site; cold labels() resolution for
    a site outside ``faults.SITES`` (future engines, tests)."""
    s = table.get(site)
    if s is not None:
        return s
    if table is _TRANSITIONS:
        return _C_TRANSITIONS.labels(site=site[0], to=site[1])
    return _obs.counter(_TABLE_NAMES[id(table)]).labels(site=site, **kv)


# ---------------------------------------------------------------------------
# Configuration (read once per reset; the harness resets per leg)
# ---------------------------------------------------------------------------

class _Config:
    __slots__ = ("threshold", "window_s", "backoff_s", "backoff_max_s",
                 "jitter", "audit_rate", "deadline_s", "seed")

    def __init__(self):
        env = os.environ.get
        self.threshold = max(1, _int(env("CS_TPU_BREAKER_THRESHOLD"), 5))
        self.window_s = _float(env("CS_TPU_BREAKER_WINDOW_MS"), 10_000) / 1e3
        self.backoff_s = _float(env("CS_TPU_BREAKER_BACKOFF_MS"), 200) / 1e3
        self.backoff_max_s = _float(
            env("CS_TPU_BREAKER_BACKOFF_MAX_MS"), 60_000) / 1e3
        self.jitter = 0.25
        self.audit_rate = _int(env("CS_TPU_AUDIT_RATE"), 0)
        self.deadline_s = _float(env("CS_TPU_DEADLINE_MS"), 0) / 1e3
        self.seed = _int(env("CS_TPU_SUPERVISOR_SEED"), 0)


def _int(raw, default):
    try:
        return int(raw)
    except (TypeError, ValueError):
        return default


def _float(raw, default):
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


_cfg = None
_rng = None


def _config() -> _Config:
    global _cfg, _rng
    if _cfg is None:
        _cfg = _Config()
        _rng = random.Random(_cfg.seed)
    return _cfg


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------

class _Breaker:
    __slots__ = ("site", "state", "fails", "slow", "reopen_at", "opens")

    def __init__(self, site):
        self.site = site
        self.state = "closed"
        self.fails = []         # recent failure timestamps (window-pruned)
        self.slow = []          # deadline overruns: a dispatch that
        #                         completed (correctly, so note_success
        #                         follows) but over budget must still
        #                         accumulate toward demotion — successes
        #                         clear ``fails`` but never this list
        self.reopen_at = 0.0    # next half-open probe time; None = never
        self.opens = 0          # consecutive opens (backoff exponent)


_breakers = {}


def _breaker(site) -> _Breaker:
    br = _breakers.get(site)
    if br is None:
        br = _breakers.setdefault(site, _Breaker(site))
    return br


def _set_state(br, state) -> None:
    br.state = state
    _GAUGES.get(br.site, _G_BREAKER.labels(site=br.site)) \
        .set(_GAUGE_STATE[state])
    to = "open" if state == "quarantined" else state
    _series(_TRANSITIONS, (br.site, to)).add()
    _flight.record("breaker", f"{br.site}:{state}")


def _open(br, cfg) -> None:
    br.opens += 1
    backoff = min(cfg.backoff_s * (2 ** (br.opens - 1)), cfg.backoff_max_s)
    backoff *= 1.0 + cfg.jitter * _rng.random()
    br.reopen_at = _clock() + backoff
    br.fails.clear()
    br.slow.clear()
    _set_state(br, "open")


def admit(site: str) -> bool:
    """Gate an engine's fast-path attempt.  True (the common case, one
    env read + a dict lookup) admits the attempt; False means the
    site's breaker is open — the engine must serve the call on its
    spec-shaped path without attempting the fast path (and without
    calling ``faults.check``: a demoted site is out of the schedule
    vocabulary until it heals)."""
    if not enabled():
        return True
    br = _breakers.get(site)
    if br is None or br.state == "closed":
        return True
    if br.state == "half_open":
        return True     # a probe is in flight; keep probing
    if br.state == "open" and _clock() >= br.reopen_at:
        _set_state(br, "half_open")     # this call is the probe
        return True
    _series(_SKIPS, site).add()
    return False


def note_success(site: str) -> None:
    """Report a fast-path success: closes a half-open probe (resetting
    the backoff schedule), clears the failure window otherwise."""
    if not enabled():
        return
    br = _breakers.get(site)
    if br is None or br.state == "closed":
        if br is not None and br.fails:
            br.fails.clear()
        return
    if br.state == "half_open":
        br.opens = 0
        br.fails.clear()
        br.reopen_at = 0.0
        _set_state(br, "closed")


def note_failure(site: str, reason: str = "guard") -> None:
    """Report a counted fallback (wired as the ``faults.count_fallback``
    hook).  A half-open probe failure re-opens with doubled backoff; in
    the closed state, ``threshold`` failures within the window open the
    breaker."""
    if not enabled() or site is None:
        return
    cfg = _config()
    br = _breaker(site)
    if br.state == "half_open":
        _open(br, cfg)
        return
    if br.state != "closed":
        return
    bucket = br.slow if reason == "deadline" else br.fails
    now = _clock()
    bucket.append(now)
    if len(bucket) > cfg.threshold:
        del bucket[:-cfg.threshold]
    if len(bucket) >= cfg.threshold and bucket[0] >= now - cfg.window_s:
        _open(br, cfg)


def states() -> dict:
    """{site: breaker state} for every site touched since reset plus
    the untouched ones (reported closed)."""
    out = {site: "closed" for site in faults.SITES}
    out.update({site: br.state for site, br in _breakers.items()})
    return out


# ---------------------------------------------------------------------------
# Sentinel audits + quarantine
# ---------------------------------------------------------------------------

_audit_calls = {}
_audit_offsets = {}
_probe_depth = 0
_quarantine_seq = 0
_last_quarantine = None


def audit_due(site: str) -> bool:
    """True when this engine call is sampled for a sentinel audit (the
    engine must then produce BOTH answers — spec authoritative — and
    report through :func:`audit_result`).  Sampling is every
    ``CS_TPU_AUDIT_RATE``-th call per site at a seeded per-site offset;
    rate 0 (the default) disables audits."""
    if not enabled():
        return False
    cfg = _config()
    k = cfg.audit_rate
    if k <= 0 or _probe_depth:
        return False
    br = _breakers.get(site)
    if br is not None and br.state != "closed":
        return False    # demoted sites run the spec path anyway
    n = _audit_calls.get(site, 0) + 1
    _audit_calls[site] = n
    off = _audit_offsets.get(site)
    if off is None:
        off = _audit_offsets.setdefault(site, _rng.randrange(k))
    return n % k == off % k


def audit_result(site: str, ok: bool, detail: str = "") -> None:
    """Book one sentinel audit verdict; a failure quarantines the
    site.  The engine must already have answered with the SPEC result —
    the audit layer never un-propagates a mismatch after the fact."""
    if ok:
        _series(_AUDIT_PASS, site, result="pass").add()
        note_success(site)
        return
    _series(_AUDIT_FAIL, site, result="fail").add()
    quarantine(site, detail)


def quarantine(site: str, detail: str = "") -> None:
    """Open ``site``'s breaker permanently (``reason=audit``): no
    backoff re-probe — an engine caught answering *wrong* (not merely
    failing) stays demoted until an operator resets the supervisor.
    Dumps a replayable artifact through the quarantine hook."""
    global _last_quarantine
    br = _breaker(site)
    if br.state == "quarantined":
        return
    br.reopen_at = None
    _series(_QUARANTINES, site).add()
    _flight.record("quarantine", f"{site}:{detail}"[:160])
    _set_state(br, "quarantined")
    _last_quarantine = _quarantine_hook(site, detail)


def _default_quarantine_dump(site: str, detail: str):
    """Minimal standalone quarantine artifact (the sim harness installs
    a richer hook that records the full scenario script so
    ``sim/repro.py`` can replay the mismatch)."""
    global _quarantine_seq
    out_dir = os.environ.get("CS_TPU_SIM_ARTIFACTS", "sim_artifacts")
    _quarantine_seq += 1
    payload = {
        "kind": "quarantine",
        "site": site,
        "detail": detail,
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith("CS_TPU_")},
        "breakers": states(),
        # last-N-events tail: what the process was doing when the site
        # went dark (sim.repro prints it when replaying the artifact)
        "flight": _flight.dump(trigger="quarantine"),
    }
    path = os.path.join(
        out_dir, f"quarantine_{site.replace('.', '-')}_{_quarantine_seq}.json")
    try:
        from consensus_specs_tpu.recovery.atomic import atomic_write_json
        os.makedirs(out_dir, exist_ok=True)
        # temp + fsync + rename: quarantine evidence must never be a
        # torn file — it is usually read after the process died
        atomic_write_json(path, payload)
    except OSError:
        return None     # read-only host: quarantine still holds
    return path


_quarantine_hook = _default_quarantine_dump


@contextmanager
def quarantine_hook(fn):
    """Temporarily replace the artifact dump hook (harness use).  The
    hook receives ``(site, detail)`` and its return value is stored as
    :func:`last_quarantine`."""
    global _quarantine_hook
    prev = _quarantine_hook
    _quarantine_hook = fn
    try:
        yield
    finally:
        _quarantine_hook = prev


def last_quarantine():
    """Whatever the quarantine hook returned last (the default hook:
    the artifact path), or None."""
    return _last_quarantine


@contextmanager
def probe():
    """Mark a spec-loop audit replay in progress: engine dispatch
    declines (``probing()`` is True) so the replay runs the pure spec
    algorithms instead of recursing into the engine under audit."""
    global _probe_depth
    _probe_depth += 1
    try:
        yield
    finally:
        _probe_depth -= 1


def probing() -> bool:
    return _probe_depth > 0


# ---------------------------------------------------------------------------
# Deadline guards
# ---------------------------------------------------------------------------

# Per-thread: the serving pipeline resolves supervised ``bls.flush``
# dispatches on a worker lane while the main thread keeps arming scopes
# around state-transition dispatches; a shared stack would interleave
# push/pop across threads and :func:`deadline_check` would read the
# other lane's budget.
_deadline_local = threading.local()


def _deadline_stack_for_thread():
    stack = getattr(_deadline_local, "stack", None)
    if stack is None:
        stack = _deadline_local.stack = []
    return stack


@contextmanager
def deadline_scope(site: str):
    """Arm the per-dispatch wall-clock budget around an engine's
    compiled/native kernel section.  No-op (one env read, no stack
    push) when the supervisor is off or ``CS_TPU_DEADLINE_MS`` unset.
    A scope that exits cleanly but over budget books a deadline trip
    and a breaker failure post-hoc — the (correct) result still stands;
    only a mid-work :func:`deadline_check` converts the call itself
    into a fallback."""
    if not enabled():
        yield
        return
    budget = _config().deadline_s
    if budget <= 0:
        yield
        return
    start = _clock()
    entry = (site, start + budget, budget)
    _deadline_stack = _deadline_stack_for_thread()
    _deadline_stack.append(entry)
    try:
        yield
    except DeadlineExceeded:
        _series(_DEADLINE_TRIPS, site).add()
        raise
    else:
        elapsed = _clock() - start
        if elapsed > budget:
            _series(_DEADLINE_TRIPS, site).add()
            note_failure(site, "deadline")
    finally:
        _deadline_stack.pop()


def deadline_check() -> None:
    """Cooperative boundary check: raises :class:`DeadlineExceeded`
    when the innermost armed scope's budget is spent.  Disarmed cost:
    one thread-local attribute read."""
    _deadline_stack = getattr(_deadline_local, "stack", None)
    if not _deadline_stack:
        return
    site, until, budget = _deadline_stack[-1]
    now = _clock()
    if now > until:
        raise DeadlineExceeded(site, now - (until - budget), budget)


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def reset() -> None:
    """Forget all breaker/audit/deadline state and re-read the numeric
    knobs from the environment.  The sim harness calls this at every
    leg start (after applying the leg's env overrides) so legs replay
    cold; the test suite resets per test."""
    global _cfg, _rng, _last_quarantine, _quarantine_seq
    _breakers.clear()
    _audit_calls.clear()
    _audit_offsets.clear()
    _deadline_stack_for_thread().clear()
    _cfg = None
    _rng = None
    _last_quarantine = None
    _quarantine_seq = 0
    for g in _GAUGES.values():
        g.set(0)


# engines report counted fallbacks through faults.count_fallback; the
# hooks keep faults dependency-free while routing every counted trip
# into the breaker state machine and classifying deadline trips
faults._failure_hook = note_failure
faults._deadline_cls = DeadlineExceeded
