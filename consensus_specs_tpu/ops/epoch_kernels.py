"""Vectorized epoch-processing engine: array-native rewards, penalties
and balance updates.

The per-validator epoch loops (``process_rewards_and_penalties``,
``process_inactivity_updates``, ``process_effective_balance_updates``,
``process_registry_updates`` eligibility scans, ``process_slashings``)
are O(validators) python iterations over SSZ typed views — the last
python-loop-bound hot path at registry scale (BENCHMARKS.md config #5:
the 1M-validator epoch transition is all epoch-loop time).  This module
re-expresses them as columnar array kernels over the canonical
struct-of-arrays state store (``state/arrays.py``): columns are
extracted once per state lineage, revalidated structurally against the
SSZ mutation generations, mutated copy-on-write by the kernels, and —
inside the ``state_arrays.commit_scope`` the fork ladder opens around
``process_epoch`` — committed back to SSZ chunks once per epoch
transition instead of once per sub-transition.

Layering mirrors the BLS backend switch (``utils/bls.py``):

  use_vectorized() / use_loops() / use_auto()   runtime switch; auto
      (the default) is ON unless ``CS_TPU_VECTORIZED_EPOCH=0``
  try_process_*(spec, state) -> bool            entry points the fork
      ladder calls first; True means the vectorized engine committed the
      transition, False means "run the spec loop" (switch off, genesis
      no-op, or a guard tripped)
  install_vectorized_epoch(cls)                 wraps a markdown-compiled
      spec class's epoch methods with the same dispatch (the compiled
      ladder cannot carry hand-written calls in its method bodies)

Exactness contract: every kernel reproduces the spec loops bit-for-bit
— same uint64 truncations, same clamp-at-zero balance decreases, same
ordering — so post-state ``hash_tree_root`` is identical (enforced by
``tests/test_epoch_vectorized.py``).  All intermediate products are
range-checked against 2**64 with python-int bounds before any array op
runs; a state that could overflow a uint64 lane falls back to the spec
loop instead of wrapping.

The kernels themselves (``*_kernel``) are pure functions of arrays and
python scalars written against an ``xp`` array namespace: ``numpy`` on
the host (the production CPU path) and ``jax.numpy`` under ``jax.jit``
for device dispatch (uint64 lanes need ``jax_enable_x64``).
"""
import math

import numpy as np

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.utils import env_flags

from consensus_specs_tpu.state import arrays as state_arrays
# shared commit/extraction primitives live in the state layer now;
# re-exported here because the merkle bench smoke and older call sites
# import them under these names
from consensus_specs_tpu.state.arrays import (   # noqa: F401
    u64_column, _write_u64_list)
from consensus_specs_tpu.utils.ssz import sequence_items

_U64_MAX = (1 << 64) - 1

# ---------------------------------------------------------------------------
# Runtime switch (mirrors utils/bls.py's use_py/use_jax/use_fastest)
# ---------------------------------------------------------------------------

_mode = "auto"


def use_vectorized() -> None:
    """Force the array engine on (guards can still fall back)."""
    global _mode
    _mode = "on"


def use_loops() -> None:
    """Force the per-validator spec loops (the differential oracle)."""
    global _mode
    _mode = "off"


def use_auto() -> None:
    """Default policy: on unless ``CS_TPU_VECTORIZED_EPOCH=0``."""
    global _mode
    _mode = "auto"


def backend_name() -> str:
    return "loops" if not enabled() else "vectorized"


def enabled() -> bool:
    if _mode == "on":
        return True
    if _mode == "off":
        return False
    return env_flags.switch("CS_TPU_VECTORIZED_EPOCH")


# vectorized-commit / guard-fallback counters; the differential suite
# asserts on these so a silent fallback cannot turn its comparisons
# into loop-vs-loop tautologies.  Registered in the obs metrics registry
# as ``epoch.transition{path=vectorized|loop}`` plus a dedicated
# guard-trip counter (series pre-bound, speclint O5xx hot-path rule).
# ``path=loop`` counts every transition the spec loop ended up running
# (engine off, genesis no-op, or a guard trip); ``epoch.fallbacks
# {reason=guard|injected}`` counts only the trips among them — organic
# guard refusals vs faults injected by the adversarial harness
# (``consensus_specs_tpu/faults.py``).
_C_EPOCH_VECTORIZED = obs_registry.counter(
    "epoch.transition").labels(path="vectorized")
_C_EPOCH_LOOP = obs_registry.counter("epoch.transition").labels(path="loop")
_C_EPOCH_FALLBACKS_ALL = obs_registry.counter("epoch.fallbacks")
_EPOCH_FALLBACKS = {
    "guard": _C_EPOCH_FALLBACKS_ALL.labels(reason="guard"),
    "injected": _C_EPOCH_FALLBACKS_ALL.labels(reason="injected"),
    "deadline": _C_EPOCH_FALLBACKS_ALL.labels(reason="deadline"),
}


def stats() -> dict:
    """Back-compat alias view of the ``epoch.*`` registry metrics (the
    differential suite asserts on these keys)."""
    return {"vectorized": _C_EPOCH_VECTORIZED.n,
            "fallback": _C_EPOCH_FALLBACKS_ALL.total()}


def reset_stats() -> None:
    obs_registry.reset("epoch.")


class _Fallback(Exception):
    """A guard refused the array path (possible uint64 overflow or an
    unsupported shape); the caller runs the spec loop instead."""


def _guard(*products) -> None:
    """Fail over to the spec loop if any python-int bound reaches 2**64."""
    for p in products:
        if p > _U64_MAX:
            raise _Fallback()


# ---------------------------------------------------------------------------
# Struct-of-arrays state access (state/arrays.py)
# ---------------------------------------------------------------------------
#
# The registry snapshot, balance and participation columns all come from
# the state's attached copy-on-write ``StateArrays`` store: extracted
# once per state lineage, revalidated against the SSZ sequences'
# mutation generations (a write through the sequence API bumps the
# generation, so a stale column is structurally impossible), and — when
# the fork ladder's ``commit_scope`` is open around ``process_epoch`` —
# committed back to SSZ chunks once per transition.  The root-keyed
# ``_COLS_CACHE`` LRU this module used to keep is gone; every guard
# fallback flushes pending column writes first so the spec loop always
# reads fresh SSZ.


# ---------------------------------------------------------------------------
# Pure array kernels (xp = numpy on host, jax.numpy under jit)
# ---------------------------------------------------------------------------

def apply_deltas_kernel(xp, balances, rewards, penalties):
    """increase_balance then clamped decrease_balance, per validator."""
    up = balances + rewards
    return xp.where(penalties > up, xp.uint64(0), up - penalties)


# speclint: guarded-by-caller (try_process_* bounds every product < 2**64)
def flag_deltas_kernel(xp, base_reward, eligible, participating, *,
                       weight, weight_denominator, participating_increments,
                       active_increments, in_leak, is_head_flag):
    """altair ``get_flag_index_deltas`` for one participation flag."""
    zero = xp.uint64(0)
    reward = (base_reward * xp.uint64(weight)
              * xp.uint64(participating_increments)) \
        // xp.uint64(active_increments * weight_denominator)
    rewards = xp.where(eligible & participating & (not in_leak), reward, zero)
    penalty = (base_reward * xp.uint64(weight)) // xp.uint64(weight_denominator)
    penalize = eligible & ~participating & (not is_head_flag)
    penalties = xp.where(penalize, penalty, zero)
    return rewards, penalties


# speclint: guarded-by-caller (try_process_* bounds eff * scores < 2**64)
def inactivity_penalty_kernel(xp, eff, scores, eligible, target_participating,
                              *, denominator):
    """altair+ ``get_inactivity_penalty_deltas`` (score-scaled)."""
    penalty = (eff * scores) // xp.uint64(denominator)
    return xp.where(eligible & ~target_participating, penalty, xp.uint64(0))


def inactivity_updates_kernel(xp, scores, eligible, participating, *,
                              bias, recovery_rate, in_leak):
    """altair ``process_inactivity_updates`` score transition."""
    one = xp.uint64(1)
    bumped = xp.where(participating, scores - xp.minimum(one, scores),
                      scores + xp.uint64(bias))
    if not in_leak:
        rec = xp.uint64(recovery_rate)
        bumped = bumped - xp.minimum(rec, bumped)
    return xp.where(eligible, bumped, scores)


# speclint: guarded-by-caller (br_max * att_increments bounded < 2**64)
def phase0_component_kernel(xp, base_reward, eligible, attesting, *,
                            in_leak, attesting_increments, total_increments):
    """phase0 ``get_attestation_component_deltas`` (source/target/head)."""
    zero = xp.uint64(0)
    if in_leak:
        # full base reward; canceled later by the inactivity deltas
        reward = base_reward
    else:
        reward = (base_reward * xp.uint64(attesting_increments)) \
            // xp.uint64(total_increments)
    rewards = xp.where(eligible & attesting, reward, zero)
    penalties = xp.where(eligible & ~attesting, base_reward, zero)
    return rewards, penalties


# speclint: guarded-by-caller (base_pen + extra bounded together < 2**64)
# speclint: invariant: base_rewards_per_epoch >= 1
# speclint: invariant: proposer_reward_quotient >= 1
def phase0_inactivity_kernel(xp, base_reward, eff, eligible,
                             target_attesting, *, base_rewards_per_epoch,
                             proposer_reward_quotient, finality_delay,
                             inactivity_penalty_quotient):
    """phase0 ``get_inactivity_penalty_deltas`` (leak epochs only)."""
    zero = xp.uint64(0)
    proposer_reward = base_reward // xp.uint64(proposer_reward_quotient)
    # machine-checked safe (speclint U9xx range prover): the declared
    # invariants give proposer_reward <= base_reward <= brpe*base_reward
    base_pen = (xp.uint64(base_rewards_per_epoch) * base_reward
                - proposer_reward)
    extra = (eff * xp.uint64(finality_delay)) \
        // xp.uint64(inactivity_penalty_quotient)
    pen = base_pen + xp.where(target_attesting, zero, extra)
    return xp.where(eligible, pen, zero)


def effective_balance_kernel(xp, balances, eff, *, increment,
                             downward_threshold, upward_threshold,
                             max_effective_balance):
    """``process_effective_balance_updates`` hysteresis."""
    crossed = ((balances + xp.uint64(downward_threshold) < eff)
               | (eff + xp.uint64(upward_threshold) < balances))
    capped = xp.minimum(balances - balances % xp.uint64(increment),
                        xp.uint64(max_effective_balance))
    return xp.where(crossed, capped, eff)


# speclint: guarded-by-caller ((eff // increment) * adjusted bounded < 2**64)
def slashing_penalty_kernel(xp, eff, target, *, increment,
                            adjusted_total_slashing_balance, total_balance):
    """``process_slashings`` penalty column (spec's truncation order:
    divide by total_balance BEFORE multiplying back by increment)."""
    numer = (eff // xp.uint64(increment)) \
        * xp.uint64(adjusted_total_slashing_balance)
    penalty = (numer // xp.uint64(total_balance)) * xp.uint64(increment)
    return xp.where(target, penalty, xp.uint64(0))


# ---------------------------------------------------------------------------
# Scalar plumbing shared by the orchestrators
# ---------------------------------------------------------------------------

def _fork_lineage(spec) -> frozenset:
    """Fork names along the spec class's inheritance chain — works for
    the hand-written and the markdown-compiled ladder alike (both stamp
    ``fork`` on every class)."""
    return frozenset(
        c.__dict__["fork"] for c in type(spec).__mro__
        if isinstance(c.__dict__.get("fork"), str))


def _masked_sum(eff, mask) -> int:
    """Exact python-int sum of a masked uint64 column."""
    sub = eff[mask]
    if sub.size == 0:
        return 0
    mx = int(sub.max())
    if mx and sub.size > _U64_MAX // mx:
        return sum(int(x) for x in sub.tolist())
    return int(sub.sum(dtype=np.uint64))


def _epoch_masks(spec, cols, previous_epoch):
    """active-at-previous-epoch and reward-eligibility masks
    (``get_eligible_validator_indices``)."""
    prev = np.uint64(previous_epoch)
    active_prev = (cols["act"] <= prev) & (prev < cols["ext"])
    eligible = active_prev | (cols["sl"] & (previous_epoch + 1 < cols["wd"]))
    return active_prev, eligible


def _total_active_balance(spec, cols, current_epoch) -> int:
    """``get_total_active_balance`` from columns (same increment clamp)."""
    cur = np.uint64(current_epoch)
    active = (cols["act"] <= cur) & (cur < cols["ext"])
    return max(int(spec.EFFECTIVE_BALANCE_INCREMENT),
               _masked_sum(cols["eff"], active))


def _mask_from_indices(n, indices) -> np.ndarray:
    mask = np.zeros(n, dtype=bool)
    if indices:
        mask[np.fromiter(indices, dtype=np.int64, count=len(indices))] = True
    return mask


# ---------------------------------------------------------------------------
# Supervised dispatch plumbing (shared by the five try_process_* sites)
# ---------------------------------------------------------------------------

def _audited(spec, state, site, method_name, fast_fn) -> bool:
    """Sentinel-audited epoch call (``supervisor.audit_due``): the spec
    loop runs on the REAL state — its result is authoritative, so a
    silently-wrong kernel cannot leak into the chain even on the
    audited call itself — while the vectorized kernel runs on a
    throwaway copy, and the two post-states must merkleize
    byte-identical.  A mismatch quarantines the site.  Returns True:
    the sub-transition has been applied (by the spec loop) either way,
    so the caller must not run its body again."""
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    probe = state.copy()
    handled = False
    try:
        faults.check(site)
        with supervisor.deadline_scope(site):
            handled = fast_fn(spec, probe)
    except (_Fallback, faults.InjectedFault,
            supervisor.DeadlineExceeded) as exc:
        faults.count_fallback(_EPOCH_FALLBACKS, exc, site=site)
    # the spec loop, via the wrapped/inline-dispatched spec method:
    # probing() makes every try_process_* decline — which books the
    # path=loop counter and flushes pending columns itself (_decline),
    # so this helper must NOT double-book either — and the replay
    # really is the per-validator loop, never recursing into the kernel
    with supervisor.probe():
        getattr(spec, method_name)(state)
    if handled:
        state_arrays.flush(probe)
        ok = bytes(hash_tree_root(probe)) == bytes(hash_tree_root(state))
        supervisor.audit_result(
            site, ok, f"vectorized {method_name} post-state root "
            "diverged from the spec loop")
    return True


def _decline(state) -> bool:
    """The common spec-loop decline bookkeeping (returns False)."""
    state_arrays.flush(state)
    _C_EPOCH_LOOP.add()
    return False


def _supervised(spec, state, site, method_name, fast_fn) -> bool:
    """The shared supervised-dispatch skeleton behind every
    try_process_* site (the per-site wrappers keep only their
    site-specific no-op pre-checks): breaker admission, sentinel-audit
    sampling, the fault hook + deadline scope around the kernel body,
    counted fallback on any fallback-class trip, health reporting on
    success.  ``fast_fn`` returns False when the kernel has nothing to
    do (the caller's spec body runs instead, no fallback implied)."""
    if not supervisor.admit(site):
        return _decline(state)
    if supervisor.audit_due(site):
        return _audited(spec, state, site, method_name, fast_fn)
    try:
        faults.check(site)
        with supervisor.deadline_scope(site):
            if not fast_fn(spec, state):
                _C_EPOCH_LOOP.add()
                return False
    except (_Fallback, faults.InjectedFault,
            supervisor.DeadlineExceeded) as exc:
        state_arrays.flush(state)
        faults.count_fallback(_EPOCH_FALLBACKS, exc, site=site)
        _C_EPOCH_LOOP.add()
        return False
    supervisor.note_success(site)
    _C_EPOCH_VECTORIZED.add()
    return True


# ---------------------------------------------------------------------------
# process_rewards_and_penalties
# ---------------------------------------------------------------------------

def _fast_rewards_and_penalties(spec, state) -> bool:
    from consensus_specs_tpu.parallel import mesh_epoch
    if mesh_epoch.try_rewards_and_penalties(spec, state):
        pass    # SPMD program committed the balance column
    elif "altair" in _fork_lineage(spec):
        _altair_rewards_and_penalties(spec, state)
    else:
        _phase0_rewards_and_penalties(spec, state)
    if faults.corrupt_armed("epoch.rewards_and_penalties"):
        # silent-corruption injection (sentinel-audit test vector):
        # one gwei on validator 0, exactly the class of wrongness a
        # counted fallback can never surface
        sa = state_arrays.of(state)
        balances = sa.balances().copy()
        if balances.size:
            balances[0] += np.uint64(1)
            sa.set_balances(balances)
    return True


def try_process_rewards_and_penalties(spec, state) -> bool:
    if not enabled() or supervisor.probing():
        return _decline(state)
    if spec.get_current_epoch(state) == spec.GENESIS_EPOCH:
        _C_EPOCH_LOOP.add()
        return False    # the spec body is already a no-op early return
    return _supervised(spec, state, "epoch.rewards_and_penalties",
                       "process_rewards_and_penalties",
                       _fast_rewards_and_penalties)


def _base_reward_phase0(spec, cols, total_balance):
    """phase0 ``get_base_reward`` column + its python-int max bound."""
    sqrt_total = spec.integer_squareroot(total_balance)
    brf = int(spec.BASE_REWARD_FACTOR)
    brpe = int(spec.BASE_REWARDS_PER_EPOCH)
    max_eff = int(cols["eff"].max(initial=0))
    _guard(max_eff * brf)
    base_reward = (cols["eff"] * np.uint64(brf)) \
        // np.uint64(int(sqrt_total)) // np.uint64(brpe)
    return base_reward, max_eff * brf // int(sqrt_total) // brpe


def _phase0_rewards_and_penalties(spec, state) -> None:
    """``get_attestation_deltas`` + the balance-update loop, columnar.
    The O(attestations) committee work stays in python (it is already
    cached and small); every O(validators) pass runs as an array op."""
    xp = np
    prev_epoch = spec.get_previous_epoch(state)
    # spec helpers up front: their assertion behavior (exception as
    # invalidity) must fire exactly as in the loop path, before any write
    src_atts = spec.get_matching_source_attestations(state, prev_epoch)
    tgt_atts = spec.get_matching_target_attestations(state, prev_epoch)
    head_atts = spec.get_matching_head_attestations(state, prev_epoch)
    src_set = spec.get_unslashed_attesting_indices(state, src_atts)
    tgt_set = spec.get_unslashed_attesting_indices(state, tgt_atts)
    head_set = spec.get_unslashed_attesting_indices(state, head_atts)

    sa = state_arrays.of(state)
    cols = sa.registry()
    n = len(cols)
    if n == 0:
        return
    eff = cols["eff"]
    _, eligible = _epoch_masks(spec, cols, int(prev_epoch))
    total_balance = _total_active_balance(spec, cols,
                                          int(spec.get_current_epoch(state)))
    _guard(total_balance)
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    total_increments = total_balance // increment
    in_leak = bool(spec.is_in_inactivity_leak(state))
    base_reward, br_max = _base_reward_phase0(spec, cols, total_balance)

    reward_parts, penalty_parts = [], []
    for att_set in (src_set, tgt_set, head_set):
        att_mask = _mask_from_indices(n, att_set)
        att_balance = max(increment, _masked_sum(eff, att_mask))
        att_increments = att_balance // increment
        _guard(br_max * att_increments)
        r, p = phase0_component_kernel(
            xp, base_reward, eligible, att_mask, in_leak=in_leak,
            attesting_increments=att_increments,
            total_increments=total_increments)
        reward_parts.append(r)
        penalty_parts.append(p)

    # cooperative deadline boundary between the component kernels and
    # the inclusion-delay pass (scope armed by the try_process wrapper)
    supervisor.deadline_check()
    # inclusion-delay rewards: one ordered pass over the source
    # attestations finds each attester's earliest-included attestation
    # (the spec's min() keeps the first minimum, hence the strict <)
    # speclint: invariant: prq >= 1
    prq = int(spec.PROPOSER_REWARD_QUOTIENT)
    src_mask = _mask_from_indices(n, src_set)
    best_delay = np.full(n, _U64_MAX, dtype=np.uint64)
    best_proposer = np.zeros(n, dtype=np.int64)
    for att in src_atts:
        idxs = spec.get_attesting_indices(state, att.data,
                                          att.aggregation_bits)
        if not idxs:
            continue
        ii = np.fromiter(idxs, dtype=np.int64, count=len(idxs))
        upd = np.uint64(int(att.inclusion_delay)) < best_delay[ii]
        sel = ii[upd]
        best_delay[sel] = np.uint64(int(att.inclusion_delay))
        best_proposer[sel] = int(att.proposer_index)
    proposer_reward = base_reward // np.uint64(prq)
    incl_rewards = np.zeros(n, dtype=np.uint64)
    src_idx = np.nonzero(src_mask)[0]
    if src_idx.size:
        # machine-checked safe (speclint U9xx range prover):
        # proposer_reward = base_reward // prq <= base_reward with the
        # declared prq >= 1 invariant, preserved under the shared index
        max_attester = (base_reward[src_idx]
                        - proposer_reward[src_idx])
        incl_rewards[src_idx] = max_attester // best_delay[src_idx]
        # every attester's proposer cut could land on ONE proposer index
        _guard(br_max + src_idx.size * (br_max // prq))
        np.add.at(incl_rewards, best_proposer[src_idx],
                  proposer_reward[src_idx])
    reward_parts.append(incl_rewards)

    # inactivity penalties (leak epochs)
    if in_leak:
        finality_delay = int(spec.get_finality_delay(state))
        tgt_mask = _mask_from_indices(n, tgt_set)
        max_eff = int(eff.max(initial=0))
        # base_pen + extra is one uint64 lane sum: bound the two together
        _guard(int(spec.BASE_REWARDS_PER_EPOCH) * br_max
               + max_eff * finality_delay)
        penalty_parts.append(phase0_inactivity_kernel(
            xp, base_reward, eff, eligible, tgt_mask,
            base_rewards_per_epoch=int(spec.BASE_REWARDS_PER_EPOCH),
            proposer_reward_quotient=prq, finality_delay=finality_delay,
            inactivity_penalty_quotient=int(spec.INACTIVITY_PENALTY_QUOTIENT)))

    _guard(sum(int(r.max(initial=0)) for r in reward_parts),
           sum(int(p.max(initial=0)) for p in penalty_parts))
    rewards = reward_parts[0]
    for r in reward_parts[1:]:
        rewards = rewards + r
    penalties = penalty_parts[0]
    for p in penalty_parts[1:]:
        penalties = penalties + p

    balances = sa.balances()
    _guard(int(balances.max(initial=0)) + int(rewards.max(initial=0)))
    new_balances = apply_deltas_kernel(xp, balances, rewards, penalties)
    sa.set_balances(new_balances)


def _altair_participation(spec, sa, cols, flag_index, active_prev):
    """``get_unslashed_participating_indices`` as a mask (prev epoch)."""
    flags = sa.participation("previous")
    has_flag = (flags >> np.uint8(flag_index)) & np.uint8(1) == np.uint8(1)
    return active_prev & has_flag & ~cols["sl"]


def _altair_rewards_and_penalties(spec, state) -> None:
    """altair+ flag deltas + inactivity deltas, applied pairwise in spec
    order (each pair's decrease clamps at zero before the next applies)."""
    xp = np
    sa = state_arrays.of(state)
    cols = sa.registry()
    n = len(cols)
    if n == 0:
        return
    eff = cols["eff"]
    prev_epoch = int(spec.get_previous_epoch(state))
    active_prev, eligible = _epoch_masks(spec, cols, prev_epoch)
    total_balance = _total_active_balance(spec, cols,
                                          int(spec.get_current_epoch(state)))
    _guard(total_balance)
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    active_increments = total_balance // increment
    in_leak = bool(spec.is_in_inactivity_leak(state))
    weight_denominator = int(spec.WEIGHT_DENOMINATOR)
    brpi = increment * int(spec.BASE_REWARD_FACTOR) \
        // math.isqrt(total_balance)
    max_eff = int(eff.max(initial=0))
    _guard((max_eff // increment) * brpi)
    base_reward = (eff // np.uint64(increment)) * np.uint64(brpi)
    br_max = (max_eff // increment) * brpi

    head_flag = int(spec.TIMELY_HEAD_FLAG_INDEX)
    target_flag = int(spec.TIMELY_TARGET_FLAG_INDEX)
    delta_pairs = []
    target_participating = None
    for flag_index, weight in enumerate(spec.PARTICIPATION_FLAG_WEIGHTS):
        # cooperative deadline boundary: one check per flag component
        # (deadline_scope armed by try_process_rewards_and_penalties)
        supervisor.deadline_check()
        participating = _altair_participation(
            spec, sa, cols, flag_index, active_prev)
        if flag_index == target_flag:
            target_participating = participating
        up_balance = max(increment, _masked_sum(eff, participating))
        up_increments = up_balance // increment
        _guard(br_max * int(weight) * up_increments)
        delta_pairs.append(flag_deltas_kernel(
            xp, base_reward, eligible, participating,
            weight=int(weight), weight_denominator=weight_denominator,
            participating_increments=up_increments,
            active_increments=active_increments, in_leak=in_leak,
            is_head_flag=flag_index == head_flag))

    quotient = (int(spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
                if "bellatrix" in _fork_lineage(spec)
                else int(spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR))
    # the store's view: includes the scores process_inactivity_updates
    # may have written earlier in this (possibly still uncommitted)
    # epoch transition — exactly what the spec loop would read from SSZ
    scores = sa.inactivity_scores()
    _guard(max_eff * int(scores.max(initial=0)))
    inactivity_penalties = inactivity_penalty_kernel(
        xp, eff, scores, eligible, target_participating,
        denominator=int(spec.config.INACTIVITY_SCORE_BIAS) * quotient)
    delta_pairs.append((np.zeros(n, dtype=np.uint64), inactivity_penalties))

    balances = sa.balances()
    max_bal = int(balances.max(initial=0))
    for rewards, penalties in delta_pairs:
        _guard(max_bal + int(rewards.max(initial=0)))
        balances = apply_deltas_kernel(xp, balances, rewards, penalties)
        max_bal = int(balances.max(initial=0))
    sa.set_balances(balances)


# ---------------------------------------------------------------------------
# process_inactivity_updates (altair+)
# ---------------------------------------------------------------------------

def _fast_inactivity_updates(spec, state) -> bool:
    from consensus_specs_tpu.parallel import mesh_epoch
    if mesh_epoch.try_inactivity_updates(spec, state):
        return True
    sa = state_arrays.of(state)
    cols = sa.registry()
    if len(cols) == 0:
        return False
    prev_epoch = int(spec.get_previous_epoch(state))
    active_prev, eligible = _epoch_masks(spec, cols, prev_epoch)
    participating = _altair_participation(
        spec, sa, cols, int(spec.TIMELY_TARGET_FLAG_INDEX), active_prev)
    scores = sa.inactivity_scores()
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    _guard(int(scores.max(initial=0)) + bias)
    new_scores = inactivity_updates_kernel(
        np, scores, eligible, participating, bias=bias,
        recovery_rate=int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE),
        in_leak=bool(spec.is_in_inactivity_leak(state)))
    sa.set_inactivity_scores(new_scores)
    return True


def try_process_inactivity_updates(spec, state) -> bool:
    if not enabled() or supervisor.probing():
        return _decline(state)
    if spec.get_current_epoch(state) == spec.GENESIS_EPOCH:
        _C_EPOCH_LOOP.add()
        return False    # spec body no-ops
    if "altair" not in _fork_lineage(spec):
        _C_EPOCH_LOOP.add()
        return False
    return _supervised(spec, state, "epoch.inactivity_updates",
                       "process_inactivity_updates",
                       _fast_inactivity_updates)


# ---------------------------------------------------------------------------
# process_registry_updates
# ---------------------------------------------------------------------------

def _fast_registry_updates(spec, state) -> bool:
    from consensus_specs_tpu.parallel import mesh_epoch
    if mesh_epoch.try_registry_updates(spec, state):
        return True
    _registry_updates(spec, state)
    return True


def try_process_registry_updates(spec, state) -> bool:
    if not enabled() or supervisor.probing():
        return _decline(state)
    return _supervised(spec, state, "epoch.registry_updates",
                       "process_registry_updates", _fast_registry_updates)


def _registry_updates(spec, state) -> None:
    """Eligibility scans and the activation-queue sort as array ops; the
    per-ejection exit-queue recurrence (a running max + churn counter) is
    simulated incrementally instead of re-scanning the registry per exit.

    Registry mutations run copy-on-write: the shared store columns are
    only copied (``registry_writable``) when this epoch actually stamps,
    ejects or activates someone — the common quiet epoch touches
    nothing.  SSZ per-index writes and column writes stay paired, then
    ``mark_registry_committed`` re-stamps the store."""
    sa = state_arrays.of(state)
    cols = sa.registry()
    n = len(cols)
    if n == 0:
        return
    current_epoch = int(spec.get_current_epoch(state))
    far_future = int(spec.FAR_FUTURE_EPOCH)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    aee = cols["aee"]

    # cooperative deadline boundary before the eligibility scans
    # (deadline_scope armed by try_process_registry_updates)
    supervisor.deadline_check()
    # eligibility scans (the half the mesh engine runs shard-local on
    # the device mesh — parallel/mesh_epoch._p_registry_scan computes
    # these same facts as compact per-shard candidate index buffers and
    # funnels them into the shared _registry_apply_idx body below)
    queue_mask = (aee == np.uint64(far_future)) \
        & (cols["eff"] == np.uint64(max_eb))
    cur = np.uint64(current_epoch)
    active_cur = (cols["act"] <= cur) & (cur < cols["ext"])
    eject_mask = active_cur & (cols["eff"] <= np.uint64(
        int(spec.config.EJECTION_BALANCE)))
    # pending activations: stamped-this-epoch entries carry aee ==
    # current_epoch + 1 > finalized, and unstamped candidates carry
    # FAR_FUTURE_EPOCH — neither passes the finalized bound, so the
    # scan commutes with the stamping writes below
    eligible_mask = (aee <= np.uint64(
        int(state.finalized_checkpoint.epoch))) \
        & (cols["act"] == np.uint64(far_future))
    # explicit accumulator: a bool .sum() uses the platform default int,
    # which is 32-bit on some hosts — silently wrong above 2**31 lanes
    active_count = int(active_cur.sum(dtype=np.int64))
    _registry_apply(spec, state, sa, cols, queue_mask, eject_mask,
                    eligible_mask, active_count)


def _registry_apply(spec, state, sa, cols, queue_mask, eject_mask,
                    eligible_mask, active_count) -> None:
    """Mask-shaped entry into :func:`_registry_apply_idx` for the
    single-device engine: reduce the full-column eligibility masks to
    their (ascending) candidate index sets and resolve through the
    shared churn-ordered body."""
    _registry_apply_idx(spec, state, sa, cols,
                        np.nonzero(queue_mask)[0],
                        np.nonzero(eject_mask)[0],
                        np.nonzero(eligible_mask)[0],
                        active_count)


def _registry_apply_idx(spec, state, sa, cols, queue_idx, eject_idx,
                        eligible_idx, active_count) -> None:
    """Churn-ordered resolution of the registry scans: activation-queue
    stamps, the per-ejection exit-queue recurrence, and the
    (activation_eligibility_epoch, index)-sorted dequeue — shared by the
    single-device engine (via the :func:`_registry_apply` mask wrapper)
    and the mesh engine (whose shard-local scans hand their bounded,
    ascending candidate index sets straight here), so cross-shard
    ordering is byte-identical to the spec loop by construction.
    Candidate sets are bounded (registry churn, not registry size), so
    this body touches O(candidates) lanes — the two full-column ejection
    scans below are the documented exception, spec-required exact
    queue-state reads at the commit boundary."""
    validators = sequence_items(state.validators)
    current_epoch = int(spec.get_current_epoch(state))
    far_future = int(spec.FAR_FUTURE_EPOCH)

    wcols = None

    def writable():
        nonlocal wcols, cols
        if wcols is None:
            wcols = sa.registry_writable()
            cols = wcols
        return wcols

    aee = cols["aee"]

    # activation-queue eligibility stamps (is_eligible_for_activation_queue)
    stamp = current_epoch + 1
    if queue_idx.size:
        # copy-on-write BEFORE the paired SSZ writes: the generation
        # bump would otherwise read as a stale cell and re-extract
        aee = writable()["aee"]
        for i in queue_idx.tolist():
            validators[i].activation_eligibility_epoch = stamp
        aee[queue_idx] = np.uint64(stamp)

    # ejections: initiate_validator_exit per index, in index order.  The
    # churn limit is constant across the loop (assigned exit epochs are
    # all in the future, so current-epoch activity never changes).
    churn = max(int(spec.config.MIN_PER_EPOCH_CHURN_LIMIT),
                active_count // int(spec.config.CHURN_LIMIT_QUOTIENT))
    if eject_idx.size:
        ext = writable()["ext"]
        wd = wcols["wd"]
        # the exit-queue seed (max assigned exit epoch, and how much of
        # that epoch's churn is already spent) is a property of the FULL
        # exit column — a spec-required exact read, O(n) by nature, not
        # replaceable by a candidate gather (any validator may already
        # hold the max exit epoch)
        exited = ext[ext != np.uint64(far_future)]  # noqa: N1301
        queue_epoch = current_epoch + 1 + int(spec.MAX_SEED_LOOKAHEAD)
        if exited.size:
            queue_epoch = max(queue_epoch, int(exited.max()))
        qe = np.uint64(queue_epoch)
        queue_churn = int((ext == qe).sum(dtype=np.int64))  # noqa: N1301
        delay = int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
        _guard(queue_epoch + eject_idx.size + delay)
        for i in eject_idx.tolist():
            if int(ext[i]) != far_future:
                continue
            if queue_churn >= churn:
                queue_epoch += 1
                queue_churn = 0
            queue_churn += 1
            ext[i] = np.uint64(queue_epoch)
            wd[i] = np.uint64(queue_epoch + delay)
            validators[i].exit_epoch = queue_epoch
            validators[i].withdrawable_epoch = queue_epoch + delay

    # activations: sort eligibles by (activation_eligibility_epoch, index),
    # dequeue up to the (fork-dependent) activation churn limit
    idx = eligible_idx
    if idx.size:
        # re-read: the queue stamps above may have copied the column
        aee = cols["aee"]
        order = np.lexsort((idx, aee[idx]))
        activation_churn = churn
        if "deneb" in _fork_lineage(spec):
            activation_churn = min(
                int(spec.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT), churn)
        take = idx[order][:activation_churn].tolist()
        if take:
            activation_epoch = current_epoch + 1 + int(spec.MAX_SEED_LOOKAHEAD)
            act = writable()["act"]
            for i in take:
                validators[i].activation_epoch = activation_epoch
                act[i] = np.uint64(activation_epoch)

    if wcols is not None:
        sa.mark_registry_committed()


# ---------------------------------------------------------------------------
# process_slashings
# ---------------------------------------------------------------------------

def _fast_slashings(spec, state) -> bool:
    lineage = _fork_lineage(spec)
    if "bellatrix" in lineage:
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
    elif "altair" in lineage:
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    else:
        multiplier = spec.PROPORTIONAL_SLASHING_MULTIPLIER
    from consensus_specs_tpu.parallel import mesh_epoch
    if mesh_epoch.try_slashings(spec, state, int(multiplier)):
        return True
    _slashings(spec, state, int(multiplier))
    return True


def try_process_slashings(spec, state) -> bool:
    if not enabled() or supervisor.probing():
        return _decline(state)
    return _supervised(spec, state, "epoch.slashings",
                       "process_slashings", _fast_slashings)


def _slashings(spec, state, multiplier) -> None:
    sa = state_arrays.of(state)
    cols = sa.registry()
    if len(cols) == 0:
        return
    epoch = int(spec.get_current_epoch(state))
    total_balance = _total_active_balance(spec, cols, epoch)
    _guard(total_balance)
    slashed_sum = sum(int(s) for s in sequence_items(state.slashings))
    adjusted = min(slashed_sum * multiplier, total_balance)
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    target_epoch = epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2
    _guard(target_epoch)
    target = cols["sl"] & (cols["wd"] == np.uint64(target_epoch))
    if not target.any():
        return
    _guard((int(cols["eff"].max(initial=0)) // increment) * adjusted)
    penalties = slashing_penalty_kernel(
        np, cols["eff"], target, increment=increment,
        adjusted_total_slashing_balance=adjusted, total_balance=total_balance)
    balances = sa.balances()
    new_balances = np.where(penalties > balances, np.uint64(0),
                            balances - penalties)
    sa.set_balances(new_balances)


# ---------------------------------------------------------------------------
# process_effective_balance_updates
# ---------------------------------------------------------------------------

def _fast_effective_balance_updates(spec, state) -> bool:
    from consensus_specs_tpu.parallel import mesh_epoch
    if mesh_epoch.try_effective_balance_updates(spec, state):
        return True
    _effective_balance_updates(spec, state)
    return True


def try_process_effective_balance_updates(spec, state) -> bool:
    if not enabled() or supervisor.probing():
        return _decline(state)
    return _supervised(spec, state, "epoch.effective_balance_updates",
                       "process_effective_balance_updates",
                       _fast_effective_balance_updates)


def _effective_balance_updates(spec, state) -> None:
    sa = state_arrays.of(state)
    cols = sa.registry()
    if len(cols) == 0:
        return
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    hysteresis_increment = increment // int(spec.HYSTERESIS_QUOTIENT)
    down = hysteresis_increment * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)
    up = hysteresis_increment * int(spec.HYSTERESIS_UPWARD_MULTIPLIER)
    # the store's balances: includes this transition's still-deferred
    # reward/slashing writes, exactly what the spec loop would read
    balances = sa.balances()
    eff = cols["eff"]
    _guard(int(balances.max(initial=0)) + down, int(eff.max(initial=0)) + up)
    new_eff = effective_balance_kernel(
        np, balances, eff, increment=increment, downward_threshold=down,
        upward_threshold=up,
        max_effective_balance=int(spec.MAX_EFFECTIVE_BALANCE))
    changed = np.nonzero(eff != new_eff)[0]
    if changed.size == 0:
        return
    # copy-on-write BEFORE the paired SSZ writes (generation bump)
    sa.registry_writable()["eff"] = new_eff
    validators = sequence_items(state.validators)
    for i in changed.tolist():
        validators[i].effective_balance = int(new_eff[i])
    sa.mark_registry_committed()


# ---------------------------------------------------------------------------
# Compiled-ladder routing
# ---------------------------------------------------------------------------

_TRY_BY_NAME = {
    "process_rewards_and_penalties": try_process_rewards_and_penalties,
    "process_inactivity_updates": try_process_inactivity_updates,
    "process_registry_updates": try_process_registry_updates,
    "process_slashings": try_process_slashings,
    "process_effective_balance_updates": try_process_effective_balance_updates,
}


def install_vectorized_epoch(cls) -> None:
    """Wrap a spec class's own epoch methods with the vectorized
    dispatch.  Used for the markdown-compiled ladder, whose method bodies
    are emitted verbatim from the spec text and therefore cannot carry
    the hand-written ladder's inline ``try_process_*`` calls.  Only
    methods defined on ``cls`` itself are wrapped (inherited ones are
    already wrapped on the base class), and wrapping is idempotent.

    ``process_epoch`` itself is additionally wrapped in the state-store
    commit scope (``state_arrays.commit_scope``) so the deferrable
    column writes of the whole transition flush to SSZ chunks once, at
    scope exit — unless the class opts out via
    ``_defer_epoch_commits = False`` (forks whose epoch ordering
    interleaves non-engine balance writes, e.g. custody_game)."""
    import functools
    for name, try_fn in _TRY_BY_NAME.items():
        fn = cls.__dict__.get(name)
        if fn is None or getattr(fn, "_vectorized_epoch_wrapper", False):
            continue

        def _make(orig, tfn):
            @functools.wraps(orig)
            def wrapper(self, state):
                if tfn(self, state):
                    return None
                return orig(self, state)
            wrapper._vectorized_epoch_wrapper = True
            return wrapper

        setattr(cls, name, _make(fn, try_fn))

    fn = cls.__dict__.get("process_epoch")
    if fn is not None and not getattr(fn, "_vectorized_epoch_wrapper", False) \
            and getattr(cls, "_defer_epoch_commits", True):
        @functools.wraps(fn)
        def epoch_wrapper(self, state, _orig=fn):
            with state_arrays.commit_scope(state):
                return _orig(self, state)
        epoch_wrapper._vectorized_epoch_wrapper = True
        setattr(cls, "process_epoch", epoch_wrapper)
