"""Deneb KZG polynomial-commitment library.

Behavioral parity with ``specs/deneb/polynomial-commitments.md`` (cited per
function).  This is the second crypto surface of the reference (the role
arkworks plays there, ``eth2spec/utils/bls.py:22-27``): commitments and
proofs over the 4096-element Lagrange trusted setup.

Performance design (same results, faster algorithms):
- ``g1_lincomb`` runs Pippenger windowed-bucket MSM on the pure-python
  oracle (``polynomial-commitments.md:268`` notes the optimization is
  allowed), and dispatches to the batched JAX MSM kernel
  (``ops/jax_bls/msm.py``) when the jax backend is selected.
- ``evaluate_polynomial_in_evaluation_form`` uses one Montgomery batch
  inversion instead of 4096 modular inverses.
"""
import json
import os
from functools import lru_cache
from typing import Sequence, Tuple

from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR, g1_from_compressed,
    g2_from_compressed)
from consensus_specs_tpu.ops.bls12_381.pairing import multi_pairing_check

# Constants (polynomial-commitments.md:70-100)
BLS_MODULUS = R_ORDER
BYTES_PER_FIELD_ELEMENT = 32
KZG_ENDIANNESS = "big"
PRIMITIVE_ROOT_OF_UNITY = 7
G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"


# ---------------------------------------------------------------------------
# Bit-reversal permutation (polynomial-commitments.md:105-144)
# ---------------------------------------------------------------------------

def is_power_of_two(value: int) -> bool:
    return value > 0 and (value & (value - 1)) == 0


def reverse_bits(n: int, order: int) -> int:
    assert is_power_of_two(order)
    return int(format(n, f"0{order.bit_length() - 1}b")[::-1], 2)


def bit_reversal_permutation(sequence):
    return [sequence[reverse_bits(i, len(sequence))]
            for i in range(len(sequence))]


# ---------------------------------------------------------------------------
# Field helpers (polynomial-commitments.md:146-305)
# ---------------------------------------------------------------------------

def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hash(data), KZG_ENDIANNESS) % BLS_MODULUS


def bytes_to_bls_field(b: bytes) -> int:
    """Rejects values >= the BLS modulus (md:160)."""
    field_element = int.from_bytes(b, KZG_ENDIANNESS)
    assert field_element < BLS_MODULUS
    return field_element


def bls_field_to_bytes(x: int) -> bytes:
    return int(x).to_bytes(BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS)


def bls_modular_inverse(x: int) -> int:
    assert x % BLS_MODULUS != 0
    return pow(x, -1, BLS_MODULUS)


def div(x: int, y: int) -> int:
    return x * bls_modular_inverse(y) % BLS_MODULUS


def compute_powers(x: int, n: int) -> list:
    current_power = 1
    powers = []
    for _ in range(n):
        powers.append(current_power)
        current_power = current_power * x % BLS_MODULUS
    return powers


@lru_cache(maxsize=8)
def compute_roots_of_unity(order: int) -> tuple:
    assert (BLS_MODULUS - 1) % order == 0
    root_of_unity = pow(PRIMITIVE_ROOT_OF_UNITY,
                        (BLS_MODULUS - 1) // order, BLS_MODULUS)
    return tuple(compute_powers(root_of_unity, order))


@lru_cache(maxsize=8)
def _roots_of_unity_brp(order: int) -> tuple:
    """Bit-reversed roots of unity, cached (the hot-path domain)."""
    return tuple(bit_reversal_permutation(list(compute_roots_of_unity(order))))


@lru_cache(maxsize=8)
def _roots_brp_index(order: int) -> dict:
    """root value -> brp index, for O(1) in-domain membership checks."""
    return {w: i for i, w in enumerate(_roots_of_unity_brp(order))}


def _batch_inverse(values) -> list:
    """Montgomery batch inversion: one pow, 3n mults (all values != 0)."""
    prefix = []
    acc = 1
    for v in values:
        prefix.append(acc)
        acc = acc * v % BLS_MODULUS
    inv = bls_modular_inverse(acc)
    out = [0] * len(values)
    for i in range(len(values) - 1, -1, -1):
        out[i] = prefix[i] * inv % BLS_MODULUS
        inv = inv * values[i] % BLS_MODULUS
    return out


# ---------------------------------------------------------------------------
# G1 helpers
# ---------------------------------------------------------------------------

class _BoundedCache(dict):
    """Decompression cache bounded so adversary-chosen one-off commitment
    and proof encodings cannot grow memory without limit; the fixed
    trusted-setup basis (8192 points) always fits."""

    MAX = 1 << 14

    def put(self, key, value):
        if len(self) >= self.MAX:
            self.clear()
        self[key] = value


_g1_cache = _BoundedCache()


def _to_g1(b48: bytes) -> G1Point:
    pt = _g1_cache.get(b48)
    if pt is None:
        pt = g1_from_compressed(b48)
        _g1_cache.put(b48, pt)
    return pt


def validate_kzg_g1(b: bytes) -> None:
    """md:174 — KeyValidate semantics except infinity is allowed."""
    if bytes(b) == G1_POINT_AT_INFINITY:
        return
    pt = g1_from_compressed(bytes(b))  # raises on non-canonical/off-curve
    assert not pt.infinity
    assert pt.in_subgroup()


def bytes_to_kzg_commitment(b: bytes) -> bytes:
    validate_kzg_g1(b)
    return bytes(b)


def bytes_to_kzg_proof(b: bytes) -> bytes:
    validate_kzg_g1(b)
    return bytes(b)


# Large MSMs go to the device kernel when the jax BLS backend is active;
# below this size host Pippenger beats the dispatch overhead.
_DEVICE_MSM_MIN = 256


def g1_lincomb(points: Sequence[bytes], scalars: Sequence[int],
               cache_key=None) -> bytes:
    """MSM (md:265).  Pippenger bucket method on the oracle; the JAX
    backend swaps in the digit-parallel device kernel (ops/jax_bls/msm.py).

    ``cache_key``: optional hashable identity for a fixed basis (the
    trusted setup) letting the device kernel reuse its window expansion.
    """
    assert len(points) == len(scalars)
    pts = [_to_g1(bytes(p)) for p in points]
    scalars = [int(s) % BLS_MODULUS for s in scalars]
    if len(points) >= _DEVICE_MSM_MIN:
        from consensus_specs_tpu.utils import bls as _bls
        if _bls.backend_name() == "jax":
            from consensus_specs_tpu.ops.jax_bls import msm as _msm
            return _msm.g1_msm(pts, scalars,
                               cache_key=cache_key).to_compressed()
    from consensus_specs_tpu.ops import native_bls
    if native_bls.available():
        return native_bls.g1_msm_affine(
            [(0, 0) if p.infinity else (p.x.n, p.y.n) for p in pts], scalars)
    return _pippenger_msm(pts, scalars).to_compressed()


def _pippenger_msm(pts, scalars, window: int = 8) -> G1Point:
    """Windowed bucket accumulation, MSB window first."""
    if not pts:
        return G1Point.inf()
    n_windows = (255 + window - 1) // window
    result = G1Point.inf()
    mask = (1 << window) - 1
    for w in range(n_windows - 1, -1, -1):
        if not result.infinity:
            for _ in range(window):
                result = result.double()
        buckets = [None] * (mask + 1)
        for pt, s in zip(pts, scalars):
            digit = (s >> (w * window)) & mask
            if digit == 0 or pt.infinity:
                continue
            buckets[digit] = pt if buckets[digit] is None else buckets[digit] + pt
        running = G1Point.inf()
        window_sum = G1Point.inf()
        for digit in range(mask, 0, -1):
            if buckets[digit] is not None:
                running = running + buckets[digit]
            window_sum = window_sum + running
        result = result + window_sum
    return result


# ---------------------------------------------------------------------------
# Trusted setup (reference: setup.py:112-143 injects these constants from
# presets/<preset>/trusted_setups/trusted_setup_4096.json)
# ---------------------------------------------------------------------------

class TrustedSetup:
    def __init__(self, preset_name: str):
        # anchored on the package root, not this module's __file__: the
        # markdown-compiled copy of this class lives under forks/compiled/
        import consensus_specs_tpu as _pkg
        path = os.path.join(os.path.dirname(
            os.path.abspath(_pkg.__file__)), "presets", preset_name,
            "trusted_setup_4096.json")
        with open(path) as f:
            data = json.load(f)
        self.KZG_SETUP_G1_MONOMIAL = [
            bytes.fromhex(p[2:]) for p in data["g1_monomial"]]
        self.KZG_SETUP_G1_LAGRANGE = [
            bytes.fromhex(p[2:]) for p in data["g1_lagrange"]]
        self.KZG_SETUP_G2_MONOMIAL = [
            bytes.fromhex(p[2:]) for p in data["g2_monomial"]]
        self.FIELD_ELEMENTS_PER_BLOB = len(self.KZG_SETUP_G1_LAGRANGE)
        # hot path: the bit-reversed Lagrange basis (md:347)
        self.g1_lagrange_brp = bit_reversal_permutation(
            self.KZG_SETUP_G1_LAGRANGE)
        self._g2_tau = None

    @property
    def g2_tau(self):
        """[tau]G2 = KZG_SETUP_G2_MONOMIAL[1], decompressed lazily."""
        if self._g2_tau is None:
            self._g2_tau = g2_from_compressed(self.KZG_SETUP_G2_MONOMIAL[1])
        return self._g2_tau


@lru_cache(maxsize=4)
def trusted_setup(preset_name: str) -> TrustedSetup:
    return TrustedSetup(preset_name)


# ---------------------------------------------------------------------------
# Blob <-> polynomial
# ---------------------------------------------------------------------------

def blob_to_polynomial(blob: bytes, width: int) -> list:
    """md:209"""
    assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
    return [bytes_to_bls_field(
        blob[i * BYTES_PER_FIELD_ELEMENT:(i + 1) * BYTES_PER_FIELD_ELEMENT])
        for i in range(width)]


def compute_challenge(blob: bytes, commitment: bytes, width: int) -> int:
    """md:223 — Fiat-Shamir over domain | degree | blob | commitment."""
    degree_poly = int.to_bytes(width, 16, KZG_ENDIANNESS)
    data = FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + bytes(blob) \
        + bytes(commitment)
    return hash_to_bls_field(data)


def evaluate_polynomial_in_evaluation_form(polynomial, z: int,
                                           width: int) -> int:
    """Barycentric evaluation (md:308); batch-inverted denominators."""
    assert len(polynomial) == width
    inverse_width = bls_modular_inverse(width)
    roots_brp = _roots_of_unity_brp(width)
    z = int(z) % BLS_MODULUS
    in_domain = _roots_brp_index(width).get(z)
    if in_domain is not None:
        return int(polynomial[in_domain])
    denoms = [(z - w) % BLS_MODULUS for w in roots_brp]
    inv_denoms = _batch_inverse(denoms)
    result = 0
    for p, w, inv_d in zip(polynomial, roots_brp, inv_denoms):
        result += int(p) * w % BLS_MODULUS * inv_d
    result = (result % BLS_MODULUS) * (pow(z, width, BLS_MODULUS) - 1) \
        * inverse_width
    return result % BLS_MODULUS


# ---------------------------------------------------------------------------
# KZG core (md:340-640); ``setup`` = TrustedSetup for the active preset
# ---------------------------------------------------------------------------

def blob_to_kzg_commitment(blob: bytes, setup: TrustedSetup) -> bytes:
    """md:344"""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
    return g1_lincomb(setup.g1_lagrange_brp, blob_to_polynomial(blob, width),
                      cache_key=("lagrange-brp", id(setup)))


def verify_kzg_proof(commitment_bytes: bytes, z_bytes: bytes, y_bytes: bytes,
                     proof_bytes: bytes, setup: TrustedSetup) -> bool:
    """md:355"""
    assert len(commitment_bytes) == 48
    assert len(z_bytes) == BYTES_PER_FIELD_ELEMENT
    assert len(y_bytes) == BYTES_PER_FIELD_ELEMENT
    assert len(proof_bytes) == 48
    return verify_kzg_proof_impl(bytes_to_kzg_commitment(commitment_bytes),
                                 bytes_to_bls_field(z_bytes),
                                 bytes_to_bls_field(y_bytes),
                                 bytes_to_kzg_proof(proof_bytes), setup)


def _g1_of(b48: bytes) -> G1Point:
    if bytes(b48) == G1_POINT_AT_INFINITY:
        return G1Point.inf()
    return _to_g1(bytes(b48))


def _native():
    from consensus_specs_tpu.ops import native_bls
    return native_bls if native_bls.available() else None


def _pairing_check(pairs) -> bool:
    """multi_pairing_check, through the native C pairing when present
    (the arkworks multi_pairing role; oracle fallback otherwise)."""
    nb = _native()
    if nb is not None:
        return nb.pairing_check_compressed(
            [p.to_compressed() for p, _ in pairs],
            [q.to_compressed() for _, q in pairs])
    return multi_pairing_check(pairs)


def _g1_combine(point_scalar_pairs) -> G1Point:
    """sum([k]P) over a few points — native when present."""
    nb = _native()
    if nb is not None:
        out = nb.g1_msm_affine(
            [(0, 0) if p.infinity else (p.x.n, p.y.n)
             for p, _ in point_scalar_pairs],
            [int(k) for _, k in point_scalar_pairs])
        return _g1_of(out)
    acc = G1Point.inf()
    for p, k in point_scalar_pairs:
        acc = acc + p.mult(int(k))
    return acc


def _g2_combine(point_scalar_pairs) -> G2Point:
    """sum([k]Q) over a few G2 points — native when present."""
    nb = _native()
    if nb is not None:
        out = nb.g2_msm_compressed(
            [q.to_compressed() for q, _ in point_scalar_pairs],
            [int(k) for _, k in point_scalar_pairs])
        return g2_from_compressed(out)
    acc = G2Point.inf()
    for q, k in point_scalar_pairs:
        acc = acc + q.mult(int(k))
    return acc


def verify_kzg_proof_impl(commitment: bytes, z: int, y: int, proof: bytes,
                          setup: TrustedSetup) -> bool:
    """md:379 — e(P - y, -G2) * e(proof, [tau - z]G2) == 1."""
    X_minus_z = _g2_combine([(setup.g2_tau, 1),
                             (G2_GENERATOR, (BLS_MODULUS - z) % BLS_MODULUS)])
    P_minus_y = _g1_combine([(_g1_of(commitment), 1),
                             (G1_GENERATOR, (BLS_MODULUS - y) % BLS_MODULUS)])
    return _pairing_check([
        (P_minus_y, -G2_GENERATOR),
        (_g1_of(proof), X_minus_z),
    ])


def verify_kzg_proof_batch(commitments, zs, ys, proofs,
                           setup: TrustedSetup) -> bool:
    """md:404 — random linear combination -> 2 MSMs + 1 pairing check."""
    assert len(commitments) == len(zs) == len(ys) == len(proofs)
    width = setup.FIELD_ELEMENTS_PER_BLOB

    degree_poly = int.to_bytes(width, 8, KZG_ENDIANNESS)
    num_commitments = int.to_bytes(len(commitments), 8, KZG_ENDIANNESS)
    data = RANDOM_CHALLENGE_KZG_BATCH_DOMAIN + degree_poly + num_commitments
    for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
        data += bytes(commitment) + bls_field_to_bytes(z) \
            + bls_field_to_bytes(y) + bytes(proof)
    r = hash_to_bls_field(data)
    r_powers = compute_powers(r, len(commitments))

    proof_lincomb = g1_lincomb(proofs, r_powers)
    proof_z_lincomb = g1_lincomb(
        proofs, [int(z) * r_power % BLS_MODULUS
                 for z, r_power in zip(zs, r_powers)])
    C_minus_ys = [
        _g1_combine([(_g1_of(commitment), 1),
                     (G1_GENERATOR, (BLS_MODULUS - int(y)) % BLS_MODULUS)])
        .to_compressed()
        for commitment, y in zip(commitments, ys)]
    C_minus_y_lincomb = g1_lincomb(C_minus_ys, r_powers)

    pairs = [
        (_g1_of(proof_lincomb), -setup.g2_tau),
        (_g1_of(C_minus_y_lincomb) + _g1_of(proof_z_lincomb), G2_GENERATOR),
    ]
    # Inside an assert-style batched_verification scope (deneb on_block:
    # data availability + state transition share one flush) the final
    # pairing folds into the block's single RLC pairing check instead of
    # paying its own final exponentiation (utils/bls.py batch contract:
    # any check deferred under a scope is assert-style).
    from consensus_specs_tpu.utils import bls as _bls
    if _bls.defer_pairing_check(pairs, label="kzg_batch"):
        return True
    return _pairing_check(pairs)


def compute_kzg_proof(blob: bytes, z_bytes: bytes,
                      setup: TrustedSetup) -> Tuple[bytes, bytes]:
    """md:448"""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
    assert len(z_bytes) == BYTES_PER_FIELD_ELEMENT
    polynomial = blob_to_polynomial(blob, width)
    proof, y = compute_kzg_proof_impl(polynomial, bytes_to_bls_field(z_bytes),
                                      setup)
    return proof, bls_field_to_bytes(y)


def compute_quotient_eval_within_domain(z: int, polynomial, y: int,
                                        setup: TrustedSetup) -> int:
    """md:464 — q(x_m) when z is a root of unity."""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    roots_brp = _roots_of_unity_brp(width)
    result = 0
    for i, omega_i in enumerate(roots_brp):
        if omega_i == z:
            continue
        f_i = (BLS_MODULUS + int(polynomial[i]) - int(y)) % BLS_MODULUS
        numerator = f_i * omega_i % BLS_MODULUS
        denominator = z * ((BLS_MODULUS + z - omega_i) % BLS_MODULUS) \
            % BLS_MODULUS
        result += div(numerator, denominator)
    return result % BLS_MODULUS


def compute_kzg_proof_impl(polynomial, z: int,
                           setup: TrustedSetup) -> Tuple[bytes, int]:
    """md:492 — quotient polynomial in evaluation form."""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    roots_brp = _roots_of_unity_brp(width)

    y = evaluate_polynomial_in_evaluation_form(polynomial, z, width)
    polynomial_shifted = [(int(p) - y) % BLS_MODULUS for p in polynomial]
    denominator_poly = [(x - z) % BLS_MODULUS for x in roots_brp]

    quotient_polynomial = [0] * width
    # batch-invert the non-zero denominators (behavioral parity with md:510)
    nz = [i for i, d in enumerate(denominator_poly) if d != 0]
    inv_map = dict(zip(nz, _batch_inverse([denominator_poly[i] for i in nz])))
    for i, (a, b) in enumerate(zip(polynomial_shifted, denominator_poly)):
        if b == 0:
            quotient_polynomial[i] = compute_quotient_eval_within_domain(
                roots_brp[i], polynomial, y, setup)
        else:
            quotient_polynomial[i] = a * inv_map[i] % BLS_MODULUS

    return g1_lincomb(setup.g1_lagrange_brp, quotient_polynomial,
                      cache_key=("lagrange-brp", id(setup))), y


def compute_blob_kzg_proof(blob: bytes, commitment_bytes: bytes,
                           setup: TrustedSetup) -> bytes:
    """md:522"""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
    assert len(commitment_bytes) == 48
    commitment = bytes_to_kzg_commitment(commitment_bytes)
    polynomial = blob_to_polynomial(blob, width)
    evaluation_challenge = compute_challenge(blob, commitment, width)
    proof, _ = compute_kzg_proof_impl(polynomial, evaluation_challenge, setup)
    return proof


def verify_blob_kzg_proof(blob: bytes, commitment_bytes: bytes,
                          proof_bytes: bytes, setup: TrustedSetup) -> bool:
    """md:543"""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
    assert len(commitment_bytes) == 48
    assert len(proof_bytes) == 48
    commitment = bytes_to_kzg_commitment(commitment_bytes)
    polynomial = blob_to_polynomial(blob, width)
    evaluation_challenge = compute_challenge(blob, commitment, width)
    y = evaluate_polynomial_in_evaluation_form(
        polynomial, evaluation_challenge, width)
    proof = bytes_to_kzg_proof(proof_bytes)
    return verify_kzg_proof_impl(commitment, evaluation_challenge, y, proof,
                                 setup)


def verify_blob_kzg_proof_batch(blobs, commitments_bytes, proofs_bytes,
                                setup: TrustedSetup) -> bool:
    """md:571"""
    assert len(blobs) == len(commitments_bytes) == len(proofs_bytes)
    width = setup.FIELD_ELEMENTS_PER_BLOB
    commitments, evaluation_challenges, ys, proofs = [], [], [], []
    for blob, commitment_bytes, proof_bytes in zip(
            blobs, commitments_bytes, proofs_bytes):
        assert len(blob) == BYTES_PER_FIELD_ELEMENT * width
        assert len(commitment_bytes) == 48
        assert len(proof_bytes) == 48
        commitment = bytes_to_kzg_commitment(commitment_bytes)
        commitments.append(commitment)
        polynomial = blob_to_polynomial(blob, width)
        evaluation_challenge = compute_challenge(blob, commitment, width)
        evaluation_challenges.append(evaluation_challenge)
        ys.append(evaluate_polynomial_in_evaluation_form(
            polynomial, evaluation_challenge, width))
        proofs.append(bytes_to_kzg_proof(proof_bytes))
    return verify_kzg_proof_batch(commitments, evaluation_challenges, ys,
                                  proofs, setup)
