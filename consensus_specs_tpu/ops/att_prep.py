"""Vmapped ``process_attestation`` message preparation: one columnar
pass computes every attestation signing root of a block.

The per-attestation python cost of block verification is not the
pairing (the RLC flush already folds a whole block into one — see
``docs/bls-batching.md``) but the message preparation feeding it:
``is_valid_indexed_attestation`` merkleizes one ``AttestationData``
(two checkpoint subtrees + an 8-chunk container) and one
``SigningData`` per attestation, object by object.  This module batches
all of it: for the N attestations of a block it computes

* both checkpoint roots per attestation      — one ``(2N, 64)`` batch,
* all ``AttestationData`` roots              — three level reductions
  over an ``(N, 8, 32)`` chunk cube,
* all signing roots (``H(data_root‖domain)``) — one ``(N, 64)`` batch,

five batched hash dispatches total, and installs the results where the
spec bodies will find them: the exact container roots are poked into
the SSZ root memos (value-semantics copies inherit them, so the
``PendingAttestation`` path is also warm), and the signing roots go
into a per-block lookup consulted by an externally-installed
``is_valid_indexed_attestation`` wrapper (``install_att_prep`` — same
outside-in pattern as the epoch / fork-choice engine installs, so spec
method bodies stay spec-shaped and the markdown-compiled ladder gets
the identical treatment).  Every prepared verification then feeds the
existing deferred-batch RLC flush unchanged.

The lookup key includes the fork version in force at the attestation's
target epoch and the genesis validators root, so a hit can never hand
back a signing root computed for a different chain or fork boundary; any
miss (attester slashings, cross-state fork-choice validation after a
fork transition) falls through to the spec body.
"""
import functools

import numpy as np

from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import merkle

_C_BLOCKS = obs_registry.counter("att_prep.blocks").labels()
_C_PREPARED = obs_registry.counter("att_prep.prepared").labels()
_C_HITS = obs_registry.counter("att_prep.hits").labels()
_C_MISSES = obs_registry.counter("att_prep.misses").labels()

# one prepare's worth of {key: signing root bytes} — a single block's
# attestations, or a whole serving window's (several in-flight blocks +
# the loose attestation stream); replaced wholesale by the next prepare
# call (bounded by MAX_ATTESTATIONS x window)
_table = {}
# identities of the attestation lists the table was built from: fork
# overrides chain process_operations through super(), so the inner
# (wrapped) call would otherwise re-prepare the same block, and a
# window prepare covers every block body it batched — the per-block
# wrapper calls inside that window skip straight to the lookups.  The
# list holds STRONG references: ``is`` identity is only meaningful
# while the prepared lists stay alive.
_prepared_srcs = []


# the exact AttestationData layout the chunk cube is built for (the
# legacy sharding lineage appends shard_transition_root — see the
# layout gate in prepare_block_attestations)
_PHASE0_DATA_FIELDS = ("slot", "index", "beacon_block_root",
                       "source", "target")


def _fork_version(state, epoch):
    return (state.fork.previous_version if epoch < state.fork.epoch
            else state.fork.current_version)


def _key(state, data):
    e = int(data.target.epoch)
    return (int(data.slot), int(data.index), bytes(data.beacon_block_root),
            int(data.source.epoch), bytes(data.source.root),
            e, bytes(data.target.root),
            bytes(_fork_version(state, e)),
            bytes(state.genesis_validators_root))


def _prepare(spec, state, datas):
    """The five batched hash dispatches over ``datas`` (any number of
    blocks' worth, concatenated): poke the container-root memos and
    return the {key: signing root} table — or None when the layout gate
    trips (the legacy sharding lineage appends shard_transition_root;
    the 5-field chunk cube below would compute, and memo-poke, wrong
    container roots for that layout)."""
    n = len(datas)
    if n == 0:
        return {}
    if tuple(type(datas[0])._fields) != _PHASE0_DATA_FIELDS:
        return None

    # checkpoint roots: rows [0:n] = sources, [n:2n] = targets
    ck = np.zeros((2 * n, 64), dtype=np.uint8)
    se = np.fromiter((int(d.source.epoch) for d in datas),
                     dtype="<u8", count=n)
    te = np.fromiter((int(d.target.epoch) for d in datas),
                     dtype="<u8", count=n)
    ck[:n, :8] = se.view(np.uint8).reshape(n, 8)
    ck[n:, :8] = te.view(np.uint8).reshape(n, 8)
    ck[:n, 32:] = np.frombuffer(
        b"".join(bytes(d.source.root) for d in datas),
        dtype=np.uint8).reshape(n, 32)
    ck[n:, 32:] = np.frombuffer(
        b"".join(bytes(d.target.root) for d in datas),
        dtype=np.uint8).reshape(n, 32)
    ckr = merkle.hash_rows(ck)

    # AttestationData roots: (slot, index, beacon_block_root, source,
    # target) padded to 8 chunks, reduced level-synchronously
    cube = np.zeros((n, 8, 32), dtype=np.uint8)
    slots = np.fromiter((int(d.slot) for d in datas), dtype="<u8", count=n)
    idxs = np.fromiter((int(d.index) for d in datas), dtype="<u8", count=n)
    cube[:, 0, :8] = slots.view(np.uint8).reshape(n, 8)
    cube[:, 1, :8] = idxs.view(np.uint8).reshape(n, 8)
    cube[:, 2, :] = np.frombuffer(
        b"".join(bytes(d.beacon_block_root) for d in datas),
        dtype=np.uint8).reshape(n, 32)
    cube[:, 3, :] = ckr[:n]
    cube[:, 4, :] = ckr[n:]
    lvl = cube
    while lvl.shape[1] > 1:
        half = lvl.shape[1] // 2
        lvl = merkle.hash_rows(lvl.reshape(n * half, 64)) \
            .reshape(n, half, 32)
    data_roots = lvl.reshape(n, 32)

    # domains (one get_domain per distinct target epoch) + signing roots
    domains = {}
    for e in {int(d.target.epoch) for d in datas}:
        domains[e] = bytes(spec.get_domain(
            state, spec.DOMAIN_BEACON_ATTESTER, e))
    sd = np.zeros((n, 64), dtype=np.uint8)
    sd[:, :32] = data_roots
    sd[:, 32:] = np.frombuffer(
        b"".join(domains[int(d.target.epoch)] for d in datas),
        dtype=np.uint8).reshape(n, 32)
    signing = merkle.hash_rows(sd)

    table = {}
    for i, d in enumerate(datas):
        # poke the exact roots into the SSZ memos: every later
        # hash_tree_root on these containers (or their value-semantics
        # copies — get_indexed_attestation, PendingAttestation) hits
        object.__setattr__(d, "_root_cache", data_roots[i].tobytes())
        object.__setattr__(d.source, "_root_cache", ckr[i].tobytes())
        object.__setattr__(d.target, "_root_cache", ckr[n + i].tobytes())
        table[_key(state, d)] = signing[i].tobytes()
    return table


def prepare_block_attestations(spec, state, attestations) -> None:
    """Batch-compute checkpoint/data/signing roots for every
    attestation in the block body, poke the container-root memos, and
    (re)fill the signing-root lookup.  Idempotent per list identity
    (nested ``super().process_operations`` chains prepare once, and a
    window prepare covers its block bodies); a stale skip can only
    cause lookup misses, never wrong hits — the lookup key re-derives
    the fork/genesis identity from the querying state."""
    global _table, _prepared_srcs
    for src in _prepared_srcs:
        if src is attestations:
            return
    _prepared_srcs = [attestations]
    table = _prepare(spec, state, [a.data for a in attestations])
    _table = table or {}
    if table:
        _C_BLOCKS.add()
        _C_PREPARED.add(len(attestations))


def prepare_window_attestations(spec, state, groups) -> None:
    """Cross-block batching entry (the serving pipeline): prepare the
    attestation messages of every in-flight block body — plus any loose
    attestation stream — in ONE set of batched dispatches instead of
    one per block.  ``groups`` is a list of attestation lists; block
    bodies passed here are remembered by identity so the per-block
    ``process_operations`` wrapper calls inside the window skip their
    own prepare.  ``state`` only feeds the fork-version/genesis lookup
    identity and the per-epoch domains, so any state of the same chain
    serves; across a fork boundary the keys simply miss into the spec
    body (never a wrong hit)."""
    global _table, _prepared_srcs
    groups = [g for g in groups if len(g) > 0]
    if not groups:
        return
    datas = [a.data for g in groups for a in g]
    table = _prepare(spec, state, datas)
    if table is None:
        return
    _prepared_srcs = list(groups)
    _table = table
    _C_BLOCKS.add(len(groups))
    _C_PREPARED.add(len(datas))


def lookup_signing_root(state, data):
    """The signing root prepared for this attestation data under this
    state's fork/genesis identity, or None."""
    hit = _table.get(_key(state, data))
    if hit is not None:
        _C_HITS.add()
    else:
        _C_MISSES.add()
    return hit


def install_att_prep(cls) -> None:
    """Wrap ``cls``'s own ``process_operations`` (prepare the block's
    attestation messages in one columnar pass before the ops loops) and
    ``is_valid_indexed_attestation`` (serve the prepared signing root;
    fall through to the spec body on any miss).  Only methods defined
    on ``cls`` itself are wrapped; wrapping is idempotent.  Applied to
    the hand-written ladder by ``forks.register_fork`` and to each
    markdown-compiled class by ``forks.use_compiled_registry``."""
    fn = cls.__dict__.get("process_operations")
    if fn is not None and not getattr(fn, "_att_prep_wrapper", False):
        @functools.wraps(fn)
        def process_operations(self, state, body, _orig=fn):
            prepare_block_attestations(self, state, body.attestations)
            return _orig(self, state, body)
        process_operations._att_prep_wrapper = True
        setattr(cls, "process_operations", process_operations)

    fn = cls.__dict__.get("is_valid_indexed_attestation")
    if fn is not None and not getattr(fn, "_att_prep_wrapper", False):
        @functools.wraps(fn)
        def is_valid_indexed_attestation(self, state, indexed_attestation,
                                         _orig=fn):
            signing_root = lookup_signing_root(
                state, indexed_attestation.data)
            if signing_root is None:
                return _orig(self, state, indexed_attestation)
            # the spec body with the two merkleizations pre-resolved;
            # index checks stay bit-for-bit (beacon-chain.md:739)
            indices = list(indexed_attestation.attesting_indices)
            if len(indices) == 0 or not indices == sorted(set(indices)):
                return False
            pubkeys = [state.validators[i].pubkey for i in indices]
            return bls.FastAggregateVerify(
                pubkeys, signing_root, indexed_attestation.signature)
        is_valid_indexed_attestation._att_prep_wrapper = True
        setattr(cls, "is_valid_indexed_attestation",
                is_valid_indexed_attestation)
