"""Random-linear-combination (RLC) whole-batch BLS verification.

The one-pairing-per-block engine behind ``utils/bls.DeferredBatch.flush``
(``CS_TPU_BLS_RLC``, default on).  Each queued assert-style check i says

    e(agg_pk_i, H(m_i)) * e(-G1, sig_i) == 1.

Draw independent nonzero 128-bit scalars r_i and verify the single folded
product instead::

    prod_i e([r_i] agg_pk_i, H(m_i)) * e(-G1, sum_i [r_i] sig_i)
          * prod_k prod_j e([r_k] P_kj, Q_kj)  == 1

(the trailing factor folds deferred *raw* pairing-product checks such as
the Deneb blob-KZG batch, each with its own scalar r_k).  By bilinearity
a batch of all-valid items always passes; a batch containing any invalid
item passes with probability <= 2^-128 over the scalar draw (the checks'
pairing values generate a cyclic group of order r, and a nontrivial
combination must hit the identity).  The work collapses to one G2 MSM
over the signatures, one batched G1 aggregate+scale over the pubkeys,
hash-to-curve, and ONE product pairing check (one final exponentiation)
- versus one full pairing check per item on the per-lane path.

Scalars are seeded deterministically (Fiat-Shamir style) from a SHA-256
hash of the queued tuples, so scripted runs and replays are bit-for-bit
reproducible; fixing the batch fixes the scalars, but any *change* to a
queued item re-randomizes every coefficient, so an adversary cannot
steer a forged batch toward a passing combination.

Failure semantics live in the caller: a ``False`` combined verdict (or a
``None`` = structurally invalid item: bad encoding, out-of-subgroup
point, infinity pubkey, empty pubkey list) makes ``flush`` re-run the
per-lane path to bisect and report exactly which item failed.

Backends: the python oracle, the native C library (streaming product
pairing in ``csrc/bls12_381.c``), and the JAX device path
(``ops/bls_jax.rlc_combined_check``), which lowers the signature MSM
onto the points-sharded mesh program of
``parallel/sharded_verify.make_sharded_g2_msm`` when a mesh has been
registered via :func:`use_mesh`.
"""
import hashlib

from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, g1_from_compressed, msm)
from consensus_specs_tpu.ops.bls12_381 import ciphersuite as _oracle
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.ops.bls12_381.hash_to_curve import hash_to_g2, DST_G2
from consensus_specs_tpu.ops.bls12_381.pairing import multi_pairing_check
from consensus_specs_tpu import supervisor
from consensus_specs_tpu.utils.profiling import span

SCALAR_BITS = 128
_DOMAIN = b"CS_TPU_BLS_RLC_V1"
_NEG_G1 = None      # lazy: -G1_GENERATOR and its compressed form
_NEG_G1_C = None


def _neg_g1():
    global _NEG_G1, _NEG_G1_C
    if _NEG_G1 is None:
        from consensus_specs_tpu.ops.bls12_381.curve import G1_GENERATOR
        _NEG_G1 = -G1_GENERATOR
        _NEG_G1_C = _NEG_G1.to_compressed()
    return _NEG_G1, _NEG_G1_C


# ---------------------------------------------------------------------------
# Deterministic scalar derivation
# ---------------------------------------------------------------------------

def _u64(n: int) -> bytes:
    return int(n).to_bytes(8, "little")


def derive_scalars(items, extra_checks=()) -> list:
    """Per-check 128-bit nonzero coefficients, seeded from a hash of the
    whole queue: ``len(items) + len(extra_checks)`` scalars, items first.

    Deterministic by design (reproducible replays); every byte of every
    queued tuple feeds the seed, so no queued value can be chosen as a
    function of its own coefficient.
    """
    h = hashlib.sha256(_DOMAIN)
    h.update(_u64(len(items)))
    for pubkeys, message, signature in items:
        h.update(_u64(len(pubkeys)))
        for pk in pubkeys:
            h.update(bytes(pk))
        h.update(_u64(len(message)))
        h.update(bytes(message))
        h.update(bytes(signature))
    h.update(_u64(len(extra_checks)))
    for pairs, label in extra_checks:
        lb = label.encode() if isinstance(label, str) else bytes(label)
        h.update(_u64(len(lb)))
        h.update(lb)
        h.update(_u64(len(pairs)))
        for p, q in pairs:
            h.update(p.to_compressed())
            h.update(q.to_compressed())
    seed = h.digest()
    out = []
    for i in range(len(items) + len(extra_checks)):
        r = int.from_bytes(
            hashlib.sha256(seed + _u64(i)).digest()[:SCALAR_BITS // 8],
            "little")
        out.append(r if r else 1)
    return out


# ---------------------------------------------------------------------------
# Optional device mesh for the signature MSM (jax backend only)
# ---------------------------------------------------------------------------

_MESH_DEVICES = None


def use_mesh(devices) -> None:
    """Register a 1D device tuple: the jax-path signature MSM shards its
    point axis across it (``parallel.sharded_verify`` — any batch size,
    uneven shards padded with identity lanes).  Pass ``"auto"`` to
    derive the mesh shape from ``jax.devices()`` live at every flush
    (the serving-deployment mode: nothing hardcodes a device count);
    pass ``None`` to go back to the single-device program."""
    global _MESH_DEVICES
    if devices == "auto":
        _MESH_DEVICES = "auto"
    else:
        _MESH_DEVICES = tuple(devices) if devices else None


def mesh_devices():
    if _MESH_DEVICES == "auto":
        import jax
        devs = tuple(jax.devices())
        return devs if len(devs) > 1 else None
    return _MESH_DEVICES


# ---------------------------------------------------------------------------
# Backend combiners.  Each returns True/False for the folded product, or
# None when an item is structurally invalid (caller bisects).
# ---------------------------------------------------------------------------

def _scale_g1_host(p: G1Point, r: int) -> G1Point:
    """[r]P for a handful of host-side oracle points (the deferred raw
    pairs), through the native library when present."""
    if p.infinity or r % R_ORDER == 0:
        return G1Point.inf()
    try:
        from consensus_specs_tpu.ops import native_bls
        if native_bls.available():
            return g1_from_compressed(
                native_bls.g1_msm_affine([(p.x.n, p.y.n)], [r]))
    except Exception:
        pass
    return p.mult(r)


def _check_py(items, extra_checks, scalars):
    n = len(items)
    pairs = []
    sig_pts, sig_rs = [], []
    for (pubkeys, message, signature), r in zip(items, scalars):
        if not pubkeys:
            return None
        agg = G1Point.inf()
        for pk in pubkeys:
            pt = _oracle._decode_pubkey(bytes(pk))
            if pt is None:
                return None
            agg = agg + pt
        try:
            spt = _oracle._decode_sig(bytes(signature))
        except Exception:
            return None
        pairs.append((agg.mult(r), hash_to_g2(bytes(message))))
        sig_pts.append(spt)
        sig_rs.append(r)
    if sig_pts:
        pairs.append((_neg_g1()[0], msm(sig_pts, sig_rs)))
    for (chk_pairs, _label), r in zip(extra_checks, scalars[n:]):
        for p, q in chk_pairs:
            pairs.append((_scale_g1_host(p, r), q))
    if not pairs:
        return True
    return multi_pairing_check(pairs)


def _check_native(items, extra_checks, scalars):
    from consensus_specs_tpu.ops import native_bls as nb
    n = len(items)
    g1s, g2s = [], []
    sig_bytes, sig_rs = [], []
    try:
        for (pubkeys, message, signature), r in zip(items, scalars):
            if not pubkeys:
                return None
            signature = bytes(signature)
            if not nb.g2_validate(signature):
                return None
            # AggregatePKs KeyValidates every pubkey (raises on invalid)
            agg = nb.AggregatePKs([bytes(pk) for pk in pubkeys])
            g1s.append(nb.g1_msm_compressed([agg], [r]))
            g2s.append(nb.hash_to_g2_compressed(bytes(message), DST_G2))
            sig_bytes.append(signature)
            sig_rs.append(r)
    except ValueError:
        return None
    if sig_bytes:
        g1s.append(_neg_g1()[1])
        g2s.append(nb.g2_msm_compressed(sig_bytes, sig_rs))
    for (chk_pairs, _label), r in zip(extra_checks, scalars[n:]):
        for p, q in chk_pairs:
            g1s.append(_scale_g1_host(p, r).to_compressed())
            g2s.append(q.to_compressed())
    if not g1s:
        return True
    return nb.pairing_check_compressed(g1s, g2s)


def _check_jax(items, extra_checks, scalars):
    from consensus_specs_tpu.ops import bls_jax
    n = len(items)
    pk_rows, msgs, sig_pts = [], [], []
    for pubkeys, message, signature in items:
        if not pubkeys:
            return None
        rows = [bls_jax._packed_g1(pk) for pk in pubkeys]
        if any(r is None for r in rows):
            return None
        spt = bls_jax._decompress_g2(signature)
        if spt is None:
            return None
        pk_rows.append(rows)
        msgs.append(bytes(message))
        sig_pts.append(spt)
    extra_pairs = []
    for (chk_pairs, _label), r in zip(extra_checks, scalars[n:]):
        for p, q in chk_pairs:
            extra_pairs.append((_scale_g1_host(p, r), q))
    if not pk_rows and not extra_pairs:
        return True
    return bls_jax.rlc_combined_check(
        pk_rows, msgs, sig_pts, scalars[:n], extra_pairs=extra_pairs,
        mesh_devices=mesh_devices())


_COMBINERS = {"py": _check_py, "native": _check_native, "jax": _check_jax}


def combined_check(items, extra_checks, backend_name: str):
    """Fold the whole queue into one product pairing and evaluate it.

    ``items``: [(pubkeys, message, signature)] byte triples;
    ``extra_checks``: [(pairs, label)] deferred raw pairing-product
    checks over oracle points.  Returns the combined verdict, or None
    when any item is structurally invalid - the caller then re-runs the
    per-lane path to report per-item results.
    """
    with span("bls.rlc.combine"):
        scalars = derive_scalars(items, extra_checks)
        # cooperative deadline boundary between the (cheap) Fiat-Shamir
        # scalar stage and the MSM + pairing stage: an armed per-dispatch
        # budget (supervisor.deadline_scope in DeferredBatch.flush)
        # converts a pathologically slow flush into a counted
        # reason=deadline fallback onto the per-lane path
        supervisor.deadline_check()
        combine = _COMBINERS.get(backend_name, _check_py)
        return combine(items, extra_checks, scalars)
