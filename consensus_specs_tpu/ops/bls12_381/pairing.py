"""Optimal ate pairing on BLS12-381.

e(P, Q) = f_{|x|,Q}(P)^((p¹²−1)/r) with a conjugation correcting for the
negative BLS parameter x. Line evaluations embed G2 (on the M-twist) into
Fq12 via the untwist (x/w², y/w³), i.e. a line a + b·w + c·w³ form; here we
simply lift both points into E(Fq12) coordinates and use generic line
functions — clarity over speed, this is the oracle.
"""
from .fields import P, R_ORDER, X_PARAM, Fq2, Fq6, Fq12
from .curve import G1Point, G2Point


def _fq12_from_fq(a) -> Fq12:
    return Fq12(Fq6(Fq2(a.n, 0), Fq2.zero(), Fq2.zero()), Fq6.zero())


def _fq12_from_fq2(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.zero(), Fq2.zero()), Fq6.zero())


# w and its powers as Fq12 elements (w² = v, v³ = ξ)
W = Fq12(Fq6.zero(), Fq6.one())
W2 = W * W
W3 = W2 * W


def _untwist(q: G2Point):
    """Map a twist point (x,y) ∈ E2(Fq2) to E(Fq12): (x/w², y/w³)."""
    x = _fq12_from_fq2(q.x) * W2.inv()
    y = _fq12_from_fq2(q.y) * W3.inv()
    return x, y


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """f_{|x|, Q}(P), conjugated for x < 0 (before final exponentiation)."""
    if p.infinity or q.infinity:
        return Fq12.one()
    qx, qy = _untwist(q)
    px = _fq12_from_fq(p.x)
    py = _fq12_from_fq(p.y)

    three = _fq12_from_fq2(Fq2(3, 0))

    rx, ry = qx, qy
    f = Fq12.one()
    t = -X_PARAM  # positive loop count
    for bit in bin(t)[3:]:  # skip the leading 1
        # doubling step: tangent line at R evaluated at P
        slope = (three * rx * rx) * (ry + ry).inv()
        line = slope * (px - rx) - (py - ry)
        f = f * f * line
        new_rx = slope * slope - rx - rx
        new_ry = slope * (rx - new_rx) - ry
        rx, ry = new_rx, new_ry
        if bit == "1":
            # addition step: chord through R and Q evaluated at P.
            # R = [j]Q with 1 < j < |x| < r, so R = ±Q cannot occur mid-loop.
            slope = (qy - ry) * (qx - rx).inv()
            line = slope * (px - rx) - (py - ry)
            f = f * line
            new_rx = slope * slope - rx - qx
            new_ry = slope * (rx - new_rx) - ry
            rx, ry = new_rx, new_ry
    # x < 0: f_{x} = conjugate(f_{|x|}) up to final exponentiation
    return f.conjugate()


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p¹²−1)/r): cheap easy part, then direct hard-part exponentiation."""
    # easy part: f^(p⁶−1) then ^(p²+1)
    f = f.conjugate() * f.inv()
    f = f.frobenius().frobenius() * f
    # hard part: (p⁴ − p² + 1)/r, done by plain square-and-multiply (oracle)
    hard = (P ** 4 - P ** 2 + 1) // R_ORDER
    return f ** hard


def pairing(p: G1Point, q: G2Point) -> Fq12:
    return final_exponentiation(miller_loop(p, q))


def multi_pairing_check(pairs) -> bool:
    """True iff ∏ e(Pᵢ, Qᵢ) == 1 (one shared final exponentiation)."""
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f) == Fq12.one()
