"""BLS12-381 field towers: Fq, Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³−ξ),
Fq12 = Fq6[w]/(w²−v), with ξ = 1+u.

Pure Python (arbitrary-precision ints). This is the correctness oracle for
the TPU kernels; speed only needs to be "good enough for tests".
"""

# Base field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order r
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative, low hamming weight); p and r are polynomials in x
X_PARAM = -0xD201000000010000

assert (X_PARAM - 1) ** 2 * ((X_PARAM ** 4 - X_PARAM ** 2 + 1)) // 3 + X_PARAM == P, \
    "p(x) consistency"
assert X_PARAM ** 4 - X_PARAM ** 2 + 1 == R_ORDER, "r(x) consistency"


class Fq:
    __slots__ = ("n",)

    def __init__(self, n):
        self.n = n % P

    def __add__(self, o):
        return Fq(self.n + o.n)

    def __sub__(self, o):
        return Fq(self.n - o.n)

    def __neg__(self):
        return Fq(-self.n)

    def __mul__(self, o):
        return Fq(self.n * o.n)

    def __eq__(self, o):
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self):
        return hash(self.n)

    def inv(self):
        return Fq(pow(self.n, -1, P))

    def __pow__(self, e):
        return Fq(pow(self.n, e, P))

    def is_zero(self):
        return self.n == 0

    def sqrt(self):
        """Square root; p ≡ 3 (mod 4) so x^((p+1)/4) works. None if non-residue."""
        c = pow(self.n, (P + 1) // 4, P)
        if c * c % P == self.n:
            return Fq(c)
        return None

    @staticmethod
    def zero():
        return Fq(0)

    @staticmethod
    def one():
        return Fq(1)

    def __repr__(self):
        return f"Fq(0x{self.n:x})"


class Fq2:
    """a + b·u with u² = −1."""
    __slots__ = ("a", "b")

    def __init__(self, a, b):
        self.a = a if isinstance(a, Fq) else Fq(a)
        self.b = b if isinstance(b, Fq) else Fq(b)

    def __add__(self, o):
        return Fq2(self.a + o.a, self.b + o.b)

    def __sub__(self, o):
        return Fq2(self.a - o.a, self.b - o.b)

    def __neg__(self):
        return Fq2(-self.a, -self.b)

    def __mul__(self, o):
        # (a+bu)(c+du) = (ac−bd) + (ad+bc)u  (Karatsuba)
        ac = self.a * o.a
        bd = self.b * o.b
        abcd = (self.a + self.b) * (o.a + o.b)
        return Fq2(ac - bd, abcd - ac - bd)

    def mul_scalar(self, k: int):
        return Fq2(Fq(self.a.n * k), Fq(self.b.n * k))

    def square(self):
        # (a+bu)² = (a+b)(a−b) + 2ab·u
        return Fq2((self.a + self.b) * (self.a - self.b), Fq(2 * self.a.n * self.b.n))

    def conjugate(self):
        return Fq2(self.a, -self.b)

    def inv(self):
        # 1/(a+bu) = (a−bu)/(a²+b²)
        norm = (self.a * self.a + self.b * self.b).inv()
        return Fq2(self.a * norm, -self.b * norm)

    def __pow__(self, e):
        if e < 0:
            return self.inv() ** (-e)
        result = Fq2.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def __eq__(self, o):
        return isinstance(o, Fq2) and self.a == o.a and self.b == o.b

    def __hash__(self):
        return hash((self.a.n, self.b.n))

    def is_zero(self):
        return self.a.is_zero() and self.b.is_zero()

    def is_square(self):
        # Euler criterion via the norm map: a+bu is a square in Fq2 iff
        # N(a+bu) = a²+b² is a square in Fq (since q ≡ 3 mod 4).
        n = (self.a * self.a + self.b * self.b).n
        return pow(n, (P - 1) // 2, P) in (0, 1)

    def sqrt(self):
        """Square root in Fq2 (complex method, p ≡ 3 mod 4). None if non-residue."""
        if self.is_zero():
            return Fq2.zero()
        if self.b.is_zero():
            r = self.a.sqrt()
            if r is not None:
                return Fq2(r, Fq(0))
            # sqrt(a) = sqrt(-a) * u since u² = −1
            r = (-self.a).sqrt()
            assert r is not None
            return Fq2(Fq(0), r)
        # alpha = sqrt(a² + b²) in Fq (norm is a square iff self is a square)
        alpha = (self.a * self.a + self.b * self.b).sqrt()
        if alpha is None:
            return None
        # x² = (a + alpha)/2, y = b/(2x)
        inv2 = Fq((P + 1) // 2)
        delta = (self.a + alpha) * inv2
        x = delta.sqrt()
        if x is None:
            delta = (self.a - alpha) * inv2
            x = delta.sqrt()
            if x is None:
                return None
        y = self.b * (x + x).inv()
        c = Fq2(x, y)
        assert c.square() == self
        return c

    @staticmethod
    def zero():
        return Fq2(0, 0)

    @staticmethod
    def one():
        return Fq2(1, 0)

    def frobenius(self):
        """x -> x^p (= conjugate in Fq2)."""
        return self.conjugate()

    def __repr__(self):
        return f"Fq2(0x{self.a.n:x}, 0x{self.b.n:x})"


# Non-residue for the sextic extension: ξ = 1 + u
XI = Fq2(1, 1)


class Fq6:
    """c0 + c1·v + c2·v² with v³ = ξ."""
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0, c1, c2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __add__(self, o):
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        c0 = t0 + ((a1 + a2) * (b1 + b2) - t1 - t2) * XI
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2 * XI
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def mul_by_fq2(self, x: Fq2):
        return Fq6(self.c0 * x, self.c1 * x, self.c2 * x)

    def mul_by_v(self):
        """multiply by v: (c0,c1,c2) -> (ξ·c2, c0, c1)"""
        return Fq6(self.c2 * XI, self.c0, self.c1)

    def square(self):
        return self * self

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - a1 * a2 * XI
        t1 = a2.square() * XI - a0 * a1
        t2 = a1.square() - a0 * a2
        factor = (a0 * t0 + a2 * t1 * XI + a1 * t2 * XI).inv()
        return Fq6(t0 * factor, t1 * factor, t2 * factor)

    def __eq__(self, o):
        return isinstance(o, Fq6) and self.c0 == o.c0 and self.c1 == o.c1 and self.c2 == o.c2

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    @staticmethod
    def zero():
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one():
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())


# Frobenius constants, derived (not memorized): v^p = FROB_V1 · v, v²ᵖ = FROB_V2 · v²
FROB_V1 = XI ** ((P - 1) // 3)
FROB_V2 = FROB_V1 * FROB_V1
# w^p = FROB_W · w with w² = v
FROB_W = XI ** ((P - 1) // 6)


def fq6_frobenius(x: Fq6) -> Fq6:
    return Fq6(x.c0.frobenius(),
               x.c1.frobenius() * FROB_V1,
               x.c2.frobenius() * FROB_V2)


class Fq12:
    """c0 + c1·w with w² = v."""
    __slots__ = ("c0", "c1")

    def __init__(self, c0, c1):
        self.c0, self.c1 = c0, c1

    def __add__(self, o):
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o):
        a0, a1 = self.c0, self.c1
        b0, b1 = o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self):
        return self * self

    def conjugate(self):
        """x -> x^(p^6): negates the w component."""
        return Fq12(self.c0, -self.c1)

    def inv(self):
        t = (self.c0.square() - self.c1.square().mul_by_v()).inv()
        return Fq12(self.c0 * t, -(self.c1 * t))

    def frobenius(self):
        c0 = fq6_frobenius(self.c0)
        c1 = fq6_frobenius(self.c1)
        # w-component picks up FROB_W on each Fq2 coefficient
        c1 = Fq6(c1.c0 * FROB_W, c1.c1 * FROB_W, c1.c2 * FROB_W)
        return Fq12(c0, c1)

    def __pow__(self, e):
        if e < 0:
            return self.inv() ** (-e)
        result = Fq12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def __eq__(self, o):
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero()

    @staticmethod
    def zero():
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one():
        return Fq12(Fq6.one(), Fq6.zero())
