"""BLS12-381 curve groups.

E1: y² = x³ + 4 over Fq            (G1 ⊂ E1(Fq), r-torsion)
E2: y² = x³ + 4(1+u) over Fq2      (G2 ⊂ E2(Fq2), M-twist)

Points are affine with an explicit infinity flag; group ops use simple
affine formulas (the python oracle favors clarity; the TPU kernels use
Jacobian/projective forms). Serialization is the ZCash compressed format the
reference's backends use (48-byte G1 / 96-byte G2, flag bits in the top three
bits of the first byte).
"""
from .fields import P, R_ORDER, Fq, Fq2

B1 = Fq(4)
B2 = Fq2(4, 4)


class _Point:
    """Affine point on y² = x³ + b over field F."""
    __slots__ = ("x", "y", "infinity")
    b = None
    field_one = None

    def __init__(self, x=None, y=None, infinity=False):
        self.x, self.y, self.infinity = x, y, infinity

    @classmethod
    def inf(cls):
        return cls(infinity=True)

    def is_on_curve(self):
        if self.infinity:
            return True
        return self.y * self.y == self.x * self.x * self.x + type(self).b

    def __eq__(self, o):
        if self.infinity or o.infinity:
            return self.infinity and o.infinity
        return self.x == o.x and self.y == o.y

    def __neg__(self):
        if self.infinity:
            return self
        return type(self)(self.x, -self.y)

    def double(self):
        if self.infinity or self.y.is_zero():
            return type(self).inf()
        x, y = self.x, self.y
        three = self.x + self.x + self.x
        lam = three * x * (y + y).inv()
        x3 = lam * lam - x - x
        y3 = lam * (x - x3) - y
        return type(self)(x3, y3)

    def __add__(self, o):
        if self.infinity:
            return o
        if o.infinity:
            return self
        if self.x == o.x:
            if self.y == o.y:
                return self.double()
            return type(self).inf()
        lam = (o.y - self.y) * (o.x - self.x).inv()
        x3 = lam * lam - self.x - o.x
        y3 = lam * (self.x - x3) - self.y
        return type(self)(x3, y3)

    def __sub__(self, o):
        return self + (-o)

    def mult(self, k: int):
        """Scalar multiplication; negative scalars negate the point."""
        if k < 0:
            return (-self).mult(-k)
        result = type(self).inf()
        addend = self
        while k:
            if k & 1:
                result = result + addend
            addend = addend.double()
            k >>= 1
        return result

    def in_subgroup(self):
        return self.mult(R_ORDER).infinity


class G1Point(_Point):
    b = B1

    def to_compressed(self) -> bytes:
        if self.infinity:
            return bytes([0xC0]) + b"\x00" * 47
        data = bytearray(self.x.n.to_bytes(48, "big"))
        data[0] |= 0x80
        if self.y.n > (P - 1) // 2:
            data[0] |= 0x20
        return bytes(data)


class G2Point(_Point):
    b = B2

    def to_compressed(self) -> bytes:
        if self.infinity:
            return bytes([0xC0]) + b"\x00" * 95
        data = bytearray(self.x.b.n.to_bytes(48, "big") + self.x.a.n.to_bytes(48, "big"))
        data[0] |= 0x80
        y_im, y_re = self.y.b.n, self.y.a.n
        if (y_im > (P - 1) // 2) if y_im != 0 else (y_re > (P - 1) // 2):
            data[0] |= 0x20
        return bytes(data)


def msm(points, scalars, window: int = 4):
    """Windowed-bucket (Pippenger) multi-scalar multiplication over either
    group: ``sum([k_i] P_i)`` for a list of ``G1Point``s or ``G2Point``s.

    Group-agnostic — only uses the shared affine ``+``/``double``/``inf``
    surface, so the RLC batch verifier can run its G2 signature
    combination ``sum(r_i * sig_i)`` through the same code path as small
    G1 folds.  The window defaults to 4 bits: RLC coefficients are
    128-bit, where 32 windows x 15 buckets beats the 8-bit setup cost.
    """
    assert len(points) == len(scalars)
    live = [(p, int(s)) for p, s in zip(points, scalars)
            if not p.infinity and int(s) % R_ORDER != 0]
    if not live:
        return (type(points[0]).inf() if points else G1Point.inf())
    cls = type(live[0][0])
    scal = [s % R_ORDER for _, s in live]
    n_bits = max(s.bit_length() for s in scal)
    n_windows = (n_bits + window - 1) // window
    mask = (1 << window) - 1
    result = cls.inf()
    for w in range(n_windows - 1, -1, -1):
        if not result.infinity:
            for _ in range(window):
                result = result.double()
        buckets = [None] * (mask + 1)
        for (pt, _), s in zip(live, scal):
            digit = (s >> (w * window)) & mask
            if digit:
                buckets[digit] = pt if buckets[digit] is None \
                    else buckets[digit] + pt
        running = cls.inf()
        window_sum = cls.inf()
        for digit in range(mask, 0, -1):
            if buckets[digit] is not None:
                running = running + buckets[digit]
            window_sum = window_sum + running
        result = result + window_sum
    return result


def _check_flags(data: bytes):
    c_flag = (data[0] >> 7) & 1
    i_flag = (data[0] >> 6) & 1
    s_flag = (data[0] >> 5) & 1
    if c_flag != 1:
        raise ValueError("only compressed encodings supported")
    return i_flag, s_flag


def g1_from_compressed(data: bytes) -> G1Point:
    if len(data) != 48:
        raise ValueError("G1 compressed encoding must be 48 bytes")
    i_flag, s_flag = _check_flags(data)
    x_int = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if i_flag:
        if x_int != 0 or s_flag:
            raise ValueError("malformed infinity encoding")
        return G1Point.inf()
    if x_int >= P:
        raise ValueError("x not canonical")
    x = Fq(x_int)
    y2 = x * x * x + B1
    y = y2.sqrt()
    if y is None:
        raise ValueError("x not on curve")
    if (y.n > (P - 1) // 2) != bool(s_flag):
        y = -y
    pt = G1Point(x, y)
    assert pt.is_on_curve()
    return pt


def g2_from_compressed(data: bytes) -> G2Point:
    if len(data) != 96:
        raise ValueError("G2 compressed encoding must be 96 bytes")
    i_flag, s_flag = _check_flags(data)
    x_im = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x_re = int.from_bytes(data[48:], "big")
    if i_flag:
        if x_im != 0 or x_re != 0 or s_flag:
            raise ValueError("malformed infinity encoding")
        return G2Point.inf()
    if x_im >= P or x_re >= P:
        raise ValueError("x not canonical")
    x = Fq2(x_re, x_im)
    y2 = x * x * x + B2
    y = y2.sqrt()
    if y is None:
        raise ValueError("x not on curve")
    y_im, y_re = y.b.n, y.a.n
    y_sign = (y_im > (P - 1) // 2) if y_im != 0 else (y_re > (P - 1) // 2)
    if y_sign != bool(s_flag):
        y = -y
    pt = G2Point(x, y)
    assert pt.is_on_curve()
    return pt


# Standard generators (public parameters of the ciphersuite).
G1_GENERATOR = G1Point(
    Fq(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB),
    Fq(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1),
)
G2_GENERATOR = G2Point(
    Fq2(0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    Fq2(0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)

assert G1_GENERATOR.is_on_curve(), "G1 generator must lie on E1"
assert G2_GENERATOR.is_on_curve(), "G2 generator must lie on E2"
