"""BLS signature scheme (proof-of-possession ciphersuite), python backend.

The 9-function API surface the reference exposes from its backends
(reference: ``tests/core/pyspec/eth2spec/utils/bls.py:107-202``): SkToPk,
Sign, Verify, Aggregate, AggregateVerify, FastAggregateVerify, AggregatePKs,
KeyValidate, plus point helpers. Pubkeys are 48-byte compressed G1,
signatures 96-byte compressed G2.
"""
from typing import Sequence

from .fields import R_ORDER
from .curve import (
    G1Point, G2Point, G1_GENERATOR,
    g1_from_compressed, g2_from_compressed,
)
from .pairing import multi_pairing_check
from .hash_to_curve import hash_to_g2


def SkToPk(sk: int) -> bytes:
    if not 0 < sk < R_ORDER:
        raise ValueError("secret key out of range")
    return G1_GENERATOR.mult(sk).to_compressed()


def Sign(sk: int, msg: bytes) -> bytes:
    if not 0 < sk < R_ORDER:
        raise ValueError("secret key out of range")
    return hash_to_g2(msg).mult(sk).to_compressed()


def _decode_pubkey(pk: bytes):
    """Decode + KeyValidate in one pass; returns the G1 point or None."""
    try:
        p = g1_from_compressed(pk)
    except Exception:
        return None
    if p.infinity or not p.in_subgroup():
        return None
    return p


def KeyValidate(pk: bytes) -> bool:
    return _decode_pubkey(pk) is not None


def _decode_sig(sig: bytes) -> G2Point:
    s = g2_from_compressed(sig)
    if not s.in_subgroup():
        raise ValueError("signature not in G2 subgroup")
    return s


def Verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        p = _decode_pubkey(pk)
        if p is None:
            return False
        s = _decode_sig(sig)
        hm = hash_to_g2(msg)
        return multi_pairing_check([(p, hm), (-G1_GENERATOR, s)])
    except Exception:
        return False


def Aggregate(signatures: Sequence[bytes]) -> bytes:
    if len(signatures) == 0:
        raise ValueError("cannot aggregate empty signature list")
    acc = G2Point.inf()
    for sig in signatures:
        acc = acc + g2_from_compressed(sig)
    return acc.to_compressed()


def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    if len(pubkeys) == 0:
        raise ValueError("cannot aggregate empty pubkey list")
    acc = G1Point.inf()
    for pk in pubkeys:
        p = _decode_pubkey(pk)
        if p is None:
            raise ValueError("invalid pubkey in aggregation")
        acc = acc + p
    return acc.to_compressed()


def AggregateVerify(pubkeys: Sequence[bytes], messages: Sequence[bytes], sig: bytes) -> bool:
    try:
        if len(pubkeys) == 0 or len(pubkeys) != len(messages):
            return False
        s = _decode_sig(sig)
        pairs = []
        for pk, msg in zip(pubkeys, messages):
            p = _decode_pubkey(pk)
            if p is None:
                return False
            pairs.append((p, hash_to_g2(msg)))
        pairs.append((-G1_GENERATOR, s))
        return multi_pairing_check(pairs)
    except Exception:
        return False


def FastAggregateVerify(pubkeys: Sequence[bytes], msg: bytes, sig: bytes) -> bool:
    try:
        if len(pubkeys) == 0:
            return False
        acc = G1Point.inf()
        for pk in pubkeys:
            p = _decode_pubkey(pk)
            if p is None:
                return False
            acc = acc + p
        s = _decode_sig(sig)
        hm = hash_to_g2(msg)
        return multi_pairing_check([(acc, hm), (-G1_GENERATOR, s)])
    except Exception:
        return False
