"""Hash-to-G2 for the BLS signature scheme.

Implements the RFC 9380 construction used by the eth2 ciphersuite
``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_`` (reference:
``specs/phase0/beacon-chain.md:660``): expand_message_xmd(SHA-256) →
hash_to_field(Fq2, m=2, L=64) → simplified-SWU on the 3-isogenous curve E'
(A' = 240u, B' = 1012(1+u), Z = −(2+u)) → the RFC 9380 Appendix E.3
3-isogeny rational map to E2 → cofactor clearing via the ψ
(untwist-Frobenius-twist) endomorphism (Budroni–Pintore).

The isogeny uses the standard E.3 constant table (not a derived map).  It
is self-verified at import: every mapped point must land on E2, the map
must be a group homomorphism E'→E2, and hashed points must land in the
r-torsion subgroup — a single wrong constant fails those checks with
overwhelming probability.
"""
import hashlib
from typing import List, Tuple

from .fields import P, R_ORDER, X_PARAM, Fq2
from .curve import G2Point, G2_GENERATOR, B2

# SSWU curve E': y² = x³ + A'x + B'
A_PRIME = Fq2(0, 240)
B_PRIME = Fq2(1012, 1012)
Z_SSWU = Fq2(-2 % P, -1 % P)  # −(2+u)

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


# ---------------------------------------------------------------------------
# expand_message_xmd + hash_to_field  (RFC 9380 §5)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    b_in_bytes = 32   # SHA-256 output
    r_in_bytes = 64   # SHA-256 block
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * r_in_bytes
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b_vals = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        prev = b_vals[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        b_vals.append(hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(b_vals)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2) -> List[Fq2]:
    L = 64
    data = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = L * (j + i * 2)
            coords.append(int.from_bytes(data[off:off + L], "big") % P)
        out.append(Fq2(coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# simplified SWU on E'
# ---------------------------------------------------------------------------

def _sgn0(x: Fq2) -> int:
    s0 = x.a.n % 2
    if x.a.n != 0:
        return s0
    return x.b.n % 2


def map_to_curve_sswu(u: Fq2) -> Tuple[Fq2, Fq2]:
    """RFC 9380 §6.6.2 (simple version); returns a point on E'."""
    A, B, Z = A_PRIME, B_PRIME, Z_SSWU
    zu2 = Z * u.square()
    tv = zu2.square() + zu2
    if tv.is_zero():
        x1 = B * (Z * A).inv()
    else:
        x1 = (-B) * A.inv() * (Fq2.one() + tv.inv())
    gx1 = x1.square() * x1 + A * x1 + B
    if gx1.is_square():
        x, y = x1, gx1.sqrt()
    else:
        x2 = zu2 * x1
        gx2 = x2.square() * x2 + A * x2 + B
        y = gx2.sqrt()
        assert y is not None, "SSWU: one of gx1/gx2 must be square"
        x = x2
    if _sgn0(u) != _sgn0(y):
        y = -y
    assert y.square() == x.square() * x + A * x + B
    return x, y


# ---------------------------------------------------------------------------
# 3-isogeny E' -> E2: RFC 9380 Appendix E.3 rational map
# ---------------------------------------------------------------------------
#
# X = x_num(x')/x_den(x');  Y = y' * y_num(x')/y_den(x')
# Coefficients k_(i,j) as Fq2 = re + im*u, low degree first.

ISO_XNUM = (
    Fq2(0x5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6,
        0x5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6),
    Fq2(0,
        0x11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71a),
    Fq2(0x11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71e,
        0x8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38d),
    Fq2(0x171d6541fa38ccfaed6dea691f5fb614cb14b4e7f4e810aa22d6108f142b85757098e38d0f671c7188e2aaaaaaaa5ed1,
        0),
)
ISO_XDEN = (
    Fq2(0,
        0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa63),
    Fq2(0xc,
        0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa9f),
    Fq2(1, 0),  # monic x'^2
)
ISO_YNUM = (
    Fq2(0x1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706,
        0x1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706),
    Fq2(0,
        0x5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97be),
    Fq2(0x11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71c,
        0x8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38f),
    Fq2(0x124c9ad43b6cf79bfbf7043de3811ad0761b0f37a1e26286b0e977c69aa274524e79097a56dc4bd9e1b371c71c718b10,
        0),
)
ISO_YDEN = (
    Fq2(0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb,
        0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb),
    Fq2(0,
        0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa9d3),
    Fq2(0x12,
        0x1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa99),
    Fq2(1, 0),  # monic x'^3
)


def _poly_eval(coeffs, x: Fq2) -> Fq2:
    acc = Fq2.zero()
    for c in reversed(coeffs):
        acc = acc * x + c
    return acc


def iso_map_g2(x: Fq2, y: Fq2) -> Tuple[Fq2, Fq2]:
    """Evaluate the E.3 rational map at an affine E' point."""
    x_num = _poly_eval(ISO_XNUM, x)
    x_den = _poly_eval(ISO_XDEN, x)
    y_num = _poly_eval(ISO_YNUM, x)
    y_den = _poly_eval(ISO_YDEN, x)
    return x_num * x_den.inv(), y * y_num * y_den.inv()




# ---------------------------------------------------------------------------
# ψ endomorphism + cofactor clearing
# ---------------------------------------------------------------------------

from .fields import XI  # noqa: E402

_PSI_CX = (XI ** ((P - 1) // 3)).inv()
_PSI_CY = (XI ** ((P - 1) // 2)).inv()


def psi(pt: G2Point) -> G2Point:
    if pt.infinity:
        return pt
    return G2Point(pt.x.frobenius() * _PSI_CX, pt.y.frobenius() * _PSI_CY)


# sanity: ψ acts as multiplication by p on G2
assert psi(G2_GENERATOR) == G2_GENERATOR.mult(P % R_ORDER), "psi must equal [p] on G2"


def clear_cofactor(pt: G2Point) -> G2Point:
    """Budroni–Pintore fast cofactor clearing:
    [h_eff]P = [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P), x the (negative) BLS param.
    """
    x = X_PARAM
    t1 = pt.mult(x * x - x - 1)
    t2 = psi(pt).mult(x - 1)
    t3 = psi(psi(pt.double()))
    out = t1 + t2 + t3
    return out


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> G2Point:
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = iso_map_g2(*map_to_curve_sswu(u0))
    q1 = iso_map_g2(*map_to_curve_sswu(u1))
    p0 = G2Point(q0[0], q0[1])
    p1 = G2Point(q1[0], q1[1])
    return clear_cofactor(p0 + p1)


# ---------------------------------------------------------------------------
# one-time import self-checks
# ---------------------------------------------------------------------------

def _eprime_add(p1, p2):
    """Generic affine short-Weierstrass addition on E' (for verification)."""
    (x1, y1), (x2, y2) = p1, p2
    if x1 == x2 and y1 == y2:
        lam = (x1.square().mul_scalar(3) + A_PRIME) * (y1 + y1).inv()
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam.square() - x1 - x2
    return x3, lam * (x1 - x3) - y1


def _verify_iso():
    # 1. mapped SSWU points are on E2 (y² = x³ + B2)
    pts = []
    for tag in (b"iso-check-0", b"iso-check-1", b"iso-check-2"):
        u = hash_to_field_fq2(tag, 1)[0]
        xp, yp = map_to_curve_sswu(u)
        X, Y = iso_map_g2(xp, yp)
        assert Y.square() == X.square() * X + B2, "E.3 map image must lie on E2"
        pts.append(((xp, yp), G2Point(X, Y)))
    # 2. homomorphism: iso(P ⊕' Q) == iso(P) + iso(Q) on E2
    (p_aff, p_img), (q_aff, q_img) = pts[0], pts[1]
    s_aff = _eprime_add(p_aff, q_aff)
    Xs, Ys = iso_map_g2(*s_aff)
    assert G2Point(Xs, Ys) == p_img + q_img, "E.3 map must be a homomorphism"


_verify_iso()

# hashed points land in the r-torsion subgroup
_probe = hash_to_g2(b"subgroup-probe")
assert _probe.mult(R_ORDER).infinity, "hash_to_g2 must land in G2"
assert not _probe.infinity
