"""Hash-to-G2 for the BLS signature scheme.

Implements the RFC 9380 construction used by the eth2 ciphersuite
``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_`` (reference:
``specs/phase0/beacon-chain.md:660``): expand_message_xmd(SHA-256) →
hash_to_field(Fq2, m=2, L=64) → simplified-SWU on the 3-isogenous curve E'
(A' = 240u, B' = 1012(1+u), Z = −(2+u)) → 3-isogeny to E2 → cofactor
clearing via the ψ (untwist-Frobenius-twist) endomorphism.

Zero-egress caveat: the 3-isogeny rational map is DERIVED here at import via
Vélu's formulas from a kernel root of E'’s 3-division polynomial, then
self-verified (image on E2, homomorphism property, subgroup landing). The
derivation pins down the isogeny only up to post-composition with an
automorphism of E2, so hashed points may differ from the IETF ciphersuite by
that automorphism until checked against official vectors; the scheme is
internally consistent (sign↔verify) either way. TODO(round-2+): pin exact
RFC 9380 E.3 constants against external vectors.
"""
import hashlib
from typing import List, Tuple

from .fields import P, R_ORDER, X_PARAM, Fq, Fq2
from .curve import G2Point, G2_GENERATOR, B2

# SSWU curve E': y² = x³ + A'x + B'
A_PRIME = Fq2(0, 240)
B_PRIME = Fq2(1012, 1012)
Z_SSWU = Fq2(-2 % P, -1 % P)  # −(2+u)

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


# ---------------------------------------------------------------------------
# expand_message_xmd + hash_to_field  (RFC 9380 §5)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    b_in_bytes = 32   # SHA-256 output
    r_in_bytes = 64   # SHA-256 block
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * r_in_bytes
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b_vals = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        prev = b_vals[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        b_vals.append(hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(b_vals)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2) -> List[Fq2]:
    L = 64
    data = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = L * (j + i * 2)
            coords.append(int.from_bytes(data[off:off + L], "big") % P)
        out.append(Fq2(coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# simplified SWU on E'
# ---------------------------------------------------------------------------

def _sgn0(x: Fq2) -> int:
    s0 = x.a.n % 2
    if x.a.n != 0:
        return s0
    return x.b.n % 2


def map_to_curve_sswu(u: Fq2) -> Tuple[Fq2, Fq2]:
    """RFC 9380 §6.6.2 (simple version); returns a point on E'."""
    A, B, Z = A_PRIME, B_PRIME, Z_SSWU
    zu2 = Z * u.square()
    tv = zu2.square() + zu2
    if tv.is_zero():
        x1 = B * (Z * A).inv()
    else:
        x1 = (-B) * A.inv() * (Fq2.one() + tv.inv())
    gx1 = x1.square() * x1 + A * x1 + B
    if gx1.is_square():
        x, y = x1, gx1.sqrt()
    else:
        x2 = zu2 * x1
        gx2 = x2.square() * x2 + A * x2 + B
        y = gx2.sqrt()
        assert y is not None, "SSWU: one of gx1/gx2 must be square"
        x = x2
    if _sgn0(u) != _sgn0(y):
        y = -y
    assert y.square() == x.square() * x + A * x + B
    return x, y


# ---------------------------------------------------------------------------
# 3-isogeny E' -> E2, derived via Vélu's formulas
# ---------------------------------------------------------------------------

def _cube_root(c: Fq2):
    """Cube root in Fq2; None if c is not a cube.

    q² − 1 = 3^s·t with s = 2 for this field, so after computing
    x0 = c^(3⁻¹ mod t) (correct up to a 3-Sylow component of order ≤ 9) the
    right cube root is found by scanning x0·e^j over the 9-element Sylow
    subgroup.
    """
    if c.is_zero():
        return Fq2.zero()
    q1 = P * P - 1
    s, t = 0, q1
    while t % 3 == 0:
        s, t = s + 1, t // 3
    # find a generator of the 3-Sylow subgroup: e = g^t for a cubic non-residue g
    e = None
    for trial_a in range(2, 40):
        g = Fq2(trial_a, 1)
        if (g ** (q1 // 3)) != Fq2.one():
            e = g ** t
            break
    assert e is not None, "no cubic non-residue found"
    x0 = c ** pow(3, -1, t)
    cand = x0
    for _ in range(3 ** s):
        if cand * cand * cand == c:
            return cand
        cand = cand * e
    return None


def _sixth_root(c: Fq2):
    r = c.sqrt()
    if r is not None:
        cr = _cube_root(r)
        if cr is not None:
            return cr
        cr = _cube_root(-r)
        if cr is not None:
            return cr
    return None


def _derive_isogeny():
    """Find the 3-isogeny E' -> E2 (Vélu) and return its rational map.

    Returns (iso,) where iso(x, y) -> (X, Y) on E2.
    """
    A, B = A_PRIME, B_PRIME
    # 3-division polynomial of E': ψ₃(x) = 3x⁴ + 6Ax² + 12Bx − A²
    # Find its roots in Fq2 by exhaustive gcd with x^(q²) − x over the quartic
    # — implemented as: for each candidate root found by factoring via
    # repeated root-extraction (the quartic has at most 4 roots; find them by
    # solving with resolvent-free numeric search: try roots of form derived
    # from polynomial gcd). Simpler: use that ψ₃ factors and find roots by
    # computing gcd(x^q² − x, ψ₃) via modular exponentiation of x.
    q2 = P * P

    def poly_mulmod(f, g, mod):
        out = [Fq2.zero()] * (len(f) + len(g) - 1)
        for i, fi in enumerate(f):
            if fi.is_zero():
                continue
            for j, gj in enumerate(g):
                out[i + j] = out[i + j] + fi * gj
        return poly_mod(out, mod)

    def poly_mod(f, mod):
        # mod: monic, degree 4
        f = list(f)
        dm = len(mod) - 1
        while len(f) > dm:
            lead = f[-1]
            if not lead.is_zero():
                shift = len(f) - 1 - dm
                for i in range(dm):
                    f[shift + i] = f[shift + i] - lead * mod[i]
            f.pop()
        return f

    inv3 = Fq2(pow(3, -1, P), 0)
    # monic ψ₃: x⁴ + 2A x² + 4B x − A²/3
    psi3 = [(-(A * A)) * inv3, B.mul_scalar(4), A.mul_scalar(2), Fq2.zero(), Fq2.one()]

    # x^(q²) mod ψ₃ by square-and-multiply on the polynomial x
    xpoly = [Fq2.zero(), Fq2.one()]
    result = [Fq2.one()]
    base = xpoly
    e = q2
    while e:
        if e & 1:
            result = poly_mulmod(result, base, psi3)
        base = poly_mulmod(base, base, psi3)
        e >>= 1
    # gcd(x^(q²) − x, ψ₃)
    f1 = [a for a in result]
    while len(f1) < 2:
        f1.append(Fq2.zero())
    f1[1] = f1[1] - Fq2.one()  # subtract x

    def poly_gcd(a, b):
        a, b = list(a), list(b)

        def norm(f):
            while f and f[-1].is_zero():
                f.pop()
            return f
        a, b = norm(a), norm(b)
        while b:
            # a mod b
            binv = b[-1].inv()
            while len(a) >= len(b):
                lead = a[-1] * binv
                shift = len(a) - len(b)
                for i in range(len(b)):
                    a[shift + i] = a[shift + i] - lead * b[i]
                a = norm(a)
                if len(a) < len(b):
                    break
            a, b = b, a
        return norm(a)

    g = poly_gcd([a for a in psi3], f1)
    # g has the Fq2-rational kernel x-coordinates as roots (degree 1 or 2)
    roots = []
    if len(g) == 2:  # linear: x + c0  (monic after normalization)
        roots.append(-(g[0] * g[1].inv()))
    elif len(g) == 3:  # quadratic
        c = g[0] * g[2].inv()
        bq = g[1] * g[2].inv()
        disc = bq * bq - c.mul_scalar(4)
        sd = disc.sqrt()
        if sd is not None:
            half = Fq2(pow(2, -1, P), 0)
            roots.append((-bq + sd) * half)
            roots.append((-bq - sd) * half)
    else:
        # fall back: try all roots via quartic being fully split — factor by
        # repeatedly extracting linear factors with random shifts
        raise RuntimeError(f"unexpected kernel gcd degree {len(g) - 1}")

    for x0 in roots:
        y0sq = x0 * x0 * x0 + A * x0 + B
        # Vélu needs the kernel point coordinates; y0 may live in Fq4 but the
        # formulas below only use y0² — they stay in Fq2 regardless.
        gx = x0.square().mul_scalar(3) + A
        u_p = y0sq.mul_scalar(4)
        v_p = gx.mul_scalar(2)
        v_sum, w_sum = v_p, u_p + x0 * v_p
        a_cod = A - v_sum.mul_scalar(5)
        b_cod = B - w_sum.mul_scalar(7)
        if not a_cod.is_zero():
            continue  # wrong kernel: codomain must have j = 0
        # scale codomain y² = x³ + b_cod onto E2: need s⁶ = B2 / b_cod
        s = _sixth_root(B2 * b_cod.inv())
        if s is None:
            continue
        s2, s3 = s.square(), s.square() * s

        global ISO_CONSTANTS
        ISO_CONSTANTS = (x0, u_p, v_p, s2, s3)

        def iso(x, y, x0=x0, u_p=u_p, v_p=v_p, s2=s2, s3=s3):
            d = x - x0
            dinv = d.inv()
            X = x + v_p * dinv + u_p * dinv.square()
            Y = y * (Fq2.one() - v_p * dinv.square() - u_p.mul_scalar(2) * dinv.square() * dinv)
            return X * s2, Y * s3

        # verify on a sample of E' points produced by SSWU
        ok = True
        for test_msg in (b"velu-test-1", b"velu-test-2", b"velu-test-3"):
            ux = hash_to_field_fq2(test_msg, 1)[0]
            px, py = map_to_curve_sswu(ux)
            X, Y = iso(px, py)
            if Y.square() != X.square() * X + B2:
                ok = False
                break
        if ok:
            return iso
    raise RuntimeError("3-isogeny derivation failed")


_ISO = _derive_isogeny()


# ---------------------------------------------------------------------------
# ψ endomorphism + cofactor clearing
# ---------------------------------------------------------------------------

from .fields import XI  # noqa: E402

_PSI_CX = (XI ** ((P - 1) // 3)).inv()
_PSI_CY = (XI ** ((P - 1) // 2)).inv()


def psi(pt: G2Point) -> G2Point:
    if pt.infinity:
        return pt
    return G2Point(pt.x.frobenius() * _PSI_CX, pt.y.frobenius() * _PSI_CY)


# sanity: ψ acts as multiplication by p on G2
assert psi(G2_GENERATOR) == G2_GENERATOR.mult(P % R_ORDER), "psi must equal [p] on G2"


def clear_cofactor(pt: G2Point) -> G2Point:
    """Budroni–Pintore fast cofactor clearing:
    [h_eff]P = [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P), x the (negative) BLS param.
    """
    x = X_PARAM
    t1 = pt.mult(x * x - x - 1)
    t2 = psi(pt).mult(x - 1)
    t3 = psi(psi(pt.double()))
    out = t1 + t2 + t3
    return out


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> G2Point:
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = _ISO(*map_to_curve_sswu(u0))
    q1 = _ISO(*map_to_curve_sswu(u1))
    p0 = G2Point(q0[0], q0[1])
    p1 = G2Point(q1[0], q1[1])
    return clear_cofactor(p0 + p1)


# one-time self-check: hashed points land in the r-torsion subgroup
_probe = hash_to_g2(b"subgroup-probe")
assert _probe.mult(R_ORDER).infinity, "hash_to_g2 must land in G2"
assert not _probe.infinity
