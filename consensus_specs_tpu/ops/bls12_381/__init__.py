"""BLS12-381 arithmetic.

Pure-Python reference implementation (the differential-test oracle, standing
in for the reference's ``py_ecc`` dependency — reference:
``tests/core/pyspec/eth2spec/utils/bls.py``) plus shared curve parameters for
the JAX/TPU kernels in ``consensus_specs_tpu.ops.bls_jax``.

Every derived constant (Frobenius coefficients, the SSWU isogeny, cofactor
formulas) is computed from the base parameters at import and self-verified,
so there are no opaque magic numbers to mistype.
"""
from .fields import P, R_ORDER, X_PARAM, Fq, Fq2, Fq6, Fq12
from .curve import (
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR,
    g1_from_compressed, g2_from_compressed,
)
from .pairing import miller_loop, final_exponentiation, pairing, multi_pairing_check

__all__ = [
    "P", "R_ORDER", "X_PARAM", "Fq", "Fq2", "Fq6", "Fq12",
    "G1Point", "G2Point", "G1_GENERATOR", "G2_GENERATOR",
    "g1_from_compressed", "g2_from_compressed",
    "miller_loop", "final_exponentiation", "pairing", "multi_pairing_check",
]
