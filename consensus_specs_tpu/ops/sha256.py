"""Batched SHA-256 as a JAX kernel.

SHA-256 dominates ``hash_tree_root`` (reference hash fn:
``tests/core/pyspec/eth2spec/utils/hash_function.py:8``); a 1M-validator
``BeaconState`` merkleization is millions of 64-byte-message hashes. The
reference does them one by one through hashlib; here a whole tree layer is
hashed as ONE vectorized kernel call: the compression function is written in
``jnp.uint32`` ops and ``vmap``-ed over the message axis, so XLA lays the
64-round schedule out across the TPU VPU lanes.

Two entry points:

- :func:`hash64_batch` — the merkle hot path: N independent 64-byte messages
  (two compression rounds each: message block + constant padding block).
- :func:`sha256_blocks` — generic N-block single-message path used by the
  hash-to-curve ``expand_message_xmd`` kernel.

Shapes are bucketed to powers of two so XLA compiles O(log N) program
variants, not one per layer width.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

# Round constants (FIPS 180-4 §4.2.2): cube-root fractional parts of the
# first 64 primes.
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

# Initial hash state (square-root fractional parts of the first 8 primes).
_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress(state, block_words):
    """One SHA-256 compression: state (..., 8) u32, block (..., 16) u32.

    Round loops run under ``lax.scan`` (not unrolled): XLA traces ONE
    round body instead of 112, keeping the compiled module small —
    unrolling blew XLA:CPU's LLVM pipeline past 50 minutes of compile
    at the batched shapes the merkle layer uses, and the scan form is
    the compiler-friendly shape on TPU as well.

    W-extension scan carries the sliding 16-word window along the last
    axis; the round scan carries the 8 working variables.
    """
    w16 = jnp.stack([block_words[..., i] for i in range(16)], axis=0)

    def w_step(window, _):
        # window: (16, ...) — oldest word first
        s0 = _rotr(window[1], 7) ^ _rotr(window[1], 18) ^ (window[1] >> 3)
        s1 = _rotr(window[14], 17) ^ _rotr(window[14], 19) \
            ^ (window[14] >> 10)
        nxt = window[0] + s0 + window[9] + s1
        return jnp.concatenate([window[1:], nxt[None]], axis=0), nxt

    window, w_ext = jax.lax.scan(w_step, w16, None, length=48)
    w_all = jnp.concatenate([w16, w_ext], axis=0)          # (64, ...)

    def round_step(vars8, inputs):
        k_i, w_i = inputs
        a, b, c, d, e, f, g, h = vars8
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_i + w_i
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    init = tuple(state[..., i] for i in range(8))
    ks = jnp.asarray(_K).reshape((64,) + (1,) * (w_all.ndim - 1))
    (a, b, c, d, e, f, g, h), _ = jax.lax.scan(
        round_step, init, (ks, w_all))
    return state + jnp.stack([a, b, c, d, e, f, g, h], axis=-1)


# Padding block for a 64-byte message: 0x80 marker, zeros, 512-bit length.
_PAD64 = np.zeros(16, dtype=np.uint32)
_PAD64[0] = 0x80000000
_PAD64[15] = 512


@functools.partial(jax.jit, static_argnames=())
def _hash64_words(words):
    """words: (N, 16) u32 big-endian message words -> (N, 8) u32 digests."""
    n = words.shape[0]
    state = jnp.broadcast_to(jnp.asarray(_H0), (n, 8))
    state = _compress(state, words)
    pad = jnp.broadcast_to(jnp.asarray(_PAD64), (n, 16))
    return _compress(state, pad)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def hash64_batch(data: bytes, n: int) -> bytes:
    """Hash ``n`` concatenated 64-byte messages -> ``n`` 32-byte digests.

    This is the ``set_batched_hasher`` plug for the merkle engine
    (:mod:`consensus_specs_tpu.utils.ssz.merkle`).
    """
    return hash64_batch_np(
        np.frombuffer(data, dtype=np.uint8).reshape(n, 64)).tobytes()


def hash64_batch_np(rows: np.ndarray) -> np.ndarray:
    """Array-path variant for the incremental engine's gathered dirty-pair
    buffers (``set_batched_hasher_np``): ``(n, 64)`` uint8 message rows in,
    ``(n, 32)`` uint8 digests out — no bytes round-trip on either side."""
    n = rows.shape[0]
    words = rows.view(">u4").astype(np.uint32)
    n_pad = _next_pow2(n)
    if n_pad != n:
        words = np.concatenate([words, np.zeros((n_pad - n, 16), np.uint32)])
    out = np.asarray(_hash64_words(jnp.asarray(words)))[:n]
    return out.astype(">u4").view(np.uint8).reshape(n, 32)


@functools.partial(jax.jit, static_argnames=("num_blocks",))
def sha256_blocks(blocks, num_blocks: int):
    """Sequential compression of pre-padded blocks.

    blocks: (..., num_blocks, 16) u32 -> (..., 8) u32. The caller is
    responsible for FIPS-180-4 padding; used by the in-graph
    ``expand_message_xmd`` (hash-to-curve kernel).
    """
    state = jnp.broadcast_to(jnp.asarray(_H0), blocks.shape[:-2] + (8,))
    for i in range(num_blocks):  # noqa: J203 (static unroll per block count)
        state = _compress(state, blocks[..., i, :])
    return state


def install_merkle_hasher() -> None:
    """Route SSZ layer hashing through the batched kernel (both the
    bytes-layer and the gathered-pair array entry points)."""
    from consensus_specs_tpu.utils.ssz import merkle
    merkle.set_batched_hasher(hash64_batch)
    merkle.set_batched_hasher_np(hash64_batch_np)


def sha256_bytes(msg: bytes) -> bytes:
    """One-shot SHA-256 of an arbitrary message via the kernel (testing aid)."""
    length = len(msg)
    padded = msg + b"\x80"
    if len(padded) % 64 > 56:
        padded += b"\x00" * (64 - len(padded) % 64)
    padded += b"\x00" * (56 - len(padded) % 64 if len(padded) % 64 <= 56 else 0)
    padded += (length * 8).to_bytes(8, "big")
    nb = len(padded) // 64
    words = np.frombuffer(padded, dtype=">u4").reshape(nb, 16).astype(np.uint32)
    out = np.asarray(sha256_blocks(jnp.asarray(words), nb))
    return out.astype(">u4").tobytes()
