"""Curdleproofs-style zero-knowledge shuffle argument for whisk.

The reference delegates whisk shuffle verification to the external
``curdleproofs`` package (reference ``setup.py:555``; whisk
beacon-chain.md: "verifier code ... is specified in curdleproofs.pie").
This module implements the argument in-tree, with the same architecture
as the curdleproofs construction:

* the **shuffle relation**: given pre-shuffle tracker columns
  ``R, S`` and post-shuffle columns ``T, U`` (all G1 vectors), the
  prover knows a permutation ``sigma`` and a scalar ``k`` with
  ``T[i] = k * R[sigma[i]]`` and ``U[i] = k * S[sigma[i]]``;
* a Pedersen vector commitment ``B`` to the permuted powers
  ``b[sigma[i]] = a^(i+1)`` of a Fiat-Shamir challenge ``a``;
* a **grand-product argument** (Neff check): ``b`` is a permutation of
  the powers iff ``prod(b_j + beta) == prod(a^j + beta)`` for random
  ``beta``; proven over the committed vector via a partial-products
  vector and a two-vector Bulletproofs-style **inner-product argument**
  with log-size L/R folding;
* a **same-multiscalar argument**: the MSM values
  ``V_R = <b, R>``, ``V_S = <b, S>`` use the same ``b`` committed in
  ``B`` (masked sigma-opening + simultaneous three-base folding);
* a **same-scalar (DLEQ)** argument: ``sum a^i T_i = k * V_R`` and
  ``sum a^i U_i = k * V_S`` for one common ``k``.

Zero-knowledge: the permutation never appears on the wire; the folded
vectors are one-time masked (challenge rho / gamma) so every revealed
scalar is uniform.  The MSM values ``V_R, V_S`` are single group
elements whose discrete logs encode the permutation - hidden
computationally (DL), the same flavour of hiding the tracker scheme
itself relies on.  This is an original construction following the
curdleproofs architecture, not a byte-compatible port of
curdleproofs.pie; the wire format is this framework's own.

Proof size: ``2 + 6*log2(N) + 2*log2(N) + 7`` G1 points and ~8 scalars
for N padded trackers - logarithmic, vs the linear permutation-revealing
stand-in it replaces.
"""
import hashlib
from typing import List, Sequence, Tuple

from consensus_specs_tpu.ops.bls12_381.fields import P, R_ORDER, Fq
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, g1_from_compressed)
from consensus_specs_tpu.ops.kzg import _pippenger_msm

# G1 cofactor: multiplying any curve point by it lands in the r-order
# subgroup (standard BLS12-381 parameter).
_G1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB


# ---------------------------------------------------------------------------
# Scalar / point helpers
# ---------------------------------------------------------------------------

def _inv(x: int) -> int:
    return pow(x % R_ORDER, -1, R_ORDER)


def msm(points: Sequence[G1Point], scalars: Sequence[int]) -> G1Point:
    """Multi-scalar multiplication (host Pippenger for width, naive for
    tiny inputs)."""
    assert len(points) == len(scalars)
    scalars = [s % R_ORDER for s in scalars]
    if len(points) >= 8:
        return _pippenger_msm(points, scalars)
    acc = G1Point.inf()
    for pt, s in zip(points, scalars):
        if s and not pt.infinity:
            acc = acc + pt.mult(s)
    return acc


def _point_bytes(pt: G1Point) -> bytes:
    return pt.to_compressed()


def _read_point(data: bytes, off: int) -> Tuple[G1Point, int]:
    pt = g1_from_compressed(data[off:off + 48])
    assert pt.in_subgroup()
    return pt, off + 48


def _read_scalar(data: bytes, off: int) -> Tuple[int, int]:
    s = int.from_bytes(data[off:off + 32], "big")
    assert s < R_ORDER
    return s, off + 32


# ---------------------------------------------------------------------------
# Fiat-Shamir transcript
# ---------------------------------------------------------------------------

class Transcript:
    """Domain-separated SHA-256 sponge; prover and verifier must absorb
    the identical sequence."""

    def __init__(self, domain: bytes):
        self._state = hashlib.sha256(b"curdleproofs-v1/" + domain).digest()

    def absorb(self, label: bytes, *data: bytes) -> None:
        h = hashlib.sha256()
        h.update(self._state)
        h.update(label)
        for d in data:
            h.update(len(d).to_bytes(4, "big"))
            h.update(d)
        self._state = h.digest()

    def absorb_points(self, label: bytes, pts: Sequence[G1Point]) -> None:
        self.absorb(label, *[_point_bytes(p) for p in pts])

    def challenge(self, label: bytes) -> int:
        """Nonzero scalar challenge."""
        counter = 0
        while True:
            h = hashlib.sha256(
                self._state + b"/chal/" + label
                + counter.to_bytes(4, "big")).digest()
            c = int.from_bytes(h, "big") % R_ORDER
            self._state = hashlib.sha256(self._state + h).digest()
            if c != 0:
                return c
            counter += 1


# ---------------------------------------------------------------------------
# CRS: nothing-up-my-sleeve generators (hash-and-increment + cofactor)
# ---------------------------------------------------------------------------

def _hash_to_g1_nums(seed: bytes) -> G1Point:
    """Deterministic subgroup generator with unknown discrete logs:
    hash-and-increment to an x coordinate, then clear the cofactor."""
    counter = 0
    while True:
        x = int.from_bytes(
            hashlib.sha256(b"curdleproofs-crs/" + seed
                           + counter.to_bytes(4, "big")).digest(),
            "big") % P
        rhs = (pow(x, 3, P) + 4) % P
        y = pow(rhs, (P + 1) // 4, P)
        if (y * y) % P == rhs:
            pt = G1Point(Fq(x), Fq(min(y, P - y))).mult(_G1_COFACTOR)
            if not pt.infinity:
                return pt
        counter += 1


class CRS:
    """Generator vectors for up to ``size`` (power of two) trackers."""
    _cache = {}

    def __init__(self, size: int):
        assert size & (size - 1) == 0, "CRS size must be a power of two"
        self.size = size
        self.G_vec = [_hash_to_g1_nums(b"G/%d" % i) for i in range(size)]
        self.H_vec = [_hash_to_g1_nums(b"H/%d" % i) for i in range(size)]
        # padding-pin bases: lanes >= n are forced to zero in the
        # committed vector via a fourth same-msm family whose target the
        # VERIFIER fixes at infinity — a nonzero padding coefficient
        # would exhibit a discrete-log relation among these CRS points
        self.Z_vec = [_hash_to_g1_nums(b"Z/%d" % i) for i in range(size)]
        self.Q = _hash_to_g1_nums(b"Q")
        self.H_blind = _hash_to_g1_nums(b"Hblind")

    @classmethod
    def get(cls, size: int) -> "CRS":
        n = 1
        while n < size:
            n *= 2
        if n not in cls._cache:
            cls._cache[n] = cls(n)
        return cls._cache[n]


# ---------------------------------------------------------------------------
# Same-multiscalar argument (masked opening + 3-base simultaneous folding)
# ---------------------------------------------------------------------------

def _pad_pin_bases(crs: CRS, n: int) -> List[G1Point]:
    """Fourth base family: infinity on the live lanes, CRS points on the
    padding lanes.  <b, Z_eff> must be the identity, which (absent a
    discrete-log break) forces b_j = 0 for every padding lane j >= n —
    without it a prover could park an a-power in a lane where R/S are
    infinity and silently delete a tracker from the shuffle."""
    return [G1Point.inf()] * n + crs.Z_vec[n:]


def _prove_same_msm(t: Transcript, crs: CRS, R_pts, S_pts, Z_pts,
                    b, r_B, rng):
    """Prove V_R = <b, R>, V_S = <b, S>, and <b, Z> = O for the b
    committed in B (which the transcript has already absorbed)."""
    N = len(b)
    m = [rng() for _ in range(N)]
    r_m = rng()
    M_G = msm(crs.G_vec[:N], m) + crs.H_blind.mult(r_m)
    M_R = msm(R_pts, m)
    M_S = msm(S_pts, m)
    M_Z = msm(Z_pts, m)
    t.absorb_points(b"smsm/M", [M_G, M_R, M_S, M_Z])
    gamma = t.challenge(b"smsm/gamma")
    z = [(mi + gamma * bi) % R_ORDER for mi, bi in zip(m, b)]
    r_z = (r_m + gamma * r_B) % R_ORDER

    # fold z against (G, R, S, Z) simultaneously
    G = list(crs.G_vec[:N])
    Rp, Sp, Zp = list(R_pts), list(S_pts), list(Z_pts)
    rounds = []
    while len(z) > 1:
        h = len(z) // 2
        zl, zh = z[:h], z[h:]
        pairs = []
        for base in (G, Rp, Sp, Zp):
            L = msm(base[h:], zl)
            R_ = msm(base[:h], zh)
            pairs.append((L, R_))
        t.absorb_points(b"smsm/LR", [p for lr in pairs for p in lr])
        u = t.challenge(b"smsm/u")
        ui = _inv(u)
        z = [(a + u * c) % R_ORDER for a, c in zip(zl, zh)]
        G = [lo + hi.mult(ui) for lo, hi in zip(G[:h], G[h:])]
        Rp = [lo + hi.mult(ui) for lo, hi in zip(Rp[:h], Rp[h:])]
        Sp = [lo + hi.mult(ui) for lo, hi in zip(Sp[:h], Sp[h:])]
        Zp = [lo + hi.mult(ui) for lo, hi in zip(Zp[:h], Zp[h:])]
        rounds.append(pairs)
    return (M_G, M_R, M_S, M_Z, r_z, rounds, z[0])


def _verify_same_msm(t: Transcript, crs: CRS, R_pts, S_pts, Z_pts,
                     B, V_R, V_S, proof) -> bool:
    (M_G, M_R, M_S, M_Z, r_z, rounds, z0) = proof
    N = len(R_pts)
    t.absorb_points(b"smsm/M", [M_G, M_R, M_S, M_Z])
    gamma = t.challenge(b"smsm/gamma")
    targets = [M_G + B.mult(gamma) - crs.H_blind.mult(r_z),
               M_R + V_R.mult(gamma),
               M_S + V_S.mult(gamma),
               M_Z]  # <b, Z> is REQUIRED to be the identity
    bases = [list(crs.G_vec[:N]), list(R_pts), list(S_pts), list(Z_pts)]
    size = N
    for pairs in rounds:
        if size <= 1:
            return False
        h = size // 2
        t.absorb_points(b"smsm/LR", [p for lr in pairs for p in lr])
        u = t.challenge(b"smsm/u")
        ui = _inv(u)
        for idx in range(4):
            L, R_ = pairs[idx]
            targets[idx] = L.mult(ui) + targets[idx] + R_.mult(u)
            base = bases[idx]
            bases[idx] = [lo + hi.mult(ui)
                          for lo, hi in zip(base[:h], base[h:])]
        size = h
    if size != 1:
        return False
    return all(bases[i][0].mult(z0) == targets[i] for i in range(4))


# ---------------------------------------------------------------------------
# Grand-product argument via two-vector inner-product folding
# ---------------------------------------------------------------------------

def _gp_weight_vectors(N: int, x: int, y: int):
    """Public left-vector adjustment and its commitment coefficients.

    The weighted grand-product identity (partial products e, factors c):
        sum_j x^j c_j e_j = sum_{j<N} x^j e_{j+1} + x^N * prod
    plus the ``e_1 = 1`` pin (challenge y) folds into one inner product
        < c o x_pow - shift + y*e1 , e > = x^N * prod + y
    where ``shift_j = x^(j-1) [j>=2]``.  Under the rescaled generators
    ``G'_j = x^(-j) G_j`` the commitment to ``c o x_pow`` is the
    original C, and the public adjustment has coefficients
    ``(-shift_j + y[j==1]) * x^(-j)`` against the original G."""
    xi = _inv(x)
    adj = []
    xij = 1  # x^(-j) running
    for j in range(1, N + 1):
        xij = (xij * xi) % R_ORDER
        coeff = (y if j == 1 else (-pow(x, j - 1, R_ORDER))) % R_ORDER
        adj.append((coeff * xij) % R_ORDER)
    return adj


def _prove_grand_product(t: Transcript, crs: CRS, c, r_C, prod, rng):
    """Prove the vector c committed (blinder r_C) under G has
    ``prod(c) == prod``; transcript already absorbed C's preimage."""
    N = len(c)
    e = [1] * N
    for j in range(1, N):
        e[j] = (e[j - 1] * c[j - 1]) % R_ORDER
    assert (e[-1] * c[-1]) % R_ORDER == prod % R_ORDER
    r_D = rng()
    D = msm(crs.H_vec[:N], e) + crs.H_blind.mult(r_D)
    t.absorb_points(b"gp/D", [D])
    x = t.challenge(b"gp/x")
    y = t.challenge(b"gp/y")

    # left vector w under rescaled G', right vector e under H
    w = []
    for j in range(1, N + 1):
        wj = (c[j - 1] * pow(x, j, R_ORDER)) % R_ORDER
        if j >= 2:
            wj = (wj - pow(x, j - 1, R_ORDER)) % R_ORDER
        if j == 1:
            wj = (wj + y) % R_ORDER
        w.append(wj)
    v = (pow(x, N, R_ORDER) * prod + y) % R_ORDER
    assert sum(wi * ei for wi, ei in zip(w, e)) % R_ORDER == v

    xi = _inv(x)
    Gp = []
    sc = 1
    for j in range(1, N + 1):
        sc = (sc * xi) % R_ORDER
        Gp.append(crs.G_vec[j - 1].mult(sc))

    # ZK masking
    m_w = [rng() for _ in range(N)]
    m_e = [rng() for _ in range(N)]
    r_mask = rng()
    M = msm(Gp, m_w) + msm(crs.H_vec[:N], m_e) + crs.H_blind.mult(r_mask)
    t0 = sum(a * b for a, b in zip(m_w, m_e)) % R_ORDER
    t1 = (sum(a * b for a, b in zip(m_w, e))
          + sum(a * b for a, b in zip(w, m_e))) % R_ORDER
    t.absorb_points(b"gp/M", [M])
    t.absorb(b"gp/t", t0.to_bytes(32, "big"), t1.to_bytes(32, "big"))
    rho = t.challenge(b"gp/rho")
    ws = [(a + rho * b) % R_ORDER for a, b in zip(m_w, w)]
    es = [(a + rho * b) % R_ORDER for a, b in zip(m_e, e)]
    r_star = (r_mask + rho * (r_C + r_D)) % R_ORDER

    # plain two-vector IPA folding on the masked vectors
    H = list(crs.H_vec[:N])
    rounds = []
    while len(ws) > 1:
        h = len(ws) // 2
        wl, wh = ws[:h], ws[h:]
        el, eh = es[:h], es[h:]
        cl = sum(a * b for a, b in zip(wl, eh)) % R_ORDER
        cr = sum(a * b for a, b in zip(wh, el)) % R_ORDER
        L = msm(Gp[h:], wl) + msm(H[:h], eh) + crs.Q.mult(cl)
        R_ = msm(Gp[:h], wh) + msm(H[h:], el) + crs.Q.mult(cr)
        t.absorb_points(b"gp/LR", [L, R_])
        u = t.challenge(b"gp/u")
        ui = _inv(u)
        ws = [(a + u * b) % R_ORDER for a, b in zip(wl, wh)]
        es = [(a + ui * b) % R_ORDER for a, b in zip(el, eh)]
        Gp = [lo + hi.mult(ui) for lo, hi in zip(Gp[:h], Gp[h:])]
        H = [lo + hi.mult(u) for lo, hi in zip(H[:h], H[h:])]
        rounds.append((L, R_))
    return (D, M, t0, t1, r_star, rounds, ws[0], es[0])


def _verify_grand_product(t: Transcript, crs: CRS, C, prod, N,
                          proof) -> bool:
    (D, M, t0, t1, r_star, rounds, w0, e0) = proof
    t.absorb_points(b"gp/D", [D])
    x = t.challenge(b"gp/x")
    y = t.challenge(b"gp/y")
    v = (pow(x, N, R_ORDER) * prod + y) % R_ORDER

    xi = _inv(x)
    Gp = []
    sc = 1
    for j in range(1, N + 1):
        sc = (sc * xi) % R_ORDER
        Gp.append(crs.G_vec[j - 1].mult(sc))
    adj = _gp_weight_vectors(N, x, y)
    C_w = C + msm(crs.G_vec[:N], adj)

    t.absorb_points(b"gp/M", [M])
    t.absorb(b"gp/t", t0.to_bytes(32, "big"), t1.to_bytes(32, "big"))
    rho = t.challenge(b"gp/rho")
    v_star = (t0 + rho * t1 + rho * rho % R_ORDER * v) % R_ORDER
    target = M + (C_w + D).mult(rho) - crs.H_blind.mult(r_star) \
        + crs.Q.mult(v_star)

    H = list(crs.H_vec[:N])
    size = N
    for (L, R_) in rounds:
        if size <= 1:
            return False
        h = size // 2
        t.absorb_points(b"gp/LR", [L, R_])
        u = t.challenge(b"gp/u")
        ui = _inv(u)
        target = L.mult(ui) + target + R_.mult(u)
        Gp = [lo + hi.mult(ui) for lo, hi in zip(Gp[:h], Gp[h:])]
        H = [lo + hi.mult(u) for lo, hi in zip(H[:h], H[h:])]
        size = h
    if size != 1:
        return False
    expect = Gp[0].mult(w0) + H[0].mult(e0) \
        + crs.Q.mult((w0 * e0) % R_ORDER)
    return expect == target


# ---------------------------------------------------------------------------
# Top-level shuffle proof
# ---------------------------------------------------------------------------

def _instance_transcript(R_pts, S_pts, T_pts, U_pts) -> Transcript:
    t = Transcript(b"whisk-shuffle")
    t.absorb(b"n", len(R_pts).to_bytes(8, "big"))
    for label, pts in ((b"R", R_pts), (b"S", S_pts),
                       (b"T", T_pts), (b"U", U_pts)):
        t.absorb_points(label, pts)
    return t


def _pad(points: List[G1Point], N: int) -> List[G1Point]:
    return points + [G1Point.inf()] * (N - len(points))


def prove_shuffle(R_pts, S_pts, T_pts, U_pts, sigma, k, rng=None):
    """Produce the shuffle proof.  ``T[i] = k * R[sigma[i]]``,
    ``U[i] = k * S[sigma[i]]`` must hold.  Inputs may be G1Point values
    or 48-byte compressed encodings."""
    import secrets
    rng = rng or (lambda: secrets.randbelow(R_ORDER - 1) + 1)
    R_pts = [_to_subgroup_point(p) for p in R_pts]
    S_pts = [_to_subgroup_point(p) for p in S_pts]
    T_pts = [_to_subgroup_point(p) for p in T_pts]
    U_pts = [_to_subgroup_point(p) for p in U_pts]
    n = len(R_pts)
    assert len(S_pts) == len(T_pts) == len(U_pts) == n
    assert sorted(sigma) == list(range(n)), "sigma must be a permutation"
    k = int(k) % R_ORDER
    assert k != 0
    crs = CRS.get(max(n, 2))
    N = crs.size
    t = _instance_transcript(R_pts, S_pts, T_pts, U_pts)

    a = t.challenge(b"a")
    a_pow = [pow(a, i + 1, R_ORDER) for i in range(n)]
    b = [0] * N
    for i in range(n):
        b[sigma[i]] = a_pow[i]

    r_B = rng()
    B = msm(crs.G_vec, b) + crs.H_blind.mult(r_B)
    t.absorb_points(b"B", [B])
    beta = t.challenge(b"beta")

    Rp, Sp = _pad(list(R_pts), N), _pad(list(S_pts), N)
    V_R = msm(Rp, b)
    V_S = msm(Sp, b)
    t.absorb_points(b"V", [V_R, V_S])

    # grand product: {b_j + beta} is {a^i + beta} plus (N-n) zeros+beta
    c = [(bj + beta) % R_ORDER for bj in b]
    prod = 1
    for ai in a_pow:
        prod = prod * (ai + beta) % R_ORDER
    prod = prod * pow(beta, N - n, R_ORDER) % R_ORDER
    gp = _prove_grand_product(t, crs, c, r_B, prod, rng)

    smsm = _prove_same_msm(t, crs, Rp, Sp, _pad_pin_bases(crs, n),
                           b, r_B, rng)

    # DLEQ: A_T = k*V_R, A_U = k*V_S with one k
    w = rng()
    W_R = V_R.mult(w)
    W_S = V_S.mult(w)
    t.absorb_points(b"dleq/W", [W_R, W_S])
    ch = t.challenge(b"dleq/c")
    s_k = (w + ch * k) % R_ORDER
    return _serialize(n, B, V_R, V_S, gp, smsm, (W_R, W_S, s_k))


def verify_shuffle(R_pts, S_pts, T_pts, U_pts, proof: bytes) -> bool:
    """Inputs may be G1Point values or 48-byte compressed encodings."""
    try:
        R_pts = [_to_subgroup_point(p) for p in R_pts]
        S_pts = [_to_subgroup_point(p) for p in S_pts]
        T_pts = [_to_subgroup_point(p) for p in T_pts]
        U_pts = [_to_subgroup_point(p) for p in U_pts]
        n = len(R_pts)
        if not (len(S_pts) == len(T_pts) == len(U_pts) == n and n >= 1):
            return False
        crs = CRS.get(max(n, 2))
        N = crs.size
        parsed = _deserialize(proof, n, N)
        if parsed is None:
            return False
        (B, V_R, V_S, gp, smsm, dleq) = parsed
        t = _instance_transcript(R_pts, S_pts, T_pts, U_pts)
        a = t.challenge(b"a")
        a_pow = [pow(a, i + 1, R_ORDER) for i in range(n)]
        t.absorb_points(b"B", [B])
        beta = t.challenge(b"beta")
        t.absorb_points(b"V", [V_R, V_S])

        prod = 1
        for ai in a_pow:
            prod = prod * (ai + beta) % R_ORDER
        prod = prod * pow(beta, N - n, R_ORDER) % R_ORDER
        # C commits c = b + beta*1 under G with the SAME blinder as B
        C = B + msm(crs.G_vec, [beta] * N)
        if not _verify_grand_product(t, crs, C, prod, N, gp):
            return False

        Rp, Sp = _pad(R_pts, N), _pad(S_pts, N)
        if not _verify_same_msm(t, crs, Rp, Sp, _pad_pin_bases(crs, n),
                                B, V_R, V_S, smsm):
            return False

        (W_R, W_S, s_k) = dleq
        A_T = msm(T_pts, a_pow)
        A_U = msm(U_pts, a_pow)
        if V_R.infinity or V_S.infinity:
            return False
        t.absorb_points(b"dleq/W", [W_R, W_S])
        ch = t.challenge(b"dleq/c")
        return (V_R.mult(s_k) == W_R + A_T.mult(ch)
                and V_S.mult(s_k) == W_S + A_U.mult(ch))
    except Exception:
        return False


def _to_subgroup_point(p) -> G1Point:
    if isinstance(p, G1Point):
        return p
    pt = g1_from_compressed(bytes(p))
    assert pt.in_subgroup()
    return pt


# ---------------------------------------------------------------------------
# Serialization (framework wire format; length fixed by n)
# ---------------------------------------------------------------------------

def _serialize(n, B, V_R, V_S, gp, smsm, dleq) -> bytes:
    (D, M, t0, t1, r_star, gp_rounds, w0, e0) = gp
    (M_G, M_R, M_S, M_Z, r_z, sm_rounds, z0) = smsm
    (W_R, W_S, s_k) = dleq
    out = bytearray()
    for pt in (B, V_R, V_S, D, M):
        out += _point_bytes(pt)
    for s in (t0, t1, r_star):
        out += s.to_bytes(32, "big")
    for (L, R_) in gp_rounds:
        out += _point_bytes(L) + _point_bytes(R_)
    out += w0.to_bytes(32, "big") + e0.to_bytes(32, "big")
    for pt in (M_G, M_R, M_S, M_Z):
        out += _point_bytes(pt)
    out += r_z.to_bytes(32, "big")
    for pairs in sm_rounds:
        for (L, R_) in pairs:
            out += _point_bytes(L) + _point_bytes(R_)
    out += z0.to_bytes(32, "big")
    out += _point_bytes(W_R) + _point_bytes(W_S)
    out += s_k.to_bytes(32, "big")
    return bytes(out)


def _deserialize(proof: bytes, n: int, N: int):
    try:
        logN = N.bit_length() - 1
        expect = 48 * 5 + 32 * 3 + logN * 96 + 64 \
            + 48 * 4 + 32 + logN * 8 * 48 + 32 + 96 + 32
        if len(proof) != expect:
            return None
        off = 0
        pts = []
        for _ in range(5):
            pt, off = _read_point(proof, off)
            pts.append(pt)
        B, V_R, V_S, D, M = pts
        t0, off = _read_scalar(proof, off)
        t1, off = _read_scalar(proof, off)
        r_star, off = _read_scalar(proof, off)
        gp_rounds = []
        for _ in range(logN):
            L, off = _read_point(proof, off)
            R_, off = _read_point(proof, off)
            gp_rounds.append((L, R_))
        w0, off = _read_scalar(proof, off)
        e0, off = _read_scalar(proof, off)
        M_G, off = _read_point(proof, off)
        M_R, off = _read_point(proof, off)
        M_S, off = _read_point(proof, off)
        M_Z, off = _read_point(proof, off)
        r_z, off = _read_scalar(proof, off)
        sm_rounds = []
        for _ in range(logN):
            pairs = []
            for _b in range(4):
                L, off = _read_point(proof, off)
                R_, off = _read_point(proof, off)
                pairs.append((L, R_))
            sm_rounds.append(pairs)
        z0, off = _read_scalar(proof, off)
        W_R, off = _read_point(proof, off)
        W_S, off = _read_point(proof, off)
        s_k, off = _read_scalar(proof, off)
        gp = (D, M, t0, t1, r_star, gp_rounds, w0, e0)
        smsm = (M_G, M_R, M_S, M_Z, r_z, sm_rounds, z0)
        return (B, V_R, V_S, gp, smsm, (W_R, W_S, s_k))
    except Exception:
        return None
