"""Fq (BLS12-381 base field) arithmetic as JAX uint32 limb kernels.

TPUs have no native 64-bit integer multiply, so a 381-bit field element is
held as 24 x 16-bit limbs in ``uint32`` lanes (little-endian limb order,
shape ``(..., 24)``).  A limb product is exact in uint32
(``(2^16-1)^2 < 2^32``); products are split into lo/hi halves so column
accumulations stay below ``48 * 2^16 < 2^22`` and never overflow.

Multiplication = one batched outer product, antidiagonal column sums via a
single static gather, and a 48-step ``lax.scan`` carry chain - about 25 HLO
ops per Montgomery multiply, so the big consumers (Miller loop, final
exponentiation, SSWU) compile to compact XLA programs.  Everything carries
arbitrary leading batch dims; the batch axis is the TPU vector axis.

All elements are kept in Montgomery form (R = 2^384) between byte
boundaries.  This module replaces the role of the reference's Rust field
arithmetic inside milagro/arkworks (reference
``tests/core/pyspec/eth2spec/utils/bls.py:22-30``).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from consensus_specs_tpu.ops.bls12_381.fields import P

NLIMB = 24
LIMB_BITS = 16
MASK = jnp.uint32(0xFFFF)
R_MONT = (1 << (NLIMB * LIMB_BITS)) % P          # 2^384 mod p
R2_MONT = (R_MONT * R_MONT) % P                  # for to_mont
# -p^{-1} mod 2^384, for the separate Montgomery reduction m = T_lo * NPRIME.
NPRIME = (-pow(P, -1, 1 << (NLIMB * LIMB_BITS))) % (1 << (NLIMB * LIMB_BITS))


def int_to_limbs(n: int) -> np.ndarray:
    """Host-side: python int -> (24,) uint32 limb array (little-endian)."""
    return np.array([(n >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMB)],
                    dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    """Host-side: (..., 24) limb array -> python int (single element only)."""
    arr = np.asarray(limbs).reshape(-1)
    assert arr.shape == (NLIMB,)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(NLIMB))


P_LIMBS = int_to_limbs(P)
NPRIME_LIMBS = int_to_limbs(NPRIME)
ZERO = np.zeros(NLIMB, dtype=np.uint32)
# Montgomery representations of small constants.
ONE_M = int_to_limbs(R_MONT)                     # mont(1)
R2_LIMBS = int_to_limbs(R2_MONT)


def _carry_chain(cols, n_out):
    """Propagate 16-bit carries over ``cols`` (..., n) -> (..., n_out) limbs.

    Column values must be < 2^32 - carry headroom (they are < 2^22 here).
    Runs as a ``lax.scan`` so the HLO stays one small While loop regardless
    of width; the final carry is dropped (callers guarantee no overflow).
    """
    xs = jnp.moveaxis(cols[..., :n_out], -1, 0)
    carry0 = jnp.zeros(cols.shape[:-1], jnp.uint32)

    def step(carry, x):
        t = x + carry
        return t >> LIMB_BITS, t & MASK

    _, out = jax.lax.scan(step, carry0, xs)
    return jnp.moveaxis(out, 0, -1)


# Static gather indices for antidiagonal (polynomial-product column) sums:
# col[k] = sum_i lo[i, k-i] + sum_i hi[i, k-1-i].  Out-of-range entries are
# routed to a zero pad column.
_NCOL = 2 * NLIMB
_I = np.arange(NLIMB)[:, None]
_K = np.arange(_NCOL)[None, :]
_LO_IDX = np.where((_K - _I >= 0) & (_K - _I < NLIMB), _K - _I, NLIMB)
_HI_IDX = np.where((_K - 1 - _I >= 0) & (_K - 1 - _I < NLIMB), _K - 1 - _I, NLIMB)


def _product_columns(a, b):
    """(...,24) x (...,24) -> (...,48) antidiagonal column sums (< 2^22)."""
    prods = a[..., :, None] * b[..., None, :]            # exact in uint32
    lo = prods & MASK
    hi = prods >> LIMB_BITS
    # one zero pad column at index NLIMB for out-of-range gathers
    pad = jnp.zeros(prods.shape[:-1] + (1,), jnp.uint32)
    lo = jnp.concatenate([lo, pad], axis=-1)
    hi = jnp.concatenate([hi, pad], axis=-1)
    lo_idx = jnp.broadcast_to(jnp.asarray(_LO_IDX), lo.shape[:-2] + _LO_IDX.shape)
    hi_idx = jnp.broadcast_to(jnp.asarray(_HI_IDX), hi.shape[:-2] + _HI_IDX.shape)
    cols = (jnp.take_along_axis(lo, lo_idx, axis=-1)
            + jnp.take_along_axis(hi, hi_idx, axis=-1))
    return cols.sum(axis=-2)


def _full_mul(a, b):
    """Exact 768-bit product as 48 carried 16-bit limbs."""
    return _carry_chain(_product_columns(a, b), _NCOL)


def _low_mul(a, b):
    """(a*b) mod 2^384 as 24 carried limbs."""
    return _carry_chain(_product_columns(a, b), NLIMB)


def _add_raw(a, b, n):
    """Limbwise add + carry chain over n limbs (no modular reduction)."""
    return _carry_chain(a + b, n)


def _sub_limbs(a, b):
    """a - b over 24 limbs: returns (diff mod 2^384, borrow flag)."""
    xs_a = jnp.moveaxis(a, -1, 0)
    xs_b = jnp.moveaxis(b, -1, 0)
    borrow0 = jnp.zeros(a.shape[:-1], jnp.uint32)

    def step(borrow, ab):
        ai, bi = ab
        t = ai + (MASK + jnp.uint32(1)) - bi - borrow    # in [1, 2^17)
        return jnp.uint32(1) - (t >> LIMB_BITS), t & MASK

    borrow, out = jax.lax.scan(step, borrow0, (xs_a, xs_b))
    return jnp.moveaxis(out, 0, -1), borrow


def _cond_sub_p(x):
    """x in [0, 2p) -> x mod p, branchless."""
    p = jnp.asarray(P_LIMBS)
    d, borrow = _sub_limbs(x, jnp.broadcast_to(p, x.shape))
    return jnp.where((borrow != 0)[..., None], x, d)


def add_mod(a, b):
    """(a + b) mod p; inputs reduced."""
    return _cond_sub_p(_add_raw(a, b, NLIMB))


def sub_mod(a, b):
    """(a - b) mod p; inputs reduced."""
    d, borrow = _sub_limbs(a, b)
    d2 = _carry_chain(d + jnp.asarray(P_LIMBS), NLIMB)
    return jnp.where((borrow != 0)[..., None], d2, d)


def neg_mod(a):
    """(-a) mod p. neg(0) must stay 0, so route through sub_mod."""
    return sub_mod(jnp.zeros_like(a), a)


def mont_mul(a, b):
    """Montgomery product: a * b * R^{-1} mod p (inputs/outputs reduced)."""
    t = _full_mul(a, b)
    m = _low_mul(t[..., :NLIMB], jnp.asarray(NPRIME_LIMBS))
    u = _full_mul(m, jnp.asarray(P_LIMBS))
    # t + u: lower 24 limbs sum to == 0 mod 2^384 by construction; we only
    # need the high half plus the carry out of the low half.  Column values
    # < 2^17 so one carry chain over all 48 limbs is exact.
    s = _carry_chain(t + u, _NCOL)
    # carry out of limb 23 into limb 24 is already handled by the chain;
    # (t + m*p) < p^2 + 2^384*p < 2^768 so no final carry is lost.
    return _cond_sub_p(s[..., NLIMB:])


def mont_sqr(a):
    return mont_mul(a, a)


def to_mont(a):
    return mont_mul(a, jnp.asarray(R2_LIMBS))


def from_mont(a):
    one = jnp.zeros(NLIMB, jnp.uint32).at[0].set(1)
    return mont_mul(a, jnp.broadcast_to(one, a.shape))


def _exp_bits(e: int, width: int = None) -> np.ndarray:
    """Host-side: exponent -> MSB-first bit array for scan-based powering."""
    if width is None:
        width = max(1, e.bit_length())
    return np.array([(e >> (width - 1 - i)) & 1 for i in range(width)],
                    dtype=np.uint32)


def pow_fixed(a, bits: np.ndarray):
    """a^e for a fixed public exponent given as MSB-first bits (Montgomery).

    381-bit exponents (inverse, sqrt) run as a 381-step scan: one square
    plus one conditional multiply per step.
    """
    one = jnp.broadcast_to(jnp.asarray(ONE_M), a.shape)

    def step(acc, bit):
        acc = mont_sqr(acc)
        acc = jnp.where(bit != 0, mont_mul(acc, a), acc)
        return acc, None

    out, _ = jax.lax.scan(step, one, jnp.asarray(bits))
    return out


_INV_BITS = _exp_bits(P - 2)
_SQRT_BITS = _exp_bits((P + 1) // 4)
_LEGENDRE_BITS = _exp_bits((P - 1) // 2)


def inv_mod(a):
    """a^{-1} via Fermat (a must be nonzero; inv(0) returns 0)."""
    return pow_fixed(a, _INV_BITS)


def sqrt_candidate(a):
    """a^((p+1)/4): the square root when a is a QR (p = 3 mod 4)."""
    return pow_fixed(a, _SQRT_BITS)


def legendre_is_qr(a):
    """True where a is zero or a quadratic residue (Euler criterion)."""
    l = pow_fixed(a, _LEGENDRE_BITS)
    return eq(l, jnp.broadcast_to(jnp.asarray(ONE_M), a.shape)) | is_zero(a)


def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """Branchless limb select: cond (...) broadcast over the limb axis."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# Batched op helpers: stack k independent ops into ONE kernel call so the
# XLA program has a constant number of scan instances regardless of how many
# field ops a tower multiply needs.  This is both the compile-time fix
# (1-core box, see memory) and the TPU-right shape: one wide vector op
# instead of k narrow ones.
# ---------------------------------------------------------------------------

def _stack(items):
    shapes = [x.shape for x in items]
    common = jnp.broadcast_shapes(*shapes)
    return jnp.stack([jnp.broadcast_to(x, common) for x in items])


def mont_mul_many(pairs):
    """[(a, b), ...] -> [a*b*R^-1 mod p, ...] in one batched multiply."""
    if len(pairs) == 1:
        return [mont_mul(pairs[0][0], pairs[0][1])]
    out = mont_mul(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


def add_mod_many(pairs):
    if len(pairs) == 1:
        return [add_mod(pairs[0][0], pairs[0][1])]
    out = add_mod(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


def sub_mod_many(pairs):
    if len(pairs) == 1:
        return [sub_mod(pairs[0][0], pairs[0][1])]
    out = sub_mod(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


# ---------------------------------------------------------------------------
# Host-side packing helpers
# ---------------------------------------------------------------------------

def fq_const(n: int) -> np.ndarray:
    """Host-side: python int mod p -> Montgomery limb constant."""
    return int_to_limbs((n % P) * R_MONT % P)


def pack_ints_mont(values) -> jnp.ndarray:
    """Host-side: iterable of ints -> (N, 24) Montgomery limb batch."""
    return jnp.asarray(np.stack([fq_const(v) for v in values]))


def unpack_mont(limbs) -> list:
    """Host-side: (..., 24) Montgomery limbs -> list of python ints."""
    arr = np.asarray(from_mont(limbs)).reshape(-1, NLIMB)
    return [sum(int(row[i]) << (LIMB_BITS * i) for i in range(NLIMB))
            for row in arr]
