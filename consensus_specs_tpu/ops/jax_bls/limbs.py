"""Fq (BLS12-381 base field) arithmetic as JAX uint32 limb kernels.

TPUs have no native 64-bit integer multiply, so a 381-bit field element is
held as 24 x 16-bit limbs in ``uint32`` lanes (little-endian limb order,
shape ``(..., 24)``).  A limb product is exact in uint32
(``(2^16-1)^2 < 2^32``); products split into 16-bit lo/hi halves, each
exact in f32, and the 48-term antidiagonal column sums stay below
``48 * (2^16-1) < 2^22`` - exact in f32 accumulation (< 2^24) and far
from uint32 overflow in the carry chain.

Multiplication = one batched uint32 outer product, column sums as ONE f32
matmul against a constant 0/1 scatter matrix (``_product_columns`` - the
MXU on TPU, a library sgemm on CPU), then a Kogge-Stone carry-lookahead
network over the carried limbs.  Everything carries arbitrary leading
batch dims; the batch axis is the TPU vector axis.

All elements are kept in Montgomery form (R = 2^384) between byte
boundaries.  This module replaces the role of the reference's Rust field
arithmetic inside milagro/arkworks (reference
``tests/core/pyspec/eth2spec/utils/bls.py:22-30``).
"""

import numpy as np
from .backend import xp as jnp, lax, kjit, dot_f32, at_set

from consensus_specs_tpu.ops.bls12_381.fields import P

NLIMB = 24
LIMB_BITS = 16
MASK = jnp.uint32(0xFFFF)
R_MONT = (1 << (NLIMB * LIMB_BITS)) % P          # 2^384 mod p
R2_MONT = (R_MONT * R_MONT) % P                  # for to_mont
# -p^{-1} mod 2^384, for the separate Montgomery reduction m = T_lo * NPRIME.
NPRIME = (-pow(P, -1, 1 << (NLIMB * LIMB_BITS))) % (1 << (NLIMB * LIMB_BITS))


def int_to_limbs(n: int) -> np.ndarray:
    """Host-side: python int -> (24,) uint32 limb array (little-endian)."""
    return np.array([(n >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMB)],
                    dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    """Host-side: (..., 24) limb array -> python int (single element only)."""
    arr = np.asarray(limbs).reshape(-1)
    assert arr.shape == (NLIMB,)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(NLIMB))


P_LIMBS = int_to_limbs(P)
NPRIME_LIMBS = int_to_limbs(NPRIME)
ZERO = np.zeros(NLIMB, dtype=np.uint32)
# Montgomery representations of small constants.
ONE_M = int_to_limbs(R_MONT)                     # mont(1)
R2_LIMBS = int_to_limbs(R2_MONT)


def _shift_limbs(x, d):
    """Shift limb values up by ``d`` positions (toward higher indices),
    filling with zeros - i.e. out[..., i] = x[..., i-d]."""
    pad = jnp.zeros(x.shape[:-1] + (d,), x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def _carry_chain(cols, n_out):
    """Propagate 16-bit carries over ``cols`` (..., n) -> (..., n_out) limbs.

    Carry-lookahead, fully parallel: two split-and-add passes shrink every
    carry to {0, 1}, then a Kogge-Stone generate/propagate prefix network
    (log2 depth, unrolled - no sequential loop at all) resolves the ripple.
    Column values must be < 2^32 with carry headroom (they are < 2^22
    here); carries INTO the kept range come only from kept columns, so
    truncating first is exact, and the final carry out of limb ``n_out-1``
    is dropped (callers guarantee no overflow / want mod 2^(16*n_out)).
    """
    c = cols[..., :n_out]
    # pass 1: columns < 2^22 -> limbs < 2^16 + 2^6
    c = (c & MASK) + _shift_limbs(c >> LIMB_BITS, 1)
    # pass 2: -> values <= 2^16 (carry in {0, 1})
    c = (c & MASK) + _shift_limbs(c >> LIMB_BITS, 1)
    lo = c & MASK
    g = c >> LIMB_BITS                    # generates a carry (0/1)
    p = (lo == MASK).astype(jnp.uint32)   # propagates an incoming carry
    carry_in = _shift_limbs(_kogge_stone(g, p, n_out), 1)
    return (lo + carry_in) & MASK


def _kogge_stone(g, p, n):
    """Resolve a generate/propagate prefix over ``n`` limb positions:
    out[i] = g[i] | (p[i] & g[i-1]) | (p[i] & p[i-1] & g[i-2]) | ... -
    the carry (or borrow) out of position i.  Unrolled log2 depth."""
    d = 1
    while d < n:  # noqa: J203 (static log2-depth unroll: n is a python int)
        g = g | (p & _shift_limbs(g, d))
        p = p & _shift_limbs(p, d)
        d *= 2
    return g


_NCOL = 2 * NLIMB


def _make_scatter_matrix() -> np.ndarray:
    """(2*24*24, 48) f32 0/1 matrix routing outer-product halves to their
    columns: lo[i, j] -> col i+j, hi[i, j] -> col i+j+1 (max index
    23+23+1 = 47, so every term lands inside the 48 columns)."""
    S = np.zeros((2, NLIMB, NLIMB, _NCOL), np.float32)
    for i in range(NLIMB):
        for j in range(NLIMB):
            S[0, i, j, i + j] = 1.0
            S[1, i, j, i + j + 1] = 1.0
    return S.reshape(2 * NLIMB * NLIMB, _NCOL)


_SCATTER = _make_scatter_matrix()


def _product_columns(a, b):
    """(...,24) x (...,24) -> (...,48) antidiagonal column sums (< 2^22).

    col[k] = sum_i lo[i, k-i] + sum_i hi[i, k-1-i], realized as ONE f32
    matmul against a constant 0/1 scatter matrix: the uint32 outer
    product splits into exact 16-bit halves, each half casts exactly to
    f32, and the 48-term column sums stay < 2^22 so the f32 accumulation
    is exact too (forced to HIGHEST precision so the TPU MXU path does
    full-f32 passes, keeping integer exactness).

    Formulation note, measured on XLA:CPU (the 1-core dryrun host):
    take_along_axis gathers explode compile time; an INTEGER dot_general
    has no CPU library kernel and unrolls to ~55k LLVM instructions; a
    statically-padded stack + reduction compiles fine alone but fuses
    superlinearly into each consumer carry chain (~12 s compile PER
    MONT_MUL, the round-1..3 bench/dryrun timeout root cause).  The f32
    matmul hits Eigen's sgemm on CPU / the MXU on TPU - an opaque
    library call XLA cannot fuse into - so a mont_mul compiles in ~1 s
    and runs 8-17x faster than the stacked-pad form on wide batches.
    """
    prods = a[..., :, None] * b[..., None, :]            # exact in uint32
    lo = (prods & MASK).astype(jnp.float32)
    hi = (prods >> LIMB_BITS).astype(jnp.float32)
    stacked = jnp.concatenate([lo, hi], axis=-2)         # (..., 48, 24)
    flat = stacked.reshape(stacked.shape[:-2] + (2 * NLIMB * NLIMB,))
    cols = dot_f32(flat, jnp.asarray(_SCATTER))
    return cols.astype(jnp.uint32)


def _full_mul(a, b):
    """Exact 768-bit product as 48 carried 16-bit limbs."""
    return _carry_chain(_product_columns(a, b), _NCOL)


def _low_mul(a, b):
    """(a*b) mod 2^384 as 24 carried limbs."""
    return _carry_chain(_product_columns(a, b), NLIMB)


def _add_raw(a, b, n):
    """Limbwise add + carry chain over n limbs (no modular reduction)."""
    return _carry_chain(a + b, n)


def _sub_limbs(a, b):
    """a - b over 24 limbs: returns (diff mod 2^384, borrow flag).

    Borrow-lookahead mirror of :func:`_carry_chain`: per-limb provisional
    t = a + 2^16 - b in [1, 2^17); a limb *generates* a borrow when
    t < 2^16 and *propagates* an incoming borrow when t == 2^16 (its
    output digit is then 0 minus the borrow).  Kogge-Stone resolves the
    ripple in log2 depth with no sequential loop.
    """
    t = a + (MASK + jnp.uint32(1)) - b
    g = (jnp.uint32(1) - (t >> LIMB_BITS))          # borrows on its own
    p = (t == MASK + jnp.uint32(1)).astype(jnp.uint32)
    borrow_in = _shift_limbs(_kogge_stone(g, p, a.shape[-1]), 1)
    out = (t - borrow_in) & MASK
    # borrow out of the top limb
    top = (t[..., -1] - borrow_in[..., -1]) >> LIMB_BITS
    borrow = jnp.uint32(1) - top
    return out, borrow


def _cond_sub_p(x):
    """x in [0, 2p) -> x mod p, branchless."""
    p = jnp.asarray(P_LIMBS)
    d, borrow = _sub_limbs(x, jnp.broadcast_to(p, x.shape))
    return jnp.where((borrow != 0)[..., None], x, d)


def add_mod(a, b):
    """(a + b) mod p; inputs reduced."""
    return _cond_sub_p(_add_raw(a, b, NLIMB))


def sub_mod(a, b):
    """(a - b) mod p; inputs reduced."""
    d, borrow = _sub_limbs(a, b)
    d2 = _carry_chain(d + jnp.asarray(P_LIMBS), NLIMB)
    return jnp.where((borrow != 0)[..., None], d2, d)


def neg_mod(a):
    """(-a) mod p. neg(0) must stay 0, so route through sub_mod."""
    return sub_mod(jnp.zeros_like(a), a)


def mont_mul(a, b):
    """Montgomery product: a * b * R^{-1} mod p (inputs/outputs reduced)."""
    t = _full_mul(a, b)
    m = _low_mul(t[..., :NLIMB], jnp.asarray(NPRIME_LIMBS))
    u = _full_mul(m, jnp.asarray(P_LIMBS))
    # t + u: lower 24 limbs sum to == 0 mod 2^384 by construction; we only
    # need the high half plus the carry out of the low half.  Column values
    # < 2^17 so one carry chain over all 48 limbs is exact.
    s = _carry_chain(t + u, _NCOL)
    # carry out of limb 23 into limb 24 is already handled by the chain;
    # (t + m*p) < p^2 + 2^384*p < 2^768 so no final carry is lost.
    return _cond_sub_p(s[..., NLIMB:])


def mont_sqr(a):
    return mont_mul(a, a)


def to_mont(a):
    return mont_mul(a, jnp.asarray(R2_LIMBS))


def from_mont(a):
    one = at_set(jnp.zeros(NLIMB, jnp.uint32), 0, 1)
    return mont_mul(a, jnp.broadcast_to(one, a.shape))


def _exp_bits(e: int, width: int = None) -> np.ndarray:
    """Host-side: exponent -> MSB-first bit array for scan-based powering."""
    if width is None:
        width = max(1, e.bit_length())
    return np.array([(e >> (width - 1 - i)) & 1 for i in range(width)],
                    dtype=np.uint32)


def pow_fixed(a, bits: np.ndarray):
    """a^e for a fixed public exponent given as MSB-first bits (Montgomery).

    4-bit fixed-window ladder: a 16-entry table (15 setup multiplies) then
    one scan step per window - 4 squarings + 1 table multiply - so a
    381-bit exponent (inverse, sqrt) runs in ~96 sequential steps instead
    of 381, with ~40% fewer multiplies overall.
    """
    e = 0
    for b in np.asarray(bits).astype(int):
        e = (e << 1) | int(b)
    width = len(bits)
    nwin = (width + 3) // 4
    windows = np.array([(e >> (4 * (nwin - 1 - i))) & 0xF
                        for i in range(nwin)], dtype=np.uint32)

    one = jnp.broadcast_to(jnp.asarray(ONE_M), a.shape)
    entries = [one, a]
    for _ in range(14):
        entries.append(mont_mul(entries[-1], a))
    table = jnp.stack(entries)                  # (16, ..., 24)

    def step(acc, w):
        acc = mont_sqr(mont_sqr(mont_sqr(mont_sqr(acc))))
        return mont_mul(acc, jnp.take(table, w, axis=0)), None

    # first window seeds the accumulator directly (acc = table[w0])
    acc = jnp.take(table, jnp.asarray(windows[0]), axis=0)
    out, _ = lax.scan(step, acc, jnp.asarray(windows[1:]))
    return out


_INV_BITS = _exp_bits(P - 2)
_SQRT_BITS = _exp_bits((P + 1) // 4)
_LEGENDRE_BITS = _exp_bits((P - 1) // 2)


# ---------------------------------------------------------------------------
# Shared exponentiation ladder: ONE compiled program for every fixed-
# exponent power (inversion, sqrt, Legendre) across every staged pipeline.
#
# Why: each in-trace ``pow_fixed`` instance duplicates its 15-multiply
# table setup and 96-step scan body in the XLA module; the SSWU map alone
# holds five instances, making hash-to-curve the compile-time whale
# (185 s of the ~450 s cold staged pipeline on a 1-core XLA:CPU host -
# measured round 4).  Staging the pows out of their callers and passing
# the exponent as a TRACED window array leaves exactly one compiled
# ladder per row-bucket, shared by all of them.
# ---------------------------------------------------------------------------

N_WINDOWS = 96  # ceil(384/4): every exponent here is < 2^384


def exp_windows(e: int) -> np.ndarray:
    """Host-side: exponent -> (96,) MSB-first 4-bit windows, left-padded
    with zeros (exact for the ladder: acc stays 1 through leading zero
    windows since 1^16 * table[0] == 1)."""
    return np.array([(e >> (4 * (N_WINDOWS - 1 - i))) & 0xF
                     for i in range(N_WINDOWS)], dtype=np.uint32)


INV_WINDOWS = exp_windows(P - 2)
SQRT_WINDOWS = exp_windows((P + 1) // 4)
LEGENDRE_WINDOWS = exp_windows((P - 1) // 2)


@kjit
def _j_pow_windows(a, windows):
    """a^e for (R, 24) Montgomery rows; e given as traced 4-bit windows.

    Same math as :func:`pow_fixed` without the first-window seeding
    optimization (left-zero-padding needs the neutral start).  a == 0
    rows yield 0 (table powers >= 1 are zero), preserving the
    inv(0) == 0 convention."""
    one = jnp.broadcast_to(jnp.asarray(ONE_M), a.shape)
    entries = [one, a]
    for _ in range(14):
        entries.append(mont_mul(entries[-1], a))
    table = jnp.stack(entries)

    def step(acc, w):
        acc = mont_sqr(mont_sqr(mont_sqr(mont_sqr(acc))))
        return mont_mul(acc, jnp.take(table, w, axis=0)), None

    out, _ = lax.scan(step, one, windows)
    return out


def pow_windows_staged(a, windows: np.ndarray):
    """Dispatch the shared ladder for any leading batch shape.

    Rows are flattened and zero-padded to a power-of-two bucket (floor
    64) so only a handful of shapes ever compile regardless of call
    site."""
    from .backend import NUMPY_KERNELS
    lead = a.shape[:-1]
    flat = a.reshape((-1, NLIMB))
    rows = flat.shape[0]
    if NUMPY_KERNELS:
        bucket = rows   # eager numpy: no compile to amortize, no padding
    else:
        bucket = max(64, 1 << max(0, rows - 1).bit_length()) if rows else 64
    if bucket != rows:
        flat = jnp.concatenate(
            [flat, jnp.zeros((bucket - rows, NLIMB), jnp.uint32)], axis=0)
    out = _j_pow_windows(flat, jnp.asarray(windows))
    return out[:rows].reshape(lead + (NLIMB,))


def inv_mod(a):
    """a^{-1} via Fermat (a must be nonzero; inv(0) returns 0)."""
    return pow_fixed(a, _INV_BITS)


def sqrt_candidate(a):
    """a^((p+1)/4): the square root when a is a QR (p = 3 mod 4)."""
    return pow_fixed(a, _SQRT_BITS)


def legendre_is_qr(a):
    """True where a is zero or a quadratic residue (Euler criterion)."""
    l = pow_fixed(a, _LEGENDRE_BITS)
    return eq(l, jnp.broadcast_to(jnp.asarray(ONE_M), a.shape)) | is_zero(a)


def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """Branchless limb select: cond (...) broadcast over the limb axis."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# Batched op helpers: stack k independent ops into ONE kernel call so the
# XLA program has a constant number of scan instances regardless of how many
# field ops a tower multiply needs.  This is both the compile-time fix
# (1-core box, see memory) and the TPU-right shape: one wide vector op
# instead of k narrow ones.
# ---------------------------------------------------------------------------

def _stack(items):
    shapes = [x.shape for x in items]
    common = jnp.broadcast_shapes(*shapes)
    return jnp.stack([jnp.broadcast_to(x, common) for x in items])


def mont_mul_many(pairs):
    """[(a, b), ...] -> [a*b*R^-1 mod p, ...] in one batched multiply."""
    if len(pairs) == 1:
        return [mont_mul(pairs[0][0], pairs[0][1])]
    out = mont_mul(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


def add_mod_many(pairs):
    if len(pairs) == 1:
        return [add_mod(pairs[0][0], pairs[0][1])]
    out = add_mod(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


def sub_mod_many(pairs):
    if len(pairs) == 1:
        return [sub_mod(pairs[0][0], pairs[0][1])]
    out = sub_mod(_stack([p[0] for p in pairs]), _stack([p[1] for p in pairs]))
    return [out[i] for i in range(len(pairs))]


# ---------------------------------------------------------------------------
# Host-side packing helpers
# ---------------------------------------------------------------------------

def fq_const(n: int) -> np.ndarray:
    """Host-side: python int mod p -> Montgomery limb constant."""
    return int_to_limbs((n % P) * R_MONT % P)


def pack_ints_mont(values) -> jnp.ndarray:
    """Host-side: iterable of ints -> (N, 24) Montgomery limb batch."""
    return jnp.asarray(np.stack([fq_const(v) for v in values]))


def unpack_mont(limbs) -> list:
    """Host-side: (..., 24) Montgomery limbs -> list of python ints."""
    arr = np.asarray(from_mont(limbs)).reshape(-1, NLIMB)
    return [sum(int(row[i]) << (LIMB_BITS * i) for i in range(NLIMB))
            for row in arr]
