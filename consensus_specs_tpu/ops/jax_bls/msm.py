"""G1 multi-scalar multiplication as a batched JAX kernel.

The TPU-native answer to Pippenger (reference role: arkworks'
``G1Point`` MSM behind ``g1_lincomb``,
``specs/deneb/polynomial-commitments.md:268``).  Bucket accumulation is
scatter-heavy and serial, which is hostile to the MXU/VPU; instead this
kernel is *digit-parallel*:

1. window expansion — ``W[w][i] = [2^(8w)] P_i`` for the 32 8-bit windows,
   built by repeated doubling (or loaded from cache for the fixed trusted
   setup);
2. per-lane digit multiplication — ``Q[i,w] = d_{i,w} * W[w][i]`` via an
   8-step double-and-add, vectorized over all ``32*N`` lanes at once;
3. one log-depth tree reduction over all lanes.

Sequential depth is ~`8*2 + log2(32N)` complete-addition steps on wide
tensors versus thousands of dependent bucket operations — the shape XLA
and the TPU vector units want.
"""
import numpy as np
import jax  # tree_util only; array ops ride the backend switch
from .backend import xp as jnp, lax, kjit

from consensus_specs_tpu.ops.bls12_381.curve import G1Point
from . import points as PT

WINDOW_BITS = 8
N_WINDOWS = 32  # ceil(255 / 8)


def _double_k_times(p, k):
    for _ in range(k):  # noqa: J203 (static unroll: k is a trace-time int)
        p = PT.g1_add(p, p)
    return p


@kjit
def _expand_windows(pts):
    """(N,) packed G1 -> (N_WINDOWS, N) stacked window multiples."""
    def step(carry, _):
        nxt = _double_k_times(carry, WINDOW_BITS)
        return nxt, carry
    _, stacked = lax.scan(step, pts, None, length=N_WINDOWS)
    return stacked


@kjit
def _msm_core(window_pts, digit_bits):
    """window_pts: (M,) packed points; digit_bits: (M, 8) uint32 bits
    (MSB first) -> single packed point."""
    q = PT.g1_scalar_mul(window_pts, digit_bits)
    return PT.g1_normalize(PT.g1_tree_sum(q))


def _digits_msb_bits(scalars) -> np.ndarray:
    """(N,) ints -> (N_WINDOWS * N, 8) uint32 bit planes, window-major,
    each digit's 8 bits MSB first."""
    n = len(scalars)
    out = np.zeros((N_WINDOWS, n, WINDOW_BITS), dtype=np.uint32)
    for i, s in enumerate(scalars):
        s = int(s)
        for w in range(N_WINDOWS):
            d = (s >> (WINDOW_BITS * w)) & 0xFF
            for b in range(WINDOW_BITS):
                out[w, i, b] = (d >> (WINDOW_BITS - 1 - b)) & 1
    return out.reshape(N_WINDOWS * n, WINDOW_BITS)


def _flatten_windows(stacked):
    """(N_WINDOWS, N) pytree -> (N_WINDOWS * N,) pytree."""
    return jax.tree_util.tree_map(
        lambda a: a.reshape((-1,) + a.shape[2:]), stacked)


class _SetupCache:
    """Window expansions keyed by the identity of a fixed point list
    (the KZG trusted setup) so the 248 doublings run once per process."""

    def __init__(self):
        self._cache = {}

    def windows_for(self, key, pts_packed):
        hit = self._cache.get(key)
        if hit is None:
            hit = _flatten_windows(_expand_windows(pts_packed))
            hit = jax.tree_util.tree_map(jnp.asarray, hit)
            self._cache[key] = hit
        return hit


_setup_cache = _SetupCache()


def g1_msm(points, scalars, cache_key=None) -> G1Point:
    """MSM over oracle ``G1Point``s (host API).

    ``cache_key``: hashable id for a fixed basis (e.g. ("lagrange",
    preset)) to reuse the window expansion across calls.
    """
    assert len(points) == len(scalars)
    if not points:
        return G1Point.inf()
    packed = PT.g1_pack(list(points))
    if cache_key is not None:
        windows = _setup_cache.windows_for(cache_key, packed)
    else:
        windows = _flatten_windows(_expand_windows(packed))
    bits = jnp.asarray(_digits_msb_bits(scalars))
    out = _msm_core(windows, bits)
    return PT.g1_unpack(jax.tree_util.tree_map(lambda a: a[None], out))
