"""JAX/TPU-native BLS12-381 kernel library.

This package is the TPU replacement for the reference's Rust crypto backends
(milagro_bls_binding / py_arkworks_bls12381, reference
``tests/core/pyspec/eth2spec/utils/bls.py:30,22``): BLS12-381 field towers,
curve arithmetic, pairings, hash-to-curve and MSM, all written in
``jax.numpy`` integer ops so the whole verification pipeline jit-compiles to
one XLA program and ``vmap``s across attestations / blobs / pubkeys.

Design for TPU hardware:

- **No 64-bit multiplies.** TPUs have no native u64 multiply, so field
  elements are 24 × 16-bit limbs held in ``uint32`` lanes; limb products are
  exact in uint32 (< 2^32) and column accumulations stay < 2^22, so carries
  can be propagated lazily with static unrolled loops the XLA vectorizer
  fuses into wide VPU ops.
- **Montgomery form everywhere.** One REDC per multiply; conversions only at
  byte boundaries.
- **Branchless.** Point ops use complete projective formulas, square roots
  and inverses are fixed-exponent powers via ``lax.scan``, selections are
  ``jnp.where`` — nothing data-dependent blocks vectorization.
- **Batch-first.** Every function takes arbitrary leading batch dims; the
  signature/KZG entry points vmap over them.
"""
