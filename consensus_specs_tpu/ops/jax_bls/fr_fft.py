"""Fr (BLS12-381 scalar field) batched radix-2 FFT as limb kernels.

The DAS engine's erasure recovery is FFT-bound: recovering B blobs is
4 forward/inverse FFTs of 2N field elements each.  This module holds a
255-bit element as 16 x 16-bit limbs in ``uint32`` lanes (the
``limbs.py`` representation scaled down from Fq's 24 limbs) and runs
the whole batch's butterflies stage-by-stage as one vectorized dispatch
per stage — ``(B, n/2, 16)`` Montgomery multiplies against precomputed
twiddle tables, then carry-lookahead normalization, exactly the
formulation ``limbs.py`` documents for the MXU/TPU path.

Backend: ``from .backend import xp`` — the JAX device kernel by
default, the pure-numpy mirror under ``CS_TPU_NUMPY_KERNELS=1`` (same
source, eager numpy, no XLA compile — the 1-core-host mode the engine's
``CS_TPU_DAS_FFT=limb`` knob is measured with).  The per-blob python
spec loop stays the counted fallback; this kernel is opt-in.

Exactness argument (same as ``limbs.py``): limb products split into
16-bit halves exact in f32; the 32-term antidiagonal column sums stay
below ``32 * (2^16 - 1) < 2^21`` — exact in f32 accumulation and far
from uint32 overflow in the carry chain.
"""
import numpy as np

from .backend import xp as jnp, dot_f32, kjit

from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER

NLIMB = 16
LIMB_BITS = 16
MASK = jnp.uint32(0xFFFF)
_NCOL = 2 * NLIMB

R_MONT = (1 << (NLIMB * LIMB_BITS)) % R_ORDER        # 2^256 mod r
R2_MONT = (R_MONT * R_MONT) % R_ORDER
NPRIME = (-pow(R_ORDER, -1, 1 << (NLIMB * LIMB_BITS))) \
    % (1 << (NLIMB * LIMB_BITS))


def int_to_limbs(n: int) -> np.ndarray:
    return np.array([(n >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMB)],
                    dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs).reshape(-1)
    assert arr.shape == (NLIMB,)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(NLIMB))


R_LIMBS = int_to_limbs(R_ORDER)
NPRIME_LIMBS = int_to_limbs(NPRIME)
R2_LIMBS = int_to_limbs(R2_MONT)


def _shift_limbs(x, d):
    pad = jnp.zeros(x.shape[:-1] + (d,), x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def _kogge_stone(g, p, n):
    d = 1
    while d < n:  # static log2-depth unroll: n is a python int
        g = g | (p & _shift_limbs(g, d))
        p = p & _shift_limbs(p, d)
        d *= 2
    return g


def _carry_chain(cols, n_out):
    """Propagate 16-bit carries over (..., n) columns -> (..., n_out)."""
    c = cols[..., :n_out]
    c = (c & MASK) + _shift_limbs(c >> LIMB_BITS, 1)
    c = (c & MASK) + _shift_limbs(c >> LIMB_BITS, 1)
    lo = c & MASK
    g = c >> LIMB_BITS
    p = (lo == MASK).astype(jnp.uint32)
    carry_in = _shift_limbs(_kogge_stone(g, p, n_out), 1)
    return (lo + carry_in) & MASK


def _make_scatter_matrix() -> np.ndarray:
    S = np.zeros((2, NLIMB, NLIMB, _NCOL), np.float32)
    for i in range(NLIMB):
        for j in range(NLIMB):
            S[0, i, j, i + j] = 1.0
            S[1, i, j, i + j + 1] = 1.0
    return S.reshape(2 * NLIMB * NLIMB, _NCOL)


_SCATTER = _make_scatter_matrix()


def _product_columns(a, b):
    """(...,16) x (...,16) -> (...,32) antidiagonal column sums (< 2^21)
    as ONE f32 matmul against the constant scatter matrix (the
    ``limbs._product_columns`` formulation; rationale documented there)."""
    prods = a[..., :, None] * b[..., None, :]            # exact in uint32
    lo = (prods & MASK).astype(jnp.float32)
    hi = (prods >> LIMB_BITS).astype(jnp.float32)
    stacked = jnp.concatenate([lo, hi], axis=-2)
    flat = stacked.reshape(stacked.shape[:-2] + (2 * NLIMB * NLIMB,))
    cols = dot_f32(flat, jnp.asarray(_SCATTER))
    return cols.astype(jnp.uint32)


def _full_mul(a, b):
    return _carry_chain(_product_columns(a, b), _NCOL)


def _low_mul(a, b):
    return _carry_chain(_product_columns(a, b), NLIMB)


def _sub_limbs(a, b):
    t = a + (MASK + jnp.uint32(1)) - b
    g = (jnp.uint32(1) - (t >> LIMB_BITS))
    p = (t == MASK + jnp.uint32(1)).astype(jnp.uint32)
    borrow_in = _shift_limbs(_kogge_stone(g, p, a.shape[-1]), 1)
    out = (t - borrow_in) & MASK
    top = (t[..., -1] - borrow_in[..., -1]) >> LIMB_BITS
    borrow = jnp.uint32(1) - top
    return out, borrow


def _cond_sub_r(x):
    r = jnp.asarray(R_LIMBS)
    d, borrow = _sub_limbs(x, jnp.broadcast_to(r, x.shape))
    return jnp.where((borrow != 0)[..., None], x, d)


def add_mod(a, b):
    return _cond_sub_r(_carry_chain(a + b, NLIMB))


def sub_mod(a, b):
    d, borrow = _sub_limbs(a, b)
    d2 = _carry_chain(d + jnp.asarray(R_LIMBS), NLIMB)
    return jnp.where((borrow != 0)[..., None], d2, d)


def mont_mul(a, b):
    """Montgomery product a * b * R^{-1} mod r (inputs/outputs reduced)."""
    t = _full_mul(a, b)
    m = _low_mul(t[..., :NLIMB], jnp.asarray(NPRIME_LIMBS))
    u = _full_mul(m, jnp.asarray(R_LIMBS))
    s = _carry_chain(t + u, _NCOL)
    return _cond_sub_r(s[..., NLIMB:])


def pack_ints_mont(values) -> np.ndarray:
    """Host: nested int lists -> (..., 16) Montgomery limb array."""
    arr = np.asarray(
        [[int_to_limbs(int(v) % R_ORDER) for v in row] for row in values],
        dtype=np.uint32)
    r2 = jnp.broadcast_to(jnp.asarray(R2_LIMBS), arr.shape)
    return mont_mul(jnp.asarray(arr), r2)


def unpack_mont(limbs) -> list:
    """Device (..., 16) Montgomery limbs -> nested python-int lists."""
    one = np.zeros(NLIMB, np.uint32)
    one[0] = 1
    plain = np.asarray(mont_mul(limbs, jnp.broadcast_to(jnp.asarray(one),
                                                        np.shape(limbs))))
    out = []
    for row in plain:
        out.append([sum(int(row[i][k]) << (LIMB_BITS * k)
                        for k in range(NLIMB)) for i in range(row.shape[0])])
    return out


# ---------------------------------------------------------------------------
# Batched radix-2 FFT
# ---------------------------------------------------------------------------
# Stage tables are host-precomputed per (n, inv): gather indices for the
# lo/hi butterfly halves and the Montgomery-form twiddles, so the device
# kernel is pure vectorized arithmetic — one (B, n/2) mont_mul + one
# add/sub pair per stage, log2(n) stages.

_STAGE_CACHE = {}


def _stage_tables(n: int, roots_key, roots):
    key = (n, roots_key)
    hit = _STAGE_CACHE.get(key)
    if hit is not None:
        return hit
    assert n & (n - 1) == 0 and len(roots) == n
    stages = []
    m = 2
    while m <= n:
        stride = n // m
        half = m // 2
        lo_idx = np.concatenate(
            [np.arange(start, start + half) for start in range(0, n, m)])
        hi_idx = lo_idx + half
        tw = np.asarray(
            [int_to_limbs(int(roots[j * stride]) * R_MONT % R_ORDER)
             for j in range(half)] * (n // m), dtype=np.uint32)
        order = np.argsort(np.concatenate([lo_idx, hi_idx]))
        stages.append((lo_idx, hi_idx, tw, order))
        m *= 2
    brev = np.array([int(format(i, f"0{n.bit_length() - 1}b")[::-1], 2)
                     for i in range(n)])
    _STAGE_CACHE[key] = (stages, brev)
    return stages, brev


@kjit
def _butterfly(lo, hi, tw):
    b = mont_mul(hi, tw)
    return add_mod(lo, b), sub_mod(lo, b)


def fft_batch(rows, roots, inv: bool = False, roots_key=None):
    """Batched FFT: ``rows`` is a list of equal-length int lists (one
    polynomial per row), ``roots`` the full forward domain.  Returns the
    transformed rows as python ints — identical to mapping
    ``ops.kzg_7594.fft_field`` over the rows.

    ``roots_key`` is a hashable identity for the domain (defaults to
    the domain size + first root) letting the stage tables cache."""
    if not rows:
        return []
    n = len(rows[0])
    assert all(len(r) == n for r in rows)
    if roots_key is None:
        roots_key = (n, int(roots[1]) if n > 1 else 1)
    if inv:
        domain = list(roots[0:1]) + list(roots[:0:-1])
        key = (roots_key, "inv")
    else:
        domain = list(roots)
        key = (roots_key, "fwd")
    stages, brev = _stage_tables(n, key, domain)
    vals = pack_ints_mont([[row[j] for j in brev] for row in rows])
    for lo_idx, hi_idx, tw, order in stages:
        lo, hi = _butterfly(vals[:, lo_idx], vals[:, hi_idx],
                            jnp.broadcast_to(jnp.asarray(tw),
                                             (len(rows),) + tw.shape))
        # undo the gather layout: lo/hi back to natural positions
        vals = jnp.concatenate([lo, hi], axis=1)[:, order]
    out = unpack_mont(vals)
    if inv:
        invlen = pow(n, R_ORDER - 2, R_ORDER)
        out = [[x * invlen % R_ORDER for x in row] for row in out]
    return out
