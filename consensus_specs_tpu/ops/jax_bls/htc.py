"""Hash-to-G2 as a batched JAX kernel (RFC 9380 SSWU route).

``expand_message_xmd`` runs host-side (a handful of SHA-256 calls per
message - negligible next to the curve math); field mapping, SSWU, the
3-isogeny, and Budroni-Pintore cofactor clearing all run on device,
branchless, batched over messages.  One block's 128 attestation messages
hash-to-curve as a single vectorized program (reference: per-call Rust FFI
inside ``FastAggregateVerify``, ``eth2spec/utils/bls.py:133-143``).

The SSWU/isogeny constants come from the same derivation the python oracle
performs at import (``ops/bls12_381/hash_to_curve.py``), so the two
backends hash identically by construction.
"""
import numpy as np
from .backend import xp as jnp, kjit, lax


from consensus_specs_tpu.ops.bls12_381.fields import X_PARAM
from consensus_specs_tpu.ops.bls12_381 import hash_to_curve as _oracle
from . import limbs as L
from . import tower as T
from . import points as PT

# SSWU curve E' : y^2 = x^3 + A'x + B', Z = -(2+u)
_A = T.f2_const(_oracle.A_PRIME)
_B = T.f2_const(_oracle.B_PRIME)
_Z = T.f2_const(_oracle.Z_SSWU)
# -B/A and B/(Z*A), precomputed host-side for the exceptional branch
_NEG_B_OVER_A = T.f2_const(-(_oracle.B_PRIME) * _oracle.A_PRIME.inv())
_B_OVER_ZA = T.f2_const(
    _oracle.B_PRIME * (_oracle.Z_SSWU * _oracle.A_PRIME).inv())

# 3-isogeny rational-map coefficient tables (RFC 9380 Appendix E.3,
# shared with the oracle), low degree first
_XNUM = tuple(T.f2_const(c) for c in _oracle.ISO_XNUM)
_XDEN = tuple(T.f2_const(c) for c in _oracle.ISO_XDEN)
_YNUM = tuple(T.f2_const(c) for c in _oracle.ISO_YNUM)
_YDEN = tuple(T.f2_const(c) for c in _oracle.ISO_YDEN)

# psi endomorphism constants
_PSI_CX = T.f2_const(_oracle._PSI_CX)
_PSI_CY = T.f2_const(_oracle._PSI_CY)

# cofactor clearing (Budroni-Pintore): h_eff P = [x^2-x-1]P + [x-1]psi(P)
# + psi^2([2]P).  Both scalar terms factor through the 64-bit BLS
# parameter: with R = [x]P - P,  [x^2-x-1]P = [x]R - P  and
# [x-1]psi(P) = psi(R) - so two |x|-multiplications (Hamming weight 6)
# replace the naive 127-bit + 64-bit generic ladders.
_ABS_X_BITS = np.array([int(c) for c in bin(-X_PARAM)[2:]], dtype=np.uint32)


_bc = T.f2_broadcast


def _sgn0(x):
    """RFC 9380 sgn0 for Fq2 (lexicographic parity), branchless."""
    a = L.from_mont(x[0])
    b = L.from_mont(x[1])
    a_par = a[..., 0] & 1
    b_par = b[..., 0] & 1
    a_zero = L.is_zero(a)
    return jnp.where(a_zero, b_par, a_par)


def sswu_map(u):
    """Simplified SWU: field element u (Fq2 pair) -> affine point on E'."""
    A, B, Z = _bc(_A, u), _bc(_B, u), _bc(_Z, u)
    zu2 = T.f2_mul(Z, T.f2_sqr(u))
    tv = T.f2_add(T.f2_sqr(zu2), zu2)
    tv_zero = T.f2_is_zero(tv)
    x1_main = T.f2_mul(_bc(_NEG_B_OVER_A, u),
                       T.f2_add(T.f2_one_like(u), T.f2_inv(tv)))
    x1 = T.f2_select(tv_zero, _bc(_B_OVER_ZA, u), x1_main)
    gx1 = T.f2_add(T.f2_add(T.f2_mul(T.f2_sqr(x1), x1), T.f2_mul(A, x1)), B)
    sq1 = T.f2_is_square(gx1)
    x2 = T.f2_mul(zu2, x1)
    gx2 = T.f2_add(T.f2_add(T.f2_mul(T.f2_sqr(x2), x2), T.f2_mul(A, x2)), B)
    x = T.f2_select(sq1, x1, x2)
    y = T.f2_sqrt(T.f2_select(sq1, gx1, gx2))
    flip = _sgn0(u) != _sgn0(y)
    return x, T.f2_select(flip, T.f2_neg(y), y)


def iso_map(x, y):
    """3-isogeny E' -> E2: the RFC 9380 E.3 rational map (affine, Horner).

    One shared field inversion: inv(x_den*y_den) recovers both 1/x_den and
    1/y_den via multiplication by the other denominator.
    """
    def horner(coeffs):
        acc = _bc(coeffs[-1], x)
        for c in reversed(coeffs[:-1]):
            acc = T.f2_add(T.f2_mul(acc, x), _bc(c, x))
        return acc

    x_num, x_den = horner(_XNUM), horner(_XDEN)
    y_num, y_den = horner(_YNUM), horner(_YDEN)
    inv_both = T.f2_inv(T.f2_mul(x_den, y_den))
    X = T.f2_mul(x_num, T.f2_mul(inv_both, y_den))
    Y = T.f2_mul(y, T.f2_mul(y_num, T.f2_mul(inv_both, x_den)))
    return X, Y


def psi(p):
    """Untwist-Frobenius-twist endomorphism, projective: acts as [p] on G2."""
    X, Y, Z = p
    return (T.f2_mul(T.f2_conj(X), _bc(_PSI_CX, X)),
            T.f2_mul(T.f2_conj(Y), _bc(_PSI_CY, Y)),
            T.f2_conj(Z))


def _mul_x(p):
    """[x]P for the (negative) BLS parameter x: MSB-first ladder over the
    static bits of |x| with the 5 adds under ``lax.cond``, then negate."""
    return PT.g2_neg(PT.g2_scalar_mul(p, _ABS_X_BITS))


def clear_cofactor(p):
    """Budroni-Pintore via the x-chain: [x]R - P + psi(R) + psi^2([2]P)
    with R = [x]P - P."""
    r = PT.g2_add(_mul_x(p), PT.g2_neg(p))
    out = PT.g2_add(_mul_x(r), PT.g2_neg(p))
    out = PT.g2_add(out, psi(r))
    return PT.g2_add(out, psi(psi(PT.g2_add(p, p))))


def map_to_g2(u0, u1):
    """Two field elements -> one G2 point (projective), batched."""
    x0, y0 = iso_map(*sswu_map(u0))
    x1, y1 = iso_map(*sswu_map(u1))
    one = T.f2_one_like(x0)
    p = PT.g2_add((x0, y0, one), (x1, y1, one))
    return clear_cofactor(p)


# Staged pipeline: XLA:CPU's fusion pass goes superlinear with module
# size (the monolithic map_to_g2 module does not compile in 30+ minutes
# on a 1-core host while its pieces take ~1 minute each), so the batch
# entry point dispatches a chain of bounded programs.  The double-run
# program takes a TRACED trip count, so every segment of the cofactor
# ladder reuses ONE compiled program.  The five fixed-exponent powers
# inside SSWU (inversion, Legendre, three sqrt ladders) dispatch through
# the SHARED ladder program (``limbs._j_pow_windows``) - in-trace they
# each duplicated a 96-step scan body, making the SSWU program the
# single biggest compile of the whole pipeline (47 s of the 185 s
# hash-to-curve total on the 1-core host; measured round 4).


@kjit
def _j_sswu_tv(u):
    """u -> (zu2, tv): tv is the inversion operand of the x1 numerator."""
    zu2 = T.f2_mul(_bc(_Z, u), T.f2_sqr(u))
    tv = T.f2_add(T.f2_sqr(zu2), zu2)
    return zu2, tv


@kjit
def _j_sswu_x(u, zu2, tv, tvinv):
    """Candidate x's and their curve polynomials + the Legendre operand."""
    A, B = _bc(_A, u), _bc(_B, u)
    tv_zero = T.f2_is_zero(tv)
    x1_main = T.f2_mul(_bc(_NEG_B_OVER_A, u),
                       T.f2_add(T.f2_one_like(u), tvinv))
    x1 = T.f2_select(tv_zero, _bc(_B_OVER_ZA, u), x1_main)
    gx1 = T.f2_add(T.f2_add(T.f2_mul(T.f2_sqr(x1), x1), T.f2_mul(A, x1)), B)
    x2 = T.f2_mul(zu2, x1)
    gx2 = T.f2_add(T.f2_add(T.f2_mul(T.f2_sqr(x2), x2), T.f2_mul(A, x2)), B)
    norm_gx1 = L.add_mod(L.mont_sqr(gx1[0]), L.mont_sqr(gx1[1]))
    return x1, x2, gx1, gx2, norm_gx1


@kjit
def _j_sswu_pick(x1, x2, gx1, gx2, norm_gx1, lq):
    """Select (x, gx) by the Legendre result lq = norm_gx1^((p-1)/2)."""
    one = jnp.broadcast_to(jnp.asarray(L.ONE_M), lq.shape)
    sq1 = L.eq(lq, one) | L.is_zero(norm_gx1)
    return T.f2_select(sq1, x1, x2), T.f2_select(sq1, gx1, gx2)


@kjit
def _j_sswu_sign(u, x, y):
    flip = _sgn0(u) != _sgn0(y)
    return x, T.f2_select(flip, T.f2_neg(y), y)


def _horner_all(x):
    def horner(coeffs):
        acc = _bc(coeffs[-1], x)
        for c in reversed(coeffs[:-1]):
            acc = T.f2_add(T.f2_mul(acc, x), _bc(c, x))
        return acc
    return horner(_XNUM), horner(_XDEN), horner(_YNUM), horner(_YDEN)


@kjit
def _j_iso_horner(x):
    x_num, x_den, y_num, y_den = _horner_all(x)
    return x_num, x_den, y_num, y_den, T.f2_mul(x_den, y_den)


@kjit
def _j_iso_post(y, x_num, x_den, y_num, y_den, dinv):
    X = T.f2_mul(x_num, T.f2_mul(dinv, y_den))
    Y = T.f2_mul(y, T.f2_mul(y_num, T.f2_mul(dinv, x_den)))
    return X, Y


def _staged_sswu_iso(u):
    """SSWU + 3-isogeny as a pipeline of bounded programs; u batches over
    arbitrary leading dims (map_to_g2_staged stacks u0/u1 on axis 0 so
    both halves ride every program once)."""
    zu2, tv = _j_sswu_tv(u)
    tvinv = T.staged_f2_inv(tv)
    x1, x2, gx1, gx2, n1 = _j_sswu_x(u, zu2, tv, tvinv)
    lq = L.pow_windows_staged(n1, L.LEGENDRE_WINDOWS)
    x, gx = _j_sswu_pick(x1, x2, gx1, gx2, n1, lq)
    y = T.staged_f2_sqrt(gx)
    x, y = _j_sswu_sign(u, x, y)
    x_num, x_den, y_num, y_den, den = _j_iso_horner(x)
    dinv = T.staged_f2_inv(den)
    return _j_iso_post(y, x_num, x_den, y_num, y_den, dinv)


@kjit
def _j_affine_add(x0, y0, x1, y1):
    one = T.f2_one_like(x0)
    return PT.g2_add((x0, y0, one), (x1, y1, one))


@kjit
def _j_g2_dbl_run(acc, n):
    return lax.fori_loop(
        0, n, lambda _, a: PT.g2_dbl(a), acc)


@kjit
def _j_g2_add_point(a, b):
    return PT.g2_add(a, b)


@kjit
def _j_neg_add(a, b):
    """-(a + b)."""
    return PT.g2_neg(PT.g2_add(a, b))


@kjit
def _j_cofactor_combine(mulx_r, r, p):
    """[x]R - P + psi(R) + psi^2([2]P), given [|x|]R (x < 0 so
    [x]R = -[|x|]R)."""
    s = PT.g2_neg(mulx_r)
    t3 = psi(psi(PT.g2_add(p, p)))
    return PT.g2_add(PT.g2_add(s, PT.g2_neg(p)),
                     PT.g2_add(psi(r), t3))


# schedule over |x|'s bits after the leading one: (n_doublings, add_after)
from consensus_specs_tpu.ops.jax_bls.pairing import bit_schedule
_X_SCHEDULE = bit_schedule(_ABS_X_BITS[1:])


def _staged_mul_abs_x(p):
    """[|x|]P via the run/add programs (acc seeds at P for the lead bit)."""
    acc = p
    for n, with_add in _X_SCHEDULE:
        acc = _j_g2_dbl_run(acc, n)
        if with_add:
            acc = _j_g2_add_point(acc, p)
    return acc


def _staged_clear_cofactor(p):
    r = _j_neg_add(_staged_mul_abs_x(p), p)          # [x]P - P
    return _j_cofactor_combine(_staged_mul_abs_x(r), r, p)


def map_to_g2_staged(u0, u1):
    """Same math as :func:`map_to_g2`, as a pipeline of bounded programs.

    u0/u1 are stacked on a fresh leading axis so SSWU + isogeny run once
    over both halves (every program dispatch covers 2x the lanes)."""
    u = (jnp.stack([u0[0], u1[0]]), jnp.stack([u0[1], u1[1]]))
    X, Y = _staged_sswu_iso(u)
    x0, y0 = (X[0][0], X[1][0]), (Y[0][0], Y[1][0])
    x1, y1 = (X[0][1], X[1][1]), (Y[0][1], Y[1][1])
    return _staged_clear_cofactor(_j_affine_add(x0, y0, x1, y1))


def hash_to_field_host(msgs, dst=_oracle.DST_G2) -> tuple:
    """Host-side: list of messages -> packed (u0, u1) Fq2 limb batches."""
    us = [_oracle.hash_to_field_fq2(bytes(m), 2, dst) for m in msgs]
    def pack(idx):
        return (L.pack_ints_mont([u[idx].a.n for u in us]),
                L.pack_ints_mont([u[idx].b.n for u in us]))
    return pack(0), pack(1)


def hash_to_g2_batch(msgs, dst=_oracle.DST_G2):
    """List of messages -> batched projective G2 limb points (device)."""
    u0, u1 = hash_to_field_host(msgs, dst)
    return _map_to_g2_jit(u0, u1)


_map_to_g2_jit = map_to_g2_staged
