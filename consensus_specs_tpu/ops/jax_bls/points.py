"""G1/G2 point arithmetic for TPU: complete projective formulas, branchless.

Points are pytrees ``(X, Y, Z)`` of field elements in homogeneous projective
coordinates - G1 over Fq (limb arrays), G2 over Fq2 (limb pairs).  The
addition law is the Renes-Costello-Batina complete formula for short
Weierstrass curves with a = 0, which is total: it handles doubling,
inverses and the identity (0 : 1 : 0) with no branches, exactly what an XLA
program wants (reference's backends use branchy Jacobian code in Rust;
branchless completeness is the TPU-first redesign).

Scalar multiplication and multi-point aggregation are ``lax.scan`` /
tree-reduction over these complete adds, so aggregating 2048 attestation
pubkeys is a depth-11 vectorized reduction rather than a serial loop
(reference hot path ``eth2spec/utils/bls.py:133-143``).
"""
import numpy as np
import jax  # tree_util only; array ops ride the backend switch
from .backend import xp as jnp, lax, kjit

from consensus_specs_tpu.ops.bls12_381.fields import Fq2 as _OFq2
from consensus_specs_tpu.ops.bls12_381.curve import G1Point, G2Point
from . import limbs as L
from . import tower as T


# Field-op dispatch: G1 coords are Fq limb arrays, G2 coords are Fq2 pairs.
class _FqOps:
    add = staticmethod(L.add_mod)
    sub = staticmethod(L.sub_mod)
    neg = staticmethod(L.neg_mod)
    mul = staticmethod(L.mont_mul)
    sqr = staticmethod(L.mont_sqr)
    mul_many = staticmethod(L.mont_mul_many)
    add_many = staticmethod(L.add_mod_many)
    sub_many = staticmethod(L.sub_mod_many)
    select = staticmethod(L.select)
    is_zero = staticmethod(L.is_zero)
    eq = staticmethod(L.eq)

    @staticmethod
    def zero_like(x):
        return jnp.zeros_like(x)

    @staticmethod
    def one_like(x):
        return jnp.broadcast_to(jnp.asarray(L.ONE_M), x.shape)


class _Fq2Ops:
    add = staticmethod(T.f2_add)
    sub = staticmethod(T.f2_sub)
    neg = staticmethod(T.f2_neg)
    mul = staticmethod(T.f2_mul)
    sqr = staticmethod(T.f2_sqr)
    mul_many = staticmethod(T.f2_mul_many)
    add_many = staticmethod(T.f2_add_many)
    sub_many = staticmethod(T.f2_sub_many)
    select = staticmethod(T.f2_select)
    is_zero = staticmethod(T.f2_is_zero)
    eq = staticmethod(T.f2_eq)
    zero_like = staticmethod(T.f2_zero_like)
    one_like = staticmethod(T.f2_one_like)


# 3*b curve constants: b = 4 on G1, b = 4(1+u) on G2.
_B3_G1 = L.fq_const(12)
_B3_G2 = _OFq2(12, 12)


def _b3(f, like):
    if f is _FqOps:
        return jnp.broadcast_to(jnp.asarray(_B3_G1), like.shape)
    return T.f2_broadcast(T.f2_const(_B3_G2), like)


def _complete_add(f, p, q):
    """RCB 2015 Algorithm 7 (complete addition, a = 0, projective).

    Multiplications in three batched waves (6 + 2 + 6), and every group
    of independent adds/subs in one batched wave too — XLA:CPU compile
    cost is ~linear in the number of carry networks, so singles are the
    enemy.
    """
    x1, y1, z1 = p
    x2, y2, z2 = q
    b3 = _b3(f, x1)
    s = f.add_many([(x1, y1), (y1, z1), (x1, z1),
                    (x2, y2), (y2, z2), (x2, z2)])
    t0, t1, t2, m1, m2, m3 = f.mul_many([
        (x1, x2), (y1, y2), (z1, z2),
        (s[0], s[3]), (s[1], s[4]), (s[2], s[5])])
    w = f.add_many([(t0, t1), (t1, t2), (t0, t2), (t0, t0)])
    t3, t4, yp = f.sub_many([(m1, w[0]), (m2, w[1]), (m3, w[2])])
    x3 = f.add_many([(w[3], t0)])[0]                   # 3 x1x2
    t2b, y3 = f.mul_many([(b3, t2), (b3, yp)])
    z3 = f.add_many([(t1, t2b)])[0]                    # y1y2 + 3b z1z2
    t1b = f.sub_many([(t1, t2b)])[0]                   # y1y2 - 3b z1z2
    p1, p2, p3, p4, p5, p6 = f.mul_many([
        (t3, t1b), (t4, y3), (t1b, z3), (y3, x3), (z3, t4), (x3, t3)])
    fin_a = f.add_many([(p3, p4), (p5, p6)])
    return (f.sub_many([(p1, p2)])[0], fin_a[0], fin_a[1])


def _complete_dbl(f, p):
    """RCB 2015 Algorithm 9 (exception-free doubling, a = 0, projective):
    9 muls in three batched waves vs 12 for the general complete add;
    adds wave-batched like :func:`_complete_add`.
    The identity (and any y = 0 input) correctly lands on (0 : c : 0)."""
    X, Y, Z = p
    b3 = _b3(f, X)
    t0, t1, xy, zz = f.mul_many([(Y, Y), (Y, Z), (X, Y), (Z, Z)])
    w1 = f.add_many([(t0, t0)])[0]
    w2 = f.add_many([(w1, w1)])[0]
    t2 = f.mul_many([(b3, zz)])[0]                     # 3b Z^2
    w3 = f.add_many([(w2, w2), (t0, t2), (t2, t2)])
    z3, y3 = w3[0], w3[1]                              # z3 = 8Y^2
    t2_3 = f.add_many([(w3[2], t2)])[0]                # 3 t2
    t0 = f.sub_many([(t0, t2_3)])[0]                   # Y^2 - 9b Z^2
    m1, m2, m3, m4 = f.mul_many([(t2, z3), (t1, z3), (t0, y3), (t0, xy)])
    fin = f.add_many([(m4, m4), (m1, m3)])
    return (fin[0], fin[1], m2)


def _identity_like(f, p):
    return (f.zero_like(p[0]), f.one_like(p[1]), f.zero_like(p[2]))


def _neg(f, p):
    return (p[0], f.neg(p[1]), p[2])


def _is_identity(f, p):
    return f.is_zero(p[2])


def _select(f, cond, p, q):
    return tuple(f.select(cond, a, b) for a, b in zip(p, q))


def _scalar_mul(f, p, bits):
    """[k]P via MSB-first double-and-add.

    ``bits``: static numpy bit array (shared exponent) or a traced
    ``(..., n)`` uint32 array (per-element scalars).  Doubling uses the
    dedicated 9-mul formula; for a static (unbatched) schedule the add
    hangs off ``lax.cond`` so zero bits pay nothing at runtime.
    """
    acc = _identity_like(f, p)
    bits = jnp.asarray(bits)
    if bits.ndim > 1:
        xs = jnp.moveaxis(bits, -1, 0)
        batched_bits = True
    else:
        xs = bits
        batched_bits = False

    def step(acc, bit):
        acc = _complete_dbl(f, acc)
        if batched_bits:
            nxt = _complete_add(f, acc, p)
            acc = _select(f, bit != 0, nxt, acc)
        else:
            acc = lax.cond(bit != 0,
                               lambda a: _complete_add(f, a, p),
                               lambda a: a, acc)
        return acc, None

    acc, _ = lax.scan(step, acc, xs)
    return acc


def _tree_sum(f, pts):
    """Sum points over the leading axis by halving reductions (log depth)."""
    n = jax.tree_util.tree_leaves(pts)[0].shape[0]
    # pad to a power of two with identities (m - n < n always)
    m = 1 if n <= 1 else 1 << (n - 1).bit_length()
    if m != n:
        ident = _identity_like(f, pts)
        pts = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b[: m - n]]), pts, ident)
    while m > 1:
        m //= 2
        lo = jax.tree_util.tree_map(lambda a: a[:m], pts)
        hi = jax.tree_util.tree_map(lambda a: a[m:], pts)
        pts = _complete_add(f, lo, hi)
    return jax.tree_util.tree_map(lambda a: a[0], pts)


def _to_affine_host(f, p):
    """Host-side: projective limb point -> oracle affine point (single)."""
    if f is _FqOps:
        zs = L.unpack_mont(p[2])[0]
        if zs == 0:
            return G1Point.inf()
        from consensus_specs_tpu.ops.bls12_381.fields import Fq
        x, y = L.unpack_mont(p[0])[0], L.unpack_mont(p[1])[0]
        zi = Fq(zs).inv()
        return G1Point(Fq(x) * zi, Fq(y) * zi)
    zs = (L.unpack_mont(p[2][0])[0], L.unpack_mont(p[2][1])[0])
    if zs == (0, 0):
        return G2Point.inf()
    x = _OFq2(L.unpack_mont(p[0][0])[0], L.unpack_mont(p[0][1])[0])
    y = _OFq2(L.unpack_mont(p[1][0])[0], L.unpack_mont(p[1][1])[0])
    zi = _OFq2(*zs).inv()
    return G2Point(x * zi, y * zi)


# ---------------------------------------------------------------------------
# Public G1/G2 API
# ---------------------------------------------------------------------------

def g1_add(p, q):
    return _complete_add(_FqOps, p, q)


def g1_dbl(p):
    return _complete_dbl(_FqOps, p)


def g2_dbl(p):
    return _complete_dbl(_Fq2Ops, p)


def g2_add(p, q):
    return _complete_add(_Fq2Ops, p, q)


def g1_neg(p):
    return _neg(_FqOps, p)


def g2_neg(p):
    return _neg(_Fq2Ops, p)


def g1_identity_like(p):
    return _identity_like(_FqOps, p)


def g2_identity_like(p):
    return _identity_like(_Fq2Ops, p)


def g1_scalar_mul(p, bits):
    return _scalar_mul(_FqOps, p, bits)


def g2_scalar_mul(p, bits):
    return _scalar_mul(_Fq2Ops, p, bits)


def g1_tree_sum(pts):
    return _tree_sum(_FqOps, pts)


def g1_tree_sum_batched(pts):
    """Sum over axis 1 of a (B, N, ...) packed batch, N a power of two.

    Fixed-shape halving: every level is one full-width complete add of
    the array against itself rolled by the (traced) stride, keeping only
    the live prefix — so the whole reduction is ONE fori_loop program
    with a ~13-mul body, not log2(N) differently-shaped adds.  (XLA:CPU
    compile cost scales superlinearly with module size; this keeps the
    aggregation program bounded for any N.)
    """
    f = _FqOps
    n = jax.tree_util.tree_leaves(pts)[0].shape[1]
    if n == 1:
        return jax.tree_util.tree_map(lambda a: a[:, 0], pts)
    assert n & (n - 1) == 0, "pad the aggregation axis to a power of two"
    levels = n.bit_length() - 1
    lane = jnp.arange(n, dtype=jnp.uint32)

    def body(k, arr):
        stride = jnp.uint32(n) >> (k + 1)
        rolled = jax.tree_util.tree_map(
            lambda a: jnp.roll(a, -stride.astype(jnp.int32), axis=1), arr)
        summed = _complete_add(f, arr, rolled)
        keep = (lane < stride)[None, :]
        return _select(f, keep, summed, arr)

    out = lax.fori_loop(0, levels, body, pts)
    return jax.tree_util.tree_map(lambda a: a[:, 0], out)


def g2_tree_sum(pts):
    return _tree_sum(_Fq2Ops, pts)


def g1_is_identity(p):
    return _is_identity(_FqOps, p)


def g2_is_identity(p):
    return _is_identity(_Fq2Ops, p)


def g1_select(cond, p, q):
    return _select(_FqOps, cond, p, q)


def g2_select(cond, p, q):
    return _select(_Fq2Ops, cond, p, q)


def g1_normalize(p):
    """Projective -> affine-with-Z=1 (identity maps to (0, 1, 0))."""
    zinv = L.inv_mod(p[2])
    inf = L.is_zero(p[2])
    x = L.mont_mul(p[0], zinv)
    y = L.mont_mul(p[1], zinv)
    one = _FqOps.one_like(p[2])
    return (L.select(inf, jnp.zeros_like(x), x),
            L.select(inf, one, y),
            L.select(inf, jnp.zeros_like(p[2]), one))


def g2_normalize(p):
    zinv = T.f2_inv(p[2])
    inf = T.f2_is_zero(p[2])
    x = T.f2_mul(p[0], zinv)
    y = T.f2_mul(p[1], zinv)
    one = T.f2_one_like(p[2])
    zero = T.f2_zero_like(p[2])
    return (T.f2_select(inf, zero, x),
            T.f2_select(inf, one, y),
            T.f2_select(inf, zero, one))


# Staged normalizations: the field inversion dispatches through the
# shared ladder program (limbs._j_pow_windows) so only the cheap
# combine compiles per call site.  Same math as g1/g2_normalize.

@kjit
def _j_g1_norm_post(p, zinv):
    inf = L.is_zero(p[2])
    x = L.mont_mul(p[0], zinv)
    y = L.mont_mul(p[1], zinv)
    one = _FqOps.one_like(p[2])
    pt = (L.select(inf, jnp.zeros_like(x), x),
          L.select(inf, one, y),
          L.select(inf, jnp.zeros_like(p[2]), one))
    return pt, inf


def g1_normalize_flag_staged(p):
    """Projective -> affine-with-Z=1 + identity flag, staged."""
    zinv = L.pow_windows_staged(p[2], L.INV_WINDOWS)
    return _j_g1_norm_post(p, zinv)


@kjit
def _j_g2_norm_post(p, zinv):
    inf = T.f2_is_zero(p[2])
    x = T.f2_mul(p[0], zinv)
    y = T.f2_mul(p[1], zinv)
    one = T.f2_one_like(p[2])
    zero = T.f2_zero_like(p[2])
    return (T.f2_select(inf, zero, x),
            T.f2_select(inf, one, y),
            T.f2_select(inf, zero, one))


def g2_normalize_staged(p):
    return _j_g2_norm_post(p, T.staged_f2_inv(p[2]))


# ---------------------------------------------------------------------------
# Host-side packing: oracle points <-> limb pytrees
# ---------------------------------------------------------------------------

def g1_pack(points) -> tuple:
    """List of oracle G1Points -> batched projective limb point (N, 24)."""
    xs, ys, zs = [], [], []
    for pt in points:
        if pt.infinity:
            xs.append(0); ys.append(1); zs.append(0)
        else:
            xs.append(pt.x.n); ys.append(pt.y.n); zs.append(1)
    return (L.pack_ints_mont(xs), L.pack_ints_mont(ys), L.pack_ints_mont(zs))


def g2_pack(points) -> tuple:
    """List of oracle G2Points -> batched projective limb point."""
    coords = {k: [] for k in ("xa", "xb", "ya", "yb", "za", "zb")}
    for pt in points:
        if pt.infinity:
            vals = (0, 0, 1, 0, 0, 0)
        else:
            vals = (pt.x.a.n, pt.x.b.n, pt.y.a.n, pt.y.b.n, 1, 0)
        for k, v in zip(coords, vals):
            coords[k].append(v)
    pk = {k: L.pack_ints_mont(v) for k, v in coords.items()}
    return ((pk["xa"], pk["xb"]), (pk["ya"], pk["yb"]), (pk["za"], pk["zb"]))


def g1_unpack(p) -> G1Point:
    return _to_affine_host(_FqOps, p)


def g2_unpack(p) -> G2Point:
    return _to_affine_host(_Fq2Ops, p)


def g1_pack_affine_rows(pt: G1Point) -> tuple:
    """Host-side: one affine (non-identity) oracle point -> its packed
    Montgomery (x, y) limb rows.  The z row is implied mont(1) — see
    g1_stack_packed, which owns the projective encoding."""
    return (L.fq_const(pt.x.n), L.fq_const(pt.y.n))


def g1_stack_packed(rows, n_pad: int) -> tuple:
    """Host-side: rows of g1_pack_affine_rows outputs -> batched packed
    projective pytree ((N,24) x3), each row identity-padded to ``n_pad``.

    Owns the projective encoding next to g1_pack: live points are
    (x, y, mont(1)); identity is (0, mont(1), 0).
    """
    zero_row, one_row = L.ZERO, L.ONE_M
    xs, ys, zs = [], [], []
    for row in rows:
        pad = n_pad - len(row)
        xs.extend([p[0] for p in row] + [zero_row] * pad)
        ys.extend([p[1] for p in row] + [one_row] * pad)
        zs.extend([one_row] * len(row) + [zero_row] * pad)
    return (jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys)),
            jnp.asarray(np.stack(zs)))
