"""Optimal ate pairing on BLS12-381 as a JAX kernel.

Miller loop = one ``lax.scan`` over the 63 post-leading bits of |x|
(x = BLS parameter), Jacobian doubling/mixed-addition on the M-twist with
inversion-free line evaluation; final exponentiation = easy part plus the
(x-1)^2 (x+p)(x^2+p^2-1)+3 decomposition of 3*(p^4-p^2+1)/r (verified
against the integers at import), which only needs five 64-bit
x-exponentiations.  Scaling lines by arbitrary nonzero Fq2 factors is sound
because (p^2-1) | (p^12-1)/r, so such factors die in the final
exponentiation; the pairing *check* may use exponent 3h because
gcd(3, r) = 1.

Everything vmaps over leading batch dims: a batch of aggregate
verifications is a batch of 2-pair Miller loops sharing one vectorized
program (reference equivalent: per-call Rust FFI, one at a time -
``eth2spec/utils/bls.py:107-143``).
"""
import numpy as np
import jax  # vmap/tree_util for the monolithic path; arrays ride the backend
from .backend import xp as jnp, lax, kjit

from consensus_specs_tpu.ops.bls12_381.fields import P, R_ORDER, X_PARAM
from . import limbs as L
from . import tower as T

_ABS_X = -X_PARAM
# sanity: the hard-part decomposition used below (also checked in tests)
assert 3 * ((P ** 4 - P ** 2 + 1) // R_ORDER) == \
    (X_PARAM - 1) ** 2 * (X_PARAM + P) * (X_PARAM ** 2 + P ** 2 - 1) + 3

# MSB-first bits of |x| after the leading 1 (Miller loop schedule).
_MILLER_BITS = np.array(
    [int(c) for c in bin(_ABS_X)[3:]], dtype=np.uint32)
# MSB-first bits of |x| including the leading 1 (x-exponentiation).
_X_BITS = np.array([int(c) for c in bin(_ABS_X)[2:]], dtype=np.uint32)


def _line_to_f12(c0, c3, c5):
    """Sparse line c0 + c3*w^3 + c5*w^5 as a full Fq12 element.

    w^3 = v*w and w^5 = v^2*w, so the w-part Fq6 is (0, c3, c5).
    """
    z = T.f2_zero_like(c0)
    return ((c0, z, z), (z, c3, c5))


def _mul_by_line(f, line):
    """f * (c0 + c3 w^3 + c5 w^5), exploiting the line's sparsity.

    Direct split over w: (a + bw)(l0 + l1 w) = (a l0 + v(b l1)) +
    (a l1 + b l0) w with l0 = (c0,0,0), l1 = (0,c3,c5) - 16 Fq2 products
    in ONE batched multiply, and every combination add/sub/xi in a
    batched wave (16 muls beats 18 for a dense f12_mul, and the wave
    discipline keeps the carry-network count - the XLA:CPU compile-time
    driver - minimal).
    """
    (c0, _, _), (_, c3, c5) = line
    a, b = f
    pre = T.f2_add_many([(a[1], a[2]), (b[1], b[2]), (c3, c5)])
    sa, sb, sc = pre
    m = T.f2_mul_many([
        (a[0], c0), (a[1], c0), (a[2], c0),                 # a * l0
        (b[0], c0), (b[1], c0), (b[2], c0),                 # b * l0
        (a[1], c3), (a[2], c5), (sa, sc), (a[0], c3), (a[0], c5),
        (b[1], c3), (b[2], c5), (sb, sc), (b[0], c3), (b[0], c5),
    ])
    t0, bl0 = m[0:3], m[3:6]
    a11, a22, aS, a01, a02 = m[6:11]
    b11, b22, bS, b01, b02 = m[11:16]
    # sparse product x*l1: r0 = xi(S - t11 - t22), r1 = p01 + xi(t22),
    # r2 = p02 + t11  (xi(a+bu) = (a-b) + (a+b)u, batched at limb level)
    w1 = T.f2_sub_many([(aS, a11), (bS, b11)])
    w2 = T.f2_sub_many([(w1[0], a22), (w1[1], b22)])
    xire = L.sub_mod_many([(w2[0][0], w2[0][1]), (w2[1][0], w2[1][1]),
                           (a22[0], a22[1]), (b22[0], b22[1])])
    xiim = L.add_mod_many([(w2[0][0], w2[0][1]), (w2[1][0], w2[1][1]),
                           (a22[0], a22[1]), (b22[0], b22[1])])
    w3 = T.f2_add_many([(a01, (xire[2], xiim[2])), (a02, a11),
                        (b01, (xire[3], xiim[3])), (b02, b11)])
    al1 = ((xire[0], xiim[0]), w3[0], w3[1])
    bl1 = ((xire[1], xiim[1]), w3[2], w3[3])
    # v * bl1 = (xi(bl1[2]), bl1[0], bl1[1])
    xv_re = L.sub_mod_many([(bl1[2][0], bl1[2][1])])[0]
    xv_im = L.add_mod_many([(bl1[2][0], bl1[2][1])])[0]
    vbl1 = ((xv_re, xv_im), bl1[0], bl1[1])
    out0 = T.f2_add_many(list(zip(t0, vbl1)))
    out1 = T.f2_add_many(list(zip(al1, bl0)))
    return (tuple(out0), tuple(out1))


def _dbl_step(r, px, py):
    """Jacobian doubling of R on the twist + tangent line at R through P.

    Line (scaled by 2YZ^3 * xi, an Fq2 factor):
      c0 = 2YZ^3 * xi * py,  c3 = 3X^3 - 2Y^2,  c5 = -3X^2 Z^2 * px.
    Multiplications are grouped into three batched "waves".
    """
    X, Y, Z = r
    # wave 1: X^2, Y^2, Z^2
    A, B, Z2 = T.f2_sqr_many([X, Y, Z])
    E = T.f2_add(T.f2_add(A, A), A)       # 3X^2
    XB = T.f2_add(X, B)
    # wave 2: Y^4, (X+B)^2, E^2, E*X, E*Z^2, Y*Z
    C, U, F = T.f2_sqr_many([B, XB, E])
    EX, EZ2, YZ = T.f2_mul_many([(E, X), (E, Z2), (Y, Z)])
    t = T.f2_sub(U, T.f2_add(A, C))
    D = T.f2_add(t, t)                    # 4XY^2
    X3 = T.f2_sub(F, T.f2_add(D, D))
    C4 = T.f2_add(T.f2_add(C, C), T.f2_add(C, C))
    C8 = T.f2_add(C4, C4)                 # 8Y^4
    Z3 = T.f2_add(YZ, YZ)
    B2 = T.f2_add(B, B)                   # 2Y^2
    # wave 3: E*(D - X3), Y*Z^3 (for the line's d = 2YZ^3), px/py scalings
    EDX, YZc = T.f2_mul_many([(E, T.f2_sub(D, X3)), (YZ, Z2)])
    d = T.f2_add(YZc, YZc)                # 2YZ^3
    c0pair = L.mont_mul_many([(a, py) for a in T.f2_mul_xi(d)]
                             + [(a, px) for a in EZ2])
    Y3 = T.f2_sub(EDX, C8)
    c0 = (c0pair[0], c0pair[1])
    c3 = T.f2_sub(EX, B2)                 # 3X^3 - 2Y^2
    c5 = T.f2_neg((c0pair[2], c0pair[3]))
    return (X3, Y3, Z3), _line_to_f12(c0, c3, c5)


def _add_step(r, q, px, py):
    """Mixed addition R + Q (Q affine on the twist) + chord line through them.

    With n = S2 - Y1 and d = Z1*H (cross-multiplied slope n/d):
      c0 = d * xi * py,  c3 = n*qx - d*qy,  c5 = -n*px.
    """
    X1, Y1, Z1 = r
    qx, qy = q
    # wave 1: Z1^2
    Z1Z1 = T.f2_sqr(Z1)
    # wave 2: U2 = qx Z1^2, Z1^3, then S2 = qy Z1^3
    U2, Z1c = T.f2_mul_many([(qx, Z1Z1), (Z1, Z1Z1)])
    H = T.f2_sub(U2, X1)
    # wave 3: S2, H^2, Z1*H
    S2, HH, Z1H = T.f2_mul_many([(qy, Z1c), (H, H), (Z1, H)])
    I = T.f2_add(T.f2_add(HH, HH), T.f2_add(HH, HH))
    n = T.f2_sub(S2, Y1)
    rr = T.f2_add(n, n)
    # wave 4: J = H*I, V = X1*I, rr^2, n*qx, d*qy
    J, V, RR2, NQX, DQY = T.f2_mul_many(
        [(H, I), (X1, I), (rr, rr), (n, qx), (Z1H, qy)])
    X3 = T.f2_sub(T.f2_sub(RR2, J), T.f2_add(V, V))
    # wave 5: rr*(V - X3), Y1*J, and px/py Fq scalings
    RVX, Y1J = T.f2_mul_many([(rr, T.f2_sub(V, X3)), (Y1, J)])
    sc = L.mont_mul_many([(a, py) for a in T.f2_mul_xi(Z1H)]
                         + [(a, px) for a in n])
    Y3 = T.f2_sub(RVX, T.f2_add(Y1J, Y1J))
    Z3 = T.f2_add(Z1H, Z1H)
    c0 = (sc[0], sc[1])
    c3 = T.f2_sub(NQX, DQY)
    c5 = T.f2_neg((sc[2], sc[3]))
    return (X3, Y3, Z3), _line_to_f12(c0, c3, c5)


def miller_loop(px, py, q, degenerate):
    """f_{|x|, Q}(P) conjugated for x < 0.

    px, py: G1 affine coords (Fq limbs); q = (qx, qy): G2 affine twist
    coords (Fq2).  ``degenerate``: bool mask - where set, the result is
    forced to 1 (the pairing with the identity).  All args batch.

    The bit schedule is static with Hamming weight 6: the chord/add work
    hangs off a ``lax.cond`` on the (unbatched) schedule bit, so it only
    *executes* on the 6 set bits while the loop still compiles as ONE
    scan body.  (cond stays a true branch under vmap because the
    predicate is not batched.)
    """
    one = T.f12_one_like(((q[0], q[0], q[0]), (q[0], q[0], q[0])))
    r0 = (q[0], q[1], T.f2_one_like(q[0]))

    def step(carry, bit):
        r, f = carry
        f = T.f12_sqr(f)
        r, line = _dbl_step(r, px, py)
        f = _mul_by_line(f, line)

        def with_add(rf):
            r, f = rf
            r, line = _add_step(r, q, px, py)
            return (r, _mul_by_line(f, line))

        carry = lax.cond(bit != 0, with_add, lambda rf: rf, (r, f))
        return carry, None

    (_, f), _ = lax.scan(step, (r0, one), jnp.asarray(_MILLER_BITS))
    f = T.f12_conj(f)                       # x < 0
    return T.f12_select(degenerate, one, f)


def _pow_x(f):
    """f^|x| for a CYCLOTOMIC-subgroup element: Granger-Scott squarings;
    the 5 multiplies at set bits execute under ``lax.cond``."""
    def step(acc, bit):
        acc = T.f12_cyclotomic_sqr(acc)
        acc = lax.cond(bit != 0, lambda a: T.f12_mul(a, f),
                           lambda a: a, acc)
        return acc, None

    out, _ = lax.scan(step, f, jnp.asarray(_X_BITS[1:]))
    return out


def _pow_x_minus_1(f):
    """f^(x-1) = conj(f^|x| * f)  (x negative; conj = inverse after easy part)."""
    return T.f12_conj(T.f12_mul(_pow_x(f), f))


def final_exp_is_one(f):
    """True iff f^((p^12-1)/r) == 1, via the 3h decomposition."""
    # easy part: g = f^((p^6-1)(p^2+1)); g lands in the cyclotomic subgroup
    g = T.f12_mul(T.f12_conj(f), T.f12_inv(f))
    g = T.f12_mul(T.f12_frobenius(T.f12_frobenius(g)), g)
    # hard part (exponent 3h): t4 = g^((x-1)^2 (x+p)(x^2+p^2-1)), out = t4 g^3
    t1 = _pow_x_minus_1(g)
    t2 = _pow_x_minus_1(t1)
    t3 = T.f12_mul(T.f12_conj(_pow_x(t2)), T.f12_frobenius(t2))     # t2^(x+p)
    xx = T.f12_conj(_pow_x(T.f12_conj(_pow_x(t3))))                 # t3^(x^2)
    t4 = T.f12_mul(T.f12_mul(xx, T.f12_frobenius(T.f12_frobenius(t3))),
                   T.f12_conj(t3))
    out = T.f12_mul(t4, T.f12_mul(T.f12_cyclotomic_sqr(g), g))
    return T.f12_is_one(out)


def multi_miller(px, py, q, degenerate):
    """Product of Miller loops over the leading 'pairs' axis.

    Args have a leading axis of size n_pairs (possibly after batch dims at
    the *end* - this function reduces axis 0 of each input).
    """
    fs = jax.vmap(miller_loop)(px, py, q, degenerate)
    n = jax.tree_util.tree_leaves(fs)[0].shape[0]
    out = jax.tree_util.tree_map(lambda a: a[0], fs)
    for i in range(1, n):
        out = T.f12_mul(out, jax.tree_util.tree_map(lambda a: a[i], fs))
    return out


def pairing_check(px, py, q, degenerate):
    """True iff prod_i e(P_i, Q_i) == 1.  Inputs carry a leading pairs axis."""
    return final_exp_is_one(multi_miller(px, py, q, degenerate))


# ---------------------------------------------------------------------------
# Staged pairing: the same math as pairing_check, decomposed into a small
# set of bounded jit programs orchestrated from the host.  XLA:CPU's
# fusion pass scales superlinearly with module size (a monolithic pairing
# module takes 30+ minutes on a 1-core host while its pieces compile in
# ~1 minute total), so each stage stays small and the double/square runs
# use ``fori_loop`` with a TRACED trip count - one compiled program
# regardless of segment length.  Carries stay on device between stages.
# ---------------------------------------------------------------------------

def bit_schedule(bits):
    """MSB-first bit array -> [(n_square_or_double_steps, mul_or_add_after)]:
    the static run-length schedule the staged ladders share."""
    runs, n = [], 0
    for b in bits:
        n += 1
        if b:
            runs.append((int(n), True))
            n = 0
    if n:
        runs.append((int(n), False))
    return runs


_MILLER_SCHEDULE = bit_schedule(_MILLER_BITS)
_X_SCHEDULE = bit_schedule(_X_BITS[1:])


@kjit
def _j_miller_init(q):
    one = T.f12_one_like(((q[0], q[0], q[0]), (q[0], q[0], q[0])))
    return (q[0], q[1], T.f2_one_like(q[0])), one


@kjit
def _j_miller_dbl_run(carry, px, py, n):
    """``n`` (traced) square+double+line steps - one compiled program."""
    def body(_, carry):
        r, f = carry
        f = T.f12_sqr(f)
        r, line = _dbl_step(r, px, py)
        return (r, _mul_by_line(f, line))
    return lax.fori_loop(0, n, body, carry)


@kjit
def _j_miller_add(carry, q, px, py):
    r, f = carry
    r, line = _add_step(r, q, px, py)
    return (r, _mul_by_line(f, line))


@kjit
def _j_miller_finish(carry, degenerate):
    _, f = carry
    one = T.f12_one_like(f)
    return T.f12_select(degenerate, one, T.f12_conj(f))


@kjit
def _j_f12_mul(a, b):
    return T.f12_mul(a, b)


# The easy part split around its single Fq inversion so the 96-step
# ladder dispatches through the SHARED pow program instead of inlining
# (the in-trace version cost 73 s of cold XLA:CPU compile; round 4).

@kjit
def _j_easy_det(f):
    """f12_inv front half: f6_inv partials of d6 = a0^2 - v*a1^2 down to
    the Fq2 determinant (mirrors tower.f6_inv)."""
    a0, a1 = f
    d6 = T.f6_sub(T.f6_sqr(a0), T.f6_mul_by_v(T.f6_sqr(a1)))
    b0, b1, b2 = d6
    m = T.f2_mul_many([(b0, b0), (b1, b1), (b2, b2),
                       (b1, b2), (b0, b1), (b0, b2)])
    sq0, sq1, sq2, m12, m01, m02 = m
    t = T.f2_sub_many([(sq0, T.f2_mul_xi(m12)),
                       (T.f2_mul_xi(sq2), m01),
                       (sq1, m02)])
    d = T.f2_mul_many([(b0, t[0]), (b2, t[1]), (b1, t[2])])
    det = T.f2_add(d[0], T.f2_add(T.f2_mul_xi(d[1]), T.f2_mul_xi(d[2])))
    return t[0], t[1], t[2], det


@kjit
def _j_easy_finish(f, t0, t1, t2, dinv):
    inv6 = tuple(T.f2_mul_many([(t0, dinv), (t1, dinv), (t2, dinv)]))
    a0, a1 = f
    inv12 = (T.f6_mul(a0, inv6), T.f6_neg(T.f6_mul(a1, inv6)))
    g = T.f12_mul(T.f12_conj(f), inv12)
    return T.f12_mul(T.f12_frobenius(T.f12_frobenius(g)), g)


def _staged_easy_part(f):
    t0, t1, t2, det = _j_easy_det(f)
    dinv = T.staged_f2_inv(det)
    return _j_easy_finish(f, t0, t1, t2, dinv)


@kjit
def _j_cyc_sqr_run(acc, n):
    return lax.fori_loop(
        0, n, lambda _, a: T.f12_cyclotomic_sqr(a), acc)


@kjit
def _j_conj(f):
    return T.f12_conj(f)


@kjit
def _j_hard_combine_t3(t2, t2x):
    """t2^(x+p) given t2 and t2^|x|: conj(t2^|x|) * frobenius(t2)."""
    return T.f12_mul(T.f12_conj(t2x), T.f12_frobenius(t2))


@kjit
def _j_hard_combine_t4(t3, xx):
    """xx = t3^(x^2); t4 = xx * t3^(p^2) * t3^{-1} (conj = inverse)."""
    return T.f12_mul(
        T.f12_mul(xx, T.f12_frobenius(T.f12_frobenius(t3))),
        T.f12_conj(t3))


@kjit
def _j_final_combine(t4, g):
    out = T.f12_mul(t4, T.f12_mul(T.f12_cyclotomic_sqr(g), g))
    return T.f12_is_one(out)


def _staged_pow_x(f):
    """f^|x| for cyclotomic f via the run/mul programs."""
    acc = f
    for n, with_mul in _X_SCHEDULE:
        acc = _j_cyc_sqr_run(acc, n)
        if with_mul:
            acc = _j_f12_mul(acc, f)
    return acc


def staged_miller(px, py, q, degenerate):
    """Batched product Miller loop over the leading pairs axis, staged.

    Inputs carry (pairs, batch, ...) leading axes; the pairs axis is
    folded INTO the batch so every stage runs once over pairs*batch
    lanes (full vectorization), then the per-pair results fold with
    n_pairs-1 small f12 products.
    """
    tm = jax.tree_util.tree_map
    npairs = jax.tree_util.tree_leaves(px)[0].shape[0]

    def flat(a):
        return a.reshape((-1,) + a.shape[2:])

    pxf, pyf = tm(flat, px), tm(flat, py)
    qf, df = tm(flat, q), tm(flat, degenerate)
    carry = _j_miller_init(qf)
    for n, with_add in _MILLER_SCHEDULE:
        carry = _j_miller_dbl_run(carry, pxf, pyf, n)
        if with_add:
            carry = _j_miller_add(carry, qf, pxf, pyf)
    f = _j_miller_finish(carry, df)
    fs = tm(lambda a: a.reshape((npairs, a.shape[0] // npairs)
                                + a.shape[1:]), f)
    out = tm(lambda a: a[0], fs)
    for i in range(1, npairs):
        out = _j_f12_mul(out, tm(lambda a, i=i: a[i], fs))
    return out


def staged_final_exp_is_one(f):
    """Staged equivalent of :func:`final_exp_is_one`."""
    g = _staged_easy_part(f)
    t1 = _j_conj(_j_f12_mul(_staged_pow_x(g), g))          # g^(x-1), x<0
    t2 = _j_conj(_j_f12_mul(_staged_pow_x(t1), t1))        # t1^(x-1)
    t3 = _j_hard_combine_t3(t2, _staged_pow_x(t2))
    xx = _j_conj(_staged_pow_x(_j_conj(_staged_pow_x(t3))))
    t4 = _j_hard_combine_t4(t3, xx)
    return _j_final_combine(t4, g)


LANE_BUCKET = 8


def lane_bucket(batch: int) -> int:
    """Power-of-two lane bucket (floor LANE_BUCKET) every staged
    consumer pads its batch axis to - ONE set of compiled programs per
    topology regardless of caller batch.  Identity in numpy-kernel mode
    (eager: no compile to amortize)."""
    from .backend import NUMPY_KERNELS
    if NUMPY_KERNELS:
        return batch
    return max(LANE_BUCKET, 1 << max(0, batch - 1).bit_length())


def pad_axis(a, axis: int, n: int, fill=0):
    """Append ``n`` entries of ``fill`` (scalar or broadcastable row)
    along ``axis``."""
    shape = a.shape[:axis] + (n,) + a.shape[axis + 1:]
    pad = jnp.broadcast_to(jnp.asarray(fill), shape).astype(a.dtype)
    return jnp.concatenate([a, pad], axis=axis)


def staged_product_pairing_check(px, py, q, degenerate):
    """ONE product pairing over a single flat pairs axis: True iff
    ``prod_i e(P_i, Q_i) == 1``.

    Inputs carry one leading ``(n_pairs,)`` axis (no batch axis).  This
    is the RLC batch-verification finisher: a whole block's checks fold
    into one pair list, so unlike :func:`staged_pairing_check` there is
    exactly ONE final exponentiation regardless of how many pairs (the
    lane path pays one per batch element).

    The pairs axis pads to a power-of-two bucket (floor ``LANE_BUCKET``)
    with degenerate pairs so the Miller stages compile once per bucket;
    the per-pair Miller outputs then fold in a log-depth f12 product
    tree (each level one bounded program) down to a single lane for the
    final exp.  Skipped in numpy-kernel mode (eager).
    """
    from .backend import NUMPY_KERNELS
    tm = jax.tree_util.tree_map
    n = jax.tree_util.tree_leaves(px)[0].shape[0]
    # the fold tree needs a power of two even in eager numpy mode (where
    # lane_bucket is the identity and there is no compile to amortize)
    pow2 = 1 if n <= 1 else 1 << (n - 1).bit_length()
    bucket = pow2 if NUMPY_KERNELS else max(lane_bucket(n), pow2)
    if bucket != n:
        pad = lambda a: pad_axis(a, 0, bucket - n)
        px, py, q = tm(pad, px), tm(pad, py), tm(pad, q)
        degenerate = pad_axis(degenerate, 0, bucket - n, fill=True)

    carry = _j_miller_init(q)
    for runs, with_add in _MILLER_SCHEDULE:
        carry = _j_miller_dbl_run(carry, px, py, runs)
        if with_add:
            carry = _j_miller_add(carry, q, px, py)
    f = _j_miller_finish(carry, degenerate)

    m = bucket
    while m > 1:
        m //= 2
        lo = tm(lambda a: a[:m], f)
        hi = tm(lambda a: a[m:2 * m], f)
        f = _j_f12_mul(lo, hi)
    return staged_final_exp_is_one(f)[0]


def staged_pairing_check(px, py, q, degenerate):
    """pairing_check as a pipeline of bounded compiled programs.

    Unlike :func:`pairing_check` the inputs carry (pairs, batch) leading
    axes directly (no outer vmap) - each stage is already batch-shaped.

    The batch axis is padded to a power-of-two lane bucket (floor
    ``LANE_BUCKET``) with degenerate pairs, so every consumer of the
    staged pipeline (the bench batch, the graft-entry compile check, the
    multichip dryrun) hits ONE set of compiled programs - on a slow
    XLA:CPU host the per-shape recompile of the Miller/final-exp stages
    is minutes each (measured round 4).  Skipped in numpy-kernel mode
    (eager: no compile to amortize).
    """
    tm = jax.tree_util.tree_map
    batch = jax.tree_util.tree_leaves(px)[0].shape[1]
    bucket = lane_bucket(batch)
    if bucket != batch:
        pad = lambda a: pad_axis(a, 1, bucket - batch)
        px, py, q = tm(pad, px), tm(pad, py), tm(pad, q)
        degenerate = pad_axis(degenerate, 1, bucket - batch, fill=True)
    out = staged_final_exp_is_one(staged_miller(px, py, q, degenerate))
    return out[:batch]
