"""Extension-field towers Fq2/Fq6/Fq12 over the JAX limb kernels.

Same tower as the python oracle (``ops/bls12_381/fields.py``):
Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3 - xi) with xi = 1+u,
Fq12 = Fq6[w]/(w^2 - v).  Elements are pytrees of Montgomery limb arrays -
Fq2 = (a, b), Fq6 = (c0, c1, c2), Fq12 = (d0, d1) - so ``vmap``/``scan``
thread them transparently and all ops batch over leading dims.
"""
from .backend import xp as jnp, kjit

from consensus_specs_tpu.ops.bls12_381.fields import (
    P, Fq2 as _OFq2, FROB_V1 as _OFROB_V1, FROB_V2 as _OFROB_V2,
    FROB_W as _OFROB_W,
)
from . import limbs as L

# ---------------------------------------------------------------------------
# Fq2: x = (a, b) meaning a + b*u
# ---------------------------------------------------------------------------


def f2(a, b):
    return (a, b)


def f2_const(x: _OFq2):
    """Host-side: oracle Fq2 -> Montgomery limb constant pair."""
    return (jnp.asarray(L.fq_const(x.a.n)), jnp.asarray(L.fq_const(x.b.n)))


def f2_zero_like(x):
    z = jnp.zeros_like(x[0])
    return (z, z)


def f2_one_like(x):
    one = jnp.broadcast_to(jnp.asarray(L.ONE_M), x[0].shape)
    return (one, jnp.zeros_like(x[0]))


def f2_add(x, y):
    return (L.add_mod(x[0], y[0]), L.add_mod(x[1], y[1]))


def f2_sub(x, y):
    return (L.sub_mod(x[0], y[0]), L.sub_mod(x[1], y[1]))


def f2_neg(x):
    return (L.neg_mod(x[0]), L.neg_mod(x[1]))


def f2_mul(x, y):
    # Karatsuba: (a+bu)(c+du) = (ac - bd) + ((a+b)(c+d) - ac - bd) u
    ac = L.mont_mul(x[0], y[0])
    bd = L.mont_mul(x[1], y[1])
    cross = L.mont_mul(L.add_mod(x[0], x[1]), L.add_mod(y[0], y[1]))
    return (L.sub_mod(ac, bd), L.sub_mod(L.sub_mod(cross, ac), bd))


def f2_sqr(x):
    # (a+bu)^2 = (a+b)(a-b) + 2ab u
    re = L.mont_mul(L.add_mod(x[0], x[1]), L.sub_mod(x[0], x[1]))
    im = L.mont_mul(x[0], x[1])
    return (re, L.add_mod(im, im))


def f2_mul_fq(x, s):
    """Multiply by an Fq element (limb array)."""
    out = L.mont_mul_many([(x[0], s), (x[1], s)])
    return (out[0], out[1])


def f2_muli(x, k: int):
    """Multiply by a small integer constant."""
    c = jnp.broadcast_to(jnp.asarray(L.fq_const(k)), x[0].shape)
    return f2_mul_fq(x, c)


def f2_conj(x):
    return (x[0], L.neg_mod(x[1]))


def f2_mul_xi(x):
    """Multiply by xi = 1 + u: (a - b) + (a + b) u."""
    return (L.sub_mod(x[0], x[1]), L.add_mod(x[0], x[1]))


def f2_inv(x):
    # 1/(a+bu) = (a - bu) / (a^2 + b^2)
    norm = L.add_mod(L.mont_sqr(x[0]), L.mont_sqr(x[1]))
    ninv = L.inv_mod(norm)
    return (L.mont_mul(x[0], ninv), L.neg_mod(L.mont_mul(x[1], ninv)))


def f2_is_zero(x):
    return L.is_zero(x[0]) & L.is_zero(x[1])


def f2_eq(x, y):
    return L.eq(x[0], y[0]) & L.eq(x[1], y[1])


def f2_select(cond, x, y):
    return (L.select(cond, x[0], y[0]), L.select(cond, x[1], y[1]))


def f2_is_square(x):
    """Euler criterion via the norm map: a+bu square iff N = a^2+b^2 is a QR."""
    norm = L.add_mod(L.mont_sqr(x[0]), L.mont_sqr(x[1]))
    return L.legendre_is_qr(norm)


def f2_sqrt(x):
    """Branchless sqrt in Fq2 (complex method, p = 3 mod 4).

    Caller must know x is a square (use :func:`f2_is_square`); for
    non-squares the result is unspecified.  Mirrors the oracle
    (``fields.py:138-166``) with selects instead of branches.
    """
    a, b = x
    # generic path (b != 0): alpha = sqrt(a^2+b^2); delta = (a+alpha)/2
    norm = L.add_mod(L.mont_sqr(a), L.mont_sqr(b))
    alpha = L.sqrt_candidate(norm)
    inv2 = jnp.broadcast_to(jnp.asarray(L.fq_const(pow(2, -1, P))), a.shape)
    delta1 = L.mont_mul(L.add_mod(a, alpha), inv2)
    delta2 = L.mont_mul(L.sub_mod(a, alpha), inv2)
    # one stacked chain covers both deltas AND the b == 0 path (sqrt(a)
    # directly, or sqrt(-a)*u when a is a non-residue) - the exponent is
    # shared, so the four candidates ride one scan
    roots = L.sqrt_candidate(jnp.stack(
        [delta1, delta2, a, L.neg_mod(a)]))
    x1, x2c, ra, rb = roots[0], roots[1], roots[2], roots[3]
    use1 = L.eq(L.mont_sqr(x1), delta1)
    xr = L.select(use1, x1, x2c)
    yr = L.mont_mul(b, L.inv_mod(L.add_mod(xr, xr)))
    a_is_qr = L.eq(L.mont_sqr(ra), a)
    b0_re = L.select(a_is_qr, ra, jnp.zeros_like(ra))
    b0_im = L.select(a_is_qr, jnp.zeros_like(rb), rb)
    b_zero = L.is_zero(b)
    return (L.select(b_zero, b0_re, xr), L.select(b_zero, b0_im, yr))


# ---------------------------------------------------------------------------
# Staged Fq2 inversion / sqrt: the expensive fixed-exponent powers inside
# f2_inv/f2_sqrt/f2_is_square dispatch through the SHARED ladder program
# (``limbs._j_pow_windows``) instead of inlining their own scan bodies;
# only the cheap glue compiles per call site.  Use these from host-
# orchestrated staged pipelines; the in-trace f2_inv/f2_sqrt above remain
# for code that is compiled as one program anyway.
# ---------------------------------------------------------------------------

@kjit
def _j_f2_norm(x):
    """a^2 + b^2 - the Fq norm every Fq2 inv/sqrt/Legendre reduces to."""
    return L.add_mod(L.mont_sqr(x[0]), L.mont_sqr(x[1]))


@kjit
def _j_f2_inv_post(x, ninv):
    """(a, b), 1/(a^2+b^2) -> (a*ninv, -b*ninv)."""
    m = L.mont_mul_many([(x[0], ninv), (x[1], ninv)])
    return (m[0], L.neg_mod(m[1]))


def staged_f2_inv(x):
    """f2_inv as [tiny norm] -> [shared ladder] -> [tiny combine]."""
    ninv = L.pow_windows_staged(_j_f2_norm(x), L.INV_WINDOWS)
    return _j_f2_inv_post(x, ninv)


@kjit
def _j_sqrt_stack(x, alpha):
    """Candidates whose shared-exponent roots cover every sqrt branch:
    stacks (delta1, delta2, a, -a) on a new leading axis."""
    a, b = x
    inv2 = jnp.broadcast_to(jnp.asarray(L.fq_const(pow(2, -1, P))), a.shape)
    d = L.mont_mul_many([(L.add_mod(a, alpha), inv2),
                         (L.sub_mod(a, alpha), inv2)])
    return jnp.stack([d[0], d[1], a, L.neg_mod(a)])


@kjit
def _j_sqrt_sel(stacked, roots):
    """Pick xr from the two delta roots; return (xr, 2*xr)."""
    x1, x2c = roots[0], roots[1]
    use1 = L.eq(L.mont_sqr(x1), stacked[0])
    xr = L.select(use1, x1, x2c)
    return xr, L.add_mod(xr, xr)


@kjit
def _j_sqrt_final(x, roots, xr, den_inv):
    """Assemble the Fq2 root, covering the b == 0 branch."""
    a, b = x
    ra, rb = roots[2], roots[3]
    yr = L.mont_mul(b, den_inv)
    a_is_qr = L.eq(L.mont_sqr(ra), a)
    b0_re = L.select(a_is_qr, ra, jnp.zeros_like(ra))
    b0_im = L.select(a_is_qr, jnp.zeros_like(rb), rb)
    b_zero = L.is_zero(b)
    return (L.select(b_zero, b0_re, xr), L.select(b_zero, b0_im, yr))


def staged_f2_sqrt(x):
    """f2_sqrt as a pipeline over the shared ladder (same math/branches
    as :func:`f2_sqrt`; caller must know x is a square)."""
    norm = _j_f2_norm(x)
    alpha = L.pow_windows_staged(norm, L.SQRT_WINDOWS)
    stacked = _j_sqrt_stack(x, alpha)
    roots = L.pow_windows_staged(stacked, L.SQRT_WINDOWS)
    xr, den = _j_sqrt_sel(stacked, roots)
    den_inv = L.pow_windows_staged(den, L.INV_WINDOWS)
    return _j_sqrt_final(x, roots, xr, den_inv)


# ---------------------------------------------------------------------------
# Batched Fq2 ops: k independent ops -> constant number of kernel calls.
# These are what the Fq6/Fq12 multiplies and the pairing step "waves" use;
# without them every tower multiply would emit hundreds of tiny scans
# (slow to compile on the 1-core box, and narrow on the TPU VPU).
# ---------------------------------------------------------------------------

def f2_add_many(pairs):
    flat = L.add_mod_many([(x[0], y[0]) for x, y in pairs]
                          + [(x[1], y[1]) for x, y in pairs])
    k = len(pairs)
    return [(flat[i], flat[k + i]) for i in range(k)]


def f2_sub_many(pairs):
    flat = L.sub_mod_many([(x[0], y[0]) for x, y in pairs]
                          + [(x[1], y[1]) for x, y in pairs])
    k = len(pairs)
    return [(flat[i], flat[k + i]) for i in range(k)]


def f2_mul_many(pairs):
    """Karatsuba over the whole batch: 3k base muls in one kernel call."""
    k = len(pairs)
    sums = L.add_mod_many([(x[0], x[1]) for x, _ in pairs]
                          + [(y[0], y[1]) for _, y in pairs])
    reqs = []
    for i, (x, y) in enumerate(pairs):
        reqs += [(x[0], y[0]), (x[1], y[1]), (sums[i], sums[k + i])]
    prods = L.mont_mul_many(reqs)
    # re = ac - bd ; im = cross - ac - bd
    d = L.sub_mod_many([(prods[3 * i], prods[3 * i + 1]) for i in range(k)]
                       + [(prods[3 * i + 2], prods[3 * i]) for i in range(k)])
    im = L.sub_mod_many([(d[k + i], prods[3 * i + 1]) for i in range(k)])
    return [(d[i], im[i]) for i in range(k)]


def f2_sqr_many(xs):
    """(a+b)(a-b), 2ab batched: 2k base muls in one call."""
    k = len(xs)
    sums = L.add_mod_many([(x[0], x[1]) for x in xs])
    difs = L.sub_mod_many([(x[0], x[1]) for x in xs])
    prods = L.mont_mul_many([(sums[i], difs[i]) for i in range(k)]
                            + [(x[0], x[1]) for x in xs])
    ims = L.add_mod_many([(prods[k + i], prods[k + i]) for i in range(k)])
    return [(prods[i], ims[i]) for i in range(k)]


# ---------------------------------------------------------------------------
# Fq6: x = (c0, c1, c2) meaning c0 + c1 v + c2 v^2
# ---------------------------------------------------------------------------

def f6_zero_like(x):
    z = f2_zero_like(x[0])
    return (z, z, z)


def f6_one_like(x):
    return (f2_one_like(x[0]), f2_zero_like(x[0]), f2_zero_like(x[0]))


def f6_add(x, y):
    return tuple(f2_add(a, b) for a, b in zip(x, y))


def f6_sub(x, y):
    return tuple(f2_sub(a, b) for a, b in zip(x, y))


def f6_neg(x):
    return tuple(f2_neg(a) for a in x)


def f6_mul_many(pairs):
    """Toom/Karatsuba Fq6 products, all 6k Fq2 muls in one batched call."""
    k = len(pairs)
    # pre-sums: (a1+a2, a0+a1, a0+a2) and same for b, per pair
    pre = []
    for x, y in pairs:
        pre += [(x[1], x[2]), (x[0], x[1]), (x[0], x[2]),
                (y[1], y[2]), (y[0], y[1]), (y[0], y[2])]
    s = f2_add_many(pre)
    reqs = []
    for i, (x, y) in enumerate(pairs):
        a12, a01, a02, b12, b01, b02 = s[6 * i: 6 * i + 6]
        reqs += [(x[0], y[0]), (x[1], y[1]), (x[2], y[2]),
                 (a12, b12), (a01, b01), (a02, b02)]
    m = f2_mul_many(reqs)
    # combination, fully batched:
    #   c0 = t0 + xi(m12 - t1 - t2)
    #   c1 = (m01 - t0 - t1) + xi(t2)
    #   c2 = (m02 - t0 - t2) + t1
    r = f2_sub_many([(m[6 * i + 3], m[6 * i + 1]) for i in range(k)]
                    + [(m[6 * i + 4], m[6 * i]) for i in range(k)]
                    + [(m[6 * i + 5], m[6 * i]) for i in range(k)])
    u = f2_sub_many([(r[i], m[6 * i + 2]) for i in range(k)]
                    + [(r[k + i], m[6 * i + 1]) for i in range(k)]
                    + [(r[2 * k + i], m[6 * i + 2]) for i in range(k)])
    # xi(x) = (x0 - x1, x0 + x1), batched over the u's and t2's
    xire = L.sub_mod_many([(u[i][0], u[i][1]) for i in range(k)]
                          + [(m[6 * i + 2][0], m[6 * i + 2][1]) for i in range(k)])
    xiim = L.add_mod_many([(u[i][0], u[i][1]) for i in range(k)]
                          + [(m[6 * i + 2][0], m[6 * i + 2][1]) for i in range(k)])
    fin = f2_add_many(
        [(m[6 * i], (xire[i], xiim[i])) for i in range(k)]
        + [(u[k + i], (xire[k + i], xiim[k + i])) for i in range(k)]
        + [(u[2 * k + i], m[6 * i + 1]) for i in range(k)])
    return [(fin[i], fin[k + i], fin[2 * k + i]) for i in range(k)]


def f6_mul(x, y):
    return f6_mul_many([(x, y)])[0]


def f6_sqr(x):
    return f6_mul(x, x)


def f6_mul_f2(x, s):
    return tuple(f2_mul(a, s) for a in x)


def f6_mul_by_v(x):
    return (f2_mul_xi(x[2]), x[0], x[1])


def f6_inv(x):
    a0, a1, a2 = x
    m = f2_mul_many([(a0, a0), (a1, a1), (a2, a2),
                     (a1, a2), (a0, a1), (a0, a2)])
    sq0, sq1, sq2, m12, m01, m02 = m
    t = f2_sub_many([(sq0, f2_mul_xi(m12)),
                     (f2_mul_xi(sq2), m01),
                     (sq1, m02)])
    t0, t1, t2 = t
    d = f2_mul_many([(a0, t0), (a2, t1), (a1, t2)])
    det = f2_add(d[0], f2_add(f2_mul_xi(d[1]), f2_mul_xi(d[2])))
    dinv = f2_inv(det)
    out = f2_mul_many([(t0, dinv), (t1, dinv), (t2, dinv)])
    return tuple(out)


def f6_select(cond, x, y):
    return tuple(f2_select(cond, a, b) for a, b in zip(x, y))


# Frobenius constants (derived by the oracle at import, converted to limbs).
def _frob_consts():
    return (f2_const(_OFROB_V1), f2_const(_OFROB_V2), f2_const(_OFROB_W),
            f2_const(_OFROB_V1 * _OFROB_W), f2_const(_OFROB_V2 * _OFROB_W))


_FROB_V1, _FROB_V2, _FROB_W, _FROB_V1W, _FROB_V2W = _frob_consts()


def f6_frobenius(x):
    return (f2_conj(x[0]),
            f2_mul(f2_conj(x[1]), _bc2(_FROB_V1, x[1])),
            f2_mul(f2_conj(x[2]), _bc2(_FROB_V2, x[2])))


def f2_broadcast(const_pair, like):
    """Broadcast a constant Fq2 pair against a batched element."""
    return (jnp.broadcast_to(const_pair[0], like[0].shape),
            jnp.broadcast_to(const_pair[1], like[1].shape))


_bc2 = f2_broadcast


# ---------------------------------------------------------------------------
# Fq12: x = (d0, d1) meaning d0 + d1 w
# ---------------------------------------------------------------------------

def f12_zero_like(x):
    z = f6_zero_like(x[0])
    return (z, z)


def f12_one_like(x):
    return (f6_one_like(x[0]), f6_zero_like(x[0]))


def f12_mul(x, y):
    a0, a1 = x
    b0, b1 = y
    sa = f2_add_many(list(zip(a0, a1)))
    sb = f2_add_many(list(zip(b0, b1)))
    t0, t1, tc = f6_mul_many([(a0, b0), (a1, b1), (tuple(sa), tuple(sb))])
    c0 = f6_add(t0, f6_mul_by_v(t1))
    c1 = f6_sub(f6_sub(tc, t0), t1)
    return (c0, c1)


def f12_sqr(x):
    """Complex squaring over Fq6: (a + bw)^2 with w^2 = v.

    c0 = (a + b)(a + vb) - ab - v*ab, c1 = 2ab — two Fq6 products instead
    of f12_mul's three.  Pre/post adds are wave-batched (XLA:CPU compile
    time is ~linear in the number of carry networks, so every group of
    independent adds must ride one batched call).
    """
    a, b = x
    vb = f6_mul_by_v(b)
    pre = f2_add_many(list(zip(a, b)) + list(zip(a, vb)))
    m0, m1 = f6_mul_many([(tuple(pre[:3]), tuple(pre[3:])), (a, b)])
    vm1 = f6_mul_by_v(m1)
    d = f2_sub_many([(m0[i], m1[i]) for i in range(3)])
    c0 = f2_sub_many([(d[i], vm1[i]) for i in range(3)])
    c1 = f2_add_many([(m1[i], m1[i]) for i in range(3)])
    return (tuple(c0), tuple(c1))


def f12_cyclotomic_sqr(x):
    """Granger-Scott squaring for elements of the cyclotomic subgroup
    (anything that has been through the final-exp easy part): 9 Fq2
    squarings total vs 12 Fq2 products for a generic f12_sqr.

    Coordinates (x0..x5) = (c0.c0, c0.c1, c0.c2, c1.c0, c1.c1, c1.c2);
    the three Fq4 sub-squarings pair them as (x0, x4), (x3, x2), (x1, x5)
    with v the Fq4 non-residue and xi the Fq2 one.  All combination
    adds/subs run as four batched waves.
    """
    (x0, x1, x2), (x3, x4, x5) = x
    pre = f2_add_many([(x0, x4), (x3, x2), (x1, x5)])
    sq = f2_sqr_many([x0, x4, x3, x2, x1, x5] + pre)
    s0, s4, s3, s2, s1, s5, s04, s32, s15 = sq
    # wave A: xi multiples ride raw limb batches; pair sums for the 2ab
    # terms.  xi(a+bu) = (a-b) + (a+b)u.
    wa_add = L.add_mod_many([
        (s4[0], s4[1]), (s2[0], s2[1]), (s5[0], s5[1]),   # xi(s4,s2,s5).im
        (s0[0], s4[0]), (s3[0], s2[0]), (s1[0], s5[0]),   # (s+s').re
        (s0[1], s4[1]), (s3[1], s2[1]), (s1[1], s5[1]),   # (s+s').im
    ])
    wa_sub = L.sub_mod_many([
        (s4[0], s4[1]), (s2[0], s2[1]), (s5[0], s5[1]),   # xi(s4,s2,s5).re
    ])
    xi4 = (wa_sub[0], wa_add[0])
    xi2 = (wa_sub[1], wa_add[1])
    xi5 = (wa_sub[2], wa_add[2])
    # wave B: t0/t2/t4 = s + xi(s'); t1/t3/t5 = s'' - (s + s')
    tb_add = f2_add_many([(s0, xi4), (s3, xi2), (s1, xi5)])
    t0, t2, t4 = tb_add
    tb_sub = f2_sub_many([
        (s04, (wa_add[3], wa_add[6])),
        (s32, (wa_add[4], wa_add[7])),
        (s15, (wa_add[5], wa_add[8]))])
    t1, t3, t5 = tb_sub
    # xi(t5) for z3
    xt5 = (L.sub_mod_many([(t5[0], t5[1])])[0],
           L.add_mod_many([(t5[0], t5[1])])[0])
    # wave C: d = t -/+ x (z = 2d + t)
    wc = f2_sub_many([(t0, x0), (t2, x1), (t4, x2)]) \
        + f2_add_many([(xt5, x3), (t1, x4), (t3, x5)])
    # wave D: z = (d + d) + t
    dd = f2_add_many([(w, w) for w in wc])
    fin = f2_add_many(list(zip(dd, [t0, t2, t4, xt5, t1, t3])))
    z0, z1, z2, z3, z4, z5 = fin
    return ((z0, z1, z2), (z3, z4, z5))


def f12_conj(x):
    return (x[0], f6_neg(x[1]))


def f12_inv(x):
    t = f6_inv(f6_sub(f6_sqr(x[0]), f6_mul_by_v(f6_sqr(x[1]))))
    return (f6_mul(x[0], t), f6_neg(f6_mul(x[1], t)))


def f12_frobenius(x):
    a, b = x
    v1 = _bc2(_FROB_V1, a[1])
    v2 = _bc2(_FROB_V2, a[2])
    w = _bc2(_FROB_W, b[0])
    ac = tuple(f2_conj(c) for c in a)
    bc = tuple(f2_conj(c) for c in b)
    m = f2_mul_many([(ac[1], v1), (ac[2], v2),
                     (bc[0], w), (bc[1], _bc2(_FROB_V1W, b[1])),
                     (bc[2], _bc2(_FROB_V2W, b[2]))])
    return ((ac[0], m[0], m[1]), (m[2], m[3], m[4]))


def f12_eq(x, y):
    out = None
    for a, b in zip(_flatten12(x), _flatten12(y)):
        e = L.eq(a, b)
        out = e if out is None else (out & e)
    return out


def f12_is_one(x):
    return f12_eq(x, f12_one_like(x))


def f12_select(cond, x, y):
    return ((f2_select(cond, x[0][0], y[0][0]),
             f2_select(cond, x[0][1], y[0][1]),
             f2_select(cond, x[0][2], y[0][2])),
            (f2_select(cond, x[1][0], y[1][0]),
             f2_select(cond, x[1][1], y[1][1]),
             f2_select(cond, x[1][2], y[1][2])))


def _flatten12(x):
    for six in x:
        for two in six:
            for limb in two:
                yield limb


# Host-side conversion oracle <-> limbs, for tests and constants.
def f12_const(x):
    """Oracle Fq12 -> limb pytree."""
    return (tuple(f2_const(c) for c in (x.c0.c0, x.c0.c1, x.c0.c2)),
            tuple(f2_const(c) for c in (x.c1.c0, x.c1.c1, x.c1.c2)))


def f12_to_oracle(x):
    """Limb pytree (unbatched) -> oracle Fq12."""
    from consensus_specs_tpu.ops.bls12_381.fields import Fq2, Fq6, Fq12
    vals = [L.unpack_mont(a)[0] for a in _flatten12(x)]
    f2s = [Fq2(vals[i], vals[i + 1]) for i in range(0, 12, 2)]
    return Fq12(Fq6(*f2s[0:3]), Fq6(*f2s[3:6]))
