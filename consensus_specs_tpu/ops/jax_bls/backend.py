"""Array-backend switch for the BLS12-381 limb kernels: JAX or numpy.

Default is JAX (jnp ops, ``jax.jit``, ``jax.lax`` control flow) - the
TPU path.  Setting ``CS_TPU_NUMPY_KERNELS=1`` BEFORE import selects a
pure-numpy mirror: the same kernel source executes eagerly on numpy
arrays with python-loop shims for scan/fori/cond and identity ``kjit``.

Why this exists: on a weak 1-core host neither XLA:CPU compilation of
the staged pipeline (> 9 min) nor per-op JAX eager dispatch (> 9 min)
fits the driver's multichip-dryrun budget, while the identical limb
arithmetic in vectorized numpy completes in seconds.  The numpy mode
powers the dryrun's documented fallback (real mesh collectives run
compiled/eager in a jax process; the full pairing math is then
cross-checked in a numpy process) and doubles as a fast differential
oracle for kernel tests.

The switch is process-level (import-time): kernels bind their array
namespace once.  Nothing else in the framework flips it at runtime.
"""
import os

import numpy as _np

NUMPY_KERNELS = os.environ.get("CS_TPU_NUMPY_KERNELS") == "1"


if NUMPY_KERNELS:
    xp = _np

    def kjit(fn=None, **kwargs):
        """Identity stand-in for jax.jit (numpy executes eagerly)."""
        if fn is None:
            return lambda f: f
        return fn

    class lax:  # noqa: N801 - mirrors jax.lax's lowercase module name
        @staticmethod
        def scan(f, init, xs, length=None):
            carry = init
            if xs is None:
                n = length
                get = lambda i: None
            else:
                n = len(xs) if isinstance(xs, (list, tuple)) else \
                    _np.asarray(xs).shape[0]
                get = lambda i: xs[i]
            ys = []
            for i in range(n):
                carry, y = f(carry, get(i))
                ys.append(y)
            if not ys or all(y is None for y in ys):
                return carry, None
            import jax.tree_util as tu   # pure-python pytree walk
            stacked = tu.tree_map(lambda *leaves: _np.stack(leaves), *ys)
            return carry, stacked

        @staticmethod
        def fori_loop(lo, hi, body, init):
            val = init
            for i in range(int(lo), int(hi)):
                val = body(i, val)
            return val

        @staticmethod
        def cond(pred, true_fn, false_fn, operand):
            return true_fn(operand) if bool(pred) else false_fn(operand)

    def dot_f32(a, b):
        """f32 matmul (exactness argument in limbs._product_columns)."""
        return _np.dot(a, b)

    def at_set(arr, idx, value):
        out = _np.array(arr)
        out[idx] = value
        return out

    def block_until_ready(x):
        return x
else:
    import jax as _jax
    import jax.numpy as xp  # noqa: F401
    from jax import lax  # noqa: F401

    kjit = _jax.jit

    def dot_f32(a, b):
        return xp.dot(a, b, precision=_jax.lax.Precision.HIGHEST)

    def at_set(arr, idx, value):
        return arr.at[idx].set(value)

    def block_until_ready(x):
        return _jax.block_until_ready(x)
