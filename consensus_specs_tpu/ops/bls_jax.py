"""JAX/TPU BLS backend - the ``bls.use_jax()`` implementation.

Plays the role the milagro/arkworks Rust backends play in the reference
(``tests/core/pyspec/eth2spec/utils/bls.py:22-47``), but as batched XLA
programs: pubkey aggregation is a vectorized tree reduction, hash-to-curve
and the 2-pair product pairing run as one jitted kernel, and a whole
block's worth of aggregate verifications dispatches as a single batch
(``verify_aggregates_batch``).

Division of labor:

- Hot verification paths (``Verify``, ``FastAggregateVerify``,
  ``AggregateVerify`` and their batch forms) run on device.
- Cold/setup paths (``Sign``, ``SkToPk``, ``Aggregate``, ``AggregatePKs``,
  ``KeyValidate``) delegate to the pure-python oracle - same split as the
  reference's ``fastest_bls`` which mixes backends per function
  (``bls.py:35-47``).

Shape discipline: batch and aggregate axes are padded to powers of two so
the number of compiled program variants stays O(log n); padding lanes are
degenerate pairs that contribute the identity to the pairing product.
"""
from collections import OrderedDict

import numpy as np
import jax  # tree_util; array ops ride the kernel backend switch
from consensus_specs_tpu.ops.jax_bls.backend import (
    xp as jnp, kjit, NUMPY_KERNELS)

from consensus_specs_tpu.ops.bls12_381 import ciphersuite as _oracle
from consensus_specs_tpu.utils import profiling
from consensus_specs_tpu.utils.profiling import span
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, G2Point, G1_GENERATOR, g1_from_compressed, g2_from_compressed)
from consensus_specs_tpu.ops.jax_bls import points as PT
from consensus_specs_tpu.ops.jax_bls import pairing as PR
from consensus_specs_tpu.ops.jax_bls import htc as HTC
from consensus_specs_tpu.utils import env_flags


def _profile_sync(tree):
    """Drain device work at a stage boundary, but ONLY while profiling —
    unconditional blocking would serialize the async dispatch pipeline
    the staged TPU path relies on."""
    if profiling.is_enabled():
        jax.block_until_ready(tree)

# Cold-path delegation (oracle)
Sign = _oracle.Sign
SkToPk = _oracle.SkToPk
Aggregate = _oracle.Aggregate
AggregatePKs = _oracle.AggregatePKs
KeyValidate = _oracle.KeyValidate

# ---------------------------------------------------------------------------
# Host-side decompression caches.  Pubkeys repeat across blocks/epochs (the
# validator registry), so decompression + subgroup checking is amortized -
# the reference gets the same effect from LRU caches around bytes48_to_G1.
# ---------------------------------------------------------------------------

class _LRU(OrderedDict):
    """Tiny bounded cache (reference analog: the C lru-dict the spec builder
    injects, ``pysetup/spec_builders/phase0.py:47-105``)."""

    def __init__(self, maxsize):
        super().__init__()
        self.maxsize = maxsize

    def put(self, key, value):
        self[key] = value
        self.move_to_end(key)
        if len(self) > self.maxsize:
            self.popitem(last=False)


# Pubkeys are bounded by the validator registry; signatures are unique per
# message so their cache mainly serves immediate re-verification.  One
# cache entry per pubkey holds BOTH views — the oracle G1Point and the
# lazily-packed Montgomery limb rows the device path stacks — so the
# registry is never resident twice.
_g1_cache = _LRU(1 << 21)
_g2_cache = _LRU(1 << 14)


def _g1_entry(data: bytes):
    """bytes48 -> [G1Point|None, packed|None] (KeyValidate semantics:
    non-canonical, off-curve, out-of-subgroup and identity are None)."""
    key = bytes(data)
    if key not in _g1_cache:
        try:
            pt = g1_from_compressed(key)
            ok = (not pt.infinity) and pt.in_subgroup()
            _g1_cache.put(key, [pt if ok else None, None])
        except Exception:
            _g1_cache.put(key, [None, None])
    return _g1_cache[key]


def _decompress_g1(data: bytes):
    """bytes48 -> G1Point or None if invalid per KeyValidate."""
    return _g1_entry(data)[0]


def _decompress_g2(data: bytes):
    """bytes96 -> G2Point (subgroup-checked; infinity allowed - the pairing
    handles it as a degenerate pair) or None if invalid."""
    key = bytes(data)
    if key not in _g2_cache:
        try:
            pt = g2_from_compressed(key)
            _g2_cache.put(key, pt if pt.in_subgroup() else None)
        except Exception:
            _g2_cache.put(key, None)
    return _g2_cache[key]


def _packed_g1(data: bytes):
    """bytes48 -> (x_limbs, y_limbs) numpy rows (affine, Montgomery) or
    None if the key fails KeyValidate.  The python int->limb conversion
    costs ~50us/point and registry pubkeys repeat across every block, so
    the rows are packed once and cached alongside the point."""
    entry = _g1_entry(data)
    if entry[0] is None:
        return None
    if entry[1] is None:
        entry[1] = PT.g1_pack_affine_rows(entry[0])
    return entry[1]


def _pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


_NEG_G1 = PT.g1_pack([-G1_GENERATOR])

# All batches are chunked to this fixed size so the expensive programs
# (hash-to-curve, pairing) compile exactly once per process regardless of
# caller batch size.  Default: small on host CPU (compile time dominates),
# wide on an accelerator (fill the vector units — a mainnet block carries
# up to 128 aggregates).  Override via env for throughput runs.
def bucket_b() -> int:
    """Resolved lazily at first dispatch: jax.default_backend() initializes
    the backend, which must never happen at import time (a tunnel-backed
    accelerator plugin can hang there)."""
    global _BUCKET_B
    if _BUCKET_B is None:
        raw = env_flags.knob("CS_TPU_BLS_BATCH")
        if raw is not None:
            _BUCKET_B = int(raw)
        elif NUMPY_KERNELS:
            _BUCKET_B = 8
        else:
            try:
                _BUCKET_B = 32 if jax.default_backend() != "cpu" else 8
            except Exception:
                _BUCKET_B = 8
    return _BUCKET_B


_BUCKET_B = None
# Pubkey-aggregation axis buckets (the aggregate program is cheap to
# compile, so power-of-two buckets with a floor are fine).
_N_MIN = 8
# Fuse aggregate+hash-to-curve+pairing into ONE compiled program (single
# dispatch, cross-stage XLA fusion) vs the staged pipeline of bounded
# programs.  Default is backend-dependent: an accelerator (tunnel-backed
# TPU) wants one dispatch — per-stage round trips are latency-bound and
# its compiler handles the monolith; XLA:CPU cannot compile the monolith
# on this 1-core host, so tests/dryrun run staged.  Override with
# CS_TPU_BLS_FUSE=1/0.
def fuse_verify() -> bool:
    global _FUSE_VERIFY
    if _FUSE_VERIFY is None:
        if NUMPY_KERNELS:
            # numpy mode has no fused path: _program_multi_pair_verify's
            # jax.vmap cannot trace numpy-bound kernels
            _FUSE_VERIFY = False
        elif env_flags.knob("CS_TPU_BLS_FUSE") is not None:
            _FUSE_VERIFY = env_flags.knob("CS_TPU_BLS_FUSE") == "1"
        else:
            try:
                _FUSE_VERIFY = jax.default_backend() != "cpu"
            except Exception:
                _FUSE_VERIFY = False
    return _FUSE_VERIFY


_FUSE_VERIFY = None


# ---------------------------------------------------------------------------
# Device programs (jitted once per shape bucket)
# ---------------------------------------------------------------------------

@kjit
def _j_tree_sum(pk_pts):
    """(B, N) projective G1 pytree -> (B,) unnormalized sum; one bounded
    fori_loop program per (B, N) bucket."""
    return PT.g1_tree_sum_batched(pk_pts)


def _j_g1_normalize_flag(p):
    """Normalize + identity flag; the inversion rides the shared ladder
    program (round-4 compile-cost restructuring)."""
    return PT.g1_normalize_flag_staged(p)


def _aggregate_nopad(pk_pts):
    return _j_g1_normalize_flag(_j_tree_sum(pk_pts))


def _program_aggregate(pk_pts):
    """(B, N) projective G1 pytree -> normalized (B,) aggregate + inf
    flag, as bounded programs (sum, then staged normalize).

    The batch axis pads to the shared lane bucket (identity rows) so the
    bench / graft-entry / dryrun consumers hit the same tree-sum and
    normalize compiles.  (The fused monolith uses the nopad variant -
    it compiles per-shape anyway, so padding would only waste lanes.)"""
    b = pk_pts[0].shape[0]
    bucket = PR.lane_bucket(b)
    if bucket != b:
        from consensus_specs_tpu.ops.jax_bls.limbs import ZERO, ONE_M
        n = bucket - b
        pk_pts = (PR.pad_axis(pk_pts[0], 0, n, ZERO),
                  PR.pad_axis(pk_pts[1], 0, n, ONE_M),
                  PR.pad_axis(pk_pts[2], 0, n, ZERO))
    agg, inf = _aggregate_nopad(pk_pts)
    if bucket != b:
        agg = jax.tree_util.tree_map(lambda a: a[:b], agg)
        inf = inf[:b]
    return agg, inf


def _program_g2_normalize(p):
    return PT.g2_normalize_staged(p)


def _htc_nopad(u0, u1):
    return _program_g2_normalize(HTC.map_to_g2_staged(u0, u1))


def _program_htc(u0, u1):
    """hash_to_field outputs -> affine G2 points (B,).

    Staged dispatch (sswu+iso stacked, add+cofactor, normalize): the
    monolithic module compiles pathologically slowly on XLA:CPU; the
    stages are each bounded and individually cached.  The batch axis is
    padded to the shared lane bucket so every consumer hits one set of
    compiled SSWU/ladder programs (see pairing.staged_pairing_check)."""
    b = u0[0].shape[0]
    bucket = PR.lane_bucket(b)
    if bucket != b:
        pad = lambda a: PR.pad_axis(a, 0, bucket - b)
        u0 = (pad(u0[0]), pad(u0[1]))
        u1 = (pad(u1[0]), pad(u1[1]))
    out = _htc_nopad(u0, u1)
    if bucket != b:
        out = jax.tree_util.tree_map(lambda a: a[:b], out)
    return out


@kjit
def _program_multi_pair_verify(px, py, qx0, qx1, qy0, qy1, degen):
    """Batched n-pair product pairing check: (B, n_pairs, ...) inputs.

    THE flagship kernel: one compile per (B, n_pairs) bucket, shared by
    Verify / FastAggregateVerify / AggregateVerify and the batch APIs.
    """
    def one(px, py, a, b, c, d, dg):
        return PR.pairing_check(px, py, ((a, b), (c, d)), dg)
    return jax.vmap(one)(px, py, qx0, qx1, qy0, qy1, degen)


def _agg_verify_body(pk_pts, u0, u1, sig_q, agg_degen, sig_degen,
                     *, aggregate, htc, pair):
    agg, agg_inf = aggregate(pk_pts)
    hpt = htc(u0, u1)
    px = jnp.stack([agg[0], jnp.broadcast_to(_NEG_G1[0][0], agg[0].shape)], axis=1)
    py = jnp.stack([agg[1], jnp.broadcast_to(_NEG_G1[1][0], agg[1].shape)], axis=1)
    qx0 = jnp.stack([hpt[0][0], sig_q[0][0]], axis=1)
    qx1 = jnp.stack([hpt[0][1], sig_q[0][1]], axis=1)
    qy0 = jnp.stack([hpt[1][0], sig_q[1][0]], axis=1)
    qy1 = jnp.stack([hpt[1][1], sig_q[1][1]], axis=1)
    degen = jnp.stack([agg_degen | agg_inf, sig_degen], axis=1)
    return pair(px, py, qx0, qx1, qy0, qy1, degen)


@kjit
def _program_agg_verify_fused(pk_pts, u0, u1, sig_q, agg_degen, sig_degen):
    """Whole FastAggregateVerify batch as ONE compiled program: one
    dispatch, no intermediate host round trips, cross-stage XLA fusion.
    Reuses the staged programs — jit-of-jit inlines during tracing, so the
    math cannot diverge between modes."""
    return _agg_verify_body(
        pk_pts, u0, u1, sig_q, agg_degen, sig_degen,
        aggregate=_aggregate_nopad,      # monolith compiles per shape -
        htc=_htc_nopad,                  # lane padding would waste work
        pair=_program_multi_pair_verify)


def _program_agg_verify(pk_pts, u0, u1, sig_q, agg_degen, sig_degen):
    """Batched FastAggregateVerify.

    Staged mode runs a pipeline of bounded device programs (fast
    compiles on the 1-core host, maximal cross-shape reuse — only the
    aggregation program depends on the per-aggregate pubkey count);
    fused mode compiles the whole thing once and dispatches once (the
    TPU toolchain handles the monolith; XLA:CPU's fusion pass does not).
    """
    if fuse_verify():
        return _program_agg_verify_fused(pk_pts, u0, u1, sig_q, agg_degen,
                                         sig_degen)
    with span("bls.stage.aggregate"):
        agg, agg_inf = _program_aggregate(pk_pts)
        _profile_sync(agg)
    with span("bls.stage.htc"):
        hpt = _program_htc(u0, u1)
        _profile_sync(hpt)
    # assemble (pairs=2, B, ...) inputs for the staged pairing pipeline
    px = jnp.stack([agg[0], jnp.broadcast_to(_NEG_G1[0][0], agg[0].shape)])
    py = jnp.stack([agg[1], jnp.broadcast_to(_NEG_G1[1][0], agg[1].shape)])
    qx0 = jnp.stack([hpt[0][0], sig_q[0][0]])
    qx1 = jnp.stack([hpt[0][1], sig_q[0][1]])
    qy0 = jnp.stack([hpt[1][0], sig_q[1][0]])
    qy1 = jnp.stack([hpt[1][1], sig_q[1][1]])
    degen = jnp.stack([agg_degen | agg_inf, sig_degen])
    with span("bls.stage.pairing"):
        return np.asarray(PR.staged_pairing_check(
            px, py, ((qx0, qx1), (qy0, qy1)), degen))


# ---------------------------------------------------------------------------
# Batch API - the TPU-native entry points
# ---------------------------------------------------------------------------

def verify_aggregates_batch(items) -> list:
    """items: [(pubkeys: list[bytes48], message: bytes, signature: bytes96)].

    One device dispatch for the whole batch - this is what
    ``process_operations`` maps a block's 128 attestations onto.
    """
    if not items:
        return []
    with span("bls.verify_aggregates_batch"):
        return _verify_aggregates_batch(items)


def _verify_aggregates_batch(items) -> list:
    results_host = [None] * len(items)
    rows = []
    for idx, (pubkeys, msg, sig) in enumerate(items):
        pts = [_packed_g1(pk) for pk in pubkeys]
        spt = _decompress_g2(sig)
        if len(pubkeys) == 0 or any(p is None for p in pts) or spt is None:
            results_host[idx] = False
            continue
        rows.append((idx, pts, bytes(msg), spt))
    if not rows:
        return [bool(r) for r in results_host]

    B = bucket_b()
    # A lone wide aggregate (altair's 512-key sync committee) would pad
    # B-1 dead lanes through aggregation+hash-to-curve+pairing — an 8-16x
    # waste exactly where the work per lane is largest.  Give it a 1-lane
    # program set instead; the >=128 floor keeps small single verifies on
    # the shared lane bucket so this adds at most one extra compile set.
    if len(rows) == 1 and _pow2(len(rows[0][1])) >= 128:
        B = 1
    for start in range(0, len(rows), B):
        chunk = rows[start:start + B]
        n_pad = max(_N_MIN, _pow2(max(len(r[1]) for r in chunk)))
        sig_pts, msgs, pk_rows = [], [], []
        for _, pts, msg, spt in chunk:
            pk_rows.append(pts)
            sig_pts.append(spt)
            msgs.append(msg)
        for _ in range(B - len(chunk)):   # degenerate padding rows
            pk_rows.append([])
            sig_pts.append(G2Point.inf())
            msgs.append(b"")

        with span("bls.stage.host_pack"):
            packed = PT.g1_stack_packed(pk_rows, n_pad)
            pk_pts = jax.tree_util.tree_map(
                lambda a: a.reshape((B, n_pad) + a.shape[1:]), packed)
        with span("bls.stage.hash_to_field"):
            u0, u1 = HTC.hash_to_field_host(msgs)
        sig_packed = PT.g2_pack(sig_pts)
        sig_q = (sig_packed[0], sig_packed[1])
        sig_degen = jnp.array([p.infinity for p in sig_pts])
        agg_degen = jnp.array(
            [False] * len(chunk) + [True] * (B - len(chunk)))

        out = np.asarray(_program_agg_verify(
            pk_pts, u0, u1, sig_q, agg_degen, sig_degen))
        for j, (idx, _, _, _) in enumerate(chunk):
            results_host[idx] = bool(out[j])
    return [bool(r) for r in results_host]


def aggregate_verify_batch(items) -> list:
    """items: [(pubkeys, messages, signature)] with distinct messages.

    Each item becomes n+1 pairs: (pk_i, H(m_i)) ... (-G1, sig), padded to a
    power of two with degenerate pairs.
    """
    if not items:
        return []
    results_host = [None] * len(items)
    rows = []
    for idx, (pubkeys, messages, sig) in enumerate(items):
        pts = [_decompress_g1(pk) for pk in pubkeys]
        spt = _decompress_g2(sig)
        if (len(pubkeys) == 0 or len(pubkeys) != len(messages)
                or any(p is None for p in pts) or spt is None):
            results_host[idx] = False
            continue
        rows.append((idx, pts, [bytes(m) for m in messages], spt))
    if not rows:
        return [bool(r) for r in results_host]

    B = bucket_b()
    for start in range(0, len(rows), B):
        chunk = rows[start:start + B]
        npair_pad = max(_N_MIN, _pow2(max(len(r[1]) for r in chunk) + 1))
        all_msgs, g1_rows, g2_sigs, degen_rows = [], [], [], []
        for _, pts, messages, spt in chunk:
            pad = npair_pad - 1 - len(pts)
            g1_rows.append(pts + [G1Point.inf()] * pad + [-G1_GENERATOR])
            all_msgs.extend(messages + [b""] * pad)
            g2_sigs.append(spt)
            degen_rows.append([False] * len(pts) + [True] * pad
                              + [spt.infinity])
        for _ in range(B - len(chunk)):
            g1_rows.append([G1Point.inf()] * npair_pad)
            all_msgs.extend([b""] * (npair_pad - 1))
            g2_sigs.append(G2Point.inf())
            degen_rows.append([True] * npair_pad)

        # hash all messages in one device call, scatter into (B, n-1) slots
        u0, u1 = HTC.hash_to_field_host(all_msgs)
        hpts = _program_g2_normalize(HTC._map_to_g2_jit(u0, u1))
        hx = ((hpts[0][0]).reshape(B, npair_pad - 1, 24),
              (hpts[0][1]).reshape(B, npair_pad - 1, 24))
        hy = ((hpts[1][0]).reshape(B, npair_pad - 1, 24),
              (hpts[1][1]).reshape(B, npair_pad - 1, 24))
        sig_packed = PT.g2_pack(g2_sigs)
        qx0 = jnp.concatenate([hx[0], sig_packed[0][0][:, None]], axis=1)
        qx1 = jnp.concatenate([hx[1], sig_packed[0][1][:, None]], axis=1)
        qy0 = jnp.concatenate([hy[0], sig_packed[1][0][:, None]], axis=1)
        qy1 = jnp.concatenate([hy[1], sig_packed[1][1][:, None]], axis=1)

        packed = PT.g1_pack([p for row in g1_rows for p in row])
        px = packed[0].reshape(B, npair_pad, 24)
        py = packed[1].reshape(B, npair_pad, 24)
        degen = jnp.array(degen_rows)
        # a G1 infinity in a live pair must also degenerate its pair
        inf_mask = np.array([[p.infinity for p in row] for row in g1_rows])
        degen = degen | jnp.asarray(inf_mask)

        if fuse_verify():
            out = np.asarray(_program_multi_pair_verify(
                px, py, qx0, qx1, qy0, qy1, degen))
        else:
            mv = lambda a: jnp.moveaxis(a, 0, 1)   # (B, n_pairs) -> (n_pairs, B)
            out = np.asarray(PR.staged_pairing_check(
                mv(px), mv(py),
                ((mv(qx0), mv(qx1)), (mv(qy0), mv(qy1))), mv(degen)))
        for j, (idx, _, _, _) in enumerate(chunk):
            results_host[idx] = bool(out[j])
    return [bool(r) for r in results_host]


# ---------------------------------------------------------------------------
# RLC combined check - the one-pairing-per-block path.
#
# ``utils/bls.DeferredBatch.flush`` folds a whole block's queued
# FastAggregateVerify checks (plus any deferred raw pairing checks, e.g.
# the Deneb blob-KZG batch) into
#
#   prod_i e(r_i * agg_pk_i, H(m_i)) * e(-G1, sum_i r_i * sig_i) == 1
#
# so the device work is: one batched pubkey aggregation, one batched
# 128-bit G1 scaling, one G2 MSM over the signatures, hash-to-curve, and
# a SINGLE product pairing check (one final exponentiation) - versus one
# full 2-pair pairing check per lane on the per-lane path.
# ---------------------------------------------------------------------------

@kjit
def _j_g1_scale(pts, bits):
    """(B,) packed projective G1 x (B, n_bits) MSB-first bit planes ->
    (B,) scaled points (no reduction - per-lane [r_i]P_i)."""
    return PT.g1_scalar_mul(pts, bits)


@kjit
def _j_g2_scale_sum(sig_pts, bits):
    """(B,) packed projective G2 x (B, n_bits) bits -> sum_i [r_i]Q_i,
    the RLC signature MSM: per-lane double-and-add, log-depth tree sum."""
    return PT.g2_tree_sum(PT.g2_scalar_mul(sig_pts, bits))


def _bits_msb(scalars, n_bits: int) -> np.ndarray:
    """(B,) ints -> (B, n_bits) uint32 MSB-first bit planes.

    Vectorized via unpackbits over big-endian byte rows: this sits in
    the per-block host_pack stage, where a per-bit python loop
    (B x n_bits iterations) would be a fixed serial tax per flush."""
    n_bytes = (n_bits + 7) // 8
    rows = np.frombuffer(
        b"".join(int(s).to_bytes(n_bytes, "big") for s in scalars),
        dtype=np.uint8).reshape(len(scalars), n_bytes)
    bits = np.unpackbits(rows, axis=1)[:, -n_bits:]
    return bits.astype(np.uint32)


RLC_SCALAR_BITS = 128


def rlc_combined_check(pk_rows, msgs, sig_pts, scalars, extra_pairs=(),
                       mesh_devices=None) -> bool:
    """One product pairing for a whole flushed batch.

    ``pk_rows``: per item, the list of packed affine pubkey rows (already
    KeyValidate-checked by the caller); ``msgs``: per-item message bytes;
    ``sig_pts``: per-item oracle G2Points (subgroup-checked; infinity
    allowed); ``scalars``: the per-item 128-bit RLC coefficients;
    ``extra_pairs``: pre-scaled oracle ``(G1Point, G2Point)`` pairs
    appended to the product (deferred raw pairing checks, e.g. the
    blob-KZG batch).  ``mesh_devices``: optional 1D device tuple - the
    signature MSM shards its point axis across it through
    ``parallel.sharded_verify.make_sharded_g2_msm``.
    """
    n = len(pk_rows)
    assert n == len(msgs) == len(sig_pts) and len(scalars) >= n
    px_parts, py_parts = [], []
    qx0_parts, qx1_parts, qy0_parts, qy1_parts = [], [], [], []
    degen_parts = []
    if n:
        bucket = PR.lane_bucket(n)
        npk_pad = max(_N_MIN, _pow2(max(len(r) for r in pk_rows)))
        rows = list(pk_rows) + [[]] * (bucket - n)
        pad_scalars = list(scalars[:n]) + [0] * (bucket - n)

        with span("bls.stage.host_pack"):
            packed = PT.g1_stack_packed(rows, npk_pad)
            pk_pts = jax.tree_util.tree_map(
                lambda a: a.reshape((bucket, npk_pad) + a.shape[1:]), packed)
            sig_packed = PT.g2_pack(list(sig_pts)
                                    + [G2Point.inf()] * (bucket - n))
            bits = jnp.asarray(_bits_msb(pad_scalars, RLC_SCALAR_BITS))

        with span("bls.stage.msm"):
            # pubkey side: per-item aggregate, then the 128-bit scale
            agg = _j_tree_sum(pk_pts)
            aggp, agg_inf = _j_g1_normalize_flag(_j_g1_scale(agg, bits))
            # signature side: the G2 MSM (points-sharded when a mesh is
            # registered; uneven batches pad with identity lanes, so
            # ANY bucket size shards across ANY device count)
            if mesh_devices:
                from consensus_specs_tpu.parallel import sharded_verify
                s_total = sharded_verify.sharded_g2_msm_padded(
                    sig_packed, bits, tuple(mesh_devices))
            else:
                s_total = _j_g2_scale_sum(sig_packed, bits)
            s_total = jax.tree_util.tree_map(lambda a: a[None], s_total)
            s_aff = _program_g2_normalize(s_total)
            s_inf = jnp.asarray(PT.g2_is_identity(s_aff))
            _profile_sync(aggp)

        with span("bls.stage.hash_to_field"):
            u0, u1 = HTC.hash_to_field_host(
                list(msgs) + [b""] * (bucket - n))
        with span("bls.stage.htc"):
            hpt = _program_htc(u0, u1)
            _profile_sync(hpt)

        # flat pairs axis: n item pairs + the folded signature pair
        px_parts += [aggp[0][:n], jnp.asarray(_NEG_G1[0])]
        py_parts += [aggp[1][:n], jnp.asarray(_NEG_G1[1])]
        qx0_parts += [hpt[0][0][:n], s_aff[0][0]]
        qx1_parts += [hpt[0][1][:n], s_aff[0][1]]
        qy0_parts += [hpt[1][0][:n], s_aff[1][0]]
        qy1_parts += [hpt[1][1][:n], s_aff[1][1]]
        degen_parts += [np.asarray(agg_inf)[:n], np.asarray(s_inf)]
    if extra_pairs:
        eg1 = PT.g1_pack([p for p, _ in extra_pairs])
        eg2 = PT.g2_pack([q for _, q in extra_pairs])
        px_parts.append(eg1[0]); py_parts.append(eg1[1])
        qx0_parts.append(eg2[0][0]); qx1_parts.append(eg2[0][1])
        qy0_parts.append(eg2[1][0]); qy1_parts.append(eg2[1][1])
        degen_parts.append(np.array(
            [p.infinity or q.infinity for p, q in extra_pairs]))
    cat = jnp.concatenate
    px = cat([jnp.asarray(a) for a in px_parts])
    py = cat([jnp.asarray(a) for a in py_parts])
    q = ((cat([jnp.asarray(a) for a in qx0_parts]),
          cat([jnp.asarray(a) for a in qx1_parts])),
         (cat([jnp.asarray(a) for a in qy0_parts]),
          cat([jnp.asarray(a) for a in qy1_parts])))
    degen = jnp.asarray(np.concatenate(degen_parts))

    with span("bls.stage.pairing"):
        return bool(np.asarray(
            PR.staged_product_pairing_check(px, py, q, degen)))

# Public staged-program surface (the sharded step in
# consensus_specs_tpu.parallel and the dryrun's numpy cross-check both
# finish through this):
def verify_from_aggregate(total, u0, u1, sig_q, agg_degen, sig_degen):
    """Finish a batched FastAggregateVerify from an UNNORMALIZED projective
    aggregate: normalize, hash-to-curve, 2-pair product pairing check.

    This is the downstream half of the sharded step
    (``parallel.sharded_verify.make_sharded_agg_verify``) and of the
    multichip dryrun's numpy cross-check - one implementation, whichever
    process computed the aggregate."""
    aggp, agg_inf = _j_g1_normalize_flag(total)
    hpt = _program_htc(u0, u1)
    b = aggp[0].shape[:-1]
    px = jnp.stack([aggp[0], jnp.broadcast_to(_NEG_G1[0][0], b + (24,))])
    py = jnp.stack([aggp[1], jnp.broadcast_to(_NEG_G1[1][0], b + (24,))])
    qx = (jnp.stack([hpt[0][0], sig_q[0][0]]),
          jnp.stack([hpt[0][1], sig_q[0][1]]))
    qy = (jnp.stack([hpt[1][0], sig_q[1][0]]),
          jnp.stack([hpt[1][1], sig_q[1][1]]))
    degen = jnp.stack([agg_degen | agg_inf, sig_degen])
    return PR.staged_pairing_check(px, py, (qx, qy), degen)


def FastAggregateVerify(pubkeys, message: bytes, signature: bytes) -> bool:
    return verify_aggregates_batch([(pubkeys, message, signature)])[0]


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    return verify_aggregates_batch([([pubkey], message, signature)])[0]


def AggregateVerify(pubkeys, messages, signature: bytes) -> bool:
    # PoP ciphersuite: no distinct-message requirement (oracle parity,
    # ciphersuite.py AggregateVerify)
    return aggregate_verify_batch([(pubkeys, messages, signature)])[0]
