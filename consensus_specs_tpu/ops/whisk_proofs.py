"""Whisk tracker proofs.

The reference delegates BOTH whisk proof systems to the external
``curdleproofs`` package (``specs/_features/whisk/beacon-chain.md:101``:
"verifier code ... is specified in curdleproofs.pie"); no proof logic
lives in the reference tree.  Here:

- **Opening proofs**: a Chaum-Pedersen DLEQ sigma protocol proving
  knowledge of ``k`` with ``k_r_G == k * r_G`` and
  ``k_commitment == k * G`` (exactly the relation the spec states),
  made non-interactive by Fiat-Shamir over all public inputs.
- **Shuffle proofs**: the zero-knowledge curdleproofs-style argument in
  ``ops/curdleproofs.py`` — the prover shows the post-shuffle trackers
  are a permutation of the pre-shuffle trackers rerandomized by one
  common scalar ``k`` (``post[i] = k * pre[sigma[i]]`` componentwise)
  without revealing ``sigma`` or ``k``.  Log-size (Pedersen
  commitments, grand-product IPA, same-multiscalar folding, DLEQ).

Wire formats (ours; the spec leaves the formats to the proof library):
  opening proof  = A1(48) || A2(48) || s(32)                 = 128 bytes
  shuffle proof  = fixed-size curdleproofs encoding (log2 N rounds; see
                   ``ops/curdleproofs._serialize``)
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, G1_GENERATOR, g1_from_compressed)
from consensus_specs_tpu.ops import curdleproofs

BLS_G1_GENERATOR = G1_GENERATOR.to_compressed()
_DLEQ_DOMAIN = b"whisk-tracker-opening-v1"


def _to_point(b48: bytes) -> G1Point:
    pt = g1_from_compressed(bytes(b48))
    assert pt.in_subgroup()  # spec: subgroup check on deserialization
    return pt


def _challenge(*parts: bytes) -> int:
    return int.from_bytes(hash(_DLEQ_DOMAIN + b"".join(parts)), "big") \
        % R_ORDER


def GenerateWhiskTrackerProof(tracker, k: int, nonce: int = None) -> bytes:
    """DLEQ prove: k_r_G = k*r_G and k_commitment = k*G."""
    r_G = _to_point(tracker.r_G)
    k = int(k) % R_ORDER
    if nonce is None:
        nonce = int.from_bytes(
            hash(b"whisk-nonce" + bytes(tracker.r_G)
                 + k.to_bytes(32, "big")), "big") % R_ORDER
    A1 = r_G.mult(nonce)
    A2 = G1_GENERATOR.mult(nonce)
    k_commitment = G1_GENERATOR.mult(k)
    c = _challenge(bytes(tracker.r_G), bytes(tracker.k_r_G),
                   k_commitment.to_compressed(),
                   A1.to_compressed(), A2.to_compressed())
    s = (nonce + c * k) % R_ORDER
    return A1.to_compressed() + A2.to_compressed() + s.to_bytes(32, "big")


def IsValidWhiskOpeningProof(tracker, k_commitment: bytes,
                             tracker_proof: bytes) -> bool:
    """beacon-chain.md:122 interface — verify knowledge of k."""
    try:
        proof = bytes(tracker_proof)
        if len(proof) != 128:
            return False
        A1 = _to_point(proof[:48])
        A2 = _to_point(proof[48:96])
        s = int.from_bytes(proof[96:128], "big")
        if s >= R_ORDER:
            return False
        r_G = _to_point(tracker.r_G)
        k_r_G = _to_point(tracker.k_r_G)
        k_G = _to_point(k_commitment)
        c = _challenge(bytes(tracker.r_G), bytes(tracker.k_r_G),
                       bytes(k_commitment), proof[:48], proof[48:96])
        # s*r_G == A1 + c*k_r_G  and  s*G == A2 + c*k_G
        return (r_G.mult(s) == A1 + k_r_G.mult(c)
                and G1_GENERATOR.mult(s) == A2 + k_G.mult(c))
    except Exception:
        return False


def GenerateWhiskShuffleProof(pre_shuffle_trackers, permutation,
                              shuffle_scalar) -> tuple:
    """Build (post_shuffle_trackers, proof): post[i] is
    pre[permutation[i]] with both components multiplied by the one
    common ``shuffle_scalar`` (the curdleproofs shuffle relation — a
    common scalar keeps each tracker's ``k`` intact while refreshing
    ``r``), plus the zero-knowledge shuffle proof."""
    n = len(pre_shuffle_trackers)
    assert len(permutation) == n
    k = int(shuffle_scalar) % R_ORDER
    assert k != 0
    R_pts = [_to_point(tr.r_G) for tr in pre_shuffle_trackers]
    S_pts = [_to_point(tr.k_r_G) for tr in pre_shuffle_trackers]
    T_pts = [R_pts[permutation[i]].mult(k) for i in range(n)]
    U_pts = [S_pts[permutation[i]].mult(k) for i in range(n)]
    proof = curdleproofs.prove_shuffle(
        R_pts, S_pts, T_pts, U_pts, list(permutation), k)
    post = [(t.to_compressed(), u.to_compressed())
            for t, u in zip(T_pts, U_pts)]
    return post, proof


def IsValidWhiskShuffleProof(pre_shuffle_trackers, post_shuffle_trackers,
                             shuffle_proof: bytes) -> bool:
    """beacon-chain.md:106 interface — verify post is a rerandomized
    permutation of pre under one common scalar, in zero knowledge."""
    try:
        n = len(pre_shuffle_trackers)
        if len(post_shuffle_trackers) != n:
            return False
        R_pts = [_to_point(tr.r_G) for tr in pre_shuffle_trackers]
        S_pts = [_to_point(tr.k_r_G) for tr in pre_shuffle_trackers]
        T_pts = [_to_point(tr.r_G) for tr in post_shuffle_trackers]
        U_pts = [_to_point(tr.k_r_G) for tr in post_shuffle_trackers]
        return curdleproofs.verify_shuffle(
            R_pts, S_pts, T_pts, U_pts, bytes(shuffle_proof))
    except Exception:
        return False
