"""Whisk tracker proofs.

The reference delegates BOTH whisk proof systems to the external
``curdleproofs`` package (``specs/_features/whisk/beacon-chain.md:101``:
"verifier code ... is specified in curdleproofs.pie"); no proof logic
lives in the reference tree.  Here:

- **Opening proofs are implemented for real**: a Chaum-Pedersen DLEQ
  sigma protocol proving knowledge of ``k`` with ``k_r_G == k * r_G``
  and ``k_commitment == k * G`` (exactly the relation the spec states),
  made non-interactive by Fiat-Shamir over all public inputs.
- **Shuffle proofs use a permutation-rerandomization verifier**: the
  proof reveals the permutation and per-tracker rerandomization scalars
  and the verifier checks ``post[i] == (s_i * pre[pi(i)].r_G,
  s_i * pre[pi(i)].k_r_G)``.  This is *sound* for the shuffle relation
  (post IS a rerandomized permutation of pre) but NOT zero-knowledge —
  a stand-in with the same interface until a curdleproofs IPA port
  lands; the divergence is intentional and documented.

Wire formats (ours; the spec leaves the formats to the proof library):
  opening proof  = A1(48) || A2(48) || s(32)                 = 128 bytes
  shuffle proof  = n * [ pi_i(8, little) || s_i(32, big) ]   = 40n bytes
"""
from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1Point, G1_GENERATOR, g1_from_compressed)

BLS_G1_GENERATOR = G1_GENERATOR.to_compressed()
_DLEQ_DOMAIN = b"whisk-tracker-opening-v1"


def _to_point(b48: bytes) -> G1Point:
    pt = g1_from_compressed(bytes(b48))
    assert pt.in_subgroup()  # spec: subgroup check on deserialization
    return pt


def _challenge(*parts: bytes) -> int:
    return int.from_bytes(hash(_DLEQ_DOMAIN + b"".join(parts)), "big") \
        % R_ORDER


def GenerateWhiskTrackerProof(tracker, k: int, nonce: int = None) -> bytes:
    """DLEQ prove: k_r_G = k*r_G and k_commitment = k*G."""
    r_G = _to_point(tracker.r_G)
    k = int(k) % R_ORDER
    if nonce is None:
        nonce = int.from_bytes(
            hash(b"whisk-nonce" + bytes(tracker.r_G)
                 + k.to_bytes(32, "big")), "big") % R_ORDER
    A1 = r_G.mult(nonce)
    A2 = G1_GENERATOR.mult(nonce)
    k_commitment = G1_GENERATOR.mult(k)
    c = _challenge(bytes(tracker.r_G), bytes(tracker.k_r_G),
                   k_commitment.to_compressed(),
                   A1.to_compressed(), A2.to_compressed())
    s = (nonce + c * k) % R_ORDER
    return A1.to_compressed() + A2.to_compressed() + s.to_bytes(32, "big")


def IsValidWhiskOpeningProof(tracker, k_commitment: bytes,
                             tracker_proof: bytes) -> bool:
    """beacon-chain.md:122 interface — verify knowledge of k."""
    try:
        proof = bytes(tracker_proof)
        if len(proof) != 128:
            return False
        A1 = _to_point(proof[:48])
        A2 = _to_point(proof[48:96])
        s = int.from_bytes(proof[96:128], "big")
        if s >= R_ORDER:
            return False
        r_G = _to_point(tracker.r_G)
        k_r_G = _to_point(tracker.k_r_G)
        k_G = _to_point(k_commitment)
        c = _challenge(bytes(tracker.r_G), bytes(tracker.k_r_G),
                       bytes(k_commitment), proof[:48], proof[48:96])
        # s*r_G == A1 + c*k_r_G  and  s*G == A2 + c*k_G
        return (r_G.mult(s) == A1 + k_r_G.mult(c)
                and G1_GENERATOR.mult(s) == A2 + k_G.mult(c))
    except Exception:
        return False


def GenerateWhiskShuffleProof(pre_shuffle_trackers, permutation,
                              scalars) -> tuple:
    """Build (post_shuffle_trackers, proof) for the stand-in scheme."""
    assert len(permutation) == len(pre_shuffle_trackers) == len(scalars)
    post = []
    proof = bytearray()
    for i, (pi, s) in enumerate(zip(permutation, scalars)):
        s = int(s) % R_ORDER
        assert s != 0
        src = pre_shuffle_trackers[pi]
        post.append((
            _to_point(src.r_G).mult(s).to_compressed(),
            _to_point(src.k_r_G).mult(s).to_compressed()))
        proof += int(pi).to_bytes(8, "little") + s.to_bytes(32, "big")
    return post, bytes(proof)


def IsValidWhiskShuffleProof(pre_shuffle_trackers, post_shuffle_trackers,
                             shuffle_proof: bytes) -> bool:
    """beacon-chain.md:106 interface — verify post is a rerandomized
    permutation of pre (stand-in scheme; see module docstring)."""
    try:
        proof = bytes(shuffle_proof)
        n = len(pre_shuffle_trackers)
        if len(post_shuffle_trackers) != n or len(proof) != 40 * n:
            return False
        seen = set()
        for i in range(n):
            off = 40 * i
            pi = int.from_bytes(proof[off:off + 8], "little")
            s = int.from_bytes(proof[off + 8:off + 40], "big")
            if pi >= n or pi in seen or s == 0 or s >= R_ORDER:
                return False
            seen.add(pi)
            src = pre_shuffle_trackers[pi]
            post = post_shuffle_trackers[i]
            if _to_point(src.r_G).mult(s).to_compressed() \
                    != bytes(post.r_G):
                return False
            if _to_point(src.k_r_G).mult(s).to_compressed() \
                    != bytes(post.k_r_G):
                return False
        return True
    except Exception:
        return False
