"""ctypes binding for the native C BLS12-381 backend.

The CPU-native backend of the module switch (``utils/bls.py``): plays
the role the Rust milagro/arkworks bindings play for the reference
(reference backend ladder: ``tests/core/pyspec/eth2spec/utils/bls.py:30-53``).
Exposes the same 9-function API as the python oracle
(``ops/bls12_381/ciphersuite.py``); the shared library is built from
``csrc/bls12_381.c`` (constants generated from the oracle by
``csrc/gen_bls_consts.py``).

Semantics mirror the oracle exactly: verification functions return
``False`` on any malformed input; ``Aggregate``/``AggregatePKs`` raise
``ValueError`` on empty/invalid input; ``Sign``/``SkToPk`` raise on an
out-of-range secret key.

The library auto-builds on first import when gcc is available (a few
seconds, cached as ``csrc/libcbls12381.so``); set
``CS_TPU_NO_NATIVE_BLS=1`` to disable the backend entirely.
"""
import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Sequence

from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.utils import env_flags

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libcbls12381.so")
_SRC = os.path.join(_CSRC, "bls12_381.c")


def _build() -> bool:
    # compile to a per-process temp name: concurrent builders (parallel
    # pytest/make) each write their own file, and os.replace atomically
    # publishes a COMPLETE library — never interleaved gcc output
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(prefix="libcbls12381.", suffix=".so.tmp",
                                   dir=_CSRC)
        os.close(fd)
        res = subprocess.run(
            ["gcc", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            capture_output=True, timeout=120, cwd=_CSRC)
        if res.returncode != 0:
            return False
        os.replace(tmp, _SO)
        tmp = None
        return True
    except Exception:
        return False
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _discard_corrupt() -> None:
    """Drop a library that failed to load or self-test: leaving it on
    disk would disable the backend on every future import (the staleness
    check sees a fresh .so and never rebuilds)."""
    try:
        os.unlink(_SO)
    except OSError:
        pass


def _load() -> Optional[ctypes.CDLL]:
    if env_flags.knob("CS_TPU_NO_NATIVE_BLS") == "1":
        return None
    deps = [p for p in (_SRC, os.path.join(_CSRC, "bls12_381_consts.h"))
            if os.path.exists(p)]
    stale = (not os.path.exists(_SO)
             or any(os.path.getmtime(p) > os.path.getmtime(_SO)
                    for p in deps))
    if stale and not _build():
        # never serve crypto from a library older than its source — a
        # stale .so passing differential tests would mask the very code
        # it claims to exercise
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _discard_corrupt()
        return None
    u8p, sz = ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t
    protos = {
        "cbls_key_validate": [ctypes.c_char_p],
        "cbls_verify": [ctypes.c_char_p, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_fast_aggregate_verify":
            [ctypes.c_char_p, sz, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_aggregate_verify":
            [ctypes.c_char_p, sz, ctypes.c_char_p,
             ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p],
        "cbls_aggregate_sigs": [ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_aggregate_pks": [ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_sk_to_pk": [ctypes.c_char_p, ctypes.c_char_p],
        "cbls_sign": [ctypes.c_char_p, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_hash_to_g2":
            [ctypes.c_char_p, sz, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_pairing_check": [ctypes.c_char_p, ctypes.c_char_p, sz],
        "cbls_g2_validate": [ctypes.c_char_p],
        "cbls_g1_mult": [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p],
        "cbls_g1_msm": [ctypes.c_char_p, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_g1_msm_pippenger":
            [ctypes.c_char_p, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_g2_msm": [ctypes.c_char_p, ctypes.c_char_p, sz, ctypes.c_char_p],
        "cbls_selftest": [],
    }
    try:
        for name, argtypes in protos.items():
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = ctypes.c_int
        if lib.cbls_selftest() != 1:
            _discard_corrupt()
            return None
    except AttributeError:
        _discard_corrupt()
        return None
    del u8p
    return lib


_lib = _load()


def available() -> bool:
    return _lib is not None


def _req() -> ctypes.CDLL:
    if _lib is None:
        raise RuntimeError("native BLS library unavailable "
                           "(build csrc/libcbls12381.so or unset "
                           "CS_TPU_NO_NATIVE_BLS)")
    return _lib


# ---------------------------------------------------------------------------
# The 9-function backend API (same surface as ops/bls12_381/ciphersuite.py)
# ---------------------------------------------------------------------------

def SkToPk(sk: int) -> bytes:
    if not 0 < sk < (1 << 256):
        raise ValueError("secret key out of range")
    out = ctypes.create_string_buffer(48)
    if _req().cbls_sk_to_pk(sk.to_bytes(32, "big"), out) != 1:
        raise ValueError("secret key out of range")
    return out.raw


def Sign(sk: int, msg: bytes) -> bytes:
    if not 0 < sk < (1 << 256):
        raise ValueError("secret key out of range")
    out = ctypes.create_string_buffer(96)
    if _req().cbls_sign(sk.to_bytes(32, "big"), bytes(msg), len(msg),
                        out) != 1:
        raise ValueError("secret key out of range")
    return out.raw


def KeyValidate(pk: bytes) -> bool:
    pk = bytes(pk)
    if len(pk) != 48:
        return False
    return _req().cbls_key_validate(pk) == 1


def Verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    pk, msg, sig = bytes(pk), bytes(msg), bytes(sig)
    if len(pk) != 48 or len(sig) != 96:
        return False
    return _req().cbls_verify(pk, msg, len(msg), sig) == 1


def FastAggregateVerify(pks: Sequence[bytes], msg: bytes, sig: bytes) -> bool:
    pks = [bytes(p) for p in pks]
    msg, sig = bytes(msg), bytes(sig)
    if not pks or any(len(p) != 48 for p in pks) or len(sig) != 96:
        return False
    return _req().cbls_fast_aggregate_verify(
        b"".join(pks), len(pks), msg, len(msg), sig) == 1


def AggregateVerify(pks: Sequence[bytes], msgs: Sequence[bytes],
                    sig: bytes) -> bool:
    pks = [bytes(p) for p in pks]
    msgs = [bytes(m) for m in msgs]
    sig = bytes(sig)
    if (not pks or len(pks) != len(msgs)
            or any(len(p) != 48 for p in pks) or len(sig) != 96):
        return False
    lens = (ctypes.c_uint64 * len(msgs))(*[len(m) for m in msgs])
    return _req().cbls_aggregate_verify(
        b"".join(pks), len(pks), b"".join(msgs), lens, sig) == 1


def Aggregate(signatures: Sequence[bytes]) -> bytes:
    sigs = [bytes(s) for s in signatures]
    if not sigs:
        raise ValueError("cannot aggregate empty signature list")
    if any(len(s) != 96 for s in sigs):
        raise ValueError("malformed signature length")
    out = ctypes.create_string_buffer(96)
    if _req().cbls_aggregate_sigs(b"".join(sigs), len(sigs), out) != 1:
        raise ValueError("invalid signature in aggregation")
    return out.raw


def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    pks = [bytes(p) for p in pubkeys]
    if not pks:
        raise ValueError("cannot aggregate empty pubkey list")
    if any(len(p) != 48 for p in pks):
        raise ValueError("malformed pubkey length")
    out = ctypes.create_string_buffer(48)
    if _req().cbls_aggregate_pks(b"".join(pks), len(pks), out) != 1:
        raise ValueError("invalid pubkey in aggregation")
    return out.raw


# --------------------------------------------------------------------------
# Extras used by tests / the KZG path
# --------------------------------------------------------------------------

def hash_to_g2_compressed(msg: bytes, dst: bytes) -> bytes:
    out = ctypes.create_string_buffer(96)
    if _req().cbls_hash_to_g2(bytes(msg), len(msg), bytes(dst), len(dst),
                              out) != 1:
        raise ValueError("hash_to_g2 failed")
    return out.raw


def pairing_check_compressed(g1s: Sequence[bytes], g2s: Sequence[bytes]) -> bool:
    """Product pairing check over compressed pairs.  The C side streams
    the Miller accumulations, so a whole RLC-folded block (hundreds of
    pairs) is one call with ONE final exponentiation."""
    g1s, g2s = [bytes(p) for p in g1s], [bytes(q) for q in g2s]
    if (len(g1s) != len(g2s) or len(g1s) > (1 << 16)
            or any(len(p) != 48 for p in g1s)
            or any(len(q) != 96 for q in g2s)):
        raise ValueError("bad pairing-check input")
    return _req().cbls_pairing_check(b"".join(g1s), b"".join(g2s),
                                     len(g1s)) == 1


def g2_validate(sig: bytes) -> bool:
    """decode_sig semantics: decompression ok AND in the r-order
    subgroup (infinity allowed) — the gate signatures must pass before
    entering the (unchecked) ``g2_msm_compressed`` RLC fold."""
    sig = bytes(sig)
    if len(sig) != 96:
        return False
    return _req().cbls_g2_validate(sig) == 1


def g1_msm_affine(points_xy: Sequence[tuple], scalars: Sequence[int]) -> bytes:
    """Pippenger MSM over affine (x, y) int coordinate pairs (infinity =
    (0, 0)); returns the compressed sum.  The arkworks-role hot path for
    ``g1_lincomb`` — raw coordinates skip the per-point decompression
    sqrt."""
    if len(points_xy) != len(scalars):
        raise ValueError("length mismatch")
    buf = b"".join(int(x).to_bytes(48, "big") + int(y).to_bytes(48, "big")
                   for x, y in points_xy)
    # canonical scalar reduction (negative scalars included) — the C
    # side multiplies by the 256-bit value literally
    sc = b"".join((int(s) % R_ORDER).to_bytes(32, "big") for s in scalars)
    out = ctypes.create_string_buffer(48)
    if _req().cbls_g1_msm_pippenger(buf, sc, len(scalars), out) != 1:
        raise ValueError("invalid MSM input")
    return out.raw


def g2_msm_compressed(points: Sequence[bytes], scalars: Sequence[int]) -> bytes:
    pts = [bytes(p) for p in points]
    if len(pts) != len(scalars) or len(pts) > (1 << 16) \
            or any(len(p) != 96 for p in pts):
        raise ValueError("bad G2 MSM input")
    sc = b"".join((int(s) % R_ORDER).to_bytes(32, "big") for s in scalars)
    out = ctypes.create_string_buffer(96)
    if _req().cbls_g2_msm(b"".join(pts), sc, len(pts), out) != 1:
        raise ValueError("invalid G2 MSM input")
    return out.raw


def g1_msm_compressed(points: Sequence[bytes], scalars: Sequence[int]) -> bytes:
    pts = [bytes(p) for p in points]
    if len(pts) != len(scalars) or any(len(p) != 48 for p in pts):
        raise ValueError("bad MSM input")
    out = ctypes.create_string_buffer(48)
    sc = b"".join(int(s).to_bytes(32, "big") for s in scalars)
    if _req().cbls_g1_msm(b"".join(pts), sc, len(pts), out) != 1:
        raise ValueError("invalid MSM input")
    return out.raw
