"""EIP-7594 (PeerDAS) polynomial sampling: FFT, cells, KZG multiproofs,
erasure recovery.

Behavioral parity with
``specs/_features/eip7594/polynomial-commitments-sampling.md`` (cited per
function).  This is the reference's "long-context" axis: a blob is
Reed-Solomon-extended x2 and split into ``CELLS_PER_BLOB`` cells held by
different nodes; any half recovers the original via FFT + vanishing
polynomials (the TPU analog of ring-style sequence distribution —
SURVEY.md §2.4/§5).

The field FFT is implemented iteratively (radix-2, in-place butterflies)
rather than by the spec's recursion — identical outputs, and the
butterfly schedule is the formulation a JAX/limb-kernel port vectorizes.
"""
from typing import Sequence, Tuple

from consensus_specs_tpu.ops.bls12_381.curve import G2Point, g2_from_compressed
from consensus_specs_tpu.ops import kzg as K

BLS_MODULUS = K.BLS_MODULUS

# Preset (polynomial-commitments-sampling.md:76-86)
FIELD_ELEMENTS_PER_CELL = 64
RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"


def _ext_width(setup) -> int:
    return 2 * setup.FIELD_ELEMENTS_PER_BLOB


def cells_per_blob(setup) -> int:
    return _ext_width(setup) // FIELD_ELEMENTS_PER_CELL


def bytes_to_cell(cell_bytes) -> list:
    """md:92 — one cell's worth of Bytes32 -> field elements
    (validated).  Accepts the flat-bytes spec encoding (the markdown
    surface and the corpus format) or the library's legacy list of
    32-byte chunks."""
    if isinstance(cell_bytes, (bytes, bytearray)):
        # exact-length gate, same as the spec body and the engine: a
        # short flat cell would otherwise shrink the extended-domain
        # slice assignment in recovery and fail far from the cause
        assert len(cell_bytes) == 32 * FIELD_ELEMENTS_PER_CELL
        return [K.bytes_to_bls_field(cell_bytes[32 * i:32 * (i + 1)])
                for i in range(FIELD_ELEMENTS_PER_CELL)]
    return [K.bytes_to_bls_field(b) for b in cell_bytes]


def g2_lincomb(points: Sequence[bytes], scalars: Sequence[int]) -> bytes:
    """md:104 — small G2 MSM (vanishing-polynomial commitment); native
    C MSM when present, group-generic Pippenger (``curve.msm``) on the
    python oracle — the PR-6 bucket method replaces the old per-point
    double-and-add loop (same results, fewer group additions)."""
    assert len(points) == len(scalars)
    from consensus_specs_tpu.ops import native_bls
    if native_bls.available() and len(points) <= 64:
        return native_bls.g2_msm_compressed(
            [bytes(p) for p in points],
            [int(a) % BLS_MODULUS for a in scalars])
    from consensus_specs_tpu.ops.bls12_381.curve import msm
    if not points:
        return G2Point.inf().to_compressed()
    return msm([g2_from_compressed(bytes(x)) for x in points],
               [int(a) % BLS_MODULUS for a in scalars]).to_compressed()


# ---------------------------------------------------------------------------
# FFT (md:118-152)
# ---------------------------------------------------------------------------

def _fft_field(vals, roots_of_unity):
    """Iterative radix-2 DIT FFT; output identical to the spec's
    recursion (md:120)."""
    n = len(vals)
    if n == 1:
        return list(vals)
    out = [int(vals[K.reverse_bits(i, n)]) for i in range(n)]
    # roots_of_unity[i] = w^i over the full domain; stage m uses strides
    m = 2
    while m <= n:
        stride = n // m
        half = m // 2
        for start in range(0, n, m):
            for j in range(half):
                w = roots_of_unity[j * stride]
                a = out[start + j]
                b = out[start + j + half] * w % BLS_MODULUS
                out[start + j] = (a + b) % BLS_MODULUS
                out[start + j + half] = (a - b) % BLS_MODULUS
        m *= 2
    return out


def fft_field(vals, roots_of_unity, inv: bool = False):
    """md:137 — forward / inverse FFT over the given root domain."""
    if inv:
        invlen = pow(len(vals), BLS_MODULUS - 2, BLS_MODULUS)
        inv_roots = list(roots_of_unity[0:1]) + list(roots_of_unity[:0:-1])
        return [x * invlen % BLS_MODULUS
                for x in _fft_field(vals, inv_roots)]
    return _fft_field(vals, roots_of_unity)


# ---------------------------------------------------------------------------
# Coefficient-form polynomials (md:154-293)
# ---------------------------------------------------------------------------

def polynomial_eval_to_coeff(polynomial, setup) -> list:
    """md:156 — evaluation form (brp domain) -> coefficient form."""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    roots = list(K.compute_roots_of_unity(width))
    return fft_field(K.bit_reversal_permutation(list(polynomial)), roots,
                     inv=True)


def add_polynomialcoeff(a, b):
    a, b = (a, b) if len(a) >= len(b) else (b, a)
    return [(a[i] + (b[i] if i < len(b) else 0)) % BLS_MODULUS
            for i in range(len(a))]


def neg_polynomialcoeff(a):
    return [(BLS_MODULUS - x) % BLS_MODULUS for x in a]


def multiply_polynomialcoeff(a, b):
    r = [0] * (len(a) + len(b) - 1)
    for power, coef in enumerate(a):
        c = int(coef)
        if c == 0:
            continue
        for j, x in enumerate(b):
            r[power + j] = (r[power + j] + c * int(x)) % BLS_MODULUS
    return r


def divide_polynomialcoeff(a, b):
    """md:205 — long division."""
    a = [int(x) for x in a]
    o = []
    apos = len(a) - 1
    bpos = len(b) - 1
    diff = apos - bpos
    while diff >= 0:
        quot = K.div(a[apos], b[bpos])
        o.insert(0, quot)
        for i in range(bpos, -1, -1):
            a[diff + i] = (a[diff + i] - int(b[i]) * quot) % BLS_MODULUS
        apos -= 1
        diff -= 1
    return [x % BLS_MODULUS for x in o]


def shift_polynomialcoeff(polynomial_coeff, factor):
    """md:227 — g(x) = f(factor * x)... via successive inverse powers."""
    factor_power = 1
    inv_factor = pow(int(factor), BLS_MODULUS - 2, BLS_MODULUS)
    o = []
    for p in polynomial_coeff:
        o.append(int(p) * factor_power % BLS_MODULUS)
        factor_power = factor_power * inv_factor % BLS_MODULUS
    return o


def interpolate_polynomialcoeff(xs, ys):
    """md:244 — Lagrange interpolation in coefficient form."""
    assert len(xs) == len(ys)
    r = [0]
    for i in range(len(xs)):
        summand = [int(ys[i])]
        for j in range(len(ys)):
            if j != i:
                weight_adjustment = K.bls_modular_inverse(
                    (int(xs[i]) - int(xs[j])) % BLS_MODULUS)
                summand = multiply_polynomialcoeff(
                    summand,
                    [(-weight_adjustment * int(xs[j])) % BLS_MODULUS,
                     weight_adjustment])
        r = add_polynomialcoeff(r, summand)
    return r


def vanishing_polynomialcoeff(xs):
    p = [1]
    for x in xs:
        p = multiply_polynomialcoeff(p, [(-int(x)) % BLS_MODULUS, 1])
    return p


def evaluate_polynomialcoeff(polynomial_coeff, z) -> int:
    y = 0
    for coef in reversed(polynomial_coeff):
        y = (y * int(z) + int(coef)) % BLS_MODULUS
    return y


# ---------------------------------------------------------------------------
# KZG multiproofs (md:295-346)
# ---------------------------------------------------------------------------

def compute_kzg_proof_multi_impl(polynomial_coeff, zs,
                                 setup) -> Tuple[bytes, list]:
    """md:299"""
    ys = [evaluate_polynomialcoeff(polynomial_coeff, z) for z in zs]
    interpolation_polynomial = interpolate_polynomialcoeff(zs, ys)
    polynomial_shifted = add_polynomialcoeff(
        polynomial_coeff, neg_polynomialcoeff(interpolation_polynomial))
    denominator_poly = vanishing_polynomialcoeff(zs)
    quotient_polynomial = divide_polynomialcoeff(polynomial_shifted,
                                                 denominator_poly)
    return K.g1_lincomb(
        setup.KZG_SETUP_G1_MONOMIAL[:len(quotient_polynomial)],
        quotient_polynomial), ys


def verify_kzg_proof_multi_impl(commitment, zs, ys, proof, setup) -> bool:
    """md:323 — e(proof, [Z(tau)]G2) == e(C - [I(tau)]G1, G2)."""
    from consensus_specs_tpu.ops.bls12_381.curve import G2_GENERATOR

    assert len(zs) == len(ys)
    zero_poly = g2_lincomb(setup.KZG_SETUP_G2_MONOMIAL[:len(zs) + 1],
                           vanishing_polynomialcoeff(zs))
    interpolated_poly = K.g1_lincomb(
        setup.KZG_SETUP_G1_MONOMIAL[:len(zs)],
        interpolate_polynomialcoeff(zs, ys))
    # K._pairing_check routes through the native C pairing when present
    return K._pairing_check([
        (K._g1_of(proof), g2_from_compressed(zero_poly)),
        (K._g1_of(commitment) + (-K._g1_of(interpolated_poly)),
         -G2_GENERATOR),
    ])


# ---------------------------------------------------------------------------
# Cells (md:348-476)
# ---------------------------------------------------------------------------

def coset_for_cell(cell_id: int, setup) -> list:
    """md:350"""
    assert cell_id < cells_per_blob(setup)
    roots_brp = K.bit_reversal_permutation(
        list(K.compute_roots_of_unity(_ext_width(setup))))
    return roots_brp[FIELD_ELEMENTS_PER_CELL * cell_id:
                     FIELD_ELEMENTS_PER_CELL * (cell_id + 1)]


def compute_cells_and_proofs(blob: bytes, setup):
    """md:368 — all cells + per-cell multiproofs (O(n^2) spec algorithm)."""
    polynomial = K.blob_to_polynomial(bytes(blob),
                                      setup.FIELD_ELEMENTS_PER_BLOB)
    polynomial_coeff = polynomial_eval_to_coeff(polynomial, setup)
    cells, proofs = [], []
    for i in range(cells_per_blob(setup)):
        coset = coset_for_cell(i, setup)
        proof, ys = compute_kzg_proof_multi_impl(polynomial_coeff, coset,
                                                 setup)
        cells.append(ys)
        proofs.append(proof)
    return cells, proofs


def compute_cells(blob: bytes, setup):
    """md:396 — extended evaluations split into cells (no proofs)."""
    width = setup.FIELD_ELEMENTS_PER_BLOB
    polynomial = K.blob_to_polynomial(bytes(blob), width)
    polynomial_coeff = polynomial_eval_to_coeff(polynomial, setup)
    extended_data = fft_field(
        polynomial_coeff + [0] * width,
        list(K.compute_roots_of_unity(_ext_width(setup))))
    extended_data_rbo = K.bit_reversal_permutation(extended_data)
    return [extended_data_rbo[i * FIELD_ELEMENTS_PER_CELL:
                              (i + 1) * FIELD_ELEMENTS_PER_CELL]
            for i in range(cells_per_blob(setup))]


def verify_cell_proof(commitment_bytes, cell_id, cell_bytes, proof_bytes,
                      setup) -> bool:
    """md:417"""
    coset = coset_for_cell(cell_id, setup)
    return verify_kzg_proof_multi_impl(
        K.bytes_to_kzg_commitment(commitment_bytes), coset,
        bytes_to_cell(cell_bytes), K.bytes_to_kzg_proof(proof_bytes), setup)


def verify_cell_proof_batch(row_commitments_bytes, row_ids, column_ids,
                            cells_bytes, proofs_bytes, setup) -> bool:
    """md:438 — per-cell verification over the (row, column) matrix."""
    assert len(cells_bytes) == len(proofs_bytes) == len(row_ids) \
        == len(column_ids)
    commitments = [K.bytes_to_kzg_commitment(row_commitments_bytes[r])
                   for r in row_ids]
    cells = [bytes_to_cell(cb) for cb in cells_bytes]
    proofs = [K.bytes_to_kzg_proof(pb) for pb in proofs_bytes]
    return all(
        verify_kzg_proof_multi_impl(commitment,
                                    coset_for_cell(column_id, setup),
                                    cell, proof, setup)
        for commitment, column_id, cell, proof
        in zip(commitments, column_ids, cells, proofs))


# ---------------------------------------------------------------------------
# Reconstruction (md:478-640)
# ---------------------------------------------------------------------------

def construct_vanishing_polynomial(missing_cell_ids, setup):
    """md:478"""
    n_cells = cells_per_blob(setup)
    roots_of_unity_reduced = list(K.compute_roots_of_unity(n_cells))
    short_zero_poly = vanishing_polynomialcoeff([
        roots_of_unity_reduced[K.reverse_bits(mid, n_cells)]
        for mid in missing_cell_ids])
    zero_poly_coeff = [0] * _ext_width(setup)
    for i, coeff in enumerate(short_zero_poly):
        zero_poly_coeff[i * FIELD_ELEMENTS_PER_CELL] = coeff
    zero_poly_eval = fft_field(
        zero_poly_coeff, list(K.compute_roots_of_unity(_ext_width(setup))))
    zero_poly_eval_brp = K.bit_reversal_permutation(zero_poly_eval)
    for cell_id in range(n_cells):
        start = cell_id * FIELD_ELEMENTS_PER_CELL
        end = (cell_id + 1) * FIELD_ELEMENTS_PER_CELL
        if cell_id in missing_cell_ids:
            assert all(a == 0 for a in zero_poly_eval_brp[start:end])
        else:
            assert all(a != 0 for a in zero_poly_eval_brp[start:end])
    return zero_poly_coeff, zero_poly_eval, zero_poly_eval_brp


def recover_shifted_data(cell_ids, cells, zero_poly_eval, zero_poly_coeff,
                         roots_of_unity_extended, setup):
    """md:519"""
    shift_factor = K.PRIMITIVE_ROOT_OF_UNITY
    shift_inv = K.div(1, shift_factor)

    extended_evaluation_rbo = [0] * _ext_width(setup)
    for cell_id, cell in zip(cell_ids, cells):
        start = cell_id * FIELD_ELEMENTS_PER_CELL
        extended_evaluation_rbo[start:start + FIELD_ELEMENTS_PER_CELL] = cell
    extended_evaluation = K.bit_reversal_permutation(extended_evaluation_rbo)

    extended_evaluation_times_zero = [
        int(a) * int(b) % BLS_MODULUS
        for a, b in zip(zero_poly_eval, extended_evaluation)]
    extended_evaluations_fft = fft_field(extended_evaluation_times_zero,
                                         roots_of_unity_extended, inv=True)
    shifted_extended_evaluation = shift_polynomialcoeff(
        extended_evaluations_fft, shift_factor)
    shifted_zero_poly = shift_polynomialcoeff(zero_poly_coeff, shift_factor)
    eval_shifted_extended_evaluation = fft_field(
        shifted_extended_evaluation, roots_of_unity_extended)
    eval_shifted_zero_poly = fft_field(shifted_zero_poly,
                                       roots_of_unity_extended)
    return (eval_shifted_extended_evaluation, eval_shifted_zero_poly,
            shift_inv)


def recover_original_data(eval_shifted_extended_evaluation,
                          eval_shifted_zero_poly, shift_inv,
                          roots_of_unity_extended):
    """md:560"""
    eval_shifted_reconstructed_poly = [
        K.div(a, b) for a, b in zip(eval_shifted_extended_evaluation,
                                    eval_shifted_zero_poly)]
    shifted_reconstructed_poly = fft_field(eval_shifted_reconstructed_poly,
                                           roots_of_unity_extended, inv=True)
    reconstructed_poly = shift_polynomialcoeff(shifted_reconstructed_poly,
                                               shift_inv)
    return K.bit_reversal_permutation(
        fft_field(reconstructed_poly, roots_of_unity_extended))


def recover_polynomial(cell_ids, cells_bytes, setup):
    """md:586 — recover all evaluations from >=50% of the cells."""
    assert len(cell_ids) == len(cells_bytes)
    n_cells = cells_per_blob(setup)
    # integer form of the spec's >=50% bound (speclint D1002: no float
    # on a consensus path); equivalent for every integer n_cells
    assert n_cells <= 2 * len(cell_ids) and len(cell_ids) <= n_cells
    assert len(cell_ids) == len(set(cell_ids))

    roots_of_unity_extended = list(
        K.compute_roots_of_unity(_ext_width(setup)))
    cells = [bytes_to_cell(cb) for cb in cells_bytes]
    missing_cell_ids = [cid for cid in range(n_cells)
                        if cid not in cell_ids]
    zero_poly_coeff, zero_poly_eval, _ = construct_vanishing_polynomial(
        missing_cell_ids, setup)
    (eval_shifted_extended_evaluation, eval_shifted_zero_poly,
     shift_inv) = recover_shifted_data(
        cell_ids, cells, zero_poly_eval, zero_poly_coeff,
        roots_of_unity_extended, setup)
    reconstructed_data = recover_original_data(
        eval_shifted_extended_evaluation, eval_shifted_zero_poly, shift_inv,
        roots_of_unity_extended)
    for cell_id, cell in zip(cell_ids, cells):
        start = cell_id * FIELD_ELEMENTS_PER_CELL
        assert reconstructed_data[start:start + FIELD_ELEMENTS_PER_CELL] \
            == cell
    return reconstructed_data
