"""Canonical columnar state layer.

``state.arrays`` is the one sanctioned place where SSZ beacon-state
sequences are extracted into (and committed back from) numpy columns.
Engine code in ``ops/``, ``forkchoice/`` and ``utils/ssz/`` reads
through :func:`arrays.of` / :func:`arrays.registry_of` instead of
walking the registry itself (enforced by the speclint S6xx pass).
"""
from . import arrays  # noqa: F401
