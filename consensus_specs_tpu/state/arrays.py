"""Copy-on-write columnar ``StateArrays``: extract once, snapshot
cheaply, replay many.

Three engines used to extract struct-of-arrays views of the same SSZ
beacon state independently — the vectorized epoch engine kept a
root-keyed LRU of registry columns (``ops/epoch_kernels``), the
hash-forest stashed the uint64 columns of its last bulk container-root
build (``utils/ssz/forest``), and proto-array fork choice pulled vote
weights through the epoch engine's cache (``forkchoice/proto_array``).
Each re-keyed by heuristics (roots, weakrefs + generations) and every
state copy or cache eviction paid a fresh O(validators) python pass.

This module promotes the columns to a first-class store attached to the
state object itself:

* **One extraction per state lineage.**  ``of(state)`` returns the
  state's attached :class:`StateArrays`; columns are extracted lazily
  on first access and revalidated *structurally* — every SSZ sequence
  already bumps a mutation generation (``_SequenceBase._gen``) on any
  write through the sequence API, so a column is fresh iff its recorded
  ``(sequence identity, generation)`` still matches.  No root hashing,
  no cache keys, no eviction: the stale-column bug class dies by
  construction.
* **Copy-on-write snapshot/fork.**  :func:`fork_state` copies the SSZ
  state and re-binds the column arrays to the copy without copying
  them.  N concurrent replays (or what-if fork-choice queries) forked
  from one base share one set of arrays; a fork that writes a column
  pays for exactly that column (``registry_writable``), counted in
  ``state_arrays.cow_copies``.
* **One commit per epoch transition.**  Inside a
  :func:`commit_scope` (opened around ``process_epoch`` by the fork
  ladder), engine writes to the balances / inactivity-score columns
  stay in the store and flush back to SSZ chunks once, at scope exit,
  through the chunk-packed ``replace_basic_items(packed=)`` fast path —
  instead of once per sub-transition.  Registry (validator) columns
  commit eagerly: spec helpers outside the engine (sync-committee
  sampling, proposer selection) read effective balances mid-epoch.
* **Shared with merkleization.**  The hash-forest's columnar container
  roots read the store's committed registry columns through
  :func:`peek_registry` (registered as ``forest``'s column provider)
  instead of re-walking the typed views; conversely a forest extraction
  that ran first is adopted by the store (``state_arrays.adoptions``).

``CS_TPU_STATE_ARRAYS=0`` (see ``utils/env_flags.py``) disables the
attached store: ``of`` hands out detached single-use stores, every
access re-extracts, commits are immediate — the slow-but-simple
fallback the differential suites pin against the engine path.

This module is the *host columnar engine* — O(n) numpy passes over
registry columns are its job, and it is the decline target when the
mesh engine's bounded candidate buffers overflow (``mesh.scan_overflow``,
docs/sharding.md).  The speclint N13xx cost pass therefore exempts it
by design (``cost._EXEMPT_RELS``): the O(S)-host-work budget applies to
the ``parallel/`` dispatch paths, not to the fallback that exists
precisely to absorb their declined work.
"""
import weakref
from contextlib import contextmanager

import numpy as np

from consensus_specs_tpu import faults, sanitizer, supervisor
from consensus_specs_tpu.obs import registry as obs_registry
from consensus_specs_tpu.obs.tracing import span
from consensus_specs_tpu.utils import env_flags
from consensus_specs_tpu.utils.ssz import (
    replace_basic_items, sequence_items)
from consensus_specs_tpu.utils.ssz import forest

# ---------------------------------------------------------------------------
# Runtime switch (mirrors epoch_kernels / proto_array)
# ---------------------------------------------------------------------------

_mode = "auto"


def use_arrays() -> None:
    """Force the attached copy-on-write store on."""
    global _mode
    _mode = "on"


def use_fallback() -> None:
    """Force detached single-use stores (the per-call extraction path)."""
    global _mode
    _mode = "off"


def use_auto() -> None:
    """Default policy: on unless ``CS_TPU_STATE_ARRAYS=0``."""
    global _mode
    _mode = "auto"


def enabled() -> bool:
    if _mode == "on":
        return True
    if _mode == "off":
        return False
    return env_flags.switch("CS_TPU_STATE_ARRAYS")


def backend_name() -> str:
    return "state_arrays" if enabled() else "fallback"


# ---------------------------------------------------------------------------
# Metrics (pre-bound series, speclint O5xx hot-path rule)
# ---------------------------------------------------------------------------

_C_HIT = obs_registry.counter("cache.hit").labels(cache="state_arrays")
_C_MISS = obs_registry.counter("cache.miss").labels(cache="state_arrays")
# python-pass column extractions, by column family — the census the
# bench smoke counter-asserts ("no engine re-extracts within an epoch")
_C_X_REG = obs_registry.counter("state_arrays.extracts").labels(
    column="registry")
_C_X_BAL = obs_registry.counter("state_arrays.extracts").labels(
    column="balances")
_C_X_INACT = obs_registry.counter("state_arrays.extracts").labels(
    column="inactivity_scores")
_C_X_PART = obs_registry.counter("state_arrays.extracts").labels(
    column="participation")
# registry extractions satisfied for free from the hash-forest's bulk
# container-root column stash (no python pass)
_C_ADOPTIONS = obs_registry.counter("state_arrays.adoptions").labels()
_C_COMMITS = obs_registry.counter("state_arrays.commits").labels()
_C_COW = obs_registry.counter("state_arrays.cow_copies").labels()
_C_FORKS = obs_registry.counter("state_arrays.forks").labels()
# chunk-packed-commit fallbacks: the per-index write loop taken because
# an injected fault (consensus_specs_tpu/faults.py) failed the batched
# committer.  No organic series: the committer has no guard of its own.
_FALLBACKS = {
    "injected": obs_registry.counter(
        "state_arrays.fallbacks").labels(reason="injected"),
    "deadline": obs_registry.counter(
        "state_arrays.fallbacks").labels(reason="deadline"),
}


# ---------------------------------------------------------------------------
# Column extraction / write-back primitives
# ---------------------------------------------------------------------------

VALIDATOR_DTYPE = np.dtype([
    ("eff", "<u8"),    # effective_balance
    ("aee", "<u8"),    # activation_eligibility_epoch
    ("act", "<u8"),    # activation_epoch
    ("ext", "<u8"),    # exit_epoch
    ("wd", "<u8"),     # withdrawable_epoch
    ("sl", "?"),       # slashed
])

# SSZ Validator field name -> VALIDATOR_DTYPE key
REGISTRY_FIELDS = (
    ("effective_balance", "eff"), ("activation_eligibility_epoch", "aee"),
    ("activation_epoch", "act"), ("exit_epoch", "ext"),
    ("withdrawable_epoch", "wd"), ("slashed", "sl"))


def u64_column(seq) -> np.ndarray:
    """One uint64 column from a basic-element List/Vector."""
    items = sequence_items(seq)
    return np.fromiter(items, dtype=np.uint64, count=len(items))


def _write_u64_list(seq, elem_type, old, new) -> None:
    """Commit a uint64 column back into its SSZ list, matching the spec
    loop's per-index writes bit-for-bit but without its per-index python
    cost.  Few changes -> targeted ``__setitem__`` (keeps the incremental
    chunk tree); registry-wide changes -> wholesale item swap, building
    the element objects through a value-dedup table (epoch deltas are
    highly repetitive: equal-stake validators earn equal rewards) and
    committing chunk-level: the 32-byte leaf chunks are packed straight
    from the column (``new.astype('<u8').tobytes()``) and bulk-fed to
    the tree, so the commit materializes zero per-chunk python work and
    re-hashes through the batched layer path."""
    changed = np.nonzero(old != new)[0]
    if changed.size == 0:
        return
    if changed.size <= max(64, len(old) // 64):
        _write_u64_list_loop(seq, elem_type, old, new)
        return
    vals, inv = np.unique(new, return_inverse=True)
    if vals.size * 4 <= new.size:
        pool = [elem_type(int(v)) for v in vals.tolist()]
        items = [pool[i] for i in inv.tolist()]
    else:
        # int.__new__ skips BasicValue's range re-validation; the values
        # come out of a uint64 array, so the range holds by construction
        items = [int.__new__(elem_type, v) for v in new.tolist()]
    # cooperative deadline boundary: the object-building stage above is
    # the python-heavy part and nothing has been written yet — an armed
    # budget (supervisor.deadline_scope in commit) aborts here into the
    # counted spec-shaped loop write instead of past the point of
    # no return
    supervisor.deadline_check()
    replace_basic_items(seq, items, packed=new.astype("<u8").tobytes())


def _write_u64_list_loop(seq, elem_type, old, new) -> None:
    """The spec-shaped committer: targeted per-index ``__setitem__``
    writes.  Doubles as :func:`_write_u64_list`'s small-diff branch
    (one shared loop, so the two paths cannot drift) and as the
    graceful-degradation leg an injected commit fault forces — the
    path the adversarial harness proves byte-identical."""
    for i in np.nonzero(old != new)[0].tolist():
        seq[i] = elem_type(int(new[i]))


def _gen_of(seq) -> int:
    return getattr(seq, "_gen", 0)


def _extract_registry(seq) -> np.ndarray:
    """The validator registry as one structured array.  First choice:
    adopt the uint64 columns the hash-forest's last columnar root build
    stashed (generation-validated, zero python passes); fallback: a
    single ``np.fromiter`` pass over the typed views."""
    items = sequence_items(seq)
    n = len(items)
    shared = forest.peek_columns(seq)
    if shared is not None and all(f in shared for f, _ in REGISTRY_FIELDS):
        cols = np.empty(n, dtype=VALIDATOR_DTYPE)
        for fname, key in REGISTRY_FIELDS:
            if key == "sl":
                cols[key] = shared[fname] != 0
            else:
                cols[key] = shared[fname]
        _C_ADOPTIONS.add()
        return cols
    cols = np.fromiter(
        ((v.effective_balance, v.activation_eligibility_epoch,
          v.activation_epoch, v.exit_epoch, v.withdrawable_epoch,
          bool(v.slashed)) for v in items),
        dtype=VALIDATOR_DTYPE, count=n)
    _C_X_REG.add()
    return cols


def _extract_u64(counter):
    def extract(seq):
        col = u64_column(seq)
        counter.add()
        return col
    return extract


def _extract_u8(seq) -> np.ndarray:
    items = sequence_items(seq)
    col = np.fromiter(items, dtype=np.uint8, count=len(items))
    _C_X_PART.add()
    return col


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------

class _Cell:
    """One column (or column group) of one SSZ sequence.

    ``base`` is the committed view — it always equals the SSZ content
    as long as ``(seq identity, gen)`` still matches — and is never
    mutated in place, so forks may share it freely.  ``data`` is the
    current value: ``data is base`` means clean; anything else is a
    pending engine write awaiting :meth:`StateArrays.commit`.

    ``shard`` is the mesh engine's device placement for this column
    (``parallel/mesh_state.py``): ``(host_array, placed, epoch)`` where
    ``placed`` is the column padded and ``device_put`` across the
    validator mesh and ``epoch`` is the mesh placement epoch it was
    made under.  Validity is by identity — the placement serves reads
    only while ``shard[0] is cell.data`` and the epoch still matches
    (a device loss bumps the global epoch, retiring every placement on
    the lost mesh at once) — so a kernel write (a new ``data`` array)
    retires it without bookkeeping, and a copy-on-write fork that
    shares ``data`` shares the placement too: N replays forked from one
    base pay ONE host->device transfer per column, and committing a
    scope (``base = data``) never moves data between devices.
    """

    __slots__ = ("data", "base", "seq_ref", "gen", "shard", "__weakref__")

    def __init__(self, data, seq):
        self.data = data
        self.base = data
        self.seq_ref = weakref.ref(seq)
        self.gen = _gen_of(seq)
        self.shard = None


# (name, state field, extractor); participation columns are altair+.
_COLUMNS = {
    "registry": ("validators", _extract_registry),
    "balances": ("balances", _extract_u64(_C_X_BAL)),
    "inactivity_scores": ("inactivity_scores", _extract_u64(_C_X_INACT)),
    "participation_previous": ("previous_epoch_participation", _extract_u8),
    "participation_current": ("current_epoch_participation", _extract_u8),
}

# columns whose engine writes may sit in the store across sub-transitions
# of one commit_scope (registry commits are always eager: spec helpers
# outside the engine read effective balances mid-epoch)
_DEFERRABLE = ("balances", "inactivity_scores")

_ATTR = "_state_arrays"


class StateArrays:
    """Columnar view of one beacon state (see module docstring)."""

    __slots__ = ("_state_ref", "_cells", "_deferred", "__weakref__")

    def __init__(self, state):
        self._state_ref = weakref.ref(state)
        self._cells = {}
        self._deferred = False

    # -- plumbing -----------------------------------------------------------

    def _state(self):
        state = self._state_ref()
        if state is None:
            raise RuntimeError("StateArrays outlived its state")
        return state

    def _seq(self, name):
        return object.__getattribute__(self._state(), _COLUMNS[name][0])

    def _cell(self, name) -> _Cell:
        """The validated cell for ``name``: structurally fresh (same
        sequence object, same mutation generation) or re-extracted."""
        seq = self._seq(name)
        cell = self._cells.get(name)
        if cell is not None and cell.seq_ref() is seq \
                and cell.gen == _gen_of(seq):
            _C_HIT.add()
            return cell
        if cell is not None and name in _DEFERRABLE \
                and cell.data is not cell.base:
            # the SSZ list was written directly while an engine column
            # write was pending — re-extracting would silently drop the
            # engine write.  Same fail-loud contract as commit(); the
            # registry cell is exempt because its write protocol
            # (registry_writable -> matching SSZ writes ->
            # mark_registry_committed) legitimately passes through a
            # stale-generation window.  Under CS_TPU_SANITIZER the
            # raise names the speclint twin (E1201).
            raise sanitizer.effect_error(
                "E1201",
                f"state_arrays: {name} mutated through the SSZ API "
                f"while a deferred engine write was pending"
                + _pending_detail(self))
        _C_MISS.add()
        cell = _Cell(_COLUMNS[name][1](seq), seq)
        self._cells[name] = cell
        if name == "registry":
            _bind_registry(seq, cell)
        return cell

    # -- registry (structured VALIDATOR_DTYPE array) ------------------------

    def registry(self) -> np.ndarray:
        """Read-only structured registry columns (callers must never
        mutate the returned array; writes go through
        :meth:`registry_writable`)."""
        return self._cell("registry").data

    def registry_writable(self) -> np.ndarray:
        """A private registry array the engine may mutate in place —
        copy-on-write: shared/clean cells are copied here, exactly
        once.  The engine must apply the same changes to the SSZ state
        and then call :meth:`mark_registry_committed`."""
        cell = self._cell("registry")
        if cell.data is cell.base:
            cell.data = cell.base.copy()
            _C_COW.add()
        return cell.data

    def mark_registry_committed(self) -> None:
        """Declare the writable registry columns and the SSZ registry
        identical again (the engine just applied matching per-index
        writes through the sequence API)."""
        cell = self._cells.get("registry")
        if cell is None:
            return
        seq = self._seq("registry")
        if cell.seq_ref() is not seq:
            return
        cell.base = cell.data
        cell.gen = _gen_of(seq)

    # -- uint64 / participation columns -------------------------------------

    def balances(self) -> np.ndarray:
        """Current balances column — includes writes still pending in a
        commit scope (read-only contract)."""
        return self._cell("balances").data

    def set_balances(self, new: np.ndarray) -> None:
        self._set("balances", new)

    def inactivity_scores(self) -> np.ndarray:
        return self._cell("inactivity_scores").data

    def set_inactivity_scores(self, new: np.ndarray) -> None:
        self._set("inactivity_scores", new)

    def participation(self, which: str) -> np.ndarray:
        """uint8 participation-flag column; ``which`` is ``"previous"``
        or ``"current"`` (altair+ states only)."""
        return self._cell(f"participation_{which}").data

    def _set(self, name, new) -> None:
        cell = self._cell(name)
        if new.dtype != np.uint64 or new.shape != cell.base.shape:
            raise ValueError(f"state_arrays.{name}: column shape/dtype "
                             f"mismatch ({new.dtype}, {new.shape})")
        cell.data = new
        if not self._deferred:
            self.commit()
        else:
            sanitizer.deferred_write(self, name)

    # -- commit / discard ---------------------------------------------------

    def commit(self) -> None:
        """Write every pending deferrable column back to its SSZ list
        (chunk-packed, one batched tree rebuild per column) and re-stamp
        the cells as committed."""
        wrote = False
        for name in _DEFERRABLE:
            cell = self._cells.get(name)
            if cell is None or cell.data is cell.base:
                continue
            seq = self._seq(name)
            if cell.seq_ref() is not seq or cell.gen != _gen_of(seq):
                # the SSZ list was written directly while an engine
                # column write was pending — committing would clobber
                # one of the two.  No wired path does this; fail loud
                # (naming the speclint twin E1201 when the sanitizer
                # is armed).
                raise sanitizer.effect_error(
                    "E1201",
                    f"state_arrays: {name} mutated through the SSZ API "
                    f"while a deferred engine write was pending"
                    + _pending_detail(self))
            if not wrote:
                _C_COMMITS.add()
                wrote = True
            with span("state_arrays.commit"):
                site = "state_arrays.commit"
                fast = supervisor.admit(site)
                if fast:
                    try:
                        faults.check(site)
                        with supervisor.deadline_scope(site):
                            data = cell.data
                            if faults.corrupt_armed(site):
                                # silent-corruption injection (sentinel-
                                # audit test vector): one flipped bit in
                                # the chunk-packed write; cell.data stays
                                # true, so the read-back audit can see it
                                data = data.copy()
                                if data.size:
                                    data[0] ^= np.uint64(1)
                            _write_u64_list(seq, type(seq).elem_type,
                                            cell.base, data)
                    except (faults.InjectedFault,
                            supervisor.DeadlineExceeded) as exc:
                        faults.count_fallback(_FALLBACKS, exc,
                                              organic="injected", site=site)
                        fast = False
                if not fast:
                    _write_u64_list_loop(seq, type(seq).elem_type,
                                         cell.base, cell.data)
                elif supervisor.audit_due(site):
                    # sentinel audit: re-extract the committed column
                    # and compare against the engine's pending data; on
                    # a mismatch the site is quarantined and the column
                    # repaired through the spec-shaped targeted writes
                    back = u64_column(seq)
                    ok = bool(np.array_equal(back, cell.data))
                    supervisor.audit_result(
                        site, ok, f"chunk-packed commit of {name} read "
                        "back differently than the pending column")
                    if not ok:
                        _write_u64_list_loop(seq, type(seq).elem_type,
                                             back, cell.data)
                else:
                    supervisor.note_success(site)
                cell.base = cell.data
                cell.gen = _gen_of(seq)

    def commit_for_copy(self) -> None:
        """``Container.copy``'s pre-snapshot commit: exactly
        :meth:`commit`, plus the sanitizer's E1202 shadow check — a
        copy/fork with pending deferred writes inside an open commit
        scope is a LEGAL early commit (the child must see the flushed
        columns), but the one-commit-per-epoch contract silently
        degraded, so the armed sanitizer counts it."""
        if sanitizer.enabled():
            sanitizer.fork_event(self, self._deferred and any(
                c is not None and c.data is not c.base
                for c in (self._cells.get(n) for n in _DEFERRABLE)))
        self.commit()

    def discard_pending(self) -> None:
        """Drop uncommitted engine writes (the enclosing transition
        failed; the SSZ state is authoritative)."""
        for name in _DEFERRABLE:
            cell = self._cells.get(name)
            if cell is not None:
                cell.data = cell.base

    # -- snapshot / fork ----------------------------------------------------

    def fork(self, new_state) -> "StateArrays":
        """Bind this store's columns to ``new_state`` (an ``ssz.copy``
        of the owner) without copying them: base arrays are immutable
        by contract, so both lineages share until one writes.  Pending
        writes are committed first so the copied SSZ content matches
        the shared columns.  Only cells still structurally valid
        against the parent's sequences come along — a stale cell (the
        sequence mutated since extraction) is dropped, NOT rebound:
        stamping it with the child's fresh generation would launder
        stale data into a "valid" column."""
        self.commit()
        other = StateArrays(new_state)
        if not enabled():
            # the store was disabled after this lineage attached its
            # columns: share NOTHING with the copy — no cells, no
            # forest provider binding, no attach.  The copy behaves
            # like a plain ``ssz`` copy, which the store-off
            # differential-oracle legs rely on (shared columns would
            # let a store bug cancel out of both sides of a
            # forked-vs-independent root comparison).
            return other
        parent = self._state()
        for name, cell in self._cells.items():
            field = _COLUMNS[name][0]
            pseq = object.__getattribute__(parent, field)
            if cell.seq_ref() is not pseq or cell.gen != _gen_of(pseq):
                continue
            seq = object.__getattribute__(new_state, field)
            ncell = _Cell(cell.data, seq)
            # the mesh device placement rides along with the shared
            # column: a forked replay dispatches against the SAME
            # device arrays until it writes the column (identity check
            # in parallel/mesh_state.sharded_cell retires it then)
            ncell.shard = cell.shard
            other._cells[name] = ncell
            if name == "registry":
                _bind_registry(seq, ncell)
        object.__setattr__(new_state, _ATTR, other)
        _C_FORKS.add()
        return other


# ---------------------------------------------------------------------------
# Module-level surface
# ---------------------------------------------------------------------------

def _pending_detail(store) -> str:
    """The armed sanitizer's scope-ledger view of which deferred
    columns an E1201 violation would clobber — empty when disarmed or
    untracked."""
    pending = sanitizer.pending_columns(store)
    return f" (would clobber deferred: {', '.join(pending)})" \
        if pending else ""


def of(state) -> StateArrays:
    """The state's attached store (created on first use).  With the
    engine disabled every call returns a detached single-use store:
    per-call extraction, immediate commits, no sharing."""
    if not enabled():
        return StateArrays(state)
    store = state.__dict__.get(_ATTR)
    if store is None or store._state_ref() is not state:
        store = StateArrays(state)
        object.__setattr__(state, _ATTR, store)
    return store


def registry_of(state) -> np.ndarray:
    """Shorthand for ``of(state).registry()`` — the one sanctioned way
    for engine code to read validator registry columns."""
    return of(state).registry()


def flush(state) -> None:
    """Commit any pending deferred writes of ``state``'s attached store
    (no-op when none): every spec-loop fallback calls this before
    reading SSZ, so a half-deferred epoch can never expose stale
    balances to non-engine code."""
    d = getattr(state, "__dict__", None)
    store = d.get(_ATTR) if d is not None else None
    if store is not None and store._state_ref() is state:
        store.commit()


@contextmanager
def commit_scope(state):
    """Defer the store's balance-family commits across the enclosed
    epoch transition: sub-transitions write columns, SSZ sees ONE
    chunk-packed commit per column at scope exit.  Reentrant; a no-op
    when the engine is disabled.  On an exception the pending writes
    are discarded (exception-as-invalidity: the caller abandons the
    state)."""
    if not enabled():
        yield
        return
    store = of(state)
    if store._deferred:
        yield
        return
    store._deferred = True
    sanitizer.scope_opened(store)
    try:
        yield
    except BaseException:
        store._deferred = False
        store.discard_pending()
        sanitizer.scope_closed(store)
        raise
    store._deferred = False
    store.commit()
    sanitizer.scope_closed(store)


def fork_state(state):
    """``ssz`` state copy + column fork in one step: the returned state
    carries a store sharing this state's column arrays copy-on-write.
    The cheap way to run N concurrent replays off one base snapshot.

    With the store enabled, every plain ``state.copy()`` of a
    store-carrying state does this too (``Container.copy`` flushes
    pending writes before the field snapshot and forks the store after
    it) — this helper just guarantees a store is attached first.  With
    the store disabled it degrades to a plain ``ssz`` copy (detached
    stores have no cells to share, and counting a column-less fork
    would skew the telemetry)."""
    from consensus_specs_tpu.utils.ssz import copy as ssz_copy
    if enabled():
        of(state)               # attach; the copy hook forks it
    return ssz_copy(state)


# ---------------------------------------------------------------------------
# Column sharing with the hash-forest (utils/ssz/forest.py)
# ---------------------------------------------------------------------------

_REG_CELL_ATTR = "_sa_registry_cell"


def _bind_registry(seq, cell) -> None:
    """Backpointer for :func:`peek_registry`: the sequence knows its
    (weakly-held) registry cell, so the forest's columnar root build
    finds the columns without knowing about states or stores."""
    setattr(seq, _REG_CELL_ATTR, weakref.ref(cell))


def peek_registry(seq):
    """The committed registry columns bound to ``seq`` as
    ``{ssz field name: uint64 array}`` — or None when the cell is gone,
    stale, or belongs to another sequence.  Registered as the forest's
    column provider: bulk container-root builds read these instead of
    re-walking the typed views."""
    ref = getattr(seq, _REG_CELL_ATTR, None)
    if ref is None:
        return None
    cell = ref()
    if cell is None or cell.seq_ref() is not seq \
            or cell.gen != _gen_of(seq):
        return None
    base = cell.base
    out = {}
    for fname, key in REGISTRY_FIELDS:
        col = base[key]
        out[fname] = col.astype(np.uint64) if key == "sl" else col
    return out


forest.set_column_provider(peek_registry)
