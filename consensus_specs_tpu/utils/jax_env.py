"""Shared JAX environment setup: persistent compile cache.

Every entry point (pytest, bench.py, __graft_entry__, plain consumer
imports) uses the same cache directory so big XLA programs (pairing,
hash-to-curve, MSM) compile once per machine.  The directory is keyed by
jaxlib + libtpu build versions: replaying an AOT executable compiled by a
different libtpu than the runtime fails with FAILED_PRECONDITION (the
round-2 multichip failure mode), so a build change must land in a fresh
directory.
"""
import os

_CACHE_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")


def _cpu_fingerprint() -> str:
    """Short hash of this host's CPU feature set.

    XLA:CPU AOT artifacts embed the feature set of the machine that
    compiled them; loading them on a host with a different set at best
    spams feature-mismatch errors and at worst SIGILLs (the round-3
    ``BENCH_r03.json`` failure tail).  Keying the cache directory by the
    host's own flags guarantees artifacts are only ever replayed on a
    machine whose features match the compiling one.

    NOTE: ``bench.py``'s ``_machine_key`` inlines this exact derivation
    (its parent process must never import the package) and keys the
    last-known-good measurement store with it - change both together or
    every machine's own store entries silently degrade to
    ``foreign_machine`` fallbacks.
    """
    import hashlib
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it "flags", aarch64 "Features"
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except Exception:
        pass
    if not flags:
        import platform
        flags = platform.processor() or platform.machine() or "unknown-cpu"
    return hashlib.sha256(flags.encode()).hexdigest()[:12]


def keyed_cache_dir(hermetic=None) -> str:
    """``hermetic``: None = infer from the environment (axon plugin
    present or not); True = force the hermetic-CPU directory (used by
    the dryrun marker, which parent and child must agree on regardless
    of which env computes it)."""
    parts = []
    try:
        import jaxlib.version
        parts.append(jaxlib.version.__version__)
    except Exception:
        parts.append("jaxlib-unknown")
    try:
        import importlib.metadata as _md
        parts.append("libtpu-" + _md.version("libtpu"))
    except Exception:
        parts.append("libtpu-none")
    parts.append("cpu-" + _cpu_fingerprint())
    # Segregate plugin sessions from hermetic-CPU children: with the
    # axon plugin registered, even XLA:CPU modules may be compiled by
    # the REMOTE compile service on a machine whose LLVM feature set
    # differs from this host's — storing those artifacts in the
    # hermetic dir poisons it (every load rejects with a
    # machine-feature mismatch and recompiles; measured round 5, the
    # reason the dryrun's warm cache never took). "h2" restarts the
    # hermetic dir clean of previously mixed artifacts.
    if hermetic is None:
        hermetic = not os.environ.get("PALLAS_AXON_POOL_IPS")
    parts.append("h2" if hermetic else "axon")
    return os.path.join(_CACHE_ROOT, "-".join(parts))


def force_cpu_platform() -> None:
    """Make this process's JAX run on host CPU only, reliably.

    Setting ``JAX_PLATFORMS=cpu`` in the environment is NOT enough here:
    the container's accelerator plugin calls
    ``jax.config.update("jax_platforms", "axon,cpu")`` during interpreter
    startup (sitecustomize), which overrides the env var and makes every
    ``backends()`` call initialize the tunnel-backed accelerator first —
    hanging all JAX work whenever the tunnel is unavailable.  Tests and
    the multichip dryrun must never depend on that tunnel, so this pushes
    ``cpu`` back through jax.config (and clears any already-initialized
    backend set so the change takes effect).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        from jax._src import xla_bridge as xb
        initialized = xb.backends_are_initialized()
    except Exception:
        initialized = True  # unknown — clear defensively below
    if initialized:
        try:
            from jax.extend.backend import clear_backends
            clear_backends()
        except Exception:
            try:
                jax.clear_backends()
            except Exception:
                import warnings
                warnings.warn(
                    "force_cpu_platform: could not clear initialized JAX "
                    "backends; a previously-selected accelerator backend "
                    "may still be active")


def process_age_s() -> float:
    """Seconds since THIS process exec'd - including time burned in
    sitecustomize/.pth hooks BEFORE any script code ran.

    The container's accelerator plugin registers itself at interpreter
    start; with a flaky tunnel that registration has been observed to
    stall for minutes.  A driver wraps entry points in its own external
    timeout that started at exec, so budget-bound code must subtract
    this overhead or it overshoots the driver's window exactly when the
    tunnel is sick (the round-3 rc=124 shape).
    """
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # comm may contain spaces; fields resume after the last ')'
        fields = stat[stat.rindex(")") + 2:].split()
        start_ticks = int(fields[19])            # field 22 overall
        hz = os.sysconf("SC_CLK_TCK")
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        age = uptime - start_ticks / hz
        return max(0.0, age)
    except Exception:
        return 0.0


def cpu_subprocess_env(base=None) -> dict:
    """Environment for a CPU-only child process that must NEVER touch the
    accelerator tunnel.

    Removing ``PALLAS_AXON_POOL_IPS`` makes the container's sitecustomize
    skip accelerator-plugin registration entirely - measured round 4:
    with the tunnel flaky, ``register()`` stalls EVERY interpreter start
    for minutes (it runs from a .pth hook before the script body), which
    is unsurvivable for budget-bound children.  ``JAX_PLATFORMS=cpu``
    then binds cleanly because no plugin is registered to override it.
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # never remote-compile in a hermetic child: remote XLA:CPU artifacts
    # carry the service machine's feature set, not this host's
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the child's cache key must be computed IN the child (the axon
    # discriminator depends on the env this function just edited)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def ensure_working_backend(timeout: int = 90) -> str:
    """Probe JAX backend initialization in a subprocess; fall back to CPU
    when the default (tunnel-backed) accelerator hangs or fails.

    The container's accelerator plugin initializes a remote tunnel during
    ``jax.devices()``; when that tunnel is down the call blocks forever,
    which must never take down the bench/compile-check entry points.
    Returns the platform that will be used ("default" or "cpu").
    """
    global _PROBE_RESULT
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the env var alone is NOT binding in this container (the
        # accelerator plugin's sitecustomize overrides it through
        # jax.config) — push cpu through the config as well
        force_cpu_platform()
        return "cpu"
    if _PROBE_RESULT is not None:
        return _PROBE_RESULT
    import subprocess
    import sys as _sys
    try:
        proc = subprocess.run(
            [_sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            timeout=timeout, capture_output=True)
        if proc.returncode == 0:
            # rc=0 with a cpu default backend means jax works but no
            # accelerator is attached (CPU-only install): report "cpu"
            # so accelerator_cached()/use_fastest() pick the native
            # CPU backend instead of minutes of XLA:CPU compiles
            platform = proc.stdout.decode().strip().splitlines()[-1] \
                if proc.stdout.strip() else ""
            _PROBE_RESULT = "cpu" if platform == "cpu" else "default"
            return _PROBE_RESULT
    except subprocess.TimeoutExpired:
        pass
    except Exception:
        pass
    import sys as _s
    print("jax_env: accelerator backend unavailable (init hung or failed); "
          "falling back to host CPU", file=_s.stderr, flush=True)
    force_cpu_platform()
    _PROBE_RESULT = "cpu"
    return "cpu"


_PROBE_RESULT = None


def accelerator_cached() -> bool:
    """True iff an accelerator backend is already KNOWN to be live in
    this process — from a prior probe or an initialized jax backend.
    Never probes or initializes anything itself (a dead tunnel hangs
    ``jax.devices()``, and this is called from hot backend-selection
    paths like ``bls.use_fastest``)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False
    if _PROBE_RESULT == "default":
        return True
    import sys
    if "jax" in sys.modules:
        try:
            import jax
            from jax._src import xla_bridge
            if getattr(xla_bridge, "_backends", None):
                return jax.default_backend() != "cpu"
        except Exception:
            return False
    return False


def setup_compile_cache() -> str:
    """Point JAX at the keyed persistent cache; idempotent.

    Works both before and after ``import jax`` (config reads the env var
    lazily until a backend is initialized; after that we push it through
    jax.config as well, which is safe pre-first-compile).
    """
    cache_dir = keyed_cache_dir()
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    import sys
    if "jax" in sys.modules:
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                int(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
        except Exception:
            pass
    return cache_dir
