"""Shared JAX environment setup: persistent compile cache.

Every entry point (pytest, bench.py, __graft_entry__, plain consumer
imports) uses the same cache directory so big XLA programs (pairing,
hash-to-curve, MSM) compile once per machine.  The directory is keyed by
jaxlib + libtpu build versions: replaying an AOT executable compiled by a
different libtpu than the runtime fails with FAILED_PRECONDITION (the
round-2 multichip failure mode), so a build change must land in a fresh
directory.
"""
import os

_CACHE_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")


def keyed_cache_dir() -> str:
    parts = []
    try:
        import jaxlib.version
        parts.append(jaxlib.version.__version__)
    except Exception:
        parts.append("jaxlib-unknown")
    try:
        import importlib.metadata as _md
        parts.append("libtpu-" + _md.version("libtpu"))
    except Exception:
        parts.append("libtpu-none")
    return os.path.join(_CACHE_ROOT, "-".join(parts))


def setup_compile_cache() -> str:
    """Point JAX at the keyed persistent cache; idempotent.

    Works both before and after ``import jax`` (config reads the env var
    lazily until a backend is initialized; after that we push it through
    jax.config as well, which is safe pre-first-compile).
    """
    cache_dir = keyed_cache_dir()
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    import sys
    if "jax" in sys.modules:
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                int(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
        except Exception:
            pass
    return cache_dir
