"""SSZ type system: typed values with serialize / hash_tree_root.

A from-scratch equivalent of the reference's ``remerkleable`` dependency
(reference: ``tests/core/pyspec/eth2spec/utils/ssz/ssz_typing.py`` re-exports;
normative rules in ``ssz/simple-serialize.md``). Provides:

  basic:      uint8/16/32/64/128/256, boolean
  bytes:      ByteVector[N] (Bytes1/4/20/32/48/96 aliases), ByteList[LIMIT]
  bitfields:  Bitvector[N], Bitlist[LIMIT]
  composite:  Vector[elem, N], List[elem, LIMIT], Container, Union[...]

Values are mutable python objects with assignment-time validation: writing an
out-of-range value into a uint64 field raises, which is how the spec's
"uint64 overflow ⇒ invalid state transition" rule (reference:
``specs/phase0/beacon-chain.md:1253``) is enforced.

Every composite memoizes its hash_tree_root, invalidated precisely by
parent-pointer dirty propagation (see the note below) — so registry-scale
merkleization re-hashes only mutated subtree paths.
"""
import weakref
from typing import Dict, Optional, Sequence, Tuple

from ...obs import registry as _obs_registry
from .merkle import (
    IncrementalTree,
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
    pack_bytes_into_chunks,
)

OFFSET_BYTE_LENGTH = 4

# Composite-root memo accounting (``cache.hit{cache=root}`` — every
# hash_tree_root call on a Container / sequence either reads the memo or
# recomputes).  Pre-bound series, one int add per call (speclint O5xx):
# hash_tree_root is the hottest read in the codebase, so nothing heavier
# may sit here.
_C_ROOT_HIT = _obs_registry.counter("cache.hit").labels(cache="root")
_C_ROOT_MISS = _obs_registry.counter("cache.miss").labels(cache="root")

# Root caching uses parent-pointer dirty propagation: every mutable
# composite knows the single location that owns it (value semantics:
# storing always snapshots, so ownership is unique), and a mutation walks
# the ownership chain invalidating only the ancestors' caches, while
# sequences additionally record WHICH child index changed so
# re-merkleization re-hashes only the dirty root paths
# (``merkle.IncrementalTree``).  This is the remerkleable role in the
# reference (``setup.py:549``): per-slot state roots cost O(mutations *
# log n) hashes, not O(registry).
class SSZValue:
    """Marker base for all SSZ value instances."""
    __slots__ = ()


def _set_owner(value, parent, key) -> None:
    """Record that ``value`` is stored at ``parent[key]`` (field index or
    element index).  Only mutable composites track ownership; leaves
    (ints/bytes) are immutable and need none."""
    if isinstance(value, (Container, _SequenceBase, _BitsBase, UnionBase)):
        object.__setattr__(value, "_owner", (weakref.ref(parent), key))


def _notify_owner(value) -> None:
    """Propagate a dirty mark from ``value`` up the ownership chain."""
    owner = getattr(value, "_owner", None)
    if owner is not None:
        parent = owner[0]()
        if parent is not None:
            parent._mark_child_dirty(owner[1])


# ---------------------------------------------------------------------------
# basic types
# ---------------------------------------------------------------------------

class BasicValue(int, SSZValue):
    byte_length = 0

    def __new__(cls, value=0):
        if isinstance(value, bytes):
            value = int.from_bytes(value, "little")
        value = int(value)
        if not 0 <= value < (1 << (cls.byte_length * 8)):
            raise ValueError(f"{cls.__name__} out of range: {value}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def fixed_byte_length(cls):
        return cls.byte_length

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def coerce(cls, value):
        return value if type(value) is cls else cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls.byte_length:
            raise ValueError(f"{cls.__name__}: wrong byte length {len(data)}")
        return cls(int.from_bytes(data, "little"))

    def serialize(self) -> bytes:
        return int(self).to_bytes(self.byte_length, "little")

    def hash_tree_root(self) -> bytes:
        return int(self).to_bytes(self.byte_length, "little").ljust(32, b"\x00")

    def copy(self):
        return self


class uint8(BasicValue):
    byte_length = 1


class uint16(BasicValue):
    byte_length = 2


class uint32(BasicValue):
    byte_length = 4


class uint64(BasicValue):
    byte_length = 8


class uint128(BasicValue):
    byte_length = 16


class uint256(BasicValue):
    byte_length = 32


class boolean(BasicValue):
    byte_length = 1

    def __new__(cls, value=0):
        if isinstance(value, bytes):
            value = int.from_bytes(value, "little")
        value = int(value)
        if value not in (0, 1):
            raise ValueError(f"boolean must be 0 or 1, got {value}")
        return int.__new__(cls, value)

    def __bool__(self):
        return int(self) != 0


byte = uint8


# ---------------------------------------------------------------------------
# byte vectors / lists
# ---------------------------------------------------------------------------

def _to_bytes(value) -> bytes:
    if isinstance(value, str) and value.startswith("0x"):
        return bytes.fromhex(value[2:])
    if isinstance(value, (bytes, bytearray, memoryview)):
        return bytes(value)
    if isinstance(value, Sequence):
        return bytes(value)
    raise TypeError(f"cannot convert {type(value)} to bytes")


class ByteVectorBase(bytes, SSZValue):
    length = 0

    def __new__(cls, value=None):
        if value is None:
            value = b"\x00" * cls.length
        value = _to_bytes(value)
        if len(value) != cls.length:
            raise ValueError(f"{cls.__name__}: need {cls.length} bytes, got {len(value)}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def fixed_byte_length(cls):
        return cls.length

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        return value if type(value) is cls else cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    def serialize(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        return merkleize_chunks(pack_bytes_into_chunks(bytes(self)))

    def copy(self):
        return self

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


_byte_vector_cache: Dict[int, type] = {}


class _ParamMeta(type):
    def __getitem__(cls, params):
        return cls._make(params)


class ByteVector(ByteVectorBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, length: int):
        t = _byte_vector_cache.get(length)
        if t is None:
            t = type(f"ByteVector{length}", (ByteVectorBase,), {"length": length})
            _byte_vector_cache[length] = t
        return t


class ByteListBase(bytes, SSZValue):
    limit = 0

    def __new__(cls, value=b""):
        value = _to_bytes(value)
        if len(value) > cls.limit:
            raise ValueError(f"{cls.__name__}: {len(value)} bytes exceeds limit {cls.limit}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        return value if type(value) is cls else cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    def serialize(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        limit_chunks = (self.limit + 31) // 32
        root = merkleize_chunks(pack_bytes_into_chunks(bytes(self)), limit=max(limit_chunks, 1))
        return mix_in_length(root, len(self))

    def copy(self):
        return self

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


_byte_list_cache: Dict[int, type] = {}


class ByteList(ByteListBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, limit: int):
        t = _byte_list_cache.get(limit)
        if t is None:
            t = type(f"ByteList{limit}", (ByteListBase,), {"limit": limit})
            _byte_list_cache[limit] = t
        return t


Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]


# ---------------------------------------------------------------------------
# bitfields
# ---------------------------------------------------------------------------

class _BitsBase(SSZValue):
    __slots__ = ("_bits", "_owner")

    def _init_bits(self, value, fixed_len: Optional[int]):
        if value is None:
            bits = [False] * (fixed_len or 0)
        elif isinstance(value, _BitsBase):
            bits = list(value._bits)
        else:
            bits = [bool(b) for b in value]
        if fixed_len is not None and len(bits) != fixed_len:
            raise ValueError(f"{type(self).__name__}: need {fixed_len} bits, got {len(bits)}")
        self._bits = bits

    def __len__(self):
        return len(self._bits)

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        self._bits[i] = bool(v)
        _notify_owner(self)

    def __eq__(self, other):
        if isinstance(other, _BitsBase):
            return self._bits == other._bits
        if isinstance(other, (list, tuple)):
            return self._bits == [bool(b) for b in other]
        return NotImplemented

    def __hash__(self):
        return hash((type(self).__name__, tuple(self._bits)))

    def _bitfield_bytes(self, with_delimiter: bool) -> bytes:
        n = len(self._bits)
        nbytes = (n + (1 if with_delimiter else 0) + 7) // 8
        buf = bytearray(nbytes)
        for i, b in enumerate(self._bits):
            if b:
                buf[i // 8] |= 1 << (i % 8)
        if with_delimiter:
            buf[n // 8] |= 1 << (n % 8)
        return bytes(buf)

    def __repr__(self):
        return f"{type(self).__name__}({self._bits})"


class BitvectorBase(_BitsBase):
    length = 0

    def __init__(self, value=None):
        self._init_bits(value, type(self).length)

    @classmethod
    def is_fixed_size(cls):
        return True

    @classmethod
    def fixed_byte_length(cls):
        return (cls.length + 7) // 8

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        # value semantics on assignment (remerkleable-compatible): snapshot
        return cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != (cls.length + 7) // 8:
            raise ValueError(f"{cls.__name__}: wrong byte length")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(cls.length)]
        # padding bits beyond length must be zero
        for i in range(cls.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise ValueError(f"{cls.__name__}: nonzero padding bit")
        return cls(bits)

    def serialize(self) -> bytes:
        return self._bitfield_bytes(with_delimiter=False)

    def hash_tree_root(self) -> bytes:
        chunk_count = (self.length + 255) // 256
        return merkleize_chunks(
            pack_bytes_into_chunks(self.serialize()), limit=max(chunk_count, 1))

    def copy(self):
        return type(self)(self._bits)


_bitvector_cache: Dict[int, type] = {}


class Bitvector(BitvectorBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, length: int):
        t = _bitvector_cache.get(length)
        if t is None:
            t = type(f"Bitvector{length}", (BitvectorBase,), {"length": length})
            _bitvector_cache[length] = t
        return t


class BitlistBase(_BitsBase):
    limit = 0

    def __init__(self, value=None):
        self._init_bits(value if value is not None else [], None)
        if len(self._bits) > type(self).limit:
            raise ValueError(f"{type(self).__name__}: {len(self._bits)} bits exceeds limit")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        # value semantics on assignment (remerkleable-compatible): snapshot
        return cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError("Bitlist: empty serialization (delimiter missing)")
        if data[-1] == 0:
            raise ValueError("Bitlist: last byte zero (delimiter missing)")
        total_bits = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total_bits > cls.limit:
            raise ValueError(f"Bitlist: {total_bits} bits exceeds limit {cls.limit}")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total_bits)]
        return cls(bits)

    def append(self, v):
        if len(self._bits) >= type(self).limit:
            raise ValueError("Bitlist: append past limit")
        self._bits.append(bool(v))
        _notify_owner(self)

    def serialize(self) -> bytes:
        return self._bitfield_bytes(with_delimiter=True)

    def hash_tree_root(self) -> bytes:
        chunk_count = (type(self).limit + 255) // 256
        root = merkleize_chunks(
            pack_bytes_into_chunks(self._bitfield_bytes(with_delimiter=False) if self._bits else b""),
            limit=max(chunk_count, 1))
        return mix_in_length(root, len(self._bits))

    def copy(self):
        return type(self)(self._bits)


_bitlist_cache: Dict[int, type] = {}


class Bitlist(BitlistBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, limit: int):
        t = _bitlist_cache.get(limit)
        if t is None:
            t = type(f"Bitlist{limit}", (BitlistBase,), {"limit": limit})
            _bitlist_cache[limit] = t
        return t


# ---------------------------------------------------------------------------
# homogeneous sequences
# ---------------------------------------------------------------------------

def _pack_basic(values, elem_type) -> bytes:
    size = elem_type.byte_length
    return b"".join(int(v).to_bytes(size, "little") for v in values)


class _SequenceBase(SSZValue):
    __slots__ = ("_items", "_root_memo", "_tree", "_dirty", "_owner", "_gen",
                 "_hash_memo")
    elem_type: type = None

    def _coerce_items(self, values):
        et = type(self).elem_type
        items = [et.coerce(v) for v in values]
        for i, v in enumerate(items):
            _set_owner(v, self, i)
        return items

    def _mark_child_dirty(self, key) -> None:
        tree = getattr(self, "_tree", None)
        if tree is not None:
            self._dirty.add(key)
        self._root_memo = None
        # mutation generation: validates forest-stashed column snapshots
        self._gen = getattr(self, "_gen", 0) + 1
        _notify_owner(self)

    def _drop_tree(self) -> None:
        """Structural change the incremental path doesn't model (full
        replacement, empty shrink): fall back to a rebuild on next root."""
        object.__setattr__(self, "_tree", None)
        self._root_memo = None
        self._gen = getattr(self, "_gen", 0) + 1
        _notify_owner(self)

    def _chunks_for_items(self, indices):
        """Leaf chunks for the element ``indices`` as {chunk_idx: bytes}.
        Wide composite sets go columnar (one batched reduction for all
        dirty element roots); full builds use :meth:`_leaf_data`."""
        et = type(self).elem_type
        if issubclass(et, BasicValue):
            per = 32 // et.byte_length
            out = {}
            for ci in {i // per for i in indices}:
                seg = self._items[ci * per:(ci + 1) * per]
                out[ci] = _pack_basic(seg, et).ljust(32, b"\x00")
            return out
        if len(indices) >= forest._COLUMNAR_MIN:
            idx = sorted(indices)
            data = forest.bulk_element_root_bytes(
                [self._items[i] for i in idx], et)
            if data is not None:
                return {i: data[k * 32:(k + 1) * 32]
                        for k, i in enumerate(idx)}
        return {i: self._items[i].hash_tree_root() for i in indices}

    def _leaf_data(self):
        """The full leaf layer as one packed byte buffer (the zero-copy
        bulk-build path: no per-chunk dict or list is materialized)."""
        et = type(self).elem_type
        if issubclass(et, BasicValue):
            return _pack_basic(self._items, et)   # tree pads to chunks
        data = forest.bulk_element_root_bytes(self._items, et, self)
        if data is not None:
            return data
        return b"".join(x.hash_tree_root() for x in self._items)

    def _limit_chunks(self) -> int:
        et = type(self).elem_type
        bound = getattr(type(self), "limit", 0) or getattr(
            type(self), "length", 0)
        if issubclass(et, BasicValue):
            return max((bound * et.byte_length + 31) // 32, 1)
        return max(bound, 1)

    def _copy_tree_into(self, new) -> None:
        """Carry the cached chunk tree (and pending dirt) into a copy."""
        tree = getattr(self, "_tree", None)
        object.__setattr__(new, "_tree",
                           tree.copy() if tree is not None else None)
        object.__setattr__(new, "_dirty", set(getattr(self, "_dirty", ())))
        new._root_memo = getattr(self, "_root_memo", None)

    def _apply_dirty_leaves(self):
        """Flush pending dirty chunks into the backing tree's leaf layer
        and return ``(tree, sorted_dirty_parents)`` for the deferred
        level re-hash — the forest scope's per-tree entry point, so the
        upward hashing can be gathered across sibling trees.  None when
        nothing is pending."""
        tree = getattr(self, "_tree", None)
        if tree is None or not self._dirty:
            return None
        et = type(self).elem_type
        per = 32 // et.byte_length if issubclass(et, BasicValue) else 1
        n_chunks = (len(self._items) + per - 1) // per
        if tree.count > n_chunks:
            tree.truncate(n_chunks)
        live = {i for i in self._dirty if i < len(self._items)}
        self._dirty.clear()
        parents = tree.apply_leaves(self._chunks_for_items(live))
        return (tree, parents) if parents else None

    def _tree_root(self) -> bytes:
        """Chunk-tree root (before any length mix-in), incrementally
        maintained: only dirty chunk paths re-hash, level-batched."""
        tree = getattr(self, "_tree", None)
        if tree is None:
            tree = IncrementalTree(self._leaf_data(), self._limit_chunks())
            object.__setattr__(self, "_tree", tree)
            object.__setattr__(self, "_dirty", set())
        elif self._dirty:
            job = self._apply_dirty_leaves()
            if job is not None:
                job[0].rehash_up(job[1])
        return tree.root()

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def __setitem__(self, i, v):
        if i < 0:
            i += len(self._items)
        value = type(self).elem_type.coerce(v)
        self._items[i] = value
        _set_owner(value, self, i)
        self._mark_child_dirty(i)

    def _cached_root(self, finish):
        """Memoized root: the memo is cleared EXPLICITLY by every mutation
        (own mutators + child dirty notifications), so validity is exact -
        no global clock involved."""
        memo = getattr(self, "_root_memo", None)
        if memo is not None:
            _C_ROOT_HIT.n += 1
            return memo
        _C_ROOT_MISS.n += 1
        root = finish(self._tree_root())
        self._root_memo = root
        return root

    def __eq__(self, other):
        if isinstance(other, _SequenceBase):
            return type(self).elem_type is type(other).elem_type and self._items == other._items
        if isinstance(other, (list, tuple)):
            return list(self._items) == list(other)
        return NotImplemented

    def __hash__(self):
        # Must stay consistent with __eq__, which compares only
        # (elem_type, items) — NOT the sequence class's limit/length — so
        # a List[u64, 8] equals a List[u64, 16] with the same values and
        # they must hash alike; the tree root (which commits to the
        # limit) is therefore NOT a valid hash key.  The content hash is
        # memoized against the mutation generation, so repeated hashing
        # is O(1); the old form serialized every element on each call.
        memo = getattr(self, "_hash_memo", None)
        gen = getattr(self, "_gen", 0)
        if memo is not None and memo[0] == gen:
            return memo[1]
        h = hash(tuple(self._items))
        self._hash_memo = (gen, h)
        return h

    def index(self, v):
        return self._items.index(v)

    def __contains__(self, v):
        return v in self._items

    def _serialize_elems(self) -> bytes:
        et = type(self).elem_type
        if issubclass(et, BasicValue):
            return _pack_basic(self._items, et)
        if et.is_fixed_size():
            return b"".join(x.serialize() for x in self._items)
        parts = [x.serialize() for x in self._items]
        offset = OFFSET_BYTE_LENGTH * len(parts)
        head = bytearray()
        for p in parts:
            head += offset.to_bytes(OFFSET_BYTE_LENGTH, "little")
            offset += len(p)
        return bytes(head) + b"".join(parts)

    @classmethod
    def _decode_elems(cls, data: bytes):
        et = cls.elem_type
        if et.is_fixed_size():
            size = et.fixed_byte_length()
            if len(data) % size != 0:
                raise ValueError(f"{cls.__name__}: bad byte length {len(data)}")
            return [et.decode_bytes(data[i:i + size]) for i in range(0, len(data), size)]
        if len(data) == 0:
            return []
        first_offset = int.from_bytes(data[:OFFSET_BYTE_LENGTH], "little")
        if (first_offset % OFFSET_BYTE_LENGTH != 0 or first_offset > len(data)
                or first_offset < OFFSET_BYTE_LENGTH):
            raise ValueError(f"{cls.__name__}: bad first offset {first_offset}")
        n = first_offset // OFFSET_BYTE_LENGTH
        offsets = [int.from_bytes(data[i * 4:(i + 1) * 4], "little") for i in range(n)]
        offsets.append(len(data))
        items = []
        for i in range(n):
            if offsets[i + 1] < offsets[i] or offsets[i + 1] > len(data):
                raise ValueError(f"{cls.__name__}: bad offsets")
            items.append(et.decode_bytes(data[offsets[i]:offsets[i + 1]]))
        return items

class VectorBase(_SequenceBase):
    length = 0

    def __init__(self, value=None):
        if value is None:
            et = type(self).elem_type
            self._items = [et.default() for _ in range(type(self).length)]
            for i, x in enumerate(self._items):
                _set_owner(x, self, i)
        else:
            self._items = self._coerce_items(value)
            if len(self._items) != type(self).length:
                raise ValueError(
                    f"{type(self).__name__}: need {type(self).length} elements, got {len(self._items)}")

    @classmethod
    def is_fixed_size(cls):
        return cls.elem_type.is_fixed_size()

    @classmethod
    def fixed_byte_length(cls):
        if not cls.is_fixed_size():
            raise TypeError("variable-size vector")
        return cls.elem_type.fixed_byte_length() * cls.length

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        # value semantics on assignment (remerkleable-compatible): snapshot
        return value.copy() if type(value) is cls else cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        items = cls._decode_elems(data)
        if len(items) != cls.length:
            raise ValueError(f"{cls.__name__}: wrong element count")
        return cls(items)

    def serialize(self) -> bytes:
        return self._serialize_elems()

    def hash_tree_root(self) -> bytes:
        return self._cached_root(lambda root: root)

    def copy(self):
        new = object.__new__(type(self))
        new._items = [x.copy() for x in self._items]
        for i, x in enumerate(new._items):
            _set_owner(x, new, i)
        self._copy_tree_into(new)
        return new

    def __repr__(self):
        return f"{type(self).__name__}({self._items!r})"


_vector_cache: Dict[Tuple[type, int], type] = {}


class Vector(VectorBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, params):
        elem, length = params
        key = (elem, length)
        t = _vector_cache.get(key)
        if t is None:
            t = type(f"Vector[{elem.__name__},{length}]", (VectorBase,),
                     {"elem_type": elem, "length": length})
            _vector_cache[key] = t
        return t


class ListBase(_SequenceBase):
    limit = 0

    def __init__(self, *args):
        if len(args) == 1 and not isinstance(args[0], (SSZValue, int, bytes)) \
                and hasattr(args[0], "__iter__"):
            values = list(args[0])
        else:
            values = list(args)
        self._items = self._coerce_items(values)
        if len(self._items) > type(self).limit:
            raise ValueError(f"{type(self).__name__}: {len(self._items)} exceeds limit")

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        # value semantics on assignment (remerkleable-compatible): snapshot
        if type(value) is cls:
            return value.copy()
        if isinstance(value, _SequenceBase):
            # cross-class sequence (e.g. same-shape List from another fork's
            # spec instance): rebuild elementwise, never as a single element
            return cls(list(value))
        return cls(value)

    @classmethod
    def decode_bytes(cls, data: bytes):
        items = cls._decode_elems(data)
        if len(items) > cls.limit:
            raise ValueError(f"{cls.__name__}: too many elements")
        return cls(items)

    def append(self, v):
        if len(self._items) >= type(self).limit:
            raise ValueError(f"{type(self).__name__}: append past limit")
        value = type(self).elem_type.coerce(v)
        self._items.append(value)
        _set_owner(value, self, len(self._items) - 1)
        self._mark_child_dirty(len(self._items) - 1)

    def pop(self):
        v = self._items.pop()
        if self._items and getattr(self, "_tree", None) is not None:
            # shrink-by-one is modeled incrementally: marking the new
            # right-edge element dirty makes the next flush truncate the
            # tree and rewrite the (possibly partial) edge chunk
            self._mark_child_dirty(len(self._items) - 1)
        else:
            self._drop_tree()
        return v

    def serialize(self) -> bytes:
        return self._serialize_elems()

    def hash_tree_root(self) -> bytes:
        return self._cached_root(
            lambda root: mix_in_length(root, len(self._items)))

    def copy(self):
        new = object.__new__(type(self))
        new._items = [x.copy() for x in self._items]
        for i, x in enumerate(new._items):
            _set_owner(x, new, i)
        self._copy_tree_into(new)
        return new

    def __repr__(self):
        return f"{type(self).__name__}({self._items!r})"


_list_cache: Dict[Tuple[type, int], type] = {}


class List(ListBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, params):
        elem, limit = params
        key = (elem, limit)
        t = _list_cache.get(key)
        if t is None:
            t = type(f"List[{elem.__name__},{limit}]", (ListBase,),
                     {"elem_type": elem, "limit": limit})
            _list_cache[key] = t
        return t


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------

class _ContainerMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields: Dict[str, type] = {}
        for base in reversed(cls.__mro__):
            anns = base.__dict__.get("__annotations__", {})
            for fname, ftype in anns.items():
                if fname.startswith("_"):
                    continue  # internal bookkeeping, not an SSZ field
                if not isinstance(ftype, type):
                    raise TypeError(
                        f"{name}.{fname}: SSZ field annotations must be live types "
                        f"(got {ftype!r}); string/postponed annotations are not supported")
                fields[fname] = ftype
        cls._fields = fields
        return cls


class Container(SSZValue, metaclass=_ContainerMeta):
    """SSZ container. Declare fields with class annotations:

        class Checkpoint(Container):
            epoch: uint64
            root: Bytes32
    """
    _fields: Dict[str, type] = {}

    def __init__(self, **kwargs):
        fields = type(self)._fields
        for k in kwargs:
            if k not in fields:
                raise TypeError(f"{type(self).__name__}: unknown field {k}")
        for fname, ftype in fields.items():
            if fname in kwargs:
                value = ftype.coerce(kwargs[fname])
            else:
                value = ftype.default()
            object.__setattr__(self, fname, value)
            _set_owner(value, self, fname)
        object.__setattr__(self, "_root_cache", None)

    def __setattr__(self, name, value):
        ftype = type(self)._fields.get(name)
        if ftype is None:
            raise AttributeError(f"{type(self).__name__}: no field {name}")
        value = ftype.coerce(value)
        object.__setattr__(self, name, value)
        _set_owner(value, self, name)
        object.__setattr__(self, "_root_cache", None)
        _notify_owner(self)

    def _mark_child_dirty(self, key) -> None:
        object.__setattr__(self, "_root_cache", None)
        _notify_owner(self)

    @classmethod
    def fields(cls) -> Dict[str, type]:
        return dict(cls._fields)

    @classmethod
    def is_fixed_size(cls):
        return all(t.is_fixed_size() for t in cls._fields.values())

    @classmethod
    def fixed_byte_length(cls):
        if not cls.is_fixed_size():
            raise TypeError("variable-size container")
        return sum(t.fixed_byte_length() for t in cls._fields.values())

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def coerce(cls, value):
        # value semantics on assignment (remerkleable-compatible): snapshot
        if type(value) is cls:
            return value.copy()
        if isinstance(value, Container) and type(value)._fields.keys() == cls._fields.keys():
            return cls(**{k: getattr(value, k) for k in cls._fields})
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"cannot coerce {type(value)} to {cls.__name__}")

    @classmethod
    def decode_bytes(cls, data: bytes):
        fields = cls._fields
        fixed_sizes = []
        for t in fields.values():
            fixed_sizes.append(t.fixed_byte_length() if t.is_fixed_size() else OFFSET_BYTE_LENGTH)
        fixed_total = sum(fixed_sizes)
        if len(data) < fixed_total:
            raise ValueError(f"{cls.__name__}: truncated")
        pos = 0
        offsets = []
        fixed_parts = {}
        for (fname, ftype), size in zip(fields.items(), fixed_sizes):
            chunk = data[pos:pos + size]
            if ftype.is_fixed_size():
                fixed_parts[fname] = ftype.decode_bytes(chunk)
            else:
                offsets.append((fname, ftype, int.from_bytes(chunk, "little")))
            pos += size
        if offsets:
            if offsets[0][2] != fixed_total:
                raise ValueError(f"{cls.__name__}: bad first offset")
            bounds = [o[2] for o in offsets] + [len(data)]
            for i, (fname, ftype, off) in enumerate(offsets):
                if bounds[i + 1] < off or bounds[i + 1] > len(data):
                    raise ValueError(f"{cls.__name__}: bad offsets")
                fixed_parts[fname] = ftype.decode_bytes(data[off:bounds[i + 1]])
        elif len(data) != fixed_total:
            raise ValueError(f"{cls.__name__}: trailing bytes")
        return cls(**fixed_parts)

    def serialize(self) -> bytes:
        fields = type(self)._fields
        head = bytearray()
        tail = bytearray()
        fixed_total = sum(
            t.fixed_byte_length() if t.is_fixed_size() else OFFSET_BYTE_LENGTH
            for t in fields.values())
        offset = fixed_total
        for fname, ftype in fields.items():
            v = getattr(self, fname)
            if ftype.is_fixed_size():
                head += v.serialize()
            else:
                part = v.serialize()
                head += offset.to_bytes(OFFSET_BYTE_LENGTH, "little")
                offset += len(part)
                tail += part
        return bytes(head + tail)

    def hash_tree_root(self) -> bytes:
        # Safe to cache on EVERY container: any mutation below this node
        # (field assignment, nested setitem/append/bit flip) walks the
        # ownership chain and clears this cache precisely.
        cached = object.__getattribute__(self, "_root_cache")
        if cached is not None:
            _C_ROOT_HIT.n += 1
            return cached
        _C_ROOT_MISS.n += 1
        if forest.scope_active():
            # batch scope: flush every dirty subtree of this forest
            # level-aligned before the recursive walk reads their roots
            forest.flush_container(self)
        chunks = [getattr(self, f).hash_tree_root() for f in type(self)._fields]
        root = merkleize_chunks(chunks)
        object.__setattr__(self, "_root_cache", root)
        return root

    def copy(self):
        # A state carrying an attached StateArrays column store
        # (state/arrays.py) hands its columns to the copy
        # copy-on-write: pending column writes flush BEFORE the field
        # snapshot (so the copied SSZ content matches), and the forked
        # store rides along afterwards.  Duck-typed on the attribute so
        # this module needs no upward import; a plain container pays
        # one dict lookup.
        store = self.__dict__.get("_state_arrays")
        if store is not None:
            # commit_for_copy == commit plus the sanitizer's E1202
            # shadow check (a copy with pending deferred writes inside
            # an open commit scope is a counted early commit)
            store.commit_for_copy()
        new = object.__new__(type(self))
        for f in type(self)._fields:
            fv = getattr(self, f).copy()
            object.__setattr__(new, f, fv)
            _set_owner(fv, new, f)
        # field copies have identical roots, so the memoized root carries over
        object.__setattr__(new, "_root_cache",
                           object.__getattribute__(self, "_root_cache"))
        if store is not None:
            store.fork(new)
        return new

    def __eq__(self, other):
        if not isinstance(other, Container):
            return NotImplemented
        if type(self)._fields.keys() != type(other)._fields.keys():
            return False
        return all(getattr(self, f) == getattr(other, f) for f in type(self)._fields)

    def __hash__(self):
        return hash(self.hash_tree_root())

    def __repr__(self):
        inner = ", ".join(f"{f}={getattr(self, f)!r}" for f in type(self)._fields)
        return f"{type(self).__name__}({inner})"


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------

class UnionBase(SSZValue):
    __slots__ = ("_selector", "_value", "_owner")
    options: Tuple[Optional[type], ...] = ()

    def __init__(self, selector: int = 0, value=None):
        options = type(self).options
        if not 0 <= selector < len(options):
            raise ValueError("Union: bad selector")
        opt = options[selector]
        if opt is None:
            if value is not None:
                raise ValueError("Union: None option takes no value")
            self._value = None
        else:
            self._value = opt.coerce(value) if value is not None else opt.default()
            _set_owner(self._value, self, 0)
        self._selector = selector

    def _mark_child_dirty(self, key) -> None:
        _notify_owner(self)

    @property
    def selector(self):
        return self._selector

    @property
    def value(self):
        return self._value

    @classmethod
    def is_fixed_size(cls):
        return False

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def coerce(cls, value):
        if type(value) is cls:
            return value.copy()
        if isinstance(value, tuple) and len(value) == 2:
            return cls(value[0], value[1])
        raise TypeError(
            f"cannot coerce {type(value).__name__} to {cls.__name__}; "
            "pass a Union instance or a (selector, value) tuple")

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError("Union: empty")
        selector = data[0]
        if selector >= len(cls.options):
            raise ValueError("Union: bad selector")
        opt = cls.options[selector]
        if opt is None:
            if len(data) != 1:
                raise ValueError("Union: None option with payload")
            return cls(0)
        return cls(selector, opt.decode_bytes(data[1:]))

    def serialize(self) -> bytes:
        payload = b"" if self._value is None else self._value.serialize()
        return bytes([self._selector]) + payload

    def hash_tree_root(self) -> bytes:
        root = b"\x00" * 32 if self._value is None else self._value.hash_tree_root()
        return mix_in_selector(root, self._selector)

    def copy(self):
        new = object.__new__(type(self))
        new._selector = self._selector
        new._value = None if self._value is None else self._value.copy()
        if new._value is not None:
            _set_owner(new._value, new, 0)
        return new

    def __eq__(self, other):
        return (isinstance(other, UnionBase) and self._selector == other._selector
                and self._value == other._value)

    def __hash__(self):
        return hash(self.hash_tree_root())


_union_cache: Dict[Tuple, type] = {}


class Union(UnionBase, metaclass=_ParamMeta):
    @classmethod
    def _make(cls, params):
        if not isinstance(params, tuple):
            params = (params,)
        key = tuple(params)
        t = _union_cache.get(key)
        if t is None:
            t = type(f"Union[{','.join('None' if p is None else p.__name__ for p in params)}]",
                     (UnionBase,), {"options": tuple(params)})
            _union_cache[key] = t
        return t


# ---------------------------------------------------------------------------
# columnar access (ops/epoch_kernels.py: struct-of-arrays epoch engine)
# ---------------------------------------------------------------------------

def sequence_items(seq):
    """The backing element list of a List/Vector — a zero-copy view for
    columnar extraction (``np.fromiter`` over a registry-sized sequence
    instead of len(seq) ``__getitem__`` calls).  Read-only contract:
    callers must never mutate the returned list or its slots; all writes
    go through the sequence API (or :func:`replace_basic_items`) so dirty
    tracking stays exact."""
    if not isinstance(seq, _SequenceBase):
        raise TypeError(f"sequence_items: want List/Vector, got {type(seq)}")
    return seq._items


def replace_basic_items(seq, items, packed=None) -> None:
    """Bulk-swap every element of a basic-element List/Vector.

    ``items`` must be a list of already-coerced ``elem_type`` instances
    (the epoch engine builds them straight from validated uint64 numpy
    columns); per-element ``coerce``+dirty-marking — the O(n) python cost
    a registry-wide ``seq[i] = v`` loop pays — is skipped wholesale.

    ``packed``, when given, must be the items' concatenated little-endian
    serialization (e.g. ``column.astype('<u8').tobytes()``): the cached
    chunk tree is then rebuilt chunk-level straight from the buffer
    through batched layer hashing — a registry-wide commit materializes
    zero per-chunk python work.  Without it the tree is dropped and the
    next root pays a fresh (still batched, but python-packed) rebuild.
    """
    et = type(seq).elem_type
    if not issubclass(et, BasicValue):
        raise TypeError("replace_basic_items: basic element types only")
    limit = getattr(type(seq), "limit", 0)
    length = getattr(type(seq), "length", 0)
    if length and len(items) != length:
        raise ValueError(f"{type(seq).__name__}: need {length} elements")
    if limit and len(items) > limit:
        raise ValueError(f"{type(seq).__name__}: {len(items)} exceeds limit")
    if items and not (isinstance(items[0], et) and isinstance(items[-1], et)):
        raise TypeError(f"replace_basic_items: want {et.__name__} elements")
    if packed is not None and len(packed) != len(items) * et.byte_length:
        # validate BEFORE the swap: a rejected commit must leave the
        # sequence (items, tree, memo) fully untouched
        raise ValueError("replace_basic_items: packed length mismatch")
    object.__setattr__(seq, "_items", list(items))
    if packed is None:
        seq._drop_tree()
        return
    tree = getattr(seq, "_tree", None)
    if tree is None:
        object.__setattr__(seq, "_tree",
                           IncrementalTree(packed, seq._limit_chunks()))
    else:
        tree.set_leaves(packed)
    object.__setattr__(seq, "_dirty", set())
    seq._root_memo = None
    seq._gen = getattr(seq, "_gen", 0) + 1
    _notify_owner(seq)


# Bottom import: forest.py needs the class definitions above (it walks
# Container/_SequenceBase instances); by this point the module namespace
# is complete, so the circular reference resolves either import order.
from . import forest  # noqa: E402
