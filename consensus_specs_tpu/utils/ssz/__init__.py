"""SSZ: SimpleSerialize types, serialization and merkleization.

Equivalent of the reference's ``eth2spec.utils.ssz`` package (which wraps
``remerkleable``); normative spec: ``ssz/simple-serialize.md`` and
``ssz/merkle-proofs.md`` in the reference tree.
"""
from .types import (
    SSZValue, BasicValue, boolean, byte,
    uint8, uint16, uint32, uint64, uint128, uint256,
    ByteVector, ByteList,
    Bytes1, Bytes4, Bytes8, Bytes20, Bytes32, Bytes48, Bytes96,
    Bitvector, Bitlist, Vector, List, Container, Union,
    sequence_items, replace_basic_items,
)
from .impl import serialize, hash_tree_root, uint_to_bytes, copy, deserialize
from .merkle import merkleize_chunks, mix_in_length, mix_in_selector, zero_hashes
from .proofs import (
    GeneralizedIndex, get_generalized_index, concat_generalized_indices,
    get_generalized_index_length, get_generalized_index_bit,
    generalized_index_sibling, generalized_index_child,
    generalized_index_parent, calculate_merkle_root, verify_merkle_proof,
    get_branch_indices, get_path_indices, get_helper_indices,
    calculate_multi_merkle_root, verify_merkle_multiproof,
    compute_merkle_proof, get_subtree_node_root,
)

__all__ = [
    "SSZValue", "BasicValue", "boolean", "byte",
    "uint8", "uint16", "uint32", "uint64", "uint128", "uint256",
    "ByteVector", "ByteList",
    "Bytes1", "Bytes4", "Bytes8", "Bytes20", "Bytes32", "Bytes48", "Bytes96",
    "Bitvector", "Bitlist", "Vector", "List", "Container", "Union",
    "sequence_items", "replace_basic_items",
    "serialize", "hash_tree_root", "uint_to_bytes", "copy", "deserialize",
    "merkleize_chunks", "mix_in_length", "mix_in_selector", "zero_hashes",
    "GeneralizedIndex", "get_generalized_index", "concat_generalized_indices",
    "get_generalized_index_length", "get_generalized_index_bit",
    "generalized_index_sibling", "generalized_index_child",
    "generalized_index_parent", "calculate_merkle_root", "verify_merkle_proof",
    "get_branch_indices", "get_path_indices", "get_helper_indices",
    "calculate_multi_merkle_root", "verify_merkle_multiproof",
    "compute_merkle_proof", "get_subtree_node_root",
]
