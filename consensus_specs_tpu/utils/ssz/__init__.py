"""SSZ: SimpleSerialize types, serialization and merkleization.

Equivalent of the reference's ``eth2spec.utils.ssz`` package (which wraps
``remerkleable``); normative spec: ``ssz/simple-serialize.md`` and
``ssz/merkle-proofs.md`` in the reference tree.
"""
from .types import (
    SSZValue, BasicValue, boolean, byte,
    uint8, uint16, uint32, uint64, uint128, uint256,
    ByteVector, ByteList,
    Bytes1, Bytes4, Bytes8, Bytes20, Bytes32, Bytes48, Bytes96,
    Bitvector, Bitlist, Vector, List, Container, Union,
)
from .impl import serialize, hash_tree_root, uint_to_bytes, copy, deserialize
from .merkle import merkleize_chunks, mix_in_length, mix_in_selector, zero_hashes

__all__ = [
    "SSZValue", "BasicValue", "boolean", "byte",
    "uint8", "uint16", "uint32", "uint64", "uint128", "uint256",
    "ByteVector", "ByteList",
    "Bytes1", "Bytes4", "Bytes8", "Bytes20", "Bytes32", "Bytes48", "Bytes96",
    "Bitvector", "Bitlist", "Vector", "List", "Container", "Union",
    "serialize", "hash_tree_root", "uint_to_bytes", "copy", "deserialize",
    "merkleize_chunks", "mix_in_length", "mix_in_selector", "zero_hashes",
]
