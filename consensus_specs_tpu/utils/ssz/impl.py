"""Functional SSZ entrypoints used by spec code.

Reference: ``tests/core/pyspec/eth2spec/utils/ssz/ssz_impl.py:8-25``
(serialize / hash_tree_root / uint_to_bytes / copy).
"""
from .types import SSZValue, BasicValue, Bytes32


def serialize(obj: SSZValue) -> bytes:
    return obj.serialize()


def hash_tree_root(obj: SSZValue) -> Bytes32:
    return Bytes32(obj.hash_tree_root())


def uint_to_bytes(n: BasicValue) -> bytes:
    """Serialize a uint to its type's byte length, little-endian."""
    return n.serialize()


def copy(obj: SSZValue) -> SSZValue:
    return obj.copy()


def deserialize(typ, data: bytes) -> SSZValue:
    return typ.decode_bytes(data)
