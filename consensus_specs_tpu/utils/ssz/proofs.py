"""Generalized indices and Merkle proofs over SSZ values.

Behavioral parity with ``ssz/merkle-proofs.md`` (reference): generalized-
index arithmetic, ``get_generalized_index`` over typed paths, single-leaf
proof verification (``calculate_merkle_root`` / ``verify_merkle_proof``),
multiproofs (``get_helper_indices`` / ``calculate_multi_merkle_root``),
plus proof *construction* from a live value (``compute_merkle_proof``, the
role of remerkleable's backing-tree traversal used by the altair spec
builder, ``pysetup/spec_builders/altair.py:20-40``).

Construction walks the value's virtual chunk tree lazily — only the nodes
on (and siblings of) the requested path are materialized, so proving a
field of a 1M-validator state never builds the registry subtree.
"""
from hashlib import sha256
from typing import Sequence

from .merkle import (
    merkleize_chunks, next_power_of_two, ceil_log2, zero_hashes,
    pack_bytes_into_chunks,
)
from .types import (
    BasicValue, ByteVectorBase, ByteListBase, BitvectorBase, BitlistBase,
    VectorBase, ListBase, Container, uint64, _pack_basic,
)

GeneralizedIndex = int


# ---------------------------------------------------------------------------
# Generalized-index arithmetic (merkle-proofs.md "Generalized Merkle tree
# index" section)
# ---------------------------------------------------------------------------

def get_generalized_index_length(index: GeneralizedIndex) -> int:
    """log2(index): the depth of the node."""
    return index.bit_length() - 1


def get_generalized_index_bit(index: GeneralizedIndex, position: int) -> bool:
    """The ``position``-th bit (from the leaf end) of the index path."""
    return (index >> position) & 1 == 1


def generalized_index_sibling(index: GeneralizedIndex) -> GeneralizedIndex:
    return index ^ 1


def generalized_index_child(index: GeneralizedIndex,
                            right_side: bool) -> GeneralizedIndex:
    return index * 2 + int(right_side)


def generalized_index_parent(index: GeneralizedIndex) -> GeneralizedIndex:
    return index // 2


def concat_generalized_indices(*indices) -> GeneralizedIndex:
    """Gindex of the node reached by successive subtree navigations:
    o = o * floor_pow2(i) + (i - floor_pow2(i)) per step."""
    o = GeneralizedIndex(1)
    for i in indices:
        floor_pow = 1 << get_generalized_index_length(i)
        o = GeneralizedIndex(o * floor_pow + (i - floor_pow))
    return o


# ---------------------------------------------------------------------------
# Type introspection (merkle-proofs.md "SSZ object to index" section)
# ---------------------------------------------------------------------------

def item_length(typ) -> int:
    """Byte length of one element when packed into chunks."""
    if issubclass(typ, BasicValue):
        return typ.byte_length
    return 32


def get_elem_type(typ, index_or_name):
    if issubclass(typ, Container):
        return typ.fields()[index_or_name]
    if issubclass(typ, (ByteVectorBase, ByteListBase)):
        from .types import uint8
        return uint8
    if issubclass(typ, (BitvectorBase, BitlistBase)):
        from .types import boolean
        return boolean
    return typ.elem_type


def chunk_count(typ) -> int:
    """Number of data chunks at the type's merkleization layer."""
    if issubclass(typ, BasicValue):
        return 1
    if issubclass(typ, BitvectorBase):
        return (typ.length + 255) // 256
    if issubclass(typ, BitlistBase):
        return max((typ.limit + 255) // 256, 1)
    if issubclass(typ, ByteVectorBase):
        return max((typ.length + 31) // 32, 1)
    if issubclass(typ, ByteListBase):
        return max((typ.limit + 31) // 32, 1)
    if issubclass(typ, VectorBase):
        if issubclass(typ.elem_type, BasicValue):
            return max((typ.length * typ.elem_type.byte_length + 31) // 32, 1)
        return max(typ.length, 1)
    if issubclass(typ, ListBase):
        if issubclass(typ.elem_type, BasicValue):
            return max((typ.limit * typ.elem_type.byte_length + 31) // 32, 1)
        return max(typ.limit, 1)
    if issubclass(typ, Container):
        return len(typ.fields())
    raise TypeError(f"no chunk count for {typ}")


def get_item_position(typ, index_or_name):
    """(chunk index, start byte in chunk, end byte in chunk) of one item."""
    if issubclass(typ, (VectorBase, ListBase)):
        index = int(index_or_name)
        start = index * item_length(typ.elem_type)
        return (start // 32, start % 32,
                start % 32 + item_length(typ.elem_type))
    if issubclass(typ, (ByteVectorBase, ByteListBase)):
        index = int(index_or_name)
        return index // 32, index % 32, index % 32 + 1
    if issubclass(typ, (BitvectorBase, BitlistBase)):
        # 256 bits per 32-byte chunk — matches how bitfields actually
        # merkleize.  (merkle-proofs.md's generic formula would give
        # index // 32, which disagrees with the real chunk layout; clients
        # deriving bitfield gindices follow the 256-per-chunk packing.)
        index = int(index_or_name)
        return index // 256, (index % 256) // 8, (index % 256) // 8 + 1
    if issubclass(typ, Container):
        fields = list(typ.fields())
        pos = fields.index(index_or_name)
        return pos, 0, item_length(typ.fields()[index_or_name])
    raise TypeError(f"no item position for {typ}")


def _has_length_mixin(typ) -> bool:
    return issubclass(typ, (ListBase, ByteListBase, BitlistBase))


def get_generalized_index(typ, *path) -> GeneralizedIndex:
    """merkle-proofs.md ``get_generalized_index``: type + path -> gindex.

    Path elements: container field names, sequence indices, or the
    special ``'__len__'`` for list lengths.
    """
    root = GeneralizedIndex(1)
    for p in path:
        assert not issubclass(typ, BasicValue), "cannot descend into basic"
        if p == "__len__":
            assert _has_length_mixin(typ)
            typ = uint64
            root = GeneralizedIndex(root * 2 + 1)
        else:
            pos, _, _ = get_item_position(typ, p)
            base_index = 2 if _has_length_mixin(typ) else 1
            root = GeneralizedIndex(
                root * base_index * next_power_of_two(chunk_count(typ)) + pos)
            typ = get_elem_type(typ, p)
    return root


# ---------------------------------------------------------------------------
# Proof verification (merkle-proofs.md "Merkle multiproofs" section)
# ---------------------------------------------------------------------------

def calculate_merkle_root(leaf: bytes, proof: Sequence[bytes],
                          index: GeneralizedIndex) -> bytes:
    assert len(proof) == get_generalized_index_length(index)
    for i, h in enumerate(proof):
        if get_generalized_index_bit(index, i):
            leaf = sha256(h + leaf).digest()
        else:
            leaf = sha256(leaf + h).digest()
    return leaf


def verify_merkle_proof(leaf: bytes, proof: Sequence[bytes],
                        index: GeneralizedIndex, root: bytes) -> bool:
    return calculate_merkle_root(leaf, proof, index) == bytes(root)


def get_branch_indices(tree_index: GeneralizedIndex):
    """Sisters along the path from ``tree_index`` to the root."""
    o = [generalized_index_sibling(tree_index)]
    while o[-1] > 1:
        o.append(generalized_index_sibling(generalized_index_parent(o[-1])))
    return o[:-1]


def get_path_indices(tree_index: GeneralizedIndex):
    """Ancestors of ``tree_index`` including itself, excluding the root."""
    o = [tree_index]
    while o[-1] > 1:
        o.append(generalized_index_parent(o[-1]))
    return o[:-1]


def get_helper_indices(indices: Sequence[GeneralizedIndex]):
    """All nodes needed to prove ``indices``, sorted descending."""
    all_helper_indices = set()
    all_path_indices = set()
    for index in indices:
        all_helper_indices.update(get_branch_indices(index))
        all_path_indices.update(get_path_indices(index))
    return sorted(all_helper_indices - all_path_indices, reverse=True)


def calculate_multi_merkle_root(leaves: Sequence[bytes],
                                proof: Sequence[bytes],
                                indices: Sequence[GeneralizedIndex]) -> bytes:
    assert len(leaves) == len(indices)
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices)
    objects = {**{index: node for index, node in zip(indices, leaves)},
               **{index: node for index, node in zip(helper_indices, proof)}}
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k // 2 not in objects:
            objects[k // 2] = sha256(
                objects[(k | 1) ^ 1] + objects[k | 1]).digest()
            keys.append(k // 2)
        pos += 1
    return objects[1]


def verify_merkle_multiproof(leaves, proof, indices, root: bytes) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == bytes(root)


# ---------------------------------------------------------------------------
# Proof construction from a live value
# ---------------------------------------------------------------------------
#
# The walk only ever expands nodes ON the requested path; every off-path
# sibling's root comes from the value's (memoized, kernel-batched)
# ``hash_tree_root`` or from one ``merkleize_chunks`` call over its chunk
# range — a finalized-root proof over a 1M-validator state re-merkleizes
# nothing inside the registry.

class _Node:
    """Virtual chunk-tree node."""

    def root(self) -> bytes:
        raise NotImplementedError

    def children(self):
        raise NotImplementedError("cannot descend below a leaf")


class _RawNode(_Node):
    def __init__(self, chunk: bytes):
        self._chunk = bytes(chunk)

    def root(self) -> bytes:
        return self._chunk


class _PairNode(_Node):
    def __init__(self, left: _Node, right: _Node):
        self._l, self._r = left, right

    def root(self) -> bytes:
        return sha256(self._l.root() + self._r.root()).digest()

    def children(self):
        return self._l, self._r


class _RangeNode(_Node):
    """Subtree over chunk positions [start, start + 2^depth) of a layer.

    ``chunks`` is the full chunk-node list of the layer; positions past its
    end are virtual zero chunks.  The root of an off-path range is computed
    with one batched ``merkleize_chunks`` call, not per-pair hashing.
    """

    def __init__(self, chunks, start: int, depth: int):
        self._chunks, self._start, self._depth = chunks, start, depth

    def root(self) -> bytes:
        lo = self._start
        hi = min(self._start + (1 << self._depth), len(self._chunks))
        if lo >= len(self._chunks):
            return zero_hashes[self._depth]
        return merkleize_chunks([c.root() for c in self._chunks[lo:hi]],
                                limit=1 << self._depth)

    def children(self):
        if self._depth == 0:
            node = self._chunks[self._start] \
                if self._start < len(self._chunks) else _RawNode(b"\x00" * 32)
            return node.children()
        half = 1 << (self._depth - 1)
        return (_RangeNode(self._chunks, self._start, self._depth - 1),
                _RangeNode(self._chunks, self._start + half, self._depth - 1))


def _layer_node(chunk_nodes, limit: int) -> _Node:
    """Balanced tree over ``chunk_nodes`` virtually padded to ``limit``."""
    depth = ceil_log2(next_power_of_two(max(limit, 1)))
    if depth == 0:
        return chunk_nodes[0] if chunk_nodes else _RawNode(b"\x00" * 32)
    return _RangeNode(chunk_nodes, 0, depth)


class _ValueNode(_Node):
    """Node for a typed value: root via the value's own ``hash_tree_root``
    (memoized where the type memoizes); chunk layer expanded only when the
    proof path descends into it."""

    def __init__(self, value):
        self._value = value
        self._expanded = None

    def root(self) -> bytes:
        return self._value.hash_tree_root()

    def children(self):
        if self._expanded is None:
            self._expanded = _expand_value(self._value)
        return self._expanded.children()


def _expand_value(value) -> _Node:
    """Build the top chunk layer of a typed value (one level of detail)."""
    typ = type(value)
    if issubclass(typ, (ByteVectorBase, ByteListBase, BitvectorBase,
                        BitlistBase)):
        if issubclass(typ, BitlistBase):
            data = value._bitfield_bytes(with_delimiter=False) \
                if len(value) else b""
        elif issubclass(typ, BitvectorBase):
            data = value.serialize()
        else:
            data = bytes(value)
        chunks = [_RawNode(c) for c in pack_bytes_into_chunks(data)]
        node = _layer_node(chunks, chunk_count(typ))
        if _has_length_mixin(typ):
            node = _PairNode(node, _RawNode(
                len(value).to_bytes(32, "little")))
        return node
    if issubclass(typ, (VectorBase, ListBase)):
        et = typ.elem_type
        if issubclass(et, BasicValue):
            chunks = [_RawNode(c) for c in
                      pack_bytes_into_chunks(_pack_basic(value._items, et))]
        else:
            chunks = [_ValueNode(x) for x in value._items]
        node = _layer_node(chunks, chunk_count(typ))
        if _has_length_mixin(typ):
            node = _PairNode(node, _RawNode(
                len(value).to_bytes(32, "little")))
        return node
    if issubclass(typ, Container):
        chunks = [_ValueNode(getattr(value, f)) for f in typ.fields()]
        return _layer_node(chunks, len(typ.fields()))
    raise TypeError(f"cannot descend into {typ}")


def _value_node(value) -> _Node:
    return _ValueNode(value)


def compute_merkle_proof(value, index: GeneralizedIndex):
    """Branch proving node ``index`` of ``value``'s tree, leaf-sibling
    first (the order ``is_valid_merkle_branch`` / light-client
    ``MerkleBranch`` vectors consume)."""
    depth = get_generalized_index_length(index)
    node = _value_node(value)
    branch_top_down = []
    for level in range(depth - 1, -1, -1):
        left, right = node.children()
        if get_generalized_index_bit(index, level):
            branch_top_down.append(left.root())
            node = right
        else:
            branch_top_down.append(right.root())
            node = left
    return list(reversed(branch_top_down))


def get_subtree_node_root(value, index: GeneralizedIndex) -> bytes:
    """Root of the tree node at ``index`` (the 'leaf' a proof attests)."""
    depth = get_generalized_index_length(index)
    node = _value_node(value)
    for level in range(depth - 1, -1, -1):
        left, right = node.children()
        node = right if get_generalized_index_bit(index, level) else left
    return node.root()
