"""Merkleization engine.

Implements the SSZ merkleization rules (reference: ``ssz/simple-serialize.md``
"Merkleization" section): chunkify, pad to the chunk-count limit with
zero-subtree roots, binary-tree hash, plus ``mix_in_length`` /
``mix_in_selector``.

Design note (TPU): each tree level is hashed through :func:`hash_layer`,
which takes one contiguous byte buffer of 64-byte parent inputs. That is the
natural batch boundary for the vectorized SHA-256 kernel
(``consensus_specs_tpu.ops.sha256``) — a 1M-leaf tree becomes ~20 kernel
calls instead of ~2M scalar hashes. A hashlib loop is the small-batch
fallback.

The incremental engine (:class:`IncrementalTree`) applies the same idea to
*dirty* re-hashing: a mutation batch marks chunk paths dirty, and each tree
level's dirty sibling pairs are gathered into one contiguous buffer and
hashed in a single dispatch (native C indexed pair-gather, the JAX kernel,
or — below :data:`_PAIR_BATCH_MIN` pairs — a per-pair hashlib loop).  A
registry-wide balance update therefore re-hashes as ~40 batched calls, not
~500k scalar ones.  ``utils/ssz/forest.py`` extends the batching across
sibling trees of one state.
"""
import ctypes
import os
from bisect import bisect_right
from hashlib import sha256
from typing import List, Optional, Sequence

import numpy as np

from ... import faults, supervisor
from ...obs import registry as obs_registry
from ...obs.tracing import span
from ..env_flags import MERKLE_BATCH_MIN

ZERO_CHUNK = b"\x00" * 32


def _load_native_hasher():
    """csrc/libcsha256.so (make native): C merkle-layer SHA-256, the
    pycryptodome-role native hash path (reference setup.py:546).  Absent
    lib -> hashlib loop."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))), "csrc", "libcsha256.so")
    try:
        lib = ctypes.CDLL(path)
        lib.sha256_merkle_layer.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.sha256_merkle_layer.restype = None
        return lib
    except OSError:
        return None


def _probe_native_pairs(lib):
    """The indexed pair-gather entry point (csrc sha256_merkle_pairs) —
    absent in pre-rebuild .so files, in which case the numpy gather +
    layer hash path is used instead."""
    if lib is None:
        return None
    try:
        fn = lib.sha256_merkle_pairs
    except AttributeError:
        return None
    fn.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
                   ctypes.c_size_t, ctypes.c_char_p, ctypes.c_void_p]
    fn.restype = None
    return fn


_native = _load_native_hasher()
_native_pairs = _probe_native_pairs(_native)

# zero_hashes[i] = root of an all-zero subtree of depth i
zero_hashes: List[bytes] = [ZERO_CHUNK]
for _ in range(64):
    h = sha256(zero_hashes[-1] + zero_hashes[-1]).digest()
    zero_hashes.append(h)

# Threshold (number of 64-byte parent inputs) above which layer hashing is
# dispatched to the batched kernel instead of a hashlib loop, and (pairs)
# the dirty-pair count per level above which the incremental engine
# gathers the level into one batched dispatch.  Both are overridden by
# CS_TPU_MERKLE_BATCH_MIN (see utils/env_flags.py).
_BATCH_THRESHOLD = 256 if MERKLE_BATCH_MIN is None else MERKLE_BATCH_MIN
_PAIR_BATCH_MIN = 32 if MERKLE_BATCH_MIN is None else MERKLE_BATCH_MIN

_batched_hasher = None
_batched_hasher_np = None

# Dispatch accounting, asserted by the bench-merkle smoke (a registry-wide
# commit must hash through the batched paths, never a per-pair loop).
# Series are pre-bound at module scope (the speclint O5xx hot-path rule);
# per-event cost is one int add.
#   merkle.pairs_hashed{backend=native|jax|hashlib} — 64-byte parent
#       inputs hashed, attributed to the backend that really took them
#       (the hashlib series re-engaging at scale is the 4x-regression
#       signature the counters exist to catch)
#   merkle.dispatches{backend=...} — batched calls per backend
#   merkle.pair_batch_calls / pair_batch_pairs — batched dispatches of
#       gathered dirty sibling pairs (incremental engine + forest
#       flushes + columnar container-root reductions), and the pairs
#       they covered
#   merkle.pair_scalar  — dirty pairs hashed one at a time via hashlib
#   merkle.pair_scalar_max (gauge) — largest batch that went through the
#       scalar loop (must stay below the pair threshold: bigger ones
#       must batch)
#   merkle.layer_calls  — full-layer dispatches, native C / JAX path
#   merkle.layer_scalar — layer nodes that fell to the hashlib loop
_PAIRS_HASHED = obs_registry.counter("merkle.pairs_hashed")
_PAIRS_NATIVE = _PAIRS_HASHED.labels(backend="native")
_PAIRS_JAX = _PAIRS_HASHED.labels(backend="jax")
_PAIRS_HASHLIB = _PAIRS_HASHED.labels(backend="hashlib")
_DISPATCHES = obs_registry.counter("merkle.dispatches")
_DISPATCH_NATIVE = _DISPATCHES.labels(backend="native")
_DISPATCH_JAX = _DISPATCHES.labels(backend="jax")
_C_PAIR_BATCH_CALLS = obs_registry.counter("merkle.pair_batch_calls").labels()
_C_PAIR_BATCH_PAIRS = obs_registry.counter("merkle.pair_batch_pairs").labels()
_C_PAIR_SCALAR = obs_registry.counter("merkle.pair_scalar").labels()
_G_PAIR_SCALAR_MAX = obs_registry.gauge("merkle.pair_scalar_max").labels()
_C_LAYER_CALLS = obs_registry.counter("merkle.layer_calls").labels()
_C_LAYER_SCALAR = obs_registry.counter("merkle.layer_scalar").labels()
# batched-dispatch fallbacks: the hashlib per-row loop taken because an
# injected fault (consensus_specs_tpu/faults.py) failed the batched
# path.  No organic series: threshold-based scalar routing is a policy
# choice (counted above), not a failure.
_FALLBACKS = {
    "injected": obs_registry.counter(
        "merkle.fallbacks").labels(reason="injected"),
    "deadline": obs_registry.counter(
        "merkle.fallbacks").labels(reason="deadline"),
}


def stats() -> dict:
    """Back-compat alias view of the ``merkle.*`` registry metrics (the
    differential suites and the bench smoke assert on these keys)."""
    return {"pair_batch_calls": _C_PAIR_BATCH_CALLS.n,
            "pair_batch_pairs": _C_PAIR_BATCH_PAIRS.n,
            "pair_scalar": _C_PAIR_SCALAR.n,
            "pair_scalar_max": _G_PAIR_SCALAR_MAX.v,
            "layer_calls": _C_LAYER_CALLS.n,
            "layer_scalar": _C_LAYER_SCALAR.n}


def reset_stats() -> None:
    obs_registry.reset("merkle.")


def set_batch_thresholds(layer: Optional[int] = None,
                         pairs: Optional[int] = None) -> None:
    """Override the batching thresholds at runtime (tests force both the
    batched and the scalar code paths through this)."""
    global _BATCH_THRESHOLD, _PAIR_BATCH_MIN
    if layer is not None:
        _BATCH_THRESHOLD = layer
    if pairs is not None:
        _PAIR_BATCH_MIN = pairs


def batch_thresholds() -> tuple:
    return (_BATCH_THRESHOLD, _PAIR_BATCH_MIN)


def have_fast_backend() -> bool:
    """True when layer hashing has a non-hashlib implementation to batch
    into (native C or an installed kernel)."""
    return (_native is not None or _batched_hasher is not None
            or _batched_hasher_np is not None)


def can_batch_pairs(n: int) -> bool:
    """True when a batched backend will actually take ``n`` gathered
    pairs: native C accepts any width; a kernel-only backend engages at
    ``_BATCH_THRESHOLD`` — below it the gather would just feed a hashlib
    loop, slower than hashing the pairs in place."""
    if _native is not None:
        return True
    return ((_batched_hasher is not None or _batched_hasher_np is not None)
            and n >= _BATCH_THRESHOLD)


def set_batched_hasher(fn) -> None:
    """Install a batched hasher: fn(data: bytes, n: int) -> bytes (n*32 out).

    ``data`` is ``n`` concatenated 64-byte blocks; result is ``n``
    concatenated 32-byte digests. Used by the JAX/TPU SHA-256 kernel.
    """
    global _batched_hasher
    _batched_hasher = fn


def set_batched_hasher_np(fn) -> None:
    """Install the array-path variant: fn(rows: (n, 64) uint8 ndarray) ->
    (n, 32) uint8 digests.  Lets :func:`hash_rows` feed gathered pair
    buffers to the kernel without a bytes round-trip."""
    global _batched_hasher_np
    _batched_hasher_np = fn


def hash_layer(data: bytes) -> bytes:
    """Hash a full tree layer: data is n*64 bytes -> n*32 bytes."""
    n = len(data) // 64
    if _batched_hasher is not None and n >= _BATCH_THRESHOLD:
        _C_LAYER_CALLS.n += 1
        _DISPATCH_JAX.n += 1
        _PAIRS_JAX.n += n
        with span("sha256.dispatch"):
            return _batched_hasher(data, n)
    if _native is not None and n > 1:
        _C_LAYER_CALLS.n += 1
        _DISPATCH_NATIVE.n += 1
        _PAIRS_NATIVE.n += n
        with span("sha256.dispatch"):
            out = ctypes.create_string_buffer(n * 32)
            _native.sha256_merkle_layer(data, out, n)
            return out.raw
    _C_LAYER_SCALAR.n += n
    _PAIRS_HASHLIB.n += n
    out = bytearray(n * 32)
    for i in range(n):
        out[i * 32:(i + 1) * 32] = sha256(data[i * 64:(i + 1) * 64]).digest()
    return bytes(out)


def _hash_rows_scalar(rows: np.ndarray) -> np.ndarray:
    """The spec-shaped fallback for :func:`hash_rows`: a per-row hashlib
    loop, byte-identical to any batched backend.  Only reached through
    an injected dispatch fault; counts into the hashlib backend series
    (it really is hashlib doing the work) but not the scalar-routing
    counters, which exist to catch threshold regressions."""
    m = rows.shape[0]
    buf = rows.tobytes()
    out = bytearray(m * 32)
    for i in range(m):
        out[i * 32:(i + 1) * 32] = sha256(buf[i * 64:(i + 1) * 64]).digest()
    _PAIRS_HASHLIB.n += m
    return np.frombuffer(bytes(out), dtype=np.uint8).reshape(m, 32)


def hash_rows(rows: np.ndarray) -> np.ndarray:
    """Hash an ``(m, 64)`` uint8 array of parent inputs into ``(m, 32)``
    digests in one batched dispatch.  The entry point for gathered
    dirty-pair buffers (incremental engine, forest flushes, columnar
    container-root reductions).

    Supervised (``consensus_specs_tpu/supervisor``): an open breaker
    skips the batched attempt and serves the scalar spec path directly;
    a sampled sentinel audit recomputes the batch through the scalar
    loop and quarantines the site on any byte difference (the scalar
    digests are then the authoritative answer, so a corrupt batched
    backend cannot poison a tree past its audit)."""
    if not supervisor.admit("merkle.dispatch"):
        return _hash_rows_scalar(rows)
    try:
        faults.check("merkle.dispatch")
        with supervisor.deadline_scope("merkle.dispatch"):
            out = _hash_rows_batched(rows)
    except (faults.InjectedFault, supervisor.DeadlineExceeded) as exc:
        faults.count_fallback(_FALLBACKS, exc, organic="injected",
                              site="merkle.dispatch")
        return _hash_rows_scalar(rows)
    if faults.corrupt_armed("merkle.dispatch"):
        out = out.copy()
        out[0, 0] ^= 1
    if supervisor.audit_due("merkle.dispatch"):
        golden = _hash_rows_scalar(rows)
        ok = bool(np.array_equal(out, golden))
        supervisor.audit_result(
            "merkle.dispatch", ok,
            f"batched digests != scalar sha256 ({rows.shape[0]} pairs)")
        return golden
    supervisor.note_success("merkle.dispatch")
    return out


def _hash_rows_batched(rows: np.ndarray) -> np.ndarray:
    """The engine body of :func:`hash_rows`: route the gathered pair
    buffer to the best available batched backend."""
    m = rows.shape[0]
    if _batched_hasher_np is not None and m >= _BATCH_THRESHOLD:
        _C_PAIR_BATCH_CALLS.n += 1
        _C_PAIR_BATCH_PAIRS.n += m
        _C_LAYER_CALLS.n += 1
        _DISPATCH_JAX.n += 1
        _PAIRS_JAX.n += m
        with span("sha256.dispatch"):
            return _batched_hasher_np(np.ascontiguousarray(rows))
    # derive the pair counters from the dispatch hash_layer ACTUALLY
    # took (its layer_scalar delta), so a routing change there can never
    # silently desynchronize the CI-asserted pair accounting
    before_scalar = _C_LAYER_SCALAR.n
    digests = hash_layer(rows.tobytes())
    scalar_nodes = _C_LAYER_SCALAR.n - before_scalar
    if scalar_nodes:
        _C_PAIR_SCALAR.n += scalar_nodes
        if scalar_nodes > _G_PAIR_SCALAR_MAX.v:
            _G_PAIR_SCALAR_MAX.v = scalar_nodes
    else:
        _C_PAIR_BATCH_CALLS.n += 1
        _C_PAIR_BATCH_PAIRS.n += m
    return np.frombuffer(digests, dtype=np.uint8).reshape(m, 32)


def next_power_of_two(v: int) -> int:
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def ceil_log2(v: int) -> int:
    return (v - 1).bit_length() if v > 1 else 0


def _padded_layer(layer, level: int) -> bytes:
    """A layer as bytes, zero-subtree-padded to an even chunk count — the
    odd-width rule shared by :func:`merkleize_chunks` and
    :class:`IncrementalTree` bulk builds."""
    if (len(layer) // 32) % 2 == 1:
        return bytes(layer) + zero_hashes[level]
    return layer if type(layer) is bytes else bytes(layer)


def merkleize_chunks(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkleize 32-byte chunks, padding (virtually) to ``limit`` chunks.

    ``limit=None`` pads to the next power of two of ``len(chunks)``. A limit
    smaller than the chunk count is an error. Virtual zero-padding uses
    ``zero_hashes`` so a 2^40-chunk registry limit costs 40 extra hashes, not
    2^40.
    """
    count = len(chunks)
    if limit is None:
        limit = next_power_of_two(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        limit = next_power_of_two(limit)
    depth = ceil_log2(limit)

    if count == 0:
        return zero_hashes[depth]

    layer = b"".join(chunks)
    for level in range(depth):
        layer = hash_layer(_padded_layer(layer, level))
    return layer


class IncrementalTree:
    """Cached Merkle tree over a growable chunk list, virtually padded to
    ``limit`` chunks with zero subtrees.

    The dirty-subtree engine behind composite root caching (remerkleable's
    role in the reference, ``setup.py:549``): a mutation at chunk ``i``
    re-hashes only the ``depth`` nodes on its root path instead of the
    whole tree.  Levels store only the occupied prefix; everything to the
    right is a precomputed ``zero_hashes`` entry.  Bulk construction goes
    through :func:`hash_layer` (native/batched SHA-256); incremental
    updates gather each level's dirty sibling pairs into one batched
    dispatch above ``_PAIR_BATCH_MIN`` pairs, and fall back to a hashlib
    loop for a handful of pairs.
    """

    __slots__ = ("depth", "levels")

    def __init__(self, chunks, limit: int):
        """``chunks``: a sequence of 32-byte chunks, or a pre-packed
        bytes-like leaf buffer (whole chunks, the zero-copy bulk path)."""
        self.depth = ceil_log2(next_power_of_two(limit))
        self._build(chunks)

    def _build(self, chunks) -> None:
        if isinstance(chunks, (bytes, bytearray, memoryview)):
            data = chunks
            if len(data) % 32 != 0:   # right-pad a packed buffer to chunks
                data = bytes(data) + b"\x00" * (32 - len(data) % 32)
        else:
            data = b"".join(chunks)
        # mesh leaf-span path: each device hashes one span subtree, the
        # host combines the top log2(devices) levels — byte-identical
        # levels or None (engine off / small tree / counted fallback).
        # The cheap size pre-check keeps the engine import off the
        # small-tree hot path entirely.
        if len(data) >= 16 * 32:
            from consensus_specs_tpu.parallel import mesh_merkle
            levels = mesh_merkle.build_levels(data, self.depth)
            if levels is not None:
                self.levels = levels
                return
        levels = [bytearray(data)]
        for level in range(self.depth):
            levels.append(bytearray(hash_layer(_padded_layer(
                levels[-1], level))))
        self.levels = levels

    @property
    def count(self) -> int:
        return len(self.levels[0]) // 32

    def root(self) -> bytes:
        if self.count == 0:
            return zero_hashes[self.depth]
        return bytes(self.levels[self.depth][:32])

    # -- leaf-layer bulk replacement ------------------------------------

    def set_leaves(self, data) -> None:
        """Replace the whole leaf layer with a pre-packed byte buffer
        (right-padded to whole chunks here) and rebuild the upper levels
        via batched layer hashing — the chunk-level commit path for
        registry-wide column writes: zero per-chunk Python work."""
        if (len(data) + 31) // 32 > (1 << self.depth):
            raise ValueError("chunk count beyond tree limit")
        self._build(data)

    # -- incremental dirty-pair engine ----------------------------------

    def apply_leaves(self, updates: dict) -> list:
        """Write ``{chunk_index: chunk_bytes}`` into the leaf layer
        (indices may extend the occupied prefix by any amount; gaps
        zero-fill) and return the sorted dirty parent indices for
        :meth:`rehash_up` — split out so a forest scope can align the
        level re-hash across many trees."""
        if not updates:
            return []
        level0 = self.levels[0]
        hi = max(updates)
        if hi >= (1 << self.depth):
            raise ValueError("chunk index beyond tree limit")
        if (hi + 1) * 32 > len(level0):
            level0.extend(ZERO_CHUNK * (hi + 1 - len(level0) // 32))
        for i, chunk in updates.items():
            level0[i * 32:(i + 1) * 32] = chunk
        return sorted({i >> 1 for i in updates})

    def level_parents(self, level: int, parents: list) -> list:
        """The prefix of (sorted) ``parents`` whose children are at least
        partly occupied at ``level`` — parents of fully-virtual children
        keep their zero-hash value — with the parent layer grown to cover
        them."""
        occ = len(self.levels[level]) // 32
        if occ == 0:
            return []
        ps = parents[:bisect_right(parents, (occ - 1) // 2)]
        if ps:
            parent = self.levels[level + 1]
            if (ps[-1] + 1) * 32 > len(parent):
                parent.extend(zero_hashes[level + 1]
                              * (ps[-1] + 1 - len(parent) // 32))
        return ps

    def gather_pairs(self, level: int, ps: list) -> np.ndarray:
        """Gather the sibling pairs under parents ``ps`` into one
        contiguous ``(n, 64)`` buffer (virtual right siblings read the
        level's zero-subtree hash)."""
        cur = self.levels[level]
        occ = len(cur) // 32
        arr = np.frombuffer(cur, dtype=np.uint8).reshape(-1, 32)
        idx = np.asarray(ps, dtype=np.int64)
        buf = np.empty((len(ps), 64), dtype=np.uint8)
        buf[:, :32] = arr[2 * idx]
        ri = 2 * idx + 1
        real = ri < occ
        buf[real, 32:] = arr[ri[real]]
        if not real.all():
            buf[~real, 32:] = np.frombuffer(zero_hashes[level],
                                            dtype=np.uint8)
        return buf

    def scatter_level(self, level: int, ps: list, digests) -> list:
        """Write ``digests`` (``(n, 32)`` uint8 or n*32 bytes) into the
        parent layer at ``ps`` and return the sorted grandparent set."""
        parent = self.levels[level + 1]
        out = np.frombuffer(parent, dtype=np.uint8).reshape(-1, 32)
        if not isinstance(digests, np.ndarray):
            digests = np.frombuffer(digests, dtype=np.uint8).reshape(-1, 32)
        out[np.asarray(ps, dtype=np.int64)] = digests
        nxt, last = [], -1
        for p in ps:
            g = p >> 1
            if g != last:
                nxt.append(g)
                last = g
        return nxt

    def _native_pair_hash(self, level: int, ps: list) -> np.ndarray:
        """Hash the pairs under ``ps`` through the C indexed pair-gather
        entry point — no Python-side copy of the level buffer."""
        cur = self.levels[level]
        n = len(ps)
        _DISPATCH_NATIVE.n += 1
        _PAIRS_NATIVE.n += n
        with span("sha256.dispatch"):
            view = np.frombuffer(cur, dtype=np.uint8)
            idx = np.asarray(ps, dtype=np.uint64)
            out = ctypes.create_string_buffer(n * 32)
            _native_pairs(view.ctypes.data, len(cur) // 32, idx.ctypes.data,
                          n, zero_hashes[level], ctypes.addressof(out))
            return np.frombuffer(out.raw, dtype=np.uint8).reshape(n, 32)

    def _rehash_level(self, level: int, ps: list) -> list:
        """Re-hash the parent nodes ``ps`` at one level: batched dispatch
        above the pair threshold, per-pair hashlib below it."""
        n = len(ps)
        if n >= _PAIR_BATCH_MIN and can_batch_pairs(n):
            if _native_pairs is not None and not (
                    _batched_hasher is not None and n >= _BATCH_THRESHOLD):
                _C_PAIR_BATCH_CALLS.n += 1
                _C_PAIR_BATCH_PAIRS.n += n
                digests = self._native_pair_hash(level, ps)
            else:
                digests = hash_rows(self.gather_pairs(level, ps))
            return self.scatter_level(level, ps, digests)
        _C_PAIR_SCALAR.n += n
        if n > _G_PAIR_SCALAR_MAX.v:
            _G_PAIR_SCALAR_MAX.v = n
        _PAIRS_HASHLIB.n += n
        cur, parent = self.levels[level], self.levels[level + 1]
        occ = len(cur) // 32
        nxt, last = [], -1
        for p in ps:
            li, ri = 2 * p, 2 * p + 1
            left = bytes(cur[li * 32:(li + 1) * 32])
            right = bytes(cur[ri * 32:(ri + 1) * 32]) \
                if ri < occ else zero_hashes[level]
            parent[p * 32:(p + 1) * 32] = sha256(left + right).digest()
            g = p >> 1
            if g != last:
                nxt.append(g)
                last = g
        return nxt

    def rehash_up(self, parents: list) -> None:
        """Propagate dirty parent indices to the root, one level-batched
        re-hash per level."""
        for level in range(self.depth):
            parents = self.level_parents(level, parents)
            if not parents:
                return
            parents = self._rehash_level(level, parents)

    def update(self, updates: dict) -> None:
        """Apply ``{chunk_index: chunk_bytes}``; indices may extend the
        occupied prefix by any amount (gaps zero-fill)."""
        self.rehash_up(self.apply_leaves(updates))

    def truncate(self, count: int) -> None:
        """Shrink the occupied prefix to ``count`` chunks (pop support):
        drops trailing chunks and re-hashes the affected right edge."""
        old = self.count
        if count >= old:
            return
        self.levels[0] = self.levels[0][:count * 32]
        # re-hash the path of the last surviving chunk and every dropped
        # parent edge: rebuilding the right edge level by level
        for level in range(self.depth):
            cur = self.levels[level]
            n_parent = (len(cur) // 32 + 1) // 2
            self.levels[level + 1] = self.levels[level + 1][:n_parent * 32]
            if n_parent == 0:
                continue
            self._rehash_level(level, [n_parent - 1])

    def copy(self) -> "IncrementalTree":
        new = object.__new__(IncrementalTree)
        new.depth = self.depth
        new.levels = [bytearray(l) for l in self.levels]
        return new


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little")).digest()


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return sha256(root + selector.to_bytes(32, "little")).digest()


def pack_bytes_into_chunks(data: bytes) -> List[bytes]:
    """Right-pad ``data`` with zeros to a multiple of 32 and split."""
    if len(data) % 32 != 0:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i:i + 32] for i in range(0, len(data), 32)] or []
