"""Merkleization engine.

Implements the SSZ merkleization rules (reference: ``ssz/simple-serialize.md``
"Merkleization" section): chunkify, pad to the chunk-count limit with
zero-subtree roots, binary-tree hash, plus ``mix_in_length`` /
``mix_in_selector``.

Design note (TPU): each tree level is hashed through :func:`hash_layer`,
which takes one contiguous byte buffer of 64-byte parent inputs. That is the
natural batch boundary for the vectorized SHA-256 kernel
(``consensus_specs_tpu.ops.sha256``) — a 1M-leaf tree becomes ~20 kernel
calls instead of ~2M scalar hashes. A hashlib loop is the small-batch
fallback.
"""
import ctypes
import os
from hashlib import sha256
from typing import List, Optional, Sequence

ZERO_CHUNK = b"\x00" * 32


def _load_native_hasher():
    """csrc/libcsha256.so (make native): C merkle-layer SHA-256, the
    pycryptodome-role native hash path (reference setup.py:546).  Absent
    lib -> hashlib loop."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))), "csrc", "libcsha256.so")
    try:
        lib = ctypes.CDLL(path)
        lib.sha256_merkle_layer.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.sha256_merkle_layer.restype = None
        return lib
    except OSError:
        return None


_native = _load_native_hasher()

# zero_hashes[i] = root of an all-zero subtree of depth i
zero_hashes: List[bytes] = [ZERO_CHUNK]
for _ in range(64):
    h = sha256(zero_hashes[-1] + zero_hashes[-1]).digest()
    zero_hashes.append(h)

# Threshold (number of 64-byte parent inputs) above which layer hashing is
# dispatched to the batched kernel instead of a hashlib loop.
_BATCH_THRESHOLD = 256

_batched_hasher = None


def set_batched_hasher(fn) -> None:
    """Install a batched hasher: fn(data: bytes, n: int) -> bytes (n*32 out).

    ``data`` is ``n`` concatenated 64-byte blocks; result is ``n``
    concatenated 32-byte digests. Used by the JAX/TPU SHA-256 kernel.
    """
    global _batched_hasher
    _batched_hasher = fn


def hash_layer(data: bytes) -> bytes:
    """Hash a full tree layer: data is n*64 bytes -> n*32 bytes."""
    n = len(data) // 64
    if _batched_hasher is not None and n >= _BATCH_THRESHOLD:
        return _batched_hasher(data, n)
    if _native is not None and n > 1:
        out = ctypes.create_string_buffer(n * 32)
        _native.sha256_merkle_layer(data, out, n)
        return out.raw
    out = bytearray(n * 32)
    for i in range(n):
        out[i * 32:(i + 1) * 32] = sha256(data[i * 64:(i + 1) * 64]).digest()
    return bytes(out)


def next_power_of_two(v: int) -> int:
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def ceil_log2(v: int) -> int:
    return (v - 1).bit_length() if v > 1 else 0


def merkleize_chunks(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkleize 32-byte chunks, padding (virtually) to ``limit`` chunks.

    ``limit=None`` pads to the next power of two of ``len(chunks)``. A limit
    smaller than the chunk count is an error. Virtual zero-padding uses
    ``zero_hashes`` so a 2^40-chunk registry limit costs 40 extra hashes, not
    2^40.
    """
    count = len(chunks)
    if limit is None:
        limit = next_power_of_two(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        limit = next_power_of_two(limit)
    depth = ceil_log2(limit)

    if count == 0:
        return zero_hashes[depth]

    layer = b"".join(chunks)
    for level in range(depth):
        n = len(layer) // 32
        if n % 2 == 1:
            layer += zero_hashes[level]
            n += 1
        layer = hash_layer(layer)
    return layer


class IncrementalTree:
    """Cached Merkle tree over a growable chunk list, virtually padded to
    ``limit`` chunks with zero subtrees.

    The dirty-subtree engine behind composite root caching (remerkleable's
    role in the reference, ``setup.py:549``): a mutation at chunk ``i``
    re-hashes only the ``depth`` nodes on its root path instead of the
    whole tree.  Levels store only the occupied prefix; everything to the
    right is a precomputed ``zero_hashes`` entry.  Bulk construction goes
    through :func:`hash_layer` (native/batched SHA-256); incremental
    updates use hashlib (a handful of pairs).
    """

    __slots__ = ("depth", "levels")

    def __init__(self, chunks: Sequence[bytes], limit: int):
        self.depth = ceil_log2(next_power_of_two(limit))
        self._build(chunks)

    def _build(self, chunks: Sequence[bytes]) -> None:
        levels = [bytearray(b"".join(chunks))]
        for level in range(self.depth):
            layer = levels[-1]
            n = len(layer) // 32
            if n % 2 == 1:
                layer = layer + zero_hashes[level]
            levels.append(bytearray(hash_layer(bytes(layer))))
        self.levels = levels

    @property
    def count(self) -> int:
        return len(self.levels[0]) // 32

    def root(self) -> bytes:
        if self.count == 0:
            return zero_hashes[self.depth]
        return bytes(self.levels[self.depth][:32])

    def update(self, updates: dict) -> None:
        """Apply ``{chunk_index: chunk_bytes}``; indices may extend the
        occupied prefix by any amount (gaps zero-fill)."""
        if not updates:
            return
        from hashlib import sha256 as _sha
        level0 = self.levels[0]
        hi = max(updates)
        if hi >= (1 << self.depth):
            raise ValueError("chunk index beyond tree limit")
        if (hi + 1) * 32 > len(level0):
            level0.extend(ZERO_CHUNK * (hi + 1 - len(level0) // 32))
        dirty = set()
        for i, chunk in updates.items():
            level0[i * 32:(i + 1) * 32] = chunk
            dirty.add(i >> 1)
        for level in range(self.depth):
            cur, parent = self.levels[level], self.levels[level + 1]
            next_dirty = set()
            occ = len(cur) // 32
            for p in sorted(dirty):
                li, ri = 2 * p, 2 * p + 1
                if li * 32 >= len(cur):
                    break  # parent of fully-virtual children stays zero-hash
                left = bytes(cur[li * 32:(li + 1) * 32])
                right = bytes(cur[ri * 32:(ri + 1) * 32]) \
                    if ri < occ else zero_hashes[level]
                node = _sha(left + right).digest()
                if (p + 1) * 32 > len(parent):
                    parent.extend(zero_hashes[level + 1]
                                  * (p + 1 - len(parent) // 32))
                parent[p * 32:(p + 1) * 32] = node
                next_dirty.add(p >> 1)
            dirty = next_dirty

    def truncate(self, count: int) -> None:
        """Shrink the occupied prefix to ``count`` chunks (pop support):
        drops trailing chunks and re-hashes the affected right edge."""
        old = self.count
        if count >= old:
            return
        self.levels[0] = self.levels[0][:count * 32]
        # re-hash the path of the last surviving chunk and every dropped
        # parent edge: rebuilding the right edge level by level
        for level in range(self.depth):
            cur, parent = self.levels[level], self.levels[level + 1]
            n_parent = (len(cur) // 32 + 1) // 2
            self.levels[level + 1] = parent[:n_parent * 32]
            parent = self.levels[level + 1]
            if n_parent == 0:
                continue
            p = n_parent - 1
            li, ri = 2 * p, 2 * p + 1
            occ = len(cur) // 32
            left = bytes(cur[li * 32:(li + 1) * 32])
            right = bytes(cur[ri * 32:(ri + 1) * 32]) \
                if ri < occ else zero_hashes[level]
            from hashlib import sha256 as _sha
            parent[p * 32:(p + 1) * 32] = _sha(left + right).digest()

    def copy(self) -> "IncrementalTree":
        new = object.__new__(IncrementalTree)
        new.depth = self.depth
        new.levels = [bytearray(l) for l in self.levels]
        return new


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little")).digest()


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return sha256(root + selector.to_bytes(32, "little")).digest()


def pack_bytes_into_chunks(data: bytes) -> List[bytes]:
    """Right-pad ``data`` with zeros to a multiple of 32 and split."""
    if len(data) % 32 != 0:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i:i + 32] for i in range(0, len(data), 32)] or []
