"""Merkleization engine.

Implements the SSZ merkleization rules (reference: ``ssz/simple-serialize.md``
"Merkleization" section): chunkify, pad to the chunk-count limit with
zero-subtree roots, binary-tree hash, plus ``mix_in_length`` /
``mix_in_selector``.

Design note (TPU): each tree level is hashed through :func:`hash_layer`,
which takes one contiguous byte buffer of 64-byte parent inputs. That is the
natural batch boundary for the vectorized SHA-256 kernel
(``consensus_specs_tpu.ops.sha256``) — a 1M-leaf tree becomes ~20 kernel
calls instead of ~2M scalar hashes. A hashlib loop is the small-batch
fallback.
"""
import ctypes
import os
from hashlib import sha256
from typing import List, Optional, Sequence

ZERO_CHUNK = b"\x00" * 32


def _load_native_hasher():
    """csrc/libcsha256.so (make native): C merkle-layer SHA-256, the
    pycryptodome-role native hash path (reference setup.py:546).  Absent
    lib -> hashlib loop."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))), "csrc", "libcsha256.so")
    try:
        lib = ctypes.CDLL(path)
        lib.sha256_merkle_layer.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.sha256_merkle_layer.restype = None
        return lib
    except OSError:
        return None


_native = _load_native_hasher()

# zero_hashes[i] = root of an all-zero subtree of depth i
zero_hashes: List[bytes] = [ZERO_CHUNK]
for _ in range(64):
    h = sha256(zero_hashes[-1] + zero_hashes[-1]).digest()
    zero_hashes.append(h)

# Threshold (number of 64-byte parent inputs) above which layer hashing is
# dispatched to the batched kernel instead of a hashlib loop.
_BATCH_THRESHOLD = 256

_batched_hasher = None


def set_batched_hasher(fn) -> None:
    """Install a batched hasher: fn(data: bytes, n: int) -> bytes (n*32 out).

    ``data`` is ``n`` concatenated 64-byte blocks; result is ``n``
    concatenated 32-byte digests. Used by the JAX/TPU SHA-256 kernel.
    """
    global _batched_hasher
    _batched_hasher = fn


def hash_layer(data: bytes) -> bytes:
    """Hash a full tree layer: data is n*64 bytes -> n*32 bytes."""
    n = len(data) // 64
    if _batched_hasher is not None and n >= _BATCH_THRESHOLD:
        return _batched_hasher(data, n)
    if _native is not None and n > 1:
        out = ctypes.create_string_buffer(n * 32)
        _native.sha256_merkle_layer(data, out, n)
        return out.raw
    out = bytearray(n * 32)
    for i in range(n):
        out[i * 32:(i + 1) * 32] = sha256(data[i * 64:(i + 1) * 64]).digest()
    return bytes(out)


def next_power_of_two(v: int) -> int:
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def ceil_log2(v: int) -> int:
    return (v - 1).bit_length() if v > 1 else 0


def merkleize_chunks(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkleize 32-byte chunks, padding (virtually) to ``limit`` chunks.

    ``limit=None`` pads to the next power of two of ``len(chunks)``. A limit
    smaller than the chunk count is an error. Virtual zero-padding uses
    ``zero_hashes`` so a 2^40-chunk registry limit costs 40 extra hashes, not
    2^40.
    """
    count = len(chunks)
    if limit is None:
        limit = next_power_of_two(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        limit = next_power_of_two(limit)
    depth = ceil_log2(limit)

    if count == 0:
        return zero_hashes[depth]

    layer = b"".join(chunks)
    for level in range(depth):
        n = len(layer) // 32
        if n % 2 == 1:
            layer += zero_hashes[level]
            n += 1
        layer = hash_layer(layer)
    return layer


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little")).digest()


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return sha256(root + selector.to_bytes(32, "little")).digest()


def pack_bytes_into_chunks(data: bytes) -> List[bytes]:
    """Right-pad ``data`` with zeros to a multiple of 32 and split."""
    if len(data) % 32 != 0:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i:i + 32] for i in range(0, len(data), 32)] or []
