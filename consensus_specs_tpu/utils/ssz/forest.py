"""Hash-forest batch scope: cross-tree, level-aligned merkleization.

The incremental engine (:class:`.merkle.IncrementalTree`) batches the
dirty sibling pairs of ONE tree per level.  A beacon state, though, is a
forest: validators, balances, inactivity_scores, roots vectors, ... all
re-hash when a slot closes.  Inside a :func:`hash_forest` scope, a
container root computation first flushes every dirty subtree of the
forest together — each level's dirty pairs from ALL trees are gathered
into one contiguous buffer and hashed in a single batched dispatch — so
the hardware sees ~tree-depth large calls per state, not per field.

The scope also carries the columnar container-root fast path
(:func:`bulk_element_root_bytes`): all N element roots of a
``List[Validator, ...]``-style sequence are computed from vectorized
field serialization plus batched layer hashes over an ``(N, fields, 32)``
chunk cube, instead of N per-object merkleizations.  Column sharing
with the state layer runs both ways: a build first asks the registered
column provider (``state/arrays.py``) for the committed uint64 columns
of a live ``StateArrays`` store — skipping the per-field python walk —
and when no store exists yet, the columns extracted along the way are
stashed (generation-validated) for the store to adopt on first access.

``CS_TPU_HASH_FOREST=0`` disables both (see ``utils/env_flags.py``).
"""
import weakref
from contextlib import contextmanager

import numpy as np

from ...obs import registry as obs_registry
from ...obs.tracing import span
from .. import env_flags
from . import merkle
from .types import BasicValue, ByteVectorBase, Container, _SequenceBase

# Element count above which a composite sequence's element roots are
# computed columnar instead of per-object.  Below it, per-object
# merkleization with warm caches wins.
_COLUMNAR_MIN = 256

_scope_depth = 0
_in_flush = False

# Flush accounting (pre-bound series, speclint O5xx hot-path rule):
#   forest.flushes — hash_forest-scope flushes that found dirty trees
#   forest.flush_trees — dirty trees covered by those flushes
#   forest.cross_tree_dispatches — levels where pairs from >1 tree were
#       gathered into ONE batched hash call (the whole point of the
#       forest scope; zero here means the scope never amortized)
#   forest.bulk_roots — container sequences whose element roots were
#       computed via the columnar (N, fields, 32) cube reduction
_C_FLUSHES = obs_registry.counter("forest.flushes").labels()
_C_FLUSH_TREES = obs_registry.counter("forest.flush_trees").labels()
_C_CROSS_TREE = obs_registry.counter("forest.cross_tree_dispatches").labels()
_C_BULK_ROOTS = obs_registry.counter("forest.bulk_roots").labels()


def scope_active() -> bool:
    """True when a hash_forest scope is open (and not already flushing).
    The switch reads live through ``env_flags.switch`` (it used to latch
    the import-time constant, so a CI leg flipping
    ``CS_TPU_HASH_FOREST`` after import was silently ignored)."""
    return env_flags.switch("CS_TPU_HASH_FOREST") \
        and _scope_depth > 0 and not _in_flush


@contextmanager
def hash_forest():
    """Batch scope: while open, ``hash_tree_root`` on a container first
    flushes all its dirty subtrees level-aligned (one batched hash call
    per level across the whole forest).  Reentrant; a no-op under
    ``CS_TPU_HASH_FOREST=0``."""
    global _scope_depth
    _scope_depth += 1
    try:
        yield
    finally:
        _scope_depth -= 1


def flush_container(obj) -> None:
    """Bring every dirty sequence tree under ``obj`` up to date with one
    gathered hash dispatch per tree level.  After the flush, the normal
    recursive root computation finds all sequence roots warm."""
    global _in_flush
    if _in_flush:
        return
    _in_flush = True
    try:
        with span("hash_forest.flush"):
            jobs = []
            _collect_jobs(obj, jobs)
            if jobs:
                _C_FLUSHES.add()
                _C_FLUSH_TREES.add(len(jobs))
                _flush_jobs(jobs)
    finally:
        _in_flush = False


def _collect_jobs(container, jobs) -> None:
    """Walk the dirty-container spine gathering (tree, dirty-parents)
    jobs.  Only containers with a cleared root cache can hide dirty
    sequences (dirt propagates up the ownership chain), so clean
    subtrees are never entered."""
    for fname in type(container)._fields:
        v = object.__getattribute__(container, fname)
        if isinstance(v, Container):
            if object.__getattribute__(v, "_root_cache") is None:
                _collect_jobs(v, jobs)
        elif isinstance(v, _SequenceBase):
            job = v._apply_dirty_leaves()
            if job is not None:
                jobs.append(job)


def _flush_jobs(jobs) -> None:
    """Level-synchronous re-hash across trees: at each level, gather the
    dirty sibling pairs of every tree into one buffer and hash it in a
    single dispatch."""
    frontier = [(t, ps) for t, ps in jobs if ps]
    level = 0
    while frontier:
        live = []
        for t, ps in frontier:
            if level >= t.depth:
                continue
            ps = t.level_parents(level, ps)
            if ps:
                live.append((t, ps))
        if not live:
            return
        total = sum(len(ps) for _, ps in live)
        nxt = []
        if len(live) > 1 and total >= merkle._PAIR_BATCH_MIN \
                and merkle.can_batch_pairs(total):
            # genuine cross-tree level: one gathered dispatch for all
            _C_CROSS_TREE.add()
            bufs = [t.gather_pairs(level, ps) for t, ps in live]
            digests = merkle.hash_rows(np.concatenate(bufs))
            off = 0
            for t, ps in live:
                n = len(ps)
                nxt.append((t, t.scatter_level(
                    level, ps, digests[off:off + n])))
                off += n
        else:
            # single tree (or a sub-threshold trickle): the per-tree
            # path dispatches best — incl. the zero-copy native
            # indexed pair-gather
            for t, ps in live:
                nxt.append((t, t._rehash_level(level, ps)))
        frontier = nxt
        level += 1


# ---------------------------------------------------------------------------
# Columnar container roots
# ---------------------------------------------------------------------------

def _columnar_plan(ctype):
    """Per-field column strategy for a container type, cached on the
    class:  ``uint``   — BasicValue ≤ 8 bytes, chunk from an int column;
            ``bytes``  — ByteVector ≤ 32, chunk is the (padded) value;
            ``hash64`` — ByteVector ≤ 64, one batched hash per element;
            ``root``   — anything else, per-object field root."""
    plan = ctype.__dict__.get("_columnar_plan")
    if plan is None:
        plan = []
        for fname, ftype in ctype._fields.items():
            if issubclass(ftype, BasicValue) and ftype.byte_length <= 8:
                plan.append((fname, "uint", ftype.byte_length))
            elif issubclass(ftype, ByteVectorBase) and ftype.length <= 32:
                plan.append((fname, "bytes", ftype.length))
            elif issubclass(ftype, ByteVectorBase) and ftype.length <= 64:
                plan.append((fname, "hash64", ftype.length))
            else:
                plan.append((fname, "root", 32))
        ctype._columnar_plan = plan
    return plan


def bulk_element_root_bytes(items, et, owner=None) -> bytes:
    """All element roots of a homogeneous composite sequence as one
    ``n*32`` byte buffer, or None when the columnar path does not apply
    (small n, disabled, or an unsupported element type).

    For containers, the per-container chunk trees of all ``n`` elements
    are reduced together: one ``(n * width/2, 64)`` batched hash per
    container level.  ``owner`` (the sequence, full-extraction calls
    only) keys the uint64 column stash for :func:`peek_columns`.
    """
    n = len(items)
    if not env_flags.switch("CS_TPU_HASH_FOREST") or n < _COLUMNAR_MIN:
        return None
    if not isinstance(et, type):
        return None
    if issubclass(et, ByteVectorBase):
        size = et.length
        if size > 64:
            return None
        raw = np.frombuffer(b"".join(items), dtype=np.uint8)
        if size == 32:
            return raw.tobytes()
        if size < 32:
            out = np.zeros((n, 32), dtype=np.uint8)
            out[:, :size] = raw.reshape(n, size)
            return out.tobytes()
        buf = np.zeros((n, 64), dtype=np.uint8)
        buf[:, :size] = raw.reshape(n, size)
        return merkle.hash_rows(buf).tobytes()
    if issubclass(et, Container):
        _C_BULK_ROOTS.add()
        return _container_root_bytes(items, et, owner)
    return None


def _container_root_bytes(items, et, owner) -> bytes:
    n = len(items)
    plan = _columnar_plan(et)
    width = merkle.next_power_of_two(max(len(plan), 1))
    cols = np.zeros((n, width, 32), dtype=np.uint8)
    # full-extraction builds first ask the registered column provider
    # (state/arrays.py): a live StateArrays store already holds the
    # committed uint64 columns, so the per-field python walk is skipped
    provided = _column_provider(owner) \
        if owner is not None and _column_provider is not None else None
    if provided is not None \
            and any(c.shape[0] != n for c in provided.values()):
        provided = None     # shape desync: never trust a short column
    stash = {} if owner is not None and provided is None else None
    for j, (fname, kind, size) in enumerate(plan):
        if kind == "uint":
            vals = provided.get(fname) if provided is not None else None
            if vals is None:
                vals = np.fromiter((int(getattr(x, fname)) for x in items),
                                   dtype=np.uint64, count=n)
            # value < 2**(8*size), so bytes past `size` are zero anyway.
            # ascontiguousarray: provider columns can be strided
            # structured-array field views, which .view(uint8) rejects
            cols[:, j, :8] = np.ascontiguousarray(vals, dtype="<u8") \
                .view(np.uint8).reshape(n, 8)
            if stash is not None:
                stash[fname] = vals
        elif kind == "bytes":
            raw = b"".join(getattr(x, fname) for x in items)
            cols[:, j, :size] = np.frombuffer(
                raw, dtype=np.uint8).reshape(n, size)
        elif kind == "hash64":
            raw = b"".join(getattr(x, fname) for x in items)
            buf = np.zeros((n, 64), dtype=np.uint8)
            buf[:, :size] = np.frombuffer(raw, dtype=np.uint8).reshape(n, size)
            cols[:, j] = merkle.hash_rows(buf)
        else:
            raw = b"".join(getattr(x, fname).hash_tree_root() for x in items)
            cols[:, j] = np.frombuffer(raw, dtype=np.uint8).reshape(n, 32)
    while cols.shape[1] > 1:
        half = cols.shape[1] // 2
        cols = merkle.hash_rows(cols.reshape(n * half, 64)) \
            .reshape(n, half, 32)
    if stash:
        _stash_columns(owner, stash)
    return cols.tobytes()


# ---------------------------------------------------------------------------
# Column sharing with the state layer (state/arrays.py)
# ---------------------------------------------------------------------------

# Registered by ``state/arrays.py`` at import (keeps this module free of
# an upward dependency): maps an owning sequence to its live, committed
# ``{ssz field name: uint64 column}`` view, or None.
_column_provider = None


def set_column_provider(fn) -> None:
    global _column_provider
    _column_provider = fn


# (weakref to owning sequence, owner mutation generation, {field: u64 col})
_shared_columns = None


def _on_owner_died(ref) -> None:
    """Drop the stash with its owner — the columns are useless without
    it and would otherwise pin ~8 bytes/validator/field for the process
    lifetime."""
    global _shared_columns
    if _shared_columns is not None and _shared_columns[0] is ref:
        _shared_columns = None


def _stash_columns(owner, cols) -> None:
    global _shared_columns
    _shared_columns = (weakref.ref(owner, _on_owner_died),
                       getattr(owner, "_gen", 0), cols)


def peek_columns(owner):
    """The uint64 field columns captured during ``owner``'s last columnar
    root build — or None if ``owner`` mutated since (the generation
    counter moved) or the stash belongs to another sequence."""
    if _shared_columns is None:
        return None
    ref, gen, cols = _shared_columns
    if ref() is owner and getattr(owner, "_gen", 0) == gen:
        return cols
    return None
