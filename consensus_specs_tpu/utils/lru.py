"""Minimal bounded LRU mapping (role of the reference's ``lru-dict`` C
extension, ``setup.py:550``). Shared by the spec runtimes' committee/
proposer caches (``forks/phase0.py``) and the BLS verification memo
(``utils/bls.py``)."""
from collections import OrderedDict


class LRUDict(OrderedDict):

    def __init__(self, maxsize: int):
        super().__init__()
        self._maxsize = maxsize

    def get(self, key, default=None):
        if key in self:
            self.move_to_end(key)
            return self[key]
        return default

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self._maxsize:
            self.popitem(last=False)
