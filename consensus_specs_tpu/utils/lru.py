"""Minimal bounded LRU mapping (role of the reference's ``lru-dict`` C
extension, ``setup.py:550``). Shared by the spec runtimes' committee/
proposer caches (``forks/phase0.py``), the BLS verification memo
(``utils/bls.py``) and the epoch engine's column cache
(``ops/epoch_kernels.py``).

A cache constructed with a ``name`` reports hit/miss counts to the
telemetry registry as ``cache.hit{cache=<name>}`` /
``cache.miss{cache=<name>}`` (series bound once at construction — the
per-get cost is one int add).  Unnamed caches count nothing.
"""
from collections import OrderedDict

from ..obs import registry as _obs_registry

_CACHE_HIT = _obs_registry.counter("cache.hit")
_CACHE_MISS = _obs_registry.counter("cache.miss")


class LRUDict(OrderedDict):

    def __init__(self, maxsize: int, name: str = None):
        super().__init__()
        self._maxsize = maxsize
        if name is not None:
            self._hit = _CACHE_HIT.labels(cache=name)
            self._miss = _CACHE_MISS.labels(cache=name)
        else:
            self._hit = self._miss = None

    def get(self, key, default=None):
        if key in self:
            if self._hit is not None:
                self._hit.n += 1
            self.move_to_end(key)
            return self[key]
        if self._miss is not None:
            self._miss.n += 1
        return default

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self._maxsize:
            self.popitem(last=False)
