"""Pluggable BLS backend switch.

Mirrors the reference's backend-switch design
(``tests/core/pyspec/eth2spec/utils/bls.py:30-104``): one module-level
``bls`` API whose implementation is swapped at runtime —

  use_py()       pure-Python oracle (role of the reference's py_ecc)
  use_jax()      batched JAX kernels, jit-compiled for TPU (replaces the
                 reference's milagro/arkworks Rust backends)
  use_fastest()  jax if available, else py

plus the test kill-switch ``bls_active`` with STUB constants
(``bls.py:49-57,93-104``): when inactive, Sign returns a stub and verifies
trivially pass — used by the harness's @never_bls/@always_bls decorators.
"""
from typing import Sequence

from consensus_specs_tpu.ops.bls12_381 import ciphersuite as _py_backend
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER as CURVE_ORDER  # noqa: F401
from consensus_specs_tpu.ops.bls12_381.curve import (  # noqa: F401
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR,
    g1_from_compressed as bytes48_to_G1,
    g2_from_compressed as bytes96_to_G2,
)
from consensus_specs_tpu.ops.bls12_381.pairing import multi_pairing_check as pairing_check
from consensus_specs_tpu.ops.bls12_381.hash_to_curve import hash_to_g2

bls_active = True

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
# stub return of signature_to_G2 when bls is inactive: the G2 infinity point
STUB_COORDINATES = G2Point.inf()

_backend = _py_backend
_backend_name = "py"


def use_py():
    global _backend, _backend_name
    _backend = _py_backend
    _backend_name = "py"


def use_jax():
    global _backend, _backend_name
    from consensus_specs_tpu.ops import bls_jax
    _backend = bls_jax
    _backend_name = "jax"


def use_fastest():
    try:
        use_jax()
    except Exception:
        use_py()


def backend_name() -> str:
    return _backend_name


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped check when bls is disabled."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        return wrapper
    return decorator


@only_with_bls(alt_return=True)
def Verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    return _backend.Verify(bytes(pk), bytes(msg), bytes(sig))


@only_with_bls(alt_return=True)
def AggregateVerify(pks: Sequence[bytes], msgs: Sequence[bytes], sig: bytes) -> bool:
    return _backend.AggregateVerify([bytes(p) for p in pks], [bytes(m) for m in msgs], bytes(sig))


@only_with_bls(alt_return=True)
def FastAggregateVerify(pks: Sequence[bytes], msg: bytes, sig: bytes) -> bool:
    return _backend.FastAggregateVerify([bytes(p) for p in pks], bytes(msg), bytes(sig))


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures: Sequence[bytes]) -> bytes:
    return _backend.Aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(sk: int, msg: bytes) -> bytes:
    return _backend.Sign(sk, bytes(msg))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    return _backend.AggregatePKs([bytes(p) for p in pubkeys])


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(sk: int) -> bytes:
    # NOTE: deliberate divergence — the reference stubs SkToPk with the
    # 96-byte STUB_SIGNATURE (bls.py:182-183), which is the wrong width for a
    # pubkey; we return the 48-byte STUB_PUBKEY instead.
    return _backend.SkToPk(sk)


@only_with_bls(alt_return=True)
def KeyValidate(pk: bytes) -> bool:
    return _backend.KeyValidate(bytes(pk))


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(sig: bytes) -> G2Point:
    return bytes96_to_G2(bytes(sig))


# ---------------------------------------------------------------------------
# Raw point helpers (reference bls.py:190-326) — used directly by the KZG
# spec functions (g1_lincomb, pairing checks) and by test vector generators.
# ---------------------------------------------------------------------------

def add(lhs, rhs):
    return lhs + rhs


def multiply(point, scalar: int):
    return point.mult(int(scalar))


def neg(point):
    return -point


def Z1():
    return G1Point.inf()


def Z2():
    return G2Point.inf()


def G1():
    return G1_GENERATOR


def G2():
    return G2_GENERATOR


def G1_to_bytes48(point) -> bytes:
    return point.to_compressed()


def G2_to_bytes96(point) -> bytes:
    return point.to_compressed()
