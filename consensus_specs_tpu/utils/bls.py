"""Pluggable BLS backend switch.

Mirrors the reference's backend-switch design
(``tests/core/pyspec/eth2spec/utils/bls.py:30-104``): one module-level
``bls`` API whose implementation is swapped at runtime —

  use_py()       pure-Python oracle (role of the reference's py_ecc)
  use_jax()      batched JAX kernels, jit-compiled for TPU (replaces the
                 reference's milagro/arkworks Rust backends)
  use_fastest()  jax if available, else py

plus the test kill-switch ``bls_active`` with STUB constants
(``bls.py:49-57,93-104``): when inactive, Sign returns a stub and verifies
trivially pass — used by the harness's @never_bls/@always_bls decorators.
"""
from contextlib import contextmanager
from typing import Sequence

from consensus_specs_tpu.utils.lru import LRUDict
from consensus_specs_tpu.ops.bls12_381 import ciphersuite as _py_backend
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER as CURVE_ORDER  # noqa: F401
from consensus_specs_tpu.ops.bls12_381.curve import (  # noqa: F401
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR,
    g1_from_compressed as bytes48_to_G1,
    g2_from_compressed as bytes96_to_G2,
)
from consensus_specs_tpu.ops.bls12_381.pairing import multi_pairing_check as pairing_check  # noqa: F401 (spec API)

bls_active = True

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
# stub return of signature_to_G2 when bls is inactive: the G2 infinity point
STUB_COORDINATES = G2Point.inf()

_backend = _py_backend
_backend_name = "py"


def use_py():
    global _backend, _backend_name
    if _backend_name != "py":
        # a differential run must exercise the newly selected backend,
        # so memoized results from the other one are dropped; repeated
        # use_py() calls (the harness resets the backend per test) keep
        # the memo — cross-test reuse is its whole payoff
        clear_verify_memo()
    _backend = _py_backend
    _backend_name = "py"


def use_jax():
    global _backend, _backend_name
    from consensus_specs_tpu.ops import bls_jax
    if _backend_name != "jax":
        clear_verify_memo()
    _backend = bls_jax
    _backend_name = "jax"


def use_native():
    """CPU-native C backend (csrc/bls12_381.c) — the role the reference's
    Rust milagro/arkworks bindings play (bls.py:61-72); ~20-25x the
    python oracle on one core."""
    global _backend, _backend_name
    from consensus_specs_tpu.ops import native_bls
    if not native_bls.available():
        raise RuntimeError("native BLS library unavailable")
    if _backend_name != "native":
        clear_verify_memo()
    _backend = native_bls
    _backend_name = "native"


def use_fastest():
    """Backend ladder (reference ``fastest_bls``, bls.py:35-53): the JAX
    kernels when an accelerator is attached, else the native C library,
    else the python oracle.  On a bare CPU the jax path pays minutes of
    XLA compile for sub-oracle throughput, so it is only 'fastest' when
    a real device is present."""
    try:
        from consensus_specs_tpu.utils.jax_env import accelerator_cached
        if accelerator_cached():
            use_jax()
            return
    except Exception:
        pass
    try:
        use_native()
    except Exception:
        try:
            use_jax()
        except Exception:
            use_py()


def backend_name() -> str:
    return _backend_name


# ---------------------------------------------------------------------------
# Deferred batch verification — the TPU-native block path.
#
# The reference verifies a block's signatures one FFI call at a time inside
# the serial ``for_ops`` loop (``specs/phase0/beacon-chain.md:1757-1774``).
# Here ``process_block`` opens a batch context; every assert-style
# ``Verify``/``FastAggregateVerify`` inside it enqueues its (pubkeys, msg,
# sig) triple and optimistically returns True, and the block flushes the
# whole batch as ONE device dispatch.  Any invalid signature then raises
# AssertionError, which keeps exception-as-invalidity semantics: a block
# is atomically valid or invalid, and partially-mutated state is discarded
# by every caller on failure (reference ``test/context.py:299-310``,
# ``fork-choice.md`` on_block state copy).
#
# Only *assert-style* verifications may be deferred.  Conditional ones
# (deposit proofs of possession, where the boolean steers state) must use
# the eager paths below.
# ---------------------------------------------------------------------------

class DeferredBatch:
    """Signature-verification triples collected under one block."""

    def __init__(self):
        self.items = []

    def add(self, pubkeys, message, signature):
        self.items.append(([bytes(pk) for pk in pubkeys],
                           bytes(message), bytes(signature)))

    def flush(self) -> bool:
        items, self.items = self.items, []
        if not items:
            return True
        if _backend_name == "jax":
            from consensus_specs_tpu.ops import bls_jax
            results = bls_jax.verify_aggregates_batch(items)
        else:
            results = [_backend.FastAggregateVerify(pks, msg, sig)
                       for pks, msg, sig in items]
        return all(results)

    def assert_valid(self):
        assert self.flush(), "batched signature verification failed"


_batch_stack = []


@contextmanager
def batched_verification():
    """Defer assert-style signature checks to one batched dispatch.

    Re-entrant: a nested context joins the enclosing batch so a whole
    ``state_transition`` (block signature + block body) flushes once.
    """
    if _batch_stack:
        yield _batch_stack[-1]
        return
    batch = DeferredBatch()
    _batch_stack.append(batch)
    try:
        yield batch
    finally:
        _batch_stack.pop()


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped check when bls is disabled."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        return wrapper
    return decorator


# Verification results are pure functions of their byte inputs, so a
# bounded memo is semantically transparent. It pays off because the
# harness reuses one cached genesis per (fork, preset): identical
# (pubkey, signing-root, signature) triples recur across tests — every
# repeat verification of a proposer/randao/attestation signature becomes
# a dict hit instead of a multi-second pure-python pairing. The memo is
# cleared on every backend switch so a differential run (py vs jax over
# the same inputs) always exercises the newly selected backend, and
# benchmarks can call ``clear_verify_memo`` between reps so they time
# pairings, not dict hits.
_verify_memo = LRUDict(1 << 16, name="bls_verify")


def clear_verify_memo() -> None:
    _verify_memo.clear()


def _memo_get(key):
    return _verify_memo.get(key)


def _memo_put(key, value: bool) -> bool:
    _verify_memo[key] = value
    return value


@only_with_bls(alt_return=True)
def Verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    if _batch_stack:
        _batch_stack[-1].add([pk], msg, sig)
        return True
    key = ("v", bytes(pk), bytes(msg), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.Verify(bytes(pk), bytes(msg), bytes(sig)))


@only_with_bls(alt_return=True)
def VerifyEager(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Immediate verification even inside a batch context — for call sites
    where the boolean result steers state (deposit proof of possession,
    ``specs/phase0/beacon-chain.md:1877``) rather than block validity."""
    key = ("v", bytes(pk), bytes(msg), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.Verify(bytes(pk), bytes(msg), bytes(sig)))


@only_with_bls(alt_return=True)
def AggregateVerify(pks: Sequence[bytes], msgs: Sequence[bytes], sig: bytes) -> bool:
    key = ("av", tuple(bytes(p) for p in pks),
           tuple(bytes(m) for m in msgs), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.AggregateVerify(
        [bytes(p) for p in pks], [bytes(m) for m in msgs], bytes(sig)))


@only_with_bls(alt_return=True)
def FastAggregateVerify(pks: Sequence[bytes], msg: bytes, sig: bytes) -> bool:
    if _batch_stack:
        _batch_stack[-1].add(pks, msg, sig)
        return True
    key = ("fav", tuple(bytes(p) for p in pks), bytes(msg), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.FastAggregateVerify(
        [bytes(p) for p in pks], bytes(msg), bytes(sig)))


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures: Sequence[bytes]) -> bytes:
    return _backend.Aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(sk: int, msg: bytes) -> bytes:
    return _backend.Sign(sk, bytes(msg))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    return _backend.AggregatePKs([bytes(p) for p in pubkeys])


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(sk: int) -> bytes:
    # NOTE: deliberate divergence — the reference stubs SkToPk with the
    # 96-byte STUB_SIGNATURE (bls.py:182-183), which is the wrong width for a
    # pubkey; we return the 48-byte STUB_PUBKEY instead.
    return _backend.SkToPk(sk)


@only_with_bls(alt_return=True)
def KeyValidate(pk: bytes) -> bool:
    return _backend.KeyValidate(bytes(pk))


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(sig: bytes) -> G2Point:
    return bytes96_to_G2(bytes(sig))


# ---------------------------------------------------------------------------
# Raw point helpers (reference bls.py:190-326) — used directly by the KZG
# spec functions (g1_lincomb, pairing checks) and by test vector generators.
# ---------------------------------------------------------------------------

def add(lhs, rhs):
    return lhs + rhs


def multiply(point, scalar: int):
    return point.mult(int(scalar))


def neg(point):
    return -point


def Z1():
    return G1Point.inf()


def Z2():
    return G2Point.inf()


def G1():
    return G1_GENERATOR


def G2():
    return G2_GENERATOR


def G1_to_bytes48(point) -> bytes:
    return point.to_compressed()


def G2_to_bytes96(point) -> bytes:
    return point.to_compressed()
