"""Pluggable BLS backend switch.

Mirrors the reference's backend-switch design
(``tests/core/pyspec/eth2spec/utils/bls.py:30-104``): one module-level
``bls`` API whose implementation is swapped at runtime —

  use_py()       pure-Python oracle (role of the reference's py_ecc)
  use_jax()      batched JAX kernels, jit-compiled for TPU (replaces the
                 reference's milagro/arkworks Rust backends)
  use_fastest()  jax if available, else py

plus the test kill-switch ``bls_active`` with STUB constants
(``bls.py:49-57,93-104``): when inactive, Sign returns a stub and verifies
trivially pass — used by the harness's @never_bls/@always_bls decorators.
"""
from contextlib import contextmanager
from typing import Sequence

from consensus_specs_tpu import faults as _faults
from consensus_specs_tpu import supervisor
from consensus_specs_tpu.obs import registry as _obs_registry
from consensus_specs_tpu.utils import env_flags as _env_flags
from consensus_specs_tpu.utils.lru import LRUDict
from consensus_specs_tpu.ops.bls12_381 import ciphersuite as _py_backend
from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER as CURVE_ORDER  # noqa: F401
from consensus_specs_tpu.ops.bls12_381.curve import (  # noqa: F401
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR,
    g1_from_compressed as bytes48_to_G1,
    g2_from_compressed as bytes96_to_G2,
)
from consensus_specs_tpu.ops.bls12_381.pairing import multi_pairing_check as pairing_check  # noqa: F401 (spec API)

bls_active = True

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
# stub return of signature_to_G2 when bls is inactive: the G2 infinity point
STUB_COORDINATES = G2Point.inf()

_backend = _py_backend
_backend_name = "py"


def use_py():
    global _backend, _backend_name
    if _backend_name != "py":
        # a differential run must exercise the newly selected backend,
        # so memoized results from the other one are dropped; repeated
        # use_py() calls (the harness resets the backend per test) keep
        # the memo — cross-test reuse is its whole payoff
        clear_verify_memo()
    _backend = _py_backend
    _backend_name = "py"


def use_jax():
    global _backend, _backend_name
    from consensus_specs_tpu.ops import bls_jax
    if _backend_name != "jax":
        clear_verify_memo()
    _backend = bls_jax
    _backend_name = "jax"


def use_native():
    """CPU-native C backend (csrc/bls12_381.c) — the role the reference's
    Rust milagro/arkworks bindings play (bls.py:61-72); ~20-25x the
    python oracle on one core."""
    global _backend, _backend_name
    from consensus_specs_tpu.ops import native_bls
    if not native_bls.available():
        raise RuntimeError("native BLS library unavailable")
    if _backend_name != "native":
        clear_verify_memo()
    _backend = native_bls
    _backend_name = "native"


def use_fastest():
    """Backend ladder (reference ``fastest_bls``, bls.py:35-53): the JAX
    kernels when an accelerator is attached, else the native C library,
    else the python oracle.  On a bare CPU the jax path pays minutes of
    XLA compile for sub-oracle throughput, so it is only 'fastest' when
    a real device is present."""
    try:
        from consensus_specs_tpu.utils.jax_env import accelerator_cached
        if accelerator_cached():
            use_jax()
            return
    except Exception:
        pass
    try:
        use_native()
    except Exception:
        try:
            use_jax()
        except Exception:
            use_py()


def backend_name() -> str:
    return _backend_name


# ---------------------------------------------------------------------------
# Deferred batch verification — the TPU-native block path.
#
# The reference verifies a block's signatures one FFI call at a time inside
# the serial ``for_ops`` loop (``specs/phase0/beacon-chain.md:1757-1774``).
# Here ``process_block`` opens a batch context; every assert-style
# ``Verify``/``FastAggregateVerify`` inside it enqueues its (pubkeys, msg,
# sig) triple and optimistically returns True, and the block flushes the
# whole batch as ONE device dispatch.  Any invalid signature then raises
# AssertionError, which keeps exception-as-invalidity semantics: a block
# is atomically valid or invalid, and partially-mutated state is discarded
# by every caller on failure (reference ``test/context.py:299-310``,
# ``fork-choice.md`` on_block state copy).
#
# Only *assert-style* verifications may be deferred.  Conditional ones
# (deposit proofs of possession, where the boolean steers state) must use
# the eager paths below.
#
# Flush strategy (``CS_TPU_BLS_RLC``, default on): the whole queue folds
# into a random-linear-combination product — 2 MSMs + ONE pairing check
# for the block (``ops/bls_rlc.py``; math and soundness documented
# there).  On a combined failure (or a structurally invalid item) the
# flush re-runs the per-lane path to bisect and report exactly which
# item failed, so assert semantics are unchanged.  ``bls.flush{path=
# rlc|lanes|fallback}`` counts which strategy answered; ``bls.pairings``
# counts pairing-check evaluations so "one pairing per block" is
# counter-assertable.  The fallback series carries a ``reason`` label:
# ``bisect`` for the organic combined-failure re-run, ``injected`` for
# harness-scheduled faults (``consensus_specs_tpu/faults.py``).
# ---------------------------------------------------------------------------

_FLUSH_RLC = _obs_registry.counter("bls.flush").labels(path="rlc")
_FLUSH_LANES = _obs_registry.counter("bls.flush").labels(path="lanes")
_FLUSH_FALLBACK = {
    "bisect": _obs_registry.counter(
        "bls.flush").labels(path="fallback", reason="bisect"),
    "injected": _obs_registry.counter(
        "bls.flush").labels(path="fallback", reason="injected"),
    "deadline": _obs_registry.counter(
        "bls.flush").labels(path="fallback", reason="deadline"),
}
_PAIRINGS = _obs_registry.counter("bls.pairings").labels()


def rlc_enabled() -> bool:
    """RLC flush switch: live env re-read when the variable is present
    (CI legs flip it after import), else the import-time snapshot —
    the shared ``env_flags.switch`` contract."""
    return _env_flags.switch("CS_TPU_BLS_RLC")


class DeferredBatch:
    """Signature-verification triples (and deferred raw pairing-product
    checks, e.g. the blob-KZG batch) collected under one block."""

    def __init__(self):
        self.items = []            # (pubkeys, message, signature)
        self.item_keys = []        # per item: memo keys to record at flush
        self.pairing_checks = []   # (pairs, label): raw product checks
        self._seen = {}            # triple -> index (in-batch dedup)
        self.last_results = None
        self.last_pairing_results = None

    def add(self, pubkeys, message, signature, memo_key=None):
        item = ([bytes(pk) for pk in pubkeys],
                bytes(message), bytes(signature))
        if memo_key is None:
            memo_key = ("fav", tuple(item[0]), item[1], item[2])
        dedup = (tuple(item[0]), item[1], item[2])
        idx = self._seen.get(dedup)
        if idx is not None:
            # identical triple already queued this block: one device lane
            # serves both call sites, both memo keys get the result
            if memo_key not in self.item_keys[idx]:
                self.item_keys[idx].append(memo_key)
            return
        self._seen[dedup] = len(self.items)
        self.items.append(item)
        self.item_keys.append([memo_key])

    def add_pairing_check(self, pairs, label=""):
        """Defer a raw product-pairing check ``prod e(P_i, Q_i) == 1``
        (oracle point pairs).  Folds into the RLC flush with its own
        random coefficient; evaluated individually on the bisect path."""
        self.pairing_checks.append(
            ([(p, q) for p, q in pairs], str(label)))

    @staticmethod
    def _lane_results(items) -> list:
        if not items:
            return []
        if _backend_name == "jax":
            from consensus_specs_tpu.ops import bls_jax
            return bls_jax.verify_aggregates_batch(items)
        return [_backend.FastAggregateVerify(pks, msg, sig)
                for pks, msg, sig in items]

    @staticmethod
    def _eval_pairing_check(pairs) -> bool:
        from consensus_specs_tpu.ops.kzg import _pairing_check
        return _pairing_check(pairs)

    def flush(self) -> bool:
        items, keys = self.items, self.item_keys
        checks = self.pairing_checks
        self.items, self.item_keys, self.pairing_checks = [], [], []
        self._seen = {}
        if not items and not checks:
            return True
        site = "bls.flush"
        verdict = None
        audited = False
        if rlc_enabled() and supervisor.admit(site):
            fallback_exc = None
            try:
                _faults.check(site)
                with supervisor.deadline_scope(site):
                    from consensus_specs_tpu.ops import bls_rlc
                    verdict = bls_rlc.combined_check(items, checks,
                                                     _backend_name)
            except (_faults.InjectedFault,
                    supervisor.DeadlineExceeded) as exc:
                # the RLC combine "failed": degrade to the per-lane
                # path, exactly like a combined-verdict failure
                fallback_exc = exc
            else:
                if verdict is not None:
                    _PAIRINGS.add()      # the one combined product pairing
                    if _faults.corrupt_armed(site):
                        # silent-corruption injection (sentinel-audit
                        # test vector): the combined check lies in
                        # whichever direction the true verdict isn't
                        verdict = not verdict
                    audited = supervisor.audit_due(site)
                if verdict is True and not audited:
                    _FLUSH_RLC.add()
                    supervisor.note_success(site)
                    for ks in keys:
                        for k in ks:
                            _memo_put(k, True)
                    self.last_results = [True] * len(items)
                    self.last_pairing_results = [True] * len(checks)
                    return True
            # combined failure (False), structurally invalid item
            # (None), or an injected/deadline fault: bisect through the
            # per-lane path for exact per-item reporting.  Only an
            # audited verdict=True flush skips the count — there the
            # lanes run purely as the sentinel's cross-check, not as a
            # fallback; an audited combined FAILURE is still the
            # organic bisect and must book (and feed the breaker) like
            # any other
            if not audited or verdict is not True:
                _faults.count_fallback(_FLUSH_FALLBACK, fallback_exc,
                                       organic="bisect", site=site)
        else:
            _FLUSH_LANES.add()
        results = self._lane_results(items)
        _PAIRINGS.add(len(items))
        pairing_results = [self._eval_pairing_check(pairs)
                           for pairs, _ in checks]
        _PAIRINGS.add(len(checks))
        if audited:
            lanes_ok = all(bool(r) for r in results) \
                and all(pairing_results)
            ok = (verdict is True) == lanes_ok
            supervisor.audit_result(
                site, ok, "RLC combined verdict diverged from the "
                "per-lane pairing checks")
            if ok and verdict is True:
                _FLUSH_RLC.add()
        for ks, ok in zip(keys, results):
            for k in ks:
                _memo_put(k, bool(ok))
        self.last_results = [bool(r) for r in results]
        self.last_pairing_results = pairing_results
        return all(results) and all(pairing_results)

    def assert_valid(self):
        if not self.flush():
            failed = [i for i, r in enumerate(self.last_results or [])
                      if not r]
            failed_checks = [i for i, r in
                             enumerate(self.last_pairing_results or [])
                             if not r]
            raise AssertionError(
                "batched signature verification failed "
                f"(items {failed}, deferred checks {failed_checks})")


_batch_stack = []


@contextmanager
def batched_verification():
    """Defer assert-style signature checks to one batched dispatch.

    Re-entrant: a nested context joins the enclosing batch so a whole
    ``state_transition`` (block signature + block body) flushes once.
    """
    if _batch_stack:
        yield _batch_stack[-1]
        return
    batch = DeferredBatch()
    _batch_stack.append(batch)
    try:
        yield batch
    finally:
        _batch_stack.pop()


def batch_scope_active() -> bool:
    """True while any deferred-verification scope is open — callers that
    want to INSTALL an outermost scope (the gen runner's per-case fold)
    probe this instead of racing :func:`scoped_batch`'s RuntimeError."""
    return bool(_batch_stack)


@contextmanager
def scoped_batch(batch):
    """Install ``batch`` as the outermost deferred-verification scope.

    The serving pipeline (``consensus_specs_tpu/serving``) uses this to
    interpose a window-spanning :class:`DeferredBatch` subclass: every
    nested :func:`batched_verification` context (one per ``on_block``)
    then joins the window batch, so signature triples from several
    in-flight blocks dedup (equivocating siblings share device lanes)
    and fold into ONE flush at the window barrier.  Refuses to nest
    inside an active scope — interposition means owning the outermost
    scope, and silently joining someone else's batch would defer their
    asserts past the point they resolve them."""
    if _batch_stack:
        raise RuntimeError(
            "bls.scoped_batch: a batch scope is already active")
    _batch_stack.append(batch)
    try:
        yield batch
    finally:
        popped = _batch_stack.pop()
        assert popped is batch


def defer_pairing_check(pairs, label="") -> bool:
    """Queue a raw product-pairing check ``prod e(P_i, Q_i) == 1`` (oracle
    point pairs) into the active batch context, to fold into the block's
    single RLC pairing.  Returns False when no batch context is active or
    the RLC path is off — the caller must then evaluate eagerly.

    Deferred checks are assert-style by contract (the batched-
    verification scope rule above): the optimistic True is only sound
    when the caller asserts the result and block-level failure discards
    the state.
    """
    if not _batch_stack or not rlc_enabled():
        return False
    _batch_stack[-1].add_pairing_check(pairs, label)
    return True


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped check when bls is disabled."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        return wrapper
    return decorator


# Verification results are pure functions of their byte inputs, so a
# bounded memo is semantically transparent. It pays off because the
# harness reuses one cached genesis per (fork, preset): identical
# (pubkey, signing-root, signature) triples recur across tests — every
# repeat verification of a proposer/randao/attestation signature becomes
# a dict hit instead of a multi-second pure-python pairing. The memo is
# cleared on every backend switch so a differential run (py vs jax over
# the same inputs) always exercises the newly selected backend, and
# benchmarks can call ``clear_verify_memo`` between reps so they time
# pairings, not dict hits.
_verify_memo = LRUDict(1 << 16, name="bls_verify")


def clear_verify_memo() -> None:
    _verify_memo.clear()


def _memo_get(key):
    return _verify_memo.get(key)


def _memo_put(key, value: bool) -> bool:
    _verify_memo[key] = value
    return value


@only_with_bls(alt_return=True)
def Verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    key = ("v", bytes(pk), bytes(msg), bytes(sig))
    if _batch_stack:
        # memo before enqueue: a repeated signature (replayed block)
        # skips the device lane entirely; a memoized failure surfaces
        # immediately (assert-style callers raise just as they would at
        # flush).  Results memo back in at flush.
        hit = _memo_get(key)
        if hit is None:
            _batch_stack[-1].add([pk], msg, sig, memo_key=key)
            return True
        return hit
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.Verify(bytes(pk), bytes(msg), bytes(sig)))


@only_with_bls(alt_return=True)
def VerifyEager(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Immediate verification even inside a batch context — for call sites
    where the boolean result steers state (deposit proof of possession,
    ``specs/phase0/beacon-chain.md:1877``) rather than block validity."""
    key = ("v", bytes(pk), bytes(msg), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.Verify(bytes(pk), bytes(msg), bytes(sig)))


@only_with_bls(alt_return=True)
def AggregateVerify(pks: Sequence[bytes], msgs: Sequence[bytes], sig: bytes) -> bool:
    key = ("av", tuple(bytes(p) for p in pks),
           tuple(bytes(m) for m in msgs), bytes(sig))
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.AggregateVerify(
        [bytes(p) for p in pks], [bytes(m) for m in msgs], bytes(sig)))


@only_with_bls(alt_return=True)
def FastAggregateVerify(pks: Sequence[bytes], msg: bytes, sig: bytes) -> bool:
    key = ("fav", tuple(bytes(p) for p in pks), bytes(msg), bytes(sig))
    if _batch_stack:
        # memo before enqueue (see Verify): repeats skip device work
        hit = _memo_get(key)
        if hit is None:
            _batch_stack[-1].add(pks, msg, sig, memo_key=key)
            return True
        return hit
    hit = _memo_get(key)
    if hit is not None:
        return hit
    return _memo_put(key, _backend.FastAggregateVerify(
        [bytes(p) for p in pks], bytes(msg), bytes(sig)))


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures: Sequence[bytes]) -> bytes:
    return _backend.Aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(sk: int, msg: bytes) -> bytes:
    return _backend.Sign(sk, bytes(msg))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    return _backend.AggregatePKs([bytes(p) for p in pubkeys])


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(sk: int) -> bytes:
    # NOTE: deliberate divergence — the reference stubs SkToPk with the
    # 96-byte STUB_SIGNATURE (bls.py:182-183), which is the wrong width for a
    # pubkey; we return the 48-byte STUB_PUBKEY instead.
    return _backend.SkToPk(sk)


@only_with_bls(alt_return=True)
def KeyValidate(pk: bytes) -> bool:
    return _backend.KeyValidate(bytes(pk))


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(sig: bytes) -> G2Point:
    return bytes96_to_G2(bytes(sig))


# ---------------------------------------------------------------------------
# Raw point helpers (reference bls.py:190-326) — used directly by the KZG
# spec functions (g1_lincomb, pairing checks) and by test vector generators.
# ---------------------------------------------------------------------------

def add(lhs, rhs):
    return lhs + rhs


def multiply(point, scalar: int):
    return point.mult(int(scalar))


def neg(point):
    return -point


def Z1():
    return G1Point.inf()


def Z2():
    return G2Point.inf()


def G1():
    return G1_GENERATOR


def G2():
    return G2_GENERATOR


def G1_to_bytes48(point) -> bytes:
    return point.to_compressed()


def G2_to_bytes96(point) -> bytes:
    return point.to_compressed()
