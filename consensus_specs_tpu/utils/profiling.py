"""Span timing — thin aliases over the unified telemetry subsystem.

Historically this module owned a flat named-span timer; the machinery
now lives in ``consensus_specs_tpu/obs`` (hierarchical span tree,
metrics registry, exporters — see ``docs/observability.md``).  The
surface here is kept because kernels and benches import it::

    from consensus_specs_tpu.utils.profiling import span, report

    with span("bls.verify_batch"):
        ...
    print(report())

Spans nest; disabled (zero-overhead guard) unless ``CS_TPU_PROFILE=1``
or :func:`enable` is called.  ``jax.block_until_ready`` is the caller's
responsibility — a span measures wall-clock of whatever it wraps.

Nesting fix vs the old flat timer: ``stats()`` rows now carry both
``total_s`` (cumulative — a nested span's time also counts inside its
parent) and ``self_s`` (child-span time excluded), so summing a column
of ``self_s`` no longer double-counts parents.
"""
from ..obs import tracing

# the span context manager itself (class-based, zero-overhead disabled)
span = tracing.span
enable = tracing.enable
is_enabled = tracing.is_enabled
reset = tracing.reset
stats = tracing.stats


def report() -> str:
    """Human-readable flat table, longest total first (the span TREE
    view lives in ``obs.report()``)."""
    rows = sorted(stats().items(), key=lambda kv: -kv[1]["total_s"])
    if not rows:
        return "profiling: no spans recorded (enable with CS_TPU_PROFILE=1)"
    width = max(len(n) for n, _ in rows)
    out = [f"{'span'.ljust(width)}  count     total      self"
           f"      mean       max"]
    for name, s in rows:
        out.append(f"{name.ljust(width)}  {s['count']:5d}  "
                   f"{s['total_s']:8.3f}s  {s['self_s']:8.3f}s  "
                   f"{s['mean_s']:8.4f}s  {s['max_s']:8.4f}s")
    return "\n".join(out)
