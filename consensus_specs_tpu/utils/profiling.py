"""Lightweight kernel/process timing registry.

The reference ships only per-case wall-clock printing above a threshold
(``gen_base/settings.py`` TIME_THRESHOLD_TO_PRINT, used
``gen_runner.py:357-360``).  This module gives the framework the same
capability plus named-span aggregation around the hot kernels:

    from consensus_specs_tpu.utils.profiling import span, report

    with span("bls.verify_batch"):
        ...
    print(report())

Spans nest; disabled (zero-overhead guard) unless ``CS_TPU_PROFILE=1``
or :func:`enable` is called.  ``jax.block_until_ready`` is the caller's
responsibility — a span measures wall-clock of whatever it wraps.
"""
import contextlib
import os
import time
from collections import defaultdict

_enabled = os.environ.get("CS_TPU_PROFILE") == "1"
_stats = defaultdict(lambda: [0, 0.0, 0.0])   # name -> [count, total, max]


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    _stats.clear()


@contextlib.contextmanager
def span(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        s = _stats[name]
        s[0] += 1
        s[1] += dt
        s[2] = max(s[2], dt)


def stats() -> dict:
    """{name: {count, total_s, mean_s, max_s}} snapshot."""
    return {name: {"count": c, "total_s": round(t, 6),
                   "mean_s": round(t / c, 6) if c else 0.0,
                   "max_s": round(mx, 6)}
            for name, (c, t, mx) in _stats.items()}


def report() -> str:
    """Human-readable table, longest total first."""
    rows = sorted(stats().items(), key=lambda kv: -kv[1]["total_s"])
    if not rows:
        return "profiling: no spans recorded (enable with CS_TPU_PROFILE=1)"
    width = max(len(n) for n, _ in rows)
    out = [f"{'span'.ljust(width)}  count     total      mean       max"]
    for name, s in rows:
        out.append(f"{name.ljust(width)}  {s['count']:5d}  "
                   f"{s['total_s']:8.3f}s  {s['mean_s']:8.4f}s  "
                   f"{s['max_s']:8.4f}s")
    return "\n".join(out)
