"""RLP encoding + hexary Merkle-Patricia trie roots (execution layer).

Replaces the reference's ``rlp`` + ``trie`` dependencies
(``test/helpers/execution_payload.py:1-4``) for fabricating
reference-corpus-compatible execution block hashes: the EL block hash is
``keccak256(rlp(header))`` with transaction / withdrawal / receipt tries
rooted per EIP-2718/4895 (``patriciaTrie(rlp(index) => data)``).

Only insertion-then-root is needed (no proofs, no deletes), so the trie
is built in one recursive pass over the sorted nibble keys instead of a
node database.
"""
from .keccak import keccak256


# ---------------------------------------------------------------------------
# RLP
# ---------------------------------------------------------------------------

def rlp_encode(item) -> bytes:
    """RLP-encode bytes, ints (big-endian minimal), or nested lists."""
    if isinstance(item, int):
        if item < 0:
            raise ValueError("RLP cannot encode negative integers")
        payload = b"" if item == 0 else item.to_bytes(
            (item.bit_length() + 7) // 8, "big")
        return _rlp_bytes(payload)
    if isinstance(item, (bytes, bytearray, memoryview)):
        return _rlp_bytes(bytes(item))
    if isinstance(item, (list, tuple)):
        payload = b"".join(rlp_encode(x) for x in item)
        return _rlp_length(len(payload), 0xC0) + payload
    raise TypeError(f"cannot RLP-encode {type(item).__name__}")


def _rlp_bytes(b: bytes) -> bytes:
    if len(b) == 1 and b[0] < 0x80:
        return b
    return _rlp_length(len(b), 0x80) + b


def _rlp_length(n: int, offset: int) -> bytes:
    if n < 56:
        return bytes([offset + n])
    ll = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


# ---------------------------------------------------------------------------
# Hexary Merkle-Patricia trie root
# ---------------------------------------------------------------------------

EMPTY_TRIE_ROOT = keccak256(rlp_encode(b""))   # 56e81f17...


def _hex_prefix(nibbles, is_leaf: bool) -> bytes:
    """Yellow-paper hex-prefix encoding of a nibble path."""
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2:
        first = bytes([(flag + 1) << 4 | nibbles[0]])
        rest = nibbles[1:]
    else:
        first = bytes([flag << 4])
        rest = nibbles
    return first + bytes(
        rest[i] << 4 | rest[i + 1] for i in range(0, len(rest), 2))


def _node_ref(node):
    """Node -> its reference inside a parent: the rlp itself when short,
    else its keccak."""
    encoded = rlp_encode(node)
    return encoded if len(encoded) < 32 else keccak256(encoded)


def _build_node(items):
    """items: list of (nibble_tuple, value) with distinct keys -> node
    structure (an rlp-able list), or b"" for no entries."""
    if not items:
        return b""
    if len(items) == 1:
        nibbles, value = items[0]
        return [_hex_prefix(list(nibbles), True), value]
    # strip the longest common prefix into an extension node
    first = items[0][0]
    prefix_len = 0
    while (prefix_len < len(first)
           and all(len(k) > prefix_len and k[prefix_len] == first[prefix_len]
                   for k, _ in items)):
        prefix_len += 1
    if prefix_len:
        child = _build_node([(k[prefix_len:], v) for k, v in items])
        return [_hex_prefix(list(first[:prefix_len]), False),
                _node_ref(child)]
    # branch node: bucket by first nibble; empty-key entry is the value slot
    branch = [b""] * 17
    buckets = {}
    for k, v in items:
        if len(k) == 0:
            branch[16] = v
        else:
            buckets.setdefault(k[0], []).append((k[1:], v))
    for nib, sub in buckets.items():
        branch[nib] = _node_ref(_build_node(sub))
    return branch


def trie_root(pairs) -> bytes:
    """Root hash of the MPT holding ``{key_bytes: value_bytes}``."""
    items = sorted(
        (tuple(n for byte in key for n in (byte >> 4, byte & 0xF)), value)
        for key, value in pairs)
    node = _build_node(items)
    if node == b"":
        return EMPTY_TRIE_ROOT
    return keccak256(rlp_encode(node))


def indexed_trie_root(values) -> bytes:
    """EIP-2718-style ``patriciaTrie(rlp(index) => value)`` root."""
    return trie_root((rlp_encode(i), bytes(v)) for i, v in enumerate(values))
