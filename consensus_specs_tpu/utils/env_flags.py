"""Environment tier flags, importable without pulling in jax or the fork
registry (test modules read these at collection time)."""
import os

# Heavy crypto tier gate (jit-compile-bound tests; ``make test-crypto``)
HEAVY = os.environ.get("CS_TPU_HEAVY") == "1"
