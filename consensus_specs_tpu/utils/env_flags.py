"""Environment tier flags, importable without pulling in jax or the fork
registry (test modules read these at collection time)."""
import os

# Heavy crypto tier gate (jit-compile-bound tests; ``make test-crypto``)
HEAVY = os.environ.get("CS_TPU_HEAVY") == "1"


def _int_env(name):
    """Optional integer env knob: None when unset or non-numeric."""
    raw = os.environ.get(name, "")
    try:
        return int(raw)
    except ValueError:
        return None


# Merkleization batching floor.  When set, overrides BOTH batching
# thresholds in ``utils/ssz/merkle.py``: the kernel-layer threshold
# (``_BATCH_THRESHOLD``, default 256 — 64-byte inputs above which a full
# layer is dispatched to the batched JAX hasher instead of native C /
# hashlib) and the dirty-pair batching floor (``_PAIR_BATCH_MIN``,
# default 32 — dirty sibling pairs per tree level above which the
# incremental engine gathers the level into one batched dispatch instead
# of a per-pair hashlib loop).  ``CS_TPU_MERKLE_BATCH_MIN=1`` forces the
# batched paths everywhere; a huge value forces the scalar paths.
MERKLE_BATCH_MIN = _int_env("CS_TPU_MERKLE_BATCH_MIN")

# Hash-forest batch scope kill switch: ``CS_TPU_HASH_FOREST=0`` turns
# ``utils/ssz/forest.py`` scopes into no-ops (every tree flushes alone)
# and disables the columnar bulk container-root path.
HASH_FOREST = os.environ.get("CS_TPU_HASH_FOREST") != "0"

# Telemetry span gates (``consensus_specs_tpu/obs``).  PROFILE turns on
# hierarchical tracing spans (wall-clock span tree + flat aggregates,
# ``obs.tracing`` / the ``utils/profiling`` aliases); TRACE additionally
# attaches per-span counter deltas (a registry-wide counter diff on
# every span entry/exit — more detail, more overhead) and implies
# PROFILE.  Both default OFF: the disabled span path is a single
# module-global read.  Metric *counters* are not gated — the
# differential suites assert on them to prove which engine answered.
PROFILE = os.environ.get("CS_TPU_PROFILE") == "1"
TRACE = os.environ.get("CS_TPU_TRACE") == "1"

# Random-linear-combination batch-verification switch:
# ``CS_TPU_BLS_RLC=0`` makes ``utils/bls.DeferredBatch.flush`` run the
# per-lane path (one pairing check per queued item) instead of folding
# the whole batch into 2 MSMs + ONE product pairing check.  Like
# ``CS_TPU_PROTO_ARRAY``, this snapshot is the import-time default and
# the switch re-reads the environment at call time when the variable is
# present (``utils/bls.rlc_enabled``), so a test/CI leg can flip it
# after import.
BLS_RLC = os.environ.get("CS_TPU_BLS_RLC") != "0"

# Copy-on-write columnar state store kill switch:
# ``CS_TPU_STATE_ARRAYS=0`` detaches the per-state ``StateArrays``
# column store (``state/arrays.py``): every engine access re-extracts
# its columns and commits immediately instead of sharing one extraction
# per state lineage with deferred per-epoch commits.  Like
# ``CS_TPU_PROTO_ARRAY``, this snapshot is the import-time default and
# ``state.arrays.enabled()`` re-reads the environment at call time when
# the variable is present, so a test/CI leg can flip it after import.
STATE_ARRAYS = os.environ.get("CS_TPU_STATE_ARRAYS") != "0"

# Proto-array fork-choice kill switch: ``CS_TPU_PROTO_ARRAY=0`` runs the
# spec-loop ``get_head`` / ``get_weight`` / ``get_filtered_block_tree``
# (``forks/fork_choice.py``) instead of the incremental columnar engine
# in ``forkchoice/proto_array.py``, and stores are created without an
# engine attached.  This snapshot is the default
# ``forkchoice.proto_array.enabled()`` answers with; setting the
# variable after import also works (like ``CS_TPU_VECTORIZED_EPOCH``,
# the switch re-reads the environment at call time when it is present).
PROTO_ARRAY = os.environ.get("CS_TPU_PROTO_ARRAY") != "0"
